"""Pod-scale elastic training plane: multi-host sharded streaming fits.

This module is the JAX-native replacement for the reference's
``treeAggregate`` over cluster RDDs: the shard manifest is partitioned
round-robin across the mesh's row positions (data/partition.py), every
position sweeps only its slice, and per-position histogram
contributions are reduced across the ``{dcn_data, data}`` axes before
split selection.  Two reduction modes:

- ``reduce="ordered"`` (default): one ``all_gather`` per sweep step,
  folded position-by-position in a static unroll.  Because position
  ``w`` holds shard ``k*W + w`` at step ``k``, the fold visits shard
  contributions in exactly the global order ``0..S-1`` — the same f32
  additions, in the same order, as the single-host shard sweep.  Each
  contribution is computed as ``0 + D_s`` (a fresh zero accumulator),
  which only normalizes ``-0`` to ``+0``; the running accumulator is
  never ``-0`` (IEEE-754 round-to-nearest: ``x + y`` is ``-0`` only
  when both are ``-0``), and ``x + (+0) == x + (-0) == x`` for every
  ``x`` the fold can hold, so the distributed fit is BIT-IDENTICAL to
  the single-host ``hist="stream"``/streaming fit — not close, equal
  (tests/test_elastic.py pins it).  Ragged-tail positions contribute
  exact ``+0`` blocks (zero-packed shards pair bin-0 rows with all-zero
  value channels), so ONE step program serves every step — program
  count stays fixed as shard and host counts vary, extending PR-8's
  contract (analysis/contracts.json ``gbm_regressor.fit_elastic``).
- ``reduce="psum"``: a single ``psum`` over the row axes — cheaper on
  DCN (reduce-scatter wire pattern vs a full gather) but f32 addition
  is not associative, so results are allclose to the single-host fit,
  not bit-equal.  Use it when throughput beats replayability.

Elasticity: the sweep polls the chaos/runtime ``host_preempt`` hook at
every step boundary.  The draw is a pure function of ``(seed, fault,
site)``, so every host reaches the same verdict at the same site
without communicating; all hosts first drain in-flight collectives,
then the victim raises :class:`~spark_ensemble_tpu.robustness.chaos.
ChaosHostPreemption` (and must leave the rendezvous) while survivors
raise :class:`HostLostError`.  :class:`ElasticCoordinator` catches it,
rebuilds the mesh from the surviving hosts' devices, and re-enters the
fit: the orphaned manifest slice is re-dealt automatically (the
round-robin layout is a pure function of ``(num_shards, W)``) and the
fit rewinds through the last committed round checkpoint — whose
fingerprint has no mesh component, so checkpoints are interchangeable
across mesh shapes.  Because the ordered fold makes every round's math
partition-invariant, the resumed fit is bit-identical to an
uninterrupted fit on the surviving mesh (and to the single-host fit).

Single-process "pods": when ``jax.process_count() == 1``, each mesh row
position plays the role of a host — ``host_preempt`` drops one
position's devices instead of one process's.  Everything else
(repartition, rewind, bit-identity) is exercised identically, which is
what lets tier-1 tests pin the elastic contract on 8 virtual CPU
devices.
"""

from __future__ import annotations

import itertools
import os
import time
import zlib
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec
from jax.experimental.shard_map import shard_map

from spark_ensemble_tpu.data.partition import (
    PartitionedShardReader,
    digest_words,
    manifest_digest,
    partition_steps,
)
from spark_ensemble_tpu.models.base import cached_program
from spark_ensemble_tpu.ops.binning import CompressedBins, unpack_bins
from spark_ensemble_tpu.ops.tree import (
    _HIST_PRECISION,
    _routing_precision,
    Tree,
    stream_leaf_step,
    stream_level_step,
)
from spark_ensemble_tpu.parallel.mesh import (
    mesh_row_axes,
    mesh_row_spec,
)
from spark_ensemble_tpu.robustness.chaos import ChaosHostPreemption
from spark_ensemble_tpu.telemetry.events import global_metrics
from spark_ensemble_tpu.telemetry.flight import dump_flight

REDUCE_MODES = ("ordered", "psum")


def preempt_flow_id(victim: int, site: str) -> int:
    """Trace-flow id tying a ``host_preempt`` span to the ``rewind``
    span of the attempt that absorbs it.  Deliberately NOT
    ``trace.new_flow_id()`` (pid-local): the preemption verdict is the
    same pure function of ``(victim, site)`` on every host, so deriving
    the id from it gives every process the SAME id without
    communicating — which is what lets ``telemetry/podview.py`` stitch
    the victim's flow_out to the survivor's flow_in across streams."""
    return zlib.crc32(f"host_preempt:{victim}:{site}".encode()) & 0x7FFFFFFF


#: flow id of the preemption this process must acknowledge on its next
#: distributed attempt (set when raising, consumed by the next
#: DistributedSweep so the rewind span carries the matching flow_in)
_PENDING_REWIND_FLOW: List[int] = []


def consume_rewind_flow() -> int:
    """Pop the pending preemption flow id (0 when none): called by every
    ``DistributedSweep.__init__`` so a stale id never leaks into an
    unrelated fit."""
    if _PENDING_REWIND_FLOW:
        fid = _PENDING_REWIND_FLOW[-1]
        _PENDING_REWIND_FLOW.clear()
        return fid
    return 0


def _site_round(site: str) -> int:
    """The stream-round index out of a sweep site string
    (``"{family}:stream_round:{r}:..."``; -1 when absent) — attached to
    the dist sweep spans so skew attribution is per-round."""
    marker = "stream_round:"
    i = site.find(marker)
    if i < 0:
        return -1
    digits = ""
    for ch in site[i + len(marker):]:
        if ch.isdigit():
            digits += ch
        else:
            break
    return int(digits) if digits else -1

#: env flag: block around every reduce dispatch and accumulate its wall
#: share (bench.py's dcn_reduce_share metric).  Off by default — the
#: fences serialize the sweep, which is a measurement mode, not a
#: production mode.
_MEASURE_ENV = "SE_TPU_DIST_MEASURE"


class HostLostError(Exception):
    """A peer host (or, single-process, a mesh row position) was
    preempted mid-round.  Raised on SURVIVORS only — the victim gets
    ``ChaosHostPreemption`` — after all in-flight collectives have
    drained, so catching it and re-entering the fit on a smaller mesh
    is always safe."""

    def __init__(self, victim: int, site: str):
        super().__init__(
            f"host {victim} preempted at {site}; rebuild the mesh from "
            "the survivors and re-enter the fit (ElasticCoordinator)"
        )
        self.victim = int(victim)
        self.site = site


def _mesh_key(mesh: Mesh) -> tuple:
    """Cache-key fingerprint of a mesh: axis names/sizes plus the flat
    device-id order.  Two elastic attempts on different surviving
    meshes must never share a program."""
    return (
        tuple(mesh.axis_names),
        tuple(int(mesh.shape[a]) for a in mesh.axis_names),
        tuple(int(d.id) for d in mesh.devices.flat),
    )


def surviving_devices(mesh: Mesh, victim: int) -> List:
    """Devices of ``mesh`` that outlive ``victim``, in mesh-flat order.

    Multi-process: the victim is a process index and all its devices
    leave.  Single-process: the victim is a mesh row position (the
    simulated host) and that position's device column leaves.
    """
    flat = list(mesh.devices.flat)
    if jax.process_count() > 1:
        out = [d for d in flat if d.process_index != victim]
    else:
        member = int(mesh.shape.get("member", 1))
        grid = np.asarray(mesh.devices).reshape(-1, member)
        out = [d for w in range(grid.shape[0]) if w != victim
               for d in grid[w]]
    if not out:
        raise ValueError(f"no devices survive losing host {victim}")
    return out


def survivor_mesh(mesh: Mesh, victim: int) -> Mesh:
    """The mesh the fit resumes on after losing ``victim`` — the
    surviving devices re-laid as a plain ``("data", "member")`` mesh
    (survivor counts are rarely slice-aligned, so the hybrid DCN axis
    is not reconstructed; collectives still ride the right links)."""
    member = int(mesh.shape.get("member", 1))
    devs = surviving_devices(mesh, victim)
    arr = np.array(devs).reshape(len(devs) // member, member)
    return Mesh(arr, ("data", "member"))


class DistributedSweep:
    """The distributed twin of ``data/streaming._sweep_forest``.

    Owns the mesh-global state of one fit: the manifest partition, the
    per-position shard feeds, the mesh programs (contribution, reduce,
    node gather, digest agreement) and the preemption hook.  One
    instance per fit attempt; ``data/streaming`` delegates its shard
    sweeps here when the fit is given a mesh.
    """

    def __init__(self, mesh: Mesh, store, *, reduce: str = "ordered",
                 telem=None):
        if reduce not in REDUCE_MODES:
            raise ValueError(
                f"reduce={reduce!r}; expected one of {REDUCE_MODES}"
            )
        if int(mesh.shape.get("member", 1)) != 1:
            raise ValueError(
                "distributed streaming shards rows only; use member=1 "
                f"(got member={mesh.shape.get('member')})"
            )
        self.mesh = mesh
        self.reduce = reduce
        self.telem = telem
        self.store = store
        self.row_axes = mesh_row_axes(mesh)
        self.row_spec = mesh_row_spec(mesh)
        self.W = 1
        for a in self.row_axes:
            self.W *= int(mesh.shape[a])
        self.S = int(store.num_shards)
        self.R = int(store.shard_rows)
        self.K = partition_steps(self.S, self.W)
        # flat [W] of row-position devices (member axis is size 1)
        self._row_devices = list(np.asarray(mesh.devices).reshape(-1))
        pidx = jax.process_index()
        self.local_positions = [
            w for w in range(self.W)
            if self._row_devices[w].process_index == pidx
        ]
        if not self.local_positions:
            raise ValueError(
                "this process owns no row position on the mesh; every "
                "participating process must contribute devices"
            )
        # "host" granularity for the preemption fault: processes when
        # actually multi-process, else simulated per-row-position hosts
        self.num_hosts = (
            jax.process_count() if jax.process_count() > 1 else self.W
        )
        self.measure = os.environ.get(_MEASURE_ENV, "") == "1"
        self.reduce_s = 0.0
        self.sweep_s = 0.0
        rewind_fid = consume_rewind_flow()
        if telem is not None:
            telem.emit(
                "dist_config", hosts=self.num_hosts, positions=self.W,
                steps=self.K, shards=self.S, reduce=reduce,
                process=pidx,
            )
            if rewind_fid:
                # this attempt absorbs a preemption: an instant span
                # whose flow_in matches the victim's host_preempt
                # flow_out (preempt_flow_id is host-symmetric), so the
                # viewer draws the rewind arrow — across hosts once the
                # streams are stitched (telemetry/podview.py)
                telem.emit_span(
                    "rewind", time.time(), 0.0, thread=f"host{pidx}",
                    flow_in=rewind_fid,
                )

    # -- manifest agreement ------------------------------------------------

    def reader(self) -> PartitionedShardReader:
        """This process's manifest slice as a prefetchable store."""
        return PartitionedShardReader(
            self.store, self.local_positions, self.W
        )

    def check_agreement(self) -> str:
        """All-gather every position's manifest digest and require them
        equal — hosts that disagree on the global row count or bin
        thresholds must fail loudly BEFORE any histogram math."""
        digest = manifest_digest(self.store)
        words = digest_words(digest)
        dig_w = self._row_global(
            {w: words for w in self.local_positions}, np.uint32
        )
        prog = self._digest_prog()
        all_w = np.asarray(prog(dig_w))
        bad = [w for w in range(self.W)
               if not np.array_equal(all_w[w], all_w[0])]
        if bad:
            raise ValueError(
                f"manifest digests disagree across the mesh at row "
                f"positions {bad}: hosts are not training on the same "
                "shard store (global n / thresholds mismatch)"
            )
        if self.telem is not None:
            self.telem.emit("dist_manifest_agreed", digest=digest[:16])
        return digest

    # -- global-array plumbing ---------------------------------------------

    def _row_sharding(self, ndim: int) -> NamedSharding:
        return NamedSharding(
            self.mesh,
            PartitionSpec(self.row_spec, *([None] * (ndim - 1))),
        )

    def _row_global(self, blocks: dict, dtype) -> jax.Array:
        """Assemble a ``[W, ...]``-leading global array from this
        process's per-position host blocks (other processes supply
        theirs — every process calls this with the same shapes)."""
        item = next(iter(blocks.values()))
        shape = (self.W,) + tuple(item.shape)
        arrs = [
            jax.device_put(
                np.asarray(blocks[w], dtype)[None], self._row_devices[w]
            )
            for w in self.local_positions
        ]
        return jax.make_array_from_single_device_arrays(
            shape, self._row_sharding(len(shape)), arrs
        )

    def _fetch(self, arr) -> np.ndarray:
        """Replicated global array -> host numpy (every process holds a
        full copy, so the fetch is addressable everywhere)."""
        return np.asarray(arr)

    # -- mesh programs -----------------------------------------------------

    def _shmap(self, fn, in_specs, out_specs):
        return jax.jit(
            shard_map(
                fn, mesh=self.mesh, in_specs=in_specs,
                out_specs=out_specs, check_rep=False,
            )
        )

    def _digest_prog(self):
        mk = _mesh_key(self.mesh)
        row, names = self.row_spec, self.row_axes

        def build():
            def run(dig):  # [1, 8] per position
                return jax.lax.all_gather(
                    dig[0], names, axis=0, tiled=False
                )  # [W, 8] replicated

            return self._shmap(
                run, (PartitionSpec(row, None),), PartitionSpec()
            )

        return cached_program(("dist_digest", mk), build)

    def _zeros_prog(self, shape: tuple, dtype, sharded: bool):
        """Mesh-placed zeros — initial accumulators (replicated) and
        per-position node state (row-sharded)."""
        mk = _mesh_key(self.mesh)
        spec = (
            PartitionSpec(self.row_spec, *([None] * (len(shape) - 1)))
            if sharded
            else PartitionSpec()
        )
        sh = NamedSharding(self.mesh, spec)

        def build():
            return jax.jit(
                lambda: jnp.zeros(shape, dtype), out_shardings=sh
            )

        return cached_program(
            ("dist_zeros", mk, tuple(shape), np.dtype(dtype).str, sharded),
            build,
        )

    def _level_contrib_prog(self, level: int, B: int, bits: int, d: int,
                            prec: str):
        """Each position's contribution to level ``level`` at step ``k``:
        the resident ``stream_level_step`` over its own shard, folded
        into a FRESH zero accumulator (``0 + D_s`` — see module
        docstring for why that preserves bit-identity)."""
        mk = _mesh_key(self.mesh)
        stat_prec = _HIST_PRECISION[prec]
        route_prec = _routing_precision(B)
        n_nodes = 2 ** level
        row = self.row_spec

        def build():
            def step(packed, node_w, vals_w, k, tables):
                # per-position blocks: packed [1,R,words], node [1,K,R,M],
                # vals [1,K,R,M,C]
                xb = unpack_bins(
                    CompressedBins(
                        packed=packed[0], bits=bits, num_features=d
                    )
                )
                nd = jax.lax.dynamic_index_in_dim(
                    node_w[0], k, axis=0, keepdims=False
                )
                vl = jax.lax.dynamic_index_in_dim(
                    vals_w[0], k, axis=0, keepdims=False
                )
                M, C = vl.shape[1], vl.shape[2]
                zero = jnp.zeros((M, n_nodes, C, d, B), jnp.float32)
                contrib, nd = stream_level_step(
                    zero, xb, nd, vl, n_nodes=n_nodes, tables=tables,
                    max_bins=B, stat_prec=stat_prec,
                    route_prec=route_prec,
                )
                node_w = jax.lax.dynamic_update_index_in_dim(
                    node_w[0], nd, k, axis=0
                )[None]
                return contrib[None], node_w

            if level == 0:
                run = lambda packed, node_w, vals_w, k: step(
                    packed, node_w, vals_w, k, None
                )
                in_specs = (
                    PartitionSpec(row, None, None),
                    PartitionSpec(row, None, None, None),
                    PartitionSpec(row, None, None, None, None),
                    PartitionSpec(),
                )
            else:
                run = lambda packed, node_w, vals_w, k, bf, bt: step(
                    packed, node_w, vals_w, k, (bf, bt)
                )
                in_specs = (
                    PartitionSpec(row, None, None),
                    PartitionSpec(row, None, None, None),
                    PartitionSpec(row, None, None, None, None),
                    PartitionSpec(),
                    PartitionSpec(),
                    PartitionSpec(),
                )
            out_specs = (
                PartitionSpec(row, None, None, None, None, None),
                PartitionSpec(row, None, None, None),
            )
            return self._shmap(run, in_specs, out_specs)

        return cached_program(
            ("dist_level_contrib", mk, level, B, bits, d, prec), build
        )

    def _leaf_contrib_prog(self, max_depth: int, B: int, bits: int,
                           d: int, prec: str):
        mk = _mesh_key(self.mesh)
        stat_prec = _HIST_PRECISION[prec]
        route_prec = _routing_precision(B)
        num_leaves = 2 ** max_depth
        row = self.row_spec

        def build():
            def run(packed, node_w, vals_w, k, bf, bt):
                xb = unpack_bins(
                    CompressedBins(
                        packed=packed[0], bits=bits, num_features=d
                    )
                )
                nd = jax.lax.dynamic_index_in_dim(
                    node_w[0], k, axis=0, keepdims=False
                )
                vl = jax.lax.dynamic_index_in_dim(
                    vals_w[0], k, axis=0, keepdims=False
                )
                M, C = vl.shape[1], vl.shape[2]
                zero = jnp.zeros((M, num_leaves, C), jnp.float32)
                contrib, nd = stream_leaf_step(
                    zero, xb, nd, vl, num_leaves=num_leaves,
                    tables=(bf, bt), stat_prec=stat_prec,
                    route_prec=route_prec,
                )
                node_w = jax.lax.dynamic_update_index_in_dim(
                    node_w[0], nd, k, axis=0
                )[None]
                return contrib[None], node_w

            in_specs = (
                PartitionSpec(row, None, None),
                PartitionSpec(row, None, None, None),
                PartitionSpec(row, None, None, None, None),
                PartitionSpec(),
                PartitionSpec(),
                PartitionSpec(),
            )
            out_specs = (
                PartitionSpec(row, None, None, None),
                PartitionSpec(row, None, None, None),
            )
            return self._shmap(run, in_specs, out_specs)

        return cached_program(
            ("dist_leaf_contrib", mk, max_depth, B, bits, d, prec), build
        )

    def _reduce_prog(self):
        """Fold the W per-position contributions into the running
        accumulator: static position-order unroll under ``ordered``
        (bit-exact, see module docstring), one ``psum`` otherwise.
        Shape-polymorphic: one cached program serves every level and
        the leaf sweep (jit re-traces per shape under the same key)."""
        mk = _mesh_key(self.mesh)
        mode = self.reduce
        names = self.row_axes
        row = self.row_spec
        W = self.W

        def build():
            def run(acc, contrib):  # acc replicated, contrib [1, ...]
                c = contrib[0]
                if mode == "psum":
                    return acc + jax.lax.psum(c, names)
                gathered = jax.lax.all_gather(
                    c, names, axis=0, tiled=False
                )  # [W, ...] — position-major == global shard order
                for w in range(W):
                    acc = acc + gathered[w]
                return acc

            # shard_map in_specs depend on rank, so keep one jitted
            # instance per contrib rank (jit itself re-traces per shape)
            jits = {}

            def runner(acc, contrib):
                f = jits.get(contrib.ndim)
                if f is None:
                    in_specs = (
                        PartitionSpec(),
                        PartitionSpec(row, *([None] * (contrib.ndim - 1))),
                    )
                    f = jits.setdefault(
                        contrib.ndim,
                        self._shmap(run, in_specs, PartitionSpec()),
                    )
                return f(acc, contrib)

            return runner

        return cached_program(("dist_reduce", mk, mode), build)

    def _gather_nodes_prog(self):
        """Collect every position's swept node ids back into the
        single-host ``node_all [S, R, M]`` layout (exact int ops)."""
        mk = _mesh_key(self.mesh)
        names = self.row_axes
        row = self.row_spec
        W, K, S = self.W, self.K, self.S

        def build():
            def run(node_w):  # [1, K, R, M] per position
                g = jax.lax.all_gather(
                    node_w[0], names, axis=0, tiled=False
                )  # [W, K, R, M]
                g = jnp.transpose(g, (1, 0, 2, 3))  # [K, W, R, M]
                return g.reshape((K * W,) + g.shape[2:])[:S]

            return self._shmap(
                run,
                (PartitionSpec(row, None, None, None),),
                PartitionSpec(),
            )

        return cached_program(("dist_gather_nodes", mk, K, S), build)

    # -- sweep mechanics ---------------------------------------------------

    def _scatter_vals(self, vals_np: np.ndarray) -> jax.Array:
        """Host ``vals_p [S, R, M, C]`` -> global ``[W, K, R, M, C]``
        in round-robin step-major layout; steps past the manifest end
        are zero blocks (exact ``+0`` contributions)."""
        S, R, M, C = vals_np.shape
        zero = np.zeros((R, M, C), np.float32)
        blocks = {}
        for w in self.local_positions:
            blocks[w] = np.stack([
                vals_np[k * self.W + w]
                if k * self.W + w < S else zero
                for k in range(self.K)
            ])
        return self._row_global(blocks, np.float32)

    def _collect_step(self, sweep_iter) -> jax.Array:
        """Next P prefetched blocks -> global ``packed [W, R, words]``
        for one step (the reader yields step-major, position order)."""
        blocks = {}
        for w in self.local_positions:
            _, packed = next(sweep_iter)
            blocks[w] = np.asarray(packed)
        return self._row_global(blocks, np.uint32)

    def _run_reduce(self, red, acc, contrib):
        if not self.measure:
            return red(acc, contrib)
        t0 = time.perf_counter()
        acc = red(acc, contrib)
        jax.block_until_ready(acc)
        self.reduce_s += time.perf_counter() - t0
        return acc

    def _maybe_preempt(self, ctl, site: str, *pending):
        """Chaos seam: symmetric deterministic verdict, drain, then
        victim/survivor-specific raise (see chaos.host_preempt).

        Before raising, this is the flush-on-crash chokepoint
        (docs/tracing.md#pod-scope): the victim's buffered telemetry is
        fsync'd to its JSONL sink and the flight-recorder ring is dumped
        — a preempted process may be SIGKILLed the moment it leaves the
        rendezvous, and the black box must already be on disk."""
        hook = getattr(ctl, "host_preempt", None)
        if hook is None or not hook(site):
            return
        victim = ctl.pick("host_preempt", site, self.num_hosts)
        # drain: nobody may stop participating while a collective is in
        # flight, or the survivors hang inside XLA instead of rewinding
        # graftlint: ignore[unfenced-blocking-read] -- preemption teardown path; the fit is being abandoned, there is no dispatch pipeline left to charge the wait to
        jax.block_until_ready([p for p in pending if p is not None])
        fid = preempt_flow_id(victim, site)
        if self.telem is not None:
            self.telem.emit("host_preempted", victim=victim, site=site)
            if jax.process_count() == 1 or victim == jax.process_index():
                # the flow SOURCE is victim-only in multi-process mode:
                # a survivor's standalone stream must fail --validate on
                # the rewind's unresolved flow_in, proving the pod view
                # is needed — stitching restores the arrow
                self.telem.emit_span(
                    "host_preempt", time.time(), 0.0,
                    thread=f"host{jax.process_index()}",
                    flow_out=[fid], victim=victim, site=site,
                )
            self.telem.flush(fsync=True)
        _PENDING_REWIND_FLOW.clear()
        _PENDING_REWIND_FLOW.append(fid)
        dump_flight(
            reason="host_preempt",
            telemetry_path=getattr(self.telem, "_path", None),
            extra={"victim": victim, "site": site,
                   "process_index": jax.process_index()},
        )
        if jax.process_count() > 1 and victim == jax.process_index():
            raise ChaosHostPreemption(
                f"chaos: host {victim} preempted at {site}"
            )
        raise HostLostError(victim, site)

    def _maybe_stall(self, ctl, site: str) -> None:
        """Straggler chaos seam: the ``host_stall`` verdict is symmetric
        (same pure draw on every host) but only the picked victim
        sleeps, dragging its sweep step — the skew the pod report
        (telemetry/podview.py ``skew_report``) must attribute."""
        hook = getattr(ctl, "host_stall_s", None)
        if hook is None:
            return
        seconds = hook(site)
        if seconds <= 0:
            return
        victim = ctl.pick("host_stall", site, self.num_hosts)
        if jax.process_count() > 1 and victim != jax.process_index():
            return  # peers saw the same draw; only the victim drags
        if self.telem is not None:
            self.telem.emit(
                "host_stalled", victim=victim, site=site,
                seconds=float(seconds),
            )
        time.sleep(seconds)

    def sweep_forest(self, prefetch, ctl, site, vals_p, y_mean, mask,
                     thresholds, *, max_depth, B, bits, d, prec,
                     min_gain):
        """Distributed twin of ``streaming._sweep_forest``: same
        signature, same return contract ``(Tree [M, ...], node_all
        [S, R, M])``, bit-identical outputs under ``reduce="ordered"``.
        The level/leaf *finish* programs stay host-local and shared
        with the single-host path — only the sweeps ride the mesh."""
        from spark_ensemble_tpu.data.streaming import (
            _leaf_finish_prog,
            _level_finish_prog,
        )

        S, R, M, C = vals_p.shape
        t_fetch0 = time.perf_counter()
        vals_np = np.asarray(vals_p)
        if self.telem is not None:
            self.telem.host_blocked(time.perf_counter() - t_fetch0)
        vals_w = self._scatter_vals(vals_np)
        node_w = self._zeros_prog(
            (self.W, self.K, R, M), np.int32, sharded=True
        )()
        num_internal = 2 ** max_depth - 1
        sf = jnp.zeros((M, num_internal), jnp.int32)
        sb = jnp.zeros((M, num_internal), jnp.int32)
        stt = jnp.zeros((M, num_internal), jnp.float32)
        sg = jnp.zeros((M, num_internal), jnp.float32)
        parent_value = y_mean[:, None, :]
        best_f = best_t = None
        bf_np = bt_np = None
        red = self._reduce_prog()
        thread = f"host{jax.process_index()}"
        rnd = _site_round(site)
        for level in range(max_depth):
            t_lvl = time.time()
            t0 = time.perf_counter()
            prog = self._level_contrib_prog(level, B, bits, d, prec)
            acc = self._zeros_prog(
                (M, 2 ** level, C, d, B), np.float32, sharded=False
            )()
            sweep_iter = prefetch.sweep()
            for k in range(self.K):
                step_site = f"{site}:level:{level}:dist_step:{k}"
                self._maybe_stall(ctl, step_site)
                self._maybe_preempt(ctl, step_site, acc, node_w)
                packed_w = self._collect_step(sweep_iter)
                if level == 0:
                    contrib, node_w = prog(
                        packed_w, node_w, vals_w, np.int32(k)
                    )
                else:
                    contrib, node_w = prog(
                        packed_w, node_w, vals_w, np.int32(k),
                        bf_np, bt_np,
                    )
                acc = self._run_reduce(red, acc, contrib)
            # replicated accumulator -> host-local operands for the
            # SHARED finish program (byte-identical to single-host)
            t_fetch0 = time.perf_counter()
            steps_s = t_fetch0 - t0
            acc_h = jnp.asarray(self._fetch(acc))
            fetch_s = time.perf_counter() - t_fetch0
            if self.telem is not None:
                self.telem.host_blocked(fetch_s)
            fin = _level_finish_prog(level, B, d, prec, min_gain)
            best_f, best_t, parent_value, sf, sb, stt, sg = fin(
                acc_h, mask, thresholds, parent_value, sf, sb, stt, sg
            )
            # the contribution programs take the tables as replicated
            # host values: every process feeds the same bytes, which is
            # exactly what multi-process jit requires of non-addressable
            # inputs
            t_fetch0 = time.perf_counter()
            bf_np = np.asarray(best_f)
            bt_np = np.asarray(best_t)
            dur = time.perf_counter() - t0
            if self.telem is not None:
                self.telem.host_blocked(time.perf_counter() - t_fetch0)
                # steps_s/fetch_s split the level wall at the blocking
                # reduce fetch — the cross-host sync barrier podview
                # estimates clock offsets at and skew_report attributes
                # stragglers with (docs/tracing.md#pod-scope)
                self.telem.emit_span(
                    f"dist_level_{level}", t_lvl, dur, thread=thread,
                    steps=self.K, steps_s=steps_s, fetch_s=fetch_s,
                    round=rnd,
                )
            self.sweep_s += dur
        t_lvl = time.time()
        t0 = time.perf_counter()
        leaf = self._leaf_contrib_prog(max_depth, B, bits, d, prec)
        acc = self._zeros_prog(
            (M, 2 ** max_depth, C), np.float32, sharded=False
        )()
        sweep_iter = prefetch.sweep()
        for k in range(self.K):
            step_site = f"{site}:leaf:dist_step:{k}"
            self._maybe_stall(ctl, step_site)
            self._maybe_preempt(ctl, step_site, acc, node_w)
            packed_w = self._collect_step(sweep_iter)
            contrib, node_w = leaf(
                packed_w, node_w, vals_w, np.int32(k), bf_np, bt_np
            )
            acc = self._run_reduce(red, acc, contrib)
        t_fetch0 = time.perf_counter()
        steps_s = t_fetch0 - t0
        acc_h = jnp.asarray(self._fetch(acc))
        node_all = jnp.asarray(
            self._fetch(self._gather_nodes_prog()(node_w))
        )
        fetch_s = time.perf_counter() - t_fetch0
        if self.telem is not None:
            self.telem.host_blocked(fetch_s)
        leaf_value = _leaf_finish_prog()(acc_h, parent_value, y_mean)
        dur = time.perf_counter() - t0
        if self.telem is not None:
            self.telem.emit_span(
                "dist_leaf", t_lvl, dur, thread=thread, steps=self.K,
                steps_s=steps_s, fetch_s=fetch_s, round=rnd,
            )
        self.sweep_s += dur
        tree = Tree(
            split_feature=sf, split_bin=sb, split_threshold=stt,
            leaf_value=leaf_value, split_gain=sg,
        )
        return tree, node_all

    def take_stats(self) -> dict:
        """Cumulative sweep/reduce wall (reduce only measured under
        SE_TPU_DIST_MEASURE=1); resets the counters."""
        out = {"sweep_s": self.sweep_s, "reduce_s": self.reduce_s}
        self.sweep_s = 0.0
        self.reduce_s = 0.0
        return out


#: stats of the most recent distributed fit in this process (bench.py
#: reads the reduce share from here — the sweep object itself lives and
#: dies inside the fit call)
_LAST_FIT_STATS: dict = {}


def last_fit_stats() -> dict:
    return dict(_LAST_FIT_STATS)


def _record_fit_stats(dist: DistributedSweep) -> None:
    stats = dist.take_stats()
    _LAST_FIT_STATS.clear()
    _LAST_FIT_STATS.update(stats)
    if dist.telem is not None:
        dist.telem.emit(
            "dist_sweep",
            sweep_us=int(stats["sweep_s"] * 1e6),
            reduce_us=int(stats["reduce_s"] * 1e6),
        )


_COORD_SEQ = itertools.count()


class ElasticCoordinator:
    """Detect -> drain -> repartition -> rewind -> resume.

    Wraps a distributed ``fit_streaming`` call in the preemption-
    recovery loop: on :class:`HostLostError` the coordinator rebuilds
    the mesh from the survivors (``survivor_mesh``), and re-enters the
    fit — which repartitions the manifest over the new mesh for free
    (round-robin is a pure function of the mesh width) and rewinds
    through the estimator's last committed round checkpoint.  Give the
    estimator a ``checkpoint_dir`` or the "rewind" is a full replay
    from round 0 (still bit-identical, just slower).

    The victim process must NOT use this class to keep training — it
    receives ``ChaosHostPreemption`` (or a real SIGTERM) and leaves;
    ``max_losses`` bounds how many peers the survivors will absorb.
    """

    def __init__(self, mesh: Mesh, *, reduce: str = "ordered",
                 max_losses: int = 2):
        if reduce not in REDUCE_MODES:
            raise ValueError(
                f"reduce={reduce!r}; expected one of {REDUCE_MODES}"
            )
        self.mesh = mesh
        self.reduce = reduce
        self.max_losses = int(max_losses)
        #: (victim, site, surviving_width) per absorbed preemption
        self.losses: List[Tuple[int, str, int]] = []
        #: fit attempts entered (1 for an uninterrupted fit)
        self.attempts = 0
        self._t0 = time.time()
        self._label = f"elastic:{os.getpid()}:{next(_COORD_SEQ)}"
        self._source_name = f"elastic/{self._label}"

    def statusz(self) -> dict:
        """Live coordinator state, mirroring ``FleetRouter.statusz()``:
        the current mesh shape, absorbed losses and attempt count, plus
        the last distributed fit's sweep/reduce walls.  Registered as a
        ``global_metrics()`` source for the duration of each
        ``fit_streaming`` call, so a mid-fit snapshot (/statusz pages,
        the flight-recorder dump) shows where the pod stands — and the
        operator plane's /metrics exporter flattens the numeric leaves
        into the ``se_tpu_elastic`` gauge family (docs/operator.md)."""
        width = 1
        for a in mesh_row_axes(self.mesh):
            width *= int(self.mesh.shape[a])
        return {
            "label": self._label,
            "uptime_s": time.time() - self._t0,
            "reduce": self.reduce,
            "mesh_axes": {
                a: int(self.mesh.shape[a]) for a in self.mesh.axis_names
            },
            "width": width,
            "process_index": int(jax.process_index()),
            "process_count": int(jax.process_count()),
            "attempts": self.attempts,
            "max_losses": self.max_losses,
            "losses": [
                {"victim": v, "site": s, "width": w}
                for v, s, w in self.losses
            ],
            "last_fit": last_fit_stats(),
        }

    def fit_streaming(self, est, store, y, **kw):
        """Run ``est.fit_streaming(store, y, mesh=..., reduce=...)``
        to completion, absorbing up to ``max_losses`` host losses.
        Returns the fitted model; ``self.mesh`` ends as the mesh the
        fit actually finished on."""
        metrics = global_metrics()
        metrics.register_source(self._source_name, self.statusz)
        # drop any stale preemption flow left by an ABANDONED fit (a
        # loss over max_losses re-raises with the id still pending) —
        # a fresh fit's first attempt is not a resume and must not emit
        # a phantom rewind span
        consume_rewind_flow()
        try:
            while True:
                self.attempts += 1
                try:
                    return est.fit_streaming(
                        store, y, mesh=self.mesh, reduce=self.reduce, **kw
                    )
                except HostLostError as e:
                    if len(self.losses) >= self.max_losses:
                        raise
                    self.mesh = survivor_mesh(self.mesh, e.victim)
                    width = int(np.prod([
                        self.mesh.shape[a]
                        for a in mesh_row_axes(self.mesh)
                    ]))
                    self.losses.append((e.victim, e.site, width))
        finally:
            metrics.unregister_source(self._source_name)
            consume_rewind_flow()  # never leak into a later fit
