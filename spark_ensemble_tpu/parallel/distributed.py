"""Distributed GBM training step: rows sharded over "data", class dims over
"member", XLA collectives over both axes.

This is the SPMD replacement for the reference's entire distribution story
for one boosting round (`GBMClassifier.scala:325-483`):

| reference (Spark)                        | here (XLA)                        |
|------------------------------------------|-----------------------------------|
| RDD rows on executors                    | rows sharded over mesh "data"     |
| treeReduce/treeAggregate(hessian sums,   | lax.psum over "data"              |
|   split histograms via base-learner jobs)|                                   |
| driver Futures over K class dims         | class-dim block sharded over      |
|                                          |   "member", all_gather to rejoin  |
| Broadcast(line-search coefficients)      | replicated operands (SPMD)        |
| breeze LBFGS-B on the driver, each       | projected Newton inside the       |
|   evaluation a distributed pass          |   shard_map; psum per evaluation  |

One call = one full GBM round (pseudo-residuals -> K tree fits -> K-dim
line search -> prediction update) as a single jitted SPMD program.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from spark_ensemble_tpu.ops.linesearch import projected_newton_box
from spark_ensemble_tpu.ops.tree import fit_tree, predict_tree_binned


def make_sharded_gbm_round(
    mesh: Mesh,
    loss,
    *,
    max_depth: int = 5,
    max_bins: int = 64,
    learning_rate: float = 1.0,
    updates: str = "newton",
    optimized_weights: bool = True,
    line_search_iters: int = 10,
):
    """Build the jitted SPMD round step.

    Inputs (global shapes; K = loss.dim must divide the "member" axis size):
      Xb        i32[n, d]   binned features      sharded P("data", None)
      thresholds f32[d, B-1]                     replicated
      y_enc     f32[n, K]   encoded labels       sharded P("data", None)
      pred      f32[n, K]   raw predictions      sharded P("data", None)
      w         f32[n]      instance weights     sharded P("data")
      bag_w     f32[n]      bag multiplicities   sharded P("data")

    Returns (trees stacked over the LOCAL class block [K_local, ...],
    step_weights f32[K], new_pred sharded like pred).
    """
    dim = loss.dim
    member_size = mesh.shape["member"]
    assert dim % member_size == 0, (dim, member_size)

    def round_fn(Xb, thresholds, y_enc, pred, w, bag_w):
        # ---- pseudo-residuals (local rows, local class block) -------------
        # y_enc/pred carry the FULL class dim on each member shard (they are
        # only sharded over rows); the member axis picks its class block for
        # the tree fits.
        from spark_ensemble_tpu.models.gbm import _pseudo_residuals_and_weights

        midx = jax.lax.axis_index("member")
        k_local = dim // member_size
        sl = midx * k_local

        labels, fit_w_all = _pseudo_residuals_and_weights(
            loss, updates, y_enc, pred, bag_w, w, axis_name="data"
        )

        labels_blk = jax.lax.dynamic_slice_in_dim(labels, sl, k_local, axis=1)
        fitw_blk = jax.lax.dynamic_slice_in_dim(fit_w_all, sl, k_local, axis=1)

        # ---- K_local tree fits, histograms psum-ed over "data" ------------
        fit_one = lambda lab, fw: fit_tree(
            Xb,
            lab[:, None],
            fw,
            thresholds,
            max_depth=max_depth,
            max_bins=max_bins,
            axis_name="data",
        )
        trees = jax.vmap(fit_one, in_axes=(1, 1))(labels_blk, fitw_blk)

        # ---- directions: local block predict, gathered over "member" ------
        dir_blk = jax.vmap(lambda t: predict_tree_binned(t, Xb)[:, 0])(trees).T
        directions = jax.lax.all_gather(
            dir_blk, "member", axis=1, tiled=True
        )  # [n_loc, K]

        # ---- K-dim line search, value/grad/hess psum-ed over "data" -------
        if optimized_weights:

            def phi(a):
                # shard-local; projected_newton_box psums over "data" itself
                # (a psum inside the objective would yield local gradients)
                return jnp.sum(
                    bag_w * loss.loss(y_enc, pred + a[None, :] * directions)
                )

            alpha = projected_newton_box(
                phi,
                jnp.ones((dim,), jnp.float32),
                max_iter=line_search_iters,
                axis_name="data",
            )
        else:
            alpha = jnp.ones((dim,), jnp.float32)
        step_w = learning_rate * alpha
        new_pred = pred + step_w[None, :] * directions
        return trees, step_w, new_pred

    sharded = shard_map(
        round_fn,
        mesh=mesh,
        in_specs=(
            P("data", None),  # Xb
            P(),  # thresholds
            P("data", None),  # y_enc
            P("data", None),  # pred
            P("data"),  # w
            P("data"),  # bag_w
        ),
        out_specs=(P("member"), P(), P("data", None)),
        check_vma=False,
    )
    return jax.jit(sharded)
