from spark_ensemble_tpu.parallel import multihost
from spark_ensemble_tpu.parallel.mesh import (
    create_mesh,
    data_member_mesh,
    hybrid_data_member_mesh,
)

__all__ = [
    "create_mesh",
    "data_member_mesh",
    "hybrid_data_member_mesh",
    "multihost",
]
