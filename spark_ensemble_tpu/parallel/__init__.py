from spark_ensemble_tpu.parallel import multihost
from spark_ensemble_tpu.parallel.elastic import (
    DistributedSweep,
    ElasticCoordinator,
    HostLostError,
    survivor_mesh,
)
from spark_ensemble_tpu.parallel.mesh import (
    create_mesh,
    data_member_mesh,
    hybrid_data_member_mesh,
)
from spark_ensemble_tpu.parallel.multihost import slice_count

__all__ = [
    "DistributedSweep",
    "ElasticCoordinator",
    "HostLostError",
    "create_mesh",
    "data_member_mesh",
    "hybrid_data_member_mesh",
    "multihost",
    "slice_count",
    "survivor_mesh",
]
