"""Multi-host (multi-process) initialization — the connect-to-cluster step.

The reference's distribution story starts with a SparkSession bound to a
master (`local[*]` in its tests, a cluster URL in production); everything
after that is RDD mechanics.  The TPU-native analogue: each host process
calls :func:`initialize` once (the ``jax.distributed`` rendezvous — on Cloud
TPU pods the coordinator/process count/index are auto-detected from the TPU
metadata), after which ``jax.devices()`` spans EVERY host's chips and the
``parallel.mesh`` constructors build global meshes whose collectives ride
ICI within a slice and DCN across slices/hosts.  Estimator ``fit(...,
mesh=...)`` then runs unchanged: the SPMD programs this package builds are
single-controller-per-host jit programs, exactly what multi-host JAX
expects (SURVEY.md §2.5, §5 "Distributed communication backend").

Typical pod usage (same program on every host):

    import jax
    from spark_ensemble_tpu.parallel import multihost, mesh

    multihost.initialize()                    # auto-detect on Cloud TPU
    m = mesh.hybrid_data_member_mesh(dcn_data="auto")  # dcn_data = slice count
    model = GBMClassifier(...).fit(X_local, y_local, mesh=m)

(``dcn_data="auto"`` resolves via :func:`slice_count` — the SLICE count,
NOT the host count: one slice may span several host processes, and the
DCN axis groups by slice.)

(Every process must pass the same global arrays / shardings; use
``jax.make_array_from_process_local_data`` for per-host input pipelines.)
"""

from __future__ import annotations

from typing import Optional

import jax

# set once initialize() has joined (or decided to skip) the rendezvous —
# jax.process_count() CANNOT serve as the guard, because calling it
# instantiates the local backend, after which jax.distributed.initialize
# refuses to run ("must be called before any JAX computations")
_initialized = False


def _already_distributed() -> bool:
    """Whether the distributed client already exists, WITHOUT touching the
    backend (the private global_state probe is the only pre-init check jax
    offers; degrade to the module flag if it moves)."""
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:  # noqa: BLE001 - private API may move between versions
        return _initialized


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-process JAX rendezvous (idempotent; single-process
    runs may skip this entirely).

    With no arguments, environment auto-detection applies (Cloud TPU
    metadata, or the ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/
    ``JAX_PROCESS_ID`` variables).  Explicit arguments mirror
    ``jax.distributed.initialize`` — all three must be supplied together.
    """
    global _initialized
    explicit = (coordinator_address, num_processes, process_id)
    if any(v is not None for v in explicit) and any(
        v is None for v in explicit
    ):
        raise ValueError(
            "coordinator_address, num_processes, and process_id must be "
            "passed together (or all omitted for auto-detection)"
        )
    if _initialized or _already_distributed():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def slice_count(devices: Optional[list] = None) -> int:
    """Number of distinct TPU slices across ``devices`` (default: all).

    This is the right ``dcn_data`` axis size for
    ``mesh.hybrid_data_member_mesh``: the DCN axis groups devices by
    slice, and one slice may span several host processes, so neither
    ``process_count()`` nor host count is a substitute.  Devices without
    a ``slice_index`` (CPU, single-slice) count as one slice.
    """
    devs = list(devices) if devices is not None else jax.devices()
    return max(len({getattr(d, "slice_index", 0) for d in devs}), 1)


def process_count() -> int:
    """Number of host processes in the rendezvous (1 when single-process)."""
    return jax.process_count()


def process_index() -> int:
    """This host's index (0 when single-process)."""
    return jax.process_index()


def local_device_count() -> int:
    """Chips attached to THIS host (``jax.local_device_count()``)."""
    return jax.local_device_count()
