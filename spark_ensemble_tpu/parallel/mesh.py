"""Device-mesh construction for the framework's two parallel axes.

The reference's parallelism axes (SURVEY.md §2.5) are:
- **rows** (data parallelism): RDD partitions over Spark executors, reduced
  with ``treeReduce``/``treeAggregate``;
- **ensemble members / class dims** (task parallelism): driver thread-pool
  Futures (`BaggingClassifier.scala:180-201`, `GBMClassifier.scala:377-411`).

The TPU-native mapping is a 2-D ``jax.sharding.Mesh`` with axes
``("data", "member")``: rows sharded over ``data`` (reductions become
``psum`` over ICI), members/class-dims sharded over ``member``.  On
multi-slice pods, put ``data`` on the DCN-spanning axis (gradient-style
psums tolerate DCN latency) and ``member`` within a slice.  The reference
has no sequence dimension, so there is no sequence/context-parallel axis —
rows x members IS the scaling surface (SURVEY.md §5).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def create_mesh(axis_sizes: dict, devices: Optional[Sequence] = None) -> Mesh:
    """Mesh from ``{axis_name: size}``; sizes must multiply to #devices."""
    devices = list(devices if devices is not None else jax.devices())
    shape = tuple(axis_sizes.values())
    total = int(np.prod(shape))
    if total != len(devices):
        raise ValueError(
            f"mesh {axis_sizes} needs {total} devices, have {len(devices)}"
        )
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(axis_sizes.keys()))


def data_member_mesh(
    n_devices: Optional[int] = None, member: int = 1
) -> Mesh:
    """The standard ("data", "member") mesh; ``member`` divides n_devices."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if n % member != 0:
        raise ValueError(f"member axis {member} must divide device count {n}")
    return create_mesh(
        {"data": n // member, "member": member}, devices=devices[:n]
    )


def data_sharding(mesh: Mesh, *batch_axis_first: int) -> NamedSharding:
    """Rows-on-data sharding for an array whose axis 0 is the row axis."""
    return NamedSharding(mesh, PartitionSpec("data"))


def pad_to_multiple(x, multiple: int, axis: int = 0, fill=0.0):
    """Pad the row axis so it divides the data-axis size.  Padding rows get
    weight 0 downstream, so statistics are unchanged (weight-mask sampling
    makes padding free — SURVEY.md §2.5 row-sampling note)."""
    import jax.numpy as jnp

    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, 0)] * x.ndim
    pad_width[axis] = (0, rem)
    return jnp.pad(x, pad_width, constant_values=fill), n
