"""Device-mesh construction for the framework's two parallel axes.

The reference's parallelism axes (SURVEY.md §2.5) are:
- **rows** (data parallelism): RDD partitions over Spark executors, reduced
  with ``treeReduce``/``treeAggregate``;
- **ensemble members / class dims** (task parallelism): driver thread-pool
  Futures (`BaggingClassifier.scala:180-201`, `GBMClassifier.scala:377-411`).

The TPU-native mapping is a 2-D ``jax.sharding.Mesh`` with axes
``("data", "member")``: rows sharded over ``data`` (reductions become
``psum`` over ICI), members/class-dims sharded over ``member``.  On
multi-slice pods, put ``data`` on the DCN-spanning axis (gradient-style
psums tolerate DCN latency) and ``member`` within a slice.  The reference
has no sequence dimension, so there is no sequence/context-parallel axis —
rows x members IS the scaling surface (SURVEY.md §5).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def create_mesh(axis_sizes: dict, devices: Optional[Sequence] = None) -> Mesh:
    """Mesh from ``{axis_name: size}``; sizes must multiply to #devices."""
    devices = list(devices if devices is not None else jax.devices())
    shape = tuple(axis_sizes.values())
    total = int(np.prod(shape))
    if total != len(devices):
        raise ValueError(
            f"mesh {axis_sizes} needs {total} devices, have {len(devices)}"
        )
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(axis_sizes.keys()))


def data_member_mesh(
    n_devices: Optional[int] = None, member: int = 1
) -> Mesh:
    """The standard ("data", "member") mesh; ``member`` divides n_devices."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if n % member != 0:
        raise ValueError(f"member axis {member} must divide device count {n}")
    return create_mesh(
        {"data": n // member, "member": member}, devices=devices[:n]
    )


def hybrid_data_member_mesh(
    dcn_data=1, member: int = 1, devices: Optional[Sequence] = None
) -> Mesh:
    """Multi-slice pod mesh: ``("dcn_data", "data", "member")``.

    ``dcn_data="auto"`` sizes the DCN axis to the slice count of the
    participating devices (``multihost.slice_count``) — the recipe pod
    users previously copy-pasted from the multihost module docstring.

    The outer ``dcn_data`` axis spans slices over DCN; ``data`` and
    ``member`` stay within a slice on ICI.  Row reductions then decompose
    into a fast ICI psum per slice plus one small cross-slice psum over
    ``dcn_data`` — histogram/hessian/objective sums are gradient-like
    reductions that tolerate DCN latency (module docstring).  Estimator
    fits accept this mesh directly: pass shardings with rows split over
    ``("dcn_data", "data")``.

    On multi-slice TPU hardware the device order comes from
    ``mesh_utils.create_hybrid_device_mesh`` (DCN-aware placement); on
    single-slice or CPU devices it falls back to a plain reshape, which is
    functionally identical (collectives still compile and run — placement
    is a performance detail the real pod supplies).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dcn_data == "auto":
        from spark_ensemble_tpu.parallel.multihost import slice_count

        dcn_data = slice_count(devices)
    dcn_data = int(dcn_data)
    if n % (dcn_data * member) != 0:
        raise ValueError(
            f"dcn_data={dcn_data} * member={member} must divide {n} devices"
        )
    ici_data = n // (dcn_data * member)
    shape = (dcn_data, ici_data, member)
    if getattr(devices[0], "slice_index", None) is None:
        # single-slice / CPU devices: no slice topology to respect; a plain
        # reshape is functionally identical (placement is a perf detail the
        # real pod supplies)
        arr = np.array(devices).reshape(shape)
    else:
        # real multi-slice topology: DCN-aware placement; configuration
        # errors (e.g. dcn_data != slice count) must propagate, not be
        # silently reshaped across slice boundaries
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            (1, ici_data, member),
            (dcn_data, 1, 1),
            devices=devices,
        )
    return Mesh(arr, ("dcn_data", "data", "member"))


def data_sharding(mesh: Mesh, *batch_axis_first: int) -> NamedSharding:
    """Rows-on-data sharding for an array whose axis 0 is the row axis."""
    return NamedSharding(mesh, PartitionSpec("data"))


def pad_to_multiple(x, multiple: int, axis: int = 0, fill=0.0):
    """Pad the row axis so it divides the data-axis size.  Padding rows get
    weight 0 downstream, so statistics are unchanged (weight-mask sampling
    makes padding free — SURVEY.md §2.5 row-sampling note)."""
    import jax.numpy as jnp

    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, 0)] * x.ndim
    pad_width[axis] = (0, rem)
    return jnp.pad(x, pad_width, constant_values=fill), n


# ---------------------------------------------------------------------------
# Row-sharding helpers shared by every row-sharding estimator fit (GBM,
# Boosting, Bagging, standalone base learners).  They live here — the
# neutral parallel layer — so foundational modules (models/base.py) never
# import from a downstream estimator module.
# ---------------------------------------------------------------------------

def pad_rows(arr, n_pad: int):
    """Zero-pad axis 0 to ``n_pad`` rows (padding rows carry weight 0
    downstream, so statistics are unchanged)."""
    rem = n_pad - arr.shape[0]
    if rem == 0:
        return arr
    return jnp.pad(arr, [(0, rem)] + [(0, 0)] * (arr.ndim - 1))


def pad_ctx_rows(ctx, specs, n_pad: int, data_axis: str = "data"):
    """Pad every row-indexed ctx leaf (per its shard spec) to ``n_pad``."""

    def pad(leaf, spec):
        if len(spec) > 0 and spec[0] == data_axis:
            return pad_rows(leaf, n_pad)
        return leaf

    return jax.tree_util.tree_map(pad, ctx, specs)


def shard_put(tree, specs, mesh: Mesh):
    """device_put a pytree with NamedShardings built from its spec pytree."""
    shardings = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs)
    return jax.device_put(tree, shardings)


def shard_ctx_rows(mesh: Mesh, base, ctx, n_pad: int):
    """Pad the fit ctx to the data-axis size and device_put it row-sharded
    (over "data", or ("dcn_data", "data") on a hybrid multi-slice mesh).
    Returns ``(ctx, ctx_specs)``.  Shared by every row-sharding estimator
    (GBM, Boosting, Bagging)."""
    row_spec = mesh_row_spec(mesh)
    ctx_specs = base.ctx_specs(ctx, row_spec)
    ctx = shard_put(
        pad_ctx_rows(ctx, ctx_specs, n_pad, data_axis=row_spec),
        ctx_specs,
        mesh,
    )
    return ctx, ctx_specs


def shard_fit_rows(mesh: Mesh, base, ctx, X, n_pad: int):
    """``shard_ctx_rows`` plus the feature matrix (estimators whose round
    step predicts on X: GBM, Boosting; see also ``setup_row_sharding``)."""
    ctx, _ = shard_ctx_rows(mesh, base, ctx, n_pad)
    X = jax.device_put(
        pad_rows(X, n_pad), NamedSharding(mesh, PartitionSpec(mesh_row_spec(mesh), None))
    )
    return ctx, X


def setup_row_sharding(mesh: Mesh, base, ctx, X, n: int, row_vectors=()):
    """The full mesh row-sharding preamble shared by every row-sharding
    estimator fit: resolve the row axis spec and padded length, pad+shard
    the fit ctx and feature matrix, and pad+shard each 1-D per-row vector
    (labels, weights, validity masks).  Returns
    ``(ctx, X, ax, n_pad, sharded_vectors)``."""
    data_size, _ = mesh_sizes(mesh)
    ax = mesh_row_spec(mesh)
    n_pad = n + (-n) % data_size
    ctx, X = shard_fit_rows(mesh, base, ctx, X, n_pad)
    row = NamedSharding(mesh, PartitionSpec(ax))
    vecs = tuple(jax.device_put(pad_rows(v, n_pad), row) for v in row_vectors)
    return ctx, X, ax, n_pad, vecs


def shard_validation_rows(mesh: Mesh, n_val: int, vectors=(), matrices=()):
    """Pad+shard a validation split over the row axis for in-chunk SPMD
    evaluation (shared by both GBM flavors).  Returns
    ``(nv_pad, valid_mask, sharded_vectors, sharded_matrices)`` — the mask
    is 1.0 on real rows, 0.0 on padding, so weighted val-loss means ignore
    the padding."""
    data_size, _ = mesh_sizes(mesh)
    ax = mesh_row_spec(mesh)
    nv_pad = n_val + (-n_val) % data_size
    row = NamedSharding(mesh, PartitionSpec(ax))
    row2 = NamedSharding(mesh, PartitionSpec(ax, None))
    valid = jax.device_put(
        pad_rows(jnp.ones((n_val,), jnp.float32), nv_pad), row
    )
    vecs = tuple(jax.device_put(pad_rows(v, nv_pad), row) for v in vectors)
    mats = tuple(jax.device_put(pad_rows(m, nv_pad), row2) for m in matrices)
    return nv_pad, valid, vecs, mats


def mesh_row_axes(mesh: Mesh):
    """Mesh axes rows shard over: ("dcn_data", "data") on a multi-slice
    hybrid mesh (`parallel/mesh.py:hybrid_data_member_mesh`) — row
    reductions then psum over BOTH, i.e. a fast ICI reduction per slice
    plus one cross-slice DCN hop — else just ("data",)."""
    if "dcn_data" in mesh.axis_names:
        return ("dcn_data", "data")
    return ("data",)


def mesh_sizes(mesh: Mesh):
    if "data" not in mesh.axis_names:
        raise ValueError(
            f"mesh must have a 'data' axis; got axes {mesh.axis_names}"
        )
    member = int(mesh.shape.get("member", 1))
    data = 1
    for a in mesh_row_axes(mesh):
        data *= int(mesh.shape[a])
    return data, member


def mesh_row_spec(mesh: Mesh):
    """PartitionSpec entry (and psum axis_name) for the row axis: the plain
    string "data", or the ("dcn_data", "data") tuple on a hybrid mesh."""
    axes = mesh_row_axes(mesh)
    return axes if len(axes) > 1 else "data"


