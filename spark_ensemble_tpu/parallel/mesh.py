"""Device-mesh construction for the framework's two parallel axes.

The reference's parallelism axes (SURVEY.md §2.5) are:
- **rows** (data parallelism): RDD partitions over Spark executors, reduced
  with ``treeReduce``/``treeAggregate``;
- **ensemble members / class dims** (task parallelism): driver thread-pool
  Futures (`BaggingClassifier.scala:180-201`, `GBMClassifier.scala:377-411`).

The TPU-native mapping is a 2-D ``jax.sharding.Mesh`` with axes
``("data", "member")``: rows sharded over ``data`` (reductions become
``psum`` over ICI), members/class-dims sharded over ``member``.  On
multi-slice pods, put ``data`` on the DCN-spanning axis (gradient-style
psums tolerate DCN latency) and ``member`` within a slice.  The reference
has no sequence dimension, so there is no sequence/context-parallel axis —
rows x members IS the scaling surface (SURVEY.md §5).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def create_mesh(axis_sizes: dict, devices: Optional[Sequence] = None) -> Mesh:
    """Mesh from ``{axis_name: size}``; sizes must multiply to #devices."""
    devices = list(devices if devices is not None else jax.devices())
    shape = tuple(axis_sizes.values())
    total = int(np.prod(shape))
    if total != len(devices):
        raise ValueError(
            f"mesh {axis_sizes} needs {total} devices, have {len(devices)}"
        )
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(axis_sizes.keys()))


def data_member_mesh(
    n_devices: Optional[int] = None, member: int = 1
) -> Mesh:
    """The standard ("data", "member") mesh; ``member`` divides n_devices."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if n % member != 0:
        raise ValueError(f"member axis {member} must divide device count {n}")
    return create_mesh(
        {"data": n // member, "member": member}, devices=devices[:n]
    )


def hybrid_data_member_mesh(
    dcn_data: int = 1, member: int = 1, devices: Optional[Sequence] = None
) -> Mesh:
    """Multi-slice pod mesh: ``("dcn_data", "data", "member")``.

    The outer ``dcn_data`` axis spans slices over DCN; ``data`` and
    ``member`` stay within a slice on ICI.  Row reductions then decompose
    into a fast ICI psum per slice plus one small cross-slice psum over
    ``dcn_data`` — histogram/hessian/objective sums are gradient-like
    reductions that tolerate DCN latency (module docstring).  Estimator
    fits accept this mesh directly: pass shardings with rows split over
    ``("dcn_data", "data")``.

    On multi-slice TPU hardware the device order comes from
    ``mesh_utils.create_hybrid_device_mesh`` (DCN-aware placement); on
    single-slice or CPU devices it falls back to a plain reshape, which is
    functionally identical (collectives still compile and run — placement
    is a performance detail the real pod supplies).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % (dcn_data * member) != 0:
        raise ValueError(
            f"dcn_data={dcn_data} * member={member} must divide {n} devices"
        )
    ici_data = n // (dcn_data * member)
    shape = (dcn_data, ici_data, member)
    if getattr(devices[0], "slice_index", None) is None:
        # single-slice / CPU devices: no slice topology to respect; a plain
        # reshape is functionally identical (placement is a perf detail the
        # real pod supplies)
        arr = np.array(devices).reshape(shape)
    else:
        # real multi-slice topology: DCN-aware placement; configuration
        # errors (e.g. dcn_data != slice count) must propagate, not be
        # silently reshaped across slice boundaries
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            (1, ici_data, member),
            (dcn_data, 1, 1),
            devices=devices,
        )
    return Mesh(arr, ("dcn_data", "data", "member"))


def data_sharding(mesh: Mesh, *batch_axis_first: int) -> NamedSharding:
    """Rows-on-data sharding for an array whose axis 0 is the row axis."""
    return NamedSharding(mesh, PartitionSpec("data"))


def pad_to_multiple(x, multiple: int, axis: int = 0, fill=0.0):
    """Pad the row axis so it divides the data-axis size.  Padding rows get
    weight 0 downstream, so statistics are unchanged (weight-mask sampling
    makes padding free — SURVEY.md §2.5 row-sampling note)."""
    import jax.numpy as jnp

    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, 0)] * x.ndim
    pad_width[axis] = (0, rem)
    return jnp.pad(x, pad_width, constant_values=fill), n
