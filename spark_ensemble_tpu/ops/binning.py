"""Quantile feature binning for histogram-based tree training.

The reference's base learner is Spark MLlib's DecisionTree, which discretizes
continuous features into up to ``maxBins`` candidate split bins via quantile
sketching on a sample of rows (Spark `RandomForest.findSplits`).  We do the
same, TPU-style: per-feature quantile thresholds computed with an exact sort
(one pass, jitted), then an int32 bin matrix computed by ``searchsorted``.

Bin semantics: ``bin(x) = #{i : t_i < x}`` so that a split at bin ``b``
("go left iff bin <= b") is exactly "go left iff x <= t_b", which lets trees
trained on binned features predict on raw ones.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from spark_ensemble_tpu.autotune.resolve import resolve as _tuned


class Bins(NamedTuple):
    """Per-feature split thresholds; ``thresholds[f, i]`` ascending in i."""

    thresholds: jax.Array  # f32[d, max_bins - 1]

    @property
    def max_bins(self) -> int:
        return self.thresholds.shape[1] + 1

    @property
    def num_features(self) -> int:
        return self.thresholds.shape[0]


def compute_bins(X: jax.Array, max_bins: int = 64) -> Bins:
    """Quantile thresholds at (i+1)/max_bins, i = 0..max_bins-2, per feature."""
    qs = jnp.arange(1, max_bins) / max_bins
    thresholds = jnp.quantile(X.astype(jnp.float32), qs, axis=0).T  # [d, B-1]
    return Bins(thresholds=thresholds)


def bin_features(X: jax.Array, bins: Bins) -> jax.Array:
    """``int32[n, d]`` bin indices: count of thresholds strictly below x."""

    def per_feature(col, thr):
        return jnp.searchsorted(thr, col, side="left").astype(jnp.int32)

    return jax.vmap(per_feature, in_axes=(1, 0), out_axes=1)(
        X.astype(jnp.float32), bins.thresholds
    )


def bin_occupancy(X: jax.Array, bins: Bins) -> jax.Array:
    """``int32[d, max_bins]`` per-feature bin-count histogram of ``X``'s
    rows under ``bins`` — the drift-sketch primitive
    (telemetry/quality.py).

    Counts are computed as a one-hot float sum and cast back to int32, so
    they are EXACT integers (row counts stay far below the f32 mantissa),
    which makes the sketch invariant to row order and to how a request
    stream was split into batches: histograms of any partition of the same
    rows sum to the histogram of the whole — the property the serving
    engine's padded-bucket accumulation and the batching-order tests rely
    on."""
    ids = bin_features(X, bins)  # i32[n, d]
    onehot = jax.nn.one_hot(ids, bins.max_bins, dtype=jnp.float32)
    return jnp.sum(onehot, axis=0).astype(jnp.int32)  # [d, max_bins]


# ---------------------------------------------------------------------------
# Compressed (bit-packed) bin storage for the fused round kernel
# ---------------------------------------------------------------------------
#
# Bin ids are tiny integers (< max_bins <= 256), yet the i32 bin matrix
# spends 32 bits per id — at letter scale the per-level re-read of ``Xb``
# is the round loop's dominant HBM operand.  ELLPACK-style compressed bin
# storage (XGBoost GPU, arXiv:1806.11248) packs ids into the narrowest
# lane that holds ``max_bins`` values: 4-bit lanes for max_bins <= 16,
# 8-bit for <= 256 — a 4-8x cut of that read.  Layout is LANE-MAJOR:
# word ``w`` of a row packs features ``l*W + w`` for lane ``l`` (W words
# per row), so the in-kernel unpack is ``lanes`` shift-and-mask passes
# each producing a CONTIGUOUS feature block — no minor-dim shuffles on
# the TPU vector unit.


class CompressedBins(NamedTuple):
    """Bit-packed bin matrix: ``packed[r, w]`` holds ``32 // bits`` ids.

    Plain metadata ints ride along for host-side use; jitted consumers
    (the fused kernel path) treat ``bits`` / ``num_features`` as static
    and read only ``packed``.
    """

    packed: jax.Array  # u32[n, W], W = ceil(d / (32 // bits))
    bits: int  # lane width: 4, 8, or 32 (32 = unpacked passthrough)
    num_features: int  # d before padding

    @property
    def lanes(self) -> int:
        return 32 // self.bits

    @property
    def words_per_row(self) -> int:
        return self.packed.shape[1]


def pack_width(max_bins: int) -> int:
    """Lane width (bits) for ``max_bins`` bin ids: the narrowest of
    {4, 8} that holds ``max_bins`` values, or 32 (no packing) past 256.
    A measured winner (autotune: "pack_bits"; 0 = auto) overrides the
    choice but never below what ``max_bins`` needs."""
    auto = 4 if max_bins <= 16 else (8 if max_bins <= 256 else 32)
    tuned = int(_tuned("pack_bits", 0))
    if tuned in (4, 8, 32) and tuned >= auto:
        return tuned
    return auto


def pack_bins(Xb: jax.Array, max_bins: int, bits: int = 0) -> CompressedBins:
    """Pack ``Xb i32[n, d]`` (ids in [0, max_bins)) into ``bits``-bit
    lanes of u32 words; ``bits=0`` resolves via :func:`pack_width`.
    Trailing pad features pack as id 0 and are sliced off on unpack."""
    n, d = Xb.shape
    bits = bits or pack_width(max_bins)
    if bits >= 32:
        return CompressedBins(
            packed=Xb.astype(jnp.uint32), bits=32, num_features=d
        )
    lanes = 32 // bits
    W = -(-d // lanes)
    X = jnp.pad(Xb.astype(jnp.uint32), ((0, 0), (0, W * lanes - d)))
    # lane-major: lane l carries the contiguous feature block [l*W, (l+1)*W)
    X = X.reshape(n, lanes, W)
    words = jnp.zeros((n, W), jnp.uint32)
    for lane in range(lanes):
        words = words | (X[:, lane, :] << jnp.uint32(lane * bits))
    return CompressedBins(packed=words, bits=bits, num_features=d)


def unpack_bins(cb: CompressedBins) -> jax.Array:
    """Inverse of :func:`pack_bins`: ``i32[n, d]`` bin ids."""
    if cb.bits >= 32:
        return cb.packed.astype(jnp.int32)
    mask = jnp.uint32(2**cb.bits - 1)
    blocks = [
        (cb.packed >> jnp.uint32(lane * cb.bits)) & mask
        for lane in range(cb.lanes)
    ]
    full = jnp.concatenate(blocks, axis=1)
    return full[:, : cb.num_features].astype(jnp.int32)
