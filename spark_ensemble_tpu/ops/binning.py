"""Quantile feature binning for histogram-based tree training.

The reference's base learner is Spark MLlib's DecisionTree, which discretizes
continuous features into up to ``maxBins`` candidate split bins via quantile
sketching on a sample of rows (Spark `RandomForest.findSplits`).  We do the
same, TPU-style: per-feature quantile thresholds computed with an exact sort
(one pass, jitted), then an int32 bin matrix computed by ``searchsorted``.

Bin semantics: ``bin(x) = #{i : t_i < x}`` so that a split at bin ``b``
("go left iff bin <= b") is exactly "go left iff x <= t_b", which lets trees
trained on binned features predict on raw ones.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class Bins(NamedTuple):
    """Per-feature split thresholds; ``thresholds[f, i]`` ascending in i."""

    thresholds: jax.Array  # f32[d, max_bins - 1]

    @property
    def max_bins(self) -> int:
        return self.thresholds.shape[1] + 1

    @property
    def num_features(self) -> int:
        return self.thresholds.shape[0]


def compute_bins(X: jax.Array, max_bins: int = 64) -> Bins:
    """Quantile thresholds at (i+1)/max_bins, i = 0..max_bins-2, per feature."""
    qs = jnp.arange(1, max_bins) / max_bins
    thresholds = jnp.quantile(X.astype(jnp.float32), qs, axis=0).T  # [d, B-1]
    return Bins(thresholds=thresholds)


def bin_features(X: jax.Array, bins: Bins) -> jax.Array:
    """``int32[n, d]`` bin indices: count of thresholds strictly below x."""

    def per_feature(col, thr):
        return jnp.searchsorted(thr, col, side="left").astype(jnp.int32)

    return jax.vmap(per_feature, in_axes=(1, 0), out_axes=1)(
        X.astype(jnp.float32), bins.thresholds
    )
