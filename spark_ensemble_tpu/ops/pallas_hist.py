"""Pallas TPU kernel for the forest level-histogram build.

The XLA matmul path (`ops/tree.py fit_forest`) materializes two large HBM
operands per level and re-streams them on every MXU pass:

- ``A [n, M*nodes*(1+k)]`` — the node-one-hot times (w, w*y) channels
  (~50 MB at letter scale, level 4);
- ``bin_oh [n, d*B]`` — the loop-invariant row-to-bin one-hot (61 MB at
  letter scale, **1 GB** at the BENCH_LARGE config).

This kernel fuses both away: each grid step DMAs only the COMPACT inputs
(binned features ``i32[blk, d]``, node ids ``i32[blk, M]``, value channels
``f32[blk, M, C]``), builds both one-hots in VMEM, runs the same
``A^T @ bin_oh`` contraction on the MXU, and accumulates the histogram in a
VMEM-resident output across the sequential grid — HBM traffic drops from
O(n * d * B) per pass to O(n * (d + M*C)) per level.

Precision: the value channels split into bf16 hi + lo terms (two MXU
passes, ~16-bit statistic mantissa — between the 'default' (8-bit) and
'high' (~24-bit) matmul tiers).  The one-hot side is exact 0/1 bf16.
Empty nodes dot to exactly 0.0 (an all-zero one-hot column), so — unlike
the histogram-subtraction fast tiers — no derived-noise weight floor is
needed: every level is computed directly.

Used by ``fit_forest`` when ``hist_precision="pallas"`` (TPU backends; any
other backend runs the kernel in interpreter mode, which is only suitable
for the small shapes the parity tests use).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from spark_ensemble_tpu.autotune.resolve import resolve as _tuned

# rows per grid step: bounds VMEM (block one-hots + hi/lo operands) while
# keeping the MXU contraction dimension >= 2 tiles.  The literal is the
# DEFAULT; a measured winner (autotune: "pallas_block_rows") overrides it
# through block_rows() at trace time
_BLOCK_ROWS = 256

# VMEM budget for the resident accumulator + per-block operands (bytes);
# configs over this fall back to the XLA matmul path (decided at trace
# time from static shapes in ops/tree.py).  Tuned via vmem_budget()
# (autotune: "pallas_vmem_budget")
_VMEM_BUDGET = 12 * 2**20


def block_rows() -> int:
    """Rows per grid step: the tuned winner for this device, defaulting
    to the live module constant (so tests monkeypatching ``_BLOCK_ROWS``
    keep working)."""
    return int(_tuned("pallas_block_rows", _BLOCK_ROWS))


def vmem_budget() -> int:
    """Kernel VMEM budget in bytes (tuned, live-default like above)."""
    return int(_tuned("pallas_vmem_budget", _VMEM_BUDGET))


# off-TPU, fit_forest only dispatches the interpreted kernel below this
# many rows; larger inputs fall back to the 'high' matmul tier (the
# Python-level interpreter is ~1e4x slower than compiled code and
# effectively hangs at dataset scale)
_INTERPRET_MAX_ROWS = 4096


def _interpret() -> bool:
    """Interpreter mode off-TPU: correctness-only (tests use tiny shapes)."""
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:  # noqa: BLE001 - no backend at all
        return True


def hist_vmem_bytes(
    n_nodes: int, M: int, C: int, d: int, B: int, blk: int = 0
) -> int:
    """Static VMEM estimate for the accumulator + block operands;
    ``blk`` defaults to the resolved grid-step row count.

    Counts the i32 bin-iota/compare scratch the one-hot build
    materializes BEFORE the bf16 cast (``[blk, d, B]`` i32) — an
    earlier version omitted it, so the fallback decision in
    ``ops/tree.py`` (which consults this estimate) and the kernel's
    real footprint could disagree for large ``M*C``.
    """
    blk = blk or block_rows()
    acc = M * n_nodes * C * d * B * 4
    rhs = blk * d * B * 2
    unpack_scratch = blk * d * B * 4
    lhs = blk * M * n_nodes * C * (4 + 2 + 2)
    return acc + rhs + unpack_scratch + lhs


def _hist_kernel(xb_ref, node_ref, vals_ref, out_ref, *, n_nodes, B):
    """One grid step: accumulate this row block's histogram contribution.

    Shapes (VMEM blocks): xb i32[blk, d], node i32[blk, M],
    vals f32[blk, M, C], out f32[M*n_nodes*C, d*B] (revisited every step).
    """
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    xb = xb_ref[:]
    node = node_ref[:]
    vals = vals_ref[:]
    blk, d = xb.shape
    _, M, C = vals.shape

    # row-to-bin one-hot, built in VMEM (exact 0/1 in bf16)
    bins = jax.lax.broadcasted_iota(jnp.int32, (blk, d, B), 2)
    rhs = (xb[:, :, None] == bins).astype(jnp.bfloat16).reshape(blk, d * B)

    # node-one-hot x value channels -> A block [blk, M*n_nodes*C]
    nodes_iota = jax.lax.broadcasted_iota(jnp.int32, (blk, M, n_nodes), 2)
    noh = (node[:, :, None] == nodes_iota).astype(jnp.float32)
    lhs = (noh[:, :, :, None] * vals[:, :, None, :]).reshape(
        blk, M * n_nodes * C
    )
    # two-pass hi/lo split: bf16 inputs on the MXU, f32 accumulate
    hi = lhs.astype(jnp.bfloat16)
    lo = (lhs - hi.astype(jnp.float32)).astype(jnp.bfloat16)

    contract = (((0,), (0,)), ((), ()))
    acc = jax.lax.dot_general(
        hi, rhs, contract, preferred_element_type=jnp.float32
    )
    acc = acc + jax.lax.dot_general(
        lo, rhs, contract, preferred_element_type=jnp.float32
    )
    out_ref[:] += acc


def hist_level_pallas(Xb, node, vals, *, n_nodes: int, max_bins: int):
    """Level histogram ``H f32[M, n_nodes, C, d, B]`` for all members.

    ``Xb i32[n, d]`` shared binned features; ``node i32[n, M]`` each row's
    node at this level per member; ``vals f32[n, M, C]`` the statistic
    channels (w, w*y...).  Zero-weight (padding) rows contribute exactly 0.

    The grid-step row count resolves through ``block_rows()`` here — at
    trace time, outside the jit below — and enters the compiled program
    as a static arg, so a tuned value produces a distinct trace instead
    of silently reusing a program tiled for the old block size.
    """
    return _hist_level_pallas(
        Xb, node, vals, n_nodes=n_nodes, max_bins=max_bins,
        blk=block_rows(),
    )


@functools.partial(
    jax.jit, static_argnames=("n_nodes", "max_bins", "blk")
)
def _hist_level_pallas(Xb, node, vals, *, n_nodes, max_bins, blk):
    n, d = Xb.shape
    _, M, C = vals.shape
    B = max_bins

    pad = (-n) % blk
    if pad:
        # padded rows: vals 0 -> zero contribution regardless of node/bin
        Xb = jnp.pad(Xb, ((0, pad), (0, 0)))
        node = jnp.pad(node, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0), (0, 0)))
    steps = (n + pad) // blk

    kernel = functools.partial(_hist_kernel, n_nodes=n_nodes, B=B)
    out = pl.pallas_call(
        kernel,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((blk, d), lambda i: (i, 0)),
            pl.BlockSpec((blk, M), lambda i: (i, 0)),
            pl.BlockSpec((blk, M, C), lambda i: (i, 0, 0)),
        ],
        # constant index map: the accumulator stays VMEM-resident and is
        # revisited (+=) by every sequential grid step
        out_specs=pl.BlockSpec(
            (M * n_nodes * C, d * B), lambda i: (0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((M * n_nodes * C, d * B), jnp.float32),
        interpret=_interpret(),
    )(Xb, node, vals)
    return out.reshape(M, n_nodes, C, d, B)


# ---------------------------------------------------------------------------
# Fused round kernel over bit-packed bins (hist="fused")
# ---------------------------------------------------------------------------
#
# The histogram kernel above still reads the bin matrix as i32 — 32 bits
# per id that is < max_bins.  The fused tier reads the ELLPACK-style
# packed words from ops/binning.py instead (4-8x less HBM on the round
# loop's dominant operand) and additionally folds the LEVEL ROUTING into
# the same grid step: each step DMAs the packed block once, unpacks it
# with shift-and-mask passes in VMEM, routes the block's rows through the
# previous level's split tables, builds both one-hots, and accumulates
# the level histogram — so one pallas program per level replaces the
# separate route + one-hot + A-build + histogram dispatch chain, and the
# split-scan / leaf-solve between kernels stay on-device inside the same
# jitted program (ops/tree.py::_fit_forest_fused).
#
# Routing identity: a row goes left iff its bin at the node's split
# feature is <= the split bin.  The kernel derives that bit from the bin
# one-hot it already built — ``rhs @ T^T`` where ``T[m*p, f*B+b] =
# 1[f == best_f[m,p] and b <= best_t[m,p]]`` — every operand is exact 0/1
# in bf16 and each row dots to exactly 0.0 or 1.0, so routing is
# bit-identical to ops/tree.py::_route_members for max_bins <= 256 (the
# packable range).  Histogram precision is the hi/lo two-pass of the
# kernel above (~16-bit statistic mantissa); leaf sums accumulate in f32.

_FUSED_BLOCK_ROWS = 256

_FUSED_VMEM_BUDGET = 12 * 2**20


def fused_block_rows() -> int:
    """Rows per grid step of the fused round kernel (tuned, live-default
    like ``block_rows``)."""
    return int(_tuned("fused_block_rows", _FUSED_BLOCK_ROWS))


def fused_vmem_budget() -> int:
    """Fused-kernel VMEM budget in bytes (tuned, live-default)."""
    return int(_tuned("fused_vmem_budget", _FUSED_VMEM_BUDGET))


def fused_vmem_bytes(
    n_nodes: int, M: int, C: int, d: int, B: int, bits: int, blk: int = 0
) -> int:
    """Static VMEM estimate for the fused kernel's deepest level: the
    resident accumulator, the unpack/one-hot scratch, the 3-term bf16
    statistic operands, and the routing tables.  Consulted by
    ``_resolve_hist`` (ops/tree.py) — configs over
    :func:`fused_vmem_budget` fall back to the matmul/stream tiers."""
    blk = blk or fused_block_rows()
    lanes = max(32 // max(bits, 1), 1)
    half = max(n_nodes // 2, 1)
    acc = M * n_nodes * C * d * B * 4
    packed = blk * (-(-d // lanes)) * 4
    xb = blk * d * 4
    unpack_scratch = blk * d * B * 4
    rhs = blk * d * B * 2
    lhs = blk * M * n_nodes * C * (4 + 2 + 2 + 2)
    route = M * half * d * B * (4 + 2) + blk * M * half * (4 + 4)
    return acc + packed + xb + unpack_scratch + rhs + lhs + route


def _fused_kernel(
    packed_ref, node_ref, vals_ref, bf_ref, bt_ref, hist_ref, node_ref_out,
    *, n_nodes, B, bits, d, route, leaf,
):
    """One grid step of the fused round: unpack + route + accumulate.

    VMEM blocks: packed u32[blk, W], node i32[blk, M] (PARENT-level ids
    when ``route``), vals f32[blk, M, C], bf/bt i32[M, half] the previous
    level's split tables.  Outputs: hist f32[M*n_nodes*C, d*B] (or
    [M*n_nodes*C, 1] column sums when ``leaf``), revisited every step;
    node_out i32[blk, M] this level's routed ids.
    """
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        hist_ref[:] = jnp.zeros_like(hist_ref)

    packed = packed_ref[:]
    node = node_ref[:]
    vals = vals_ref[:]
    blk = node.shape[0]
    _, M, C = vals.shape

    # shift-and-mask unpack: lane l holds the contiguous feature block
    # [l*W, (l+1)*W) (ops/binning.py lane-major layout), so each pass
    # yields whole columns and the concat is lane-aligned
    if bits >= 32:
        xb = packed.astype(jnp.int32)[:, :d]
    else:
        lanes = 32 // bits
        mask = jnp.uint32(2**bits - 1)
        blocks = [
            (packed >> jnp.uint32(lane * bits)) & mask
            for lane in range(lanes)
        ]
        xb = jnp.concatenate(blocks, axis=1)[:, :d].astype(jnp.int32)

    # row-to-bin one-hot (exact 0/1 bf16): the histogram RHS, and the
    # operand the routing bit is contracted out of
    bins = jax.lax.broadcasted_iota(jnp.int32, (blk, d, B), 2)
    rhs = (xb[:, :, None] == bins).astype(jnp.bfloat16).reshape(blk, d * B)

    if route:
        bf = bf_ref[:]
        bt = bt_ref[:]
        half = bf.shape[1]
        # T[m*p, f*B+b] = 1[f == best_f[m,p] and b <= best_t[m,p]]
        f_iota = jax.lax.broadcasted_iota(jnp.int32, (M, half, d, B), 2)
        b_iota = jax.lax.broadcasted_iota(jnp.int32, (M, half, d, B), 3)
        T = (
            (f_iota == bf[:, :, None, None])
            & (b_iota <= bt[:, :, None, None])
        ).astype(jnp.bfloat16).reshape(M * half, d * B)
        # U[r, m*p] == 1.0 iff row r's bin at best_f[m,p] <= best_t[m,p]
        U = jax.lax.dot_general(
            rhs, T, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(blk, M, half)
        parents = jax.lax.broadcasted_iota(jnp.int32, (blk, M, half), 2)
        poh = (node[:, :, None] == parents).astype(jnp.float32)
        go_left = jnp.sum(poh * U, axis=2)  # exactly 0.0 or 1.0
        node = 2 * node + 1 - go_left.astype(jnp.int32)
    node_ref_out[:] = node

    nodes_iota = jax.lax.broadcasted_iota(jnp.int32, (blk, M, n_nodes), 2)
    noh = (node[:, :, None] == nodes_iota).astype(jnp.float32)
    lhs = (noh[:, :, :, None] * vals[:, :, None, :]).reshape(
        blk, M * n_nodes * C
    )
    if leaf:
        # leaf statistics need no bin axis: f32 column sums, exact
        # per-block accumulation
        hist_ref[:] += jnp.sum(lhs, axis=0)[:, None]
    else:
        # 3-term bf16 split of the statistic operand (~24-bit mantissa,
        # f32-grade): hi + lo covers 16 bits, the residual term the rest.
        # The rhs one-hot is exact in bf16, so the dots' only rounding is
        # this split — split scores land within f32 tie-break distance of
        # the dense 'highest' tier (test_fused_gbm_letter_leg_parity).
        hi = lhs.astype(jnp.bfloat16)
        lo = (lhs - hi.astype(jnp.float32)).astype(jnp.bfloat16)
        lo2 = (
            lhs - hi.astype(jnp.float32) - lo.astype(jnp.float32)
        ).astype(jnp.bfloat16)
        contract = (((0,), (0,)), ((), ()))
        acc = jax.lax.dot_general(
            hi, rhs, contract, preferred_element_type=jnp.float32
        )
        acc = acc + jax.lax.dot_general(
            lo, rhs, contract, preferred_element_type=jnp.float32
        )
        acc = acc + jax.lax.dot_general(
            lo2, rhs, contract, preferred_element_type=jnp.float32
        )
        hist_ref[:] += acc


def fused_round_level(
    packed, node, vals, best_f=None, best_t=None, *,
    n_nodes: int, max_bins: int, bits: int, num_features: int,
    leaf: bool = False,
):
    """One fused level: histogram ``H f32[M, n_nodes, C, d, B]`` (or leaf
    sums ``[M, n_nodes, C]`` when ``leaf``) plus the routed node ids
    ``i32[n, M]``.

    ``packed u32[n, W]`` bit-packed bins (ops/binning.py); ``node`` the
    PREVIOUS level's ids when split tables ``best_f/best_t i32[M, half]``
    are given (routing is deferred into this kernel, like the stream
    tier), else this level's ids.  Zero-weight (padding) rows contribute
    exactly 0.  Block size resolves through ``fused_block_rows()`` at
    trace time and enters as a static arg (see ``hist_level_pallas``).
    """
    M = node.shape[1]
    route = best_f is not None
    if not route:
        best_f = jnp.zeros((M, 1), jnp.int32)
        best_t = jnp.zeros((M, 1), jnp.int32)
    return _fused_round_level(
        packed, node, vals, best_f, best_t, n_nodes=n_nodes,
        max_bins=max_bins, bits=bits, num_features=num_features,
        leaf=leaf, route=route, blk=fused_block_rows(),
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_nodes", "max_bins", "bits", "num_features", "leaf", "route",
        "blk",
    ),
)
def _fused_round_level(
    packed, node, vals, best_f, best_t, *, n_nodes, max_bins, bits,
    num_features, leaf, route, blk,
):
    n, W = packed.shape
    _, M, C = vals.shape
    B = max_bins
    d = num_features
    half = best_f.shape[1]

    pad = (-n) % blk
    if pad:
        # padded rows: vals 0 -> zero contribution regardless of node/bin
        packed = jnp.pad(packed, ((0, pad), (0, 0)))
        node = jnp.pad(node, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0), (0, 0)))
    steps = (n + pad) // blk

    out_w = 1 if leaf else d * B
    kernel = functools.partial(
        _fused_kernel, n_nodes=n_nodes, B=B, bits=bits, d=d, route=route,
        leaf=leaf,
    )
    hist, node_out = pl.pallas_call(
        kernel,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((blk, W), lambda i: (i, 0)),
            pl.BlockSpec((blk, M), lambda i: (i, 0)),
            pl.BlockSpec((blk, M, C), lambda i: (i, 0, 0)),
            pl.BlockSpec((M, half), lambda i: (0, 0)),
            pl.BlockSpec((M, half), lambda i: (0, 0)),
        ],
        out_specs=[
            # the accumulator stays VMEM-resident across the grid; the
            # routed ids stream out block by block
            pl.BlockSpec((M * n_nodes * C, out_w), lambda i: (0, 0)),
            pl.BlockSpec((blk, M), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M * n_nodes * C, out_w), jnp.float32),
            jax.ShapeDtypeStruct((n + pad, M), jnp.int32),
        ],
        interpret=_interpret(),
    )(packed, node, vals, best_f, best_t)
    shape = (M, n_nodes, C) if leaf else (M, n_nodes, C, d, B)
    return hist.reshape(shape), node_out[:n]
