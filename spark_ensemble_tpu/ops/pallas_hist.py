"""Pallas TPU kernel for the forest level-histogram build.

The XLA matmul path (`ops/tree.py fit_forest`) materializes two large HBM
operands per level and re-streams them on every MXU pass:

- ``A [n, M*nodes*(1+k)]`` — the node-one-hot times (w, w*y) channels
  (~50 MB at letter scale, level 4);
- ``bin_oh [n, d*B]`` — the loop-invariant row-to-bin one-hot (61 MB at
  letter scale, **1 GB** at the BENCH_LARGE config).

This kernel fuses both away: each grid step DMAs only the COMPACT inputs
(binned features ``i32[blk, d]``, node ids ``i32[blk, M]``, value channels
``f32[blk, M, C]``), builds both one-hots in VMEM, runs the same
``A^T @ bin_oh`` contraction on the MXU, and accumulates the histogram in a
VMEM-resident output across the sequential grid — HBM traffic drops from
O(n * d * B) per pass to O(n * (d + M*C)) per level.

Precision: the value channels split into bf16 hi + lo terms (two MXU
passes, ~16-bit statistic mantissa — between the 'default' (8-bit) and
'high' (~24-bit) matmul tiers).  The one-hot side is exact 0/1 bf16.
Empty nodes dot to exactly 0.0 (an all-zero one-hot column), so — unlike
the histogram-subtraction fast tiers — no derived-noise weight floor is
needed: every level is computed directly.

Used by ``fit_forest`` when ``hist_precision="pallas"`` (TPU backends; any
other backend runs the kernel in interpreter mode, which is only suitable
for the small shapes the parity tests use).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from spark_ensemble_tpu.autotune.resolve import resolve as _tuned

# rows per grid step: bounds VMEM (block one-hots + hi/lo operands) while
# keeping the MXU contraction dimension >= 2 tiles.  The literal is the
# DEFAULT; a measured winner (autotune: "pallas_block_rows") overrides it
# through block_rows() at trace time
_BLOCK_ROWS = 256

# VMEM budget for the resident accumulator + per-block operands (bytes);
# configs over this fall back to the XLA matmul path (decided at trace
# time from static shapes in ops/tree.py).  Tuned via vmem_budget()
# (autotune: "pallas_vmem_budget")
_VMEM_BUDGET = 12 * 2**20


def block_rows() -> int:
    """Rows per grid step: the tuned winner for this device, defaulting
    to the live module constant (so tests monkeypatching ``_BLOCK_ROWS``
    keep working)."""
    return int(_tuned("pallas_block_rows", _BLOCK_ROWS))


def vmem_budget() -> int:
    """Kernel VMEM budget in bytes (tuned, live-default like above)."""
    return int(_tuned("pallas_vmem_budget", _VMEM_BUDGET))


# off-TPU, fit_forest only dispatches the interpreted kernel below this
# many rows; larger inputs fall back to the 'high' matmul tier (the
# Python-level interpreter is ~1e4x slower than compiled code and
# effectively hangs at dataset scale)
_INTERPRET_MAX_ROWS = 4096


def _interpret() -> bool:
    """Interpreter mode off-TPU: correctness-only (tests use tiny shapes)."""
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:  # noqa: BLE001 - no backend at all
        return True


def hist_vmem_bytes(
    n_nodes: int, M: int, C: int, d: int, B: int, blk: int = 0
) -> int:
    """Static VMEM estimate for the accumulator + block operands;
    ``blk`` defaults to the resolved grid-step row count."""
    blk = blk or block_rows()
    acc = M * n_nodes * C * d * B * 4
    rhs = blk * d * B * 2
    lhs = blk * M * n_nodes * C * (4 + 2 + 2)
    return acc + rhs + lhs


def _hist_kernel(xb_ref, node_ref, vals_ref, out_ref, *, n_nodes, B):
    """One grid step: accumulate this row block's histogram contribution.

    Shapes (VMEM blocks): xb i32[blk, d], node i32[blk, M],
    vals f32[blk, M, C], out f32[M*n_nodes*C, d*B] (revisited every step).
    """
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    xb = xb_ref[:]
    node = node_ref[:]
    vals = vals_ref[:]
    blk, d = xb.shape
    _, M, C = vals.shape

    # row-to-bin one-hot, built in VMEM (exact 0/1 in bf16)
    bins = jax.lax.broadcasted_iota(jnp.int32, (blk, d, B), 2)
    rhs = (xb[:, :, None] == bins).astype(jnp.bfloat16).reshape(blk, d * B)

    # node-one-hot x value channels -> A block [blk, M*n_nodes*C]
    nodes_iota = jax.lax.broadcasted_iota(jnp.int32, (blk, M, n_nodes), 2)
    noh = (node[:, :, None] == nodes_iota).astype(jnp.float32)
    lhs = (noh[:, :, :, None] * vals[:, :, None, :]).reshape(
        blk, M * n_nodes * C
    )
    # two-pass hi/lo split: bf16 inputs on the MXU, f32 accumulate
    hi = lhs.astype(jnp.bfloat16)
    lo = (lhs - hi.astype(jnp.float32)).astype(jnp.bfloat16)

    contract = (((0,), (0,)), ((), ()))
    acc = jax.lax.dot_general(
        hi, rhs, contract, preferred_element_type=jnp.float32
    )
    acc = acc + jax.lax.dot_general(
        lo, rhs, contract, preferred_element_type=jnp.float32
    )
    out_ref[:] += acc


def hist_level_pallas(Xb, node, vals, *, n_nodes: int, max_bins: int):
    """Level histogram ``H f32[M, n_nodes, C, d, B]`` for all members.

    ``Xb i32[n, d]`` shared binned features; ``node i32[n, M]`` each row's
    node at this level per member; ``vals f32[n, M, C]`` the statistic
    channels (w, w*y...).  Zero-weight (padding) rows contribute exactly 0.

    The grid-step row count resolves through ``block_rows()`` here — at
    trace time, outside the jit below — and enters the compiled program
    as a static arg, so a tuned value produces a distinct trace instead
    of silently reusing a program tiled for the old block size.
    """
    return _hist_level_pallas(
        Xb, node, vals, n_nodes=n_nodes, max_bins=max_bins,
        blk=block_rows(),
    )


@functools.partial(
    jax.jit, static_argnames=("n_nodes", "max_bins", "blk")
)
def _hist_level_pallas(Xb, node, vals, *, n_nodes, max_bins, blk):
    n, d = Xb.shape
    _, M, C = vals.shape
    B = max_bins

    pad = (-n) % blk
    if pad:
        # padded rows: vals 0 -> zero contribution regardless of node/bin
        Xb = jnp.pad(Xb, ((0, pad), (0, 0)))
        node = jnp.pad(node, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0), (0, 0)))
    steps = (n + pad) // blk

    kernel = functools.partial(_hist_kernel, n_nodes=n_nodes, B=B)
    out = pl.pallas_call(
        kernel,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((blk, d), lambda i: (i, 0)),
            pl.BlockSpec((blk, M), lambda i: (i, 0)),
            pl.BlockSpec((blk, M, C), lambda i: (i, 0, 0)),
        ],
        # constant index map: the accumulator stays VMEM-resident and is
        # revisited (+=) by every sequential grid step
        out_specs=pl.BlockSpec(
            (M * n_nodes * C, d * B), lambda i: (0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((M * n_nodes * C, d * B), jnp.float32),
        interpret=_interpret(),
    )(Xb, node, vals)
    return out.reshape(M, n_nodes, C, d, B)
