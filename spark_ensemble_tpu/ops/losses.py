"""GBM loss layer: pure, batched, differentiable loss functions.

Re-designs the reference's loss subsystem (`GBMLoss.scala:78-318`) as pure
JAX functions over batched arrays.  Where the reference hand-writes per-row
scalar loops for loss/gradient/hessian and reduces them through Spark's
``DifferentiableLossAggregator`` (`GBMLoss.scala:34-76`), here every loss is
an elementwise kernel on ``(label[n, k], prediction[n, k])`` arrays whose
gradient/hessian are closed-form (matching the reference's formulas exactly,
e.g. the Huber/Quantile subgradients) and whose aggregate objective is a
single jitted ``value_and_grad`` with a ``psum`` across data shards.

Loss inventory and semantics mirror the reference:
- regression (dim=1, identity label encoding): squared (`:129-137`),
  absolute (`:139-143`), logcosh (`:145-152`), scaled logcosh(alpha)
  (`:154-166`), huber(delta) (`:168-177`), quantile(q) (`:179-188`)
- classification: logloss(K) softmax cross-entropy (`:196-263`),
  exponential (`:265-291`), bernoulli (`:293-318`) — the latter two use
  {0,1} -> {-1,+1} label encoding and dim=1.

Losses without a reference hessian (absolute, huber, quantile) report
``has_hessian=False``; GBM's "newton" update is only valid for the others,
mirroring ``HasHessian`` (`GBMLoss.scala:96-105`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _logcosh(x):
    # log(cosh(x)) computed stably: |x| + log1p(exp(-2|x|)) - log(2)
    a = jnp.abs(x)
    return a + jnp.log1p(jnp.exp(-2.0 * a)) - jnp.log(2.0)


def _log1pexp(x):
    # log(1 + exp(x)) stably (reference: spark ml impl Utils.log1pExp)
    return jnp.logaddexp(0.0, x)


class GBMLoss:
    """Protocol: batched loss over ``label[n, dim]`` / ``prediction[n, dim]``.

    ``loss`` returns per-instance values ``[n]``; ``gradient`` and ``hessian``
    return ``[n, dim]``.  All methods are traceable (jit/vmap/grad-safe).
    """

    dim: int = 1
    has_hessian: bool = False
    name: str = ""

    def encode_label(self, y: jax.Array) -> jax.Array:
        """``y[n] -> encoded[n, dim]`` (reference ``encodeLabel``)."""
        return y[:, None]

    def loss(self, label: jax.Array, prediction: jax.Array) -> jax.Array:
        raise NotImplementedError

    def gradient(self, label: jax.Array, prediction: jax.Array) -> jax.Array:
        raise NotImplementedError

    def negative_gradient(self, label, prediction):
        return -self.gradient(label, prediction)

    def hessian(self, label: jax.Array, prediction: jax.Array) -> jax.Array:
        raise NotImplementedError(f"{self.name} has no hessian")

    def sampling_scores(self, label, prediction):
        """Per-row gradient magnitude ``[n]`` driving gradient-based row
        sampling (GOSS/MVS, models/gbm.py): the l2 norm of the negative
        gradient over the class dims.  One definition so the regressor,
        the classifier, and the legacy weight-mask GOSS rank rows by the
        exact same statistic."""
        g = self.negative_gradient(label, prediction)
        return jnp.sqrt(jnp.sum(g * g, axis=-1))

    def linesearch_grad_hess(self, label, prediction, directions, bag_w):
        """Closed-form ``(grad[dim], hess[dim, dim])`` of the step-size
        objective ``a -> sum_i bag_w_i * L(label_i, pred_i + a∘dir_i)``,
        evaluated at the given ``prediction`` (= pred + a∘dir).

        Sums are SHARD-LOCAL; the Newton solver psums them.  Replaces
        ``jax.hessian`` of the objective — which costs ``dim`` forward
        passes per Newton iteration — with ONE pass over the data.  The
        default uses the per-row diagonal hessian, which is exact for
        ``dim == 1`` losses; multi-dim losses (LogLoss) override with the
        full per-row hessian.  Returns None when the loss has no hessian
        (caller falls back to autodiff).
        """
        if not self.has_hessian:
            return None
        g = self.gradient(label, prediction)
        h = self.hessian(label, prediction)
        grad = jnp.einsum("n,nk,nk->k", bag_w, g, directions)
        hess = jnp.diag(
            jnp.einsum("n,nk,nk->k", bag_w, h, directions * directions)
        )
        return grad, hess

    # serialization hooks (see utils.persist)
    def config(self) -> dict:
        return {"name": self.name}


class GBMClassificationLoss(GBMLoss):
    """Adds raw-score -> class-probability mapping (reference `:190-194`)."""

    num_classes: int = 2

    def raw2probability(self, raw: jax.Array) -> jax.Array:
        """``raw[n, num_classes] -> proba[n, num_classes]``."""
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Regression losses
# ---------------------------------------------------------------------------


class SquaredLoss(GBMLoss):
    name = "squared"
    has_hessian = True

    def loss(self, label, prediction):
        return jnp.sum((label - prediction) ** 2 / 2.0, axis=-1)

    def gradient(self, label, prediction):
        return -(label - prediction)

    def hessian(self, label, prediction):
        return jnp.ones_like(prediction)


class AbsoluteLoss(GBMLoss):
    name = "absolute"

    def loss(self, label, prediction):
        return jnp.sum(jnp.abs(label - prediction), axis=-1)

    def gradient(self, label, prediction):
        return -jnp.sign(label - prediction)


class LogCoshLoss(GBMLoss):
    name = "logcosh"
    has_hessian = True

    def loss(self, label, prediction):
        return jnp.sum(_logcosh(label - prediction), axis=-1)

    def gradient(self, label, prediction):
        return -jnp.tanh(label - prediction)

    def hessian(self, label, prediction):
        t = jnp.tanh(label - prediction)
        return 1.0 - t * t


class ScaledLogCoshLoss(GBMLoss):
    """Asymmetric logcosh: alpha above the prediction, (1-alpha) below
    (reference `GBMLoss.scala:154-166`)."""

    name = "scaledlogcosh"
    has_hessian = True

    def __init__(self, alpha: float = 0.5):
        self.alpha = alpha

    def _scale(self, label, prediction):
        return jnp.where(label > prediction, self.alpha, 1.0 - self.alpha)

    def loss(self, label, prediction):
        return jnp.sum(
            self._scale(label, prediction) * _logcosh(label - prediction), axis=-1
        )

    def gradient(self, label, prediction):
        return self._scale(label, prediction) * -jnp.tanh(label - prediction)

    def hessian(self, label, prediction):
        t = jnp.tanh(label - prediction)
        return self._scale(label, prediction) * (1.0 - t * t)

    def config(self):
        return {"name": self.name, "alpha": self.alpha}


class HuberLoss(GBMLoss):
    name = "huber"

    def __init__(self, delta: float = 1.0):
        self.delta = delta

    def loss(self, label, prediction):
        r = label - prediction
        quad = r * r / 2.0
        lin = self.delta * (jnp.abs(r) - self.delta / 2.0)
        return jnp.sum(jnp.where(jnp.abs(r) <= self.delta, quad, lin), axis=-1)

    def gradient(self, label, prediction):
        r = label - prediction
        return jnp.where(jnp.abs(r) <= self.delta, -r, -self.delta * jnp.sign(r))

    def config(self):
        return {"name": self.name, "delta": self.delta}


class QuantileLoss(GBMLoss):
    name = "quantile"

    def __init__(self, quantile: float = 0.5):
        self.quantile = quantile

    def loss(self, label, prediction):
        r = label - prediction
        return jnp.sum(
            jnp.where(r > 0, self.quantile * r, (self.quantile - 1.0) * r), axis=-1
        )

    def gradient(self, label, prediction):
        r = label - prediction
        return jnp.where(r > 0, -self.quantile, 1.0 - self.quantile)

    def config(self):
        return {"name": self.name, "quantile": self.quantile}


# ---------------------------------------------------------------------------
# Classification losses
# ---------------------------------------------------------------------------


class LogLoss(GBMClassificationLoss):
    """K-class softmax cross-entropy on one-hot labels (`GBMLoss.scala:196-263`)."""

    name = "logloss"
    has_hessian = True

    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        self.dim = num_classes

    def encode_label(self, y):
        return jax.nn.one_hot(y.astype(jnp.int32), self.num_classes)

    def loss(self, label, prediction):
        logsumexp = jax.scipy.special.logsumexp(prediction, axis=-1, keepdims=True)
        return jnp.sum(-label * (prediction - logsumexp), axis=-1)

    def gradient(self, label, prediction):
        return jax.nn.softmax(prediction, axis=-1) - label

    def hessian(self, label, prediction):
        p = jax.nn.softmax(prediction, axis=-1)
        return p * (1.0 - p)

    def linesearch_grad_hess(self, label, prediction, directions, bag_w):
        """Exact softmax form: per-row hessian ``diag(p) - p pᵀ`` contracted
        with the directions — one data pass instead of ``num_classes``
        forward passes per Newton iteration."""
        p = jax.nn.softmax(prediction, axis=-1)
        g = p - label
        grad = jnp.einsum("n,nk,nk->k", bag_w, g, directions)
        pd = p * directions
        hess = jnp.diag(
            jnp.einsum("n,nk->k", bag_w, p * directions * directions)
        ) - jnp.einsum("n,nj,nk->jk", bag_w, pd, pd)
        return grad, hess

    def raw2probability(self, raw):
        return jax.nn.softmax(raw, axis=-1)

    def config(self):
        return {"name": self.name, "num_classes": self.num_classes}


class ExponentialLoss(GBMClassificationLoss):
    """AdaBoost exponential loss on {-1,+1}-encoded labels (`GBMLoss.scala:265-291`)."""

    name = "exponential"
    has_hessian = True
    num_classes = 2

    def encode_label(self, y):
        return (2.0 * y - 1.0)[:, None]

    def loss(self, label, prediction):
        return jnp.sum(jnp.exp(-label * prediction), axis=-1)

    def gradient(self, label, prediction):
        return -label * jnp.exp(-label * prediction)

    def hessian(self, label, prediction):
        return label * label * jnp.exp(-label * prediction)

    def raw2probability(self, raw):
        # reference: proba(1) = sigmoid(2 * raw(0)) with raw = (-f, f),
        # i.e. P(y=1) = sigmoid(-2 f) as composed by GBMClassificationModel
        # (`GBMClassifier.scala:562-565,583-587`); we preserve the composed
        # behavior on the K=2 raw vector.
        p1 = jax.nn.sigmoid(2.0 * raw[..., 0])
        return jnp.stack([1.0 - p1, p1], axis=-1)


class BernoulliLoss(GBMClassificationLoss):
    """Logistic loss on {-1,+1}-encoded labels (`GBMLoss.scala:293-318`)."""

    name = "bernoulli"
    has_hessian = True
    num_classes = 2

    def encode_label(self, y):
        return (2.0 * y - 1.0)[:, None]

    def loss(self, label, prediction):
        return jnp.sum(_log1pexp(-2.0 * label * prediction), axis=-1)

    def gradient(self, label, prediction):
        return -2.0 * label / (1.0 + jnp.exp(2.0 * label * prediction))

    def hessian(self, label, prediction):
        e = jnp.exp(2.0 * prediction * label)
        return (4.0 * e * label * label) / (1.0 + e) ** 2

    def raw2probability(self, raw):
        # reference: proba(1) = 1 / (1 + exp(raw(0))) with raw = (-f, f),
        # i.e. P(y=1) = sigmoid(f) (`GBMLoss.scala:311-316`).
        p1 = jax.nn.sigmoid(-raw[..., 0])
        return jnp.stack([1.0 - p1, p1], axis=-1)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

def get_regression_loss(
    name: str,
    alpha: float = 0.5,
    delta: float = 1.0,
    quantile: float = 0.5,
) -> GBMLoss:
    """By-name lookup mirroring ``GBMRegressor.getLoss`` (case-insensitive)."""
    name = name.lower()
    if name == "squared":
        return SquaredLoss()
    if name == "absolute":
        return AbsoluteLoss()
    if name == "logcosh":
        return LogCoshLoss()
    if name == "scaledlogcosh":
        return ScaledLogCoshLoss(alpha)
    if name == "huber":
        return HuberLoss(delta)
    if name == "quantile":
        return QuantileLoss(quantile)
    raise ValueError(f"unknown regression loss {name!r}")


def get_classification_loss(name: str, num_classes: int = 2) -> GBMClassificationLoss:
    """By-name lookup mirroring ``GBMClassifier.getLoss``."""
    name = name.lower()
    if name == "logloss":
        return LogLoss(num_classes)
    if name == "exponential":
        return ExponentialLoss()
    if name == "bernoulli":
        return BernoulliLoss()
    raise ValueError(f"unknown classification loss {name!r}")


def loss_from_config(cfg: dict) -> GBMLoss:
    name = cfg["name"]
    if name == "logloss":
        return LogLoss(cfg["num_classes"])
    if name in ("exponential", "bernoulli"):
        return get_classification_loss(name)
    return get_regression_loss(
        name,
        alpha=cfg.get("alpha", 0.5),
        delta=cfg.get("delta", 1.0),
        quantile=cfg.get("quantile", 0.5),
    )


def aggregate_loss(
    loss: GBMLoss,
    label: jax.Array,
    weight: jax.Array,
    prediction: jax.Array,
    axis_name: Optional[str] = None,
) -> jax.Array:
    """Weighted-mean objective with optional cross-shard ``psum``.

    The SPMD replacement for ``GBMLossAggregator`` + ``RDDLossFunction``
    (`GBMLoss.scala:34-76`): every shard computes its weighted loss sum, a
    ``psum`` over the mesh data axis produces the identical global mean on
    all devices.
    """
    num = jnp.sum(weight * loss.loss(label, prediction))
    den = jnp.sum(weight)
    if axis_name is not None:
        num = jax.lax.psum(num, axis_name)
        den = jax.lax.psum(den, axis_name)
    return num / jnp.maximum(den, 1e-30)
