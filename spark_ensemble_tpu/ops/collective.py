"""Shared collective helpers for SPMD kernels.

Every base learner psums its sufficient statistics over the mesh data axis
when fitting inside ``shard_map`` (the XLA stand-in for Spark executors
aggregating per-partition statistics with ``treeAggregate``,
`GBMClassifier.scala:344-355`).  One helper so the psum-or-identity logic
cannot silently diverge between learners.
"""

from __future__ import annotations

from typing import Optional

import jax


def preduce(x, axis_name: Optional[str]):
    """``psum`` over ``axis_name`` inside shard_map; identity when unsharded."""
    return jax.lax.psum(x, axis_name) if axis_name is not None else x


def pmax_reduce(x, axis_name: Optional[str]):
    """``pmax`` over ``axis_name`` inside shard_map; identity when unsharded
    (Drucker boosting's distributed ``maxError``,
    `BoostingRegressor.scala:232-249`)."""
    return jax.lax.pmax(x, axis_name) if axis_name is not None else x


def pvary_like_shard(x, axis_name: Optional[str]):
    """Mark ``x`` as varying over ``axis_name`` for shard_map's manual-axes
    tracking; identity when unsharded.  Needed for replicated literals
    (e.g. a ``lax.scan`` zero accumulator) that combine with sharded
    operands inside the scan body — without it the carry's in/out types
    disagree on their varying axes."""
    if axis_name is None:
        return x
    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    # version seam (ADVICE.md finding): jax renamed pvary -> pcast(to=
    # "varying") around 0.8, and pyproject's jax>=0.8 floor must not
    # AttributeError on runtimes that only have the old spelling; jax
    # versions predating BOTH have no varying-axes tracking at all
    # (check_rep-era shard_map), where the marking is a no-op anyway.
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, names, to="varying")
    pvary = getattr(jax.lax, "pvary", None)
    if pvary is not None:
        return pvary(x, names)
    return x


def pzero_like_shard(x, axis_name: Optional[str]):
    """A zeros-like loop-accumulator seed whose replication/varying type
    matches psum outputs on EVERY shard_map tracking generation.

    A plain ``jnp.zeros_like`` literal enters a ``scan``/``fori_loop``
    carry as replicated, but a body that adds psum-ed state to it makes
    the carry's output varying — and shard_map rejects carries whose
    in/out types disagree.  On vma-era jax the fix is ``pvary``
    (:func:`pvary_like_shard`); on check_rep-era jax (no pvary/pcast) a
    ``psum`` of the zeros is value-identical (zero summed over shards is
    zero) and carries the collective's replication set.
    """
    import jax.numpy as jnp

    z = jnp.zeros_like(x)
    if axis_name is None:
        return z
    if getattr(jax.lax, "pcast", None) is not None or getattr(
        jax.lax, "pvary", None
    ) is not None:
        return pvary_like_shard(z, axis_name)
    return preduce(z, axis_name)


def pmin_reduce(x, axis_name: Optional[str]):
    """``pmin`` over ``axis_name`` inside shard_map; identity when unsharded
    (brackets the distributed quantile refinement, `utils/quantile.py`)."""
    return jax.lax.pmin(x, axis_name) if axis_name is not None else x
