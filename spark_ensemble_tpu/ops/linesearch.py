"""Line-search optimizers for GBM step sizes, compiled on-device.

The reference runs these on the *driver*, with each objective evaluation a
full distributed pass (`RDDLossFunction` + treeAggregate):

- 1-D: commons-math ``BrentOptimizer(tol, tol)`` over [0, 100]
  (`GBMRegressor.scala:311,398-425`);
- K-dim: breeze ``LBFGSB`` with bounds [0, inf)^K, memory 10
  (`GBMClassifier.scala:290-292,413-431`).

Here both solvers live *inside* the jitted training step: the objective is a
fused XLA kernel over the (sharded) bag, so a whole Brent solve is one device
program with no host round-trips.  The K-dim box-constrained solve uses
projected Newton (jax.grad/jax.hessian, active-set masking, backtracking),
which for the smooth convex K<=num_classes objectives converges in a handful
of iterations — the role LBFGS-B plays in the reference.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from spark_ensemble_tpu.ops.collective import preduce

_CGOLD = 0.3819660112501051  # golden-section fraction


def chol_solve_psd(A: jax.Array, b: jax.Array) -> jax.Array:
    """Solve ``A x = b`` for small SPD ``A`` via an unrolled-in-XLA
    Cholesky–Crout factorization + two triangular solves, built entirely
    from elementwise/masked vector ops.

    ``jax.scipy.linalg.solve(assume_a="pos")`` dispatches to a LAPACK
    (batched-)Cholesky whose batched kernel is NOT bit-identical to the
    single-matrix one on ill-conditioned inputs — so a ``vmap``-ed Newton
    iteration (the megabatch sweep, models/gbm_sweep.py) would silently
    diverge from the sequential fit at the last bit and then walk a
    different backtracking path.  Masked vector ops batch to the SAME
    per-lane arithmetic under ``vmap``, which is what pins sweep fits
    bit-identical to sequential ones.  K here is the class-dim count
    (<= num_classes), so the O(K^3) loop is trivially small."""
    k = A.shape[0]
    idx = jnp.arange(k)

    def factor_col(j, L):
        # s = A[:, j] - L[:, :j] @ L[j, :j]  (mask replaces the :j slice;
        # broadcast-multiply + row reduce, NOT a matvec — dot_general picks
        # a different contraction order once vmap adds a batch dim)
        prior = (idx < j).astype(A.dtype)
        s = A[:, j] - jnp.sum(L * (L[j] * prior)[None, :], axis=1)
        dj = jnp.sqrt(s[j])
        col = jnp.where(idx == j, dj, jnp.where(idx > j, s / dj, 0.0))
        return L.at[:, j].set(col)

    L = jax.lax.fori_loop(0, k, factor_col, jnp.zeros_like(A))

    def fwd(i, yv):  # L y = b
        prior = (idx < i).astype(A.dtype)
        yi = (b[i] - jnp.sum(L[i] * yv * prior)) / L[i, i]
        return yv.at[i].set(yi)

    yv = jax.lax.fori_loop(0, k, fwd, jnp.zeros_like(b))

    def bwd(t, xv):  # L^T x = y
        i = k - 1 - t
        later = (idx > i).astype(A.dtype)
        xi = (yv[i] - jnp.sum(L[:, i] * xv * later)) / L[i, i]
        return xv.at[i].set(xi)

    return jax.lax.fori_loop(0, k, bwd, jnp.zeros_like(b))


def brent_minimize(
    f: Callable[[jax.Array], jax.Array],
    lo: float,
    hi: float,
    tol: float = 1e-6,
    max_iter: int = 100,
) -> jax.Array:
    """Classic Brent minimization (golden section + parabolic interpolation).

    Matches commons-math ``BrentOptimizer(rel=tol, abs=tol)`` stopping
    semantics closely enough for GBM step sizes; ``f`` is traced, so each
    iteration is one fused objective evaluation.

    NaN objective values are treated as +inf: Brent's bracketing updates
    are pure comparisons, and a NaN ``f(u)`` (an overflowing loss at an
    aggressive trial point) fails BOTH ``fu <= fx`` and its negation's
    bookkeeping, silently corrupting the bracket.  Mapping NaN to +inf
    makes such points ordinary rejections, so the returned step size stays
    finite whenever any bracketed point evaluates finite — the step-size
    half of the training-runtime numeric guards (docs/robustness.md); the
    per-round weight check in the GBM driver is the other half.
    """

    def f_safe(x):
        fx = f(x)
        return jnp.where(jnp.isnan(fx), jnp.inf, fx)

    lo = jnp.asarray(lo, jnp.float32)
    hi = jnp.asarray(hi, jnp.float32)
    x0 = lo + _CGOLD * (hi - lo)
    f0 = f_safe(x0)

    # state: (a, b, x, w, v, fx, fw, fv, d, e, it, done)
    init = (lo, hi, x0, x0, x0, f0, f0, f0, 0.0, 0.0, 0, False)

    def cond(s):
        *_, it, done = s
        return (~done) & (it < max_iter)

    def body(s):
        a, b, x, w, v, fx, fw, fv, d, e, it, _ = s
        m = 0.5 * (a + b)
        tol1 = tol * jnp.abs(x) + tol
        tol2 = 2.0 * tol1
        done = jnp.abs(x - m) <= tol2 - 0.5 * (b - a)

        # trial parabolic fit through (x, w, v)
        r = (x - w) * (fx - fv)
        q = (x - v) * (fx - fw)
        p = (x - v) * q - (x - w) * r
        q = 2.0 * (q - r)
        p = jnp.where(q > 0, -p, p)
        q = jnp.abs(q)
        etemp = e
        use_para = (
            (jnp.abs(p) < jnp.abs(0.5 * q * etemp))
            & (p > q * (a - x))
            & (p < q * (b - x))
            & (q != 0.0)
        )
        d_para = jnp.where(q != 0.0, p / jnp.where(q == 0.0, 1.0, q), 0.0)
        u_para = x + d_para
        # keep parabolic steps a tolerance away from the bounds
        d_para = jnp.where(
            (u_para - a < tol2) | (b - u_para < tol2),
            jnp.sign(m - x) * tol1 + jnp.where(m == x, tol1, 0.0),
            d_para,
        )
        e_gold = jnp.where(x >= m, a - x, b - x)
        d_gold = _CGOLD * e_gold
        e_new = jnp.where(use_para, etemp, e_gold)
        d_new = jnp.where(use_para, d_para, d_gold)
        # never step less than tol1
        u = jnp.where(
            jnp.abs(d_new) >= tol1, x + d_new, x + jnp.sign(d_new) * tol1
        )
        fu = f_safe(u)

        better = fu <= fx
        a_n = jnp.where(better, jnp.where(u >= x, x, a), jnp.where(u < x, u, a))
        b_n = jnp.where(better, jnp.where(u >= x, b, x), jnp.where(u < x, b, u))
        x_n = jnp.where(better, u, x)
        fx_n = jnp.where(better, fu, fx)
        # shift (w, v) bookkeeping
        promote_w = (~better) & ((fu <= fw) | (w == x))
        promote_v = (~better) & (~promote_w) & ((fu <= fv) | (v == x) | (v == w))
        w_n = jnp.where(better, x, jnp.where(promote_w, u, w))
        fw_n = jnp.where(better, fx, jnp.where(promote_w, fu, fw))
        v_n = jnp.where(better, w, jnp.where(promote_w, w, jnp.where(promote_v, u, v)))
        fv_n = jnp.where(better, fw, jnp.where(promote_w, fw, jnp.where(promote_v, fu, fv)))
        return (a_n, b_n, x_n, w_n, v_n, fx_n, fw_n, fv_n, d_new, e_new, it + 1, done)

    out = jax.lax.while_loop(cond, body, init)
    return out[2]


def projected_newton_box(
    f: Callable[[jax.Array], jax.Array],
    x0: jax.Array,
    lower: float = 0.0,
    max_iter: int = 20,
    tol: float = 1e-6,
    num_backtracks: int = 15,
    axis_name=None,
    grad_hess: Callable = None,
) -> jax.Array:
    """Minimize ``f`` over the box ``x >= lower`` by projected Newton.

    Active set = coordinates pinned at the bound with inward-pointing
    gradient; the Newton system is solved on the free set via masked
    Cholesky-backed solve with a small ridge; steps are backtracked with
    first-success halving (usually one objective evaluation per iteration).

    Inside ``shard_map`` with data-sharded rows, pass the SHARD-LOCAL
    objective plus ``axis_name``: the value, gradient, and Hessian are each
    psum-ed over the mesh axis here, so every shard runs the identical
    Newton iteration on the global objective.  (Passing an objective that
    already psums internally would silently produce *local* gradients —
    the transpose of ``psum`` does not re-reduce cotangents across shards —
    which is the distributed-line-search bug this parameter exists to
    prevent.  The reference's analogue is each breeze LBFGS-B evaluation
    being a full treeAggregate pass, `GBMClassifier.scala:413-431`.)
    """
    k = x0.shape[0]

    red = lambda v: preduce(v, axis_name)

    fval = lambda x: red(f(x))
    if grad_hess is None:
        # autodiff fallback: jax.hessian costs k forward passes over the
        # objective per iteration; losses supply a one-pass closed form
        # via `grad_hess` (ops/losses.py linesearch_grad_hess)
        grad_hess = lambda x: (jax.grad(f)(x), jax.hessian(f)(x))

    def proj(x):
        return jnp.maximum(x, lower)

    # while_loops with data-uniform conditions (all operands are psum-ed, so
    # every shard agrees): Newton exits when the projected gradient is flat
    # (typically ~5 iterations instead of the max), and backtracking stops at
    # the FIRST accepted candidate (same first-success semantics as sweeping
    # t in {1, 1/2, 1/4, ...}; usually 1 objective eval per iteration)
    def cond(s):
        x, fx, it, done = s
        return (~done) & (it < max_iter)

    def body(s):
        x, fx, it, _ = s
        g, H = grad_hess(x)
        g, H = red(g), red(H)
        active = (x <= lower + 1e-12) & (g > 0)
        free = ~active
        fm = free.astype(x.dtype)
        converged = jnp.max(jnp.abs(g * fm)) <= tol * (1.0 + jnp.abs(fx))
        Hm = H * fm[:, None] * fm[None, :] + jnp.diag(
            jnp.where(free, 1e-6, 1.0)
        )
        # batch-stable Cholesky solve: identical bits with and without a
        # vmap axis (the sweep-vs-sequential bit-identity contract)
        step = -chol_solve_psd(Hm, g * fm) * fm

        def bt_cond(b):
            t, fc, j = b
            # ~(fc < fx), NOT fc >= fx: a NaN objective (overflowing loss at
            # an aggressive full Newton step times 0-weight padding rows)
            # must count as "not accepted" and keep halving
            return ~(fc < fx) & (j < num_backtracks)

        def bt_body(b):
            t, fc, j = b
            t2 = 0.5 * t
            return (t2, fval(proj(x + t2 * step)), j + 1)

        t, fc, _ = jax.lax.while_loop(
            bt_cond, bt_body, (1.0, fval(proj(x + step)), 1)
        )
        accepted = fc < fx
        ok = accepted & ~converged
        x_new = jnp.where(ok, proj(x + t * step), x)
        f_new = jnp.where(ok, fc, fx)
        done = converged | ~accepted  # converged, or no decrease found
        return (x_new, f_new, it + 1, done)

    x, _, _, _ = jax.lax.while_loop(
        cond, body, (proj(x0), fval(proj(x0)), 0, False)
    )
    return x
