"""Histogram decision-tree kernels: level-wise fit and heap-descent predict.

This is the TPU-native re-design of the reference's workhorse base learner
(Spark MLlib ``DecisionTree{Classifier,Regressor}``, used by every reference
test suite).  Design points:

- **Dense complete binary tree** (heap layout, ``2^depth - 1`` internal
  nodes, ``2^depth`` leaves): all shapes are static, so a single ``fit_tree``
  trace serves every member/round and is `vmap`-able across ensemble members
  and class dims — the XLA replacement for the reference's driver-side
  ``Future`` parallelism (`BaggingClassifier.scala:180-201`,
  `GBMClassifier.scala:377-411`).
- **Level-wise histogram building**: per level, the (node, feature, bin)
  cell statistics are accumulated either by ``segment_sum`` (scatter-add;
  fast on CPU) or — the TPU path — as a **one-hot matmul on the MXU**:
  ``H[node*(1+k), d*B] = A^T @ binoh`` where ``A`` carries the per-row
  node-one-hot times ``(w, w*y)`` channels and ``binoh`` is the loop-
  invariant row-to-bin one-hot.  TPU scatter-adds serialize; the matmul
  form runs ~30x faster on a v5e for the 26-tree vmapped case and is exact
  with ``Precision.HIGHEST``.  A cumulative-sum scan over bins then yields
  every candidate split's left/right statistics.  With an ``axis_name`` the
  histograms are ``psum``-ed across the mesh data axis, which is the entire
  distributed-training story — the analogue of Spark executors aggregating
  per-partition statistics via ``treeAggregate``.
- **Unified impurity**: targets are ``Y[n, k]``; the split score
  ``sum_k (S_L^2/W_L + S_R^2/W_R)`` is weighted-variance gain for k=1
  regression and *exactly* weighted Gini gain for one-hot classification
  targets, so one kernel implements both DecisionTreeRegressor (variance)
  and DecisionTreeClassifier (gini).
- **Sampling by weights, not subsets**: bootstrap/subbag row sampling enters
  as ``w`` (Poisson/Bernoulli weights) and feature subspaces as a boolean
  ``feature_mask`` multiplied into split validity — static shapes, identical
  estimator statistics (see `spark_ensemble_tpu/utils/random.py`).
- Targets are centered at the root before accumulation: gains are
  shift-invariant, and centering keeps the S^2/W cancellation well inside
  float32 range on TPU.

Structure-of-arrays ``Tree`` pytree; a stacked ``Tree`` (leading member axis)
is a forest.
"""

from __future__ import annotations

import functools
import warnings
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from spark_ensemble_tpu.autotune.resolve import resolve as _tuned
from spark_ensemble_tpu.ops.collective import (
    preduce as _preduce,
    pvary_like_shard as _pvary_like_shard,
)


class Tree(NamedTuple):
    """Fitted tree; leaf_value[l] is the (weighted-mean) target vector."""

    split_feature: jax.Array  # i32[2^depth - 1]
    split_bin: jax.Array  # i32[2^depth - 1]; max_bins-1 encodes "always left"
    split_threshold: jax.Array  # f32[2^depth - 1]; +inf encodes "always left"
    leaf_value: jax.Array  # f32[2^depth, k]
    # f32[2^depth - 1] impurity gain of each realized split (0 at no-split
    # sentinels) — feeds gain-based feature importances, the TPU analogue
    # of Spark tree models' `featureImportances`
    split_gain: jax.Array

    @classmethod
    def _persist_defaults(cls, fields: dict) -> dict:
        """Persistence format evolution (consulted by ``persist._decode``):
        ``split_gain`` was added in round 3 — saves made before it load
        with zero gains (predictions unaffected; importances degrade to
        zeros)."""
        if "split_gain" not in fields and "split_threshold" in fields:
            fields["split_gain"] = jnp.zeros_like(fields["split_threshold"])
        return fields

    @property
    def depth(self) -> int:
        return (self.leaf_value.shape[-2]).bit_length() - 1

    @property
    def num_outputs(self) -> int:
        return self.leaf_value.shape[-1]


# bin-one-hot HBM budget for the matmul path under hist="auto":
# above this many (row x feature-bin) cells fall back to scatter
_MATMUL_HIST_MAX_CELLS = 2**28


# routing contractions (node-one-hot x small-int split tables) are exact in
# ONE bf16 MXU pass when every operand value is an integer the bf16 mantissa
# holds exactly (0..256): one-hots are 0/1 and bin indices are < max_bins.
# Above that bin count, fall back to the 6-pass f32 emulation.
_ROUTING_EXACT_MAX_BINS = 256

_HIST_PRECISION = {
    "highest": jax.lax.Precision.HIGHEST,  # 6-pass bf16 emulation of f32
    "high": jax.lax.Precision.HIGH,  # 3-pass bf16x3 (~f32 mantissa)
    "default": jax.lax.Precision.DEFAULT,  # single-pass bf16 inputs
    # pallas tier (fit_forest only): the level histogram runs as a pallas
    # kernel (ops/pallas_hist.py, 2-pass hi/lo ~16-bit statistics); every
    # OTHER statistic matmul (prefix sums, leaf stage, single-tree
    # fallback) runs at the 'high' setting
    "pallas": jax.lax.Precision.HIGH,
}


def _derived_hist_weight_floor(stat_prec, parent_w):
    """Weight floor for SUBTRACTION-derived histograms: an empty child's
    weight is exactly 0.0 when computed directly (an all-zero one-hot
    column dots to 0 even in bf16) but `parent - left` carries the tier's
    rounding noise — single-pass bf16 ~2^-8 relative to the TREE-PARENT's
    magnitude, 3-pass ~f32-mantissa — which would sail past an absolute
    1e-12 floor and record garbage splits/gains/fallback-values on a node
    no row occupies.  The floor must scale with the tree-parent's weight
    ``parent_w`` (the subtraction operands' magnitude): the node's own
    derived weight is itself ~noise for exactly the empty nodes the floor
    protects.  Children below the tier's noise level (1% / 1e-6 of their
    parent) are treated as empty — the same statistical degradation the
    fast tiers already accept on histogram contents."""
    rel = 1e-2 if stat_prec == jax.lax.Precision.DEFAULT else 1e-6
    return rel * parent_w


def _routing_precision(B: int):
    """Single-pass precision for the gather-free routing matmuls whenever it
    is provably bit-exact (see _ROUTING_EXACT_MAX_BINS)."""
    if B <= _ROUTING_EXACT_MAX_BINS:
        return jax.lax.Precision.DEFAULT
    return jax.lax.Precision.HIGHEST


def _prefix_sums(hist_w, hist_wy, bins_axis_w, stat_prec, hist):
    """Left-prefix sums over the bins axis of the histogram stats.

    Exact tier (or scatter hist path): ``jnp.cumsum`` — bit-identical
    summation order to the scatter path (the pinned scatter-vs-matmul
    parity invariant).  Fast tiers on the matmul path trade that ulp-level
    order identity away anyway, so they compute the prefix sums as ONE
    batched matmul against a triangular 0/1 matrix — an MXU op instead of
    a sequential scan, attacking the per-level cumsum tail in the round
    profile.  The tier policy lives HERE, next to the code it selects.
    The stream tier's histograms are the same matmul statistics (chunk-
    accumulated), so its fast tiers take the same triangular form — and
    the fused tier's (kernel-accumulated) likewise."""
    fast_tier = (
        hist in ("matmul", "stream", "fused")
        and stat_prec != jax.lax.Precision.HIGHEST
    )
    if not fast_tier:
        return (
            jnp.cumsum(hist_w, axis=bins_axis_w),
            jnp.cumsum(hist_wy, axis=bins_axis_w),
        )
    B = hist_w.shape[bins_axis_w]
    tri = jnp.triu(jnp.ones((B, B), jnp.float32))  # tri[b, c] = 1[b <= c]
    prec = _stat_precision_vs_onehot(stat_prec)
    assert bins_axis_w == hist_w.ndim - 1 and bins_axis_w == hist_wy.ndim - 2
    cw = jnp.einsum("...b,bc->...c", hist_w, tri, precision=prec)
    cwy = jnp.einsum("...bk,bc->...ck", hist_wy, tri, precision=prec)
    return cw, cwy


def _bin_one_hot(Xb, B):
    """Row-to-bin one-hot ``f32[rows, d*B]`` — the histogram matmul's RHS,
    shared by every tier that builds it (fit_tree, dense fit_forest, and
    the stream tier's per-chunk body)."""
    rows, d = Xb.shape
    return (
        (Xb[:, :, None] == jnp.arange(B, dtype=Xb.dtype))
        .astype(jnp.float32)
        .reshape(rows, d * B)
    )


def _route_members(Xb, node, best_f, best_t, n_nodes, route_prec):
    """Gather-free level routing shared by the dense and streamed fused-
    forest paths (see fit_tree): contract the node one-hot against the
    split tables — each contraction picks exactly one small-int term, so
    single-pass bf16 is bit-exact for max_bins <= 256
    (`_routing_precision`).  ``Xb [n, d]``, ``node [n, M]`` level-local
    ids -> child-level ids."""
    d = Xb.shape[1]
    node_oh = jax.nn.one_hot(node, n_nodes, dtype=jnp.float32)  # [n,M,nodes]
    t_row = jnp.einsum(
        "nmo,mo->nm", node_oh, best_t.astype(jnp.float32),
        precision=route_prec,
    )
    f_oh = jax.nn.one_hot(best_f, d, dtype=jnp.float32)  # [M, nodes, d]
    sel = jnp.einsum("nmo,mod->nmd", node_oh, f_oh, precision=route_prec)
    xb_f = jnp.einsum(
        "nmd,nd->nm", sel, Xb.astype(jnp.float32), precision=route_prec
    )
    return 2 * node + jnp.where(xb_f <= t_row, 0, 1)


def _level_split_tables(
    H, feature_mask, node_floor, min_info_gain, thresholds, B, stat_prec,
    hist,
):
    """Candidate-split scoring for one level, shared by the dense and
    streamed fused-forest paths: histograms ``H [M, nodes, 1+k, d, B]`` ->
    best-split tables + per-node statistics.  Same gain rule and
    tie-breaking argmax as ``fit_tree``."""
    M, n_nodes, _, d, _ = H.shape
    hist_w = H[:, :, 0]  # [M, nodes, d, B]
    hist_wy = jnp.moveaxis(H[:, :, 1:], 2, -1)  # [M,nodes,d,B,k]

    cw, cwy = _prefix_sums(hist_w, hist_wy, 3, stat_prec, hist)
    W = cw[:, :, :1, -1:]  # [M, nodes, 1, 1]
    S = cwy[:, :, :1, -1:, :]  # [M, nodes, 1, 1, k]
    WL = cw[:, :, :, : B - 1]
    SL = cwy[:, :, :, : B - 1, :]
    WR = W - WL
    SR = S - SL

    def score(s, wgt):
        return jnp.sum(s * s, axis=-1) / jnp.maximum(wgt, 1e-12)

    parent_score = score(S[:, :, 0, 0, :], W[:, :, 0, 0])[:, :, None, None]
    gain = score(SL, WL) + score(SR, WR) - parent_score  # [M,nodes,d,B-1]
    wf = node_floor[:, :, None, None]
    valid = (WL > wf) & (WR > wf) & feature_mask[:, None, :, None]
    gain = jnp.where(valid, gain, -jnp.inf)

    flat = gain.reshape(M, n_nodes, d * (B - 1))
    best = jnp.argmax(flat, axis=2)
    best_gain = jnp.take_along_axis(flat, best[:, :, None], axis=2)[:, :, 0]
    best_f = (best // (B - 1)).astype(jnp.int32)
    best_t = (best % (B - 1)).astype(jnp.int32)

    do_split = best_gain > min_info_gain
    best_f = jnp.where(do_split, best_f, 0)
    best_t = jnp.where(do_split, best_t, B - 1)
    thr = jnp.where(
        do_split, thresholds[best_f, jnp.minimum(best_t, B - 2)], jnp.inf
    )
    node_w = cw[:, :, 0, -1]  # [M, nodes]
    node_wy = cwy[:, :, 0, -1, :]  # [M, nodes, k]
    return best_f, best_t, thr, do_split, best_gain, node_w, node_wy


def _stat_precision_vs_onehot(stat_prec):
    """Per-operand precision for statistic matmuls whose OTHER side is a
    pure 0/1 one-hot: the one-hot is exactly bf16-representable, so it
    needs only a single decomposition term — on the MXU this halves the
    pass count of the exact tier with a bit-identical result.  Returns the
    (stat_side, onehot_side) pair."""
    return (stat_prec, jax.lax.Precision.DEFAULT)


def _auto_hist_heuristic(n: int, d: int, B: int) -> str:
    """Static tier heuristic behind hist='auto' (also the fused tier's
    fallback): every accelerator backend (tpu, tpu-like plugins, gpu)
    serializes scatter-adds, so only CPU prefers the segment_sum path;
    past the matmul tier's one-hot budget an accelerator takes the
    row-chunked STREAM tier (same matmuls, no [n, d*B] operand)."""
    if jax.default_backend() != "cpu":
        if n * d * B <= _MATMUL_HIST_MAX_CELLS:
            return "matmul"
        return "stream"
    return "scatter"


def _resolve_fused(
    n: int, d: int, B: int, *, M: int, C: int, max_depth: int,
    warn: bool = True,
) -> str:
    """Gate for the fused round kernel (hist='fused'): confirm the tier
    or fall back.  The decision consults the SAME static VMEM estimate
    (``fused_vmem_bytes``) the kernel's footprint is modeled by, so the
    fallback decision and the estimate cannot disagree."""
    from spark_ensemble_tpu.ops.binning import pack_width
    from spark_ensemble_tpu.ops.pallas_hist import (
        _INTERPRET_MAX_ROWS,
        _interpret,
        fused_vmem_budget,
        fused_vmem_bytes,
    )

    reason = None
    if B > _ROUTING_EXACT_MAX_BINS:
        # 8-bit lanes top out at 256 bins, and past that the in-kernel
        # routing contraction also loses its bf16 exactness proof
        reason = f"max_bins={B} exceeds the packable range (256)"
    elif _interpret() and n > _INTERPRET_MAX_ROWS:
        reason = (
            f"no TPU backend at n={n} rows (interpreter mode is viable "
            f"only below {_INTERPRET_MAX_ROWS})"
        )
    else:
        bits = pack_width(B)
        need = fused_vmem_bytes(2 ** max(max_depth - 1, 0), M, C, d, B, bits)
        if need > fused_vmem_budget():
            reason = (
                f"VMEM estimate {need} bytes exceeds the "
                f"{fused_vmem_budget()}-byte budget"
            )
    if reason is None:
        return "fused"
    fallback = _auto_hist_heuristic(n, d, B)
    if warn:
        warnings.warn(
            f"hist='fused' falling back to the '{fallback}' tier: {reason}",
            stacklevel=3,
        )
    return fallback


def _resolve_hist(
    hist: str, n: int, d: int, B: int, *, M: int = 1, C: int = 2,
    max_depth: int = 5, warn: bool = True,
) -> str:
    if hist == "fused":
        return _resolve_fused(n, d, B, M=M, C=C, max_depth=max_depth,
                              warn=warn)
    if hist != "auto":
        return hist
    # a measured winner for this device/shape class overrides the static
    # heuristic below (autotune.resolve; "auto" == no winner recorded).
    # An explicit hist param never reaches this branch — hand-set wins.
    tier = _tuned("hist_tier", "auto", n=n)
    if tier == "fused":
        return _resolve_fused(n, d, B, M=M, C=C, max_depth=max_depth,
                              warn=warn)
    if tier in ("scatter", "matmul", "stream"):
        return tier
    return _auto_hist_heuristic(n, d, B)


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_depth", "max_bins", "min_info_gain", "axis_name", "hist",
        "hist_precision", "return_leaf",
    ),
)
def fit_tree(
    Xb: jax.Array,  # i32[n, d] binned features
    Y: jax.Array,  # f32[n, k] targets (k=1 regression, k=K one-hot classes)
    w: jax.Array,  # f32[n] sample weights (0 allowed; rows never dropped)
    thresholds: jax.Array,  # f32[d, max_bins-1] raw-value split thresholds
    feature_mask: Optional[jax.Array] = None,  # bool[d]
    *,
    max_depth: int = 5,
    max_bins: int = 64,
    min_info_gain: float = 0.0,
    axis_name: Optional[str] = None,
    hist: str = "auto",  # auto | scatter | matmul | stream | fused
    hist_precision: str = "highest",  # statistic-matmul MXU passes, see below
    return_leaf: bool = False,  # also return each row's final leaf id [n]
) -> Tree:
    """``hist_precision`` sets the MXU precision of the STATISTIC math
    (histogram accumulation, leaf sums, and — on the fast tiers — the bin
    prefix sums, which switch from an exact cumsum scan to a triangular
    matmul): "highest" is exact f32 (bit-equal to the scatter path),
    "high" is 3-pass bf16x3 (~f32 mantissa; split choices rarely move),
    "default" is single-pass bf16 inputs (~3 decimal digits on the
    statistics — the fastest; split quality degrades gracefully like
    subsampled histograms).  Fast tiers additionally use the
    histogram-subtraction trick (left children computed, right = parent -
    left), halving the dominant matmul's node dimension at every level
    past the root — ~2x fewer histogram FLOPs per tree.  Routing
    contractions are NOT affected: they pick single one-hot terms and run
    single-pass whenever that is provably bit-exact."""
    n, d = Xb.shape
    k = Y.shape[1]
    B = max_bins
    num_internal = 2**max_depth - 1
    hist = _resolve_hist(hist, n, d, B, M=1, C=1 + k, max_depth=max_depth)
    if hist in ("stream", "fused"):
        # the row-chunked and fused-kernel tiers live in the forest
        # path; a single tree is their M=1 case
        forest = fit_forest(
            Xb,
            Y[:, None, :],
            w[:, None],
            thresholds,
            feature_mask,
            max_depth=max_depth,
            max_bins=max_bins,
            min_info_gain=min_info_gain,
            axis_name=axis_name,
            hist=hist,
            hist_precision=hist_precision,
            return_leaf=return_leaf,
        )
        if return_leaf:
            forest, node = forest
            return jax.tree_util.tree_map(lambda a: a[0], forest), node[:, 0]
        return jax.tree_util.tree_map(lambda a: a[0], forest)
    # case-normalized here (not at the Param) so direct kernel callers get
    # the same tolerance as estimator users
    stat_prec = _HIST_PRECISION[hist_precision.lower()]
    route_prec = _routing_precision(B)

    preduce = lambda x: _preduce(x, axis_name)

    w = w.astype(jnp.float32)
    # center targets at the (global) weighted root mean: shift-invariant gains,
    # better f32 conditioning of the S^2/W terms
    w_tot = preduce(jnp.sum(w))
    y_mean = preduce(jnp.sum(w[:, None] * Y, axis=0)) / jnp.maximum(w_tot, 1e-30)
    Yc = Y - y_mean

    if feature_mask is None:
        feature_mask = jnp.ones((d,), bool)

    feat_offsets = jnp.arange(d, dtype=jnp.int32) * B
    if hist == "matmul":
        # loop-invariant row-to-bin one-hot, consumed by every level's matmul
        bin_oh = _bin_one_hot(Xb, B)

    split_feature = jnp.zeros((num_internal,), jnp.int32)
    split_bin = jnp.zeros((num_internal,), jnp.int32)
    split_threshold = jnp.zeros((num_internal,), jnp.float32)
    split_gain = jnp.zeros((num_internal,), jnp.float32)

    node = jnp.zeros((n,), jnp.int32)  # node-local index within current level
    parent_value = y_mean[None, :]  # [1, k] fallback values, updated per level
    prev_H = None  # previous level's histograms (fast-tier subtraction)
    prev_W = None  # previous level's node weights (tier-scaled floors)
    prev_floor = None  # previous level's floors (accumulated along derived chains)

    for level in range(max_depth):
        n_nodes = 2**level
        # ---- histograms over (node, feature, bin) cells -------------------
        sub_path = False
        if hist == "matmul":
            vals = jnp.concatenate([w[:, None], w[:, None] * Yc], axis=1)  # [n,1+k]
            # hoisted: the hist A-matrix (exact tier) and the routing
            # contraction below both consume it
            node_oh = jax.nn.one_hot(node, n_nodes, dtype=jnp.float32)
            fast_tier = stat_prec != jax.lax.Precision.HIGHEST
            sub_path = fast_tier and level >= 1
            if sub_path:
                # histogram-subtraction trick (XGBoost/LightGBM): compute
                # only the LEFT children's histograms and derive the right
                # siblings as parent - left — halves the dominant matmul's
                # M dimension at every level >= 1 (~2x fewer hist FLOPs
                # per tree overall).  f32 subtraction reorders the
                # accumulation, so this lives on the fast tiers only; the
                # exact tier keeps the bit-parity-with-scatter guarantee.
                half = n_nodes // 2
                left_oh = jax.nn.one_hot(
                    node >> 1, half, dtype=jnp.float32
                ) * (1.0 - (node & 1))[:, None].astype(jnp.float32)
                A = (left_oh[:, :, None] * vals[:, None, :]).reshape(
                    n, half * (1 + k)
                )
                Hl = preduce(
                    jax.lax.dot_general(
                        A.T,
                        bin_oh,
                        (((1,), (0,)), ((), ())),
                        precision=_stat_precision_vs_onehot(stat_prec),
                    ).reshape(half, 1 + k, d, B)
                )
                Hr = prev_H - Hl
                # interleave: children 2p (left), 2p+1 (right)
                H = jnp.stack([Hl, Hr], axis=1).reshape(n_nodes, 1 + k, d, B)
            else:
                A = (node_oh[:, :, None] * vals[:, None, :]).reshape(
                    n, n_nodes * (1 + k)
                )
                H = preduce(
                    jax.lax.dot_general(
                        A.T,
                        bin_oh,
                        (((1,), (0,)), ((), ())),
                        precision=_stat_precision_vs_onehot(stat_prec),
                    ).reshape(n_nodes, 1 + k, d, B)
                )
            prev_H = H  # next level's parent histograms (fast tier)
            # H is already preduce-d (the subtraction path must subtract
            # globally-reduced operands; psum commutes with the linear
            # subtraction either way)
            hist_w = H[:, 0]
            hist_wy = jnp.moveaxis(H[:, 1:], 1, -1)  # [nodes, d, B, k]
        else:
            seg = (node[:, None] * (d * B) + feat_offsets[None, :] + Xb).reshape(-1)
            hist_w = preduce(
                jax.ops.segment_sum(
                    jnp.broadcast_to(w[:, None], (n, d)).reshape(-1),
                    seg,
                    num_segments=n_nodes * d * B,
                ).reshape(n_nodes, d, B)
            )
            hist_wy = preduce(
                jax.ops.segment_sum(
                    jnp.broadcast_to(
                        (w[:, None] * Yc)[:, None, :], (n, d, k)
                    ).reshape(-1, k),
                    seg,
                    num_segments=n_nodes * d * B,
                ).reshape(n_nodes, d, B, k)
            )

        # ---- candidate split scores via cumulative sums over bins ---------
        cw, cwy = _prefix_sums(
            hist_w, hist_wy, 2, stat_prec, hist
        )  # [nodes, d, B], [nodes, d, B, k]
        W = cw[:, :1, -1:]  # [nodes, 1, 1] node total weight
        S = cwy[:, :1, -1:, :]  # [nodes, 1, 1, k] node total sums
        WL = cw[:, :, : B - 1]
        SL = cwy[:, :, : B - 1, :]
        WR = W - WL
        SR = S - SL

        def score(s, wgt):
            return jnp.sum(s * s, axis=-1) / jnp.maximum(wgt, 1e-12)

        parent_score = score(S[:, 0, 0, :], W[:, 0, 0])[:, None, None]
        gain = score(SL, WL) + score(SR, WR) - parent_score  # [nodes, d, B-1]
        if sub_path:
            # per-child floors: LEFT children are computed directly (an
            # empty one-hot column dots to exactly 0.0 at any tier), so
            # they take the direct-path floor; only the subtraction-derived
            # RIGHT children inherit the parent's accumulated error plus
            # this level's rounding at the parent's magnitude.  The sum
            # bounds the error of the chain actually derived by
            # subtraction (~depth * rel * local weight); a max() with the
            # parent's floor would pin every descendant at rel * ROOT
            # weight — a global cap on child size no tier intends
            right_floor = prev_floor + _derived_hist_weight_floor(
                stat_prec, prev_W
            )  # [half]
            node_floor = jnp.stack(
                [jnp.full_like(right_floor, 1e-12), right_floor], axis=-1
            ).reshape(n_nodes)
        else:
            node_floor = jnp.full((n_nodes,), 1e-12, jnp.float32)
        wf = node_floor[:, None, None]
        valid = (WL > wf) & (WR > wf) & feature_mask[None, :, None]
        gain = jnp.where(valid, gain, -jnp.inf)

        flat = gain.reshape(n_nodes, d * (B - 1))
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        best_f = (best // (B - 1)).astype(jnp.int32)
        best_t = (best % (B - 1)).astype(jnp.int32)

        do_split = best_gain > min_info_gain
        best_f = jnp.where(do_split, best_f, 0)
        # bin index B-1 means "every bin goes left" (threshold +inf)
        best_t = jnp.where(do_split, best_t, B - 1)
        thr = jnp.where(
            do_split, thresholds[best_f, jnp.minimum(best_t, B - 2)], jnp.inf
        )

        heap = (2**level - 1) + jnp.arange(n_nodes)
        split_feature = split_feature.at[heap].set(best_f)
        split_bin = split_bin.at[heap].set(best_t)
        split_threshold = split_threshold.at[heap].set(thr)
        split_gain = split_gain.at[heap].set(
            jnp.where(do_split, best_gain, 0.0)
        )

        # ---- route rows to children; update fallback values ---------------
        if hist == "matmul":
            # gather-free routing: TPU serializes per-row gathers (measured
            # ~3.8 ms per n-element gather at letter scale — the dominant
            # round cost, not the histograms).  Contract the node one-hot
            # against the per-node split tables instead; every contraction
            # selects exactly one term and all values are small integers,
            # so a single bf16 pass is bit-exact vs the gather for
            # max_bins <= 256 (_routing_precision).
            t_row = jax.lax.dot_general(
                node_oh,
                best_t.astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                precision=route_prec,
            )  # [n]
            f_oh = jax.nn.one_hot(best_f, d, dtype=jnp.float32)  # [nodes, d]
            sel = jax.lax.dot_general(
                node_oh,
                f_oh,
                (((1,), (0,)), ((), ())),
                precision=route_prec,
            )  # [n, d] one-hot of each row's split feature
            xb_f = jnp.sum(sel * Xb.astype(jnp.float32), axis=1)
            go_left = xb_f <= t_row
        else:
            xb_f = jnp.take_along_axis(Xb, best_f[node][:, None], axis=1)[:, 0]
            go_left = xb_f <= best_t[node]
        node = 2 * node + jnp.where(go_left, 0, 1)

        node_w = cw[:, 0, -1]  # [nodes]
        node_val = cwy[:, 0, -1, :] / jnp.maximum(node_w[:, None], 1e-30)
        # the same tier-scaled floor guards the fallback value: a derived
        # empty node's weight is noise >> 1e-12, and noise/noise garbage
        # must not displace the parent's fallback
        node_val = jnp.where(
            node_w[:, None] > node_floor[:, None], node_val, parent_value
        )
        # children inherit this level's value as fallback
        parent_value = jnp.repeat(node_val, 2, axis=0)
        prev_W = node_w  # next level's tree-parent weights
        prev_floor = node_floor

    # ---- leaf values ------------------------------------------------------
    num_leaves = 2**max_depth
    if hist == "matmul":
        leaf_oh = jax.nn.one_hot(node, num_leaves, dtype=jnp.float32)
        vals = jnp.concatenate([w[:, None], w[:, None] * Yc], axis=1)
        L = jax.lax.dot_general(
            leaf_oh.T,
            vals,
            (((1,), (0,)), ((), ())),
            precision=_stat_precision_vs_onehot(stat_prec)[::-1],
        )  # [leaves, 1+k]
        leaf_w = preduce(L[:, 0])
        leaf_wy = preduce(L[:, 1:])
    else:
        leaf_w = preduce(jax.ops.segment_sum(w, node, num_segments=num_leaves))
        leaf_wy = preduce(
            jax.ops.segment_sum(w[:, None] * Yc, node, num_segments=num_leaves)
        )
    leaf_value = leaf_wy / jnp.maximum(leaf_w[:, None], 1e-30)
    leaf_value = jnp.where(leaf_w[:, None] > 1e-12, leaf_value, parent_value)
    tree = Tree(
        split_feature=split_feature,
        split_bin=split_bin,
        split_threshold=split_threshold,
        leaf_value=leaf_value + y_mean[None, :],
        split_gain=split_gain,
    )
    # the loop's final `node` IS each row's leaf id — callers fitting then
    # immediately predicting on the SAME rows (the GBM round) reuse it
    # instead of re-routing (bit-identical: binned and raw routing agree,
    # test_binned_and_raw_predict_agree)
    return (tree, node) if return_leaf else tree


def feature_gains(trees: Tree, d: int) -> jax.Array:
    """Per-feature summed split gains ``f32[..., d]`` for a single tree or
    a stacked-member Tree pytree (any leading batch dims).

    No-split sentinel nodes carry gain 0 (and feature 0), so they
    contribute nothing.  Feeds gain-based ``feature_importances_`` — the
    analogue of Spark tree models' ``featureImportances`` (which the
    reference's users get from their Spark base models)."""
    sf = trees.split_feature
    sg = trees.split_gain
    flat_f = sf.reshape(-1, sf.shape[-1])
    flat_g = sg.reshape(-1, sg.shape[-1])
    out = jax.vmap(
        lambda f, g: jnp.zeros((d,), jnp.float32).at[f].add(g)
    )(flat_f, flat_g)
    return out.reshape(sf.shape[:-1] + (d,))


# fused-forest A-matrix budget: n * M * nodes * (1+k) cells at the deepest
# level; above this the fused build's intermediates outgrow their win and
# the vmapped per-tree path is used instead
_FOREST_FUSED_MAX_CELLS = 2**28

# rows per scan step of the STREAM tier: bounds the chunk's one-hot
# intermediates (bin_oh [chunk, d*B], A [chunk, M*nodes*(1+k)]) while
# keeping the matmul's contraction dim MXU-sized
_STREAM_CHUNK_ROWS = 32768

# rows * members * leaves budget of the fused predict routing one-hot
# (leaf_one_hot_forest); past it predict paths lax.map over row chunks —
# HBM-scale inference (~200 GB of one-hot at n=2M for a 100-round 8-class
# GBM if unchunked).  ONE constant and ONE helper for every layer:
# predict_forest chunks internally, and model predicts that reduce members
# inside their chunk call predict_chunked_rows directly.
_PREDICT_FUSED_MAX_CELLS = 2**27


def predict_chunked_rows(fn, Xq, n_members, leaves):
    """Apply ``fn`` (a per-chunk ``[rows, d] -> [rows, ...]`` predict whose
    member reduction — if any — happens INSIDE) over row chunks sized so
    the fused forest predict's ``[rows, members, leaves]`` one-hot stays
    under ``_PREDICT_FUSED_MAX_CELLS``; single direct call when it already
    fits.  Member-leading outputs: transpose around the call (cheap — XLA
    layout assignment)."""
    n = Xq.shape[0]
    # the module constant is the live default (tests monkeypatch it); a
    # measured winner for this device/shape class overrides it
    cells = _tuned("predict_fused_max_cells", _PREDICT_FUSED_MAX_CELLS, n=n)
    chunk = max(1024, cells // max(n_members * leaves, 1))
    if n <= chunk:
        return fn(Xq)
    nc = -(-n // chunk)
    pad = nc * chunk - n
    Xp = jnp.pad(Xq, ((0, pad), (0, 0))).reshape(nc, chunk, Xq.shape[1])
    out = jax.lax.map(fn, Xp)  # sequential: bounded live memory
    return out.reshape((nc * chunk,) + out.shape[2:])[:n]


def stream_vals_prep(Y, w, axis_name=None):
    """Stream-tier target preparation -> ``(w_tot, y_mean, vals)``.

    ``vals[n, M, 1+k]`` concatenates the per-row weight channel with the
    weighted, root-mean-centered target channels — the compact per-row
    statistic the chunked histogram bodies contract.  Shared by the
    resident scan (``_fit_forest_streamed``) and the out-of-core shard
    plane (``data/streaming.py``) so the two compute the SAME ops on the
    same operands — bit-identity by construction, not by tolerance."""
    w = w.astype(jnp.float32)
    w_tot = _preduce(jnp.sum(w, axis=0), axis_name)  # [M]
    y_mean = _preduce(
        jnp.sum(w[:, :, None] * Y, axis=0), axis_name
    ) / jnp.maximum(w_tot[:, None], 1e-30)  # [M, k]
    vals = jnp.concatenate(
        [w[:, :, None], w[:, :, None] * (Y - y_mean[None, :, :])], axis=2
    )  # [n, M, 1+k]
    return w_tot, y_mean, vals


def stream_level_step(
    acc, xb, nd, vl, *, n_nodes, tables, max_bins, stat_prec, route_prec
):
    """One row chunk's contribution to one level's histogram: route the
    chunk through the PREVIOUS level's split tables and matmul-accumulate
    into ``acc [M, n_nodes, 1+k, d, B]`` -> ``(acc, nd)``.

    This is the stream tier's scan body, extracted so the resident
    ``lax.scan`` and the per-shard programs of ``data/streaming.py`` run
    literally the same contraction at the same precision — a shard sweep
    accumulates ``acc`` across program calls in the same sequential order
    the scan does, so the histograms are bitwise equal."""
    chunk, d = xb.shape
    _, M, C = vl.shape
    if tables is not None:
        nd = _route_members(
            xb, nd, tables[0], tables[1], n_nodes // 2, route_prec
        )
    node_oh = jax.nn.one_hot(nd, n_nodes, dtype=jnp.float32)
    bin_oh = _bin_one_hot(xb, max_bins)
    A = (node_oh[:, :, :, None] * vl[:, :, None, :]).reshape(
        chunk, M * n_nodes * C
    )
    acc = acc + jax.lax.dot_general(
        A.T,
        bin_oh,
        (((1,), (0,)), ((), ())),
        precision=_stat_precision_vs_onehot(stat_prec),
    ).reshape(M, n_nodes, C, d, max_bins)
    return acc, nd


def stream_leaf_step(acc, xb, nd, vl, *, num_leaves, tables, stat_prec,
                     route_prec):
    """One row chunk's contribution to the leaf sums: route through the
    LAST level's tables and accumulate ``acc [M, num_leaves, 1+k]`` ->
    ``(acc, nd)``.  Shared with ``data/streaming.py`` (see
    ``stream_level_step``)."""
    nd = _route_members(
        xb, nd, tables[0], tables[1], num_leaves // 2, route_prec
    )
    leaf_oh = jax.nn.one_hot(nd, num_leaves, dtype=jnp.float32)
    acc = acc + jnp.einsum(
        "nml,nmc->mlc", leaf_oh, vl,
        precision=_stat_precision_vs_onehot(stat_prec)[::-1],
    )
    return acc, nd


def stream_level_update(
    H, feature_mask, min_info_gain, thresholds, max_bins, stat_prec, level,
    parent_value, split_feature, split_bin, split_threshold, split_gain,
):
    """Score one level's (already psum-ed) histograms and write its heap
    rows -> ``(tables, parent_value, split_feature, split_bin,
    split_threshold, split_gain)`` where ``tables = (best_f, best_t)``
    routes the NEXT scan/sweep.  Shared with ``data/streaming.py``."""
    M, n_nodes = H.shape[0], H.shape[1]
    node_floor = jnp.full((M, n_nodes), 1e-12, jnp.float32)
    best_f, best_t, thr, do_split, best_gain, node_w, node_wy = (
        _level_split_tables(
            H, feature_mask, node_floor, min_info_gain, thresholds,
            max_bins, stat_prec, "stream",
        )
    )
    heap = (2**level - 1) + jnp.arange(n_nodes)
    split_feature = split_feature.at[:, heap].set(best_f)
    split_bin = split_bin.at[:, heap].set(best_t)
    split_threshold = split_threshold.at[:, heap].set(thr)
    split_gain = split_gain.at[:, heap].set(
        jnp.where(do_split, best_gain, 0.0)
    )
    node_val = node_wy / jnp.maximum(node_w[:, :, None], 1e-30)
    node_val = jnp.where(
        node_w[:, :, None] > node_floor[:, :, None], node_val,
        parent_value,
    )
    parent_value = jnp.repeat(node_val, 2, axis=1)
    return (
        (best_f, best_t), parent_value,
        split_feature, split_bin, split_threshold, split_gain,
    )


def stream_leaf_values(leaf_w, leaf_wy, parent_value, y_mean):
    """Leaf sums -> final leaf values (zero-weight leaves fall back to the
    parent), re-centered at the root mean.  Shared with
    ``data/streaming.py``."""
    leaf_value = leaf_wy / jnp.maximum(leaf_w[:, :, None], 1e-30)
    leaf_value = jnp.where(
        leaf_w[:, :, None] > 1e-12, leaf_value, parent_value
    )
    return leaf_value + y_mean[:, None, :]


def _fit_forest_streamed(
    Xb, Y, w, thresholds, feature_mask, *, max_depth, max_bins,
    min_info_gain, axis_name, stat_prec, route_prec, return_leaf=False,
):
    """Row-chunked fused-forest fit (``hist="stream"``): the HBM-scale tier.

    The dense matmul path materializes three [n, ...] one-hot operands per
    level (``bin_oh [n, d*B]``, ``A [n, M*nodes*(1+k)]``, ``node_oh``) —
    ~16 GB of bin-one-hot alone at n=2M, d=64, B=64.  Here each level is ONE
    ``lax.scan`` over row chunks whose body (a) routes the chunk through the
    PREVIOUS level's split tables and (b) builds the chunk's one-hots in
    registers/VMEM and matmul-accumulates this level's histogram — so the
    per-level HBM traffic is one read of the compact inputs (binned
    features, node ids, value channels) and the one-hots never exist at full
    n.  Same statistic precision, gain rule, tie-breaking argmax, and psum
    points as the dense path (histograms are psum-ed AFTER the scan, so the
    mesh contract stays O(nodes·bins·k) per level; the reference's
    treeAggregate analogue, `GBMClassifier.scala:413-431`).  Prefix sums
    take the same tier policy as the dense path (`_prefix_sums`): exact
    cumsums at 'highest', the triangular matmul on the fast tiers.

    Routing identity: level-L routing is deferred into the level-(L+1)
    scan body (and the leaf scan) — the same einsum contractions at the
    same precision as the dense path, just chunked.
    """
    n, d = Xb.shape
    _, M, k = Y.shape
    B = max_bins
    C = 1 + k
    num_internal = 2**max_depth - 1
    preduce = lambda x: _preduce(x, axis_name)
    _pvary = lambda x: _pvary_like_shard(x, axis_name)

    _, y_mean, vals = stream_vals_prep(Y, w, axis_name)

    chunk = min(_tuned("stream_chunk_rows", _STREAM_CHUNK_ROWS, n=n), n)
    nc = -(-n // chunk)
    pad = nc * chunk - n
    # the scan re-reads the binned features once per level: store them at
    # uint8 when the bin count allows (4x less HBM traffic on the tier's
    # dominant read; bin ids 0..B-1 <= 255 are exact) and the one-hot /
    # routing casts upcast per chunk
    if B <= 256:
        Xb = Xb.astype(jnp.uint8)
    # zero-weight padding: all-zero ``vals`` rows contribute exactly 0.0
    # to every histogram/leaf statistic; where they route is irrelevant
    Xb_c = jnp.pad(Xb, ((0, pad), (0, 0))).reshape(nc, chunk, d)
    vals_c = jnp.pad(vals, ((0, pad), (0, 0), (0, 0))).reshape(
        nc, chunk, M, C
    )
    node_c = jnp.zeros((nc, chunk, M), jnp.int32)

    split_feature = jnp.zeros((M, num_internal), jnp.int32)
    split_bin = jnp.zeros((M, num_internal), jnp.int32)
    split_threshold = jnp.zeros((M, num_internal), jnp.float32)
    split_gain = jnp.zeros((M, num_internal), jnp.float32)
    parent_value = y_mean[:, None, :]  # [M, 1, k]
    prev_tables = None  # (best_f, best_t) of the previous level

    for level in range(max_depth):
        n_nodes = 2**level

        def body(acc, xs, n_nodes=n_nodes, tables=prev_tables):
            xb, nd, vl = xs
            return stream_level_step(
                acc, xb, nd, vl, n_nodes=n_nodes, tables=tables,
                max_bins=B, stat_prec=stat_prec, route_prec=route_prec,
            )

        H, node_c = jax.lax.scan(
            body,
            _pvary(jnp.zeros((M, n_nodes, C, d, B), jnp.float32)),
            (Xb_c, node_c, vals_c),
        )
        H = preduce(H)

        (prev_tables, parent_value,
         split_feature, split_bin, split_threshold, split_gain) = (
            stream_level_update(
                H, feature_mask, min_info_gain, thresholds, B, stat_prec,
                level, parent_value,
                split_feature, split_bin, split_threshold, split_gain,
            )
        )

    # final scan: route the last level, accumulate leaf sums
    num_leaves = 2**max_depth

    def leaf_body(acc, xs, tables=prev_tables):
        xb, nd, vl = xs
        return stream_leaf_step(
            acc, xb, nd, vl, num_leaves=num_leaves, tables=tables,
            stat_prec=stat_prec, route_prec=route_prec,
        )

    L, node_c = jax.lax.scan(
        leaf_body,
        _pvary(jnp.zeros((M, num_leaves, C), jnp.float32)),
        (Xb_c, node_c, vals_c),
    )
    leaf_w = preduce(L[:, :, 0])  # [M, L]
    leaf_wy = preduce(L[:, :, 1:])  # [M, L, k]
    tree = Tree(
        split_feature=split_feature,
        split_bin=split_bin,
        split_threshold=split_threshold,
        leaf_value=stream_leaf_values(leaf_w, leaf_wy, parent_value, y_mean),
        split_gain=split_gain,
    )
    if return_leaf:
        return tree, node_c.reshape(nc * chunk, M)[:n]
    return tree


def _fit_forest_fused(
    Xb, Y, w, thresholds, feature_mask, *, max_depth, max_bins,
    min_info_gain, axis_name, stat_prec, return_leaf=False,
):
    """Fused-round tier (``hist="fused"``): bit-packed bins, one pallas
    program per level.

    The bin matrix is packed ONCE per fit into 4/8-bit lanes
    (ops/binning.py `CompressedBins`) and every level's kernel DMAs the
    packed words — a 4-8x cut of the round loop's dominant HBM read
    versus the i32 matrix the pallas histogram tier streams, and ~B*4x
    versus the dense matmul tier's ``[n, d*B]`` bin one-hot.  Each grid
    step unpacks its block in VMEM, routes the rows through the PREVIOUS
    level's split tables (deferred routing, like the stream tier — but
    inside the kernel, contracted from the bin one-hot it already built),
    and accumulates this level's histogram, so a tree level is one kernel
    dispatch; split scoring and leaf solving stay on-device between
    kernels inside the same jitted program.

    Precision contract: histograms accumulate as the kernel's 3-term bf16
    split (~24-bit statistic mantissa — f32-grade, so split scores land
    within tie-break distance of the dense 'highest' tier); routing is
    bit-exact (0/1 contractions, max_bins <= 256 is enforced at
    resolution); leaf sums accumulate in f32.  Split scoring
    downstream of the histograms follows ``hist_precision`` exactly like
    the other tiers (`_prefix_sums`).  Every level is computed directly —
    empty nodes dot to exactly 0.0 — so the exact-path node floors apply,
    not the subtraction machinery.
    """
    from spark_ensemble_tpu.ops.binning import pack_bins, pack_width
    from spark_ensemble_tpu.ops.pallas_hist import fused_round_level

    n, d = Xb.shape
    _, M, k = Y.shape
    B = max_bins
    num_internal = 2**max_depth - 1
    preduce = lambda x: _preduce(x, axis_name)

    w = w.astype(jnp.float32)
    w_tot = preduce(jnp.sum(w, axis=0))  # [M]
    y_mean = preduce(jnp.sum(w[:, :, None] * Y, axis=0)) / jnp.maximum(
        w_tot[:, None], 1e-30
    )  # [M, k]
    vals = jnp.concatenate(
        [w[:, :, None], w[:, :, None] * (Y - y_mean[None, :, :])], axis=2
    )  # [n, M, 1+k]

    bits = pack_width(B)
    # loop-invariant: packed once, read by every level's kernel
    cb = pack_bins(Xb, B, bits)

    split_feature = jnp.zeros((M, num_internal), jnp.int32)
    split_bin = jnp.zeros((M, num_internal), jnp.int32)
    split_threshold = jnp.zeros((M, num_internal), jnp.float32)
    split_gain = jnp.zeros((M, num_internal), jnp.float32)
    node = jnp.zeros((n, M), jnp.int32)
    parent_value = y_mean[:, None, :]  # [M, 1, k]
    prev_tables = (None, None)  # previous level's (best_f, best_t)

    for level in range(max_depth):
        n_nodes = 2**level
        H, node = fused_round_level(
            cb.packed, node, vals, prev_tables[0], prev_tables[1],
            n_nodes=n_nodes, max_bins=B, bits=bits, num_features=d,
        )
        H = preduce(H)

        node_floor = jnp.full((M, n_nodes), 1e-12, jnp.float32)
        best_f, best_t, thr, do_split, best_gain, node_w, node_wy = (
            _level_split_tables(
                H, feature_mask, node_floor, min_info_gain, thresholds, B,
                stat_prec, "fused",
            )
        )

        heap = (2**level - 1) + jnp.arange(n_nodes)
        split_feature = split_feature.at[:, heap].set(best_f)
        split_bin = split_bin.at[:, heap].set(best_t)
        split_threshold = split_threshold.at[:, heap].set(thr)
        split_gain = split_gain.at[:, heap].set(
            jnp.where(do_split, best_gain, 0.0)
        )

        node_val = node_wy / jnp.maximum(node_w[:, :, None], 1e-30)
        node_val = jnp.where(
            node_w[:, :, None] > node_floor[:, :, None], node_val,
            parent_value,
        )
        parent_value = jnp.repeat(node_val, 2, axis=1)
        prev_tables = (best_f, best_t)

    # final kernel: route the last level, accumulate leaf sums (no bin
    # axis — the kernel's leaf mode outputs f32 column sums)
    num_leaves = 2**max_depth
    L, node = fused_round_level(
        cb.packed, node, vals, prev_tables[0], prev_tables[1],
        n_nodes=num_leaves, max_bins=B, bits=bits, num_features=d,
        leaf=True,
    )
    leaf_w = preduce(L[:, :, 0])  # [M, L]
    leaf_wy = preduce(L[:, :, 1:])  # [M, L, k]
    leaf_value = leaf_wy / jnp.maximum(leaf_w[:, :, None], 1e-30)
    leaf_value = jnp.where(
        leaf_w[:, :, None] > 1e-12, leaf_value, parent_value
    )
    tree = Tree(
        split_feature=split_feature,
        split_bin=split_bin,
        split_threshold=split_threshold,
        leaf_value=leaf_value + y_mean[:, None, :],
        split_gain=split_gain,
    )
    return (tree, node) if return_leaf else tree


def resolved_forest_tier(
    hist: str, hist_precision: str, n: int, d: int, B: int, *,
    M: int = 1, C: int = 2, max_depth: int = 5,
) -> str:
    """The histogram tier ``fit_forest`` would actually run for these
    static shapes: ``"pallas"`` when the pallas histogram kernel hosts
    the matmul tier, else the resolved hist string (fallbacks applied).
    Pure and warning-free — telemetry and bench call it to label rounds
    without side effects."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        pallas_tier = hist_precision.lower() == "pallas" and hist != "fused"
        if pallas_tier and hist == "auto":
            hist = (
                "matmul" if n * d * B <= _MATMUL_HIST_MAX_CELLS else "stream"
            )
        elif not (pallas_tier and hist == "matmul"):
            hist = _resolve_hist(
                hist, n, d, B, M=M, C=C, max_depth=max_depth, warn=False
            )
        pallas_tier = pallas_tier and hist == "matmul"
        if pallas_tier:
            from spark_ensemble_tpu.ops.pallas_hist import (
                _INTERPRET_MAX_ROWS,
                _interpret,
                hist_vmem_bytes,
                vmem_budget,
            )

            if _interpret() and n > _INTERPRET_MAX_ROWS:
                pallas_tier = False
            elif (
                hist_vmem_bytes(2 ** max(max_depth - 1, 0), M, C, d, B)
                > vmem_budget()
            ):
                pallas_tier = False
        return "pallas" if pallas_tier else hist


def round_cost_est(
    n: int, d: int, k: int, M: int, max_depth: int, max_bins: int,
    hist: str = "auto", hist_precision: str = "highest",
    sampled_rows: int = None,
) -> dict:
    """Static per-round cost estimate from shapes + the resolved tier.

    Returns ``{"hist_tier", "pack_bits", "hbm_bytes_est", "flops_est",
    "peak_flops"}``.  ``hbm_bytes_est`` models each tier's dominant HBM
    reads of row-sized operands summed over the tree's levels plus the
    leaf pass — write traffic and O(nodes*bins) tables are negligible at
    n >> nodes.  The matmul tier's per-level cost grows with the node
    count: it materializes the ``[n, M*nodes*C]`` node-stat operand each
    level and the ``[n, M, leaves]`` leaf one-hot (fit_forest), both
    full-row HBM intermediates; the stream/pallas/fused tiers build
    their one-hots per block in VMEM, so their per-level reads are flat.
    ``flops_est`` is the histogram-contraction MAC count (2 flops each),
    identical across tiers, so ``mfu_est = flops_est / (round_seconds *
    peak_flops)`` is comparable between tiers.  Feeds FitTelemetry round
    events (models/gbm.py) and the bench hist-tier A/B leg.

    ``sampled_rows`` (the compaction bucket of a GOSS/MVS-sampled round,
    models/gbm.py) re-models the histogram costs at the bucket size —
    including re-resolving the tier, since fewer rows can fit back under
    the matmul one-hot budget — plus ONE full-row feature pass (score +
    gather + direction re-route), and adds ``hbm_saved_est``: the
    predicted per-round HBM saving the ledger checks against measurement.

    The live operator plane cross-checks this model against XLA's own
    ``cost_analysis()`` for the round program
    (``xla_vs_analytic_flops_ratio`` in round_end events and bench
    output, sentinel-floored).  The two deliberately diverge: this
    model charges every level its full node dims (no
    sibling-subtraction credit), so the ratio sits well below 1 on CPU
    — see docs/operator.md#the-cost-triangle for the documented band.
    """
    B = max_bins
    C = 1 + k
    from spark_ensemble_tpu.ops.binning import pack_width

    def cost_at(n_rows: int):
        """(tier, pack_bits, hbm, flops) at a given row count — called a
        second time at the compaction bucket for sampled rounds, where the
        tier itself may differ (fewer rows can fit back under the matmul
        one-hot budget)."""
        tier = resolved_forest_tier(
            hist, hist_precision, n_rows, d, B, M=M, C=C,
            max_depth=max_depth,
        )
        bits = pack_width(B) if tier == "fused" else 0
        lanes = 32 // bits if bits else 1
        words = -(-d // lanes)

        def level_bytes(nodes: int, leaf: bool) -> int:
            flat = {
                # scatter: bin matrix + broadcast statistic writes per
                # channel
                "scatter": n_rows * d * (C + 1) * 4,
                # stream: uint8 bin matrix (B <= 256) + node ids + channels
                "stream": n_rows * (
                    (d if B <= 256 else d * 4) + M * 4 + M * C * 4
                ),
                # pallas histogram kernel: i32 bin matrix + node ids +
                # channels
                "pallas": n_rows * (d * 4 + M * 4 + M * C * 4),
                # fused: bit-packed words + node ids + channels
                "fused": n_rows * (words * 4 + M * 4 + M * C * 4),
            }
            if tier != "matmul":
                return flat[tier]
            if leaf:
                # leaf einsum: [n, M, leaves] one-hot + value channels
                return n_rows * M * (nodes + C) * 4
            # dense matmul: [n, d*B] bin one-hot + [n, M*nodes*C] stat
            # operand
            return n_rows * (d * B * 4 + M * nodes * C * 4)

        hbm = sum(
            level_bytes(2**level, False) for level in range(max_depth)
        ) + level_bytes(2**max_depth, True)
        flops = sum(
            2.0 * n_rows * (M * 2**level * C) * (d * B)
            for level in range(max_depth)
        ) + 2.0 * n_rows * M * 2**max_depth * C
        return tier, bits, hbm, flops

    tier, bits, hbm, flops = cost_at(n)
    saved = None
    if sampled_rows is not None and int(sampled_rows) < n:
        hbm_full = hbm
        # the compacted gather itself still reads the full-row feature
        # operand once (score + gather + full-row direction re-route):
        # charge one full-n row pass so the saving claim stays honest
        tier, bits, hbm, flops = cost_at(int(sampled_rows))
        hbm += n * d * 4
        saved = max(int(hbm_full) - int(hbm), 0)
    peak = 197e12 if jax.default_backend() == "tpu" else 1e12
    # nominal HBM bandwidth paired with peak_flops: the roofline's other
    # axis, so telemetry can model round time as max(flops/peak,
    # bytes/bw) and report cost_model_error_pct against the measured
    # duration (v5p-class HBM; CPU placeholder mirrors the peak_flops
    # convention above)
    bw = 1.23e12 if jax.default_backend() == "tpu" else 5e10
    out = {
        "hist_tier": tier,
        "pack_bits": bits,
        "hbm_bytes_est": int(hbm),
        "flops_est": float(flops),
        "peak_flops": float(peak),
        "hbm_bw_est": float(bw),
    }
    if saved is not None:
        out["hbm_saved_est"] = int(saved)
    return out


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_depth", "max_bins", "min_info_gain", "axis_name", "hist",
        "hist_precision", "return_leaf",
    ),
)
def fit_forest(
    Xb: jax.Array,  # i32[n, d] binned features, SHARED by all members
    Y: jax.Array,  # f32[n, M, k] per-member targets
    w: jax.Array,  # f32[n, M] per-member sample weights
    thresholds: jax.Array,  # f32[d, max_bins-1]
    feature_mask: Optional[jax.Array] = None,  # bool[M, d] | bool[d]
    *,
    max_depth: int = 5,
    max_bins: int = 64,
    min_info_gain: float = 0.0,
    axis_name: Optional[str] = None,
    hist: str = "auto",
    hist_precision: str = "highest",  # see fit_tree
    return_leaf: bool = False,  # also return row leaf ids [n, M]
) -> Tree:
    """Fit M trees at once on shared binned features -> stacked ``Tree``
    (leading member axis, same structure as ``jax.vmap(fit_tree)``).

    The win over vmapping ``fit_tree`` is one histogram matmul per level for
    ALL members: vmap emits M batched dots of tiny M-dim (``nodes*(1+k)`` =
    2..64 rows), each re-streaming the shared ``[n, d*max_bins]`` bin-one-hot
    from HBM, so the round is bandwidth-bound (measured: the 26-dim GBM
    round spends ~85% of its time in these re-reads).  Here the member axis
    folds INTO the matmul M dim — ``H[M*nodes*(1+k), d*B] = A^T @ bin_oh``
    reads ``bin_oh`` once and puts MXU-shaped M on the systolic array.  This
    is the XLA replacement for the reference's per-class-dim driver Futures
    (`GBMClassifier.scala:377-411`) on the histogram path itself.

    Semantics are identical to ``vmap(fit_tree)``: same statistic-matmul
    precision (``hist_precision``, default exact f32), same gain rule, same
    tie-breaking argmax, same psum points under ``axis_name``.
    """
    n, d = Xb.shape
    _, M, k = Y.shape
    B = max_bins
    num_internal = 2**max_depth - 1
    # pallas tier: the level histogram runs as a VMEM-resident pallas
    # kernel (ops/pallas_hist.py) — no bin_oh / A-matrix HBM operands.
    # Falls back to the 'high' matmul tier when the accumulator would not
    # fit the kernel's VMEM budget (static shapes, decided here).
    # The kernel is hosted by the FUSED MATMUL path only, and the stream
    # tier wins any conflict: an explicit hist='stream' (or an 'auto'
    # resolution past the matmul one-hot budget) takes the chunked path —
    # which exists precisely for shapes whose dense one-hot operands (the
    # pallas fallback path) cannot materialize — at the same 'high'
    # statistic precision the pallas tier maps to.
    # the fused tier supersedes the pallas histogram hosting: its kernel
    # already IS a pallas program (packed input, in-kernel routing)
    pallas_tier = hist_precision.lower() == "pallas" and hist != "fused"
    if pallas_tier and hist == "auto":
        hist = (
            "matmul" if n * d * B <= _MATMUL_HIST_MAX_CELLS else "stream"
        )
    elif not (pallas_tier and hist == "matmul"):
        hist = _resolve_hist(
            hist, n, d, B, M=M, C=1 + k, max_depth=max_depth
        )
    pallas_tier = pallas_tier and hist == "matmul"
    if pallas_tier:
        from spark_ensemble_tpu.ops.pallas_hist import (
            _INTERPRET_MAX_ROWS,
            _interpret,
            hist_vmem_bytes,
            vmem_budget,
        )

        if _interpret() and n > _INTERPRET_MAX_ROWS:
            # off-TPU the kernel only has the Python-level interpreter —
            # fine at parity-test shapes, hangs at dataset scale.  Fall
            # back to the 'high' matmul tier (the same statistic
            # precision this tier uses for its other matmuls) instead of
            # dispatching the interpreted kernel.
            warnings.warn(
                "hist_precision='pallas' requires a TPU backend at "
                f"n={n} rows (interpreter mode is viable only below "
                f"{_INTERPRET_MAX_ROWS}); falling back to the 'high' "
                "matmul tier",
                stacklevel=2,
            )
            pallas_tier = False
        elif (
            hist_vmem_bytes(2 ** (max_depth - 1), M, 1 + k, d, B)
            > vmem_budget()
        ):
            pallas_tier = False
    # case-normalized here (not at the Param) so direct kernel callers get
    # the same tolerance as estimator users
    stat_prec = _HIST_PRECISION[hist_precision.lower()]
    route_prec = _routing_precision(B)

    if feature_mask is None:
        feature_mask = jnp.ones((M, d), bool)
    elif feature_mask.ndim == 1:
        feature_mask = jnp.broadcast_to(feature_mask[None, :], (M, d))

    if hist == "stream":
        # row-chunked tier: no full-n one-hot intermediates, so neither
        # the matmul budget below nor the per-tree fallback applies
        return _fit_forest_streamed(
            Xb, Y, w, thresholds, feature_mask,
            max_depth=max_depth, max_bins=max_bins,
            min_info_gain=min_info_gain, axis_name=axis_name,
            stat_prec=stat_prec, route_prec=route_prec,
            return_leaf=return_leaf,
        )
    if hist == "fused":
        # fused round kernel: like stream, no full-n one-hot ever exists
        # (one-hots live per block in VMEM), so no budget check either
        return _fit_forest_fused(
            Xb, Y, w, thresholds, feature_mask,
            max_depth=max_depth, max_bins=max_bins,
            min_info_gain=min_info_gain, axis_name=axis_name,
            stat_prec=stat_prec, return_leaf=return_leaf,
        )

    # budget the fused path by its LARGEST [n, M, ...] intermediate: the
    # A-matrix build for the matmul tiers; only the routing one-hot
    # [n, M, nodes] for the pallas tier (its histogram never materializes
    # A or bin_oh — that is the point of the kernel), which extends the
    # fused range by (1 + k)x before falling back to per-tree fits
    if pallas_tier:
        fused_cells = n * M * 2 ** (max_depth - 1)
    else:
        fused_cells = n * M * 2 ** (max_depth - 1) * (1 + k)
    if hist != "matmul" or fused_cells > _FOREST_FUSED_MAX_CELLS:
        # scatter backend (CPU) or over-budget fused build: per-tree path
        fit_one = lambda Ym, wm, fm: fit_tree(
            Xb,
            Ym,
            wm,
            thresholds,
            fm,
            max_depth=max_depth,
            max_bins=max_bins,
            min_info_gain=min_info_gain,
            axis_name=axis_name,
            hist=hist,
            hist_precision=hist_precision,
            return_leaf=return_leaf,
        )
        out = jax.vmap(fit_one, in_axes=(1, 1, 0))(Y, w, feature_mask)
        if return_leaf:
            trees, nodes = out
            return trees, nodes.T  # [n, M]
        return out

    preduce = lambda x: _preduce(x, axis_name)

    w = w.astype(jnp.float32)
    w_tot = preduce(jnp.sum(w, axis=0))  # [M]
    y_mean = preduce(jnp.sum(w[:, :, None] * Y, axis=0)) / jnp.maximum(
        w_tot[:, None], 1e-30
    )  # [M, k]
    Yc = Y - y_mean[None, :, :]

    if not pallas_tier:
        # loop-invariant row-to-bin one-hot; the pallas tier builds it
        # per block in VMEM instead of materializing [n, d*B] in HBM
        bin_oh = _bin_one_hot(Xb, B)

    split_feature = jnp.zeros((M, num_internal), jnp.int32)
    split_bin = jnp.zeros((M, num_internal), jnp.int32)
    split_threshold = jnp.zeros((M, num_internal), jnp.float32)
    split_gain = jnp.zeros((M, num_internal), jnp.float32)

    node = jnp.zeros((n, M), jnp.int32)  # node-local index within the level
    parent_value = y_mean[:, None, :]  # [M, 1, k]
    vals = jnp.concatenate([w[:, :, None], w[:, :, None] * Yc], axis=2)  # [n,M,1+k]
    prev_H = None  # previous level's histograms (fast-tier subtraction)
    prev_W = None  # previous level's node weights (tier-scaled floors)
    prev_floor = None  # previous level's floors (accumulated along derived chains)
    # pallas computes every level DIRECTLY (empty nodes dot to exact 0.0),
    # so it takes the exact path's floors, not the subtraction machinery
    fast_tier = stat_prec != jax.lax.Precision.HIGHEST and not pallas_tier

    for level in range(max_depth):
        n_nodes = 2**level
        # ---- ONE histogram matmul for every member ------------------------
        if fast_tier and level >= 1:
            # histogram-subtraction trick (see fit_tree): left children
            # only, right = parent - left; halves the matmul's M dim
            half = n_nodes // 2
            left_oh = jax.nn.one_hot(node >> 1, half, dtype=jnp.float32) * (
                1.0 - (node & 1)
            ).astype(jnp.float32)[:, :, None]
            A = (left_oh[:, :, :, None] * vals[:, :, None, :]).reshape(
                n, M * half * (1 + k)
            )
            Hl = preduce(
                jax.lax.dot_general(
                    A.T,
                    bin_oh,
                    (((1,), (0,)), ((), ())),
                    precision=_stat_precision_vs_onehot(stat_prec),
                ).reshape(M, half, 1 + k, d, B)
            )
            Hr = prev_H - Hl
            H = jnp.stack([Hl, Hr], axis=2).reshape(M, n_nodes, 1 + k, d, B)
        elif pallas_tier:
            from spark_ensemble_tpu.ops.pallas_hist import hist_level_pallas

            H = preduce(
                hist_level_pallas(Xb, node, vals, n_nodes=n_nodes, max_bins=B)
            )
        else:
            node_oh = jax.nn.one_hot(
                node, n_nodes, dtype=jnp.float32
            )  # [n, M, nodes]
            A = (node_oh[:, :, :, None] * vals[:, :, None, :]).reshape(
                n, M * n_nodes * (1 + k)
            )
            H = preduce(
                jax.lax.dot_general(
                    A.T,
                    bin_oh,
                    (((1,), (0,)), ((), ())),
                    precision=_stat_precision_vs_onehot(stat_prec),
                ).reshape(M, n_nodes, 1 + k, d, B)
            )
        prev_H = H

        # ---- candidate split scores (same rule as fit_tree) ---------------
        if fast_tier and level >= 1:
            # per-child accumulated floors: direct LEFT children reset to
            # the direct-path floor, derived RIGHT children accumulate
            # (see fit_tree)
            right_floor = prev_floor + _derived_hist_weight_floor(
                stat_prec, prev_W
            )  # [M, half]
            node_floor = jnp.stack(
                [jnp.full_like(right_floor, 1e-12), right_floor], axis=-1
            ).reshape(M, n_nodes)
        else:
            node_floor = jnp.full((M, n_nodes), 1e-12, jnp.float32)
        best_f, best_t, thr, do_split, best_gain, node_w, node_wy = (
            _level_split_tables(
                H, feature_mask, node_floor, min_info_gain, thresholds, B,
                stat_prec, hist,
            )
        )

        heap = (2**level - 1) + jnp.arange(n_nodes)
        split_feature = split_feature.at[:, heap].set(best_f)
        split_bin = split_bin.at[:, heap].set(best_t)
        split_threshold = split_threshold.at[:, heap].set(thr)
        split_gain = split_gain.at[:, heap].set(
            jnp.where(do_split, best_gain, 0.0)
        )

        # ---- route rows to children (all members at once) -----------------
        node = _route_members(Xb, node, best_f, best_t, n_nodes, route_prec)

        node_val = node_wy / jnp.maximum(node_w[:, :, None], 1e-30)
        # tier-scaled floor also guards the fallback value (see fit_tree)
        node_val = jnp.where(
            node_w[:, :, None] > node_floor[:, :, None], node_val, parent_value
        )
        parent_value = jnp.repeat(node_val, 2, axis=1)
        prev_W = node_w  # next level's tree-parent weights
        prev_floor = node_floor

    # ---- leaf values ------------------------------------------------------
    num_leaves = 2**max_depth
    leaf_oh = jax.nn.one_hot(node, num_leaves, dtype=jnp.float32)  # [n,M,L]
    L = jnp.einsum(
        "nml,nmc->mlc", leaf_oh, vals,
        precision=_stat_precision_vs_onehot(stat_prec)[::-1],
    )
    leaf_w = preduce(L[:, :, 0])  # [M, L]
    leaf_wy = preduce(L[:, :, 1:])  # [M, L, k]
    leaf_value = leaf_wy / jnp.maximum(leaf_w[:, :, None], 1e-30)
    leaf_value = jnp.where(leaf_w[:, :, None] > 1e-12, leaf_value, parent_value)
    tree = Tree(
        split_feature=split_feature,
        split_bin=split_bin,
        split_threshold=split_threshold,
        leaf_value=leaf_value + y_mean[:, None, :],
        split_gain=split_gain,
    )
    # see fit_tree: `node` is each row's final leaf id, reusable by
    # fit-then-predict-same-rows callers (the GBM round)
    return (tree, node) if return_leaf else tree


@functools.lru_cache(maxsize=None)
def _path_constants(depth: int):
    """Static path-structure constants of the complete heap tree.

    For leaf ``l`` and level ``v`` the ancestor internal node is
    ``a = 2^v - 1 + (l >> (depth - v))`` and the required direction is bit
    ``depth-1-v`` of ``l`` (0 = left).  Encode the per-leaf path test as an
    affine map of the per-node go-left bits: ``score[l] = bits @ C[:, l] +
    c0[l]`` equals ``depth`` iff every decision on l's path matches.  These
    depend only on ``depth``, never on a fitted tree, so they are traced-in
    constants shared by all members.
    """
    import numpy as np

    num_internal = 2**depth - 1
    num_leaves = 2**depth
    C = np.zeros((num_internal, num_leaves), np.float32)
    c0 = np.zeros((num_leaves,), np.float32)
    for leaf in range(num_leaves):
        for v in range(depth):
            a = (2**v - 1) + (leaf >> (depth - v))
            s = (leaf >> (depth - 1 - v)) & 1
            C[a, leaf] += 1.0 - 2.0 * s
            c0[leaf] += s
    return C, c0


# bf16-safe clamp for non-finite features: must stay FINITE after rounding
# to bf16 (TPU HIGHEST-precision f32 matmuls decompose into bf16 passes; a
# clamp above bf16's max finite ~3.3895e38 would round to inf and the
# residual pass would reintroduce the NaN the clamp exists to remove)
_F32_MAX = 3.0e38

# the dense path-scoring matmul builds (2^D-1, 2^D) constants: great on the
# MXU for the shallow trees ensembles use (D<=10 -> <=4 MB), catastrophic at
# the deep end of the legal range (D=20 -> TB-scale).  Deeper trees take the
# classic per-level walk.
_MATMUL_PREDICT_MAX_DEPTH = 10


def _select_columns(X: jax.Array, f: jax.Array, d: int) -> jax.Array:
    """``X[:, f]`` without per-row gathers: on accelerators a one-hot matmul
    (selection is exact under ``Precision.HIGHEST``) rides the MXU; on CPU a
    plain column take is faster.

    Non-finite features are clamped first (NaN/+inf -> +f32max, -inf ->
    -f32max) on BOTH paths: ``0 * inf = NaN`` would otherwise poison every
    selected column through the dot product, and the clamp keeps the
    comparison semantics of the classic walk — NaN/+inf go right at every
    real split, -inf goes left — identically on CPU and TPU.  (Sole
    divergence from the old per-level walk: at a no-split sentinel node,
    threshold +inf, a NaN row now goes left with every other row instead of
    right; both subtrees of a sentinel carry the parent's fallback values.)
    """
    X = jnp.nan_to_num(
        X.astype(jnp.float32), nan=_F32_MAX, posinf=_F32_MAX, neginf=-_F32_MAX
    )
    if jax.default_backend() == "cpu":
        return jnp.take(X, f, axis=1)
    oh = jax.nn.one_hot(f, d, dtype=jnp.float32)  # [J, d]
    # one-hot side single-term (bit-exact, half the passes); X side HIGHEST
    return jax.lax.dot_general(
        X,
        oh,
        (((1,), (1,)), ((), ())),
        precision=(jax.lax.Precision.HIGHEST, jax.lax.Precision.DEFAULT),
    )


def _leaf_one_hot_from_bits(bits: jax.Array, depth: int) -> jax.Array:
    """Exact ``f32[n, 2^depth]`` leaf one-hot from per-node go-left bits via
    one MXU matmul: score every leaf path at once, then threshold — each
    row satisfies exactly one complete path."""
    C, c0 = _path_constants(depth)
    # bits (0/1) and C (-1/0/+1) are exactly bf16-representable and the MXU
    # accumulates in f32, so single-pass DEFAULT is bit-exact here — 6x
    # fewer passes than HIGHEST for the same result (|score| <= depth <= 10)
    score = (
        jax.lax.dot_general(
            bits,
            jnp.asarray(C),
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
        )
        + jnp.asarray(c0)[None, :]
    )
    return (score >= depth - 0.5).astype(jnp.float32)


def leaf_one_hot(tree: Tree, X: jax.Array, binned: bool) -> jax.Array:
    """Exact leaf-membership one-hot ``f32[n, 2^depth]`` for raw
    (``binned=False``) or pre-binned (``binned=True``) features — the
    row→leaf routing building block the linear-leaf learner batches its
    per-leaf regressions with."""
    leaf_first = tree.split_feature.shape[0]
    depth = (leaf_first + 1).bit_length() - 1
    if depth > _MATMUL_PREDICT_MAX_DEPTH:
        # the path-constant matrix grows 4^depth (TB-scale at the legal
        # max_depth=20); a materialized [n, 2^depth] one-hot is equally
        # unusable, so callers must cap depth instead
        raise ValueError(
            f"leaf_one_hot supports depth <= {_MATMUL_PREDICT_MAX_DEPTH}; "
            f"got {depth}"
        )
    Xg = _select_columns(X, tree.split_feature, X.shape[1])
    keys = tree.split_bin.astype(jnp.float32) if binned else tree.split_threshold
    bits = (Xg <= keys[None, :]).astype(jnp.float32)
    return _leaf_one_hot_from_bits(bits, depth)


def _predict_dense(bits: jax.Array, leaf_value: jax.Array, depth: int) -> jax.Array:
    """Leaf values from per-node go-left bits via two MXU matmuls: score all
    leaf paths at once, then select with the exact one-hot of the (unique)
    satisfied path.  Replaces the level-serial gather walk the round-1
    VERDICT flagged as the predict bottleneck."""
    leaf_oh = _leaf_one_hot_from_bits(bits, depth)  # exactly one-hot
    # exact one-hot side takes a single decomposition term (same bit-exact
    # halving as _stat_precision_vs_onehot); the value side stays HIGHEST
    return jax.lax.dot_general(
        leaf_oh,
        leaf_value,
        (((1,), (0,)), ((), ())),
        precision=(jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGHEST),
    )


def _predict_walk(node_key, tree: Tree, X: jax.Array, depth: int) -> jax.Array:
    """Classic per-level heap walk — O(depth) gathers per row; the deep-tree
    fallback (and the semantics reference for the matmul path)."""
    n = X.shape[0]
    X = jnp.nan_to_num(
        X.astype(jnp.float32), nan=_F32_MAX, posinf=_F32_MAX, neginf=-_F32_MAX
    )
    keys = tree.split_threshold if node_key == "threshold" else tree.split_bin
    leaf_first = tree.split_feature.shape[0]
    node = jnp.zeros((n,), jnp.int32)
    for _ in range(depth):
        f = tree.split_feature[node]
        thr = keys[node].astype(jnp.float32)
        x = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
        node = 2 * node + jnp.where(x <= thr, 1, 2)
    return tree.leaf_value[node - leaf_first]


@jax.jit
def predict_tree(tree: Tree, X: jax.Array) -> jax.Array:
    """``f32[n, k]`` leaf values for raw (unbinned) features ``X[n, d]``.

    Matmul form (no serialized per-level gathers — the TPU inference path the
    reference's per-row JVM predict, `GBMClassifier.scala:567-589`, must be
    beaten by): select the J split columns, compare against thresholds to get
    all node decisions at once, then path-score every leaf.  Trees deeper
    than ``_MATMUL_PREDICT_MAX_DEPTH`` fall back to the per-level walk (the
    path-constant matrix grows 4^depth).
    """
    leaf_first = tree.split_feature.shape[0]
    depth = (leaf_first + 1).bit_length() - 1
    if depth > _MATMUL_PREDICT_MAX_DEPTH:
        return _predict_walk("threshold", tree, X, depth)
    Xg = _select_columns(X, tree.split_feature, X.shape[1])
    bits = (Xg <= tree.split_threshold[None, :]).astype(jnp.float32)
    return _predict_dense(bits, tree.leaf_value, depth)


@jax.jit
def predict_tree_binned(tree: Tree, Xb: jax.Array) -> jax.Array:
    """Predict on pre-binned features (fast path inside training loops)."""
    leaf_first = tree.split_feature.shape[0]
    depth = (leaf_first + 1).bit_length() - 1
    if depth > _MATMUL_PREDICT_MAX_DEPTH:
        return _predict_walk("bin", tree, Xb, depth)
    Xg = _select_columns(Xb, tree.split_feature, Xb.shape[1])
    bits = (Xg <= tree.split_bin[None, :].astype(jnp.float32)).astype(jnp.float32)
    return _predict_dense(bits, tree.leaf_value, depth)


def predict_forest(
    trees: Tree, X: jax.Array, fused: Optional[bool] = None
) -> jax.Array:
    """Member predict for a stacked ``Tree`` -> ``f32[m, n, k]``.

    Fused path (accelerators): ONE column-select matmul covers every
    member's split features (vmapping ``predict_tree`` re-streams ``X`` per
    member and emits M skinny dots), then batched path-scoring and leaf
    selection.  Same exact one-hot/HIGHEST-precision math as
    ``predict_tree`` — parity is test-pinned.  CPU and deep trees fall back
    to the vmapped per-tree predict.
    """
    M, J = trees.split_feature.shape
    depth = (J + 1).bit_length() - 1
    if fused is None:
        fused = (
            jax.default_backend() != "cpu"
            and depth <= _MATMUL_PREDICT_MAX_DEPTH
        )
    if not fused or depth > _MATMUL_PREDICT_MAX_DEPTH:
        return jax.vmap(lambda t: predict_tree(t, X))(trees)

    def rows(Xc):
        leaf_oh = leaf_one_hot_forest(trees, Xc, binned=False)  # [c, M, L]
        # exact one-hot side single-term; value side HIGHEST (bit-exact)
        return jnp.einsum(
            "nml,mlk->nmk",
            leaf_oh,
            trees.leaf_value,
            precision=(jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGHEST),
        )

    # HBM-scale inference: past the routing one-hot's budget, lax.map the
    # same program over row chunks so [rows, M, leaves] never materializes
    # at full n (GBM model predicts ALSO reduce members inside their own
    # predict_chunked_rows call; this guard covers every other caller)
    out = predict_chunked_rows(rows, X, M, 2**depth)
    return jnp.moveaxis(out, 1, 0)  # [M, n, k]


def leaf_one_hot_forest(trees: Tree, X: jax.Array, binned: bool) -> jax.Array:
    """Exact leaf one-hot ``f32[n, M, 2^depth]`` for every member of a
    stacked Tree in ONE column-select matmul + one path-scoring matmul —
    the fused-member routing shared by ``predict_forest`` and the
    linear-leaf learner's member predict."""
    M, J = trees.split_feature.shape
    depth = (J + 1).bit_length() - 1
    if depth > _MATMUL_PREDICT_MAX_DEPTH:
        raise ValueError(
            f"leaf_one_hot_forest supports depth <= "
            f"{_MATMUL_PREDICT_MAX_DEPTH}; got {depth}"
        )
    n, d = X.shape
    Xc = jnp.nan_to_num(
        X.astype(jnp.float32), nan=_F32_MAX, posinf=_F32_MAX, neginf=-_F32_MAX
    )
    f_oh = jax.nn.one_hot(
        trees.split_feature.reshape(M * J), d, dtype=jnp.float32
    )
    Xsel = jax.lax.dot_general(
        Xc,
        f_oh,
        (((1,), (1,)), ((), ())),
        # one-hot side single-term: bit-exact at half the passes
        precision=(jax.lax.Precision.HIGHEST, jax.lax.Precision.DEFAULT),
    )  # [n, M*J]
    keys = (
        trees.split_bin.astype(jnp.float32) if binned else trees.split_threshold
    )
    bits = (
        Xsel <= keys.reshape(M * J)[None, :]
    ).astype(jnp.float32).reshape(n, M, J)
    C, c0 = _path_constants(depth)
    # both operands exactly bf16-representable small ints, f32 accumulation:
    # single-pass DEFAULT is bit-exact (see _predict_dense)
    score = (
        jnp.einsum(
            "nmj,jl->nml",
            bits,
            jnp.asarray(C),
            precision=jax.lax.Precision.DEFAULT,
        )
        + jnp.asarray(c0)[None, None, :]
    )
    return (score >= depth - 0.5).astype(jnp.float32)
