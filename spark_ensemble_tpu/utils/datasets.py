"""Dataset loading: libsvm parsing and dense array containers.

The reference's substrate is Spark's DataFrame/libsvm reader; ours is a dense
``(X: f32[n, d], y: f32[n])`` pair of host numpy arrays that estimators move
to device.  The three datasets bundled with the reference
(`/root/reference/data/{adult,cpusmall,letter}`) are read in place — they are
data, not code, and are never copied into this repo.

A native C++ fast path for parsing (the analogue of Spark's JVM loader) is
used when the compiled extension is present; the numpy fallback is always
available.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

REFERENCE_DATA = os.environ.get(
    "SPARK_ENSEMBLE_REFERENCE_DATA", "/root/reference/data"
)

_DATASETS = {
    "adult": ("adult/adult.svm", "binary"),
    "cpusmall": ("cpusmall/cpusmall.svm", "regression"),
    "letter": ("letter/letter.svm", "multiclass"),
}


def parse_libsvm(
    path: str, n_features: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Parse a libsvm text file into dense ``(X, y)`` float32/float64 arrays.

    Mirrors the semantics of Spark's ``format("libsvm")`` reader used
    throughout the reference test suites (1-based feature indices).
    """
    try:
        from spark_ensemble_tpu.utils._libsvm_native import parse_libsvm_native

        return parse_libsvm_native(path, n_features)
    except Exception:
        pass
    labels = []
    rows = []
    max_idx = 0
    with open(path, "r") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            feats = []
            for tok in parts[1:]:
                idx, val = tok.split(":")
                idx = int(idx)
                max_idx = max(max_idx, idx)
                feats.append((idx - 1, float(val)))
            rows.append(feats)
    d = n_features if n_features is not None else max_idx
    X = np.zeros((len(rows), d), dtype=np.float32)
    for i, feats in enumerate(rows):
        for j, v in feats:
            if j < d:  # out-of-range features dropped (native path parity)
                X[i, j] = v
    y = np.asarray(labels, dtype=np.float32)
    return X, y


def load_dataset(
    name: str, data_dir: Optional[str] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Load one of the reference's bundled datasets by name.

    Labels are normalized the way the reference tests consume them:
    - adult: ±1 → {0, 1}
    - letter: 1..26 → 0..25
    - cpusmall: raw regression target
    """
    if name not in _DATASETS:
        raise KeyError(f"unknown dataset {name!r}; options: {sorted(_DATASETS)}")
    rel, kind = _DATASETS[name]
    base = data_dir or REFERENCE_DATA
    path = os.path.join(base, rel)
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    X, y = parse_libsvm(path)
    if kind == "binary":
        y = (y > 0).astype(np.float32)
    elif kind == "multiclass":
        y = (y - y.min()).astype(np.float32)
    return X, y


def has_reference_data() -> bool:
    return all(
        os.path.exists(os.path.join(REFERENCE_DATA, rel))
        for rel, _ in _DATASETS.values()
    )


def train_test_split(
    X: np.ndarray,
    y: np.ndarray,
    test_fraction: float = 0.3,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic random split (reference tests: ``df.randomSplit(Array(0.7, 0.3))``)."""
    rng = np.random.RandomState(seed)
    n = X.shape[0]
    perm = rng.permutation(n)
    n_test = int(round(n * test_fraction))
    test_idx, train_idx = perm[:n_test], perm[n_test:]
    return X[train_idx], y[train_idx], X[test_idx], y[test_idx]
