from spark_ensemble_tpu.utils.quantile import (
    weighted_median,
    weighted_quantile,
)

__all__ = ["weighted_median", "weighted_quantile"]
