"""Weighted median / quantile kernels.

Reference semantics:
- ``Utils.weightedMedian`` (`Utils.scala:26-40`): sort by value, take the first
  element whose cumulative weight reaches half the total weight.
- ``approxQuantile`` (Greenwald-Khanna sketch, used for Huber's adaptive delta
  at `GBMRegressor.scala:342-353` and DummyRegressor quantile strategy at
  `DummyRegressor.scala:119-125`).

Local (unsharded) inputs use an exact sort + cumulative-sum + searchsorted
kernel — sorts are cheap in XLA at these scales and exactness strictly
dominates the reference's sketch approximation.

Sharded inputs (``axis_name`` set, inside shard_map) must match the
reference's scaling contract: `approxQuantile` is a STREAMING sketch — no
executor ever holds the full column — so the mesh path here must not
``all_gather`` the values either.  Instead it runs a fixed number of
``psum``-ed histogram-refinement rounds over the monotone u32 *bit* space of
the f32 values: 4 rounds x 256 bins resolve one of the 2^32 possible keys
exactly, so the result is the "first value whose global cumulative weight
reaches the target" — communicated state is O(bins) per round, never O(n).
(An f32-value-space bisection could need ~30+ rounds to separate values
across binades; bit-space refinement is exact in 4 by construction.)

Exactness caveat: the *key walk* is exact, but the crossing test compares
f32 sums accumulated in different orders (the psum-ed per-bin cumulative vs
the separately-summed total target), so with general f32 weights a
crossing that lands within one ulp of the target can select the adjacent
data value instead (`test_mesh_quantile_target_above_total_degrades_to_max`
encodes the boundary case; the dyadic-weight tests sidestep it).  The
result is always an actual data value, and bit-identical to the local sort
kernel whenever the weight sums are exactly representable.  All kernels are
jit/vmap-compatible (static shapes).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from spark_ensemble_tpu.ops.collective import (
    pmax_reduce,
    pmin_reduce,
    preduce,
    pzero_like_shard,
)

# 4 rounds x 256-bin psum-ed histograms walk the full 2^32 u32 key space
# down to a single key: 256^4 = 2^32 exactly.
_BINS = 256
_ROUNDS = 4


def _f32_keys(v: jax.Array) -> jax.Array:
    """Monotone bijection f32 -> u32 (the radix-sort key trick): flip the
    sign bit for non-negatives, all bits for negatives.  Total order matches
    f32 comparison, with -0.0 keyed just below +0.0 and NaNs keyed above
    +inf (the bracket seed in ``_sharded_crossing_key`` excludes NaNs so
    they can never be walked to)."""
    b = jax.lax.bitcast_convert_type(v.astype(jnp.float32), jnp.int32)
    return jnp.where(
        b >= 0,
        b.astype(jnp.uint32) + jnp.uint32(0x80000000),
        (~b).astype(jnp.uint32),
    )


def _key_to_f32(u: jax.Array) -> jax.Array:
    """Inverse of ``_f32_keys``."""
    b = jnp.where(
        u >= jnp.uint32(0x80000000),
        (u - jnp.uint32(0x80000000)).astype(jnp.int32),
        ~u.astype(jnp.int32),
    )
    return jax.lax.bitcast_convert_type(b, jnp.float32)


def _use_matmul_hist(n: int) -> bool:
    """Same policy as the tree kernels (`ops/tree.py _resolve_hist`, shared
    budget constant): the bin one-hot matmul is the MXU path, but its
    [n, bins] intermediate must stay bounded, and on CPU (where scatter is
    fast) segment_sum wins outright."""
    from spark_ensemble_tpu.ops.tree import _MATMUL_HIST_MAX_CELLS

    return jax.default_backend() != "cpu" and n * _BINS <= _MATMUL_HIST_MAX_CELLS


def _sharded_crossing_key(values, weights, target, axis_name) -> jax.Array:
    """u32 key of the first value whose GLOBAL cumulative weight >= target.

    Each round buckets the shard's in-bracket keys into 256 equal key-space
    bins (a one-hot contraction — MXU-friendly, no scatter), ``psum``s the
    256 weights, picks the bin where the cumulative crosses ``target``, and
    narrows the bracket to it; after 4 rounds the bracket is a single key.
    The crossing bin always carries positive weight (its cumulative strictly
    exceeds its predecessor's), so the result is an actual data value, and
    zero-weight values can never be selected — the `Utils.scala:26-40` rule.
    """
    u = _f32_keys(values)
    w = weights.astype(jnp.float32)

    matmul_hist = _use_matmul_hist(values.shape[0])

    def body(_, state):
        lo, hi, cum_below = state
        step = (hi - lo) // jnp.uint32(_BINS) + jnp.uint32(1)
        rel = ((u - lo) // step).astype(jnp.int32)
        in_bracket = (u >= lo) & (u <= hi)
        if matmul_hist:
            # out-of-bracket rows one-hot to class _BINS -> all-zero row
            oh = jax.nn.one_hot(
                jnp.where(in_bracket, rel, _BINS), _BINS, dtype=jnp.float32
            )
            hist = jnp.einsum(
                "nb,n->b",
                oh,
                w,
                precision=(
                    jax.lax.Precision.DEFAULT,
                    jax.lax.Precision.HIGHEST,
                ),
            )
        else:
            hist = jax.ops.segment_sum(
                jnp.where(in_bracket, w, 0.0),
                jnp.clip(rel, 0, _BINS - 1),
                num_segments=_BINS,
            )
        hist = preduce(hist, axis_name)
        cum = cum_below + jnp.cumsum(hist)
        ge = cum >= target
        # target can exceed the final cumulative by rounding slack (the
        # total is summed in a different order than the histogram's cum);
        # degrade to the bin CONTAINING hi — later rounds then converge on
        # the data max, the exact kernel's clipped-index answer.  (Bin
        # _BINS-1 would be wrong: it can lie past hi and invert the
        # bracket into garbage.)
        hi_bin = ((hi - lo) // step).astype(jnp.int32)
        j = jnp.where(ge.any(), jnp.argmax(ge), hi_bin)
        new_lo = lo + j.astype(jnp.uint32) * step
        # saturate: the last bin's upper edge can wrap past 0xffffffff
        hi_raw = new_lo + (step - jnp.uint32(1))
        hi_raw = jnp.where(hi_raw < new_lo, jnp.uint32(0xFFFFFFFF), hi_raw)
        new_hi = jnp.minimum(hi, hi_raw)
        new_below = jnp.where(j > 0, cum[jnp.maximum(j - 1, 0)], cum_below)
        return new_lo, new_hi, new_below

    # bracket at the global data min/max: with target 0 (q=0) every bin
    # satisfies the crossing test and the walk converges to the bracket's
    # low edge — which must therefore be the minimum DATA value (the exact
    # kernel's q=0 answer), not key 0 (a NaN bit pattern).  NaNs are
    # excluded from the seed (jnp.min/max would PROPAGATE one zero-weight
    # NaN into the bracket and poison the result; the exact kernel sorts
    # NaNs last where zero weight keeps them unselectable)
    finite = ~jnp.isnan(values)
    lo0 = _f32_keys(
        pmin_reduce(jnp.min(jnp.where(finite, values, jnp.inf)), axis_name)
    )
    hi0 = _f32_keys(
        pmax_reduce(jnp.max(jnp.where(finite, values, -jnp.inf)), axis_name)
    )
    # the zero accumulator must enter the loop typed like the body's
    # psum-ed cumulative — a replicated literal trips shard_map's carry
    # replication check (ops/collective.py pzero_like_shard)
    cum0 = pzero_like_shard(jnp.float32(0.0), axis_name)
    lo, hi, _ = jax.lax.fori_loop(0, _ROUNDS, body, (lo0, hi0, cum0))
    return lo


def _crossing_value_sharded(values, weights, q, axis_name) -> jax.Array:
    total = preduce(jnp.sum(weights.astype(jnp.float32)), axis_name)
    target = jnp.asarray(q, jnp.float32) * total
    if target.ndim == 0:
        key = _sharded_crossing_key(values, weights, target, axis_name)
    else:
        key = jax.vmap(
            lambda t: _sharded_crossing_key(values, weights, t, axis_name)
        )(target)
    return _key_to_f32(key)


def weighted_median(
    values: jax.Array, weights: jax.Array, axis_name: Optional[str] = None
) -> jax.Array:
    """First value (in sorted order) whose cumulative weight >= total/2.

    Matches `Utils.scala:26-40` exactly, including the >= comparison.
    Zero-weight entries cannot be selected unless they tie with the crossing
    point, mirroring the reference's behavior under its property tests.
    With ``axis_name`` (inside shard_map) every shard computes the identical
    global median via psum-ed histogram refinement — no shard ever holds the
    full column (see module docstring).
    """
    if axis_name is not None:
        return _crossing_value_sharded(values, weights, 0.5, axis_name)
    order = jnp.argsort(values)
    v = values[order]
    w = weights[order]
    cum = jnp.cumsum(w)
    total = cum[-1]
    # index of first cum >= total/2  (reference: `cumSum >= 0.5 * total`)
    idx = jnp.argmax(cum >= 0.5 * total)
    return v[idx]


def weighted_quantile(
    values: jax.Array,
    q,
    weights: Optional[jax.Array] = None,
    axis_name: Optional[str] = None,
) -> jax.Array:
    """Exact weighted quantile(s) by sort + normalized cumulative weight.

    ``q`` may be a scalar or a vector of probabilities in [0, 1].  With
    ``axis_name`` set (inside shard_map/pjit), every device computes the
    identical global quantile via psum-ed histogram refinement over the f32
    bit space — the SPMD replacement for the reference's distributed
    ``approxQuantile``, with the same no-device-holds-the-column scaling
    (and an exact result where the reference sketches).
    """
    if weights is None:
        weights = jnp.ones_like(values)
    if axis_name is not None:
        return _crossing_value_sharded(values, weights, q, axis_name)
    order = jnp.argsort(values)
    v = values[order]
    w = weights[order]
    cum = jnp.cumsum(w)
    total = cum[-1]
    target = jnp.asarray(q) * total
    # first index with cum >= target
    idx = jnp.searchsorted(cum, target, side="left")
    idx = jnp.clip(idx, 0, v.shape[0] - 1)
    return v[idx]
