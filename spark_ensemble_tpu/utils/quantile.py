"""Weighted median / quantile kernels.

Reference semantics:
- ``Utils.weightedMedian`` (`Utils.scala:26-40`): sort by value, take the first
  element whose cumulative weight reaches half the total weight.
- ``approxQuantile`` (Greenwald-Khanna sketch, used for Huber's adaptive delta
  at `GBMRegressor.scala:342-353` and DummyRegressor quantile strategy at
  `DummyRegressor.scala:119-125`).

On TPU we compute quantiles *exactly* with a sort + cumulative-sum +
searchsorted kernel — sorts are cheap in XLA at these scales, and exactness
strictly dominates the reference's sketch approximation.  All kernels are
jit/vmap-compatible (static shapes) and accept an optional mesh axis name for
data-sharded inputs (values are all-gathered; quantiles are O(n log n) on the
gathered vector which is fine for per-round scalar statistics).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def weighted_median(
    values: jax.Array, weights: jax.Array, axis_name: Optional[str] = None
) -> jax.Array:
    """First value (in sorted order) whose cumulative weight >= total/2.

    Matches `Utils.scala:26-40` exactly, including the >= comparison.
    Zero-weight entries cannot be selected unless they tie with the crossing
    point, mirroring the reference's behavior under its property tests.
    With ``axis_name`` (inside shard_map) shards are all-gathered first so
    every shard computes the identical global median.
    """
    if axis_name is not None:
        values = jax.lax.all_gather(values, axis_name, tiled=True)
        weights = jax.lax.all_gather(weights, axis_name, tiled=True)
    order = jnp.argsort(values)
    v = values[order]
    w = weights[order]
    cum = jnp.cumsum(w)
    total = cum[-1]
    # index of first cum >= total/2  (reference: `cumSum >= 0.5 * total`)
    idx = jnp.argmax(cum >= 0.5 * total)
    return v[idx]


def weighted_quantile(
    values: jax.Array,
    q,
    weights: Optional[jax.Array] = None,
    axis_name: Optional[str] = None,
) -> jax.Array:
    """Exact weighted quantile(s) by sort + normalized cumulative weight.

    ``q`` may be a scalar or a vector of probabilities in [0, 1].  With
    ``axis_name`` set (inside shard_map/pjit), shards are all-gathered first
    so every device computes the identical global quantile — the SPMD
    replacement for the reference's distributed ``approxQuantile``.
    """
    if weights is None:
        weights = jnp.ones_like(values)
    if axis_name is not None:
        values = jax.lax.all_gather(values, axis_name, tiled=True)
        weights = jax.lax.all_gather(weights, axis_name, tiled=True)
    order = jnp.argsort(values)
    v = values[order]
    w = weights[order]
    cum = jnp.cumsum(w)
    total = cum[-1]
    target = jnp.asarray(q) * total
    # first index with cum >= target
    idx = jnp.searchsorted(cum, target, side="left")
    idx = jnp.clip(idx, 0, v.shape[0] - 1)
    return v[idx]
