"""ctypes binding for the native libsvm parser (csrc/libsvm_parser.cpp).

Compiled on demand with the system toolchain into the package build dir;
callers fall back to the pure-numpy parser on any failure (missing compiler,
read-only filesystem, ...).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional, Tuple

import numpy as np

_LIB = None


def _source_path() -> str:
    # the source ships INSIDE the package (pyproject package-data) so
    # wheel installs keep the native fast path, not just repo checkouts
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(pkg, "csrc", "libsvm_parser.cpp")


def _build_lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is not None:
        return _LIB
    src = _source_path()
    if not os.path.exists(src):
        raise ImportError("csrc/libsvm_parser.cpp not found")
    # build artifact lives in the user's cache (never inside site-packages
    # — the package's own tree may be read-only and a stray top-level dir
    # there would outlive an uninstall, and never in a shared
    # world-writable location); fall back to a fresh private tempdir
    cache_dir = os.path.join(
        os.environ.get(
            "XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache")
        ),
        "spark_ensemble_tpu", "native",
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        cache_dir = tempfile.mkdtemp(prefix="se_tpu_native_")
    so_path = os.path.join(cache_dir, "libsvm_parser.so")
    if not os.path.exists(so_path) or os.path.getmtime(so_path) < os.path.getmtime(src):
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", so_path, src],
            check=True,
            capture_output=True,
        )
    lib = ctypes.CDLL(so_path)
    lib.libsvm_scan.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_long),
    ]
    lib.libsvm_scan.restype = ctypes.c_int
    lib.libsvm_fill.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_long,
        ctypes.c_long,
    ]
    lib.libsvm_fill.restype = ctypes.c_int
    _LIB = lib
    return lib


def parse_libsvm_native(
    path: str, n_features: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray]:
    lib = _build_lib()
    n_rows = ctypes.c_long()
    max_idx = ctypes.c_long()
    if lib.libsvm_scan(path.encode(), ctypes.byref(n_rows), ctypes.byref(max_idx)):
        raise IOError(f"native scan failed for {path}")
    n = n_rows.value
    d = n_features if n_features is not None else max_idx.value
    X = np.zeros((n, d), dtype=np.float32)
    y = np.zeros((n,), dtype=np.float32)
    rc = lib.libsvm_fill(
        path.encode(),
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        d,
    )
    if rc:
        raise IOError(f"native fill failed for {path} (rc={rc})")
    return X, y
