"""Feature-name metadata propagation (`Utils.getFeaturesMetadata`,
reference `ensemble/Utils.scala:42-61`).

The reference re-indexes DataFrame ``AttributeGroup`` column metadata after
subspace slicing so a base model trained on sliced vectors still reports
meaningful feature names.  The TPU build has no DataFrame metadata; instead a
lightweight ``FeatureMetadata`` record travels with estimators/models (the
``feature_names`` param) and re-indexes itself through subspace masks.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class FeatureMetadata:
    """Ordered feature names for a feature matrix's columns."""

    def __init__(self, names: Sequence[str]):
        self.names: List[str] = [str(n) for n in names]

    @classmethod
    def default(cls, num_features: int) -> "FeatureMetadata":
        """Anonymous names, like Spark's unnamed AttributeGroup."""
        return cls([f"f{i}" for i in range(num_features)])

    @classmethod
    def resolve(
        cls, names: Optional[Sequence[str]], num_features: int
    ) -> "FeatureMetadata":
        if names is None:
            return cls.default(num_features)
        if len(names) != num_features:
            raise ValueError(
                f"feature_names has {len(names)} entries for "
                f"{num_features} features"
            )
        return cls(names)

    def __len__(self) -> int:
        return len(self.names)

    def __eq__(self, other) -> bool:
        return isinstance(other, FeatureMetadata) and self.names == other.names

    def select(self, mask_or_indices) -> "FeatureMetadata":
        """Names of a feature subspace — the re-indexing the reference does
        after ``slice()`` (`Utils.scala:42-61`).  Accepts a boolean mask
        (subspace mask) or an index array."""
        arr = np.asarray(mask_or_indices)
        if arr.dtype == bool:
            if arr.shape[0] != len(self.names):
                raise ValueError(
                    f"mask length {arr.shape[0]} != {len(self.names)} features"
                )
            idx = np.nonzero(arr)[0]
        else:
            idx = arr.astype(np.int64)
        return FeatureMetadata([self.names[int(i)] for i in idx])

    def __repr__(self):
        return f"FeatureMetadata({self.names!r})"
