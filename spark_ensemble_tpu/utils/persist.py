"""Model/estimator persistence: JSON metadata + npz array payloads.

Mirrors the reference's persistence *semantics* (§2.6 of SURVEY.md): params
are saved as JSON metadata with estimator-valued params excluded and written
as nested directories (`learner/`, `learner-$i/`, `stacker/`,
`model-$i/` — reference `ensembleParams.scala:85-194`,
`BaggingRegressor.scala:178-291`), learned arrays as a single ``.npz``
payload per directory, and loading reconstructs by class-registry lookup the
way Spark's ``DefaultParamsReader`` resolves ``className``.  Round-trip
equality of predictions is test-enforced, as in the reference suites
(e.g. `GBMClassifierSuite.scala:247-295`).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

FORMAT_VERSION = 1


def _class_registry():
    from spark_ensemble_tpu import evaluation, pipeline, tuning
    from spark_ensemble_tpu.models import (
        bagging,
        boosting,
        dummy,
        gbm,
        linear,
        linear_tree,
        mlp,
        naive_bayes,
        stacking,
        tree,
    )
    from spark_ensemble_tpu.ops.tree import Tree

    modules = [
        bagging,
        boosting,
        dummy,
        gbm,
        linear,
        linear_tree,
        mlp,
        naive_bayes,
        stacking,
        tree,
        evaluation,
        pipeline,
        tuning,
    ]
    registry: Dict[str, type] = {}
    for mod in modules:
        for name in dir(mod):
            obj = getattr(mod, name)
            if isinstance(obj, type):
                registry[name] = obj
    registry["Tree"] = Tree
    return registry


# ---------------------------------------------------------------------------
# pytree <-> (json-structure, arrays) encoding
# ---------------------------------------------------------------------------

def _encode(obj: Any, arrays: Dict[str, np.ndarray], prefix: str):
    if obj is None:
        return None
    if isinstance(obj, (bool, int, float, str)):
        return obj
    if hasattr(obj, "_fields"):  # NamedTuple (e.g. ops.tree.Tree)
        return {
            "__namedtuple__": type(obj).__name__,
            "fields": {
                f: _encode(getattr(obj, f), arrays, f"{prefix}.{f}")
                for f in obj._fields
            },
        }
    if isinstance(obj, dict):
        return {
            "__dict__": {
                k: _encode(v, arrays, f"{prefix}.{k}") for k, v in obj.items()
            }
        }
    if isinstance(obj, (list, tuple)):
        return {
            "__list__": [
                _encode(v, arrays, f"{prefix}.{i}") for i, v in enumerate(obj)
            ],
            "__tuple__": isinstance(obj, tuple),
        }
    arr = np.asarray(obj)
    arrays[prefix] = arr
    return {"__array__": prefix}


def _decode(spec: Any, arrays, registry):
    if spec is None or isinstance(spec, (bool, int, float, str)):
        return spec
    if "__array__" in spec:
        return jnp.asarray(arrays[spec["__array__"]])
    if "__namedtuple__" in spec:
        cls = registry[spec["__namedtuple__"]]
        fields = {
            k: _decode(v, arrays, registry) for k, v in spec["fields"].items()
        }
        missing = [f for f in getattr(cls, "_fields", ()) if f not in fields]
        if missing:
            # format evolution: classes declare defaults for fields added
            # after artifacts were saved (e.g. Tree._persist_defaults);
            # the decoder itself stays schema-agnostic
            defaults_hook = getattr(cls, "_persist_defaults", None)
            if defaults_hook is not None:
                fields = defaults_hook(fields)
            still = [f for f in cls._fields if f not in fields]
            if still:
                raise ValueError(
                    f"saved {spec['__namedtuple__']} is missing fields "
                    f"{still!r} and declares no defaults for them"
                )
        return cls(**fields)
    if "__dict__" in spec:
        return {k: _decode(v, arrays, registry) for k, v in spec["__dict__"].items()}
    if "__list__" in spec:
        items = [_decode(v, arrays, registry) for v in spec["__list__"]]
        return tuple(items) if spec.get("__tuple__") else items
    raise ValueError(f"cannot decode {spec!r}")


# ---------------------------------------------------------------------------
# estimator configs (nested directories, like learner/ in the reference)
# ---------------------------------------------------------------------------

def _save_estimator_params(obj, path: str) -> Dict[str, Any]:
    """Returns JSON param dict; writes nested estimator dirs under path."""
    meta_params = obj.params_to_json_dict()
    for name, p in obj._param_defs().items():
        if not p.is_estimator:
            continue
        value = getattr(obj, name)
        if value is None:
            continue
        if isinstance(value, (list, tuple)):
            for i, v in enumerate(value):
                save(v, os.path.join(path, f"{name}-{i}"))
            meta_params[f"__{name}_count__"] = len(value)
        else:
            save(value, os.path.join(path, name))
    return meta_params


def _load_estimator_params(meta: Dict[str, Any], path: str, cls) -> Dict[str, Any]:
    params = dict(meta["params"])
    for name, p in cls._param_defs().items():
        if not p.is_estimator:
            continue
        count_key = f"__{name}_count__"
        if count_key in params:
            count = params.pop(count_key)
            params[name] = [
                load(os.path.join(path, f"{name}-{i}")) for i in range(count)
            ]
        elif os.path.isdir(os.path.join(path, name)):
            params[name] = load(os.path.join(path, name))
    return params


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

_CHILD_ATTRS = ("init_model", "stack_model", "best_model")
_LIST_CHILD_ATTRS = ("base_models", "stage_models")
_EXTRA_ATTRS = (
    "num_features",
    "num_classes",
    "num_members",
    "dim",
    "avg_metrics",
    "fold_metrics",
    "validation_metrics",
    "best_index",
)


def save(obj, path: str) -> None:
    """Save an Estimator or Model directory."""
    os.makedirs(path, exist_ok=True)
    meta: Dict[str, Any] = {
        "class": type(obj).__name__,
        "format_version": FORMAT_VERSION,
    }
    meta["params"] = _save_estimator_params(obj, path)

    from spark_ensemble_tpu.models.base import Model

    is_model = isinstance(obj, Model)
    if is_model:
        arrays: Dict[str, np.ndarray] = {}
        meta["learned"] = _encode(obj.params, arrays, "p")
        extra = {}
        for attr in _EXTRA_ATTRS:
            if hasattr(obj, attr):
                extra[attr] = getattr(obj, attr)
        meta["extra"] = extra
        for attr in _CHILD_ATTRS:
            child = getattr(obj, attr, None)
            if child is not None:
                save(child, os.path.join(path, f"model-{attr}"))
                meta.setdefault("children", []).append(attr)
        for attr in _LIST_CHILD_ATTRS:
            children = getattr(obj, attr, None)
            if children:
                for i, child in enumerate(children):
                    save(child, os.path.join(path, f"model-{attr}-{i}"))
                meta.setdefault("list_children", {})[attr] = len(children)
        if arrays:
            np.savez(os.path.join(path, "arrays.npz"), **arrays)
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=2, default=float)


def load(path: str):
    """Load an Estimator or Model saved by :func:`save`."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    registry = _class_registry()
    cls = registry[meta["class"]]
    kwargs = _load_estimator_params(meta, path, cls)

    if "learned" in meta:
        arrays = {}
        npz = os.path.join(path, "arrays.npz")
        if os.path.exists(npz):
            arrays = dict(np.load(npz))
        learned = _decode(meta["learned"], arrays, registry)
        kwargs["params"] = learned
        kwargs.update(meta.get("extra", {}))
        for attr in meta.get("children", []):
            kwargs[attr] = load(os.path.join(path, f"model-{attr}"))
        for attr, count in meta.get("list_children", {}).items():
            kwargs[attr] = [
                load(os.path.join(path, f"model-{attr}-{i}")) for i in range(count)
            ]
    return cls(**kwargs)
