"""Trace analysis for ``profile_dir`` captures: where does the round go?

Every estimator fit can capture a ``jax.profiler`` trace (the ``profile_dir``
param, `utils/instrumentation.py`).  This module turns that capture into the
per-op cost table that drives kernel work — the workflow that found the
round-2 wins (per-row gathers at ~3.8 ms each dominating the GBM round;
`ops/tree.py` module docstring):

    est = GBMClassifier(num_base_learners=20, profile_dir="/tmp/prof")
    est.fit(X, y)
    python -m spark_ensemble_tpu.utils.profiling /tmp/prof

The summary groups trace slices by op name and reports total/mean duration
and call counts, descending — `kCustom fusion ... gather` rows near the top
mean serialized per-row gathers; big `dot` rows are the (expected) MXU time.
To map fusion names back to source, lower the jitted fn and read
``compiled.as_text()`` metadata (``op_name``/``source_line``).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Dict, List, Optional, Tuple


def find_trace_files(trace_dir: str, latest_only: bool = True) -> List[str]:
    """``*.trace.json.gz`` files under a profile capture directory.

    jax writes each capture under a fresh ``plugins/profile/<timestamp>/``
    subdirectory, and profile_dir is typically a REUSED fixed path — so by
    default only the latest capture is returned; summing across captures
    would silently merge pre- and post-change runs into one misleading
    table.  ``latest_only=False`` merges all captures."""
    files = sorted(
        glob.glob(
            os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
        )
    )
    if not latest_only or not files:
        return files
    by_capture: Dict[str, List[str]] = {}
    for f in files:
        by_capture.setdefault(os.path.dirname(f), []).append(f)
    # timestamp directory names sort lexicographically
    return by_capture[max(by_capture)]


def load_trace_events(path: str) -> List[dict]:
    """Complete ("X"-phase) slice events of one chrome-trace file."""
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    return [
        e
        for e in trace.get("traceEvents", [])
        if e.get("ph") == "X" and "dur" in e
    ]


def summarize_events(
    events: List[dict], device_only: bool = True
) -> List[Tuple[str, float, int]]:
    """Aggregate slice durations by event name -> [(name, total_us, count)]
    sorted by total descending.  ``device_only`` keeps XLA-op-looking names
    (fusions, dots, convolutions, collectives) and drops host/python rows,
    which otherwise double-count the device time they merely wait on."""
    totals: Dict[str, List[float]] = {}
    for e in events:
        name = e.get("name", "?")
        if device_only and (
            name.startswith(
                (
                    "$",
                    "Thread",
                    "process_",
                    # host-side dispatch/runtime lanes, not device ops —
                    # they overlap (and double-count) the device time they
                    # wait on
                    "PjitFunction(",
                    "PjRt",
                    "ThunkExecutor",
                    "DevicePut",
                )
            )
            or "python" in name.lower()
        ):
            continue
        slot = totals.setdefault(name, [0.0, 0])
        slot[0] += float(e["dur"])
        slot[1] += 1
    return sorted(
        ((n, v[0], int(v[1])) for n, v in totals.items()),
        key=lambda t: -t[1],
    )


def summarize_trace(
    trace_dir: str,
    top: int = 25,
    device_only: bool = True,
    latest_only: bool = True,
) -> Tuple[List[Tuple[str, float, int]], float]:
    """``(top rows, grand_total_us)`` for the (latest) capture — the total
    covers EVERY aggregated op, not just the displayed rows, so percentage
    shares stay honest after truncation."""
    events: List[dict] = []
    for path in find_trace_files(trace_dir, latest_only=latest_only):
        events.extend(load_trace_events(path))
    rows = summarize_events(events, device_only=device_only)
    total = sum(r[1] for r in rows)
    return rows[:top], total


def format_summary(
    rows: List[Tuple[str, float, int]], total_us: Optional[float] = None
) -> str:
    total = total_us if total_us else (sum(r[1] for r in rows) or 1.0)
    lines = [f"{'total_ms':>10}  {'%':>5}  {'count':>6}  op"]
    for name, us, count in rows:
        lines.append(
            f"{us / 1000.0:>10.3f}  {100.0 * us / total:>5.1f}  "
            f"{count:>6d}  {name[:100]}"
        )
    return "\n".join(lines)


def rows_to_records(
    rows: List[Tuple[str, float, int]], total_us: Optional[float] = None
) -> List[dict]:
    """The machine-readable form of the cost table: one record per op with
    ``{"op", "total_us", "count", "share"}`` — the SAME schema
    ``tools/telemetry_report.py`` emits for telemetry phases, so trace
    summaries and telemetry reports diff against each other directly."""
    total = total_us if total_us else (sum(r[1] for r in rows) or 1.0)
    return [
        {"op": name, "total_us": us, "count": count, "share": us / total}
        for name, us, count in rows
    ]


def write_jsonl(records: List[dict], path: str) -> None:
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument(
        "--all-events",
        action="store_true",
        help="include host/python rows, not just device-op-looking names",
    )
    ap.add_argument(
        "--merge-captures",
        action="store_true",
        help="sum across ALL captures under the dir (default: latest only)",
    )
    ap.add_argument(
        "--jsonl",
        metavar="PATH",
        help="also write the table as JSONL records "
        '{"op","total_us","count","share"} — the shared machine-readable '
        "format tools/telemetry_report.py reads and emits",
    )
    args = ap.parse_args(argv)
    rows, total = summarize_trace(
        args.trace_dir,
        top=args.top,
        device_only=not args.all_events,
        latest_only=not args.merge_captures,
    )
    if not rows:
        print(f"no trace events found under {args.trace_dir}")
        return 1
    if args.jsonl:
        write_jsonl(rows_to_records(rows, total), args.jsonl)
    print(format_summary(rows, total))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
