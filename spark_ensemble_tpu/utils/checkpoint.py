"""Training-state checkpoint/resume for the iterative estimators.

The reference's ``PeriodicRDDCheckpointer`` exists only to truncate RDD
lineage (`BoostingRegressor.scala:202-206`, `GBMRegressor.scala:314-318`);
training is NOT resumable there (SURVEY.md §5).  On TPU there is no lineage,
so ``checkpoint_interval`` buys something strictly better: a *real*
training-state checkpoint — round index, member params so far, estimator
weights, the prediction/boosting-weight arrays, patience counters — written
atomically every N rounds, from which ``fit`` resumes mid-run after
preemption.

Crash consistency: every save writes a ``manifest.json`` (sha256 + byte
size per file) inside the checkpoint directory before the atomic swap, and
the previous good checkpoint is **retained** as ``.ckpt-old`` (one extra
checkpoint of disk, reclaimed by ``delete()`` at fit end).  ``load_latest``
verifies the manifest and falls back ``latest`` → ``.ckpt-old`` → fresh
start instead of crashing on a truncated/corrupt ``state.json``; writes go
through the retry/backoff layer for transient filesystem errors.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import tempfile
from typing import Any, Dict, Optional, Tuple

import numpy as np

from spark_ensemble_tpu.telemetry.trace import NULL_SPAN

logger = logging.getLogger("spark_ensemble_tpu")


# Bumped whenever the persisted member-pytree schema changes in a way a
# resume cannot mix with (e.g. Tree.split_gain, round 3: resuming a
# pre-gain checkpoint would backfill zero gains for the already-trained
# members and silently skew feature_importances_ toward post-resume
# rounds).  A mismatch makes the fit start fresh — full-model SAVES still
# load across versions via per-class _persist_defaults hooks; only
# mid-training state is version-pinned.
_CHECKPOINT_FORMAT = 3  # 3: GBM state carries val_hist (round-aligned)


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def run_fingerprint(*parts) -> str:
    """Stable digest of estimator config + data shape, stored with each
    checkpoint so a stale checkpoint from a different run/config is never
    silently resumed."""
    import hashlib

    blob = json.dumps((_CHECKPOINT_FORMAT,) + parts, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class TrainingCheckpointer:
    """Atomic periodic checkpoints of an arbitrary training-state pytree
    (dicts/lists/scalars/arrays — same codec as model persistence).

    Saves are **asynchronous** by default: ``save`` starts the device→host
    transfers (``copy_to_host_async`` — a DMA the training loop does not
    wait on) and hands encoding + the atomic directory swap to a background
    writer thread, so the round loop keeps dispatching while the checkpoint
    lands — the TPU analogue of async-checkpoint runtimes (orbax); the
    reference blocks its driver on ``RDD.checkpoint`` materialization
    instead.  At most one save is in flight (a new save, ``load_latest``,
    and ``delete`` all join the previous one first, re-raising its
    failure), so 'latest' ordering and error reporting match the
    synchronous path exactly."""

    def __init__(
        self,
        directory: Optional[str],
        interval: int = 10,
        fingerprint: Optional[str] = None,
        async_save: bool = True,
        retry_policy=None,
        telem=None,
    ):
        self.directory = directory
        self.interval = max(int(interval), 1)
        self.fingerprint = fingerprint
        self.async_save = bool(async_save)
        self.retry_policy = retry_policy
        self.telem = telem
        # set by load_latest: {"round", "source", "fallback"} describing
        # which on-disk copy a resume actually came from
        self.last_load_detail: Optional[Dict[str, Any]] = None
        self._executor = None
        self._pending = None

    @property
    def enabled(self) -> bool:
        return bool(self.directory)

    def should_save(self, round_idx: int) -> bool:
        """The one copy of the save-cadence rule: a save fires after round
        ``round_idx`` iff checkpointing is on and ``round_idx + 1`` is a
        multiple of the interval.  Callers that build expensive state dicts
        gate on this BEFORE constructing them."""
        return self.enabled and (round_idx + 1) % self.interval == 0

    def rounds_until_save(self, i: int) -> int:
        """Rounds from (0-based) round ``i`` to the next save boundary
        inclusive — chunked round loops clamp their chunk length to this so
        chunk ends land exactly on save rounds regardless of the resume
        offset (a resume may start at a round misaligned with a *changed*
        interval)."""
        return self.interval - (i % self.interval)

    def maybe_save(self, round_idx: int, state: Dict[str, Any]) -> None:
        if self.should_save(round_idx):
            self.save(round_idx, state)

    def wait(self) -> None:
        """Join the in-flight async save, re-raising its failure (the same
        exception the synchronous path would have raised at save time)."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()  # graftlint: ignore[unfenced-blocking-read] -- async-save join at the save boundary, not the dispatch window; kept bare so the save thread's failure re-raises here

    def save(self, round_idx: int, state: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        if not self.async_save:
            self._save_sync(round_idx, state)
            return
        # one save in flight at a time: ordering of 'latest' is preserved
        self.wait()
        import jax

        for leaf in jax.tree_util.tree_leaves(state):
            if isinstance(leaf, jax.Array):
                # start the device->host DMA now; the writer thread's
                # np.asarray then completes without stalling this loop
                leaf.copy_to_host_async()
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-writer"
            )
        # explicit trace-context capture ON THE FIT THREAD: the writer
        # thread parents its checkpoint_save span to this fit's root span
        # through the two propagated ids (telemetry/trace.py)
        ctx = None if self.telem is None else self.telem.trace_context()
        self._pending = self._executor.submit(
            self._save_sync, round_idx, state, ctx
        )

    def _save_sync(self, round_idx: int, state: Dict[str, Any],
                   parent=None) -> None:
        from spark_ensemble_tpu.robustness.chaos import controller
        from spark_ensemble_tpu.robustness.retry import retry_call

        sp = NULL_SPAN if self.telem is None else self.telem.begin_span(
            "checkpoint_save", parent=parent,
            thread="ckpt-writer" if parent is not None else None,
            round=round_idx,
        )
        try:
            retry_call(
                lambda: self._write(round_idx, state),
                policy=self.retry_policy,
                op="checkpoint.save",
                telem=self.telem,
            )
            # chaos hook: simulate a crash mid-write AFTER the swap —
            # exactly the torn state load_latest's manifest check must
            # recover from
            controller().corrupt_checkpoint(
                f"ckpt:{self.directory}:{round_idx}",
                os.path.join(self.directory, "latest", "state.json"),
            )
        finally:
            sp.end()

    def _write(self, round_idx: int, state: Dict[str, Any]) -> None:
        from spark_ensemble_tpu.utils.persist import _encode

        os.makedirs(self.directory, exist_ok=True)
        arrays: Dict[str, np.ndarray] = {}
        spec = _encode(state, arrays, "s")
        tmp = tempfile.mkdtemp(dir=self.directory, prefix=".ckpt-tmp-")
        try:
            with open(os.path.join(tmp, "state.json"), "w") as f:
                json.dump(
                    {
                        "round": round_idx,
                        "spec": spec,
                        "fingerprint": self.fingerprint,
                    },
                    f,
                    default=float,
                )
            if arrays:
                np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
            manifest = {"round": round_idx, "files": {}}
            for name in ("state.json", "arrays.npz"):
                p = os.path.join(tmp, name)
                if os.path.exists(p):
                    manifest["files"][name] = {
                        "sha256": _file_sha256(p),
                        "bytes": os.path.getsize(p),
                    }
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f)
            final = os.path.join(self.directory, "latest")
            stale = os.path.join(self.directory, ".ckpt-old")
            if os.path.exists(final):
                # retain the displaced 'latest' as the crash-consistent
                # fallback; only the older generation is reclaimed
                if os.path.exists(stale):
                    shutil.rmtree(stale)
                os.rename(final, stale)
            os.rename(tmp, final)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def load_latest(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        """Newest loadable checkpoint, or ``None``: tries ``latest`` then
        falls back to the retained ``.ckpt-old`` when 'latest' is
        truncated/corrupt (manifest checksum mismatch, undecodable
        state.json) — a crash between the two rename()s of a save, or a
        torn write on a non-atomic filesystem, must cost one checkpoint
        interval, not the whole run."""
        if not self.enabled:
            return None
        self.wait()
        self.last_load_detail = None
        for source in ("latest", ".ckpt-old"):
            loaded = self._load_dir(os.path.join(self.directory, source))
            if loaded is None:
                continue
            fallback = source != "latest"
            if fallback:
                logger.warning(
                    "checkpoint 'latest' in %s is unusable; resuming from "
                    "the retained .ckpt-old copy (round %d)",
                    self.directory, loaded[0],
                )
            self.last_load_detail = {
                "round": loaded[0], "source": source, "fallback": fallback,
            }
            return loaded
        return None

    def _load_dir(self, path: str) -> Optional[Tuple[int, Dict[str, Any]]]:
        """Decode one checkpoint directory; ``None`` on any corruption
        (logged) or fingerprint mismatch instead of raising."""
        state_path = os.path.join(path, "state.json")
        if not os.path.exists(state_path):
            return None
        from spark_ensemble_tpu.utils.persist import _class_registry, _decode

        try:
            manifest_path = os.path.join(path, "manifest.json")
            if os.path.exists(manifest_path):
                with open(manifest_path) as f:
                    manifest = json.load(f)
                for name, meta in manifest.get("files", {}).items():
                    p = os.path.join(path, name)
                    if (
                        not os.path.exists(p)
                        or os.path.getsize(p) != meta["bytes"]
                        or _file_sha256(p) != meta["sha256"]
                    ):
                        logger.warning(
                            "checkpoint %s failed its manifest check "
                            "(%s corrupt/truncated); ignoring it",
                            path, name,
                        )
                        return None
            with open(state_path) as f:
                meta = json.load(f)
            if meta.get("fingerprint") != self.fingerprint:
                logger.warning(
                    "checkpoint in %s was written by a different run/config "
                    "(fingerprint %s != %s); ignoring it",
                    path, meta.get("fingerprint"), self.fingerprint,
                )
                return None
            arrays = {}
            npz = os.path.join(path, "arrays.npz")
            if os.path.exists(npz):
                arrays = dict(np.load(npz))
            state = _decode(meta["spec"], arrays, _class_registry())
            return int(meta["round"]), state
        except Exception:  # noqa: BLE001 - any corruption -> fall back
            logger.warning(
                "checkpoint in %s is corrupt/unreadable; ignoring it",
                path, exc_info=True,
            )
            return None

    def delete(self) -> None:
        """Training finished: remove the checkpoint entries THIS class wrote
        (the reference deletes its RDD checkpoints after training,
        `BoostingRegressor.scala:275-276`).  Only 'latest' and '.ckpt-*'
        entries are removed — the user-supplied directory itself and any
        unrelated contents are left untouched."""
        try:
            if self.enabled:
                self.wait()
        except Exception:  # noqa: BLE001
            # the checkpoint being discarded failed to write; training
            # itself completed, so log and proceed with teardown (failures
            # DURING training surface from the round loop's own wait())
            import logging

            logging.getLogger(__name__).warning(
                "discarding a failed background checkpoint write",
                exc_info=True,
            )
        finally:
            if self._executor is not None:
                self._executor.shutdown(wait=True)
                self._executor = None
        if not (self.enabled and os.path.isdir(self.directory)):
            return
        for entry in os.listdir(self.directory):
            if entry == "latest" or entry.startswith(".ckpt-"):
                shutil.rmtree(
                    os.path.join(self.directory, entry), ignore_errors=True
                )
        try:
            os.rmdir(self.directory)  # succeeds only if now empty
        except OSError:
            pass
