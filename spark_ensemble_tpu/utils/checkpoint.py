"""Training-state checkpoint/resume for the iterative estimators.

The reference's ``PeriodicRDDCheckpointer`` exists only to truncate RDD
lineage (`BoostingRegressor.scala:202-206`, `GBMRegressor.scala:314-318`);
training is NOT resumable there (SURVEY.md §5).  On TPU there is no lineage,
so ``checkpoint_interval`` buys something strictly better: a *real*
training-state checkpoint — round index, member params so far, estimator
weights, the prediction/boosting-weight arrays, patience counters — written
atomically every N rounds, from which ``fit`` resumes mid-run after
preemption.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, Optional, Tuple

import numpy as np


# Bumped whenever the persisted member-pytree schema changes in a way a
# resume cannot mix with (e.g. Tree.split_gain, round 3: resuming a
# pre-gain checkpoint would backfill zero gains for the already-trained
# members and silently skew feature_importances_ toward post-resume
# rounds).  A mismatch makes the fit start fresh — full-model SAVES still
# load across versions via per-class _persist_defaults hooks; only
# mid-training state is version-pinned.
_CHECKPOINT_FORMAT = 3  # 3: GBM state carries val_hist (round-aligned)


def run_fingerprint(*parts) -> str:
    """Stable digest of estimator config + data shape, stored with each
    checkpoint so a stale checkpoint from a different run/config is never
    silently resumed."""
    import hashlib

    blob = json.dumps((_CHECKPOINT_FORMAT,) + parts, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class TrainingCheckpointer:
    """Atomic periodic checkpoints of an arbitrary training-state pytree
    (dicts/lists/scalars/arrays — same codec as model persistence).

    Saves are **asynchronous** by default: ``save`` starts the device→host
    transfers (``copy_to_host_async`` — a DMA the training loop does not
    wait on) and hands encoding + the atomic directory swap to a background
    writer thread, so the round loop keeps dispatching while the checkpoint
    lands — the TPU analogue of async-checkpoint runtimes (orbax); the
    reference blocks its driver on ``RDD.checkpoint`` materialization
    instead.  At most one save is in flight (a new save, ``load_latest``,
    and ``delete`` all join the previous one first, re-raising its
    failure), so 'latest' ordering and error reporting match the
    synchronous path exactly."""

    def __init__(
        self,
        directory: Optional[str],
        interval: int = 10,
        fingerprint: Optional[str] = None,
        async_save: bool = True,
    ):
        self.directory = directory
        self.interval = max(int(interval), 1)
        self.fingerprint = fingerprint
        self.async_save = bool(async_save)
        self._executor = None
        self._pending = None

    @property
    def enabled(self) -> bool:
        return bool(self.directory)

    def should_save(self, round_idx: int) -> bool:
        """The one copy of the save-cadence rule: a save fires after round
        ``round_idx`` iff checkpointing is on and ``round_idx + 1`` is a
        multiple of the interval.  Callers that build expensive state dicts
        gate on this BEFORE constructing them."""
        return self.enabled and (round_idx + 1) % self.interval == 0

    def rounds_until_save(self, i: int) -> int:
        """Rounds from (0-based) round ``i`` to the next save boundary
        inclusive — chunked round loops clamp their chunk length to this so
        chunk ends land exactly on save rounds regardless of the resume
        offset (a resume may start at a round misaligned with a *changed*
        interval)."""
        return self.interval - (i % self.interval)

    def maybe_save(self, round_idx: int, state: Dict[str, Any]) -> None:
        if self.should_save(round_idx):
            self.save(round_idx, state)

    def wait(self) -> None:
        """Join the in-flight async save, re-raising its failure (the same
        exception the synchronous path would have raised at save time)."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def save(self, round_idx: int, state: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        if not self.async_save:
            self._save_sync(round_idx, state)
            return
        # one save in flight at a time: ordering of 'latest' is preserved
        self.wait()
        import jax

        for leaf in jax.tree_util.tree_leaves(state):
            if isinstance(leaf, jax.Array):
                # start the device->host DMA now; the writer thread's
                # np.asarray then completes without stalling this loop
                leaf.copy_to_host_async()
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-writer"
            )
        self._pending = self._executor.submit(
            self._save_sync, round_idx, state
        )

    def _save_sync(self, round_idx: int, state: Dict[str, Any]) -> None:
        from spark_ensemble_tpu.utils.persist import _encode

        os.makedirs(self.directory, exist_ok=True)
        arrays: Dict[str, np.ndarray] = {}
        spec = _encode(state, arrays, "s")
        tmp = tempfile.mkdtemp(dir=self.directory, prefix=".ckpt-tmp-")
        try:
            with open(os.path.join(tmp, "state.json"), "w") as f:
                json.dump(
                    {
                        "round": round_idx,
                        "spec": spec,
                        "fingerprint": self.fingerprint,
                    },
                    f,
                    default=float,
                )
            if arrays:
                np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
            final = os.path.join(self.directory, "latest")
            stale = os.path.join(self.directory, ".ckpt-old")
            if os.path.exists(final):
                os.rename(final, stale)
            os.rename(tmp, final)
            if os.path.exists(stale):
                shutil.rmtree(stale)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def load_latest(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        if not self.enabled:
            return None
        self.wait()
        final = os.path.join(self.directory, "latest")
        if not os.path.exists(os.path.join(final, "state.json")):
            return None
        from spark_ensemble_tpu.utils.persist import _class_registry, _decode

        with open(os.path.join(final, "state.json")) as f:
            meta = json.load(f)
        if meta.get("fingerprint") != self.fingerprint:
            import logging

            logging.getLogger(__name__).warning(
                "checkpoint in %s was written by a different run/config "
                "(fingerprint %s != %s); ignoring it",
                self.directory,
                meta.get("fingerprint"),
                self.fingerprint,
            )
            return None
        arrays = {}
        npz = os.path.join(final, "arrays.npz")
        if os.path.exists(npz):
            arrays = dict(np.load(npz))
        state = _decode(meta["spec"], arrays, _class_registry())
        return int(meta["round"]), state

    def delete(self) -> None:
        """Training finished: remove the checkpoint entries THIS class wrote
        (the reference deletes its RDD checkpoints after training,
        `BoostingRegressor.scala:275-276`).  Only 'latest' and '.ckpt-*'
        entries are removed — the user-supplied directory itself and any
        unrelated contents are left untouched."""
        try:
            if self.enabled:
                self.wait()
        except Exception:  # noqa: BLE001
            # the checkpoint being discarded failed to write; training
            # itself completed, so log and proceed with teardown (failures
            # DURING training surface from the round loop's own wait())
            import logging

            logging.getLogger(__name__).warning(
                "discarding a failed background checkpoint write",
                exc_info=True,
            )
        finally:
            if self._executor is not None:
                self._executor.shutdown(wait=True)
                self._executor = None
        if not (self.enabled and os.path.isdir(self.directory)):
            return
        for entry in os.listdir(self.directory):
            if entry == "latest" or entry.startswith(".ckpt-"):
                shutil.rmtree(
                    os.path.join(self.directory, entry), ignore_errors=True
                )
        try:
            os.rmdir(self.directory)  # succeeds only if now empty
        except OSError:
            pass
