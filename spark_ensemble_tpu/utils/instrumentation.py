"""Training instrumentation: structured logging + profiler hooks.

The reference wraps every ``train`` in Spark ML ``Instrumentation``
(``instrumented { instr => ... }``) logging pipeline stage, dataset, params,
numClasses and per-iteration values (`BaggingRegressor.scala:117-131`,
`BoostingClassifier.scala:182`, SURVEY.md §5).  This module provides the
equivalent: an ``instrumented`` context manager that logs estimator params
on entry and outcome on exit, per-round ``log_named_value``, and an optional
``jax.profiler`` trace context for TPU timeline capture (the reference has
no profiler integration; tests used ``spark.time`` wall-clock prints).

This layer is human-readable logging; the machine-readable counterpart is
``spark_ensemble_tpu.telemetry`` (structured per-round event stream, JSONL
sink, ``fit_history_`` — docs/telemetry.md), which reuses ``block_on_arrays``
below as its async-dispatch fence.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import time
from typing import Any, Dict, Iterator, Optional

logger = logging.getLogger("spark_ensemble_tpu")


class Instrumentation:
    def __init__(self, stage: str):
        self.stage = stage
        self.t0 = time.perf_counter()

    def log_params(self, params: Dict[str, Any]) -> None:
        clean = {
            k: v for k, v in params.items() if isinstance(v, (bool, int, float, str))
        }
        logger.info("[%s] params: %s", self.stage, clean)

    def log_dataset(self, n: int, d: int, num_classes: Optional[int] = None) -> None:
        extra = f", numClasses={num_classes}" if num_classes is not None else ""
        logger.info("[%s] dataset: n=%d, d=%d%s", self.stage, n, d, extra)

    def log_named_value(self, name: str, value) -> None:
        logger.info("[%s] %s=%s", self.stage, name, value)

    def log_outcome(self, **kv) -> None:
        elapsed = time.perf_counter() - self.t0
        logger.info("[%s] done in %.3fs: %s", self.stage, elapsed, kv)


@contextlib.contextmanager
def instrumented(stage: str) -> Iterator[Instrumentation]:
    """``with instrumented("GBMRegressor.fit") as instr:`` — the analogue of
    the reference's ``instrumented { instr => ... }`` wrapper."""
    instr = Instrumentation(stage)
    try:
        yield instr
    except Exception:
        logger.exception("[%s] failed", stage)
        raise


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace (TensorBoard-viewable) around a
    training run when ``log_dir`` is set; no-op otherwise."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


def instrumented_fit(fit):
    """Decorator for estimator ``fit`` methods: runs the body inside the
    ``instrumented`` logging scope (exception logging, like the reference's
    ``instrumented { ... }`` wrapper) and — when the estimator's
    ``profile_dir`` param is set — a ``jax.profiler`` trace capture of the
    whole fit (TensorBoard-viewable timeline; SURVEY.md §5 tracing row)."""

    @functools.wraps(fit)
    def wrapper(self, *args, **kwargs):
        # lazy import: events imports block_on_arrays from this module
        from spark_ensemble_tpu.telemetry import events as _events

        profile_dir = getattr(self, "profile_dir", None)
        depth0 = _events.active_fit_depth()
        with instrumented(f"{type(self).__name__}.fit"), profile_trace(
            profile_dir
        ):
            try:
                result = fit(self, *args, **kwargs)
            except BaseException as e:
                # terminal fit_aborted record for every telemetry this fit
                # (and any nested fit on this thread) opened but never
                # closed — JSONL streams always end with a terminal event
                _events.abort_active_fits(depth0, e)
                raise
            if profile_dir:
                # jax dispatch is async: without blocking here the trace
                # would stop at dispatch time and capture none of the
                # device execution (fit() keeps its async semantics when
                # not profiling)
                block_on_arrays(result)
            return result

    return wrapper


def block_on_arrays(obj) -> None:
    """Block on every jax array reachable from ``obj`` (fitted models keep
    arrays under .params but composites nest child models in attributes)."""
    import jax

    seen = set()

    def walk(o):
        if id(o) in seen:
            return
        seen.add(id(o))
        if isinstance(o, jax.Array):
            o.block_until_ready()
        elif isinstance(o, (list, tuple)):
            for x in o:
                walk(x)
        elif isinstance(o, dict):
            for x in o.values():
                walk(x)
        elif hasattr(o, "predict") and hasattr(o, "__dict__"):
            for x in vars(o).values():
                walk(x)

    walk(obj)
