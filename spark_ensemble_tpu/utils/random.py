"""PRNG-key discipline and static-shape sampling kernels.

The reference samples rows with Spark's ``RDD.sample`` — a *Poisson* sampler
when ``withReplacement=true`` and a Bernoulli sampler otherwise
(`BaggingRegressor.scala:149-150`, `GBMRegressor.scala:357-359`) — and draws
Bernoulli feature-subspace masks with ``XORShiftRandom(seed)``
(`HasSubBag.scala:73-79`).  Per-member seeds are ``seed + i``
(`BaggingRegressor.scala:141-143`).

The TPU build keeps shapes static by never materializing subsets: row
sampling becomes an integer/float *weight vector* (Poisson counts or a 0/1
Bernoulli mask) multiplied into per-sample weights, which is exactly the
sufficient statistic the downstream weighted fits consume.  Feature subspaces
become boolean masks that zero out split gains instead of slicing columns.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def member_keys(seed: int, num_members: int) -> jax.Array:
    """Independent keys per ensemble member (reference: ``seed + i``)."""
    return jax.random.split(jax.random.PRNGKey(seed), num_members)


def bootstrap_weights(
    key: jax.Array,
    n: int,
    replacement: bool,
    subsample_ratio: float,
) -> jax.Array:
    """Row-sampling weights with Spark ``RDD.sample`` semantics.

    replacement=True  -> Poisson(subsample_ratio) counts per row
    replacement=False -> Bernoulli(subsample_ratio) 0/1 mask
    Both keep the expected sampled-row count at ``n * subsample_ratio`` with
    a static output shape ``f32[n]``.
    """
    if replacement:
        return jax.random.poisson(key, subsample_ratio, (n,)).astype(jnp.float32)
    return jax.random.bernoulli(key, subsample_ratio, (n,)).astype(jnp.float32)


def subspace_mask(key: jax.Array, num_features: int, subspace_ratio: float) -> jax.Array:
    """Bernoulli feature mask (reference `HasSubBag.scala:73-79`).

    Guarantees at least one active feature (a fully-masked member would make
    the base learner degenerate; the reference's estimators would fit on an
    empty projection — we instead fall back to enabling the first drawn
    feature, preserving expected mask size for any ratio > 0).
    """
    mask = jax.random.bernoulli(key, subspace_ratio, (num_features,))
    # ensure >= 1 active feature: if empty, activate a uniformly drawn one
    any_active = jnp.any(mask)
    fallback = jnp.zeros((num_features,), bool).at[
        # graftlint: ignore[key-reuse] -- intentional: the fallback index reuses the mask key so masks stay bit-identical to the test-pinned derivation; a split here would change every historical mask
        jax.random.randint(key, (), 0, num_features)
    ].set(True)
    return jnp.where(any_active, mask, fallback)
