"""graftlint tier 1 + contract-logic tests (fast: no model fits here).

Every registered rule has a positive/negative fixture pair under
``tests/fixtures/lint/`` (``<id with _>_bad.py`` / ``_ok.py``); the
tier-2 tests that run REAL traces live in ``test_graftlint_contracts.py``
(slow tier).
"""

import json
import os
import textwrap

import pytest

from spark_ensemble_tpu.analysis import all_rules, lint_file, lint_paths
from spark_ensemble_tpu.analysis import contracts as contracts_mod
from spark_ensemble_tpu.analysis.cli import main as graftlint_main
from spark_ensemble_tpu.analysis.lint import write_jsonl

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "lint")
RULE_IDS = sorted(all_rules())


def _unsuppressed(findings, rule):
    return [f for f in findings if f.rule == rule and not f.suppressed]


def _fixture(rule_id, kind):
    return os.path.join(FIXTURES, f"{rule_id.replace('-', '_')}_{kind}.py")


# ---------------------------------------------------------------------------
# rule fixtures
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_fixture_pair_exists(rule_id):
    assert os.path.exists(_fixture(rule_id, "bad")), rule_id
    assert os.path.exists(_fixture(rule_id, "ok")), rule_id


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_fires_on_bad_fixture(rule_id):
    findings = lint_file(_fixture(rule_id, "bad"), select=[rule_id])
    assert _unsuppressed(findings, rule_id), (
        f"{rule_id} missed its positive fixture"
    )
    for f in _unsuppressed(findings, rule_id):
        assert f.line > 0 and f.message


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_quiet_on_ok_fixture(rule_id):
    findings = lint_file(_fixture(rule_id, "ok"), select=[rule_id])
    assert not _unsuppressed(findings, rule_id), [
        f.to_record() for f in _unsuppressed(findings, rule_id)
    ]


# ---------------------------------------------------------------------------
# suppression semantics
# ---------------------------------------------------------------------------

_READ_SRC = textwrap.dedent(
    """\
    import jax


    def run(model, X):
        out = model.predict(X)
        return jax.block_until_ready(out){trailing}
    """
)


def test_justified_trailing_suppression(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text(
        _READ_SRC.format(
            trailing="  # graftlint: ignore[unfenced-blocking-read]"
            " -- test fixture reason"
        )
    )
    findings = lint_file(str(p))
    hits = [f for f in findings if f.rule == "unfenced-blocking-read"]
    assert hits and all(f.suppressed for f in hits)
    assert hits[0].justification == "test fixture reason"


def test_justified_full_line_suppression(tmp_path):
    p = tmp_path / "mod.py"
    src = _READ_SRC.format(trailing="").replace(
        "    return jax.block_until_ready(out)",
        "    # graftlint: ignore[unfenced-blocking-read] -- above-line form\n"
        "    return jax.block_until_ready(out)",
    )
    p.write_text(src)
    findings = lint_file(str(p))
    hits = [f for f in findings if f.rule == "unfenced-blocking-read"]
    assert hits and all(f.suppressed for f in hits)


def test_bare_suppression_suppresses_nothing(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text(
        _READ_SRC.format(
            trailing="  # graftlint: ignore[unfenced-blocking-read]"
        )
    )
    findings = lint_file(str(p))
    # the original finding survives unsuppressed...
    assert _unsuppressed(findings, "unfenced-blocking-read")
    # ...and the bare ignore is itself a finding
    meta = [f for f in findings if f.rule == "suppression-missing-reason"]
    assert meta and not meta[0].suppressed


def test_meta_rule_cannot_be_suppressed(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text(
        "# graftlint: ignore[suppression-missing-reason] -- nice try\n"
        "x = 1  # graftlint: ignore[unfenced-blocking-read]\n"
    )
    findings = lint_file(str(p))
    meta = [f for f in findings if f.rule == "suppression-missing-reason"]
    assert meta and not any(f.suppressed for f in meta)


def test_syntax_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["syntax-error"]


# ---------------------------------------------------------------------------
# the repo itself gates clean
# ---------------------------------------------------------------------------


def test_repo_lints_clean():
    findings = lint_paths()
    loud = [f.to_record() for f in findings if not f.suppressed]
    assert not loud, loud
    # every suppression in the repo carries a justification (the engine
    # refuses to honor bare ignores, so this is structural — but pin it)
    assert all(f.justification for f in findings if f.suppressed)


# ---------------------------------------------------------------------------
# JSONL + CLI
# ---------------------------------------------------------------------------


def test_jsonl_record_shape(tmp_path):
    findings = lint_file(_fixture("key-reuse", "bad"))
    out = tmp_path / "findings.jsonl"
    write_jsonl(findings, str(out))
    records = [json.loads(line) for line in out.read_text().splitlines()]
    assert records
    for rec in records:
        assert rec["event"] == "lint_finding"
        assert {"rule", "file", "line", "col", "message", "suppressed"} <= set(
            rec
        )


def test_cli_exit_codes(tmp_path, capsys):
    assert graftlint_main([_fixture("f64-upcast", "bad")]) == 1
    assert graftlint_main([_fixture("f64-upcast", "ok")]) == 0
    assert graftlint_main(["--list-rules"]) == 0
    listing = capsys.readouterr().out
    for rule_id in RULE_IDS:
        assert rule_id in listing


def test_cli_writes_jsonl(tmp_path):
    out = tmp_path / "lint.jsonl"
    rc = graftlint_main(
        [_fixture("host-call-in-jit", "bad"), "--jsonl", str(out)]
    )
    assert rc == 1
    records = [json.loads(line) for line in out.read_text().splitlines()]
    assert any(r["rule"] == "host-call-in-jit" for r in records)


# ---------------------------------------------------------------------------
# contract logic (the failing-then-fixed demo; real traces are slow-tier)
# ---------------------------------------------------------------------------


def test_committed_baseline_wellformed():
    base = contracts_mod.load_baseline()
    assert base is not None, "analysis/contracts.json must be committed"
    assert base["version"] == 1
    eps = base["entry_points"]
    for fam in ("gbm", "boosting", "bagging", "stacking"):
        assert f"{fam}_regressor.fit" in eps
        assert f"{fam}_classifier.fit" in eps
        assert f"{fam}_regressor.predict" in eps
        assert f"{fam}_classifier.predict_proba" in eps
    assert "serving.warmup" in eps
    assert all(isinstance(v, int) and v >= 0 for v in eps.values())


def test_budget_drift_fails_then_fixed():
    pin = {"version": 1, "entry_points": {"gbm_regressor.fit": 3}}
    # FAILING: the traced budget drifted off the pin
    drifted = contracts_mod.ContractReport(budgets={"gbm_regressor.fit": 99})
    out = contracts_mod.check_contracts(baseline=pin, report=drifted)
    assert not out.ok
    assert any(
        v.contract == "budget" and "99" in v.message for v in out.violations
    )
    # FIXED: the same entry point back at its pinned budget is clean
    healthy = contracts_mod.ContractReport(budgets={"gbm_regressor.fit": 3})
    assert contracts_mod.check_contracts(baseline=pin, report=healthy).ok


def test_unpinned_entry_point_is_a_violation():
    rep = contracts_mod.ContractReport(budgets={"new_family.fit": 1})
    out = contracts_mod.check_contracts(
        baseline={"version": 1, "entry_points": {}}, report=rep
    )
    assert not out.ok
    assert "--update-baseline" in out.violations[0].message


def test_violation_record_shape():
    v = contracts_mod.ContractViolation("budget", "gbm_regressor.fit", "msg")
    rec = v.to_record()
    assert rec == {
        "event": "contract_violation",
        "contract": "budget",
        "entry_point": "gbm_regressor.fit",
        "message": "msg",
    }
