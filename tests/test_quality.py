"""Model-quality plane tests (docs/quality.md): the sketch math
(PSI/KL vs hand-computed references, coarsening, divergence), the
fit-time drift reference riding pack()/save()/take(), the engine's fused
bin sketch (exact-count determinism across bucket sizes and request
batching order), the DriftMonitor window state machine (padding
correction, raise/clear ``quality_alert`` events), staged attribution,
registry-leased shadow scoring, and the acceptance arc: a
covariate-shifted burst through a warmed FleetRouter flips /healthz
degraded via the ``quality_psi_max`` watchdog rule with zero
steady-state compiles, and clears when traffic normalizes."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import spark_ensemble_tpu as se
from spark_ensemble_tpu.ops.binning import Bins, bin_occupancy
from spark_ensemble_tpu.serving import (
    FleetRouter,
    InferenceEngine,
    ModelRegistry,
    load_packed,
    pack,
)
from spark_ensemble_tpu.telemetry.events import compile_snapshot
from spark_ensemble_tpu.telemetry.exporter import OperatorPlane
from spark_ensemble_tpu.telemetry.quality import (
    DriftMonitor,
    ShadowScorer,
    coarsen_counts,
    histogram_distribution,
    kl_divergence,
    prediction_divergence,
    psi,
    staged_attribution,
)
from spark_ensemble_tpu.telemetry.watchdog import (
    FALLBACK_THRESHOLDS,
    Rule,
    Watchdog,
    probe_quality_max,
)


def _data(n=256, d=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


@pytest.fixture(scope="module")
def fitted():
    X, y = _data()
    model = se.GBMRegressor(
        base_learner=se.DecisionTreeRegressor(max_depth=3),
        num_base_learners=4,
        seed=0,
    ).fit(X, y)
    return X, y, model


@pytest.fixture(scope="module")
def packed(fitted):
    _, _, model = fitted
    return pack(model)


def _fetch(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# sketch math
# ---------------------------------------------------------------------------


def test_psi_matches_hand_computed():
    ref = np.array([10, 20, 30, 40])
    obs = np.array([40, 30, 20, 10])
    # q = [.1 .2 .3 .4], p = [.4 .3 .2 .1]:
    # PSI = .3 ln4 + .1 ln1.5 - .1 ln(2/3) - .3 ln(1/4)
    want = (
        0.3 * np.log(4.0)
        + 0.1 * np.log(1.5)
        - 0.1 * np.log(2.0 / 3.0)
        - 0.3 * np.log(0.25)
    )
    assert np.isclose(float(psi(ref, obs, smoothing=0.0)), want, atol=1e-6)
    assert np.isclose(float(psi(ref, ref)), 0.0, atol=1e-6)
    # per-feature form: [d, B] in -> [d] out, rows independent
    stacked = psi(np.stack([ref, ref]), np.stack([obs, ref]),
                  smoothing=0.0)
    assert stacked.shape == (2,)
    assert np.isclose(stacked[0], want, atol=1e-6)
    assert np.isclose(stacked[1], 0.0, atol=1e-6)


def test_kl_matches_hand_computed():
    ref = np.array([10, 20, 30, 40])
    obs = np.array([40, 30, 20, 10])
    # KL(p || q) = .4 ln4 + .3 ln1.5 + .2 ln(2/3) + .1 ln(1/4)
    want = (
        0.4 * np.log(4.0)
        + 0.3 * np.log(1.5)
        + 0.2 * np.log(2.0 / 3.0)
        + 0.1 * np.log(0.25)
    )
    assert np.isclose(
        float(kl_divergence(ref, obs, smoothing=0.0)), want, atol=1e-6
    )
    # smoothing keeps empty observed bins finite
    assert np.isfinite(float(kl_divergence([5, 5, 5], [15, 0, 0])))


def test_histogram_distribution_and_coarsening():
    p = histogram_distribution(np.array([[1, 2, 3], [0, 0, 0]]))
    assert p.shape == (2, 3)
    assert np.allclose(p.sum(axis=-1), 1.0)
    assert np.allclose(p[1], 1.0 / 3.0)  # all-empty -> uniform
    c = coarsen_counts(np.arange(1, 9), 4)
    assert c.tolist() == [3, 7, 11, 15]
    assert coarsen_counts(np.arange(4), 99).tolist() == [0, 1, 2, 3]
    # coarsening commutes with accumulation: sum-then-coarsen ==
    # coarsen-then-sum (the monitor accumulates full-res, scores coarse)
    a, b = np.arange(8), np.arange(8)[::-1]
    assert np.array_equal(
        coarsen_counts(a + b, 4), coarsen_counts(a, 4) + coarsen_counts(b, 4)
    )


def test_prediction_divergence_both_modes():
    assert prediction_divergence(
        np.array([0, 1, 1, 0]), np.array([0, 1, 0, 0]), True
    ) == 0.25
    assert np.isclose(
        prediction_divergence(
            np.array([1.0, 1.0]), np.array([2.0, 2.0]), False
        ),
        1.0,
    )
    assert prediction_divergence(np.zeros(4), np.zeros(4), False) == 0.0


# ---------------------------------------------------------------------------
# fit-time reference through pack / save / take
# ---------------------------------------------------------------------------


def test_fit_captures_drift_reference(fitted):
    X, _, model = fitted
    ref = model.drift_ref_
    assert ref["rows"] == X.shape[0]
    d = X.shape[1]
    assert ref["thresholds"].shape[0] == d
    assert ref["occupancy"].shape == (d, ref["thresholds"].shape[1] + 1)
    # occupancy is an exact row count per feature, not a sample
    assert np.all(ref["occupancy"].sum(axis=1) == X.shape[0])


def test_packed_quality_roundtrip_and_take(fitted, packed, tmp_path):
    X, _, _ = fitted
    q = packed.quality
    assert q is not None and q["rows"] == X.shape[0]
    packed.save(str(tmp_path / "m"))
    loaded = load_packed(str(tmp_path / "m"))
    q2 = loaded.quality
    assert np.array_equal(q["thresholds"], q2["thresholds"])
    assert np.array_equal(q["occupancy"], q2["occupancy"])
    # the reference rides OUTSIDE the model node: bit-identical predictions
    want = np.asarray(packed.predict(X[:32]))
    assert np.array_equal(want, np.asarray(loaded.predict(X[:32])))
    # prefix slices keep the full-fit reference (tiers score drift too)
    prefix = packed.take(2)
    assert np.array_equal(prefix.quality["occupancy"], q["occupancy"])


# ---------------------------------------------------------------------------
# fused sketch: exact counts, invariant to buckets and batching order
# ---------------------------------------------------------------------------


def test_bin_occupancy_exact_and_split_invariant(fitted, packed):
    X, _, _ = fitted
    bins = Bins(thresholds=packed.quality["thresholds"])
    whole = np.asarray(bin_occupancy(X, bins))
    assert whole.dtype == np.int32
    assert np.all(whole.sum(axis=1) == X.shape[0])
    pieces = np.zeros_like(whole)
    for lo, hi in ((0, 1), (1, 8), (8, 17), (17, 256)):
        pieces += np.asarray(bin_occupancy(X[lo:hi], bins))
    assert np.array_equal(whole, pieces)


def test_drift_scores_invariant_to_buckets_and_order(fitted, packed):
    """The same 96 rows served through different engine bucket configs
    and different request orders must produce IDENTICAL window scores:
    the sketch is exact integer counts and summation commutes."""
    X, _, _ = fitted
    rows = X[:96]

    def serve(min_bucket, max_batch, order):
        eng = InferenceEngine(
            packed, methods=("predict",), min_bucket=min_bucket,
            max_batch_size=max_batch, warm=True, drift=True,
            drift_window=96,
        )
        try:
            for lo, hi in order:
                eng.predict(rows[lo:hi])
            snap = eng.drift_monitor.snapshot()
            assert snap["windows"] == 1, snap
            return eng.drift_monitor.feature_psi(), snap
        finally:
            eng.stop()

    psi_a, snap_a = serve(8, 32, ((0, 5), (5, 40), (40, 96)))
    psi_b, snap_b = serve(16, 64, ((40, 96), (0, 5), (5, 40)))
    assert np.array_equal(psi_a, psi_b)
    assert snap_a["psi_max"] == snap_b["psi_max"]
    assert snap_a["rows_total"] == snap_b["rows_total"] == 96


def test_engine_drift_auto_enable_and_bit_identity(fitted, packed):
    X, _, _ = fitted
    on = InferenceEngine(packed, methods=("predict",), min_bucket=8,
                         max_batch_size=32, warm=True)
    off = InferenceEngine(packed, methods=("predict",), min_bucket=8,
                          max_batch_size=32, warm=True, drift=False)
    try:
        # a packed quality reference auto-enables the sketch
        assert on.stats()["drift_enabled"] is True
        assert off.stats()["drift_enabled"] is False
        for n in (1, 7, 30):
            assert np.array_equal(on.predict(X[:n]), off.predict(X[:n]))
        assert on.stats()["drift"]["rows_total"] == 38
        assert off.stats()["drift"] is None
    finally:
        on.stop()
        off.stop()
    # drift=True without a packed reference is a loud config error
    X2, y2 = _data(n=64, d=3, seed=1)
    bare = pack(se.GBMRegressor(num_base_learners=2, seed=0).fit(X2, y2))
    if bare.quality is not None:
        bare._node.pop("quality")
    with pytest.raises(ValueError, match="drift"):
        InferenceEngine(bare, warm=False, drift=True)


# ---------------------------------------------------------------------------
# DriftMonitor state machine
# ---------------------------------------------------------------------------


def _synthetic_monitor(tmp_path=None, **kw):
    # 1 feature, 4 bins with thresholds [-1, 0, 1]; uniform reference
    thr = np.array([[-1.0, 0.0, 1.0]], np.float32)
    ref = np.array([[100, 100, 100, 100]], np.int64)
    kw.setdefault("window_rows", 40)
    kw.setdefault("score_groups", 4)
    path = str(tmp_path / "drift.jsonl") if tmp_path else None
    return DriftMonitor(thr, ref, telemetry_path=path, **kw)


def test_drift_monitor_pad_correction():
    mon = _synthetic_monitor()
    try:
        # 10 real rows uniform + 30 pad rows; pads land in the zero bin
        # (searchsorted(thr, 0.0) == 1) and must subtract back out
        counts = np.array([[10, 10 + 30, 10, 10]])
        mon.observe(counts, pad_rows=30)
        mon.observe(np.array([[0, 0, 0, 0]]))
        snap = mon.snapshot()
        assert snap["windows"] == 1
        assert snap["current_rows"] == 0
        assert np.isclose(snap["psi_max"], 0.0, atol=1e-4), snap
    finally:
        mon.close()


def test_drift_monitor_alert_raise_and_clear(tmp_path):
    mon = _synthetic_monitor(tmp_path)
    try:
        uniform = np.array([[10, 10, 10, 10]])
        shifted = np.array([[0, 0, 0, 40]])
        mon.observe(uniform)          # window 1: in-distribution
        assert mon.snapshot()["alert_active"] is False
        mon.observe(shifted)          # window 2: mass collapsed -> alert
        snap = mon.snapshot()
        assert snap["alert_active"] is True
        assert snap["psi_max"] > mon.psi_threshold
        assert snap["drifted_features"] == 1
        assert "f0" in snap["top"]
        mon.observe(uniform)          # window 3: clears
        assert mon.snapshot()["alert_active"] is False
    finally:
        mon.close()
    events = [json.loads(line) for line in
              (tmp_path / "drift.jsonl").read_text().splitlines()]
    windows = [e for e in events if e["event"] == "drift_window"]
    alerts = [e for e in events if e["event"] == "quality_alert"]
    assert [w["window"] for w in windows] == [1, 2, 3]
    assert [a["state"] for a in alerts] == ["raised", "cleared"]
    assert alerts[0]["metric"] == "psi_max"
    assert alerts[0]["value"] > alerts[0]["threshold"]


def test_drift_monitor_rejects_mismatched_shapes():
    with pytest.raises(ValueError, match="occupancy"):
        DriftMonitor(np.zeros((2, 3), np.float32), np.zeros((2, 3)))
    mon = _synthetic_monitor()
    try:
        with pytest.raises(ValueError, match="histogram"):
            mon.observe(np.zeros((2, 4)))
    finally:
        mon.close()


# ---------------------------------------------------------------------------
# staged attribution + shadow scoring
# ---------------------------------------------------------------------------


def test_staged_attribution_margins_and_uncertainty(fitted, packed):
    X, _, _ = fitted
    eng = InferenceEngine(packed, methods=("predict",),
                          prefix_tiers=(1, 2), min_bucket=8,
                          max_batch_size=32, warm=True)
    try:
        att = staged_attribution(eng, X[:16])
        assert att["tiers"] == [1, 2]
        assert set(att["margins"]) == {"1", "2"}
        assert att["uncertainty"] == max(att["margins"].values())
        assert isinstance(att["flagged"], bool)
        # a 1-member prefix of a 4-member GBM genuinely disagrees
        assert att["margins"]["1"] > 0.0
        # the caller-supplied full answer short-circuit is equivalent
        att2 = staged_attribution(eng, X[:16], full=eng.predict(X[:16]))
        assert att2["margins"] == att["margins"]
    finally:
        eng.stop()


def test_fleet_attribution_populates_response(fitted, packed):
    X, _, _ = fitted
    with FleetRouter(
        packed, replicas=1, prefix_tiers=(1, 2), min_bucket=8,
        max_batch_size=32, deadline_ms=30_000.0, drift=False,
        attribution_fraction=1.0, uncertainty_threshold=-1.0,
    ) as fleet:
        resp = fleet.predict(X[:8])
        assert resp.uncertainty is not None
        assert set(resp.staged_margins) == {"1", "2"}
        assert resp.quality_flagged is True  # threshold -1 flags any
        slo = fleet.stats()["fleet"]
        assert slo["attributed"] >= 1
        assert slo["quality_flagged"] >= 1


def test_fleet_stop_closes_owned_drift_source(fitted, packed):
    """Regression: the router-built base engine owns its drift monitor,
    so FleetRouter.stop() must unregister the ``quality/*`` source — a
    leaked live source with a stale ``psi_max`` would poison every later
    watchdog's ``quality_psi_max`` probe (max over live sources)."""
    from spark_ensemble_tpu.telemetry import global_metrics

    X, _, _ = fitted
    fleet = FleetRouter(
        packed, replicas=2, min_bucket=8, max_batch_size=32,
        deadline_ms=30_000.0, drift=True, drift_window=64,
    )
    try:
        for i in range(4):
            fleet.predict(X[16 * i: 16 * (i + 1)])
        live = [k for k in global_metrics().snapshot()
                if k.startswith("quality/") and "warm" in k]
        assert live, "drift-enabled fleet must register its quality source"
    finally:
        fleet.stop()
    leaked = [k for k in global_metrics().snapshot()
              if k.startswith("quality/") and "warm" in k]
    assert leaked == [], leaked


def test_shadow_scorer_sampling_divergence_and_labels(fitted, packed):
    X, y, _ = fitted
    registry = ModelRegistry()
    registry.register("candidate", packed, warm=True, min_bucket=8,
                      max_batch_size=32)
    scorer = ShadowScorer(registry, "candidate", fraction=0.5, window=8)
    try:
        primary = np.asarray(packed.predict(X[:8]))
        for i in range(4):
            scorer.observe(X[:8], primary, request_id=i)
        snap = scorer.snapshot()
        assert snap["requests_seen"] == 4
        assert snap["evals"] == 2          # every 2nd request sampled
        # same model both sides: divergence is float-ulp noise only (the
        # candidate serves through bucketed programs, the primary raw)
        assert snap["divergence"] < 1e-6
        assert snap["errors"] == 0
        # ids 0 and 2 were sampled; 1 was not
        assert scorer.record_label(0, y[:8]) is True
        assert scorer.record_label(1, y[:8]) is False
        assert np.isclose(scorer.snapshot()["accuracy_delta"], 0.0)
    finally:
        scorer.close()
        registry.close()


def test_shadow_scorer_survives_sick_candidate(fitted, packed):
    X, _, _ = fitted
    registry = ModelRegistry()
    scorer = ShadowScorer(registry, "never-registered", fraction=1.0)
    try:
        primary = np.asarray(packed.predict(X[:8]))
        assert scorer.observe(X[:8], primary) is None
        snap = scorer.snapshot()
        assert snap["errors"] == 1 and snap["evals"] == 0
    finally:
        scorer.close()
        registry.close()


# ---------------------------------------------------------------------------
# watchdog + /healthz acceptance arc
# ---------------------------------------------------------------------------


def test_quality_rules_in_default_surface():
    assert FALLBACK_THRESHOLDS["quality_psi_max"] == ("lower", 0.25)
    assert FALLBACK_THRESHOLDS["shadow_divergence"] == ("lower", 0.25)


def test_probe_quality_max_scans_live_sources():
    probe = probe_quality_max("psi_max")
    assert probe({}) is None  # no monitor live -> rule freezes
    mon = _synthetic_monitor(stream="probe-test")
    try:
        mon.observe(np.array([[0, 0, 0, 40]]))
        from spark_ensemble_tpu.telemetry.events import global_metrics

        value = probe(global_metrics().snapshot())
        assert value is not None and value > 0.25
    finally:
        mon.close()


def test_fleet_drift_arc_flips_healthz_and_clears(fitted, packed,
                                                  tmp_path):
    """The acceptance demo, fully deterministic: a covariate-shifted
    burst through a warmed drift-on fleet scores a window past the PSI
    threshold, lands ``quality_alert``, flips /healthz degraded through
    the ``quality_psi_max`` rule, and clears (hysteresis: clear_for=2)
    once traffic normalizes — all with ZERO steady-state compiles."""
    X, _, _ = fitted
    telemetry = tmp_path / "quality.jsonl"
    dog = Watchdog(
        rules=[Rule("quality_psi_max", probe_quality_max("psi_max"),
                    threshold=0.25, breach_for=1, clear_for=2)],
        interval_s=3600.0,
        telemetry_path=str(telemetry),
    )
    plane = OperatorPlane(port=0, watchdog=dog,
                          sampler_interval_s=3600.0).start()
    try:
        with FleetRouter(
            packed, replicas=1, min_bucket=32, max_batch_size=64,
            deadline_ms=30_000.0, drift=True, drift_window=256,
            telemetry_path=str(telemetry),
        ) as fleet:
            before = compile_snapshot()[0]
            for i in range(4):                   # window 1: in-dist
                fleet.predict(X[64 * i: 64 * (i + 1)])
            dog.evaluate_once()
            code, _ = _fetch(plane.url + "/healthz")
            assert code == 200
            for i in range(4):                   # window 2: shifted
                fleet.predict(X[64 * i: 64 * (i + 1)] + 3.0)
            dog.evaluate_once()
            code, body = _fetch(plane.url + "/healthz")
            assert code == 503
            assert "quality_psi_max" in body
            code, body = _fetch(plane.url + "/qualityz")
            qz = json.loads(body)
            drift_streams = [v for v in qz["streams"].values()
                             if v.get("kind") == "drift"]
            assert drift_streams and drift_streams[0]["alert_active"]
            assert drift_streams[0]["psi_max"] > 0.25
            for i in range(4):                   # window 3: normalized
                fleet.predict(X[64 * i: 64 * (i + 1)])
            dog.evaluate_once()
            code, _ = _fetch(plane.url + "/healthz")
            assert code == 503                   # clear_for=2 holds
            dog.evaluate_once()
            code, _ = _fetch(plane.url + "/healthz")
            assert code == 200
            # the whole arc rode the warmed programs: the sketch is fused,
            # the shifted rows hit the same buckets
            assert compile_snapshot()[0] == before
            # /metrics renders the quality series
            code, body = _fetch(plane.url + "/metrics")
            assert "se_tpu_quality_psi_max" in body
    finally:
        plane.stop()
    events = [json.loads(line)
              for line in telemetry.read_text().splitlines()]
    windows = [e for e in events if e["event"] == "drift_window"]
    assert [w["window"] for w in windows] == [1, 2, 3]
    assert windows[0]["psi_max"] < 0.25 < windows[1]["psi_max"]
    assert windows[2]["psi_max"] < 0.25
    alerts = [e for e in events if e["event"] == "quality_alert"]
    assert [a["state"] for a in alerts] == ["raised", "cleared"]
    slo = [e for e in events if e["event"] == "slo_alert"]
    assert [a["state"] for a in slo] == ["raised", "cleared"]
    assert all(a["metric"] == "quality_psi_max" for a in slo)
