"""Instrumentation + profiler hooks (SURVEY.md §5 tracing row)."""

import logging
import os

import numpy as np
import pytest

import spark_ensemble_tpu as se


def _data(n=200, d=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


def test_fit_logs_params_and_outcome(caplog):
    X, y = _data()
    with caplog.at_level(logging.INFO, logger="spark_ensemble_tpu"):
        se.GBMRegressor(num_base_learners=2).fit(X, y)
    text = caplog.text
    assert "GBMRegressor.fit] params" in text
    assert "dataset: n=200, d=4" in text
    assert "done in" in text


def test_profile_dir_produces_trace(tmp_path):
    """profile_dir param activates a jax.profiler trace capture around fit."""
    trace_dir = str(tmp_path / "trace")
    X, y = _data()
    se.GBMRegressor(num_base_learners=2, profile_dir=trace_dir).fit(X, y)
    assert os.path.isdir(trace_dir)
    found = [
        os.path.join(root, f)
        for root, _, files in os.walk(trace_dir)
        for f in files
    ]
    assert found, "profiler trace directory is empty"


def test_instrumented_logs_exceptions(caplog):
    import pytest

    from spark_ensemble_tpu.utils.instrumentation import instrumented

    with caplog.at_level(logging.ERROR, logger="spark_ensemble_tpu"):
        with pytest.raises(RuntimeError):
            with instrumented("boom.fit"):
                raise RuntimeError("x")
    assert "[boom.fit] failed" in caplog.text


@pytest.mark.slow
def test_trace_summary_from_profile_capture(tmp_path):
    """profile_dir capture -> utils.profiling summary: the op-cost table
    that drives kernel work must be producible from a fit's own trace."""
    import numpy as np

    from spark_ensemble_tpu import DecisionTreeRegressor
    from spark_ensemble_tpu.utils import profiling

    rng = np.random.RandomState(0)
    X = rng.randn(400, 5).astype(np.float32)
    y = X[:, 0].astype(np.float32)
    prof = str(tmp_path / "prof")
    DecisionTreeRegressor(profile_dir=prof).fit(X, y)
    assert profiling.find_trace_files(prof), "no trace files captured"
    rows, total = profiling.summarize_trace(prof, top=10)
    assert rows and all(r[1] > 0 for r in rows)
    assert total >= sum(r[1] for r in rows)  # % base covers ALL ops
    text = profiling.format_summary(rows, total)
    assert "total_ms" in text and len(text.splitlines()) >= 2
    # CLI path
    assert profiling.main([prof, "--top", "5"]) == 0
