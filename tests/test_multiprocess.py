"""Real multi-process execution: two OS processes join a ``jax.distributed``
CPU rendezvous and run one psum-ed fit step over a GLOBAL mesh.

This exercises the ``process_count > 1`` branch of ``parallel/multihost.py``
— the only path that matters on a real pod — the way the reference exercises
its distribution on ``local[*]`` with a real task scheduler (SURVEY.md §4).
``tests/test_parallel.py`` covers the single-process contract; this file
covers the rendezvous itself.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(extra_args=()):
    """Launch the 2-process worker pair; returns the per-process outputs
    (skips when the sandbox forbids loopback sockets)."""
    try:
        port = _free_port()
    except OSError as e:  # environment forbids sockets
        pytest.skip(f"no loopback sockets: {e}")

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the workers pin CPU + 2 virtual devices themselves; scrub any
    # conflicting outer settings (e.g. this suite's 8-device conftest flags)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid), *extra_args],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-process rendezvous timed out (420s)")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-3000:]}"
    return outs


@pytest.mark.slow
def test_two_process_rendezvous_psum_fit():
    outs = _run_workers()
    for pid, out in enumerate(outs):
        assert "MULTIHOST_OK" in out, f"process {pid} incomplete:\n{out[-3000:]}"


@pytest.mark.slow
def test_two_process_distributed_histograms(tmp_path):
    """Each host streams only its manifest slice; the cross-DCN reduce
    must land on the single-host bits with a FIXED program count."""
    outs = _run_workers(("dist", str(tmp_path)))
    for pid, out in enumerate(outs):
        assert "DIST_OK" in out, f"process {pid} incomplete:\n{out[-3000:]}"
    # per-host telemetry JSONL written for both processes
    for pid in (0, 1):
        assert (tmp_path / f"telemetry_p{pid}.jsonl").exists()


@pytest.mark.slow
def test_two_process_elastic_preempt_resume(tmp_path):
    """Process 1 dies to a live host_preempt mid-round; process 0 rewinds,
    repartitions the orphaned slice, and resumes bit-identically.  The
    two per-host telemetry streams must then stitch into one pod trace:
    the survivor's stream ALONE fails validation (its rewind flow arrow
    has no source), the stitched trace passes with host0/host1 tracks
    and the preempt->rewind flow crossing hosts, the skew report names
    the stalled host, and the victim's crash flight dump is on disk."""
    import importlib.util
    import json

    outs = _run_workers(("elastic", str(tmp_path)))
    assert "ELASTIC_OK" in outs[0], f"survivor incomplete:\n{outs[0][-3000:]}"
    assert "PREEMPTED" in outs[1], f"victim not preempted:\n{outs[1][-3000:]}"
    assert "PREEMPT_EXIT_OK" in outs[1], outs[1][-3000:]
    assert "FLIGHT_OK" in outs[1], outs[1][-3000:]

    spec = importlib.util.spec_from_file_location(
        "_podview",
        os.path.join(
            _REPO, "spark_ensemble_tpu", "telemetry", "podview.py"
        ),
    )
    podview = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(podview)
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import trace_viewer
    finally:
        sys.path.pop(0)

    paths = [str(tmp_path / f"telemetry_p{pid}.jsonl") for pid in (0, 1)]
    streams = [podview.load_stream(p) for p in paths]

    # the survivor alone is an INCOMPLETE trace: its rewind span's
    # flow_in has no flow_out source (the victim emitted it)
    survivor_spans = trace_viewer.select_spans(streams[0])
    assert trace_viewer.validate(survivor_spans), (
        "survivor-only stream unexpectedly validated clean"
    )

    # stitched, the pod trace is whole: validation passes, both hosts
    # own tracks, and the preempt arrow lands in the survivor's rewind
    merged, info = podview.stitch_files(paths)
    assert info["hosts"] == [0, 1]
    spans = trace_viewer.select_spans(merged)
    assert trace_viewer.validate(spans) == []
    threads = {s.get("thread", "") for s in spans}
    assert any(t.startswith("host0") for t in threads), threads
    assert any(t.startswith("host1") for t in threads), threads
    preempts = [s for s in spans if s["name"] == "host_preempt"]
    rewinds = [s for s in spans if s["name"] == "rewind"]
    assert len(preempts) == 1 and len(rewinds) == 1
    assert preempts[0]["host"] == 1 and rewinds[0]["host"] == 0
    assert rewinds[0]["flow_in"] in preempts[0]["flow_out"]

    # straggler attribution: the injected round-1 stall names host 0
    skew = podview.skew_report(streams)
    round1 = next(r for r in skew["rounds"] if r["round"] == 1)
    assert round1["offender"] == 0, skew["rounds"]
    assert "0" in skew["stalls"], skew["stalls"]

    # the victim's flight dump carries its last spans/events
    dumps = list(tmp_path.glob("flight_p*.json"))
    assert dumps, list(tmp_path.iterdir())
    payload = json.loads(max(dumps, key=lambda p: p.stat().st_size).read_text())
    assert payload["rows"]
