"""Real multi-process execution: two OS processes join a ``jax.distributed``
CPU rendezvous and run one psum-ed fit step over a GLOBAL mesh.

This exercises the ``process_count > 1`` branch of ``parallel/multihost.py``
— the only path that matters on a real pod — the way the reference exercises
its distribution on ``local[*]`` with a real task scheduler (SURVEY.md §4).
``tests/test_parallel.py`` covers the single-process contract; this file
covers the rendezvous itself.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(extra_args=()):
    """Launch the 2-process worker pair; returns the per-process outputs
    (skips when the sandbox forbids loopback sockets)."""
    try:
        port = _free_port()
    except OSError as e:  # environment forbids sockets
        pytest.skip(f"no loopback sockets: {e}")

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the workers pin CPU + 2 virtual devices themselves; scrub any
    # conflicting outer settings (e.g. this suite's 8-device conftest flags)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid), *extra_args],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-process rendezvous timed out (420s)")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-3000:]}"
    return outs


@pytest.mark.slow
def test_two_process_rendezvous_psum_fit():
    outs = _run_workers()
    for pid, out in enumerate(outs):
        assert "MULTIHOST_OK" in out, f"process {pid} incomplete:\n{out[-3000:]}"


@pytest.mark.slow
def test_two_process_distributed_histograms(tmp_path):
    """Each host streams only its manifest slice; the cross-DCN reduce
    must land on the single-host bits with a FIXED program count."""
    outs = _run_workers(("dist", str(tmp_path)))
    for pid, out in enumerate(outs):
        assert "DIST_OK" in out, f"process {pid} incomplete:\n{out[-3000:]}"
    # per-host telemetry JSONL written for both processes
    for pid in (0, 1):
        assert (tmp_path / f"telemetry_p{pid}.jsonl").exists()


@pytest.mark.slow
def test_two_process_elastic_preempt_resume(tmp_path):
    """Process 1 dies to a live host_preempt mid-round; process 0 rewinds,
    repartitions the orphaned slice, and resumes bit-identically."""
    outs = _run_workers(("elastic", str(tmp_path)))
    assert "ELASTIC_OK" in outs[0], f"survivor incomplete:\n{outs[0][-3000:]}"
    assert "PREEMPTED" in outs[1], f"victim not preempted:\n{outs[1][-3000:]}"
    assert "PREEMPT_EXIT_OK" in outs[1], outs[1][-3000:]
