"""Evaluator metric kernels vs straightforward numpy references."""

import numpy as np
import pytest

from spark_ensemble_tpu.evaluation import (
    BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)


class _FixedModel:
    """Stub model returning canned predictions/probabilities."""

    def __init__(self, pred=None, proba=None, num_classes=None):
        self._pred = pred
        self._proba = proba
        self.num_classes = num_classes

    def predict(self, X):
        return self._pred

    def predict_proba(self, X):
        return self._proba


def test_regression_metrics_match_numpy():
    rng = np.random.RandomState(0)
    y = rng.randn(500).astype(np.float32)
    pred = y + 0.3 * rng.randn(500).astype(np.float32)
    model = _FixedModel(pred=pred)
    X = np.zeros((500, 1))
    err = pred - y
    assert RegressionEvaluator(metric="mse").evaluate(model, X, y) == pytest.approx(
        np.mean(err**2), rel=1e-5
    )
    assert RegressionEvaluator(metric="rmse").evaluate(model, X, y) == pytest.approx(
        np.sqrt(np.mean(err**2)), rel=1e-5
    )
    assert RegressionEvaluator(metric="mae").evaluate(model, X, y) == pytest.approx(
        np.mean(np.abs(err)), rel=1e-5
    )
    r2_ref = 1.0 - np.mean(err**2) / np.var(y)
    assert RegressionEvaluator(metric="r2").evaluate(model, X, y) == pytest.approx(
        r2_ref, rel=1e-4
    )
    assert RegressionEvaluator(metric="rmse").is_larger_better is False
    assert RegressionEvaluator(metric="r2").is_larger_better is True


def test_regression_var_is_explained_variance():
    """Spark 'var' = SSreg / weightSum (explained variance), larger-better."""
    rng = np.random.RandomState(2)
    y = rng.randn(300).astype(np.float32)
    pred = 0.5 * y + 0.1 * rng.randn(300).astype(np.float32)
    model = _FixedModel(pred=pred)
    got = RegressionEvaluator(metric="var").evaluate(model, np.zeros((300, 1)), y)
    expect = np.mean((pred - np.mean(y)) ** 2)
    assert got == pytest.approx(expect, rel=1e-4)
    assert RegressionEvaluator(metric="var").is_larger_better is True


def test_regression_weighted():
    y = np.array([0.0, 0.0], np.float32)
    pred = np.array([1.0, 3.0], np.float32)
    w = np.array([3.0, 1.0], np.float32)
    model = _FixedModel(pred=pred)
    got = RegressionEvaluator(metric="mse").evaluate(
        model, np.zeros((2, 1)), y, sample_weight=w
    )
    assert got == pytest.approx((3 * 1 + 1 * 9) / 4.0, rel=1e-6)


def test_multiclass_accuracy_and_f1():
    y = np.array([0, 0, 1, 1, 2, 2], np.float32)
    pred = np.array([0, 1, 1, 1, 2, 0], np.float32)
    model = _FixedModel(pred=pred, num_classes=3)
    X = np.zeros((6, 1))
    acc = MulticlassClassificationEvaluator(metric="accuracy").evaluate(model, X, y)
    assert acc == pytest.approx(4 / 6, rel=1e-6)
    ham = MulticlassClassificationEvaluator(metric="hammingLoss").evaluate(model, X, y)
    assert ham == pytest.approx(2 / 6, rel=1e-6)
    # sklearn weighted-f1 for this table is 0.6555...
    f1 = MulticlassClassificationEvaluator(metric="f1").evaluate(model, X, y)
    # per-class: c0 p=1/2 r=1/2 f=1/2; c1 p=2/3 r=1 f=0.8; c2 p=1 r=1/2 f=2/3
    expect = (2 * 0.5 + 2 * 0.8 + 2 * (2 / 3)) / 6
    assert f1 == pytest.approx(expect, rel=1e-5)
    wp = MulticlassClassificationEvaluator(metric="weightedPrecision").evaluate(
        model, X, y
    )
    assert wp == pytest.approx((2 * 0.5 + 2 * (2 / 3) + 2 * 1.0) / 6, rel=1e-5)


def test_multiclass_logloss():
    y = np.array([0, 1], np.float32)
    proba = np.array([[0.8, 0.2], [0.4, 0.6]], np.float32)
    model = _FixedModel(proba=proba, num_classes=2)
    got = MulticlassClassificationEvaluator(metric="logLoss").evaluate(
        model, np.zeros((2, 1)), y
    )
    assert got == pytest.approx(-(np.log(0.8) + np.log(0.6)) / 2, rel=1e-5)


def test_binary_auc_perfect_and_random():
    n = 1000
    rng = np.random.RandomState(1)
    y = (rng.rand(n) > 0.5).astype(np.float32)
    # perfect ranking
    proba = np.stack([1 - y, y], axis=1).astype(np.float32)
    proba = np.clip(proba + 0.01 * rng.rand(n, 1), 0, 1)
    model = _FixedModel(proba=proba)
    auc = BinaryClassificationEvaluator(metric="areaUnderROC").evaluate(
        model, np.zeros((n, 1)), y
    )
    assert auc > 0.99
    # random scores -> AUC ~ 0.5
    score = rng.rand(n).astype(np.float32)
    model = _FixedModel(proba=np.stack([1 - score, score], axis=1))
    auc = BinaryClassificationEvaluator(metric="areaUnderROC").evaluate(
        model, np.zeros((n, 1)), y
    )
    assert 0.45 < auc < 0.55
    pr = BinaryClassificationEvaluator(metric="areaUnderPR").evaluate(
        model, np.zeros((n, 1)), y
    )
    base_rate = float(np.mean(y))
    assert abs(pr - base_rate) < 0.1


def test_aupr_constant_scorer_is_base_rate():
    """SPARK-21806 anchor: a constant scorer's AUPR equals the base rate,
    not (1 + baseRate) / 2 as the (0, 1) anchor would give."""
    y = np.array([1.0] * 30 + [0.0] * 70, np.float32)
    proba = np.full((100, 2), 0.5, np.float32)
    pr = BinaryClassificationEvaluator(metric="areaUnderPR").evaluate(
        _FixedModel(proba=proba), np.zeros((100, 1)), y
    )
    assert pr == pytest.approx(0.3, abs=1e-6)


def test_binary_auc_tied_scores_give_chance_level():
    """A constant scorer must get AUC 0.5 regardless of row order (tie
    handling: one curve point per distinct threshold)."""
    y = np.array([1.0] * 50 + [0.0] * 50, np.float32)
    proba = np.full((100, 2), 0.5, np.float32)
    ev = BinaryClassificationEvaluator(metric="areaUnderROC")
    model = _FixedModel(proba=proba)
    assert ev.evaluate(model, np.zeros((100, 1)), y) == pytest.approx(0.5, abs=1e-6)
    assert ev.evaluate(model, np.zeros((100, 1)), y[::-1]) == pytest.approx(
        0.5, abs=1e-6
    )
    # two tied blocks: all positives scored high, ties within blocks
    y2 = np.array([1, 1, 0, 0], np.float32)
    proba2 = np.array([[0.1, 0.9], [0.1, 0.9], [0.9, 0.1], [0.9, 0.1]], np.float32)
    assert ev.evaluate(_FixedModel(proba=proba2), np.zeros((4, 1)), y2) == pytest.approx(
        1.0, abs=1e-6
    )


@pytest.mark.slow
def test_model_score_convenience():
    """model.score(X, y) == the corresponding evaluator's default metric
    (accuracy for classifiers, R^2 for regressors)."""
    import spark_ensemble_tpu as se
    from spark_ensemble_tpu.evaluation import (
        MulticlassClassificationEvaluator,
        RegressionEvaluator,
    )

    rng = np.random.RandomState(0)
    X = rng.randn(500, 4).astype(np.float32)
    yk = (X[:, 0] > 0).astype(np.float32)
    yr = (2 * X[:, 1] + 0.1 * rng.randn(500)).astype(np.float32)
    c = se.DecisionTreeClassifier(max_depth=3).fit(X, yk)
    assert c.score(X, yk) == MulticlassClassificationEvaluator(
        metric="accuracy"
    ).evaluate(c, X, yk)
    r = se.GBMRegressor(num_base_learners=3).fit(X, yr)
    assert r.score(X, yr) == RegressionEvaluator(metric="r2").evaluate(
        r, X, yr
    )
    assert r.score(X, yr) > 0.5
