"""MLP base-learner tests.

The reference accepts any Spark ML ``Predictor`` as a member
(`ensemble/package.scala:32-67`); Spark MLlib's
``MultilayerPerceptronClassifier`` is its stock nonlinear base learner.
These tests mirror the suite archetypes of SURVEY.md §4: beats-baseline
(vs the linear learner on a linearly inseparable dataset), weighted-fit
semantics, SPMD parity on the virtual mesh, ensemble composition, and
persistence round-trip.
"""

import numpy as np
import pytest

from spark_ensemble_tpu import (
    BaggingClassifier,
    GBMRegressor,
    LogisticRegression,
    MLPClassifier,
    MLPRegressor,
    StackingClassifier,
)
from spark_ensemble_tpu.parallel.mesh import data_member_mesh
from spark_ensemble_tpu.utils import persist


def _rings(n=2000, seed=0):
    """Two concentric rings: linearly inseparable by construction."""
    rng = np.random.RandomState(seed)
    r = np.where(rng.rand(n) < 0.5, 1.0, 2.5) + 0.1 * rng.randn(n)
    th = rng.rand(n) * 2 * np.pi
    X = np.stack([r * np.cos(th), r * np.sin(th)], 1).astype(np.float32)
    y = (r > 1.75).astype(np.float32)
    return X, y


def _nonlinear_reg(n=1500, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 3).astype(np.float32)
    y = (np.sin(X[:, 0]) + X[:, 1] ** 2 + 100.0).astype(np.float32)
    return X, y


def test_mlp_classifier_beats_linear_on_rings():
    X, y = _rings()
    mlp_acc = float(
        np.mean(np.asarray(MLPClassifier(max_iter=300).fit(X, y).predict(X)) == y)
    )
    lr_acc = float(
        np.mean(np.asarray(LogisticRegression().fit(X, y).predict(X)) == y)
    )
    assert mlp_acc > 0.95
    assert lr_acc < 0.65  # the dataset is linearly inseparable
    assert mlp_acc > lr_acc + 0.3


def test_mlp_regressor_fits_nonlinear_target():
    X, y = _nonlinear_reg()
    m = MLPRegressor(max_iter=400).fit(X, y)
    rmse = float(np.sqrt(np.mean((np.asarray(m.predict(X)) - y) ** 2)))
    const = float(np.sqrt(np.mean((y - y.mean()) ** 2)))
    assert rmse < 0.5 * const


def test_mlp_multiclass_probabilities():
    rng = np.random.RandomState(3)
    n, k = 1200, 4
    X = rng.randn(n, 5).astype(np.float32)
    centers = rng.randn(k, 5).astype(np.float32)
    y = np.argmax(X @ centers.T, axis=1).astype(np.float32)
    m = MLPClassifier(max_iter=250).fit(X, y)
    proba = np.asarray(m.predict_proba(X))
    assert proba.shape == (n, k)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)
    acc = float(np.mean(np.asarray(m.predict(X)) == y))
    assert acc > 0.9


def test_mlp_zero_weight_rows_do_not_affect_fit():
    """Zero-weight rows are invisible (the padding/out-of-bag contract
    every BaseLearner honors)."""
    X, y = _rings(800)
    rng = np.random.RandomState(1)
    X_noise = rng.randn(200, 2).astype(np.float32) * 10
    y_noise = rng.randint(0, 2, 200).astype(np.float32)
    est = MLPClassifier(max_iter=120, hidden_layer_sizes=(16,))
    m_clean = est.fit(X, y)
    m_padded = est.fit(
        np.concatenate([X, X_noise]),
        np.concatenate([y, y_noise]),
        sample_weight=np.concatenate(
            [np.ones(len(X)), np.zeros(200)]
        ).astype(np.float32),
    )
    p1 = np.asarray(m_clean.predict_proba(X))
    p2 = np.asarray(m_padded.predict_proba(X))
    # identical data views up to f32 reduction order in the feature stats
    np.testing.assert_allclose(p1, p2, atol=1e-3)


def test_mlp_feature_mask_equals_zeroed_columns():
    """Fitting with a subspace mask == fitting on X with masked columns
    zeroed (the reference's slice-projection semantics,
    `HasSubBag.scala:81-84`)."""
    import jax
    import jax.numpy as jnp

    X, y = _rings(600)
    X3 = np.concatenate([X, np.random.RandomState(5).randn(600, 1)], 1).astype(
        np.float32
    )
    est = MLPClassifier(max_iter=100, hidden_layer_sizes=(8,))
    ctx = est.make_fit_ctx(jnp.asarray(X3), 2)
    w = jnp.ones((600,))
    key = jax.random.PRNGKey(0)
    mask = jnp.asarray([1.0, 1.0, 0.0])
    p_masked = est.fit_from_ctx(ctx, jnp.asarray(y), w, mask, key)
    X0 = X3.copy()
    X0[:, 2] = 0.0
    ctx0 = est.make_fit_ctx(jnp.asarray(X0), 2)
    p_zeroed = est.fit_from_ctx(ctx0, jnp.asarray(y), w, mask, key)
    r1 = np.asarray(est.predict_raw_fn(p_masked, jnp.asarray(X3)))
    r2 = np.asarray(est.predict_raw_fn(p_zeroed, jnp.asarray(X0)))
    np.testing.assert_allclose(r1, r2, atol=1e-4)


@pytest.mark.slow
def test_mlp_mesh_fit_matches_single_device():
    """Standalone distributed fit: rows sharded over "data", gradients
    psum-ed — same model as single-device up to f32 reduction order."""
    rng = np.random.RandomState(0)
    n = 1003  # non-multiple of the data axis: exercises padding
    X = rng.randn(n, 4).astype(np.float32)
    y = (X[:, 0] * X[:, 1] > 0).astype(np.float32)
    est = MLPClassifier(max_iter=80, hidden_layer_sizes=(16,))
    p1 = np.asarray(est.fit(X, y).predict_proba(X))
    p2 = np.asarray(
        est.fit(X, y, mesh=data_member_mesh(8, member=2)).predict_proba(X)
    )
    np.testing.assert_allclose(p1, p2, atol=5e-3)


def test_mlp_as_ensemble_member():
    X, y = _rings(1200)
    bag = BaggingClassifier(
        base_learner=MLPClassifier(max_iter=100, hidden_layer_sizes=(16,)),
        num_base_learners=4,
    ).fit(X, y)
    assert float(np.mean(np.asarray(bag.predict(X)) == y)) > 0.9

    st = StackingClassifier(
        base_learners=[
            MLPClassifier(max_iter=100, hidden_layer_sizes=(16,)),
            LogisticRegression(),
        ],
        stacker=LogisticRegression(),
    ).fit(X, y)
    assert float(np.mean(np.asarray(st.predict(X)) == y)) > 0.9


def test_mlp_as_gbm_base_learner():
    rng = np.random.RandomState(0)
    X = rng.randn(1000, 4).astype(np.float32)
    y = (np.sin(X[:, 0]) + X[:, 1] ** 2).astype(np.float32)
    g = GBMRegressor(
        base_learner=MLPRegressor(max_iter=60, hidden_layer_sizes=(8,)),
        num_base_learners=3,
        learning_rate=0.5,
    ).fit(X, y)
    rmse = float(np.sqrt(np.mean((np.asarray(g.predict(X)) - y) ** 2)))
    const = float(np.sqrt(np.mean((y - y.mean()) ** 2)))
    assert rmse < 0.7 * const


def test_mlp_persist_round_trip(tmp_path):
    X, y = _rings(600)
    m = MLPClassifier(max_iter=80, hidden_layer_sizes=(8,)).fit(X, y)
    m.save(str(tmp_path / "m"))
    m2 = persist.load(str(tmp_path / "m"))
    np.testing.assert_allclose(
        np.asarray(m2.predict_proba(X)), np.asarray(m.predict_proba(X))
    )
    # hidden_layer_sizes round-trips through JSON as a list; the topology
    # must still match
    assert tuple(m2.hidden_layer_sizes) == tuple(m.hidden_layer_sizes)
