"""Telemetry tests (docs/telemetry.md): metric primitives, the per-fit
event stream on all four ensemble families, JSONL round-trip, the report
CLI, and the disabled-path contract (no events, same programs)."""

import gzip
import importlib.util
import json
import math
import os

import jax
import numpy as np
import pytest

import spark_ensemble_tpu as se
from spark_ensemble_tpu.telemetry import (
    FitTelemetry,
    MetricsRegistry,
    record_fits,
)
from spark_ensemble_tpu.telemetry.events import TELEMETRY_ENV
from spark_ensemble_tpu.telemetry.registry import StreamingHistogram

_REPORT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "telemetry_report.py",
)


def _load_report():
    spec = importlib.util.spec_from_file_location("telemetry_report", _REPORT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _data(n=200, d=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


# ---------------------------------------------------------------------------
# MetricsRegistry primitives
# ---------------------------------------------------------------------------


def test_counter_and_gauge_semantics():
    reg = MetricsRegistry()
    c = reg.counter("fits")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert reg.counter("fits") is c  # get-or-create returns the same metric
    g = reg.gauge("bytes")
    assert g.value is None
    g.set(7.0)
    g.set(3.0)
    assert g.value == 3.0  # last write wins
    snap = reg.snapshot()
    assert snap["fits"] == {"type": "counter", "value": 5}
    assert snap["bytes"] == {"type": "gauge", "value": 3.0}
    assert reg.names() == ["bytes", "fits"]


def test_registry_rejects_kind_mismatch():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("x")
    with pytest.raises(TypeError):
        reg.histogram("x")


def test_streaming_histogram_summary_and_quantiles():
    h = StreamingHistogram("t")
    assert h.quantile(0.5) is None and h.summary() == {
        "type": "histogram", "count": 0,
    }
    values = [0.001, 0.002, 0.004, 0.008, 1.0]
    for v in values:
        h.record(v)
    s = h.summary()
    assert s["count"] == 5
    assert s["min"] == 0.001 and s["max"] == 1.0
    assert math.isclose(s["mean"], sum(values) / 5)
    # log2 buckets: quantile answers are upper bucket edges, within 2x
    assert 0.002 <= s["p50"] <= 0.008
    assert s["p99"] == 1.0  # clamped to the observed max
    h.record(-1.0)  # non-positive values clamp into the bottom bucket
    assert h.count == 6


def test_round_timer_fences_device_work():
    reg = MetricsRegistry()
    t = reg.timer("round")
    f = jax.jit(lambda a: (a @ a).sum())
    x = jax.numpy.ones((64, 64))
    t.start()
    out = f(x)
    elapsed = t.stop(out)
    assert elapsed > 0.0
    # the fence blocked on the result before the clock read
    assert getattr(out, "is_ready", lambda: True)()
    hist = reg.histogram("round")  # timers share the named histogram
    assert hist.count == 1
    out2 = t.time(f, x)
    assert hist.count == 2 and float(out2) == float(out)
    with pytest.raises(RuntimeError, match="before start"):
        t.stop()
    # timers are per-caller handles over a shared histogram, not shared state
    assert reg.timer("round") is not reg.timer("round")


# ---------------------------------------------------------------------------
# event stream: sinks + JSONL round-trip
# ---------------------------------------------------------------------------


def test_jsonl_roundtrip_and_phase_sum(tmp_path):
    path = str(tmp_path / "fit.jsonl")
    X, y = _data()
    model = se.GBMRegressor(num_base_learners=4, telemetry_path=path).fit(X, y)
    events = [json.loads(line) for line in open(path)]
    kinds = [e["event"] for e in events]
    assert kinds[0] == "fit_start" and kinds[-1] == "fit_end"
    fit_end = events[-1]
    assert fit_end["family"] == "GBMRegressor"
    # phase map sums EXACTLY to the measured wall (host_other remainder)
    assert math.isclose(
        sum(fit_end["phases"].values()), fit_end["wall_s"], rel_tol=1e-6
    )
    ends = [e for e in events if e["event"] == "round_end"]
    assert len(ends) == fit_end["rounds"] == 4
    rounds = [e["round"] for e in ends]
    assert rounds == sorted(rounds) and len(set(rounds)) == len(rounds)
    assert all(e["duration_s"] > 0 for e in ends)
    assert fit_end["compile_count"] >= 0
    # the same history the JSONL carries is attached to the model
    np.testing.assert_array_equal(model.fit_history_["round"], rounds)


def test_env_var_sink(tmp_path, monkeypatch):
    path = str(tmp_path / "env.jsonl")
    monkeypatch.setenv(TELEMETRY_ENV, path)
    X, y = _data()
    se.BaggingRegressor(num_base_learners=3).fit(X, y)
    events = [json.loads(line) for line in open(path)]
    assert events[0]["event"] == "fit_start"
    assert events[0]["family"] == "BaggingRegressor"


def test_record_fits_in_memory_recorder(monkeypatch):
    monkeypatch.delenv(TELEMETRY_ENV, raising=False)
    X, y = _data()
    with record_fits() as rec:
        se.GBMRegressor(num_base_learners=2).fit(X, y)
        se.BaggingRegressor(num_base_learners=2).fit(X, y)
    fits = rec.fits()
    assert len(fits) == 2
    for fit_events in fits.values():
        assert fit_events[0]["event"] == "fit_start"
        assert fit_events[-1]["event"] == "fit_end"
    # the context is scoped: fits outside it record nothing new
    n = len(rec.events)
    se.GBMRegressor(num_base_learners=2).fit(X, y)
    assert len(rec.events) == n


# ---------------------------------------------------------------------------
# fit_history_ on every family
# ---------------------------------------------------------------------------


def _families():
    X, y = _data(n=250, d=5)
    return [
        ("gbm", se.GBMRegressor(num_base_learners=4), X, y),
        (
            "boosting",
            se.BoostingRegressor(
                base_learner=se.DecisionTreeRegressor(max_depth=3),
                num_base_learners=3,
            ),
            X, y,
        ),
        ("bagging", se.BaggingRegressor(num_base_learners=3), X, y),
        (
            "stacking",
            se.StackingRegressor(
                base_learners=[
                    se.DecisionTreeRegressor(max_depth=3),
                    se.LinearRegression(),
                ],
                stacker=se.LinearRegression(),
            ),
            X, y,
        ),
    ]


@pytest.mark.parametrize(
    "name,est,X,y", _families(), ids=lambda v: v if isinstance(v, str) else ""
)
def test_fit_history_present_and_monotone(name, est, X, y, monkeypatch):
    monkeypatch.delenv(TELEMETRY_ENV, raising=False)
    with record_fits():
        model = est.fit(X, y)
    h = model.fit_history_
    assert set(h) == {"round", "learner_index", "duration_s", "loss",
                      "step_size"}
    assert len(h["round"]) > 0
    assert all(len(h[k]) == len(h["round"]) for k in h)
    assert np.all(np.diff(h["round"]) >= 0), f"{name}: rounds not monotone"
    assert np.all(h["duration_s"] >= 0)


def test_gbm_history_carries_losses_and_step_sizes(monkeypatch):
    monkeypatch.delenv(TELEMETRY_ENV, raising=False)
    X, y = _data()
    # losses are per-round validation errors, so hold out a validation slice
    vi = np.zeros(len(y), bool)
    vi[::4] = True
    with record_fits():
        model = se.GBMRegressor(
            num_base_learners=5, num_rounds=5, validation_tol=1e-6
        ).fit(X, y, validation_indicator=vi)
    h = model.fit_history_
    assert len(h["round"]) > 0
    assert np.all(np.isfinite(h["loss"]))
    assert np.all(np.isfinite(h["step_size"]))
    assert np.all(np.diff(h["round"]) == 1)  # strictly consecutive


# ---------------------------------------------------------------------------
# disabled path
# ---------------------------------------------------------------------------


def test_disabled_fit_emits_nothing_and_attaches_empty_history(monkeypatch):
    monkeypatch.delenv(TELEMETRY_ENV, raising=False)
    a = FitTelemetry.start(family="x")
    b = FitTelemetry.start(family="y")
    assert a is b and not a.enabled  # shared no-op singleton, no allocation
    assert a.events() == []
    X, y = _data()
    model = se.GBMRegressor(num_base_learners=2).fit(X, y)
    h = model.fit_history_  # contract: always present
    assert all(len(v) == 0 for v in h.values())


def test_compile_counting_rides_fit_end(monkeypatch):
    monkeypatch.delenv(TELEMETRY_ENV, raising=False)
    # a shape no other test uses forces at least one fresh backend compile
    X, y = _data(n=331, d=7, seed=3)
    with record_fits() as rec:
        se.GBMRegressor(num_base_learners=2).fit(X, y)
    fit_end = rec.events[-1]
    assert fit_end["event"] == "fit_end"
    assert fit_end["compile_count"] >= 1
    assert fit_end["compile_s"] > 0.0


# ---------------------------------------------------------------------------
# report CLI + shared machine-readable format
# ---------------------------------------------------------------------------


def test_report_cli_renders_stream(tmp_path, capsys):
    path = str(tmp_path / "fit.jsonl")
    X, y = _data()
    se.GBMRegressor(num_base_learners=3, telemetry_path=path).fit(X, y)
    report = _load_report()
    out_jsonl = str(tmp_path / "phases.jsonl")
    assert report.main([path, "--jsonl", out_jsonl]) == 0
    out = capsys.readouterr().out
    assert "total_ms" in out and "GBMRegressor" in out
    assert "wall:" in out and "compiles:" in out and "rounds: 3" in out
    records = [json.loads(line) for line in open(out_jsonl)]
    assert records and set(records[0]) == {"op", "total_us", "count", "share"}
    assert math.isclose(sum(r["share"] for r in records), 1.0, rel_tol=1e-6)
    # --diff consumes the same format this tool (and profiling) emits
    assert report.main([path, "--diff", out_jsonl]) == 0
    assert "delta%" in capsys.readouterr().out


def test_report_cli_empty_stream_fails(tmp_path, capsys):
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    report = _load_report()
    assert report.main([str(empty)]) == 1


def test_profiling_jsonl_mode(tmp_path, capsys):
    from spark_ensemble_tpu.utils import profiling

    capture = tmp_path / "prof" / "plugins" / "profile" / "2026_08_05"
    capture.mkdir(parents=True)
    trace = {
        "traceEvents": [
            {"ph": "X", "dur": 300.0, "name": "fusion.1"},
            {"ph": "X", "dur": 100.0, "name": "fusion.1"},
            {"ph": "X", "dur": 600.0, "name": "dot.2"},
            {"ph": "X", "dur": 999.0, "name": "Thread 1"},  # host row, dropped
            {"ph": "M", "name": "metadata"},  # not a slice
        ]
    }
    with gzip.open(capture / "host.trace.json.gz", "wt") as f:
        json.dump(trace, f)
    out_jsonl = str(tmp_path / "ops.jsonl")
    assert profiling.main([str(tmp_path / "prof"), "--jsonl", out_jsonl]) == 0
    assert "total_ms" in capsys.readouterr().out
    records = [json.loads(line) for line in open(out_jsonl)]
    by_op = {r["op"]: r for r in records}
    assert set(by_op) == {"fusion.1", "dot.2"}
    assert by_op["fusion.1"]["total_us"] == 400.0
    assert by_op["fusion.1"]["count"] == 2
    assert math.isclose(by_op["dot.2"]["share"], 0.6)


def test_report_cli_renders_shard_io_line(tmp_path, capsys):
    """Streaming fits carry shard_load/shard_prefetch_hit/shard_wait_us
    events; the report folds them into one shard-I/O share line."""
    from spark_ensemble_tpu.data import write_shards
    from spark_ensemble_tpu.models.tree import DecisionTreeRegressor

    path = str(tmp_path / "fit.jsonl")
    X, y = _data()
    store = write_shards(X, str(tmp_path / "store"), max_bins=16,
                         shard_rows=40)
    se.GBMRegressor(
        num_base_learners=3, telemetry_path=path,
        base_learner=DecisionTreeRegressor(
            hist="stream", max_bins=16, max_depth=2
        ),
    ).fit_streaming(store, y)
    report = _load_report()
    assert report.main([path]) == 0
    out = capsys.readouterr().out
    assert "shard I/O:" in out
    assert "prefetch hits" in out
    assert "wait share" in out


# ---------------------------------------------------------------------------
# live snapshot sources + the process-global metric key contract
# ---------------------------------------------------------------------------


def test_register_source_renders_live_value_in_snapshot():
    reg = MetricsRegistry()
    reg.counter("x").inc(3)
    state = {"requests": 0}
    reg.register_source("svc/live", lambda: dict(state))
    state["requests"] = 7  # the source is LIVE: read at snapshot time
    snap = reg.snapshot()
    assert snap["x"] == {"type": "counter", "value": 3}
    assert snap["svc/live"] == {
        "type": "source", "value": {"requests": 7},
    }
    # re-registering replaces; unregistering removes
    reg.register_source("svc/live", lambda: "v2")
    assert reg.snapshot()["svc/live"]["value"] == "v2"
    reg.unregister_source("svc/live")
    assert "svc/live" not in reg.snapshot()
    reg.unregister_source("svc/live")  # idempotent


def test_source_error_is_captured_not_raised():
    reg = MetricsRegistry()

    def _boom():
        raise RuntimeError("owner is gone")

    reg.register_source("svc/bad", _boom)
    snap = reg.snapshot()
    assert snap["svc/bad"]["type"] == "source"
    assert "RuntimeError: owner is gone" in snap["svc/bad"]["error"]
    assert "value" not in snap["svc/bad"]


def test_shard_io_mirrors_into_global_metrics(tmp_path):
    """Satellite contract (docs/tracing.md): every prefetcher sweep lands
    in ``global_metrics()`` under STABLE ``data/shard_*`` keys, telemetry
    sink or not — the process snapshot is the one-stop operator view."""
    from spark_ensemble_tpu.data import ShardPrefetcher, write_shards
    from spark_ensemble_tpu.telemetry import global_metrics

    X, _ = _data()
    store = write_shards(X, str(tmp_path / "store"), max_bins=16,
                         shard_rows=64)
    g = global_metrics()
    loads0 = g.counter("data/shard_loads").value
    bytes0 = g.counter("data/shard_bytes").value
    with ShardPrefetcher(store, depth=1, to_device=False) as pf:
        taken = sum(1 for _ in pf.sweep())
        stats = pf.take_stats()
    assert taken == store.num_shards
    # take_stats drains the per-fit ledger...
    assert stats["loads"] == store.num_shards and stats["bytes"] > 0
    assert stats["hits"] + stats["misses"] == store.num_shards
    assert pf.take_stats()["loads"] == 0
    # ...while the global mirror accumulates under the pinned keys
    snap = g.snapshot()
    for key in ("data/shard_loads", "data/shard_bytes",
                "data/shard_load_s", "data/shard_wait_s"):
        assert key in snap, f"stable snapshot key {key} missing"
    assert snap["data/shard_loads"]["value"] - loads0 == store.num_shards
    assert snap["data/shard_bytes"]["value"] - bytes0 == stats["bytes"]
    assert snap["data/shard_load_s"]["type"] == "histogram"
    hits = snap.get("data/shard_prefetch_hits", {}).get("value", 0)
    misses = snap.get("data/shard_prefetch_misses", {}).get("value", 0)
    assert hits + misses >= store.num_shards
