"""Linear-leaf tree tests (piece-wise linear regression trees,
arXiv:1802.05640 — an extension beyond the reference's learner set; see
models/linear_tree.py)."""

import numpy as np
import pytest

import spark_ensemble_tpu as se
from spark_ensemble_tpu.parallel.mesh import data_member_mesh
from spark_ensemble_tpu.utils import persist


def _piecewise_linear(n=3000, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 4).astype(np.float32)
    y = (
        np.where(X[:, 0] > 0, 2.0 * X[:, 1] + 1.0, -1.5 * X[:, 1] + 3.0 * X[:, 2])
        + 0.05 * rng.randn(n)
    ).astype(np.float32)
    return X, y


def _rmse(m, X, y):
    return float(np.sqrt(np.mean((np.asarray(m.predict(X)) - y) ** 2)))


def test_linear_leaves_beat_constant_leaves_at_equal_depth():
    X, y = _piecewise_linear()
    lt = se.LinearTreeRegressor(max_depth=2).fit(X, y)
    dt = se.DecisionTreeRegressor(max_depth=2).fit(X, y)
    assert _rmse(lt, X, y) < 0.7 * _rmse(dt, X, y)


def test_gbm_with_linear_leaf_members_needs_fewer_rounds():
    """The paper's claim: linear leaves capture smooth trends that cost
    constant-leaf GBM many rounds."""
    X, y = _piecewise_linear()
    g_lt = se.GBMRegressor(
        base_learner=se.LinearTreeRegressor(max_depth=2),
        num_base_learners=4, learning_rate=0.5,
    ).fit(X, y)
    g_dt = se.GBMRegressor(
        base_learner=se.DecisionTreeRegressor(max_depth=2),
        num_base_learners=4, learning_rate=0.5,
    ).fit(X, y)
    assert _rmse(g_lt, X, y) < _rmse(g_dt, X, y)


def test_high_min_leaf_weight_falls_back_to_constant_tree():
    """Leaves without enough support keep the constant tree value — with
    an unreachable support bar the model must equal the plain tree."""
    X, y = _piecewise_linear(800)
    lt = se.LinearTreeRegressor(max_depth=3, min_leaf_weight=1e9).fit(X, y)
    dt = se.DecisionTreeRegressor(max_depth=3).fit(X, y)
    np.testing.assert_allclose(
        np.asarray(lt.predict(X)), np.asarray(dt.predict(X)), atol=1e-4
    )


def test_linear_tree_persist_and_importances(tmp_path):
    X, y = _piecewise_linear(1000)
    m = se.LinearTreeRegressor(max_depth=2).fit(X, y)
    m.save(str(tmp_path / "m"))
    m2 = persist.load(str(tmp_path / "m"))
    np.testing.assert_allclose(
        np.asarray(m2.predict(X)), np.asarray(m.predict(X))
    )
    fi = m.feature_importances_
    assert abs(fi.sum() - 1.0) < 1e-9


@pytest.mark.slow
def test_linear_tree_mesh_fit_matches_single_device():
    """SPMD: tree histograms AND the leaf normal equations psum over the
    data axis; the distributed fit matches single-device."""
    X, y = _piecewise_linear(1003)  # non-multiple of the data axis
    est = se.LinearTreeRegressor(max_depth=2)
    p1 = np.asarray(est.fit(X, y).predict(X))
    p2 = np.asarray(
        est.fit(X, y, mesh=data_member_mesh(8, member=2)).predict(X)
    )
    np.testing.assert_allclose(p1, p2, atol=5e-3)


def test_linear_tree_as_bagging_member():
    X, y = _piecewise_linear(1500)
    bag = se.BaggingRegressor(
        base_learner=se.LinearTreeRegressor(max_depth=2), num_base_learners=4
    ).fit(X, y)
    const = float(np.sqrt(np.var(y)))
    assert _rmse(bag, X, y) < 0.6 * const


def test_normalized_weights_keep_linear_leaves():
    """Boosting normalizes weights to sum 1 before member fits; the
    effective-row support bar must not silently degrade every leaf to a
    constant (absolute thresholds did)."""
    X, y = _piecewise_linear(1200)
    w = np.full(len(X), 1.0 / len(X), np.float32)  # sums to 1
    m = se.LinearTreeRegressor(max_depth=2).fit(X, y, sample_weight=w)
    m_unit = se.LinearTreeRegressor(max_depth=2).fit(X, y)
    # metric-level equivalence: rescaling all weights by 1/n flips f32
    # near-tied split argmaxes (the documented tie behavior), so compare
    # fit quality, not pointwise predictions
    assert abs(_rmse(m, X, y) - _rmse(m_unit, X, y)) < 0.05 * _rmse(
        m_unit, X, y
    ) + 1e-6
    dt = se.DecisionTreeRegressor(max_depth=2).fit(X, y, sample_weight=w)
    assert _rmse(m, X, y) < 0.7 * _rmse(dt, X, y)


def test_boosting_with_linear_tree_members():
    X, y = _piecewise_linear(1500)
    b = se.BoostingRegressor(
        base_learner=se.LinearTreeRegressor(max_depth=2), num_base_learners=4
    ).fit(X, y)
    const = float(np.sqrt(np.var(y)))
    assert _rmse(b, X, y) < 0.5 * const


def test_linear_tree_depth_capped():
    import pytest as _p

    with _p.raises(ValueError):
        se.LinearTreeRegressor(max_depth=12)


def test_nonfinite_features_stay_finite_and_fused_members_match_vmap():
    """NaN/inf features clamp like predict_tree (no NaN leak through the
    leaf linear term), and the fused member predict equals the per-member
    path."""
    import jax
    import jax.numpy as jnp

    X, y = _piecewise_linear(800)
    m = se.LinearTreeRegressor(max_depth=2).fit(X, y)
    Xbad = X[:50].copy()
    Xbad[0, 0] = np.nan
    Xbad[1, 1] = np.inf
    Xbad[2, 2] = -np.inf
    assert np.isfinite(np.asarray(m.predict(Xbad))).all()

    bag = se.BaggingRegressor(
        base_learner=se.LinearTreeRegressor(max_depth=2), num_base_learners=3
    ).fit(X, y)
    members = bag.params["members"]
    est = se.LinearTreeRegressor(max_depth=2)
    fused = np.asarray(est.predict_many_fn(members, jnp.asarray(X[:200])))
    sliced = np.stack(
        [
            np.asarray(
                est.predict_fn(
                    jax.tree_util.tree_map(lambda x: x[i], members),
                    jnp.asarray(X[:200]),
                )
            )
            for i in range(3)
        ]
    )
    np.testing.assert_allclose(fused, sliced, rtol=1e-5, atol=1e-5)


def test_zero_min_leaf_weight_empty_leaves_fall_back():
    """min_leaf_weight=0: a training-empty leaf must keep the constant
    fallback, not an all-zero linear model."""
    n = 512
    X = np.zeros((n, 3), np.float32)
    X[: n // 2, 0] = 1.0
    y = (10.0 + X[:, 0]).astype(np.float32)
    m = se.LinearTreeRegressor(max_depth=3, min_leaf_weight=0.0).fit(X, y)
    # every training point predicts near its value; a probe row routed to
    # an empty region must fall back to an ancestor mean (~10-11), not 0
    probe = np.full((1, 3), 5.0, np.float32)
    p = float(np.asarray(m.predict(probe))[0])
    assert 9.0 < p < 12.0, p
