"""Line-search optimizer tests."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_ensemble_tpu.ops.linesearch import brent_minimize, projected_newton_box


def test_brent_quadratic():
    x = brent_minimize(lambda a: (a - 3.7) ** 2, 0.0, 100.0, tol=1e-6)
    assert float(x) == pytest.approx(3.7, abs=1e-4)


def test_brent_boundary_minimum():
    x = brent_minimize(lambda a: a * 2.0 + 1.0, 0.0, 100.0, tol=1e-6)
    assert float(x) == pytest.approx(0.0, abs=1e-3)


def test_brent_nonconvex_finds_low_value():
    f = lambda a: jnp.sin(a) + 0.01 * (a - 20.0) ** 2
    x = brent_minimize(f, 0.0, 100.0, tol=1e-6)
    # must reach a point no worse than a coarse grid scan
    grid = jnp.linspace(0.0, 100.0, 2000)
    assert float(f(x)) <= float(jnp.min(jax.vmap(f)(grid))) + 0.3


def test_projected_newton_interior():
    A = jnp.asarray([[2.0, 0.3], [0.3, 1.0]])
    b = jnp.asarray([1.0, 2.0])
    f = lambda x: 0.5 * x @ A @ x - b @ x
    x = projected_newton_box(f, jnp.ones(2))
    expect = jnp.linalg.solve(A, b)
    assert np.allclose(np.asarray(x), np.asarray(expect), atol=1e-4)


def test_projected_newton_active_bound():
    # unconstrained minimum at (-1, 2): the box clips x0 to 0
    f = lambda x: (x[0] + 1.0) ** 2 + (x[1] - 2.0) ** 2
    x = projected_newton_box(f, jnp.ones(2))
    assert float(x[0]) == pytest.approx(0.0, abs=1e-5)
    assert float(x[1]) == pytest.approx(2.0, abs=1e-4)


import jax  # noqa: E402  (used by test_brent_nonconvex_finds_low_value)
