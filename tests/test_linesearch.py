"""Line-search optimizer tests."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_ensemble_tpu.ops.linesearch import brent_minimize, projected_newton_box


def test_brent_quadratic():
    x = brent_minimize(lambda a: (a - 3.7) ** 2, 0.0, 100.0, tol=1e-6)
    assert float(x) == pytest.approx(3.7, abs=1e-4)


def test_brent_boundary_minimum():
    x = brent_minimize(lambda a: a * 2.0 + 1.0, 0.0, 100.0, tol=1e-6)
    assert float(x) == pytest.approx(0.0, abs=1e-3)


def test_brent_nonconvex_finds_low_value():
    f = lambda a: jnp.sin(a) + 0.01 * (a - 20.0) ** 2
    x = brent_minimize(f, 0.0, 100.0, tol=1e-6)
    # must reach a point no worse than a coarse grid scan
    grid = jnp.linspace(0.0, 100.0, 2000)
    assert float(f(x)) <= float(jnp.min(jax.vmap(f)(grid))) + 0.3


def test_projected_newton_interior():
    A = jnp.asarray([[2.0, 0.3], [0.3, 1.0]])
    b = jnp.asarray([1.0, 2.0])
    f = lambda x: 0.5 * x @ A @ x - b @ x
    x = projected_newton_box(f, jnp.ones(2))
    expect = jnp.linalg.solve(A, b)
    assert np.allclose(np.asarray(x), np.asarray(expect), atol=1e-4)


def test_projected_newton_active_bound():
    # unconstrained minimum at (-1, 2): the box clips x0 to 0
    f = lambda x: (x[0] + 1.0) ** 2 + (x[1] - 2.0) ** 2
    x = projected_newton_box(f, jnp.ones(2))
    assert float(x[0]) == pytest.approx(0.0, abs=1e-5)
    assert float(x[1]) == pytest.approx(2.0, abs=1e-4)


import jax  # noqa: E402  (used by test_brent_nonconvex_finds_low_value)


@pytest.mark.slow
def test_closed_form_linesearch_grad_hess_matches_autodiff():
    """loss.linesearch_grad_hess == jax.grad/jax.hessian of the step-size
    objective, for every hessian-bearing loss; the Newton solve must land
    on the same optimum either way."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from spark_ensemble_tpu.ops import losses as L
    from spark_ensemble_tpu.ops.linesearch import projected_newton_box

    rng = np.random.RandomState(0)
    n = 300
    for loss in (L.LogLoss(5), L.ExponentialLoss(), L.BernoulliLoss(),
                 L.SquaredLoss(), L.LogCoshLoss()):
        k = loss.dim
        if loss.name in ("exponential", "bernoulli"):
            y = (rng.rand(n) > 0.5).astype(np.float32)
        elif loss.name == "logloss":
            y = rng.randint(0, 5, n).astype(np.float32)
        else:
            y = rng.randn(n).astype(np.float32)
        y_enc = loss.encode_label(jnp.asarray(y))
        pred = jnp.asarray(rng.randn(n, k).astype(np.float32))
        dirs = jnp.asarray(rng.randn(n, k).astype(np.float32))
        bw = jnp.asarray(rng.poisson(1.0, n).astype(np.float32))

        def phi(a):
            return jnp.sum(bw * loss.loss(y_enc, pred + a[None, :] * dirs))

        a0 = jnp.asarray(rng.rand(k).astype(np.float32))
        g_auto = jax.grad(phi)(a0)
        h_auto = jax.hessian(phi)(a0)
        g_cf, h_cf = loss.linesearch_grad_hess(
            y_enc, pred + a0[None, :] * dirs, dirs, bw
        )
        assert np.allclose(np.asarray(g_cf), np.asarray(g_auto), rtol=2e-3, atol=2e-3), loss.name
        assert np.allclose(np.asarray(h_cf), np.asarray(h_auto), rtol=2e-3, atol=2e-3), loss.name

        x_auto = projected_newton_box(phi, jnp.ones(k), max_iter=15)
        gh = lambda a: loss.linesearch_grad_hess(
            y_enc, pred + a[None, :] * dirs, dirs, bw
        )
        x_cf = projected_newton_box(phi, jnp.ones(k), max_iter=15, grad_hess=gh)
        assert np.allclose(np.asarray(x_auto), np.asarray(x_cf), atol=5e-3), loss.name


def test_backtracking_recovers_from_nan_objective():
    """A NaN objective at the full Newton step (overflowing loss) must keep
    halving, not abort the line search (NaN fails `fc >= fx` comparisons)."""
    import jax.numpy as jnp
    import numpy as np

    from spark_ensemble_tpu.ops.linesearch import projected_newton_box

    def phi(a):
        v = jnp.sum((a - 0.3) ** 2)
        return jnp.where(jnp.max(a) > 2.0, jnp.nan, v)

    # tiny reported hessian forces a huge overshooting Newton step into the
    # NaN region at t=1; backtracking must recover a finite decrease
    gh = lambda a: (2.0 * (a - 0.3), 0.005 * jnp.eye(2))
    x = np.asarray(
        projected_newton_box(
            phi, jnp.full((2,), 0.1), max_iter=10, grad_hess=gh
        )
    )
    assert np.all(np.isfinite(x))
    assert np.all(np.abs(x - 0.3) < 0.1), x


def test_squared_loss_closed_form_matches_brent():
    """GBM's squared-loss line search is now closed form (phi is exactly
    quadratic); the minimizer must match what Brent finds on the same
    objective to within its tolerance."""
    import jax.numpy as jnp
    import numpy as np

    from spark_ensemble_tpu.ops.linesearch import brent_minimize

    rng = np.random.RandomState(0)
    for trial in range(5):
        n = 500
        bw = rng.poisson(1.0, n).astype(np.float32)
        res = rng.randn(n).astype(np.float32) * 3
        direction = (res * 0.5 + rng.randn(n)).astype(np.float32)
        bwj, resj, dirj = map(jnp.asarray, (bw, res, direction))

        def phi(a):
            return jnp.sum(bwj * (resj - a * dirj) ** 2 / 2.0)

        a_brent = float(brent_minimize(phi, 0.0, 100.0, tol=1e-6, max_iter=100))
        num = float(np.sum(bw * direction * res))
        den = float(np.sum(bw * direction * direction))
        a_closed = min(max(num / den, 0.0), 100.0)
        assert abs(a_brent - a_closed) < 1e-3, (trial, a_brent, a_closed)


@pytest.mark.slow
def test_warm_start_alpha_trajectory_matches_cold_start():
    """The GBM line-search warm start (models/gbm.py round_core carries
    alpha_ws across rounds) is a convergence-SPEED device only: on each
    round's objective, a solve warm-started from the previous round's
    converged alphas and a cold solve from all-ones must land on the same
    step sizes within tol.  Emulates consecutive round_core line searches
    exactly — same phi / closed-form grad_hess, same optimizer config
    (max_iter 25, tol 1e-6) — over 5 drifting logloss rounds whose
    directions approximate fitted-tree outputs (noisy negative gradients)."""
    from spark_ensemble_tpu.ops.losses import LogLoss

    rng = np.random.RandomState(7)
    n, K, lr = 400, 4, 0.3
    loss = LogLoss(K)
    y = rng.randint(0, K, n).astype(np.float32)
    y_enc = loss.encode_label(jnp.asarray(y))
    bag_w = jnp.asarray(rng.poisson(1.0, n).astype(np.float32))
    pred = jnp.zeros((n, K), jnp.float32)
    alpha_ws = jnp.ones((K,), jnp.float32)
    for rnd in range(5):
        g = loss.gradient(y_enc, pred)
        directions = -g + 0.05 * jnp.asarray(
            rng.randn(n, K).astype(np.float32)
        )

        def phi(a, pred=pred, directions=directions):
            return jnp.sum(
                bag_w * loss.loss(y_enc, pred + a[None, :] * directions)
            )

        def gh(a, pred=pred, directions=directions):
            return loss.linesearch_grad_hess(
                y_enc, pred + a[None, :] * directions, directions, bag_w
            )

        warm = projected_newton_box(
            phi, alpha_ws, max_iter=25, tol=1e-6, grad_hess=gh
        )
        if rnd > 0:  # round 0's warm start IS all-ones; nothing to compare
            cold = projected_newton_box(
                phi, jnp.ones((K,), jnp.float32), max_iter=25, tol=1e-6,
                grad_hess=gh,
            )
            np.testing.assert_allclose(
                np.asarray(warm), np.asarray(cold), rtol=2e-3, atol=5e-4,
                err_msg=f"round {rnd}: warm/cold step sizes diverged",
            )
            # the objective values agree even tighter than the argmins
            assert float(phi(warm)) == pytest.approx(
                float(phi(cold)), rel=1e-5
            )
        alpha_ws = warm
        pred = pred + lr * warm[None, :] * directions
