"""Pod-scope trace stitching (telemetry/podview.py): clock-offset
round-trips on synthetic two-host streams with KNOWN skew, stitch
rewrite rules, cross-host flow resolution, and straggler attribution —
plus multi-input CLI smokes for both tools."""

import importlib.util
import json
import os
import sys

from spark_ensemble_tpu.telemetry import podview

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _host_stream(h, skew, steps_by_round, jitter=None, flow_out=None,
                 flow_in=None, digest="d1", stalls=()):
    """A synthetic per-host stream of one distributed fit: hosts cross
    the same TRUE barrier walls, but each records them on its own clock
    (``true + skew``).  ``steps_by_round[r]`` is this host's sweep-step
    wall for round r; fetch pads every host to the common barrier."""
    jitter = jitter or [0.0] * 8
    fid = f"fit_h{h}"
    ev = [
        {"event": "fit_start", "fit_id": fid, "family": "GBM",
         "ts": 99.0 + skew},
        {"event": "dist_config", "fit_id": fid, "process": h, "hosts": 2,
         "positions": 2, "ts": 99.5 + skew},
        # barrier 1: the manifest-agreement all_gather returns at true
        # wall 100.0 on every host
        {"event": "dist_manifest_agreed", "fit_id": fid,
         "ts": 100.0 + skew + jitter[0], "digest": digest},
    ]
    slowest = max(steps_by_round)
    for r, steps in enumerate(steps_by_round):
        # barrier: the blocking reduce fetch returns at the same true
        # instant on every host — the slowest host's steps bound it
        barrier = 101.0 + r + slowest
        fetch = barrier - (101.0 + r + steps)
        ev.append({
            "event": "span", "name": "dist_level_0",
            "trace_id": f"t{h}", "span_id": f"L{r}", "parent_id": "",
            "ts": 101.0 + r + skew + jitter[1 + r],
            "dur_s": steps + fetch + 0.01,
            "pid": 1000 + h, "thread": f"host{h}", "fit_id": fid,
            "steps_s": steps, "fetch_s": fetch, "round": r,
        })
    for site, seconds in stalls:
        ev.append({"event": "host_stalled", "fit_id": fid,
                   "ts": 101.2 + skew, "victim": h, "site": site,
                   "seconds": seconds})
    if flow_out is not None:
        ev.append({
            "event": "span", "name": "host_preempt",
            "trace_id": f"t{h}", "span_id": "pre", "parent_id": "",
            "ts": 103.0 + skew, "dur_s": 0.0, "pid": 1000 + h,
            "thread": f"host{h}", "fit_id": fid, "flow_out": [flow_out],
        })
    if flow_in is not None:
        ev.append({
            "event": "span", "name": "rewind",
            "trace_id": f"t{h}", "span_id": "rew", "parent_id": "",
            "ts": 103.5 + skew, "dur_s": 0.0, "pid": 1000 + h,
            "thread": f"host{h}", "fit_id": fid, "flow_in": flow_in,
        })
    return ev


def test_offsets_recover_known_skew():
    streams = [
        _host_stream(0, 0.0, [0.05, 0.05]),
        _host_stream(1, 3.7, [0.05, 0.05]),
    ]
    offsets = podview.estimate_offsets(streams)
    assert offsets[0] == 0.0
    assert abs(offsets[1] - 3.7) < 1e-9


def test_offsets_tolerate_barrier_jitter():
    """Hosts do not unblock at EXACTLY the same instant; the median over
    matched barriers must still land within tolerance."""
    streams = [
        _host_stream(0, 0.0, [0.05, 0.05, 0.05],
                     jitter=[0.002, -0.004, 0.001, 0.003]),
        _host_stream(1, -1.25, [0.05, 0.05, 0.05],
                     jitter=[-0.003, 0.004, -0.002, 0.001]),
    ]
    offsets = podview.estimate_offsets(streams)
    assert abs(offsets[1] - (-1.25)) < 0.01


def test_offsets_without_shared_barriers_default_to_zero():
    streams = [_host_stream(0, 0.0, [0.05]), [{"event": "fit_start"}]]
    assert podview.estimate_offsets(streams) == [0.0, 0.0]


def test_stitch_aligns_rewrites_and_roots():
    viewer = _load_tool("trace_viewer")
    streams = [
        _host_stream(0, 0.0, [0.05, 0.05]),
        _host_stream(1, 3.7, [0.05, 0.05]),
    ]
    merged, info = podview.stitch(streams)
    assert info["hosts"] == [0, 1]
    assert abs(info["offsets"][1] - 3.7) < 1e-9
    assert info["groups"] == 1
    assert info["digest_mismatches"] == []
    spans = viewer.select_spans(merged)
    assert viewer.validate(spans) == []
    # ids prefixed per host, dist spans regrouped under the pod trace
    by_id = {s["span_id"]: s for s in spans}
    assert "h0.L0" in by_id and "h1.L0" in by_id
    assert by_id["h0.L0"]["trace_id"] == "pod.0"
    assert by_id["h0.L0"]["parent_id"] == "pod.0.root"
    root = by_id["pod.0.root"]
    assert root["name"] == "pod_fit_0" and root["thread"] == "pod"
    # aligned timelines: the same round starts at the same pod ts
    assert abs(by_id["h0.L0"]["ts"] - by_id["h1.L0"]["ts"]) < 1e-6
    # the merged stream is sorted by aligned ts
    ts = [float(e.get("ts", 0.0)) for e in merged]
    assert ts == sorted(ts)


def test_digest_mismatch_reported_not_fatal():
    streams = [
        _host_stream(0, 0.0, [0.05], digest="aaaa"),
        _host_stream(1, 0.0, [0.05], digest="bbbb"),
    ]
    merged, info = podview.stitch(streams)
    assert info["digest_mismatches"] == [
        {"group": 0, "digests": {0: "aaaa", 1: "bbbb"}}
    ]
    assert merged  # the trace is still produced


def test_cross_host_flow_resolves_only_when_stitched():
    viewer = _load_tool("trace_viewer")
    fid = 424242
    victim = _host_stream(1, 0.0, [0.05], flow_out=fid)
    survivor = _host_stream(0, 0.0, [0.05], flow_in=fid)
    # the survivor alone: rewind's flow_in has no source
    assert viewer.validate(viewer.select_spans(survivor))
    merged, _ = podview.stitch([survivor, victim])
    assert viewer.validate(viewer.select_spans(merged)) == []


def test_skew_report_names_the_straggler():
    streams = [
        _host_stream(0, 0.0, [0.05, 0.05]),
        _host_stream(1, 2.0, [0.05, 0.45],
                     stalls=[("GBM:stream_round:1:level:0:dist_step:0",
                              0.4)]),
    ]
    report = podview.skew_report(streams)
    assert report["hosts"] == [0, 1]
    r1 = next(r for r in report["rounds"] if r["round"] == 1)
    assert r1["offender"] == 1
    assert r1["ratio"] > 1.5
    assert report["persistent_offender"] == 1
    assert report["pod_skew_ratio"] > 1.0
    assert report["stalls"]["1"]["count"] == 1
    text = podview.render_skew(report)
    assert "== pod skew ==" in text
    assert "offender host 1" in text
    assert "stalls: host 1" in text


def test_skew_report_single_host_is_healthy():
    report = podview.skew_report([_host_stream(0, 0.0, [0.05])])
    assert report["pod_skew_ratio"] == 1.0


def test_expand_inputs_walks_dirs_deterministically(tmp_path):
    (tmp_path / "sub").mkdir()
    for name in ("b.jsonl", "a.jsonl", "sub/c.jsonl", "skip.txt"):
        (tmp_path / name).write_text("{}\n")
    got = podview.expand_inputs([str(tmp_path),
                                 str(tmp_path / "a.jsonl")])  # dup dropped
    assert [os.path.basename(p) for p in got] == [
        "a.jsonl", "b.jsonl", "c.jsonl"
    ]


def _write_streams(tmp_path, streams):
    paths = []
    for i, ev in enumerate(streams):
        p = tmp_path / f"telemetry_p{i}.jsonl"
        p.write_text("".join(json.dumps(e) + "\n" for e in ev))
        paths.append(str(p))
    return paths


def test_trace_viewer_cli_multi_input(tmp_path, capsys):
    viewer = _load_tool("trace_viewer")
    fid = 77
    paths = _write_streams(tmp_path, [
        _host_stream(0, 0.0, [0.05], flow_in=fid),
        _host_stream(1, 1.5, [0.05], flow_out=fid),
    ])
    # validate-only over the pair
    assert viewer.main(["--jsonl", *paths, "--validate"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["problems"] == 0 and summary["hosts"] == [0, 1]
    # survivor alone fails by design
    assert viewer.main(["--jsonl", paths[0], "--validate"]) == 1
    capsys.readouterr()
    # directory export: host track groups named in the Perfetto JSON
    out = tmp_path / "pod.json"
    assert viewer.main(["--jsonl", str(tmp_path), "--out", str(out)]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["hosts"] == [0, 1]
    trace = json.loads(out.read_text())
    names = {
        e["args"]["name"] for e in trace["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert {"host0", "host1"} <= names


def test_telemetry_report_cli_multi_input(tmp_path, capsys):
    report = _load_tool("telemetry_report")
    paths = _write_streams(tmp_path, [
        _host_stream(0, 0.0, [0.05, 0.05]),
        _host_stream(1, 0.0, [0.05, 0.30]),
    ])
    assert report.main(paths) == 0
    text = capsys.readouterr().out
    assert "== pod skew ==" in text
    assert "offender host 1" in text
