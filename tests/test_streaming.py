"""Out-of-core data plane tests (docs/streaming.md): shard store
write/verify discipline, the prefetcher's stats and abandon-safety, the
PINNED per-family bit-identity of streaming vs resident stream-tier
fits, mid-shard kill-and-resume, and the shard-I/O telemetry events."""

import os

import numpy as np
import pytest

import jax

import spark_ensemble_tpu as se
from spark_ensemble_tpu.autotune.resolve import override
from spark_ensemble_tpu.data import (
    ShardLoadError,
    ShardPrefetcher,
    ShardStore,
    write_shards,
)
from spark_ensemble_tpu.models.tree import DecisionTreeRegressor
from spark_ensemble_tpu.ops.binning import (
    bin_features,
    compute_bins,
    pack_bins,
)
from spark_ensemble_tpu.robustness import chaos
from spark_ensemble_tpu.robustness.chaos import ChaosPreemption
from spark_ensemble_tpu.telemetry import record_fits


def _data(n=157, d=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


def _cls_labels(X):
    return (
        (X[:, 0] + X[:, 1] > 0).astype(np.int32)
        + (X[:, 2] > 0.5).astype(np.int32)
    )


def _base(**kw):
    kw.setdefault("max_depth", 3)
    kw.setdefault("max_bins", 16)
    kw.setdefault("hist", "stream")
    return DecisionTreeRegressor(**kw)


def _store(tmp_path, X, shard_rows=64, max_bins=16):
    return write_shards(
        X, str(tmp_path / "store"), max_bins=max_bins, shard_rows=shard_rows
    )


def _assert_tree_equal(m1, m2):
    l1 = jax.tree_util.tree_leaves(m1.params)
    l2 = jax.tree_util.tree_leaves(m2.params)
    assert len(l1) == len(l2)
    for a, b in zip(l1, l2):
        a, b = np.asarray(a), np.asarray(b)
        assert a.shape == b.shape
        # pinned: EQUAL, not close — the streaming sweep runs the same
        # f32 ops on the same operands in the same order as the resident
        # stream-tier scan
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# shard store
# ---------------------------------------------------------------------------


def test_write_shards_roundtrip(tmp_path):
    X, _ = _data()
    store = _store(tmp_path, X, shard_rows=64)
    assert (store.n, store.d) == X.shape
    assert store.num_shards == 3
    assert store.shard_rows == 64
    assert store.max_bins == 16
    assert store.bits == 4  # 16 bins pack at 4 bits/feature

    # thresholds match a resident compute_bins over the same matrix
    bins = compute_bins(jax.numpy.asarray(X), 16)
    np.testing.assert_array_equal(
        store.thresholds, np.asarray(bins.thresholds)
    )

    # each shard's packed words equal slicing a whole-matrix packing
    cb = pack_bins(bin_features(jax.numpy.asarray(X), bins), 16)
    full = np.asarray(cb.packed)
    for s in range(store.num_shards):
        lo = s * 64
        want = full[lo:lo + 64]
        got = store.load_shard(s)
        assert got.shape == (64, store.words_per_row)  # zero-padded tail
        np.testing.assert_array_equal(got[: want.shape[0]], want)
        if want.shape[0] < 64:
            assert not got[want.shape[0]:].any()

    assert store.packed_nbytes == sum(
        store.shard_meta(s)["bytes"] for s in range(store.num_shards)
    )


def test_write_shards_overwrite_flag(tmp_path):
    X, _ = _data()
    _store(tmp_path, X)
    with pytest.raises(FileExistsError):
        _store(tmp_path, X)
    store = write_shards(
        X, str(tmp_path / "store"), max_bins=16, shard_rows=50,
        overwrite=True,
    )
    assert store.shard_rows == 50


def test_open_rejects_format_mismatch(tmp_path):
    X, _ = _data()
    store = _store(tmp_path, X)
    mpath = os.path.join(store.directory, "manifest.json")
    raw = open(mpath).read().replace('"format": 1', '"format": 999')
    open(mpath, "w").write(raw)
    with pytest.raises(ValueError, match="format"):
        ShardStore.open(store.directory)


def test_open_rejects_truncation(tmp_path):
    X, _ = _data()
    store = _store(tmp_path, X)
    fpath = os.path.join(store.directory, store.shard_meta(1)["file"])
    with open(fpath, "r+b") as f:
        f.truncate(os.path.getsize(fpath) - 8)
    # size check runs even with verify=False: truncation is never silent
    with pytest.raises(ValueError, match="truncated"):
        ShardStore.open(store.directory, verify=False)


def test_open_rejects_corruption(tmp_path):
    X, _ = _data()
    store = _store(tmp_path, X)
    fpath = os.path.join(store.directory, store.shard_meta(0)["file"])
    size = os.path.getsize(fpath)
    with open(fpath, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xff\xff\xff\xff")
    with pytest.raises(ValueError, match="sha256"):
        ShardStore.open(store.directory)
    # explicit opt-out still opens (size matches)
    ShardStore.open(store.directory, verify=False)


# ---------------------------------------------------------------------------
# prefetcher
# ---------------------------------------------------------------------------


def test_prefetcher_sweep_and_stats(tmp_path):
    X, _ = _data()
    store = _store(tmp_path, X, shard_rows=64)
    with ShardPrefetcher(store, depth=2, to_device=False) as pf:
        seen = [(s, arr.copy()) for s, arr in pf.sweep()]
        assert [s for s, _ in seen] == [0, 1, 2]
        for s, arr in seen:
            np.testing.assert_array_equal(arr, store.load_shard(s))
        st = pf.take_stats()
        assert st["loads"] == 3
        assert st["hits"] + st["misses"] == 3
        assert st["bytes"] == sum(a.nbytes for _, a in seen)
        # reset-on-take
        assert pf.take_stats()["loads"] == 0
        # back-to-back sweeps reuse the cyclic schedule
        assert [s for s, _ in pf.sweep()] == [0, 1, 2]


def test_prefetcher_abandoned_sweep_recovers(tmp_path):
    X, _ = _data()
    store = _store(tmp_path, X, shard_rows=64)
    with ShardPrefetcher(store, depth=2, to_device=False) as pf:
        gen = pf.sweep()
        next(gen)
        gen.close()  # mid-round death (chaos preemption unwinding)
        # the next sweep reconciles against whatever is still in flight
        assert [s for s, _ in pf.sweep()] == [0, 1, 2]


def test_prefetcher_attributes_worker_errors(tmp_path):
    """A worker-thread read failure surfaces on the consumer as a
    ShardLoadError naming the shard that broke (not just whichever await
    lost), and lands in take_stats() for the per-round telemetry."""
    X, _ = _data()
    store = _store(tmp_path, X, shard_rows=64)

    class _FlakyStore:
        num_shards = store.num_shards
        n = store.n

        @staticmethod
        def load_shard(s):
            if s == 1:
                raise IOError("disk went away")
            return store.load_shard(s)

    with ShardPrefetcher(_FlakyStore(), depth=2, to_device=False) as pf:
        gen = pf.sweep()
        s0, _arr = next(gen)
        assert s0 == 0
        with pytest.raises(ShardLoadError, match="shard 1") as ei:
            for _ in gen:  # pragma: no branch - raises on the next shard
                pass
        assert ei.value.shard == 1
        assert isinstance(ei.value.__cause__, IOError)
        st = pf.take_stats()
        assert st["errors"] == 1
        assert "shard 1" in st["last_error"]
        assert st["loads"] == 1  # only shard 0 landed


# ---------------------------------------------------------------------------
# bit-identity (pinned, per family)
# ---------------------------------------------------------------------------


def test_streaming_regressor_bit_identical(tmp_path):
    X, y = _data()
    with override(stream_chunk_rows=64, shard_rows=64):
        store = _store(tmp_path, X, shard_rows=64)
        kw = dict(base_learner=_base(), num_base_learners=5, seed=0)
        res = se.GBMRegressor(**kw).fit(X, y)
        stm = se.GBMRegressor(**kw).fit_streaming(store, y)
    _assert_tree_equal(res, stm)
    np.testing.assert_array_equal(
        np.asarray(res.predict(X)), np.asarray(stm.predict(X))
    )


def test_streaming_classifier_bit_identical(tmp_path):
    X, _ = _data(seed=1)
    y = _cls_labels(X)
    with override(stream_chunk_rows=64, shard_rows=64):
        store = _store(tmp_path, X, shard_rows=64)
        kw = dict(base_learner=_base(), num_base_learners=4, seed=3)
        res = se.GBMClassifier(**kw).fit(X, y)
        stm = se.GBMClassifier(**kw).fit_streaming(store, y)
    _assert_tree_equal(res, stm)
    np.testing.assert_array_equal(
        np.asarray(res.predict(X)), np.asarray(stm.predict(X))
    )


def test_streaming_regressor_validation_bit_identical(tmp_path):
    X, y = _data()
    Xv, yv = _data(n=40, seed=9)
    with override(stream_chunk_rows=64, shard_rows=64):
        store = _store(tmp_path, X, shard_rows=64)
        kw = dict(base_learner=_base(), num_base_learners=6, seed=5)
        Xall = np.concatenate([X, Xv])
        yall = np.concatenate([y, yv])
        vi = np.zeros(len(yall), bool)
        vi[len(y):] = True
        res = se.GBMRegressor(**kw).fit(Xall, yall, validation_indicator=vi)
        stm = se.GBMRegressor(**kw).fit_streaming(store, y, X_val=Xv, y_val=yv)
    _assert_tree_equal(res, stm)


def test_streaming_huber_bit_identical(tmp_path):
    X, y = _data()
    with override(stream_chunk_rows=64, shard_rows=64):
        store = _store(tmp_path, X, shard_rows=64)
        kw = dict(
            base_learner=_base(), num_base_learners=3, seed=7, loss="huber"
        )
        res = se.GBMRegressor(**kw).fit(X, y)
        stm = se.GBMRegressor(**kw).fit_streaming(store, y)
    _assert_tree_equal(res, stm)


# ---------------------------------------------------------------------------
# mid-shard kill-and-resume
# ---------------------------------------------------------------------------


class _PreemptAtSite:
    """Fires exactly at one named chaos site (a mid-shard one here —
    between two accumulation programs of one tree level)."""

    enabled = True

    def __init__(self, site):
        self.site = site
        self.fired = []

    def transient(self, site):
        pass

    def preempt(self, site):
        if site == self.site and not self.fired:
            self.fired.append(site)
            raise ChaosPreemption(site)

    def poison_array(self, site, arr):
        return arr

    def poison_member_stack(self, site, tree):
        return tree

    def poison_tree(self, site, tree):
        return tree

    def corrupt_checkpoint(self, site, state_path):
        pass


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    yield
    chaos.install(None)


def test_streaming_kill_and_resume_mid_shard(tmp_path):
    X, y = _data()
    with override(stream_chunk_rows=64, shard_rows=64):
        store = _store(tmp_path, X, shard_rows=64)

        def est(ckdir):
            return se.GBMRegressor(
                base_learner=_base(max_depth=2), num_base_learners=6,
                seed=0, scan_chunk=2, checkpoint_dir=ckdir,
                checkpoint_interval=1,
            )

        ref = est(None).fit_streaming(store, y)

        # kill INSIDE round 2's level-1 sweep, between shards 0 and 1 —
        # after rounds 0-1 were committed and checkpointed
        ckdir = str(tmp_path / "ck")
        ctl = _PreemptAtSite("GBMRegressor:stream_round:2:level:1:shard:1")
        chaos.install(ctl)
        with pytest.raises(ChaosPreemption):
            est(ckdir).fit_streaming(store, y)
        assert ctl.fired
        # keep ctl installed (it fired, so it is spent) through the resume:
        # the killed fit's ASYNC checkpoint save can still be in flight, and
        # its corrupt_checkpoint hook resolves the controller at write time —
        # install(None) here would let an env-configured chaos controller
        # (the CI streaming job) tear the very checkpoint this test resumes
        # from; the autouse fixture uninstalls at teardown

        with record_fits() as rec:
            m = est(ckdir).fit_streaming(store, y)
        resumes = [
            e for e in rec.events if e["event"] == "resume_from_checkpoint"
        ]
        assert resumes and resumes[0]["round"] >= 1
    # deterministic replay: the resumed streaming fit is bit-identical
    _assert_tree_equal(ref, m)


def test_streaming_resumes_resident_checkpoint(tmp_path):
    """Streaming and resident fits share checkpoint identity: a resident
    fit killed after some rounds resumes as a STREAMING fit (and lands on
    the same model), because the checkpointed states are bit-identical."""
    X, y = _data()
    with override(stream_chunk_rows=64, shard_rows=64):
        store = _store(tmp_path, X, shard_rows=64)

        def est(ckdir):
            return se.GBMRegressor(
                base_learner=_base(max_depth=2), num_base_learners=6,
                seed=0, scan_chunk=2, checkpoint_dir=ckdir,
                checkpoint_interval=1,
            )

        ref = est(None).fit(X, y)
        ckdir = str(tmp_path / "ck")
        ctl = _PreemptAtSite("GBMRegressor:stream_round:2:level:1:shard:1")
        chaos.install(ctl)
        # resident fit never hits stream-shard sites; use its round site
        ctl.site = "GBMRegressor:after_round:1"
        with pytest.raises(ChaosPreemption):
            est(ckdir).fit(X, y)
        # ctl stays installed through the resume (see the mid-shard test:
        # a late async-save corrupt hook must not see an env controller)

        with record_fits() as rec:
            m = est(ckdir).fit_streaming(store, y)
        resumes = [
            e for e in rec.events if e["event"] == "resume_from_checkpoint"
        ]
        assert resumes and resumes[0]["round"] >= 1
    _assert_tree_equal(ref, m)


# ---------------------------------------------------------------------------
# validation + telemetry
# ---------------------------------------------------------------------------


def test_fit_streaming_input_validation(tmp_path):
    X, y = _data()
    store = _store(tmp_path, X)
    with pytest.raises(ValueError, match="init_strategy"):
        se.GBMRegressor(
            base_learner=_base(), init_strategy="base"
        ).fit_streaming(store, y)
    with pytest.raises(ValueError, match="max_bins"):
        se.GBMRegressor(
            base_learner=_base(max_bins=32)
        ).fit_streaming(store, y)
    with pytest.raises(ValueError, match="rows"):
        se.GBMRegressor(base_learner=_base()).fit_streaming(store, y[:-3])


def test_streaming_emits_shard_io_events(tmp_path):
    X, y = _data()
    with override(stream_chunk_rows=64, shard_rows=64):
        store = _store(tmp_path, X, shard_rows=64)
        with record_fits() as rec:
            se.GBMRegressor(
                base_learner=_base(), num_base_learners=3, seed=0
            ).fit_streaming(store, y)
    loads = [e for e in rec.events if e["event"] == "shard_load"]
    hits = [e for e in rec.events if e["event"] == "shard_prefetch_hit"]
    waits = [e for e in rec.events if e["event"] == "shard_wait_us"]
    assert loads and hits and waits
    # every round sweeps every shard max_depth+1 times
    total_loads = sum(e["count"] for e in loads)
    assert total_loads == 3 * (3 + 1) * store.num_shards
    assert all(e["bytes"] > 0 for e in loads)
    assert all(e["hits"] + e["misses"] > 0 for e in hits)
    cfg = [e for e in rec.events if e["event"] == "streaming_config"]
    assert cfg and cfg[0]["shards"] == store.num_shards
    assert cfg[0]["packed_bytes"] == store.packed_nbytes
