"""Golden-output test for ``tools/telemetry_report.py``: a canned JSONL
stream (fit + streaming shard-I/O + fleet SLO events, with ``span``
rows interleaved) renders byte-identical to the committed golden.  The
span events are the tracing plane riding the same stream
(docs/tracing.md) — the report must keep working over them unchanged,
which is exactly what the golden pins."""

import importlib.util
import json
import os

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIX = os.path.join(_ROOT, "tests", "fixtures", "telemetry")

spec = importlib.util.spec_from_file_location(
    "telemetry_report", os.path.join(_ROOT, "tools", "telemetry_report.py")
)
report = importlib.util.module_from_spec(spec)
spec.loader.exec_module(report)

CANNED = os.path.join(_FIX, "canned.jsonl")
GOLDEN = os.path.join(_FIX, "canned_report.golden")


def test_canned_stream_renders_golden(capsys):
    assert report.main([CANNED]) == 0
    got = capsys.readouterr().out
    want = open(GOLDEN).read()
    assert got == want, (
        "telemetry_report output drifted from the golden; if the change "
        "is deliberate, regenerate with:\n  python tools/telemetry_report.py "
        "tests/fixtures/telemetry/canned.jsonl > "
        "tests/fixtures/telemetry/canned_report.golden"
    )


def test_span_rows_do_not_leak_into_the_report():
    events = report.load_events(CANNED)
    spans = [e for e in events if e.get("event") == "span"]
    assert spans, "fixture must interleave span rows"
    fits = report.group_fits(events)
    rendered = report.render_fit(
        "GBMRegressor:1:0", fits["GBMRegressor:1:0"]
    )
    # spans group under their fit but contribute no rows of their own
    assert "span" not in rendered
    assert "round_chunk" not in rendered


def test_canned_inventory_stream_renders_cost_triangle(capsys):
    """The operator-plane extension of the golden (docs/operator.md): the
    round_end rows carry the programz join fields and the stream holds
    ``program`` inventory rows, so the report must render the three-way
    cost line and the per-program top-N table — byte-pinned above, shape-
    pinned here so a refactor cannot silently drop either section."""
    assert report.main([CANNED]) == 0
    text = capsys.readouterr().out
    assert ("xla cost: measured 50.00ms/round  analytic 40.00ms/round  "
            "xla 2.40ms/round  mfu_xla 0.48%  xla/analytic flops 0.94"
            ) in text
    assert "== programz ==" in text
    # heaviest program first, pending rows keep a '-' build column
    table = text.split("== programz ==")[1]
    assert table.index("gbm_round") < table.index("predict:raw")
    assert table.index("predict:raw") < table.index("gbm_sampling_plan")
    assert "pending" in table


def test_program_table_dedupes_reemitted_rows():
    """Long-running streams re-emit inventory snapshots; only the latest
    row per (tag, signature) may land in the table."""
    rows = [
        {"event": "program", "tag": "t", "signature": [["8", "f32"]],
         "calls": 1, "flops": 10.0, "status": "pending"},
        {"event": "program", "tag": "t", "signature": [["8", "f32"]],
         "calls": 5, "flops": 10.0, "status": "analyzed"},
    ]
    table = report.program_table(rows)
    assert table.count("\n") == 1  # header + exactly one data row
    assert "analyzed" in table and "pending" not in table


def test_canned_quality_section_renders(capsys):
    """Model-quality extension of the golden (docs/quality.md): the
    stream holds ``drift_window``/``shadow_eval``/``quality_alert`` rows
    plus attribution-sampled ``fleet_request`` rows, so the report must
    render the quality section — byte-pinned above, shape-pinned here."""
    assert report.main([CANNED]) == 0
    text = capsys.readouterr().out
    assert "== model quality ==" in text
    assert "top psi: f2 1.314" in text
    assert "shadow[shadow:1:0]: candidate gbm-v2" in text
    assert "uncertainty: 2 sampled" in text
    assert "alert raised: psi_max" in text
    # quality-only streams summarize here, never as empty fit headers
    assert "== shadow:1:0 ==" not in text


def test_fit_filter_and_aggregate_jsonl(tmp_path, capsys):
    out = tmp_path / "agg.jsonl"
    assert report.main([CANNED, "--fit", "GBMRegressor",
                        "--jsonl", str(out)]) == 0
    text = capsys.readouterr().out
    assert "== GBMRegressor:1:0 ==" in text
    assert "serving:1:0" not in text  # filtered out
    records = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["op"] for r in records] == ["rounds", "setup", "finalize"]
    assert records[0] == {
        "count": 2, "op": "rounds", "share": 0.5, "total_us": 100000.0,
    }


def test_missing_fit_filter_fails(capsys):
    assert report.main([CANNED, "--fit", "nope"]) == 1


def test_directory_input_matches_single_file_golden(tmp_path, capsys):
    """Satellite contract: pointing the tool at a DIRECTORY holding the
    same single stream renders the same per-fit sections; with only one
    host there is no pod skew signal, so no skew section appears and the
    output stays byte-identical to the golden."""
    import shutil

    shutil.copy(CANNED, tmp_path / "telemetry_p0.jsonl")
    assert report.main([str(tmp_path)]) == 0
    got = capsys.readouterr().out
    assert got == open(GOLDEN).read()
