"""Causal tracing plane tests (docs/tracing.md): span primitives and
propagation, the executor's chunk fates (committed / invalidated /
abandoned) with invalidation flow arrows, cross-thread spans from the
shard prefetcher and the checkpoint writer, the fleet's request/serve
spans with hedge flows and live ``statusz()``, the disabled path's
null objects, and ``tools/trace_viewer.py``'s validated Perfetto
export — including the ISSUE-pinned acceptance: a chaos run whose
exported trace contains a test-asserted hedge flow arrow and an
invalidated speculative chunk, with every parent/flow id resolving."""

import importlib.util
import json
import os
from collections import Counter

import numpy as np
import pytest

import spark_ensemble_tpu as se
from spark_ensemble_tpu.data import ShardPrefetcher, write_shards
from spark_ensemble_tpu.execution import RoundAdapter, RoundExecutor
from spark_ensemble_tpu.robustness.chaos import ChaosController, install
from spark_ensemble_tpu.serving import FleetRouter
from spark_ensemble_tpu.telemetry import (
    NULL_SPAN,
    NULL_TRACER,
    TraceContext,
    Tracer,
    record_fits,
    telemetry_sink_active,
)
from spark_ensemble_tpu.telemetry.events import (
    _DISABLED,
    FitTelemetry,
    emit_event,
)
from spark_ensemble_tpu.telemetry.trace import (
    NULL_CONTEXT,
    new_flow_id,
    new_span_id,
    new_trace_id,
    trace_annotations_enabled,
)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", name + ".py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


viewer = _load_tool("trace_viewer")

ROUNDS = 5


def _data(n=96, d=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


@pytest.fixture(scope="module")
def fitted():
    X, y = _data()
    model = se.GBMRegressor(num_base_learners=ROUNDS, seed=0).fit(X, y)
    return X, y, model


@pytest.fixture(autouse=True)
def _deterministic_chaos():
    # same discipline as tests/test_fleet.py: the chaos tests below
    # install their own controllers; everything else must see silence
    install(ChaosController(seed=0, rate=0.0))
    yield
    install(None)


def _spans(events, name=None):
    out = [e for e in events if e.get("event") == "span"]
    if name:
        out = [s for s in out if s.get("name") == name]
    return out


# ---------------------------------------------------------------------------
# primitives: ids, Span lifecycle, propagation, null objects
# ---------------------------------------------------------------------------


def test_ids_are_unique_and_pid_scoped():
    traces = {new_trace_id() for _ in range(50)}
    spans = {new_span_id() for _ in range(50)}
    flows = {new_flow_id() for _ in range(50)}
    assert len(traces) == 50 and len(spans) == 50 and len(flows) == 50
    pid = os.getpid()
    assert all(t.startswith(f"t{pid:x}.") for t in traces)
    assert all(s.startswith(f"s{pid:x}.") for s in spans)
    assert all(isinstance(f, int) and (f >> 24) == pid for f in flows)


def test_span_lifecycle_and_idempotent_end():
    sink = []
    tracer = Tracer(sink.append, thread="fit")
    with tracer.begin_span("fit", family="test") as root:
        root.add(rounds=3)
        with tracer.begin_span("round_chunk", parent=root, chunk_seq=0):
            pass
    root.end(ignored=True)  # second end: no duplicate record, no attr
    assert [s["name"] for s in sink] == ["round_chunk", "fit"]
    chunk, fit = sink
    assert fit["trace_id"] == tracer.trace_id
    assert fit["parent_id"] == ""
    assert fit["rounds"] == 3 and "ignored" not in fit
    assert chunk["parent_id"] == fit["span_id"]
    assert chunk["trace_id"] == fit["trace_id"]
    assert chunk["thread"] == "fit"
    assert chunk["dur_s"] >= 0.0 and chunk["ts"] <= fit["ts"] + fit["dur_s"]


def test_span_exception_records_error_attr():
    sink = []
    tracer = Tracer(sink.append)
    with pytest.raises(ValueError):
        with tracer.begin_span("serve"):
            raise ValueError("boom")
    (rec,) = sink
    assert rec["error"] == "ValueError"


def test_context_propagation_across_threads():
    sink = []
    tracer = Tracer(sink.append)
    with tracer.begin_span("fit") as root:
        ctx = root.context()
        assert isinstance(ctx, TraceContext) and ctx
        # the far side: a different Tracer (different default trace)
        # still lands on the ORIGIN trace through the two captured ids
        other = Tracer(sink.append, thread="ckpt-writer")
        with other.begin_span("checkpoint_save", parent=ctx, round=2):
            pass
        sid = other.emit_span(
            "shard_load", 12.0, 0.5, parent=ctx, thread="se-tpu-shard",
            flow_out=[7], shard=0,
        )
    ckpt = _spans(sink, "checkpoint_save")[0]
    load = _spans(sink, "shard_load")[0]
    fit = _spans(sink, "fit")[0]
    for child in (ckpt, load):
        assert child["trace_id"] == tracer.trace_id
        assert child["parent_id"] == fit["span_id"]
    assert ckpt["thread"] == "ckpt-writer"
    assert load["thread"] == "se-tpu-shard"
    assert load["span_id"] == sid
    assert load["ts"] == 12.0 and load["dur_s"] == 0.5
    assert load["flow_out"] == [7]
    assert viewer.validate(_spans(sink)) == []


def test_null_objects_are_falsy_no_ops():
    assert not NULL_SPAN and not NULL_TRACER and not NULL_CONTEXT
    assert NULL_TRACER.begin_span("x", attr=1) is NULL_SPAN
    assert NULL_TRACER.emit_span("x", 0.0, 1.0) == ""
    with NULL_SPAN as sp:
        sp.add(a=1)
        assert sp.context() is NULL_CONTEXT
    NULL_SPAN.end()  # nothing to flush, nothing raised
    # a real span is truthy — the `if req.span:` hot-path guard
    assert Tracer(lambda rec: None).begin_span("y")


def test_disabled_telemetry_hands_out_nulls():
    assert _DISABLED.begin_span("round_chunk", chunk_seq=0) is NULL_SPAN
    assert _DISABLED.emit_span("shard_load", 0.0, 1.0) == ""
    assert _DISABLED.trace_context() is NULL_CONTEXT
    assert _DISABLED.trace_id == ""


def test_telemetry_sink_active(monkeypatch, tmp_path):
    monkeypatch.delenv("SE_TPU_TELEMETRY", raising=False)
    assert not telemetry_sink_active()
    assert telemetry_sink_active(str(tmp_path / "t.jsonl"))
    with record_fits():
        assert telemetry_sink_active()
    monkeypatch.setenv("SE_TPU_TELEMETRY", str(tmp_path / "env.jsonl"))
    assert telemetry_sink_active()


def test_trace_annotations_env_gate(monkeypatch):
    monkeypatch.delenv("SE_TPU_TRACE_ANNOTATIONS", raising=False)
    assert not trace_annotations_enabled()
    monkeypatch.setenv("SE_TPU_TRACE_ANNOTATIONS", "1")
    assert trace_annotations_enabled()
    # annotated spans still emit normally outside a profiler capture
    sink = []
    with Tracer(sink.append).begin_span("fit"):
        pass
    assert len(sink) == 1


# ---------------------------------------------------------------------------
# RoundExecutor chunk fates
# ---------------------------------------------------------------------------


class _ScriptedAdapter(RoundAdapter):
    """Deterministic adapter: `total` chunks; committing a chunk listed in
    `invalidate_at` (by absolute chunk index) kills the in-flight tail."""

    def __init__(self, telem, total=5, depth=2, invalidate_at=(),
                 raise_at=None):
        self.telem = telem
        self.depth = depth
        self.total = total
        self.invalidate_at = set(invalidate_at)
        self.raise_at = raise_at
        self.committed = 0
        self.frontier = 0
        self.finished = False

    def should_continue(self):
        return self.committed < self.total

    def can_launch(self):
        return self.frontier < self.total

    def launch(self):
        entry = self.frontier
        self.frontier += 1
        return entry

    def commit(self, entry, speculated):
        if self.raise_at is not None and entry == self.raise_at:
            raise RuntimeError("chaos mid-commit")
        self.committed = entry + 1
        return entry in self.invalidate_at

    def reset_frontier(self):
        self.frontier = self.committed

    def finish(self):
        self.finished = True


def test_executor_invalidation_fates_and_flow():
    sink = []
    adapter = _ScriptedAdapter(
        Tracer(sink.append, thread="fit"), total=5, depth=2,
        invalidate_at=(0,),
    )
    RoundExecutor(adapter).run()
    assert adapter.finished and adapter.committed == 5
    chunks = _spans(sink, "round_chunk")
    fates = Counter(s["fate"] for s in chunks)
    # window 3: launch 0,1,2; committing 0 invalidates 1,2 in flight;
    # then 1..4 relaunch and commit cleanly — 5 committed + 2 invalidated
    assert fates == {"committed": 5, "invalidated": 2}
    killer = [
        s for s in chunks if s["fate"] == "committed" and s.get("flow_out")
    ]
    assert len(killer) == 1
    (flow,) = killer[0]["flow_out"]
    invalidated = [s for s in chunks if s["fate"] == "invalidated"]
    assert all(s["flow_in"] == flow for s in invalidated)
    # the invalidated chunks were dispatched speculatively
    assert all(s["speculative"] for s in invalidated)
    assert viewer.validate(chunks) == []


def test_executor_abandons_in_flight_spans_on_raise():
    sink = []
    adapter = _ScriptedAdapter(
        Tracer(sink.append), total=5, depth=2, raise_at=1,
    )
    with pytest.raises(RuntimeError, match="chaos"):
        RoundExecutor(adapter).run()
    assert not adapter.finished  # finish() only runs on a clean exit
    fates = Counter(s["fate"] for s in _spans(sink, "round_chunk"))
    assert fates["committed"] == 1  # chunk 0
    assert fates["aborted"] == 1    # chunk 1 raised mid-commit
    assert fates["abandoned"] >= 1  # the speculative tail, closed unread
    assert fates.get("invalidated", 0) == 0


def test_executor_without_telem_traces_nothing():
    adapter = _ScriptedAdapter(None, total=3, depth=1)
    RoundExecutor(adapter).run()
    assert adapter.finished and adapter.committed == 3


# ---------------------------------------------------------------------------
# fit integration: root span, chunk spans, checkpoint + prefetch threads
# ---------------------------------------------------------------------------


def test_fit_emits_rooted_round_chunk_spans():
    X, y = _data()
    with record_fits() as rec:
        se.GBMRegressor(num_base_learners=4, seed=0, scan_chunk=2).fit(X, y)
    spans = _spans(rec.events)
    roots = _spans(spans, "fit")
    assert len(roots) == 1
    root = roots[0]
    assert root["parent_id"] == "" and root["rounds"] == 4
    chunks = _spans(spans, "round_chunk")
    assert len(chunks) >= 2  # 4 rounds in scan_chunk=2 dispatches
    for s in chunks:
        assert s["trace_id"] == root["trace_id"]
        assert s["parent_id"] == root["span_id"]
        assert s["fate"] == "committed"
    assert viewer.validate(spans) == []


def test_checkpoint_save_span_on_writer_thread(tmp_path):
    X, y = _data()
    with record_fits() as rec:
        se.GBMRegressor(
            num_base_learners=4, seed=0, scan_chunk=2,
            checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_interval=2,
        ).fit(X, y)
    spans = _spans(rec.events)
    saves = _spans(spans, "checkpoint_save")
    assert saves, "checkpointed fit emitted no checkpoint_save spans"
    root = _spans(spans, "fit")[0]
    for s in saves:
        assert s["trace_id"] == root["trace_id"]
        assert s["parent_id"] == root["span_id"]
        assert s["thread"] == "ckpt-writer"
        assert s["round"] >= 0
    assert viewer.validate(spans) == []


def test_prefetcher_reconstructs_worker_spans(tmp_path):
    X, _ = _data(n=157)
    store = write_shards(
        X, str(tmp_path / "store"), max_bins=16, shard_rows=64
    )
    with record_fits() as rec:
        telem = FitTelemetry.start(family="test", n=store.n)
        with ShardPrefetcher(store, depth=1, telem=telem,
                             to_device=False) as pf:
            for _ in pf.sweep():
                pass
        telem.finish()
    spans = _spans(rec.events)
    loads = _spans(spans, "shard_load")
    waits = _spans(spans, "shard_wait")
    assert len(loads) == store.num_shards
    assert len(waits) == store.num_shards
    root = _spans(spans, "fit")[0]
    for s in loads:
        assert s["thread"] == "se-tpu-shard"  # the worker's own track
        assert s["parent_id"] == root["span_id"]
        assert s["bytes"] > 0
    # a prefetch miss is a causal edge: the wait's flow_in must point at
    # the load that was still running (shard 0 is always a cold miss)
    misses = [s for s in waits if not s["hit"]]
    assert misses
    sources = {
        fid for s in loads for fid in (s.get("flow_out") or [])
    }
    for s in misses:
        assert s["flow_in"] in sources
    assert all(s.get("flow_in") is None for s in waits if s["hit"])
    assert viewer.validate(spans) == []


# ---------------------------------------------------------------------------
# fleet: request/serve spans, statusz
# ---------------------------------------------------------------------------


def test_fleet_request_spans_and_statusz(fitted):
    X, y, model = fitted
    with record_fits() as rec:
        # hedge seed past the deadline: this test pins the UNhedged span
        # shape (6 reqs -> 6 serves, no flow arrows), so a slow first
        # serve on a loaded host must not fire a real hedge
        router = FleetRouter(
            model, replicas=2, min_bucket=8, max_batch_size=16,
            deadline_ms=30_000.0, hedge_init_ms=30_000.0,
        )
        try:
            for _ in range(6):
                router.predict(X[:4])
            z = router.statusz()
            # the router doubles as a live global_metrics() source while
            # it runs (docs/tracing.md); the key dies with stop()
            from spark_ensemble_tpu.telemetry import global_metrics

            key = f"fleet/{z['stream']}"
            live = global_metrics().snapshot()[key]
            assert live["type"] == "source"
            assert live["value"]["requests"] == 6
        finally:
            router.stop()
        assert key not in global_metrics().snapshot()
    assert z["requests"] == 6 and not z["stopped"]
    assert z["trace_id"] == router._tracer.trace_id
    assert z["model"] == {"num_members": ROUNDS, "num_features": X.shape[1]}
    assert set(z["replicas"]) == {"fleet:r0", "fleet:r1"}
    assert 0.0 <= z["hedge_rate"] <= 1.0
    assert z["counters"]["hedges_fired"] == 0
    zstop = router.statusz()
    assert zstop["stopped"] and zstop["requests"] == 6
    spans = _spans(rec.events)
    reqs = _spans(spans, "fleet_request")
    serves = _spans(spans, "serve")
    assert len(reqs) == 6 and len(serves) == 6
    for s in serves:
        assert s["parent_id"] in {r["span_id"] for r in reqs}
        assert s["thread"] in ("fleet:r0", "fleet:r1")
        assert s["delivered"]
    for r in reqs:
        assert r["trace_id"] == z["trace_id"]
        assert r["replica"] in ("fleet:r0", "fleet:r1")
        assert not r["hedged"]
    assert viewer.validate(spans) == []


# ---------------------------------------------------------------------------
# the ISSUE acceptance: chaos run -> validated Perfetto export with a
# hedge flow arrow and an invalidated speculative chunk
# ---------------------------------------------------------------------------


def test_chaos_trace_exports_hedge_and_invalidation_flows(fitted, tmp_path):
    X, y, model = fitted
    jsonl = str(tmp_path / "telemetry.jsonl")

    # leg 1: a stalled replica forces a hedge (tests/test_fleet.py's
    # deterministic idiom), spans landing in the JSONL sink
    install(ChaosController(seed=7, rate=1.0, faults=("replica_stall",)))
    router = FleetRouter(
        model, replicas=2, min_bucket=8, max_batch_size=16,
        deadline_ms=30_000.0, hedge_init_ms=10.0, telemetry_path=jsonl,
    )
    try:
        resp = router.predict(X[:4])
        assert resp.hedged
    finally:
        router.stop()
        install(ChaosController(seed=0, rate=0.0))

    # leg 2: a speculative round-loop invalidation, through the SAME
    # executor machinery the fits use, appended to the SAME stream
    def _sink(rec):
        rec = dict(rec)
        emit_event(rec.pop("event"), path=jsonl, **rec)

    RoundExecutor(_ScriptedAdapter(
        Tracer(_sink, thread="fit"), total=4, depth=2, invalidate_at=(0,),
    )).run()

    out = str(tmp_path / "trace.json")
    summary = viewer.export(jsonl, out)  # raises on any unresolved edge
    assert summary["spans"] >= 5 and summary["flows"] >= 2
    spans = viewer.select_spans(viewer.load_events(jsonl))
    assert viewer.validate(spans) == []

    # hedge flow: the request span's flow_out feeds the twin serve on the
    # OTHER replica
    req = next(
        s for s in _spans(spans, "fleet_request") if s.get("hedged")
    )
    assert len(req["flow_out"]) == 1
    (hedge_flow,) = req["flow_out"]
    serves = [
        s for s in _spans(spans, "serve")
        if s["parent_id"] == req["span_id"]
    ]
    assert len(serves) == 2  # primary + hedge twin
    twin = next(s for s in serves if s.get("flow_in") == hedge_flow)
    primary = next(s for s in serves if s.get("flow_in") is None)
    assert twin["replica"] != primary["replica"]

    # invalidation flow: the committing chunk's flow_out feeds every
    # speculative chunk it killed
    chunks = _spans(spans, "round_chunk")
    killer = next(
        s for s in chunks
        if s["fate"] == "committed" and s.get("flow_out")
    )
    invalidated = [s for s in chunks if s["fate"] == "invalidated"]
    assert len(invalidated) == 2
    assert all(s["flow_in"] == killer["flow_out"][0] for s in invalidated)

    # and the same structure must survive in the EXPORTED Perfetto JSON:
    # flow arrows as "s"/"f" pairs, one named track per thread/replica
    with open(out) as fh:
        trace = json.load(fh)["traceEvents"]
    by_ph = Counter(e["ph"] for e in trace)
    assert by_ph["X"] == len(spans)
    flow_ids = {hedge_flow, killer["flow_out"][0]}
    for fid in flow_ids:
        starts = [e for e in trace if e["ph"] == "s" and e["id"] == fid]
        finishes = [e for e in trace if e["ph"] == "f" and e["id"] == fid]
        assert len(starts) == 1
        assert finishes and all(e["bp"] == "e" for e in finishes)
        # the arrow renders forward in time
        assert all(e["ts"] >= starts[0]["ts"] for e in finishes)
    tracks = {
        e["args"]["name"] for e in trace
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert {"fleet:r0", "fleet:r1", "fit", "router"} <= tracks
    # the chaos run's hedge_fired instant rides along as a marker
    assert any(
        e["ph"] == "i" and e["name"] == "hedge_fired" for e in trace
    )


# ---------------------------------------------------------------------------
# trace_viewer unit coverage: validation failures + CLI
# ---------------------------------------------------------------------------


def _span(name, span_id, parent_id="", **kw):
    rec = {
        "event": "span", "name": name, "trace_id": "t1", "span_id": span_id,
        "parent_id": parent_id, "ts": 10.0, "dur_s": 0.5, "pid": 1,
    }
    rec.update(kw)
    return rec


def test_validate_flags_orphans_and_dangling_flows():
    clean = [
        _span("fit", "a"),
        _span("round_chunk", "b", "a", flow_out=[9]),
        _span("round_chunk", "c", "a", flow_in=9),
    ]
    assert viewer.validate(clean) == []
    problems = viewer.validate([
        _span("round_chunk", "b", "missing"),
        _span("serve", "c", flow_in=42),
    ])
    assert len(problems) == 2
    assert any("orphan" in p for p in problems)
    assert any("no flow_out source" in p for p in problems)


def test_export_raises_on_unresolved_graph(tmp_path):
    path = tmp_path / "bad.jsonl"
    path.write_text(json.dumps(_span("x", "a", "missing")) + "\n")
    with pytest.raises(ValueError, match="unresolved"):
        viewer.export(str(path))


def test_viewer_cli_roundtrip(tmp_path, capsys):
    path = tmp_path / "t.jsonl"
    rows = [
        _span("fit", "a", thread="fit"),
        _span("serve", "b", "a", thread="r0"),
        {"event": "hedge_fired", "ts": 10.2, "seq": 0, "fit_id": "s"},
        {"event": "round_end", "round": 0},  # non-span rows pass through
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    out = tmp_path / "trace.json"
    assert viewer.main(["--jsonl", str(path), "--out", str(out)]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["spans"] == 2 and summary["instants"] == 1
    trace = json.loads(out.read_text())["traceEvents"]
    names = {e["args"]["name"] for e in trace if e["ph"] == "M"}
    assert names == {"fit", "r0", "main"}  # the instant's default track
    xs = [e for e in trace if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"fit", "serve"}
    assert all(e["dur"] >= 1.0 for e in xs)  # sub-µs spans stay visible
    assert viewer.main(["--jsonl", str(path), "--validate"]) == 0

    orphan = tmp_path / "orphan.jsonl"
    orphan.write_text(json.dumps(_span("x", "z", "missing")) + "\n")
    assert viewer.main(["--jsonl", str(orphan), "--validate"]) == 1
    assert viewer.main(["--jsonl", str(orphan), "--out", str(out)]) == 1


def test_viewer_trace_id_filter(tmp_path):
    path = tmp_path / "t.jsonl"
    rows = [
        _span("fit", "a"),
        dict(_span("fit", "b"), trace_id="t2"),
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    summary = viewer.export(str(path), trace_id="t2")
    assert summary["spans"] == 1 and summary["traces"] == ["t2"]
