"""Distributed (mesh) weighted quantile/median: gather-free histogram
refinement must match the exact local kernel bit-for-bit, and must not
materialize the column on any device.

The reference computes these statistics with a streaming Greenwald-Khanna
sketch (`GBMRegressor.scala:306,342-353`, `DummyRegressor.scala:123`) so no
executor ever holds the full column; the mesh path here keeps that scaling
contract (psum-ed O(bins) state per round) while being exact where the
reference approximates.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from spark_ensemble_tpu.utils.quantile import (
    weighted_median,
    weighted_quantile,
)


@pytest.fixture(scope="module")
def mesh8(data_mesh8):
    return data_mesh8


def _dist_quantile(mesh, v, w, q):
    f = shard_map(
        lambda vv, ww: weighted_quantile(vv, q, ww, axis_name="data"),
        mesh=mesh,
        in_specs=(P("data"), P("data")),
        out_specs=P(),
    )
    return np.asarray(jax.jit(f)(jnp.asarray(v), jnp.asarray(w)))


def _mixed_values(rng, n):
    """Values spanning binades (1e-6..1e6), negatives, and heavy repeats —
    the cases a value-space (non-bit-space) bisection would need ~30 rounds
    to separate."""
    v = np.concatenate(
        [
            rng.randn(n // 4) * 1e-6,
            rng.randn(n // 4) * 1e6,
            rng.randn(n // 4),
            np.repeat(rng.randn(16), (n // 4) // 16),
        ]
    ).astype(np.float32)
    rng.shuffle(v)
    return v


def test_mesh_quantile_matches_exact_kernel(mesh8):
    rng = np.random.RandomState(3)
    for trial in range(2):
        v = _mixed_values(rng, 4096)
        # quarter-integer weights: every partial sum is f32-exact, so the
        # mesh path's different accumulation order cannot shift near-ties
        w = (rng.randint(0, 8, size=v.shape[0]) / 4.0).astype(np.float32)
        for q in (0.0, 0.1, 0.5, 0.9, 1.0):
            exact = float(weighted_quantile(jnp.asarray(v), q, jnp.asarray(w)))
            got = float(_dist_quantile(mesh8, v, w, q))
            assert got == exact, (trial, q, exact, got)


def test_mesh_median_matches_exact_kernel(mesh8):
    rng = np.random.RandomState(4)
    v = _mixed_values(rng, 2048)
    w = (rng.randint(0, 5, size=v.shape[0]) / 2.0).astype(np.float32)
    exact = float(weighted_median(jnp.asarray(v), jnp.asarray(w)))
    f = shard_map(
        lambda vv, ww: weighted_median(vv, ww, axis_name="data"),
        mesh=mesh8,
        in_specs=(P("data"), P("data")),
        out_specs=P(),
    )
    got = float(jax.jit(f)(jnp.asarray(v), jnp.asarray(w)))
    assert got == exact


def test_mesh_quantile_vector_q(mesh8):
    rng = np.random.RandomState(5)
    v = rng.randn(1024).astype(np.float32)
    w = np.ones(1024, np.float32)
    qs = np.array([0.25, 0.5, 0.75], np.float32)
    exact = np.asarray(weighted_quantile(jnp.asarray(v), qs, jnp.asarray(w)))
    got = _dist_quantile(mesh8, v, w, qs)
    np.testing.assert_array_equal(exact, got)


def test_mesh_quantile_never_gathers_the_column(mesh8):
    """The scaling contract itself: the compiled sharded program reduces
    (psum/pmin/pmax of O(bins) state) but never all-gathers the values —
    no device ever holds the full column."""
    v = jnp.arange(4096, dtype=jnp.float32)
    w = jnp.ones(4096, jnp.float32)
    f = shard_map(
        lambda vv, ww: weighted_quantile(vv, 0.9, ww, axis_name="data"),
        mesh=mesh8,
        in_specs=(P("data"), P("data")),
        out_specs=P(),
    )
    hlo = jax.jit(f).lower(v, w).compile().as_text()
    assert "all-gather" not in hlo, "quantile gathered the full column"
    assert "all-reduce" in hlo  # the psum-ed histogram state


def test_mesh_quantile_matmul_and_scatter_hists_agree(mesh8, monkeypatch):
    """The one-hot-matmul (accelerator) and segment_sum (CPU / above the
    cell budget) histogram paths produce the same exact result.  CPU tests
    default to scatter, so the matmul path is forced explicitly here."""
    import spark_ensemble_tpu.utils.quantile as qmod

    rng = np.random.RandomState(6)
    v = _mixed_values(rng, 2048)
    w = (rng.randint(0, 8, size=v.shape[0]) / 4.0).astype(np.float32)
    for forced in (True, False):
        monkeypatch.setattr(qmod, "_use_matmul_hist", lambda n: forced)
        for q in (0.1, 0.5, 0.9):
            exact = float(weighted_quantile(jnp.asarray(v), q, jnp.asarray(w)))
            got = float(_dist_quantile(mesh8, v, w, q))
            assert got == exact, (forced, q, exact, got)


def test_mesh_quantile_zero_weight_nan_does_not_poison(mesh8):
    """A NaN value masked out with weight 0 (how callers drop bad rows)
    must not leak into the result — jnp.min/max would propagate it into
    the bracket seed; the seed excludes NaNs instead."""
    rng = np.random.RandomState(7)
    v = rng.randn(512).astype(np.float32)
    w = np.ones(512, np.float32)
    v[17] = np.nan
    w[17] = 0.0
    exact = float(
        weighted_quantile(
            jnp.asarray(np.delete(v, 17)), 0.5, jnp.asarray(np.delete(w, 17))
        )
    )
    got = float(_dist_quantile(mesh8, v, w, 0.5))
    assert got == exact, (exact, got)


def test_mesh_quantile_target_above_total_degrades_to_max(mesh8):
    """General f32 weights sum in a different order in the psum-ed
    histogram than in the separately-psum-ed total, so the crossing target
    can exceed the final cumulative by a ULP.  The refinement must then
    converge on the data MAX (the exact kernel's clipped index), not jump
    past the bracket into a non-data value."""
    from spark_ensemble_tpu.utils.quantile import _sharded_crossing_key

    v = np.arange(1.0, 65.0, dtype=np.float32)
    w = np.ones(64, np.float32)
    total = np.float32(64.0)
    target = np.nextafter(total, np.float32(np.inf), dtype=np.float32)

    f = shard_map(
        lambda vv, ww: _sharded_crossing_key(
            vv, ww, jnp.float32(target), "data"
        ),
        mesh=mesh8,
        in_specs=(P("data"), P("data")),
        out_specs=P(),
    )
    from spark_ensemble_tpu.utils.quantile import _key_to_f32

    got = float(_key_to_f32(jax.jit(f)(jnp.asarray(v), jnp.asarray(w))))
    assert got == 64.0, got


def test_mesh_quantile_zero_weight_values_not_selected(mesh8):
    """`Utils.scala:26-40` rule: zero-weight entries cannot be selected
    (unless tied with the crossing value).  The global minimum has zero
    weight here and must be skipped for q>0."""
    v = np.arange(64, dtype=np.float32)
    w = np.ones(64, np.float32)
    w[0] = 0.0  # zero-weight global min
    got = float(_dist_quantile(mesh8, v, w, 0.001))
    assert got == 1.0  # first POSITIVE-weight value crossing the target
