"""Worker for the 2-process ``jax.distributed`` rendezvous tests (run by
``tests/test_multiprocess.py`` as a subprocess, once per process id).

Joins the CPU rendezvous via ``parallel.multihost.initialize`` — the
process_count>1 branch a single-process suite can never execute — and builds
a GLOBAL 4-device mesh (2 processes x 2 virtual CPU devices).  Three modes
(``argv[3]``, default ``basic``):

- ``basic``: one psum-ed GBMRegressor fit step over the global mesh; prints
  ``MULTIHOST_OK`` only if the fitted params are finite and every
  cross-process collective completed.
- ``dist``: distributed-histogram streaming fits over the global mesh with
  each process reading only its manifest slice (subset-verified store
  opens); asserts bit-identity against a process-local single-host
  streaming fit and a FIXED traced-program count across two shard sizes;
  prints ``DIST_OK``.
- ``elastic``: a deterministic mid-round ``host_preempt`` kills process 1;
  the survivor rewinds to the last committed round checkpoint, repartitions
  the orphaned manifest slice onto its own devices, resumes, and asserts
  bit-identity against the uninterrupted reference; prints ``ELASTIC_OK``
  (survivor) / ``PREEMPT_EXIT_OK`` (victim).

``dist``/``elastic`` take a shared scratch directory as ``argv[4]`` and
write per-host telemetry JSONL next to it (``telemetry_p{pid}.jsonl``).
"""

import os
import sys
import time


def _await_file(path, timeout=300.0):
    deadline = time.monotonic() + timeout
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise TimeoutError(f"timed out waiting for {path}")
        time.sleep(0.05)


def _touch(path):
    with open(path, "w") as f:
        f.write("ok\n")


def _assert_bit_identical(m1, m2):
    import jax
    import numpy as np

    l1 = jax.tree_util.tree_leaves(m1.params)
    l2 = jax.tree_util.tree_leaves(m2.params)
    assert len(l1) == len(l2)
    for a, b in zip(l1, l2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _make_store(workdir, pid, shard_rows, name):
    """Process 0 seals the store; process 1 waits, then opens a
    subset-verified handle covering only the shards its mesh row
    positions will ever read."""
    import numpy as np

    from spark_ensemble_tpu.data import write_shards
    from spark_ensemble_tpu.data.partition import partition_shards
    from spark_ensemble_tpu.data.shards import ShardStore

    sdir = os.path.join(workdir, name)
    ready = sdir + ".ready"
    rng = np.random.RandomState(7)
    X = rng.randn(300, 6).astype(np.float32)
    y = (X @ rng.randn(6) + 0.1 * rng.randn(300)).astype(np.float32)
    if pid == 0:
        store = write_shards(X, sdir, max_bins=16, shard_rows=shard_rows)
        _touch(ready)
    else:
        _await_file(ready)
        store = ShardStore.open(sdir)
    # re-open with only this host's manifest slice verified: positions
    # {2*pid, 2*pid+1} of the 4-wide row mesh, round-robin over shards
    mine = set()
    for w in (2 * pid, 2 * pid + 1):
        mine.update(partition_shards(store.num_shards, 4, w))
    sub = ShardStore.open(sdir, shards=sorted(mine))
    assert sub.verified_shards == frozenset(mine)
    return store, sub, X, y


def _streaming_reg(ckdir=None):
    from spark_ensemble_tpu import DecisionTreeRegressor, GBMRegressor

    kw = dict(
        base_learner=DecisionTreeRegressor(
            max_depth=3, max_bins=16, hist="stream"
        ),
        num_base_learners=3,
        seed=0,
    )
    if ckdir is not None:
        kw.update(checkpoint_dir=ckdir, checkpoint_interval=1)
    return GBMRegressor(**kw)


def _run_dist(pid, workdir) -> int:
    """Distributed-histogram fits over the REAL two-process mesh: each
    host streams only its manifest slice, the reduce crosses the process
    boundary, and the result must match a process-local single-host fit
    bit-for-bit — with one traced-program count across shard sizes."""
    from spark_ensemble_tpu.analysis.contracts import _ProgramRecorder
    from spark_ensemble_tpu.models.base import observe_program_calls
    from spark_ensemble_tpu.parallel.mesh import data_member_mesh

    mesh = data_member_mesh(4, member=1)
    counts = {}
    for shard_rows in (32, 16):
        store, sub, X, y = _make_store(
            workdir, pid, shard_rows, f"store{shard_rows}"
        )
        rec = _ProgramRecorder()
        with observe_program_calls(rec):
            dist = _streaming_reg().fit_streaming(sub, y, mesh=mesh)
        counts[shard_rows] = rec.count()
        if shard_rows == 32:
            ref = _streaming_reg().fit_streaming(store, y)
            _assert_bit_identical(ref, dist)
    assert len(set(counts.values())) == 1, counts
    print("DIST_OK", flush=True)
    return 0


class _HostPreemptAt:
    """Deterministic single-shot host_preempt at one site, pinned victim;
    optionally also a single-shot host_stall at another site (the skew
    report must name the stalled host)."""

    enabled = True

    def __init__(self, site, victim, stall_site=None, stall_victim=0,
                 stall_s=0.4):
        self.site = site
        self.victim = victim
        self.stall_site = stall_site
        self.stall_victim = stall_victim
        self.stall_s = stall_s
        self.fired = []
        self.stalled = []

    def host_preempt(self, site):
        if site == self.site and not self.fired:
            self.fired.append(site)
            return True
        return False

    def host_stall_s(self, site, seconds=0.25):
        # verdict is site-deterministic, so every process agrees without
        # communicating; only the picked victim actually sleeps
        if site == self.stall_site and not self.stalled:
            self.stalled.append(site)
            return self.stall_s
        return 0.0

    def pick(self, fault, site, n):
        if fault == "host_stall":
            return self.stall_victim % n
        return self.victim % n

    def preempt(self, site):
        pass

    def transient(self, site):
        pass

    def poison_array(self, site, arr):
        return arr

    def poison_member_stack(self, site, tree):
        return tree

    def poison_tree(self, site, tree):
        return tree

    def corrupt_checkpoint(self, site, state_path):
        pass


def _run_elastic(pid, workdir) -> int:
    """Mid-round host_preempt kills process 1; process 0 rewinds to the
    last committed round checkpoint, repartitions the orphaned slice
    onto its own devices, resumes, and must land on the same bits as an
    uninterrupted fit.  The victim stays parked until the survivor
    signals completion so the rendezvous stays alive."""
    from spark_ensemble_tpu.parallel.elastic import ElasticCoordinator
    from spark_ensemble_tpu.parallel.mesh import data_member_mesh
    from spark_ensemble_tpu.robustness import chaos
    from spark_ensemble_tpu.robustness.chaos import ChaosHostPreemption

    mesh = data_member_mesh(4, member=1)
    store, _sub, X, y = _make_store(workdir, pid, 32, "store_el")
    done = os.path.join(workdir, "elastic.done")

    site = "GBMRegressor:stream_round:2:level:1:dist_step:1"
    # host 0 also stalls once in round 1 (before the preemption round):
    # the pod skew report must attribute that round to host 0
    chaos.install(_HostPreemptAt(
        site, victim=1,
        stall_site="GBMRegressor:stream_round:1:level:0:dist_step:0",
        stall_victim=0,
    ))
    coord = ElasticCoordinator(mesh)
    try:
        model = coord.fit_streaming(
            _streaming_reg(os.path.join(workdir, f"ck{pid}")), store, y
        )
    except ChaosHostPreemption:
        # this process IS the preempted host: the crash flight recorder
        # must already have landed next to the telemetry stream (the
        # preempt path dumps + fsyncs BEFORE re-raising)
        import json

        fl = os.path.join(workdir, f"flight_p{os.getpid()}.json")
        with open(fl) as f:
            payload = json.load(f)
        assert payload["rows"], payload
        assert payload["recorded"] > 0
        print("FLIGHT_OK", flush=True)
        # park until the survivor finishes (exiting would tear down the
        # coordination service)
        print("PREEMPTED", flush=True)
        _await_file(done)
        print("PREEMPT_EXIT_OK", flush=True)
        return 0
    finally:
        chaos.install(None)

    assert pid == 0, "victim process must not complete the fit"
    assert [(v, s) for v, s, _ in coord.losses] == [(1, site)]
    assert coord.mesh.shape["data"] == 2  # survivors repartitioned
    ref = _streaming_reg().fit_streaming(store, y)
    _assert_bit_identical(ref, model)
    _touch(done)
    print("ELASTIC_OK", flush=True)
    return 0


def main() -> int:
    port = sys.argv[1]
    pid = int(sys.argv[2])
    mode = sys.argv[3] if len(sys.argv) > 3 else "basic"
    workdir = sys.argv[4] if len(sys.argv) > 4 else None
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    if workdir is not None:
        os.environ["SE_TPU_TELEMETRY"] = os.path.join(
            workdir, f"telemetry_p{pid}.jsonl"
        )
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:  # cross-process CPU collectives need the gloo transport
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass

    from spark_ensemble_tpu.parallel import multihost

    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=pid,
    )
    assert multihost.process_count() == 2, multihost.process_count()
    assert multihost.process_index() == pid
    assert len(jax.devices()) == 4, jax.devices()
    assert multihost.local_device_count() == 2

    if mode == "dist":
        return _run_dist(pid, workdir)
    if mode == "elastic":
        return _run_elastic(pid, workdir)
    assert mode == "basic", mode

    # a raw cross-process psum first: the global mesh's collective seam
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from spark_ensemble_tpu.parallel.mesh import data_member_mesh

    m = data_member_mesh(4, member=1)
    x = np.arange(8, dtype=np.float32)
    xs = jax.device_put(
        x, jax.sharding.NamedSharding(m, P(("data",)))
    )
    total = shard_map(
        lambda v: jax.lax.psum(jnp.sum(v), "data"),
        mesh=m,
        in_specs=P("data"),
        out_specs=P(),
    )(xs)
    np.testing.assert_allclose(np.asarray(total), x.sum())

    # one GBM fit step on the global mesh (psum-ed histograms/objective)
    from spark_ensemble_tpu import GBMRegressor

    rng = np.random.RandomState(0)
    X = rng.randn(512, 8).astype(np.float32)
    y = (X @ rng.randn(8).astype(np.float32)).astype(np.float32)
    model = GBMRegressor(num_base_learners=1).fit(X, y, mesh=m)
    leaves = jax.tree_util.tree_leaves(model.params)
    assert all(np.isfinite(np.asarray(leaf)).all() for leaf in leaves)

    # the stream histogram tier's post-scan psum must also cross the
    # process boundary (the HBM-scale path on a real pod)
    from spark_ensemble_tpu import DecisionTreeRegressor

    s_model = GBMRegressor(
        num_base_learners=1,
        base_learner=DecisionTreeRegressor(hist="stream"),
    ).fit(X, y, mesh=m)
    s_leaves = jax.tree_util.tree_leaves(s_model.params)
    assert all(np.isfinite(np.asarray(leaf)).all() for leaf in s_leaves)

    print("MULTIHOST_OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
