"""Worker for the 2-process ``jax.distributed`` rendezvous test (run by
``tests/test_multiprocess.py`` as a subprocess, once per process id).

Joins the CPU rendezvous via ``parallel.multihost.initialize`` — the
process_count>1 branch a single-process suite can never execute — builds a
GLOBAL 4-device mesh (2 processes x 2 virtual CPU devices), and runs one
psum-ed GBMRegressor fit step over it.  Prints ``MULTIHOST_OK`` only if the
fitted params are finite and every cross-process collective completed.
"""

import os
import sys


def main() -> int:
    port = sys.argv[1]
    pid = int(sys.argv[2])
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from spark_ensemble_tpu.parallel import multihost

    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=pid,
    )
    assert multihost.process_count() == 2, multihost.process_count()
    assert multihost.process_index() == pid
    assert len(jax.devices()) == 4, jax.devices()
    assert multihost.local_device_count() == 2

    # a raw cross-process psum first: the global mesh's collective seam
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from spark_ensemble_tpu.parallel.mesh import data_member_mesh

    m = data_member_mesh(4, member=1)
    x = np.arange(8, dtype=np.float32)
    xs = jax.device_put(
        x, jax.sharding.NamedSharding(m, P(("data",)))
    )
    total = shard_map(
        lambda v: jax.lax.psum(jnp.sum(v), "data"),
        mesh=m,
        in_specs=P("data"),
        out_specs=P(),
    )(xs)
    np.testing.assert_allclose(np.asarray(total), x.sum())

    # one GBM fit step on the global mesh (psum-ed histograms/objective)
    from spark_ensemble_tpu import GBMRegressor

    rng = np.random.RandomState(0)
    X = rng.randn(512, 8).astype(np.float32)
    y = (X @ rng.randn(8).astype(np.float32)).astype(np.float32)
    model = GBMRegressor(num_base_learners=1).fit(X, y, mesh=m)
    leaves = jax.tree_util.tree_leaves(model.params)
    assert all(np.isfinite(np.asarray(leaf)).all() for leaf in leaves)

    # the stream histogram tier's post-scan psum must also cross the
    # process boundary (the HBM-scale path on a real pod)
    from spark_ensemble_tpu import DecisionTreeRegressor

    s_model = GBMRegressor(
        num_base_learners=1,
        base_learner=DecisionTreeRegressor(hist="stream"),
    ).fit(X, y, mesh=m)
    s_leaves = jax.tree_util.tree_leaves(s_model.params)
    assert all(np.isfinite(np.asarray(leaf)).all() for leaf in s_leaves)

    print("MULTIHOST_OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
