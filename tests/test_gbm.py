"""GBM tests (mirrors `GBMRegressorSuite.scala` / `GBMClassifierSuite.scala`:
beats-baseline, monotone prefix improvement, early-stop exactness)."""

import numpy as np
import pytest

import spark_ensemble_tpu as se
from tests.conftest import accuracy, rmse, split


@pytest.mark.slow
def test_gbm_regressor_beats_single_tree(cpusmall):
    X, y = cpusmall
    Xtr, ytr, Xte, yte = split(X, y)
    tree = se.DecisionTreeRegressor(max_depth=5).fit(Xtr, ytr)
    gbm = se.GBMRegressor(
        base_learner=se.DecisionTreeRegressor(max_depth=5), num_base_learners=10
    ).fit(Xtr, ytr)
    assert rmse(gbm.predict(Xte), yte) < rmse(tree.predict(Xte), yte)


@pytest.mark.parametrize("loss", ["squared", "absolute", "huber", "quantile"])
def test_gbm_regressor_losses_train(cpusmall, loss):
    X, y = cpusmall
    Xtr, ytr, Xte, yte = split(X, y)
    gbm = se.GBMRegressor(num_base_learners=5, loss=loss, alpha=0.5).fit(Xtr, ytr)
    # every loss must do clearly better than predicting the train median
    base = rmse(np.full_like(yte, float(np.median(ytr))), yte)
    assert rmse(gbm.predict(Xte), yte) < base


def test_gbm_regressor_newton_updates(cpusmall):
    X, y = cpusmall
    Xtr, ytr, Xte, yte = split(X, y)
    gbm = se.GBMRegressor(num_base_learners=8, updates="newton").fit(Xtr, ytr)
    tree = se.DecisionTreeRegressor(max_depth=5).fit(Xtr, ytr)
    assert rmse(gbm.predict(Xte), yte) < rmse(tree.predict(Xte), yte)


def test_gbm_prefix_models_mostly_improve(cpusmall):
    """`GBMRegressorSuite.scala:126-164`: >= 0.8 of prefix steps improve.

    The 0.8 threshold is a statistical property of the REAL 8191-row
    cpusmall dataset the reference suite asserts on.  The synthetic
    stand-in (2000 rows, 0.1 label noise) reaches its noise floor after
    ~4 full-step (lr=1.0) rounds, after which test-set prefix deltas are
    sign-random — the fraction lands ~0.57, deterministically, and says
    nothing about the round loop (scan-chunk invariance and the early-stop
    sweep pin the round math elsewhere in this file).  Assert only where
    the property holds: on the reference data."""
    from spark_ensemble_tpu.utils import datasets as ds

    if not ds.has_reference_data():
        pytest.skip(
            "prefix-improvement threshold is a property of the real "
            "cpusmall dataset; the synthetic stand-in hits its noise "
            "floor after ~4 lr=1.0 rounds and later steps are sign-random"
        )
    X, y = cpusmall
    Xtr, ytr, Xte, yte = split(X, y)
    gbm = se.GBMRegressor(num_base_learners=8).fit(Xtr, ytr)
    errs = [rmse(gbm.take(k).predict(Xte), yte) for k in range(1, gbm.num_members + 1)]
    improving = sum(b <= a for a, b in zip(errs, errs[1:]))
    assert improving / max(len(errs) - 1, 1) >= 0.8


@pytest.mark.slow
def test_gbm_early_stop_matches_offline_sweep(cpusmall):
    """`GBMRegressorSuite.scala:78-124`: the early-stopped member count equals
    the index an offline sweep of prefix models finds."""
    X, y = cpusmall
    rng = np.random.RandomState(0)
    vi = rng.rand(X.shape[0]) < 0.25
    gbm_es = se.GBMRegressor(
        num_base_learners=20, num_rounds=1, validation_tol=0.01, seed=5
    ).fit(X, y, validation_indicator=vi)

    # offline: train without early stop, sweep prefixes on the validation set
    gbm_full = se.GBMRegressor(num_base_learners=20, seed=5).fit(
        X[~vi], y[~vi]
    )
    from spark_ensemble_tpu.ops.losses import SquaredLoss

    loss = SquaredLoss()
    errors = []
    for k in range(1, gbm_full.num_members + 1):
        pred = np.asarray(gbm_full.take(k).predict(X[vi]))
        errors.append(float(np.mean(0.5 * (pred - y[vi]) ** 2)))
    # replay the reference patience rule (`GBMRegressor.scala:457-465`)
    best, v, stop = errors[0], 0, len(errors)
    for i, err in enumerate(errors[1:], start=1):
        if best - err < 0.01 * max(err, 0.01):
            v += 1
        else:
            best, v = err, 0
        if v >= 1:
            stop = i + 1
            break
    expected_members = stop - v
    assert gbm_es.num_members == expected_members


@pytest.mark.slow
def test_gbm_scan_chunk_invariance(cpusmall):
    """The scan-chunked round loop must produce the same model regardless of
    chunk size (chunk=1 is the per-round baseline): round math is identical,
    only dispatch granularity changes.  Huber exercises the in-scan adaptive
    delta."""
    X, y = cpusmall
    Xtr, ytr, _, _ = split(X, y)
    preds = []
    for chunk in (1, 3, 16):
        m = se.GBMRegressor(
            num_base_learners=5, loss="huber", updates="newton",
            subsample_ratio=0.8, scan_chunk=chunk, seed=3,
        ).fit(Xtr, ytr)
        preds.append(np.asarray(m.predict(Xtr)))
    np.testing.assert_allclose(preds[0], preds[1], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(preds[0], preds[2], rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_gbm_classifier_scan_chunk_invariance_with_validation(letter):
    """Chunked early stopping must pick the same stop round and members as
    per-round (chunk=1) fitting, including a mid-chunk stop."""
    X, y = letter
    rng = np.random.RandomState(1)
    vi = rng.rand(X.shape[0]) < 0.3
    models = [
        se.GBMClassifier(
            num_base_learners=8, num_rounds=1, validation_tol=0.5,
            learning_rate=0.5, scan_chunk=chunk, seed=2,
        ).fit(X, y, validation_indicator=vi)
        for chunk in (1, 5)
    ]
    assert models[0].num_members == models[1].num_members
    np.testing.assert_allclose(
        np.asarray(models[0].predict_raw(X[:200])),
        np.asarray(models[1].predict_raw(X[:200])),
        rtol=1e-5, atol=1e-5,
    )


@pytest.mark.slow
def test_gbm_classifier_beats_single_tree_multiclass(letter):
    X, y = letter
    Xtr, ytr, Xte, yte = split(X, y)
    tree = se.DecisionTreeClassifier(max_depth=5).fit(Xtr, ytr)
    gbm = se.GBMClassifier(
        base_learner=se.DecisionTreeRegressor(max_depth=5), num_base_learners=5
    ).fit(Xtr, ytr)
    assert accuracy(gbm.predict(Xte), yte) > accuracy(tree.predict(Xte), yte)


@pytest.mark.slow
@pytest.mark.parametrize("loss", ["bernoulli", "exponential"])
def test_gbm_classifier_binary_losses(adult_full, loss):
    """`GBMClassifierSuite.scala:89-146` (binary, newton updates)."""
    X, y = adult_full
    Xtr, ytr, Xte, yte = split(X, y)
    tree = se.DecisionTreeClassifier(max_depth=5).fit(Xtr, ytr)
    gbm = se.GBMClassifier(num_base_learners=10, loss=loss, updates="newton").fit(
        Xtr, ytr
    )
    assert accuracy(gbm.predict(Xte), yte) >= accuracy(tree.predict(Xte), yte) - 0.01


@pytest.mark.slow
def test_gbm_classifier_proba_shapes(letter):
    X, y = letter
    Xtr, ytr, Xte, _ = split(X, y)
    k = int(y.max()) + 1
    gbm = se.GBMClassifier(num_base_learners=3).fit(Xtr, ytr)
    raw = np.asarray(gbm.predict_raw(Xte[:20]))
    proba = np.asarray(gbm.predict_proba(Xte[:20]))
    assert raw.shape == (20, k)
    assert proba.shape == (20, k)
    assert np.allclose(proba.sum(-1), 1.0, atol=1e-5)


def test_gbm_subbagging_trains(cpusmall):
    X, y = cpusmall
    Xtr, ytr, Xte, yte = split(X, y)
    gbm = se.GBMRegressor(
        num_base_learners=8,
        subsample_ratio=0.6,
        subspace_ratio=0.8,
        replacement=False,
    ).fit(Xtr, ytr)
    base = rmse(np.full_like(yte, float(np.mean(ytr))), yte)
    assert rmse(gbm.predict(Xte), yte) < 0.7 * base


def test_gbm_unoptimized_weights(cpusmall):
    X, y = cpusmall
    Xtr, ytr, Xte, yte = split(X, y)
    gbm = se.GBMRegressor(
        num_base_learners=5, optimized_weights=False, learning_rate=0.5
    ).fit(Xtr, ytr)
    base = rmse(np.full_like(yte, float(np.mean(ytr))), yte)
    assert rmse(gbm.predict(Xte), yte) < base


def test_gbm_init_strategies(cpusmall):
    X, y = cpusmall
    Xtr, ytr, Xte, yte = split(X, y)
    for strategy in ["constant", "zero", "base"]:
        gbm = se.GBMRegressor(num_base_learners=3, init_strategy=strategy).fit(
            Xtr, ytr
        )
        base = rmse(np.full_like(yte, float(np.mean(ytr))), yte)
        assert rmse(gbm.predict(Xte), yte) < base


@pytest.mark.slow
def test_gbm_classifier_validation_fold_missing_top_class():
    """Regression: the init DummyClassifier must be sized by the explicit
    class count even when the train split is missing the top class."""
    rng = np.random.RandomState(0)
    X = rng.randn(200, 4).astype(np.float32)
    y = np.where(X[:, 0] > 0, 1.0, 0.0).astype(np.float32)
    y[:8] = 2.0
    vi = np.zeros(200, bool)
    vi[:8] = True  # every class-2 row held out for validation
    model = se.GBMClassifier(num_base_learners=2).fit(
        X, y, validation_indicator=vi
    )
    assert model.num_classes == 3
    assert model.predict_raw(X[:5]).shape == (5, 3)


def test_gbm_with_dummy_base_learner():
    """Regression: every BaseLearner must accept the axis_name kwarg the
    GBM round passes (DummyRegressor missed it when the mesh path landed)."""
    rng = np.random.RandomState(1)
    X = rng.randn(150, 3).astype(np.float32)
    y = (X[:, 0] + 0.1 * rng.randn(150)).astype(np.float32)
    model = se.GBMRegressor(
        base_learner=se.DummyRegressor(strategy="mean"), num_base_learners=2
    ).fit(X, y)
    assert np.all(np.isfinite(np.asarray(model.predict(X[:5]))))


@pytest.mark.slow
def test_gbm_classifier_binary_prior_with_no_positives_in_train():
    """Regression: explicit num_classes with zero train positives must give
    a finite (clamped) log-odds init, not -inf."""
    rng = np.random.RandomState(2)
    X = rng.randn(120, 3).astype(np.float32)
    y = np.zeros(120, np.float32)
    y[100:] = 1.0
    vi = np.zeros(120, bool)
    vi[100:] = True  # all positives held out
    model = se.GBMClassifier(num_base_learners=2, loss="bernoulli").fit(
        X, y, validation_indicator=vi
    )
    raw = np.asarray(model.predict_raw(X[:5]))
    assert np.all(np.isfinite(raw)), raw


def test_gbm_with_linear_base_learner():
    """Non-tree base learners ride the default vmapped fit_many path inside
    the scanned round loop (no fused-forest specialization) — both flavors
    must train and beat trivial baselines."""
    rng = np.random.RandomState(0)
    X = rng.randn(1500, 6).astype(np.float32)
    y = (2 * X[:, 0] + X[:, 1] + 0.1 * rng.randn(1500)).astype(np.float32)
    m = se.GBMRegressor(
        base_learner=se.LinearRegression(), num_base_learners=4, learning_rate=0.5
    ).fit(X, y)
    assert rmse(m.predict(X), y) < 0.5 * float(np.std(y))

    yc = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
    mc = se.GBMClassifier(
        base_learner=se.LinearRegression(), num_base_learners=3, loss="logloss"
    ).fit(X, yc)
    assert accuracy(mc.predict(X), yc) > 0.9


def test_sampling_plan_bit_identical_to_eager_loop():
    """The one-program sampling plan must reproduce the reference-mirroring
    eager draw tree exactly (`GBMRegressor.scala:282-284` seed discipline):
    per member i, mask = subspace_mask(fold_in(fold_in(root, i), 1)) and
    bag key = fold_in(fold_in(root, i), 2)."""
    import jax

    from spark_ensemble_tpu.utils.random import subspace_mask

    est = se.GBMRegressor(num_base_learners=9, subspace_ratio=0.6, seed=7)
    bag_keys, masks = est._sampling_plan(100, 11)
    root = jax.random.PRNGKey(7)
    for i in [0, 3, 8]:
        k = jax.random.fold_in(root, i)
        np.testing.assert_array_equal(
            np.asarray(subspace_mask(jax.random.fold_in(k, 1), 11, 0.6)),
            np.asarray(masks[i]),
        )
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(jax.random.fold_in(k, 2))),
            np.asarray(jax.random.key_data(bag_keys[i])),
        )


@pytest.mark.slow
def test_validation_history_recorded(cpusmall):
    """Models fit with a validation split expose the per-round validation
    loss curve; its argmin-side structure matches the early-stop result:
    history covers exactly the evaluated rounds (kept + patience), and a
    fit without validation raises."""
    X, y = cpusmall
    rng = np.random.RandomState(0)
    is_val = rng.rand(len(X)) < 0.25
    m = se.GBMRegressor(num_base_learners=12, num_rounds=2).fit(
        X, y, validation_indicator=is_val
    )
    hist = m.validation_history_
    assert hist.ndim == 1 and len(hist) >= m.num_members
    # the early-stop accounting: evaluated rounds = kept + patience overrun
    assert len(hist) <= 12
    assert np.all(np.isfinite(hist))

    m2 = se.GBMRegressor(num_base_learners=3).fit(X[:500], y[:500])
    with pytest.raises(AttributeError):
        m2.validation_history_
    # prefix models carry the aligned prefix of the curve
    np.testing.assert_allclose(m.take(2).validation_history_, hist[:2])


def test_predict_row_chunking_matches_direct(monkeypatch):
    """HBM-scale inference: past _PREDICT_CHUNK_CELLS the model predicts
    via lax.map over row chunks (models/gbm.py _predict_chunked_rows) —
    pinning a tiny budget must not change a single prediction, incl. a
    non-divisible row count (padding)."""
    import spark_ensemble_tpu.ops.tree as T

    rng = np.random.RandomState(31)
    # > the 1024-row chunk floor AND not a multiple of it, so the chunked
    # branch (lax.map + padding) genuinely executes under the tiny budget
    n = 2500
    X = rng.randn(n, 6).astype(np.float32)
    yc = (X[:, 0] + 0.3 * rng.randn(n) > 0).astype(np.float32)
    yr = (X @ rng.randn(6) + 0.1 * rng.randn(n)).astype(np.float32)

    cm = se.GBMClassifier(num_base_learners=3, seed=0).fit(X, yc)
    rm = se.GBMRegressor(num_base_learners=3, seed=0).fit(X, yr)
    raw_direct = np.asarray(cm.predict_raw(X))
    reg_direct = np.asarray(rm.predict(X))

    monkeypatch.setattr(T, "_PREDICT_FUSED_MAX_CELLS", 64 * 1024)
    # drop the cached direct-path jits so the tiny budget is retraced
    object.__setattr__(cm, "_jit_cache", {})
    object.__setattr__(rm, "_jit_cache", {})
    np.testing.assert_allclose(
        np.asarray(cm.predict_raw(X)), raw_direct, rtol=1e-6, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(rm.predict(X)), reg_direct, rtol=1e-6, atol=1e-6
    )


def test_goss_sampling_trains_close_to_full_data():
    """sample_method='goss' (gradient-based one-side sampling,
    arXiv:1911.08820 family): with top 20% + amplified 10% of the rest,
    the fit must land close to the full-data fit and beat the constant
    baseline, and seeded runs must be deterministic."""
    rng = np.random.RandomState(41)
    n = 3000
    X = rng.randn(n, 8).astype(np.float32)
    y = (X @ rng.randn(8) + 0.3 * rng.randn(n)).astype(np.float32)
    cfg = dict(num_base_learners=10, learning_rate=0.3, seed=0)
    full = se.GBMRegressor(**cfg).fit(X, y)
    goss = se.GBMRegressor(sample_method="goss", **cfg).fit(X, y)
    goss2 = se.GBMRegressor(sample_method="goss", **cfg).fit(X, y)
    r_full = rmse(full.predict(X), y)
    r_goss = rmse(goss.predict(X), y)
    base = rmse(np.full_like(y, float(np.mean(y))), y)
    assert r_goss < 0.6 * base
    assert r_goss < 1.35 * r_full + 1e-6, (r_goss, r_full)
    np.testing.assert_array_equal(
        np.asarray(goss.predict(X)), np.asarray(goss2.predict(X))
    )
    # GOSS must actually engage: a silent no-op (e.g. a program-cache key
    # collision with the uniform fit) would reproduce full's predictions
    assert not np.array_equal(
        np.asarray(goss.predict(X)), np.asarray(full.predict(X))
    )


def test_goss_classifier_trains():
    rng = np.random.RandomState(42)
    n, k = 3000, 4
    X = rng.randn(n, 8).astype(np.float32)
    c = rng.randn(k, 8).astype(np.float32)
    y = np.argmax(X @ c.T + 0.5 * rng.randn(n, k), axis=1).astype(np.float32)
    m = se.GBMClassifier(
        sample_method="goss", num_base_learners=8, learning_rate=0.5,
        updates="newton", seed=1,
    ).fit(X, y)
    acc = float(np.mean(np.asarray(m.predict(X)) == y))
    assert acc > 0.75, acc


@pytest.mark.slow
def test_goss_mesh_metric_parity():
    """GOSS under a data mesh: the quantile threshold is the exact global
    crossing (psum-ed bit-space refinement) and the Bernoulli draws are
    shard-decorrelated, so mesh and single-device fits agree at the
    METRIC level (draw patterns differ by construction)."""
    import jax as _jax

    from spark_ensemble_tpu.parallel.mesh import data_member_mesh

    rng = np.random.RandomState(43)
    n = 2048
    X = rng.randn(n, 6).astype(np.float32)
    y = (X @ rng.randn(6) + 0.2 * rng.randn(n)).astype(np.float32)
    cfg = dict(
        sample_method="goss", num_base_learners=6, learning_rate=0.3, seed=2
    )
    single = se.GBMRegressor(**cfg).fit(X, y)
    dist = se.GBMRegressor(**cfg).fit(X, y, mesh=data_member_mesh(8, member=1))
    r_s, r_d = rmse(single.predict(X), y), rmse(dist.predict(X), y)
    assert abs(r_s - r_d) < 0.15 * max(r_s, r_d) + 1e-6, (r_s, r_d)
