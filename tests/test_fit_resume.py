"""Warm-start resume tests (docs/autopilot.md): ``fit_resume`` on every
stagewise family is PINNED bit-identical to a single longer fit — the
committed rounds are replayed host-free, the fresh fit re-enters the round
loop at the next absolute round index, and round keys/masks derive from
absolute indices so the larger plan is prefix-stable.  Also: the packed
round-trip (``take(k)`` -> ``fit_resume`` -> ``pack``), SAMME's terminal
convergence no-op, the pipelined variant, and a chaos ``refresh_crash``
mid-resume kill leaving the source model untouched and the resume
retryable."""

import numpy as np
import pytest

import jax

import spark_ensemble_tpu as se
from spark_ensemble_tpu.robustness import chaos
from spark_ensemble_tpu.robustness.chaos import (
    ChaosController,
    ChaosPreemption,
)
from spark_ensemble_tpu.serving import export, pack

K = 3       # committed rounds in the short fit
N_NEW = 3   # rounds added by the resume
N = K + N_NEW


def _reg_data(n=96, d=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


def _cls_data(n=96, d=5, seed=0, noisy=True):
    """3-class labels; ``noisy`` flips every 7th label so no tiny tree is
    perfect — SAMME's ``err <= 0`` early stop is a separate test."""
    X, y = _reg_data(n, d, seed)
    yc = np.digitize(y, np.quantile(y, [1 / 3, 2 / 3])).astype(np.float32)
    if noisy:
        yc[::7] = (yc[::7] + 1) % 3
    return X, yc


# family -> (estimator factory, data factory); every stagewise family the
# ISSUE names, with the weight recursions that make resume non-trivial:
# GBM cls with optimized line-search weights + non-unit lr (alpha_ws
# recovery), SAMME discrete + real (bw replay), Drucker (loss shaping)
FAMILIES = {
    "gbm_reg": (
        lambda n, **kw: se.GBMRegressor(num_base_learners=n, seed=0, **kw),
        _reg_data,
    ),
    "gbm_cls": (
        lambda n, **kw: se.GBMClassifier(
            num_base_learners=n, seed=0, learning_rate=0.5,
            optimized_weights=True, **kw,
        ),
        _cls_data,
    ),
    "samme_discrete": (
        lambda n, **kw: se.BoostingClassifier(
            num_base_learners=n, seed=0, algorithm="discrete", **kw,
        ),
        _cls_data,
    ),
    "samme_real": (
        lambda n, **kw: se.BoostingClassifier(
            num_base_learners=n, seed=0, algorithm="real", **kw,
        ),
        _cls_data,
    ),
    "drucker": (
        lambda n, **kw: se.BoostingRegressor(
            num_base_learners=n, seed=0, **kw,
        ),
        _reg_data,
    ),
}


def _assert_bit_identical(resumed, full, X):
    assert resumed.num_members == full.num_members
    fa, ta = jax.tree_util.tree_flatten(resumed.params)
    fb, tb = jax.tree_util.tree_flatten(full.params)
    assert ta == tb
    for a, b in zip(fa, fb):
        assert np.array_equal(
            np.asarray(a), np.asarray(b), equal_nan=True
        )
    np.testing.assert_array_equal(
        np.asarray(resumed.predict(X)), np.asarray(full.predict(X))
    )


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    yield
    chaos.install(None)


# ---------------------------------------------------------------------------
# the pin: resume k -> n is bit-identical to a straight n-round fit
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_fit_resume_bit_identical_to_longer_fit(family):
    make, data = FAMILIES[family]
    X, y = data()
    full = make(N).fit(X, y)
    short = make(K).fit(X, y)
    resumed = short.fit_resume(X, y, N_NEW)
    _assert_bit_identical(resumed, full, X)
    # the source model was never mutated: another resume from the same
    # committed state reproduces the same result (idempotent refresh)
    assert short.num_members == K
    again = short.fit_resume(X, y, N_NEW)
    _assert_bit_identical(again, full, X)


@pytest.mark.parametrize("family", ["gbm_reg", "samme_real"])
def test_fit_resume_pipelined_bit_identical(monkeypatch, family):
    """The lookahead pipeline speculates chunks but commits the same round
    math: resuming under ``SE_TPU_PIPELINE=1`` (chunked so the pipeline
    actually overlaps) still lands bit-identical to the straight fit."""
    monkeypatch.setenv("SE_TPU_PIPELINE", "1")
    make, data = FAMILIES[family]
    X, y = data()
    full = make(N, scan_chunk=2).fit(X, y)
    short = make(K, scan_chunk=2).fit(X, y)
    resumed = short.fit_resume(X, y, N_NEW)
    _assert_bit_identical(resumed, full, X)


def test_fit_resume_samme_converged_is_noop():
    """SAMME's ``err <= 0`` rule KEEPS the perfect member then stops; the
    carried weights alone cannot reveal that stop (beta=0 leaves bw
    positive), so ``fit_resume`` replays the final round error and
    returns the model unchanged — exactly what the longer fit produces."""
    X, _ = _reg_data()
    yc = (X[:, 0] > 0).astype(np.float32)  # one tree fits this perfectly
    make = FAMILIES["samme_discrete"][0]
    short = make(K).fit(X, yc)
    full = make(N).fit(X, yc)
    assert full.num_members == short.num_members  # the driver also stopped
    resumed = short.fit_resume(X, yc, N_NEW)
    assert resumed is short  # terminal convergence: resume is a no-op
    _assert_bit_identical(resumed, full, X)


def test_fit_resume_validates_args():
    X, y = _reg_data()
    model = se.GBMRegressor(num_base_learners=2, seed=0).fit(X, y)
    with pytest.raises(ValueError, match="n_new_rounds"):
        model.fit_resume(X, y, 0)
    with pytest.raises(ValueError, match="original training matrix"):
        model.fit_resume(X[:, :3], y, 2)


# ---------------------------------------------------------------------------
# packed round-trip: take(k) -> fit_resume -> pack
# ---------------------------------------------------------------------------


def test_packed_take_fit_resume_roundtrip():
    """The serving refresh path end to end: slice a served prefix with
    ``take(k)``, resume it for the remaining rounds, repack — bit-identical
    predictions to packing the straight n-round fit."""
    X, y = _reg_data()
    full = se.GBMRegressor(num_base_learners=N, seed=0).fit(X, y)
    p_full = pack(full)
    refreshed = export.fit_resume(p_full.take(K), X, y, N_NEW)
    assert refreshed.num_members == N
    np.testing.assert_array_equal(
        np.asarray(refreshed.predict(X)), np.asarray(p_full.predict(X))
    )


def test_export_fit_resume_rejects_nonstagewise():
    X, y = _reg_data()
    bag = pack(se.BaggingRegressor(num_base_learners=2).fit(X, y))
    with pytest.raises(TypeError, match="stagewise"):
        export.fit_resume(bag, X, y, 2)


# ---------------------------------------------------------------------------
# chaos: a killed refresh fit leaves the source model untouched
# ---------------------------------------------------------------------------


def test_refresh_crash_leaves_source_untouched_and_retryable():
    """The autopilot's crash contract at the model layer: chaos
    ``refresh_crash`` kills the resume mid-round; the committed model is
    byte-identical afterwards, a NORMAL fit never sees the fault (the
    site only exists on refresh fits), and the retry — with the
    controller still installed — succeeds bit-identically (at-most-once
    per site + budget)."""
    X, y = _reg_data()
    full = se.GBMRegressor(num_base_learners=N, seed=0).fit(X, y)
    short = se.GBMRegressor(num_base_learners=K, seed=0).fit(X, y)
    before = [
        np.asarray(v).copy()
        for v in jax.tree_util.tree_flatten(short.params)[0]
    ]
    ctl = ChaosController(
        seed=11, rate=1.0, faults=("refresh_crash",),
    )
    chaos.install(ctl)
    # a plain (non-refresh) fit is immune: no refresh sites are exposed
    se.GBMRegressor(num_base_learners=2, seed=1).fit(X, y)
    assert not ctl.fired
    with pytest.raises(ChaosPreemption):
        short.fit_resume(X, y, N_NEW)
    assert ctl.fired and ctl.fired[0][0] == "refresh_crash"
    after = jax.tree_util.tree_flatten(short.params)[0]
    assert short.num_members == K
    for a, b in zip(before, after):
        assert np.array_equal(a, np.asarray(b), equal_nan=True)
    # retry under the SAME controller: the fault fired its budget
    resumed = short.fit_resume(X, y, N_NEW)
    _assert_bit_identical(resumed, full, X)
