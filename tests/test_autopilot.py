"""Closed-loop fleet tests (docs/autopilot.md): torn-free rolling hot
swap under live traffic (every response computed by exactly ONE model
version, zero compiles, zero drops — chaos ``swap_crash`` included),
elastic width with ``scale_crash``, the :class:`Autopilot`'s deterministic
scale/refresh/rollback drive, a chaos-killed refresh leaving the serving
model untouched and retryable, and the registry's deferred ``remove()``
under live pin leases."""

import threading
import time

import numpy as np
import pytest

import spark_ensemble_tpu as se
from spark_ensemble_tpu.robustness.chaos import ChaosController, install
from spark_ensemble_tpu.serving import (
    Autopilot,
    FleetRouter,
    ModelRegistry,
)
from spark_ensemble_tpu.telemetry import record_fits
from spark_ensemble_tpu.telemetry.events import compile_snapshot
from spark_ensemble_tpu.telemetry.watchdog import Watchdog, default_rules

ROUNDS = 4


def _data(n=96, d=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


@pytest.fixture(scope="module")
def fitted():
    """Two distinguishable fitted GBMs shared across the module (their
    predictions differ, so a response's bits identify its version)."""
    X, y = _data()
    v1 = se.GBMRegressor(num_base_learners=ROUNDS, seed=0).fit(X, y)
    v2 = se.GBMRegressor(num_base_learners=2, seed=0).fit(X, y)
    return X, y, v1, v2


@pytest.fixture(autouse=True)
def _deterministic_chaos():
    # pin a never-fires controller so env-configured chaos tiers cannot
    # perturb the exact counters; tests install their own controllers
    install(ChaosController(seed=0, rate=0.0))
    yield
    install(None)


def _registry_fleet(fitted, replicas=3, capacity=4):
    X, y, v1, v2 = fitted
    reg = ModelRegistry(capacity=capacity, min_bucket=8, max_batch_size=16)
    reg.register("prod", v1, warm=True)
    reg.register("v2", v2, warm=True)
    fleet = FleetRouter.from_registry(
        reg, "prod", replicas=replicas, deadline_ms=30_000.0,
    )
    return reg, fleet


def _snapshot(p99=1.0, hedge=0.0, psi=0.0, div=0.0):
    """Synthetic watchdog registry snapshot: one fleet source + one
    quality source, shaped like ``global_metrics().snapshot()``."""
    return {
        "fleet/x": {"type": "source", "value": {
            "p99_ms": p99, "hedge_rate": hedge,
            "compiles_since_warmup": 0.0,
        }},
        "quality/q": {"type": "source", "value": {
            "psi_max": psi, "divergence": div,
        }},
    }


def _watchdog():
    return Watchdog(
        rules=default_rules(breach_for=1, clear_for=1), interval_s=3600.0
    )


# ---------------------------------------------------------------------------
# torn-free rolling swap under live traffic (+ chaos swap_crash)
# ---------------------------------------------------------------------------


def test_swap_under_load_is_torn_free_and_zero_compile(fitted):
    """The tentpole invariant, chaos-proven: a rolling swap with a
    ``swap_crash`` killing one replica mid-rebind still serves every
    response from exactly ONE whole model version (its bits match one
    version's prediction exactly — clones share programs, so equal inputs
    give equal bits), drops nothing, and compiles nothing."""
    X, y, v1, v2 = fitted
    Xq = X[:4]
    install(ChaosController(seed=5, rate=1.0, faults=("swap_crash",)))
    reg, fleet = _registry_fleet(fitted)
    try:
        want0 = np.asarray(fleet.predict(Xq).value)
        results, errors = [], []
        stop = threading.Event()

        def loadgen():
            while not stop.is_set():
                try:
                    r = fleet.predict(Xq)
                    results.append((r.version, np.asarray(r.value)))
                except Exception as e:  # noqa: BLE001 - collected, asserted empty
                    errors.append(e)

        threads = [threading.Thread(target=loadgen) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        c0, _ = compile_snapshot()
        info = fleet.swap_model("v2")
        time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        want1 = np.asarray(fleet.predict(Xq).value)

        assert not errors  # zero dropped / failed requests
        assert info["version"] == 1 and info["model"] == "v2"
        assert info["swap_compiles"] == 0  # registry engines pre-warmed
        assert info["swap_crashes"] == 1  # the chaos kill actually landed
        assert compile_snapshot()[0] == c0
        assert not np.array_equal(want0, want1)  # versions distinguishable
        want = {0: want0, 1: want1}
        assert results and {v for v, _ in results} <= {0, 1}
        for version, value in results:
            # whole-version bits: never a torn (mixed-version) response
            np.testing.assert_array_equal(value, want[version])
        snap = fleet.slo_snapshot()
        assert snap["version"] == 1 and snap["swaps"] == 1
        assert all(
            r["version"] == 1 and r["state"] == "healthy"
            for r in snap["replicas"].values()
        )
    finally:
        fleet.stop()
        reg.close()


def test_swap_rejects_incompatible_width(fitted):
    X, y, v1, _ = fitted
    narrow = se.GBMRegressor(num_base_learners=2, seed=0).fit(X[:, :3], y)
    with FleetRouter(
        v1, replicas=1, min_bucket=8, max_batch_size=16,
        deadline_ms=30_000.0,
    ) as fleet:
        with pytest.raises(ValueError, match="num_features"):
            fleet.swap_model(narrow)
        assert fleet.slo_snapshot()["version"] == 0  # nothing changed


# ---------------------------------------------------------------------------
# elastic width (+ chaos scale_crash): zero dropped, zero duplicated
# ---------------------------------------------------------------------------


def test_elastic_scale_zero_drop_under_scale_crash(fitted):
    X, y, v1, _ = fitted
    want = np.asarray(v1.predict(X[:4]))
    install(ChaosController(seed=2, rate=1.0, faults=("scale_crash",)))
    with FleetRouter(
        v1, replicas=2, min_bucket=8, max_batch_size=16,
        deadline_ms=30_000.0, shed_depth=10_000,
    ) as fleet:
        futs = [fleet.submit(X[:4]) for _ in range(30)]
        added = fleet.add_replica()  # chaos kills the warm-in; re-clones
        futs += [fleet.submit(X[:4]) for _ in range(30)]
        removed = fleet.remove_replica(added)
        futs += [fleet.submit(X[:4]) for _ in range(10)]
        responses = [f.result(timeout=60) for f in futs]
        assert len(responses) == 70  # zero lost; Futures resolve once
        for r in responses:
            np.testing.assert_allclose(
                r.value, want, rtol=1e-5, atol=1e-6
            )
        assert removed == added
        snap = fleet.slo_snapshot()
        assert snap["crashes"] == 1  # the warm-in kill was recorded
        assert snap["scale_ups"] == 1 and snap["scale_downs"] == 1
        assert len(snap["replicas"]) == 2
        assert snap["compiles_since_warmup"] == 0  # clones share programs
    assert fleet.slo_snapshot  # context-exit stop() is clean


def test_remove_last_replica_refused(fitted):
    X, y, v1, _ = fitted
    with FleetRouter(
        v1, replicas=1, min_bucket=8, max_batch_size=16,
    ) as fleet:
        with pytest.raises(ValueError, match="last replica"):
            fleet.remove_replica()


# ---------------------------------------------------------------------------
# autopilot: the deterministic scale/refresh/rollback drive
# ---------------------------------------------------------------------------


def test_autopilot_scales_refreshes_and_rolls_back(fitted):
    """One full closed loop, tick by tick: p99 breach scales up, drift
    triggers a warm-start refresh rolled on torn-free (zero compiles),
    shadow divergence rolls back to the pinned previous version, calm
    scales back down — each action traced as a ``fleet_action`` event."""
    X, y, v1, v2 = fitted
    reg, fleet = _registry_fleet(fitted, replicas=2)
    pilot = Autopilot(
        fleet, _watchdog(), refresh_data=lambda: (X, y),
        refresh_rounds=2, min_replicas=2, max_replicas=4,
        calm_ticks=2, background_refresh=False,
    )
    try:
        want_prod = np.asarray(fleet.predict(X[:4]).value)
        with record_fits() as rec:
            assert pilot.step(_snapshot()) == []  # healthy: no action
            a2 = pilot.step(_snapshot(p99=9999.0))
            assert [a["action"] for a in a2] == ["scale_up"]
            assert a2[0]["trigger"] == "serving_p99_ms"
            assert len(fleet.slo_snapshot()["replicas"]) == 3

            a3 = pilot.step(_snapshot(psi=0.9))
            assert [a["action"] for a in a3] == ["refresh"]
            ref = a3[0]
            assert ref["status"] == "ok"
            assert ref["model"] == "prod@v1" and "prod@v1" in reg
            assert ref["swap_compiles"] == 0
            assert ref["members"] == ROUNDS + 2  # fit_resume added rounds
            assert fleet.predict(X[:4]).version == 1
            assert pilot.statusz()["rollback_pin"] == "prod"

            a4 = pilot.step(_snapshot(div=0.9))
            assert [a["action"] for a in a4] == ["rollback"]
            assert a4[0]["status"] == "ok" and a4[0]["target"] == "prod"
            assert fleet.predict(X[:4]).version == 2
            np.testing.assert_array_equal(  # back on prod's exact bits
                np.asarray(fleet.predict(X[:4]).value), want_prod
            )
            assert pilot.statusz()["rollback_pin"] is None

            assert pilot.step(_snapshot()) == []  # calm 1/2
            a6 = pilot.step(_snapshot())          # calm 2/2
            assert [a["action"] for a in a6] == ["scale_down"]
            assert len(fleet.slo_snapshot()["replicas"]) == 2
        events = [e for e in rec.events if e["event"] == "fleet_action"]
        assert [e["action"] for e in events] == [
            "scale_up", "refresh", "rollback", "scale_down",
        ]
        assert all(
            e["status"] == "ok" and e["flow"] and e["trigger"]
            for e in events
        )
        st = pilot.statusz()
        assert st["steps"] == 6 and st["refresh_generation"] == 1
        assert not st["refresh_inflight"]
    finally:
        pilot.stop()
        fleet.stop()
        reg.close()


def test_autopilot_respects_replica_bounds(fitted):
    X, y, _, _ = fitted
    reg, fleet = _registry_fleet(fitted, replicas=2)
    pilot = Autopilot(
        fleet, _watchdog(), min_replicas=2, max_replicas=2,
        calm_ticks=1, background_refresh=False,
    )
    try:
        # pressure cannot scale past max; calm cannot drop below min
        assert pilot.step(_snapshot(p99=9999.0)) == []
        assert pilot.step(_snapshot()) == []
        assert pilot.step(_snapshot()) == []
        assert len(fleet.slo_snapshot()["replicas"]) == 2
    finally:
        pilot.stop()
        fleet.stop()
        reg.close()


# ---------------------------------------------------------------------------
# chaos refresh_crash through the autopilot: untouched + retryable
# ---------------------------------------------------------------------------


def test_refresh_crash_leaves_serving_model_untouched_and_retries(fitted):
    X, y, v1, _ = fitted
    ctl = ChaosController(seed=11, rate=1.0, faults=("refresh_crash",))
    install(ctl)
    reg, fleet = _registry_fleet(fitted, replicas=2)
    pilot = Autopilot(
        fleet, _watchdog(), refresh_data=lambda: (X, y),
        refresh_rounds=2, min_replicas=2, max_replicas=2,
        background_refresh=False,
    )
    try:
        base_before = fleet._base
        want = np.asarray(fleet.predict(X[:4]).value)
        a1 = pilot.step(_snapshot(psi=0.9))
        assert [a["action"] for a in a1] == ["refresh"]
        assert a1[0]["status"] == "failed"  # the chaos kill landed...
        assert ctl.fired and ctl.fired[0][0] == "refresh_crash"
        # ...and nothing moved: same engine object, same registry names,
        # same served version, byte-identical responses
        assert fleet._base is base_before
        assert sorted(reg.names()) == ["prod", "v2"]
        resp = fleet.predict(X[:4])
        assert resp.version == 0
        np.testing.assert_array_equal(np.asarray(resp.value), want)
        assert not pilot.statusz()["refresh_inflight"]  # retryable

        # second drift tick retries from the SAME committed state (the
        # fault's budget is spent) and completes the roll
        a2 = pilot.step(_snapshot(psi=0.9))
        assert [a["action"] for a in a2] == ["refresh"]
        assert a2[0]["status"] == "ok" and "prod@v1" in reg
        assert fleet.predict(X[:4]).version == 1
        assert pilot.statusz()["refresh_generation"] == 1
    finally:
        pilot.stop()
        fleet.stop()
        reg.close()


# ---------------------------------------------------------------------------
# registry: remove() racing a live pin lease defers like _offload
# ---------------------------------------------------------------------------


def test_registry_remove_defers_until_pins_release(fitted):
    """Regression: ``remove()`` used to pop the entry eagerly, so the
    pin-zero ``_release`` found nothing and the engine leaked, running,
    forever.  Now a removal racing a lease defers: the entry survives
    (and re-registration still conflicts) until the last pin releases,
    then the entry leaves and the engine stops."""
    X, y, v1, v2 = fitted
    with ModelRegistry(capacity=4, min_bucket=8, max_batch_size=16) as reg:
        reg.register("a", v1)
        reg.register("b", v2)
        want = np.asarray(reg.predict("a", X[:4]))
        with reg.lease("a") as eng:
            reg.remove("a")
            st = reg.stats()["a"]
            assert st["pending_remove"] and st["pins"] == 1
            assert "a" in reg  # still conflicts: no name reuse mid-flight
            with pytest.raises(ValueError, match="already registered"):
                reg.register("a", v2)
            # the leased engine still serves the pinned buffers
            np.testing.assert_array_equal(
                np.asarray(eng.predict(X[:4])), want
            )
        assert "a" not in reg and len(reg) == 1  # completed at pin zero

        # same race through the async path: a queued submit pins the
        # version; the reply is served, THEN the deferred remove lands
        want_b = np.asarray(reg.predict("b", X[:4]))
        fut = reg.submit("b", X[:4])
        reg.remove("b")
        np.testing.assert_array_equal(
            np.asarray(fut.result(timeout=30)), want_b
        )
        deadline = time.time() + 10.0
        while "b" in reg and time.time() < deadline:
            time.sleep(0.005)
        assert "b" not in reg and len(reg) == 0


def test_registry_remove_unpinned_is_immediate(fitted):
    X, y, v1, _ = fitted
    with ModelRegistry(capacity=2, min_bucket=8, max_batch_size=16) as reg:
        reg.register("a", v1, warm=True)
        reg.remove("a")
        assert "a" not in reg and len(reg) == 0
        with pytest.raises(KeyError):
            reg.engine("a")
