"""Property-based tests (hypothesis) — the direct analogue of the
reference's ScalaCheck suites (`UtilsSuite.scala:29-67`,
`HasSubBagSuite.scala:60-105`, `GBMLossSuite.scala:84-125`).

Two environment constraints shape these tests:
- shapes are FIXED per property so the jitted kernels compile once and
  every generated example reuses the executable;
- values are generated as INTEGERS and scaled in-test: jaxlib enables
  fast-math/FTZ on the process, which trips hypothesis's float-environment
  self-check (signed-zero/subnormal detection) inside `st.floats`.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis extra"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from spark_ensemble_tpu.ops import losses as losses_mod
from spark_ensemble_tpu.utils.quantile import weighted_median
from spark_ensemble_tpu.utils.random import bootstrap_weights, subspace_mask

_N = 64

_int_vals = st.lists(
    st.integers(-(10**6), 10**6), min_size=_N, max_size=_N
)
_int_weights = st.lists(st.integers(1, 10**5), min_size=_N, max_size=_N)


def _vals(ints):
    return jnp.asarray(np.asarray(ints, np.float32) / 1e3)


def _wts(ints):
    return jnp.asarray(np.asarray(ints, np.float32) / 1e2)


@settings(max_examples=25, deadline=None)
@given(_int_vals, _int_weights, st.integers(1, 1000))
def test_weighted_median_scale_invariant(v, w, c):
    """`UtilsSuite.scala`: scaling all weights never moves the median."""
    v, w = _vals(v), _wts(w)
    scale = jnp.float32(c / 10.0)
    assert float(weighted_median(v, w)) == float(weighted_median(v, scale * w))


_QSHARD = None


@settings(max_examples=15, deadline=None)
@given(
    _int_vals,
    # dyadic weights (k/32): every partial sum is f32-exact, so the mesh
    # path's different accumulation order cannot shift near-tie crossings
    st.lists(st.integers(1, 2**15), min_size=_N, max_size=_N),
    # q as a scaled integer: st.floats trips the FTZ self-check (module
    # docstring)
    st.integers(0, 1000),
)
def test_sharded_quantile_matches_exact_kernel_property(
    data_mesh8, v, w, q_milli
):
    """The mesh quantile (psum-ed bit-space histogram refinement, no
    all_gather) equals the exact sort-based kernel for ANY weights and any
    q — the property form of tests/test_distributed_quantile.py.  One
    fixed shard_map program; every generated example reuses it."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from spark_ensemble_tpu.utils.quantile import weighted_quantile

    global _QSHARD
    if _QSHARD is None:
        _QSHARD = jax.jit(
            shard_map(
                lambda vv, ww, qq: weighted_quantile(
                    vv, qq, ww, axis_name="data"
                ),
                mesh=data_mesh8,
                in_specs=(P("data"), P("data"), P()),
                out_specs=P(),
            )
        )
    v = _vals(v)
    w = jnp.asarray(np.asarray(w, np.float32) / 32.0)
    qj = jnp.float32(q_milli / 1000.0)
    exact = float(weighted_quantile(v, qj, w))
    assert float(_QSHARD(v, w, qj)) == exact


@settings(max_examples=25, deadline=None)
@given(_int_vals, _int_weights)
def test_weighted_median_is_an_element_and_order_invariant(v, w):
    """The weighted median is one of the values, and permuting the rows
    (same (v, w) pairs) never changes it."""
    v, w = _vals(v), _wts(w)
    med = float(weighted_median(v, w))
    assert med in np.asarray(v)
    perm = np.random.RandomState(0).permutation(_N)
    assert float(weighted_median(v[perm], w[perm])) == med


@settings(max_examples=25, deadline=None)
@given(_int_vals)
def test_weighted_median_unit_weights_matches_ge_half_rule(v):
    """With unit weights the >= 1/2 cumulative rule picks the
    ceil(n/2)-th order statistic (the reference's exact semantics)."""
    v = _vals(v)
    med = float(weighted_median(v, jnp.ones((_N,))))
    s = np.sort(np.asarray(v))
    assert med == s[(_N + 1) // 2 - 1]


@settings(max_examples=25, deadline=None)
@given(st.integers(0, 2**31 - 1), st.integers(5, 100))
def test_subspace_mask_properties(seed, ratio_pct):
    """`HasSubBagSuite.scala`: at least one active feature for any ratio,
    determinism in the key, and ratio=1 selects everything."""
    ratio = ratio_pct / 100.0
    key = jax.random.PRNGKey(seed)
    m = np.asarray(subspace_mask(key, 16, ratio))
    assert m.dtype == bool and m.shape == (16,)
    assert m.sum() >= 1
    m2 = np.asarray(subspace_mask(key, 16, ratio))
    np.testing.assert_array_equal(m, m2)
    if ratio == 1.0:
        assert m.all()


@settings(max_examples=25, deadline=None)
@given(st.integers(0, 2**31 - 1), st.integers(10, 100))
def test_bootstrap_weights_semantics(seed, ratio_pct):
    """`RDD.sample` semantics: replacement=True draws Poisson counts
    (non-negative integers), replacement=False Bernoulli 0/1; both keep
    the static shape."""
    ratio = ratio_pct / 100.0
    key = jax.random.PRNGKey(seed)
    pois = np.asarray(bootstrap_weights(key, _N, True, ratio))
    bern = np.asarray(bootstrap_weights(key, _N, False, ratio))
    assert pois.shape == bern.shape == (_N,)
    assert (pois >= 0).all() and (pois == np.round(pois)).all()
    assert set(np.unique(bern)) <= {0.0, 1.0}


_HUBER_DELTA = 1.3
_LOSSES = [
    losses_mod.SquaredLoss(),
    losses_mod.LogCoshLoss(),
    losses_mod.HuberLoss(_HUBER_DELTA),
    losses_mod.QuantileLoss(0.3),
]


@settings(max_examples=15, deadline=None)
@given(st.integers(-500, 500), st.integers(-500, 500))
def test_loss_gradients_match_numerical(yi, fi):
    """`GBMLossSuite.scala:84-125` gradient checking: every regression
    loss's analytic gradient matches a central difference at generated
    (label, prediction) points, away from non-smooth kinks."""
    y, f = yi / 100.0, fi / 100.0
    eps = 1e-3
    r = abs(y - f)
    for loss in _LOSSES:
        if isinstance(loss, losses_mod.QuantileLoss) and r < 5 * eps:
            continue  # kink at residual 0: one-sided derivative
        if isinstance(loss, losses_mod.HuberLoss) and abs(r - _HUBER_DELTA) < 5 * eps:
            continue  # kink at |residual| == delta
        # losses operate on ENCODED [n, dim] labels/predictions (dim=1
        # for regression; loss() sums its last axis)
        ya = jnp.asarray([[y]], jnp.float32)
        grad = float(loss.gradient(ya, jnp.asarray([[f]], jnp.float32))[0, 0])
        lp = float(loss.loss(ya, jnp.asarray([[f + eps]], jnp.float32))[0])
        lm = float(loss.loss(ya, jnp.asarray([[f - eps]], jnp.float32))[0])
        num = (lp - lm) / (2 * eps)
        assert abs(grad - num) < 5e-2 + 1e-2 * abs(num), (
            type(loss).__name__, y, f, grad, num,
        )


@given(
    seed=st.integers(0, 2**16),
    top_pct=st.integers(5, 60),
    other_pct=st.integers(5, 60),
)
@settings(max_examples=20, deadline=None)
def test_goss_multiplier_properties(seed, top_pct, other_pct):
    """GOSS multiplier invariants (models/gbm.py _goss_multiplier): every
    top-gradient row keeps weight exactly 1; rest rows are 0 or the
    reciprocal keep-rate; the EXPECTED multiplier of every rest row is 1
    (unbiased small-gradient mass), checked by averaging many draws."""
    from spark_ensemble_tpu.models.gbm import _goss_multiplier

    rng = np.random.RandomState(seed)
    n = 400
    top_rate, other_rate = top_pct / 100.0, other_pct / 100.0
    g = jnp.asarray(rng.randn(n, 3).astype(np.float32))
    w = jnp.ones((n,))
    bag = jnp.ones((n,))
    score = np.sqrt(np.sum(np.asarray(g) ** 2, axis=1))
    # derive the threshold with the IMPLEMENTATION's own quantile: an
    # independently computed numpy quantile can disagree at the boundary
    # rank (f32 target rounding), flipping one row's top/rest side
    from spark_ensemble_tpu.utils.quantile import weighted_quantile

    thr = float(
        weighted_quantile(jnp.asarray(score), 1.0 - top_rate, w * bag)
    )

    draws = np.stack([
        np.asarray(
            _goss_multiplier(
                g, w, bag, jax.random.PRNGKey(i), top_rate, other_rate,
                None,
            )
        )
        for i in range(60)
    ])
    top = score >= thr
    # top rows: always exactly 1
    assert (draws[:, top] == 1.0).all()
    rest = draws[:, ~top]
    if rest.size:
        p = min(1.0, other_rate / max(1.0 - top_rate, 1e-9))
        vals = np.unique(rest)
        assert np.all(
            np.isclose(vals[:, None], [0.0, 1.0 / p]).any(axis=1)
        ), vals
        # unbiasedness: mean multiplier -> 1 (60 draws, generous tol)
        np.testing.assert_allclose(rest.mean(), 1.0, atol=0.25)
