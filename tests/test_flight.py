"""Crash flight recorder (telemetry/flight.py): the per-process ring of
last-K telemetry rows and its post-mortem dump.  The kill-and-inspect
test is the bug-class regression for flush-on-crash: a subprocess emits,
fsyncs, dumps, then SIGKILLs itself mid-flight — the parent must find a
complete JSONL stream and an intact black box on disk."""

import json
import os
import signal
import subprocess
import sys

import pytest

from spark_ensemble_tpu.telemetry import flight
from spark_ensemble_tpu.telemetry.events import emit_event, record_fits

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ring_keeps_last_k_in_order():
    rec = flight.FlightRecorder(capacity=3)
    assert rec.rows() == [] and rec.recorded == 0
    for i in range(5):
        rec.record({"i": i})
    assert rec.rows() == [{"i": 2}, {"i": 3}, {"i": 4}]
    assert rec.recorded == 5
    rec.clear()
    assert rec.rows() == [] and rec.recorded == 0


def test_ring_under_capacity_keeps_all():
    rec = flight.FlightRecorder(capacity=8)
    rec.record({"i": 0})
    rec.record({"i": 1})
    assert rec.rows() == [{"i": 0}, {"i": 1}]


def test_capacity_must_be_positive():
    with pytest.raises(ValueError):
        flight.FlightRecorder(capacity=0)


def test_dump_payload_and_atomicity(tmp_path):
    rec = flight.FlightRecorder(capacity=4)
    rec.record({"event": "span", "name": "x"})
    out = str(tmp_path / "box.json")
    got = rec.dump(out, reason="test", error=ValueError("boom"),
                   extra={"victim": 1})
    assert got == out
    payload = json.loads(open(out).read())
    assert payload["kind"] == "flight_recorder"
    assert payload["reason"] == "test"
    assert payload["pid"] == os.getpid()
    assert payload["rows"] == [{"event": "span", "name": "x"}]
    assert payload["recorded"] == 1
    assert payload["error_type"] == "ValueError"
    assert payload["error"] == "boom"
    assert payload["victim"] == 1
    # jax is importable here, so the dump carries the memory snapshot
    assert "memory" in payload
    assert not list(tmp_path.glob("*.tmp.*"))  # renamed, not left behind


def test_dump_path_resolution(tmp_path, monkeypatch):
    monkeypatch.delenv(flight.FLIGHT_DIR_ENV, raising=False)
    monkeypatch.delenv("SE_TPU_TELEMETRY", raising=False)
    # nothing resolves -> no dump, and dump_flight degrades to None
    assert flight.flight_dump_path() is None
    assert flight.dump_flight(reason="nowhere") is None
    # next to an explicit telemetry stream
    tel = tmp_path / "t" / "fit.jsonl"
    p = flight.flight_dump_path(str(tel))
    assert p == str(tmp_path / "t" / f"flight_p{os.getpid()}.json")
    # the env stream works the same
    monkeypatch.setenv("SE_TPU_TELEMETRY", str(tel))
    assert flight.flight_dump_path() == p
    # SE_TPU_FLIGHT_DIR beats both
    monkeypatch.setenv(flight.FLIGHT_DIR_ENV, str(tmp_path / "box"))
    assert flight.flight_dump_path(str(tel)) == str(
        tmp_path / "box" / f"flight_p{os.getpid()}.json"
    )


def test_emit_chokepoints_feed_the_ring():
    rec = flight.recorder()
    before = rec.recorded
    with record_fits():
        emit_event("flight_probe", marker=123)
    assert rec.recorded == before + 1
    assert rec.rows()[-1]["event"] == "flight_probe"
    assert rec.rows()[-1]["marker"] == 123


def test_no_sink_records_nothing():
    """The disabled path stays allocation-free: with no sink active,
    emit_event returns before touching the ring."""
    before = flight.recorder().recorded
    emit_event("flight_probe_unsunk", marker=456)
    assert flight.recorder().recorded == before


_KILL_SCRIPT = """
import os, signal, sys
sys.path.insert(0, {repo!r})
from spark_ensemble_tpu.telemetry.events import FitTelemetry
from spark_ensemble_tpu.telemetry.flight import dump_flight

telem = FitTelemetry.start(family="victim", n=10, d=2,
                           telemetry_path={tel!r})
for i in range(5):
    telem.emit("probe", i=i)
telem.flush(fsync=True)
dump_flight(reason="about_to_die", telemetry_path={tel!r})
os.kill(os.getpid(), signal.SIGKILL)
"""


@pytest.mark.slow
def test_kill_and_inspect(tmp_path):
    """The preemption contract end-to-end: everything the flush-on-crash
    chokepoint wrote must be readable AFTER an uncatchable SIGKILL."""
    tel = str(tmp_path / "victim.jsonl")
    proc = subprocess.run(
        [sys.executable, "-c",
         _KILL_SCRIPT.format(repo=_REPO, tel=tel)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]
    # the stream is complete JSONL: every line parses, the probes landed
    events = [json.loads(line) for line in open(tel)]
    assert sum(e.get("event") == "probe" for e in events) == 5
    dumps = list(tmp_path.glob("flight_p*.json"))
    assert len(dumps) == 1
    payload = json.loads(dumps[0].read_text())
    assert payload["reason"] == "about_to_die"
    assert any(r.get("event") == "probe" for r in payload["rows"])
