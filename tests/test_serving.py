"""Serving subsystem tests (docs/serving.md): packed-export bit-identity on
every ensemble family, artifact round-trip + manifest corruption detection,
predict-path shape bucketing (no retraces across ad-hoc batch sizes), the
AOT-warmed inference engine (correctness, zero steady-state compiles,
micro-batching queue, throughput vs raw predict), the LRU model registry,
and the serving telemetry events."""

import json
import os

import numpy as np
import pytest

import spark_ensemble_tpu as se
from spark_ensemble_tpu.models import base as model_base
from spark_ensemble_tpu.models.base import bucket_rows, pad_rows_to_bucket
from spark_ensemble_tpu.robustness import chaos
from spark_ensemble_tpu.robustness.chaos import ChaosController
from spark_ensemble_tpu.serving import (
    InferenceEngine,
    ModelRegistry,
    PackedModel,
    load_packed,
    pack,
)
from spark_ensemble_tpu.telemetry import record_fits
from spark_ensemble_tpu.telemetry.events import SERVING_EVENT_TYPES


def _data(n=96, d=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


def _cls_data(n=96, d=5, seed=0):
    X, y = _data(n, d, seed)
    return X, (y > np.median(y)).astype(np.float32)


_FAMILIES = {
    "gbm_reg": lambda: se.GBMRegressor(num_base_learners=3),
    "gbm_clf": lambda: se.GBMClassifier(num_base_learners=3),
    "bagging_reg": lambda: se.BaggingRegressor(num_base_learners=3),
    "bagging_clf": lambda: se.BaggingClassifier(
        num_base_learners=3, voting_strategy="soft"
    ),
    "boosting_reg": lambda: se.BoostingRegressor(num_base_learners=3),
    "boosting_clf": lambda: se.BoostingClassifier(num_base_learners=3),
    "stacking_reg": lambda: se.StackingRegressor(),
    "stacking_clf": lambda: se.StackingClassifier(),
}


@pytest.fixture(scope="module")
def fitted():
    """One fitted model per family x task, shared across this module (fits
    dominate runtime; every test here only reads the models)."""
    X, y = _data()
    _, yc = _cls_data()
    out = {}
    for name, ctor in _FAMILIES.items():
        target = yc if name.endswith("_clf") else y
        out[name] = ctor().fit(X, target)
    return X, out


# ---------------------------------------------------------------------------
# shape bucketing (satellite: predict-path retracing fix)
# ---------------------------------------------------------------------------


def test_bucket_rows_properties():
    for n in range(1, 2000):
        b = bucket_rows(n)
        assert b >= n
        assert b == bucket_rows(b)  # idempotent: buckets are fixed points
        if n > 512:
            assert (b - n) / n <= 0.125 + 1e-9  # padding overhead bound
    # exact powers of two below the octave threshold map to themselves
    for p in (1, 2, 8, 64, 512):
        assert bucket_rows(p) == p
    assert bucket_rows(513) == 576  # 1024/8 granularity above 512
    assert bucket_rows(100) == 128


def test_pad_rows_to_bucket_zero_pads():
    X = np.ones((5, 3), np.float32)
    padded = np.asarray(pad_rows_to_bucket(X))
    assert padded.shape == (8, 3)
    assert np.array_equal(padded[:5], X)
    assert np.all(padded[5:] == 0.0)


def test_bucketing_env_escape_hatch(monkeypatch):
    monkeypatch.setenv(model_base.PREDICT_BUCKETS_ENV, "0")
    assert not model_base.predict_buckets_enabled()
    monkeypatch.setenv(model_base.PREDICT_BUCKETS_ENV, "1")
    assert model_base.predict_buckets_enabled()


def test_predict_traces_once_per_bucket(fitted):
    X, models = fitted
    m = _FAMILIES["gbm_reg"]().fit(X[:80], X[:80, 0])
    # ad-hoc batch sizes inside one bucket share one traced program
    for n in (65, 70, 77, 81, 90, 128):  # all bucket to 128
        m.predict(X[:80][np.arange(n) % 80])
    import jax

    cache = m._jit_cache[("predict", jax.default_backend())]
    assert cache._cache_size() == 1


def test_bucketed_predict_values_bit_identical(fitted):
    X, models = fitted
    for name, m in models.items():
        full = np.asarray(m.predict(X))
        for n in (1, 7, 33, 77):
            assert np.array_equal(np.asarray(m.predict(X[:n])), full[:n]), name


# ---------------------------------------------------------------------------
# packed export: bit identity on every family (tentpole acceptance)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(_FAMILIES))
def test_pack_predictions_bit_identical(fitted, name):
    X, models = fitted
    m = models[name]
    p = m.pack()
    assert isinstance(p, PackedModel)
    assert p.num_features == X.shape[1]
    assert np.array_equal(np.asarray(p.predict(X)), np.asarray(m.predict(X)))
    if name.endswith("_clf"):
        assert p.is_classifier and p.num_classes == 2
        assert np.array_equal(
            np.asarray(p.predict_proba(X)), np.asarray(m.predict_proba(X))
        )
    else:
        assert not p.is_classifier


@pytest.mark.parametrize("name", sorted(_FAMILIES))
def test_save_load_round_trip_bit_identical(fitted, name, tmp_path):
    X, models = fitted
    m = models[name]
    path = str(tmp_path / "artifact")
    m.pack().save(path)
    loaded = load_packed(path)
    assert loaded.class_name == type(m).__name__
    assert np.array_equal(
        np.asarray(loaded.predict(X)), np.asarray(m.predict(X))
    )


def test_pack_after_nonfinite_member_drop_round_trips(tmp_path):
    """A chaos-dropped member changes the fitted member count away from the
    configured param; the packed artifact must carry the FITTED state."""
    X, y = _cls_data()
    try:
        chaos.install(
            ChaosController(
                seed=21, rate=1.0, faults=("nan_grad",),
                budgets={"nan_grad": 1},
            )
        )
        m = se.BaggingClassifier(
            num_base_learners=5,
            voting_strategy="soft",
            on_nonfinite="skip_round",
        ).fit(X, y)
    finally:
        chaos.install(None)
    assert m.num_members == 4  # one member dropped during fit
    path = str(tmp_path / "dropped")
    m.pack().save(path)
    loaded = load_packed(path)
    assert loaded.model().num_members == 4
    assert np.array_equal(
        np.asarray(loaded.predict_proba(X)), np.asarray(m.predict_proba(X))
    )


def test_pack_rejects_unfitted_estimator():
    with pytest.raises(TypeError, match="fitted Model"):
        pack(se.GBMRegressor())


def test_load_rejects_corrupt_artifact(fitted, tmp_path):
    X, models = fitted
    path = str(tmp_path / "artifact")
    models["gbm_reg"].pack().save(path)
    # flip one payload byte: manifest checksum must catch it
    npz = os.path.join(path, "arrays.npz")
    blob = bytearray(open(npz, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(npz, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(ValueError, match="manifest"):
        load_packed(path)


def test_load_rejects_missing_manifest_and_version_skew(fitted, tmp_path):
    X, models = fitted
    with pytest.raises(FileNotFoundError, match="manifest"):
        load_packed(str(tmp_path / "nope"))
    path = str(tmp_path / "artifact")
    models["gbm_reg"].pack().save(path)
    meta_path = os.path.join(path, "packed.json")
    meta = json.load(open(meta_path))
    meta["format_version"] = 99
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    # rewrite the manifest so only the version check can fail
    from spark_ensemble_tpu.utils.checkpoint import _file_sha256

    mf_path = os.path.join(path, "manifest.json")
    manifest = json.load(open(mf_path))
    manifest["files"]["packed.json"] = {
        "sha256": _file_sha256(meta_path),
        "bytes": os.path.getsize(meta_path),
    }
    with open(mf_path, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(ValueError, match="format_version"):
        load_packed(path)


def test_offload_and_reupload_round_trips(fitted):
    X, models = fitted
    p = models["boosting_reg"].pack()
    want = np.asarray(p.predict(X))
    p.offload()
    assert not p.on_device()
    assert np.array_equal(np.asarray(p.predict(X)), want)
    assert p.on_device()  # predict re-uploaded lazily


# ---------------------------------------------------------------------------
# inference engine
# ---------------------------------------------------------------------------


def test_engine_outputs_match_model(fitted):
    X, models = fitted
    m = models["gbm_reg"]
    want = np.asarray(m.predict(X))
    with InferenceEngine(m, max_batch_size=256) as eng:
        for n in (1, 3, 8, 17, 77, 96):
            out = eng.predict(X[:n])
            assert out.shape == (n,)
            # the engine stages the WHOLE model predict as one XLA program
            # per bucket; fusion across the padded batch can move float
            # rounding by ~1 ulp, so the engine contract is tight allclose
            # (bit-identity is PackedModel's contract, asserted above)
            assert np.allclose(out, want[:n], rtol=1e-5, atol=1e-6)
        single = eng.predict(X[0])  # 1-D request -> scalar row result
        assert single.shape == ()
        assert np.allclose(single, want[0], rtol=1e-5, atol=1e-6)


def test_engine_zero_compiles_after_warmup(fitted):
    X, models = fitted
    with InferenceEngine(
        models["bagging_clf"],
        methods=("predict", "predict_proba"),
        max_batch_size=128,
    ) as eng:
        rng = np.random.RandomState(1)
        for n in rng.randint(1, 96, size=25):
            eng.predict(X[:n])
            eng.predict_proba(X[:n])
        futs = [eng.submit(X[:n]) for n in rng.randint(1, 96, size=25)]
        for f in futs:
            f.result(timeout=30)
        stats = eng.stats()
        assert stats["compiles_since_warmup"] == 0, stats


def test_engine_chunks_oversized_requests(fitted):
    X, models = fitted
    m = models["gbm_reg"]
    big = np.concatenate([X] * 4, axis=0)  # 384 rows > max bucket
    # the compile counter is process-global: take the live reference BEFORE
    # warmup so its own compiles don't count against the engine
    want = np.asarray(m.predict(big))
    with InferenceEngine(m, min_bucket=8, max_batch_size=64) as eng:
        out = eng.predict(big)
        assert out.shape == (big.shape[0],)
        assert np.allclose(out, want, rtol=1e-5, atol=1e-6)
        assert eng.stats()["compiles_since_warmup"] == 0


def test_engine_rejects_unwarmed_method_and_bad_shape(fitted):
    X, models = fitted
    with InferenceEngine(models["gbm_clf"]) as eng:
        with pytest.raises(ValueError, match="not configured"):
            eng.predict_proba(X)
        with pytest.raises(ValueError, match="num_features"):
            eng.predict(X[:, :3])


def test_engine_queue_coalesces_and_resolves_every_future(fitted):
    X, models = fitted
    m = models["stacking_reg"]
    want = np.asarray(m.predict(X))
    with record_fits() as rec:
        with InferenceEngine(
            m, max_batch_size=512, max_delay_ms=20.0
        ) as eng:
            futs = [(n, eng.submit(X[:n])) for n in (5, 9, 12, 3, 30, 1)]
            for n, fut in futs:
                out = fut.result(timeout=30)
                assert out.shape == (n,)
                assert np.allclose(out, want[:n], rtol=1e-5, atol=1e-6)
    served = [e for e in rec.events if e["event"] == "request_served"]
    queued = [e for e in served if e["source"] == "queue"]
    assert len(queued) == 6
    # at least some requests shared one device dispatch
    assert any(e["batch_rows"] > e["rows"] for e in queued)
    assert all(e["bucket"] >= e["rows"] for e in queued)


def test_engine_queue_throughput_not_worse_than_raw_predict(fitted):
    """Many tiny requests: the coalescing queue must at least match a raw
    per-request ``model.predict`` loop (it usually wins by a wide margin —
    one device dispatch serves dozens of callers)."""
    import time

    X, models = fitted
    m = models["gbm_reg"]
    reqs = [X[(7 * i) % 80 : (7 * i) % 80 + 8] for i in range(200)]
    rows = sum(r.shape[0] for r in reqs)

    for r in reqs[:4]:
        np.asarray(m.predict(r))  # warm the raw path's bucket programs
    t0 = time.perf_counter()
    for r in reqs:
        np.asarray(m.predict(r))
    raw_s = time.perf_counter() - t0

    with InferenceEngine(m, max_batch_size=1024, max_delay_ms=5.0) as eng:
        t0 = time.perf_counter()
        futs = [eng.submit(r) for r in reqs]
        for f in futs:
            f.result(timeout=60)
        eng_s = time.perf_counter() - t0
        assert eng.stats()["compiles_since_warmup"] == 0
    raw_rps = rows / raw_s
    eng_rps = rows / eng_s
    # 0.9 guard absorbs scheduler noise; in practice the engine wins big
    assert eng_rps >= 0.9 * raw_rps, (raw_rps, eng_rps)


def test_engine_accepts_packed_model_and_reports_stats(fitted):
    X, models = fitted
    p = models["gbm_reg"].pack()
    with InferenceEngine(p, min_bucket=8, max_batch_size=32) as eng:
        assert eng.buckets == (8, 16, 32)
        assert eng.bucket_for(9) == 16
        stats = eng.stats()
        assert set(stats["compiled"]) == {
            "predict@8", "predict@16", "predict@32"
        }
        assert all(s > 0 for s in stats["compiled"].values())
        assert stats["packed_bytes"] == p.nbytes


# ---------------------------------------------------------------------------
# model registry (LRU device residency)
# ---------------------------------------------------------------------------


def test_registry_lru_evicts_and_reactivates(fitted):
    X, models = fitted
    with record_fits() as rec:
        with ModelRegistry(capacity=1, max_batch_size=128) as reg:
            reg.register("g", models["gbm_reg"])
            reg.register("b", models["boosting_reg"])
            assert sorted(reg.names()) == ["b", "g"]
            assert "g" in reg and len(reg) == 2
            want_g = reg.predict("g", X)
            assert reg.stats()["g"]["resident"]
            reg.predict("b", X)  # activates b -> evicts g (capacity 1)
            stats = reg.stats()
            assert stats["b"]["resident"] and not stats["g"]["resident"]
            # reactivation returns the same predictions
            again = reg.predict("g", X)
            assert np.array_equal(again, want_g)
            assert reg.stats()["g"]["activations"] == 2
    evicted = [e for e in rec.events if e["event"] == "model_evicted"]
    assert [e["model"] for e in evicted] == ["g", "b"]
    assert all(e["bytes_freed"] > 0 for e in evicted)


def test_registry_explicit_evict_remove_and_errors(fitted):
    X, models = fitted
    reg = ModelRegistry(capacity=2, max_batch_size=64)
    with pytest.raises(ValueError, match="capacity"):
        ModelRegistry(capacity=0)
    reg.register("m", models["stacking_clf"].pack())
    with pytest.raises(ValueError, match="already registered"):
        reg.register("m", models["stacking_clf"])
    with pytest.raises(KeyError, match="no model"):
        reg.engine("missing")
    reg.predict("m", X)
    reg.evict("m")
    assert not reg.stats()["m"]["resident"]
    reg.remove("m")
    assert "m" not in reg
    reg.close()


# ---------------------------------------------------------------------------
# serving telemetry
# ---------------------------------------------------------------------------


def test_serving_events_schema(fitted):
    X, models = fitted
    with record_fits() as rec:
        p = models["gbm_reg"].pack()
        with InferenceEngine(p, min_bucket=8, max_batch_size=16) as eng:
            eng.predict(X[:5])
    by_type = {}
    for e in rec.events:
        by_type.setdefault(e["event"], []).append(e)
    assert set(SERVING_EVENT_TYPES) >= set(by_type)
    (packed,) = by_type["model_packed"]
    assert packed["family"] == "GBMRegressionModel"
    assert packed["bytes"] > 0 and packed["arrays"] > 0
    warmups = by_type["engine_warmup"]
    assert sorted(e["bucket"] for e in warmups) == [8, 16]
    assert all(e["method"] == "predict" and e["compile_s"] > 0
               for e in warmups)
    (req,) = by_type["request_served"]
    assert req["rows"] == 5 and req["bucket"] == 8
    assert req["source"] == "sync" and req["latency_ms"] > 0
    assert 0 < req["bucket_utilization"] <= 1.0
    assert all("ts" in e and "fit_id" in e for e in rec.events)


def test_serving_events_to_jsonl_sink(fitted, tmp_path):
    X, models = fitted
    path = str(tmp_path / "serving.jsonl")
    with InferenceEngine(
        models["gbm_reg"], max_batch_size=16, telemetry_path=path
    ) as eng:
        eng.predict(X[:3])
    events = [json.loads(line) for line in open(path)]
    kinds = {e["event"] for e in events}
    assert "engine_warmup" in kinds and "request_served" in kinds
