"""Lookahead dispatch pipeline (execution.py, docs/pipeline.md).

The contract under test is bit-identity: ``SE_TPU_PIPELINE=0`` pins the
synchronous pre-pipeline path, and every depth must produce the SAME
model — same members, same predictions, same early-stop round — because
member keys/masks derive from absolute round indices and a stop or guard
recovery discards the speculative in-flight chunks.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import spark_ensemble_tpu as se
from spark_ensemble_tpu import execution
from tests.conftest import accuracy


def _reg_data(n=900, d=8, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] + np.sin(2.0 * X[:, 1]) + 0.1 * rng.randn(n)).astype(
        np.float32
    )
    return X, y


def _clf_data(n=900, d=8, k=4, seed=4):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    centers = rng.randn(k, d).astype(np.float32)
    y = np.argmax(X @ centers.T, axis=1).astype(np.float32)
    return X, y


def test_depth_resolution_env_wins(monkeypatch):
    monkeypatch.setenv(execution.PIPELINE_ENV, "2")
    assert execution.resolve_pipeline_depth() == 2
    monkeypatch.setenv(execution.PIPELINE_ENV, "0")
    assert execution.resolve_pipeline_depth() == 0
    # clamped to [0, MAX_PIPELINE_DEPTH]
    monkeypatch.setenv(execution.PIPELINE_ENV, "99")
    assert execution.resolve_pipeline_depth() == execution.MAX_PIPELINE_DEPTH
    monkeypatch.setenv(execution.PIPELINE_ENV, "-3")
    assert execution.resolve_pipeline_depth() == 0


def test_depth_resolution_invalid_env_falls_back(monkeypatch):
    monkeypatch.setenv(execution.PIPELINE_ENV, "banana")
    assert (
        execution.resolve_pipeline_depth()
        == execution.DEFAULT_PIPELINE_DEPTH
    )
    monkeypatch.delenv(execution.PIPELINE_ENV, raising=False)
    assert 0 <= execution.resolve_pipeline_depth(1000) <= (
        execution.MAX_PIPELINE_DEPTH
    )


@pytest.mark.parametrize("depth", [1, 2])
def test_gbm_regressor_bit_identical_across_depths(monkeypatch, depth):
    X, y = _reg_data()
    vi = np.zeros((X.shape[0],), bool)
    vi[::4] = True

    def run(d):
        monkeypatch.setenv(execution.PIPELINE_ENV, str(d))
        return se.GBMRegressor(
            num_base_learners=10, scan_chunk=3, num_rounds=4
        ).fit(X, y, validation_indicator=vi)

    sync, piped = run(0), run(depth)
    assert sync.num_members == piped.num_members
    assert bool(jnp.array_equal(sync.predict(X), piped.predict(X)))


def test_gbm_classifier_bit_identical_and_midchunk_stop(monkeypatch):
    X, y = _clf_data()
    vi = np.zeros((X.shape[0],), bool)
    vi[::4] = True
    # tight patience + tiny chunks => the validation stop lands mid-run
    # while speculative chunks are in flight; the pipeline must discard
    # them and keep exactly the synchronous member count
    def run(d):
        monkeypatch.setenv(execution.PIPELINE_ENV, str(d))
        return se.GBMClassifier(
            num_base_learners=14, scan_chunk=2, num_rounds=2,
            learning_rate=1.0,
        ).fit(X, y, validation_indicator=vi)

    sync, piped = run(0), run(1)
    assert sync.num_members == piped.num_members
    assert sync.num_members < 14  # the stop actually fired
    assert bool(
        jnp.array_equal(sync.predict_proba(X), piped.predict_proba(X))
    )


def test_gbm_no_validation_bit_identical(monkeypatch):
    X, y = _reg_data()

    def run(d):
        monkeypatch.setenv(execution.PIPELINE_ENV, str(d))
        return se.GBMRegressor(num_base_learners=6, scan_chunk=2).fit(X, y)

    sync, piped = run(0), run(2)
    assert bool(jnp.array_equal(sync.predict(X), piped.predict(X)))


@pytest.mark.parametrize("algorithm", ["discrete", "real"])
def test_boosting_classifier_bit_identical(monkeypatch, algorithm):
    X, y = _clf_data()

    def run(d):
        monkeypatch.setenv(execution.PIPELINE_ENV, str(d))
        return se.BoostingClassifier(
            num_base_learners=6, scan_chunk=2, algorithm=algorithm
        ).fit(X, y)

    sync, piped = run(0), run(1)
    assert sync.num_members == piped.num_members
    assert bool(jnp.array_equal(sync.predict_raw(X), piped.predict_raw(X)))


def test_boosting_abort_path_bit_identical(monkeypatch):
    # pure-noise labels make discrete SAMME abort early (err >= 1 - 1/K);
    # the abort happens during commit while lookahead chunks are already
    # dispatched — those must be discarded, not appended
    rng = np.random.RandomState(7)
    X = rng.randn(600, 6).astype(np.float32)
    y = rng.randint(0, 5, size=600).astype(np.float32)

    def run(d):
        monkeypatch.setenv(execution.PIPELINE_ENV, str(d))
        return se.BoostingClassifier(
            num_base_learners=8, scan_chunk=4, algorithm="discrete"
        ).fit(X, y)

    sync, piped = run(0), run(1)
    assert sync.num_members == piped.num_members
    if sync.num_members:
        assert bool(
            jnp.array_equal(sync.predict_raw(X), piped.predict_raw(X))
        )


def test_boosting_regressor_bit_identical(monkeypatch):
    X, y = _reg_data()

    def run(d):
        monkeypatch.setenv(execution.PIPELINE_ENV, str(d))
        return se.BoostingRegressor(
            num_base_learners=5, scan_chunk=2
        ).fit(X, y)

    sync, piped = run(0), run(1)
    assert sync.num_members == piped.num_members
    assert np.allclose(
        np.asarray(sync.predict(X)), np.asarray(piped.predict(X))
    )


def test_device_patience_matches_host(monkeypatch):
    X, y = _clf_data()
    vi = np.zeros((X.shape[0],), bool)
    vi[::4] = True

    def run(dp):
        monkeypatch.setenv(execution.PIPELINE_ENV, "1")
        monkeypatch.setenv(execution.DEVICE_PATIENCE_ENV, dp)
        return se.GBMClassifier(
            num_base_learners=12, scan_chunk=3, num_rounds=3
        ).fit(X, y, validation_indicator=vi)

    host, device = run("0"), run("1")
    assert host.num_members == device.num_members
    assert bool(
        jnp.array_equal(host.predict_proba(X), device.predict_proba(X))
    )
    assert accuracy(device.predict(X), y) > 0.5


def test_host_blocked_metric_emitted(monkeypatch):
    from spark_ensemble_tpu.telemetry import record_fits

    X, y = _reg_data(n=400)
    for depth in ("0", "1"):
        monkeypatch.setenv(execution.PIPELINE_ENV, depth)
        with record_fits() as rec:
            se.GBMRegressor(num_base_learners=4, scan_chunk=2).fit(X, y)
        fit_end = next(
            e for e in rec.events if e.get("event") == "fit_end"
        )
        assert fit_end["host_blocked_us"] >= 0.0
        assert fit_end["host_blocked_us"] <= fit_end["wall_s"] * 1e6
