"""Autotuned execution engine (`spark_ensemble_tpu/autotune/`,
docs/autotune.md): the typed tunable space mirrors the live source
literals, the on-disk cache round-trips with checkpoint-grade crash
consistency, the measured search picks deterministic winners, resolution
order (override > off > cache > default) holds at every site, and
``SE_TPU_AUTOTUNE=off`` keeps fits bit-identical to an untuned build."""

import json
import os

import numpy as np
import pytest

import spark_ensemble_tpu as se
from spark_ensemble_tpu.autotune import (
    TUNABLES,
    TuningCache,
    autotune_fit,
    fingerprint,
    override,
    reset,
    resolve,
    resolved_snapshot,
    run_search,
    shape_class,
)
from spark_ensemble_tpu.autotune.cache import entry_key, manifest_signature
from spark_ensemble_tpu.autotune.resolve import _device_identity


@pytest.fixture(autouse=True)
def _isolated_cache(tmp_path, monkeypatch):
    """Every test gets an empty cache dir and the default mode; the
    memoized cache view is dropped on both sides."""
    monkeypatch.setenv("SE_TPU_AUTOTUNE_CACHE", str(tmp_path / "atc"))
    monkeypatch.delenv("SE_TPU_AUTOTUNE", raising=False)
    reset()
    yield
    reset()


def _fake_measure(times):
    """measure(tag, thunk, repeats) stub returning scripted times and
    recording every call."""
    calls = []

    def measure(tag, thunk, repeats):
        calls.append(tag)
        key = (tag["tunable"], tag["candidate"])
        return times.get(key, times.get(tag["tunable"], 1.0))

    measure.calls = calls
    return measure


# ---------------------------------------------------------------------------
# space
# ---------------------------------------------------------------------------


def test_defaults_mirror_source_literals():
    """The bit-identity contract: every tunable's default equals the live
    literal at its source site."""
    import spark_ensemble_tpu.models.base as mb
    import spark_ensemble_tpu.ops.pallas_hist as ph
    import spark_ensemble_tpu.ops.tree as T
    from spark_ensemble_tpu.models.gbm import GBMRegressor

    d = TUNABLES.defaults()
    assert d["scan_chunk"] == GBMRegressor().scan_chunk
    assert d["stream_chunk_rows"] == T._STREAM_CHUNK_ROWS
    assert d["predict_fused_max_cells"] == T._PREDICT_FUSED_MAX_CELLS
    assert d["pallas_block_rows"] == ph._BLOCK_ROWS
    assert d["pallas_vmem_budget"] == ph._VMEM_BUDGET
    assert d["predict_bucket_pow2_exact"] == mb._BUCKET_POW2_EXACT
    assert d["predict_bucket_octave_steps"] == mb._BUCKET_OCTAVE_STEPS
    assert d["hist_tier"] == "auto"


def test_validate_params_drops_unknown_and_invalid():
    got = TUNABLES.validate_params({
        "scan_chunk": 32,              # valid
        "hist_tier": "matmul",         # valid choice
        "stream_chunk_rows": -4,       # invalid: not positive
        "pallas_block_rows": "256",    # invalid: wrong type
        "scan_chunk_v2": 64,           # unknown name (future cache)
        "predict_bucket_octave_steps": True,  # bool is not an int here
    })
    assert got == {"scan_chunk": 32, "hist_tier": "matmul"}


def test_shape_class_buckets():
    assert shape_class(None) == "*"
    assert shape_class(0) == "*"
    assert shape_class(15000) == "n14"  # letter scale
    assert shape_class(16384) == "n14"
    assert shape_class(1) == "n0"


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------


def test_cache_round_trip(tmp_path):
    d = str(tmp_path / "c")
    cache = TuningCache()
    cache.put("cpu", "cpu", "n14", {"scan_chunk": 64, "hist_tier": "matmul"})
    cache.put("cpu", "cpu", "*", {"predict_bucket_pow2_exact": 1024})
    path = cache.save(d)
    assert os.path.isdir(path)
    loaded = TuningCache.load(d)
    # exact class merges over the platform-wide "*" entry
    assert loaded.lookup("cpu", "cpu", "n14") == {
        "scan_chunk": 64,
        "hist_tier": "matmul",
        "predict_bucket_pow2_exact": 1024,
    }
    # unknown shape class still serves the "*" entry
    assert loaded.lookup("cpu", "cpu", "n9") == {
        "predict_bucket_pow2_exact": 1024,
    }
    # a different device has no entries at all
    assert loaded.lookup("tpu", "TPU v5e", "n14") == {}


def test_cache_save_retains_previous_generation(tmp_path):
    d = str(tmp_path / "c")
    first = TuningCache()
    first.put("cpu", "cpu", "*", {"scan_chunk": 8})
    first.save(d)
    second = TuningCache()
    second.put("cpu", "cpu", "*", {"scan_chunk": 128})
    second.save(d)
    assert TuningCache.load(d).lookup("cpu", "cpu", "*") == {"scan_chunk": 128}
    assert os.path.isdir(os.path.join(d, ".cache-old"))


def test_manifest_corruption_falls_back(tmp_path):
    d = str(tmp_path / "c")
    cache = TuningCache()
    cache.put("cpu", "cpu", "*", {"scan_chunk": 64})
    cache.save(d)
    # corrupt the published payload without touching the manifest: the
    # sha256 check must reject it and (no .cache-old yet) load empty
    tuned = os.path.join(d, "latest", "tuned.json")
    with open(tuned, "a") as f:
        f.write(" ")
    assert TuningCache.load(d).entries == {}

    # now publish a good generation over the corrupt one, then corrupt
    # the NEW latest: load must fall back to the retained generation
    good = TuningCache()
    good.put("cpu", "cpu", "*", {"scan_chunk": 32})
    good.save(d)
    newer = TuningCache()
    newer.put("cpu", "cpu", "*", {"scan_chunk": 128})
    newer.save(d)
    with open(os.path.join(d, "latest", "manifest.json"), "w") as f:
        f.write("{not json")
    assert TuningCache.load(d).lookup("cpu", "cpu", "*") == {"scan_chunk": 32}


def test_cache_version_mismatch_ignored(tmp_path):
    d = str(tmp_path / "c")
    cache = TuningCache()
    cache.put("cpu", "cpu", "*", {"scan_chunk": 64})
    cache.save(d)
    man_path = os.path.join(d, "latest", "manifest.json")
    with open(man_path) as f:
        man = json.load(f)
    man["version"] = 999
    with open(man_path, "w") as f:
        json.dump(man, f)
    assert TuningCache.load(d).entries == {}


def test_entry_key_normalizes_slashes():
    assert entry_key("tpu", "TPU v5 lite", "n14") == "tpu/TPU v5 lite/n14"
    assert entry_key("tpu", "odd/kind", "n14") == "tpu/odd_kind/n14"


# ---------------------------------------------------------------------------
# resolve
# ---------------------------------------------------------------------------


def test_resolution_order_cache_then_default():
    platform, kind = _device_identity()
    cache = TuningCache()
    cache.put(platform, kind, "*", {"scan_chunk": 64})
    cache.save()
    reset()
    assert resolve("scan_chunk", 16, n=2048) == 64
    # a name the cache has no entry for returns the caller's default
    assert resolve("stream_chunk_rows", 32768, n=2048) == 32768


def test_off_mode_ignores_cache(monkeypatch):
    platform, kind = _device_identity()
    cache = TuningCache()
    cache.put(platform, kind, "*", {"scan_chunk": 64})
    cache.save()
    reset()
    monkeypatch.setenv("SE_TPU_AUTOTUNE", "off")
    assert resolve("scan_chunk", 16, n=2048) == 16
    assert fingerprint() == ("autotune-off",)
    snap = resolved_snapshot(2048)
    assert snap["mode"] == "off" and not snap["cache_hit"]


def test_override_wins_over_cache():
    platform, kind = _device_identity()
    cache = TuningCache()
    cache.put(platform, kind, "*", {"scan_chunk": 64})
    cache.save()
    reset()
    with override(scan_chunk=4):
        assert resolve("scan_chunk", 16, n=2048) == 4
    assert resolve("scan_chunk", 16, n=2048) == 64
    with pytest.raises(ValueError):
        with override(not_a_tunable=1):
            pass


def test_fingerprint_tracks_tuning_state():
    """Programs traced under different tuning states must get different
    cached_program keys (trace-time latching)."""
    base = fingerprint()
    with override(scan_chunk=4):
        assert fingerprint() != base
    platform, kind = _device_identity()
    cache = TuningCache()
    cache.put(platform, kind, "*", {"scan_chunk": 64})
    cache.save()
    reset()
    assert fingerprint() != base  # manifest signature changed


def test_manifest_signature_changes_on_save():
    assert manifest_signature() is None
    cache = TuningCache()
    cache.put("cpu", "cpu", "*", {"scan_chunk": 64})
    cache.save()
    assert manifest_signature() is not None


def test_bucket_rows_honors_tuned_ladder():
    from spark_ensemble_tpu.models.base import bucket_rows

    # defaults: pow2 up to 512, then 1/8-octave steps
    assert bucket_rows(300) == 512
    assert bucket_rows(1100) == 1152  # step = 1024/8 = 128
    with override(predict_bucket_pow2_exact=2048):
        assert bucket_rows(1100) == 2048  # now inside the exact-pow2 range
    with override(predict_bucket_octave_steps=4):
        assert bucket_rows(1100) == 1280  # step = 1024/4 = 256


def test_hand_set_scan_chunk_wins():
    from spark_ensemble_tpu.models.base import resolved_scan_chunk

    tuned = se.GBMRegressor()
    hand = se.GBMRegressor(scan_chunk=8)
    with override(scan_chunk=64):
        assert resolved_scan_chunk(tuned, 2048) == 64
        assert resolved_scan_chunk(hand, 2048) == 8


def test_hand_set_hist_tier_wins(monkeypatch):
    from spark_ensemble_tpu.ops.tree import _resolve_hist

    with override(hist_tier="stream"):
        # 'auto' consults the tuned tier ...
        assert _resolve_hist("auto", n=4096, d=8, B=32) == "stream"
        # ... but an explicit estimator param short-circuits it
        assert _resolve_hist("matmul", n=4096, d=8, B=32) == "matmul"


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def test_search_winner_is_deterministic_and_beats_noise_floor():
    """Scripted timings: a candidate that beats the default by more than
    the noise floor wins; one inside the floor loses to the default."""
    measure = _fake_measure({
        ("scan_chunk", 16): 1.00,
        ("scan_chunk", 4): 0.90,    # -10%: a real win
        ("scan_chunk", 8): 0.995,   # -0.5%: noise
        ("scan_chunk", 32): 1.20,
        "hist_tier": 1.0,           # flat: default ("auto") must win
    })
    res = run_search(
        budget="smoke", groups=("fit",), measure=measure, save=False
    )
    assert res["winners"] == {"scan_chunk": 4}
    assert "hist_tier" not in res["winners"]
    # deterministic: the same scripted timings pick the same winner
    res2 = run_search(
        budget="smoke", groups=("fit",),
        measure=_fake_measure({
            ("scan_chunk", 16): 1.00, ("scan_chunk", 4): 0.90,
            ("scan_chunk", 8): 0.995, ("scan_chunk", 32): 1.20,
            "hist_tier": 1.0,
        }),
        save=False,
    )
    assert res2["winners"] == res["winners"]


def test_search_publishes_both_shape_classes(tmp_path):
    d = str(tmp_path / "pub")
    measure = _fake_measure({("scan_chunk", 4): 0.5, "scan_chunk": 1.0,
                             "hist_tier": 1.0})
    res = run_search(
        budget="smoke", groups=("fit",), measure=measure, directory=d
    )
    assert res["winners"] == {"scan_chunk": 4}
    loaded = TuningCache.load(d)
    platform, kind = _device_identity()
    assert loaded.lookup(platform, kind, res["shape_class"]) == res["winners"]
    assert loaded.lookup(platform, kind, "nope") == res["winners"]  # via "*"


def test_autotune_fit_cache_hit_short_circuits():
    X = np.zeros((2048, 4), np.float32)
    platform, kind = _device_identity()
    cache = TuningCache()
    cache.put(platform, kind, shape_class(2048), {"scan_chunk": 64})
    cache.save()
    reset()
    measure = _fake_measure({})
    out = autotune_fit(se.GBMRegressor(), X, budget="smoke", measure=measure)
    assert out["cached"] is True
    assert out["params"] == {"scan_chunk": 64}
    assert measure.calls == []  # zero measurements on a hit
    # force=True re-measures even with the entry present
    out2 = autotune_fit(
        se.GBMRegressor(), X, budget="smoke", measure=measure,
        save=False, force=True,
    )
    assert "cached" not in out2
    assert len(measure.calls) > 0


def test_unknown_budget_and_group_raise():
    with pytest.raises(ValueError):
        run_search(budget="huge", measure=_fake_measure({}), save=False)
    with pytest.raises(ValueError):
        run_search(
            budget="smoke", groups=("nope",),
            measure=_fake_measure({}), save=False,
        )


# ---------------------------------------------------------------------------
# bit-identity: SE_TPU_AUTOTUNE=off vs unset-with-no-cache
# ---------------------------------------------------------------------------


def test_fit_bit_identical_off_vs_untuned(monkeypatch):
    """With no cache entries, mode 'cache' resolves every tunable to its
    default — fits must be BIT-identical to mode 'off'."""
    import jax

    rng = np.random.RandomState(0)
    X = rng.randn(300, 6).astype(np.float32)
    y = (X[:, 0] + 0.3 * rng.randn(300) > 0).astype(np.float32)

    def leaves(mode):
        if mode is None:
            monkeypatch.delenv("SE_TPU_AUTOTUNE", raising=False)
        else:
            monkeypatch.setenv("SE_TPU_AUTOTUNE", mode)
        m = se.GBMClassifier(num_base_learners=4, seed=0).fit(X, y)
        return [np.asarray(v) for v in jax.tree.leaves(m.params)]

    a, b = leaves(None), leaves("off")
    assert len(a) == len(b)
    for va, vb in zip(a, b):
        np.testing.assert_array_equal(va, vb)


def test_tuned_entry_changes_resolution_but_model_quality_holds():
    """A tuned scan_chunk produces the same model (chunking is a pure
    batching decision) while actually resolving through the cache."""
    platform, kind = _device_identity()
    cache = TuningCache()
    cache.put(platform, kind, "*", {"scan_chunk": 2})
    cache.save()
    reset()
    rng = np.random.RandomState(1)
    X = rng.randn(300, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    import jax

    m_tuned = se.GBMClassifier(num_base_learners=4, seed=0).fit(X, y)
    with override(mode="off"):
        m_off = se.GBMClassifier(num_base_learners=4, seed=0).fit(X, y)
    for va, vb in zip(
        jax.tree.leaves(m_tuned.params), jax.tree.leaves(m_off.params)
    ):
        np.testing.assert_allclose(
            np.asarray(va), np.asarray(vb), rtol=1e-6, atol=1e-6
        )


# ---------------------------------------------------------------------------
# collective version seam (ops/collective.py pvary/pcast guard)
# ---------------------------------------------------------------------------


def test_pvary_like_shard_handles_all_jax_spellings(monkeypatch):
    """Pin the pcast/pvary/neither fallback chain: the helper must track
    jax's rename (pvary -> pcast(to='varying')) without AttributeError on
    either side of it."""
    import jax

    from spark_ensemble_tpu.ops.collective import pvary_like_shard

    x = object()
    assert pvary_like_shard(x, None) is x  # unsharded: identity

    seen = {}

    def fake_pcast(v, names, to):
        seen["pcast"] = (names, to)
        return v

    def fake_pvary(v, names):
        seen["pvary"] = names
        return v

    monkeypatch.setattr(jax.lax, "pcast", fake_pcast, raising=False)
    monkeypatch.setattr(jax.lax, "pvary", fake_pvary, raising=False)
    assert pvary_like_shard(x, "data") is x
    assert seen == {"pcast": (("data",), "varying")}  # pcast preferred

    seen.clear()
    monkeypatch.delattr(jax.lax, "pcast", raising=False)
    assert pvary_like_shard(x, ("data", "model")) is x
    assert seen == {"pvary": ("data", "model")}  # old spelling

    monkeypatch.delattr(jax.lax, "pvary", raising=False)
    assert pvary_like_shard(x, "data") is x  # neither: no-op, no raise


def test_pzero_like_shard_tracks_the_same_seam(monkeypatch):
    """The zero-accumulator seed must ride the same pcast/pvary presence
    chain and fall back to a psum of zeros on check_rep-era jax (no
    varying-axes spelling at all) — value-identical, replication-typed."""
    import jax
    import numpy as np

    from spark_ensemble_tpu.ops.collective import pzero_like_shard

    x = np.ones(3, np.float32)
    np.testing.assert_array_equal(
        np.asarray(pzero_like_shard(x, None)), np.zeros(3, np.float32)
    )  # unsharded: plain zeros_like

    seen = {}

    def fake_pcast(v, names, to):
        seen["pcast"] = (names, to)
        return v

    monkeypatch.setattr(jax.lax, "pcast", fake_pcast, raising=False)
    monkeypatch.setattr(jax.lax, "pvary", None, raising=False)
    out = pzero_like_shard(x, "data")
    assert seen == {"pcast": (("data",), "varying")}
    np.testing.assert_array_equal(np.asarray(out), np.zeros(3, np.float32))

    # neither spelling: the psum-of-zeros fallback must be taken instead
    seen.clear()
    monkeypatch.delattr(jax.lax, "pcast", raising=False)
    monkeypatch.delattr(jax.lax, "pvary", raising=False)
    def fake_psum(v, a):
        seen["psum"] = a
        return v

    monkeypatch.setattr(jax.lax, "psum", fake_psum)
    out = pzero_like_shard(x, "data")
    assert seen == {"psum": "data"}
    np.testing.assert_array_equal(np.asarray(out), np.zeros(3, np.float32))


def test_enable_compilation_cache_unlatches_stale_init(tmp_path, monkeypatch):
    """jax latches its persistent-cache state at the process's FIRST
    compile; enabling after an early compile must reset the latch so the
    next compile re-initializes against the configured directory."""
    from jax._src import compilation_cache as jcc

    from spark_ensemble_tpu.autotune import compilation_cache as cc_mod

    monkeypatch.setattr(cc_mod, "_ENABLED_DIR", None)
    # simulate: something compiled before any cache dir was configured
    monkeypatch.setattr(jcc, "_cache", None)
    monkeypatch.setattr(jcc, "_cache_initialized", True)
    assert cc_mod.enable_compilation_cache(str(tmp_path / "cc"))
    assert jcc._cache_initialized is False  # re-inits on the next compile
    assert cc_mod.compilation_cache_dir() == str(tmp_path / "cc")
