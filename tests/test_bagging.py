"""Bagging tests (mirrors `BaggingRegressorSuite.scala:48-75`,
`BaggingClassifierSuite.scala:48-182`)."""

import pytest
import numpy as np

import spark_ensemble_tpu as se
from tests.conftest import accuracy, rmse, split


@pytest.mark.slow
def test_bagging_regressor_beats_single_tree(cpusmall):
    X, y = cpusmall
    Xtr, ytr, Xte, yte = split(X, y)
    tree = se.DecisionTreeRegressor(max_depth=5).fit(Xtr, ytr)
    bag = se.BaggingRegressor(
        base_learner=se.DecisionTreeRegressor(max_depth=5),
        num_base_learners=10,
        subsample_ratio=0.7,
        subspace_ratio=0.8,
        seed=1,
    ).fit(Xtr, ytr)
    assert rmse(bag.predict(Xte), yte) < rmse(tree.predict(Xte), yte)


@pytest.mark.slow
def test_bagging_classifier_beats_single_tree_and_members(letter):
    X, y = letter
    Xtr, ytr, Xte, yte = split(X, y)
    tree = se.DecisionTreeClassifier(max_depth=5).fit(Xtr, ytr)
    bag = se.BaggingClassifier(
        base_learner=se.DecisionTreeClassifier(max_depth=5),
        num_base_learners=10,
        subsample_ratio=0.7,
        subspace_ratio=0.8,
        voting_strategy="soft",
        seed=3,
    ).fit(Xtr, ytr)
    bag_acc = accuracy(bag.predict(Xte), yte)
    assert bag_acc > accuracy(tree.predict(Xte), yte)

    # beats (almost) every member, and members are diverse
    # (`BaggingClassifierSuite.scala:80-155`: pairwise agreement < 0.85)
    import jax

    base = bag._base()
    member_preds = np.asarray(
        jax.vmap(lambda p: base.predict_fn(p, se.models.base.as_f32(Xte)))(
            bag.params["members"]
        )
    )
    member_accs = [accuracy(mp, yte) for mp in member_preds]
    assert bag_acc > max(member_accs)
    agreements = [
        np.mean(member_preds[i] == member_preds[j])
        for i in range(len(member_preds))
        for j in range(i + 1, len(member_preds))
    ]
    assert max(agreements) < 0.85


def test_hard_and_soft_voting_both_work(letter):
    X, y = letter
    Xtr, ytr, Xte, yte = split(X, y)
    for strategy in ["hard", "soft"]:
        bag = se.BaggingClassifier(
            num_base_learners=5, voting_strategy=strategy, subsample_ratio=0.8
        ).fit(Xtr, ytr)
        assert accuracy(bag.predict(Xte), yte) > 0.3
        proba = np.asarray(bag.predict_proba(Xte))
        assert np.all(proba >= 0)
        assert np.allclose(proba.sum(-1), 1.0, atol=1e-4)


def test_bagging_reproducible_with_seed(cpusmall):
    X, y = cpusmall
    a = se.BaggingRegressor(num_base_learners=3, seed=7).fit(X, y)
    b = se.BaggingRegressor(num_base_learners=3, seed=7).fit(X, y)
    assert np.allclose(np.asarray(a.predict(X[:100])), np.asarray(b.predict(X[:100])))


def test_member_plan_bit_identical_to_eager_loop():
    """The one-program member plan must reproduce the eager draw tree
    exactly (seed+i discipline, `BaggingRegressor.scala:141-143`)."""
    import jax
    import jax.numpy as jnp

    from spark_ensemble_tpu.utils.random import bootstrap_weights, subspace_mask

    est = se.BaggingRegressor(
        num_base_learners=6, subsample_ratio=0.8, subspace_ratio=0.5, seed=4
    )
    w = jnp.arange(1.0, 51.0)
    fit_w, masks, keys = est._member_plan(50, 7, w)
    root = jax.random.PRNGKey(4)
    for i in [0, 2, 5]:
        key = jax.random.fold_in(root, i)
        np.testing.assert_array_equal(
            np.asarray(
                bootstrap_weights(jax.random.fold_in(key, 0), 50, True, 0.8)
            )
            * np.asarray(w),
            np.asarray(fit_w[i]),
        )
        np.testing.assert_array_equal(
            np.asarray(subspace_mask(jax.random.fold_in(key, 1), 7, 0.5)),
            np.asarray(masks[i]),
        )
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(key)),
            np.asarray(jax.random.key_data(keys[i])),
        )


@pytest.mark.slow
def test_member_extraction_matches_member_predictions(letter):
    """model.member(i) is member i as a standalone fitted model (the
    reference models' `models` array); its predictions match the fused
    member_predictions row."""
    X, y = letter
    Xs, ys = X[:2000], y[:2000]
    bag = se.BaggingClassifier(
        num_base_learners=3, subspace_ratio=0.7, seed=1
    ).fit(Xs, ys)
    fused = np.asarray(bag.member_class_predictions(Xs[:300]))
    for i in range(3):
        m = bag.member(i)
        np.testing.assert_array_equal(
            np.asarray(m.predict(Xs[:300])), fused[i]
        )
    # GBM regressor members (rounds) and classifier grid members
    yk = (Xs[:, 0] > Xs[:, 0].mean()).astype(np.float32)
    g = se.GBMClassifier(num_base_learners=2).fit(Xs, yk)
    sub = g.member(1, dim=0)
    assert np.isfinite(np.asarray(sub.predict(Xs[:50]))).all()
    import pytest

    with pytest.raises(AttributeError):
        se.DecisionTreeClassifier().fit(Xs, ys).member(0)
    # jax clamps out-of-range indices; member() must bounds-check instead
    with pytest.raises(IndexError):
        bag.member(3)
    with pytest.raises(IndexError):
        g.member(0, dim=99)
