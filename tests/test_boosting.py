"""Boosting tests (mirrors `BoostingClassifierSuite.scala:52-154`,
`BoostingRegressorSuite.scala:78-182`)."""

import pytest
import numpy as np

import spark_ensemble_tpu as se
from tests.conftest import accuracy, rmse, split


@pytest.mark.slow
def test_boosting_classifier_beats_single_tree(letter):
    X, y = letter
    Xtr, ytr, Xte, yte = split(X, y)
    tree = se.DecisionTreeClassifier(max_depth=5).fit(Xtr, ytr)
    boost = se.BoostingClassifier(
        base_learner=se.DecisionTreeClassifier(max_depth=5), num_base_learners=10
    ).fit(Xtr, ytr)
    assert accuracy(boost.predict(Xte), yte) > accuracy(tree.predict(Xte), yte)


@pytest.mark.slow
def test_prefix_models_mostly_improve(letter):
    """Monotone-improvement archetype (`BoostingClassifierSuite.scala:52-91`):
    >= 0.8 of the prefix steps must not degrade accuracy."""
    X, y = letter
    Xtr, ytr, Xte, yte = split(X, y)
    boost = se.BoostingClassifier(num_base_learners=8).fit(Xtr, ytr)
    accs = [
        accuracy(boost.take(k).predict(Xte), yte)
        for k in range(1, boost.num_members + 1)
    ]
    improving = sum(b >= a for a, b in zip(accs, accs[1:]))
    assert improving / max(len(accs) - 1, 1) >= 0.5
    assert accs[-1] > accs[0]


@pytest.mark.slow
def test_samme_and_samme_r_close(letter_full):
    """`BoostingClassifierSuite.scala:93-124`: SAMME ~= SAMME.R (reference
    asserts +-0.02 with depth-10 Spark trees; our complete-layout trees give
    sharper leaf probabilities, widening the gap slightly — allow 0.06)."""
    X, y = letter_full
    Xtr, ytr, Xte, yte = split(X, y)
    base = se.DecisionTreeClassifier(max_depth=10)
    discrete = se.BoostingClassifier(
        base_learner=base, num_base_learners=10, algorithm="discrete"
    ).fit(Xtr, ytr)
    real = se.BoostingClassifier(
        base_learner=base, num_base_learners=10, algorithm="real"
    ).fit(Xtr, ytr)
    a = accuracy(discrete.predict(Xte), yte)
    b = accuracy(real.predict(Xte), yte)
    assert abs(a - b) < 0.06


@pytest.mark.slow
def test_raw_predictions_sum_to_zero(letter):
    """Symmetric-constraint invariant (`BoostingClassifierSuite.scala:126-154`)."""
    X, y = letter
    Xtr, ytr, Xte, _ = split(X, y)
    for algorithm in ["discrete", "real"]:
        boost = se.BoostingClassifier(num_base_learners=4, algorithm=algorithm).fit(
            Xtr, ytr
        )
        raw = np.asarray(boost.predict_raw(Xte[:50]))
        assert np.allclose(raw.sum(-1), 0.0, atol=1e-2 * np.abs(raw).max())


@pytest.mark.slow
def test_boosting_regressor_beats_single_tree(cpusmall):
    X, y = cpusmall
    Xtr, ytr, Xte, yte = split(X, y)
    tree = se.DecisionTreeRegressor(max_depth=5).fit(Xtr, ytr)
    boost = se.BoostingRegressor(num_base_learners=10).fit(Xtr, ytr)
    assert rmse(boost.predict(Xte), yte) < rmse(tree.predict(Xte), yte) * 1.05


def test_weighted_median_close_to_mean_vote(cpusmall):
    """`BoostingRegressorSuite.scala:111-132`: median and mean votes agree
    within 10% of the target scale."""
    X, y = cpusmall
    Xtr, ytr, Xte, yte = split(X, y)
    boost = se.BoostingRegressor(num_base_learners=8).fit(Xtr, ytr)
    median_pred = np.asarray(boost.predict(Xte))
    boost.voting_strategy = "mean"
    mean_pred = np.asarray(boost.predict(Xte))
    scale = float(np.std(y))
    assert np.mean(np.abs(median_pred - mean_pred)) < 0.25 * scale


def test_degenerate_constant_labels_stop_early():
    """`BoostingRegressorSuite.scala:154-167` (maxErrorIsNull): all-equal
    labels stop after one perfect member."""
    rng = np.random.RandomState(0)
    X = rng.randn(300, 5).astype(np.float32)
    y = np.full(300, 2.5, np.float32)
    boost = se.BoostingRegressor(num_base_learners=10).fit(X, y)
    assert boost.num_members == 1
    assert np.allclose(np.asarray(boost.predict(X[:10])), 2.5, atol=1e-4)


@pytest.mark.slow
def test_round_program_not_stale_after_set_params():
    """Regression (ADVICE r1): the cached round-step program must not read
    `self.loss` at retrace time.  Mutating one estimator's loss after fit
    must not corrupt a later same-config fit that retraces under new
    shapes."""
    from spark_ensemble_tpu.models.base import _PROGRAM_CACHE

    rng = np.random.RandomState(7)
    X1 = rng.randn(200, 4).astype(np.float32)
    y1 = (X1[:, 0] + 0.1 * rng.randn(200)).astype(np.float32)
    X2 = rng.randn(333, 4).astype(np.float32)  # new shape -> retrace
    y2 = (X2[:, 0] + 0.1 * rng.randn(333)).astype(np.float32)

    est_a = se.BoostingRegressor(loss="exponential", num_base_learners=3, seed=1)
    est_a.fit(X1, y1)  # caches the 'exponential' round program
    est_a.set_params(loss="squared")  # mutation after fit

    est_b = se.BoostingRegressor(loss="exponential", num_base_learners=3, seed=1)
    got = np.asarray(est_b.fit(X2, y2).predict(X2[:50]))

    _PROGRAM_CACHE.clear()  # ground truth from an untainted program
    fresh = se.BoostingRegressor(loss="exponential", num_base_learners=3, seed=1)
    want = np.asarray(fresh.fit(X2, y2).predict(X2[:50]))
    assert np.allclose(got, want, atol=1e-5)


@pytest.mark.slow
def test_boosting_scan_chunk_invariance(letter, cpusmall):
    """Chunked dispatch must reproduce the per-round loop exactly — same
    member count (stop replay) and identical predictions — for both
    flavors, including mid-chunk stops."""
    X, y = letter
    Xr, yr = cpusmall
    cls = [
        se.BoostingClassifier(num_base_learners=7, scan_chunk=c, seed=2).fit(X, y)
        for c in (1, 4)
    ]
    assert cls[0].num_members == cls[1].num_members
    np.testing.assert_allclose(
        np.asarray(cls[0].predict_raw(X[:200])),
        np.asarray(cls[1].predict_raw(X[:200])),
        rtol=1e-5, atol=1e-5,
    )
    regs = [
        se.BoostingRegressor(num_base_learners=7, scan_chunk=c, seed=2).fit(Xr, yr)
        for c in (1, 4)
    ]
    assert regs[0].num_members == regs[1].num_members
    np.testing.assert_allclose(
        np.asarray(regs[0].predict(Xr[:200])),
        np.asarray(regs[1].predict(Xr[:200])),
        rtol=1e-5, atol=1e-5,
    )


class _SpyBoostingClassifier(se.BoostingClassifier):
    """Records the chunk sizes the round driver dispatches."""

    def _drive_boosting_rounds(self, ckpt, bw, root, mc, wc, run_chunk,
                               replay, start_i, ramp=False, telem=None,
                               guard=None):
        self.dispatched = []

        def spy(keys, bw):
            self.dispatched.append(int(keys.shape[0]))
            return run_chunk(keys, bw)

        return super()._drive_boosting_rounds(
            ckpt, bw, root, mc, wc, spy, replay, start_i, ramp=ramp,
            telem=telem, guard=guard,
        )


@pytest.mark.slow
def test_boosting_chunk_ramp_schedule(letter):
    """Abort-prone discrete SAMME dispatches a single-round probe chunk,
    then full chunks (probe-then-full: one extra dispatch on abort-free
    runs, zero discard on the dominant round-0 abort); ramp='off' skips
    the probe; SAMME.R (no error-threshold abort) never probes."""
    X, y = letter
    Xs, ys = X[:1500], y[:1500]
    disc = _SpyBoostingClassifier(
        num_base_learners=10, scan_chunk=16, seed=2
    )
    disc.fit(Xs, ys)
    assert disc.dispatched == [1, 9], disc.dispatched
    off = _SpyBoostingClassifier(
        num_base_learners=10, scan_chunk=16, seed=2, ramp="off"
    )
    off.fit(Xs, ys)
    assert off.dispatched == [10], off.dispatched
    real = _SpyBoostingClassifier(
        algorithm="real", num_base_learners=10, scan_chunk=16, seed=2
    )
    real.fit(Xs, ys)
    assert real.dispatched[0] == 10


def test_boosting_ramp_bounds_discarded_work_on_early_abort():
    """A constant-prediction base learner on skewed labels has weighted
    error 0.8 >= 1 - 1/K, so discrete SAMME aborts on the very first
    round; the ramp's first chunk is a single round, so exactly one base
    fit is dispatched (a fixed scan_chunk=16 would have dispatched and
    discarded 16)."""
    rng = np.random.RandomState(0)
    X = rng.randn(400, 3).astype(np.float32)
    y = (rng.rand(400) < 0.8).astype(np.float32)  # class 1 dominates
    est = _SpyBoostingClassifier(
        base_learner=se.DummyClassifier(strategy="constant", constant=0),
        num_base_learners=16, scan_chunk=16, seed=0,
    )
    m = est.fit(X, y)
    assert est.dispatched == [1], est.dispatched
    assert m.num_members == 0
