"""Training-state checkpoint/resume tests (SURVEY.md §5: the TPU build gets
real mid-run resumability where the reference only truncated RDD lineage)."""

import pytest as _pytest

pytestmark = _pytest.mark.slow


import numpy as np

import spark_ensemble_tpu as se
from spark_ensemble_tpu.utils.checkpoint import TrainingCheckpointer


def _data(n=800, d=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (2 * X[:, 0] + np.sin(3 * X[:, 1]) + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


def test_checkpointer_roundtrip(tmp_path):
    import jax.numpy as jnp

    ck = TrainingCheckpointer(str(tmp_path / "ck"), interval=2)
    state = {"v": 1, "best": 2.5, "pred": jnp.arange(4.0), "weights": [1.0, 2.0]}
    ck.maybe_save(0, state)  # round 0: (0+1) % 2 != 0 -> skipped
    assert ck.load_latest() is None
    ck.maybe_save(1, state)
    got = ck.load_latest()
    assert got is not None
    rnd, st = got
    assert rnd == 1
    assert st["v"] == 1
    assert np.allclose(np.asarray(st["pred"]), [0, 1, 2, 3])
    ck.delete()
    assert ck.load_latest() is None


def _resume_vs_full(tmp_path, make_est, X, y, n_full=6, n_part=4):
    """Shared harness: fit n_full rounds straight vs interrupted-at-n_part +
    resumed; final models must predict identically."""
    ckdir = str(tmp_path / "ck")
    full = make_est(num_base_learners=n_full).fit(X, y)
    est = make_est(
        num_base_learners=n_part, checkpoint_dir=ckdir, checkpoint_interval=n_part
    )
    orig_delete = TrainingCheckpointer.delete
    TrainingCheckpointer.delete = lambda self: None
    try:
        est.fit(X, y)
    finally:
        TrainingCheckpointer.delete = orig_delete
    import os

    assert os.path.exists(os.path.join(ckdir, "latest", "state.json"))
    resumed = make_est(
        num_base_learners=n_full, checkpoint_dir=ckdir, checkpoint_interval=100
    ).fit(X, y)
    a = np.asarray(full.predict(X[:100]))
    b = np.asarray(resumed.predict(X[:100]))
    assert resumed.num_members == full.num_members
    assert np.allclose(a, b, atol=1e-4), np.abs(a - b).max()


def test_boosting_regressor_resume_matches_uninterrupted(tmp_path):
    X, y = _data()
    _resume_vs_full(
        tmp_path, lambda **kw: se.BoostingRegressor(seed=3, loss="linear", **kw), X, y
    )


def test_boosting_classifier_resume_matches_uninterrupted(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(600, 5).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    _resume_vs_full(
        tmp_path, lambda **kw: se.BoostingClassifier(seed=3, **kw), X, y
    )


def test_gbm_resume_matches_uninterrupted(tmp_path):
    """Fit 6 rounds straight vs fit interrupted at round 4 + resumed: the
    final models must predict identically."""
    X, y = _data()
    ckdir = str(tmp_path / "gbm_ck")

    full = se.GBMRegressor(num_base_learners=6, seed=3).fit(X, y)

    # "interrupted" run: checkpoint every 2 rounds, stop after round 3
    class StopAt(Exception):
        pass

    est = se.GBMRegressor(
        num_base_learners=4, seed=3, checkpoint_dir=ckdir, checkpoint_interval=2
    )
    est.fit(X, y)
    # the 4-round run checkpointed at rounds 1 and 3 but completed, deleting
    # its checkpoints; emulate preemption by re-creating the checkpoint:
    ck = TrainingCheckpointer(ckdir, 2)
    assert ck.load_latest() is None

    # real interruption test: save a checkpoint manually mid-run by running
    # 4 rounds with interval 4 (checkpoint at round 3 survives only if the
    # run dies before delete) — emulate by monkeypatching delete to no-op
    est2 = se.GBMRegressor(
        num_base_learners=4, seed=3, checkpoint_dir=ckdir, checkpoint_interval=4
    )
    orig_delete = TrainingCheckpointer.delete
    TrainingCheckpointer.delete = lambda self: None
    try:
        est2.fit(X, y)
    finally:
        TrainingCheckpointer.delete = orig_delete
    import os

    assert os.path.exists(os.path.join(ckdir, "latest", "state.json"))

    # resume with the full budget: rounds 4..5 run on top of the restored state
    resumed = se.GBMRegressor(
        num_base_learners=6, seed=3, checkpoint_dir=ckdir, checkpoint_interval=100
    ).fit(X, y)
    a = np.asarray(full.predict(X[:100]))
    b = np.asarray(resumed.predict(X[:100]))
    assert resumed.num_members == full.num_members == 6
    assert np.allclose(a, b, atol=1e-4), np.abs(a - b).max()


def test_gbm_resume_with_changed_interval_keeps_saving(tmp_path):
    """Regression: a resume may start at a round misaligned with a CHANGED
    checkpoint_interval (interval is resume-neutral by design); the chunked
    round loop must clamp chunk ends to the new save boundaries so periodic
    saves keep firing — not silently stop until the next preemption loses
    everything."""
    X, y = _data()
    ckdir = str(tmp_path / "gbm_ck2")

    # preempted run: 4 rounds, interval 4 -> checkpoint at round idx 3
    est = se.GBMRegressor(
        num_base_learners=4, seed=3, checkpoint_dir=ckdir, checkpoint_interval=4,
        scan_chunk=4,
    )
    orig_delete = TrainingCheckpointer.delete
    TrainingCheckpointer.delete = lambda self: None
    try:
        est.fit(X, y)
    finally:
        TrainingCheckpointer.delete = orig_delete

    # resume at round 4 with interval 5 (misaligned: 4 % 5 != 0); saves must
    # fire at rounds where (idx+1) % 5 == 0 -> idx 4 and idx 9
    saved = []
    orig_save = TrainingCheckpointer.save
    TrainingCheckpointer.save = lambda self, r, s: (
        saved.append(r), orig_save(self, r, s)
    )[1]
    try:
        full = se.GBMRegressor(num_base_learners=12, seed=3, scan_chunk=4).fit(X, y)
        resumed = se.GBMRegressor(
            num_base_learners=12, seed=3, checkpoint_dir=ckdir,
            checkpoint_interval=5, scan_chunk=4,
        ).fit(X, y)
    finally:
        TrainingCheckpointer.save = orig_save
    assert 4 in saved and 9 in saved, saved
    a = np.asarray(full.predict(X[:100]))
    b = np.asarray(resumed.predict(X[:100]))
    assert resumed.num_members == full.num_members == 12
    assert np.allclose(a, b, atol=1e-4), np.abs(a - b).max()


def test_async_save_roundtrip_and_failure_propagation(tmp_path):
    """Async saves must land atomically with identical contents to sync
    saves, and a failed background write must re-raise at the next
    checkpointer call (same surface as a synchronous failure)."""
    import jax.numpy as jnp

    sync = TrainingCheckpointer(
        str(tmp_path / "sync"), interval=1, async_save=False
    )
    asy = TrainingCheckpointer(str(tmp_path / "async"), interval=1)
    state = {
        "v": 3,
        "pred": jnp.arange(16.0),
        "members": {"leaf": jnp.ones((4, 2))},
    }
    sync.save(0, state)
    asy.save(0, state)
    asy.wait()
    rs, ss = sync.load_latest()[1], asy.load_latest()[1]
    assert np.allclose(np.asarray(ss["pred"]), np.asarray(rs["pred"]))
    assert np.allclose(
        np.asarray(ss["members"]["leaf"]), np.asarray(rs["members"]["leaf"])
    )

    # overlapping saves keep ordering: the LAST save wins 'latest'
    for i in range(5):
        asy.save(i, {"v": i, "pred": jnp.full((8,), float(i))})
    rnd, st = asy.load_latest()
    assert rnd == 4 and float(np.asarray(st["pred"])[0]) == 4.0

    # failure propagation: unpicklable/unencodable state fails in the
    # writer thread and surfaces at the next wait()/save()
    class Weird:
        pass

    asy.save(5, {"bad": Weird()})
    import pytest

    with pytest.raises(Exception):
        asy.wait()
    asy.delete()


def test_gbm_fit_with_async_checkpointing_matches(tmp_path):
    """End-to-end: a fit whose periodic saves run async must produce the
    same model as one with checkpointing off (saves are pure side
    effects)."""
    import spark_ensemble_tpu as se

    X, y = _data(600)
    plain = se.GBMRegressor(num_base_learners=6, seed=3).fit(X, y)
    ck = se.GBMRegressor(
        num_base_learners=6, seed=3,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_interval=2,
        scan_chunk=2,
    ).fit(X, y)
    np.testing.assert_allclose(
        np.asarray(plain.predict(X[:100])), np.asarray(ck.predict(X[:100])),
        atol=1e-5,
    )
