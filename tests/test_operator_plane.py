"""Live operator plane tests (docs/operator.md): the per-program XLA
cost inventory (capture on cache fetch, shallow compile-free analysis,
the three-way round-ledger join), the stdlib /metrics exporter
(OpenMetrics rendering + the syntax checker + a live scrape during an
active fit), and the online watchdog (deterministic raise/clear of an
``slo_alert`` via injected replica stalls, hysteresis, probe freeze,
sentinel-derived thresholds)."""

import importlib.util
import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import spark_ensemble_tpu as se
from spark_ensemble_tpu.robustness.chaos import ChaosController, install
from spark_ensemble_tpu.serving import FleetRouter, pack
from spark_ensemble_tpu.telemetry import programz, record_fits
from spark_ensemble_tpu.telemetry.events import compile_snapshot
from spark_ensemble_tpu.telemetry.exporter import (
    OperatorPlane,
    render_openmetrics,
    validate_openmetrics,
    write_snapshot,
)
from spark_ensemble_tpu.telemetry.watchdog import (
    FALLBACK_THRESHOLDS,
    Rule,
    Watchdog,
    default_rules,
    probe_fleet_max,
    sentinel_thresholds,
)


def _data(n=96, d=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


def _fetch(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture(autouse=True)
def _deterministic_chaos():
    # the watchdog tests drive stalls with their OWN controllers; pin a
    # never-fires one so an env-configured chaos tier cannot perturb the
    # exact raise/clear tick counts
    install(ChaosController(seed=0, rate=0.0))
    yield
    install(None)


@pytest.fixture()
def inventory():
    inv = programz.enable()
    inv.clear()
    try:
        yield inv
    finally:
        programz.disable()
        inv.clear()


# ---------------------------------------------------------------------------
# program inventory
# ---------------------------------------------------------------------------


def test_inventory_captures_and_analyzes_fit_programs(inventory):
    X, y = _data()
    se.GBMRegressor(num_base_learners=3, seed=0).fit(X, y)
    assert inventory.summary()["programs"] >= 1
    inventory.analyze_pending()
    rows = inventory.rows()
    analyzed = [r for r in rows if r["status"] == "analyzed"]
    assert analyzed, rows
    top = analyzed[0]
    # rows() sorts by -flops: the top analyzed row carries the full cost
    # block, flattened to top-level keys
    assert top["flops"] > 0
    assert top["bytes_accessed"] > 0
    assert top["calls"] >= 1
    assert top["signature"]  # aval signature, JSON-friendly


def test_shallow_analysis_is_compile_free(inventory):
    X, y = _data()
    se.GBMRegressor(num_base_learners=2, seed=0).fit(X, y)
    before, _ = compile_snapshot()
    inventory.analyze_pending()  # deep=False: lower only, never compile
    after, _ = compile_snapshot()
    assert after == before, (before, after)
    assert any(r["status"] == "analyzed" for r in inventory.rows())


def test_emit_rows_lands_program_events(inventory, tmp_path):
    X, y = _data()
    se.GBMRegressor(num_base_learners=2, seed=0).fit(X, y)
    inventory.analyze_pending()
    path = tmp_path / "programs.jsonl"
    count = inventory.emit_rows(path=str(path))
    assert count >= 1
    events = [json.loads(line) for line in path.read_text().splitlines()]
    assert all(e["event"] == "program" for e in events)
    assert any(e.get("flops") for e in events)


def test_round_ledger_three_way_join_matmul_tier(inventory):
    """The acceptance tolerance (docs/operator.md#cost-triangle): on the
    matmul hist tier the XLA flop count and the analytic round estimate
    agree within the DOCUMENTED range — the analytic model charges full
    per-level node dims (no sibling-subtraction credit), so XLA/analytic
    sits well below 1 on CPU; the pinned band is drift protection, not a
    claim of equality."""
    X, y = _data(n=128)

    def fit():
        with record_fits() as rec:
            se.GBMRegressor(
                base_learner=se.DecisionTreeRegressor(
                    max_depth=3, hist="matmul"),
                num_base_learners=3, seed=0,
            ).fit(X, y)
        return [e for e in rec.events if e["event"] == "round_end"]

    fit()  # capture the programs
    inventory.analyze_pending()
    rounds = fit()  # analyzed inventory joins into this fit's ledger
    joined = [e for e in rounds if e.get("xla_flops")]
    assert joined, rounds
    e = joined[-1]
    assert e["program_tag"] == "gbm_reg_round"
    assert e["xla_modeled_s"] > 0
    assert e["mfu_xla"] >= 0
    assert e["xla_bytes_accessed"] > 0
    assert 0.05 <= e["xla_vs_analytic_flops_ratio"] <= 2.0, e


# ---------------------------------------------------------------------------
# exporter
# ---------------------------------------------------------------------------


def test_render_openmetrics_families_and_sources():
    snapshot = {
        "fit/rounds": {"type": "counter", "value": 7},
        "hbm/cpu:0/bytes_in_use": {"type": "gauge", "value": 1024},
        "fit/round_ms": {
            "type": "histogram", "count": 3, "sum": 30.0,
            "p50": 9.0, "p90": 11.0, "p99": 12.0,
        },
        "fleet/svc:1:2": {
            "type": "source",
            "value": {"p99_ms": 4.5, "stopped": False,
                      "replicas": ["r0", "r1"], "label": "svc"},
        },
    }
    text = render_openmetrics(snapshot)
    assert "# TYPE se_tpu_fit_rounds counter" in text
    assert "se_tpu_fit_rounds_total 7" in text
    assert "se_tpu_hbm_cpu:0_bytes_in_use 1024" in text
    assert 'se_tpu_fit_round_ms{quantile="0.99"} 12' in text
    assert ('se_tpu_fleet{source="svc:1:2",field="p99_ms"} 4.5'
            in text)
    assert 'field="stopped"} 0' in text          # bools become 0/1
    assert 'field="replicas.len"} 2' in text     # lists export length
    assert 'field="label"' not in text           # strings are dropped
    assert text.endswith("# EOF\n")
    assert validate_openmetrics(text) == []


def test_validate_openmetrics_catches_violations():
    assert validate_openmetrics("# EOF\n") == []
    assert validate_openmetrics("se_tpu_x 1\n") != []  # no EOF, no TYPE
    bad_suffix = (
        "# TYPE se_tpu_a counter\nse_tpu_a 1\n# EOF"
    )  # counters must sample as _total
    assert any("no declared TYPE" in p
               for p in validate_openmetrics(bad_suffix))
    dup = "# TYPE se_tpu_a gauge\n# TYPE se_tpu_a gauge\n# EOF"
    assert any("duplicate" in p for p in validate_openmetrics(dup))
    interleaved = (
        "# TYPE se_tpu_a gauge\n# TYPE se_tpu_b gauge\n"
        "se_tpu_b 1\nse_tpu_a 1\nse_tpu_b 2\n# EOF"
    )
    assert any("interleaved" in p
               for p in validate_openmetrics(interleaved))
    assert any("unparseable" in p
               for p in validate_openmetrics("!!!\n# EOF"))


def test_live_scrape_during_active_fit_is_valid_and_compile_free():
    X, y = _data()
    # warm every program first: the scrape loop below must then observe
    # ZERO compiles — neither the fit re-compiling nor the scrape
    # triggering one (the exporter renders already-collected state only)
    se.GBMRegressor(num_base_learners=3, seed=0).fit(X, y)
    plane = OperatorPlane(port=0, with_watchdog=True,
                          sampler_interval_s=0.05,
                          watchdog_interval_s=3600.0).start()
    try:
        stop = threading.Event()
        problems, codes = [], []

        def scraper():
            while not stop.is_set():
                code, body = _fetch(plane.url + "/metrics")
                codes.append(code)
                problems.extend(validate_openmetrics(body))
                _fetch(plane.url + "/programz?n=5")
                _fetch(plane.url + "/statusz")

        t = threading.Thread(target=scraper, daemon=True)
        before, _ = compile_snapshot()
        t.start()
        se.GBMRegressor(num_base_learners=3, seed=0).fit(X, y)
        stop.set()
        t.join(timeout=30)
        after, _ = compile_snapshot()
        assert codes and all(c == 200 for c in codes)
        assert problems == []
        assert after == before, (before, after)
        code, body = _fetch(plane.url + "/statusz")
        status = json.loads(body)
        assert status["backend"]
        assert status["scrapes"] >= len(codes)
        code, body = _fetch(plane.url + "/healthz")
        assert code == 200
        code, _ = _fetch(plane.url + "/nope")
        assert code == 404
    finally:
        plane.stop()


def test_write_snapshot_files_validate(tmp_path, inventory):
    X, y = _data()
    se.GBMRegressor(num_base_learners=2, seed=0).fit(X, y)
    inventory.analyze_pending()
    paths = write_snapshot(str(tmp_path / "snap"), inventory=inventory)
    text = open(paths["metrics"]).read()
    assert validate_openmetrics(text) == []
    progs = json.load(open(paths["programz"]))
    assert progs["programs"]
    status = json.load(open(paths["statusz"]))
    assert status["programs"]["programs"] >= 1


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def test_sentinel_thresholds_derive_from_baseline(tmp_path):
    tools = tmp_path / "tools"
    tools.mkdir()
    (tools / "perf_sentinel.py").write_text(
        'METRICS = {"serving_p99_ms": ("lower", 0.25, 1.0),\n'
        '           "hedge_rate": ("lower", 0.5, 0.1)}\n'
    )
    (tmp_path / "PERF_BASELINE.json").write_text(
        '{"serving_p99_ms": 100.0}\n'
    )
    th = sentinel_thresholds(repo_root=str(tmp_path))
    # baseline-pinned: max(b*(1+rel), b+floor) = max(125, 101)
    assert th["serving_p99_ms"] == ("lower", 125.0)
    # in METRICS but not in the baseline -> fallback survives
    assert th["hedge_rate"] == FALLBACK_THRESHOLDS["hedge_rate"]
    # no tools/ checkout at all -> pure fallbacks
    assert sentinel_thresholds(
        repo_root=str(tmp_path / "missing")) == FALLBACK_THRESHOLDS


def test_default_rules_cover_the_slo_surface():
    rules = {r.name: r for r in default_rules()}
    assert set(rules) == set(FALLBACK_THRESHOLDS)
    assert all(r.direction == "lower" for r in rules.values())


def test_watchdog_raises_and_clears_slo_alert(tmp_path):
    """The acceptance chaos scenario, fully deterministic: replica_stall
    at rate 1.0 pushes fleet p99 two orders past the rule threshold, one
    tick raises the alert (breach_for=1), the verdict degrades; a fast
    wash pushes the stalls out of the router's rolling window and two
    healthy ticks clear it — both transitions land as ``slo_alert``
    events and survive the Perfetto export as instants."""
    X, y = _data()
    model = pack(se.GBMRegressor(num_base_learners=3, seed=0).fit(X, y))
    telemetry = tmp_path / "slo.jsonl"
    dog = Watchdog(
        rules=[Rule("serving_p99_ms", probe_fleet_max("p99_ms"),
                    threshold=50.0, breach_for=1, clear_for=2)],
        interval_s=3600.0,
        telemetry_path=str(telemetry),
    )
    with FleetRouter(
        model, replicas=2, min_bucket=8, max_batch_size=16,
        deadline_ms=30_000.0, telemetry_path=str(telemetry),
    ) as fleet:
        install(ChaosController(seed=7, rate=1.0,
                                faults=("replica_stall",)))
        for _ in range(6):
            fleet.predict(X[:8])
        readings = dog.evaluate_once()
        assert readings["serving_p99_ms"]["active"] is True
        verdict = dog.verdict()
        assert verdict["status"] == "degraded"
        assert verdict["alerts"][0]["metric"] == "serving_p99_ms"

        install(ChaosController(seed=0, rate=0.0))
        for _ in range(300):  # wash the 256-sample rolling window
            fleet.predict(X[:8])
        dog.evaluate_once()
        assert dog.verdict()["status"] == "degraded"  # clear_for=2 holds
        dog.evaluate_once()
        assert dog.verdict()["status"] == "ok"

    lines = [json.loads(line)
             for line in telemetry.read_text().splitlines()]
    alerts = [e for e in lines if e["event"] == "slo_alert"]
    assert [a["state"] for a in alerts] == ["raised", "cleared"]
    assert all(a["metric"] == "serving_p99_ms" for a in alerts)
    assert alerts[0]["value"] > alerts[0]["threshold"]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "_viewer", os.path.join(repo, "tools", "trace_viewer.py"))
    viewer = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(viewer)
    trace = viewer.to_trace_events(
        viewer.select_spans(lines),
        [e for e in lines if e.get("event") in viewer.INSTANT_EVENTS],
    )
    names = {ev.get("name") for ev in trace["traceEvents"]
             if ev.get("ph") == "i"}
    assert "slo_alert" in names


def test_watchdog_probe_freeze_never_clears():
    """A probe returning None (fleet gone, fit finished) FREEZES the
    state machine: an active alert must not silently clear just because
    the signal disappeared."""
    values = {"v": 100.0}
    rule = Rule("x", lambda snap: values["v"], threshold=10.0,
                breach_for=1, clear_for=1)
    dog = Watchdog(rules=[rule], interval_s=3600.0)
    dog.evaluate_once(snapshot={})
    assert dog.verdict()["status"] == "degraded"
    values["v"] = None
    dog.evaluate_once(snapshot={})
    assert dog.verdict()["status"] == "degraded"  # frozen, not cleared
    values["v"] = 1.0
    dog.evaluate_once(snapshot={})
    assert dog.verdict()["status"] == "ok"


def test_watchdog_hysteresis_widths():
    values = {"v": 0.0}
    rule = Rule("x", lambda snap: values["v"], threshold=10.0,
                breach_for=3, clear_for=2)
    dog = Watchdog(rules=[rule], interval_s=3600.0)
    values["v"] = 100.0
    dog.evaluate_once(snapshot={})
    dog.evaluate_once(snapshot={})
    assert dog.verdict()["status"] == "ok"      # 2 of 3 breach ticks
    dog.evaluate_once(snapshot={})
    assert dog.verdict()["status"] == "degraded"
    values["v"] = 0.0
    dog.evaluate_once(snapshot={})
    assert dog.verdict()["status"] == "degraded"  # 1 of 2 clear ticks
    dog.evaluate_once(snapshot={})
    assert dog.verdict()["status"] == "ok"
