"""graftlint tier 2: REAL traced program contracts (slow tier).

One full contract trace per module — every family fits and predicts on
the canonical shape classes with the program observer registered, the
serving engine warms — then every assertion reads off that one report.
"""

import pytest

from spark_ensemble_tpu.analysis import contracts as contracts_mod

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def traced():
    return contracts_mod.trace_contracts()


def _copy(report):
    # check_contracts appends to the report it is given; keep the
    # module-scoped trace pristine across tests
    return contracts_mod.ContractReport(
        budgets=dict(report.budgets),
        violations=list(report.violations),
        skipped=dict(report.skipped),
    )


def test_intrinsic_contracts_hold(traced):
    # no f64, no host callbacks in round loops, no off-mesh axis names,
    # serving warmup exact and steady-state compile-free
    assert not traced.violations, [v.to_record() for v in traced.violations]


def test_committed_baseline_matches_fresh_trace(traced):
    base = contracts_mod.load_baseline()
    assert base is not None, "analysis/contracts.json must be committed"
    assert traced.baseline() == base, (
        "compile budgets drifted from analysis/contracts.json; if the "
        "change is intentional re-pin with "
        "`python tools/graftlint.py --update-baseline` and review the diff"
    )


def test_check_contracts_clean_against_committed(traced):
    report = contracts_mod.check_contracts(report=_copy(traced))
    assert report.ok, [v.to_record() for v in report.violations]


def test_corrupted_baseline_fails_then_committed_fixes(traced):
    base = contracts_mod.load_baseline()
    corrupted = {
        "version": 1,
        "entry_points": dict(
            base["entry_points"], **{"gbm_regressor.fit": base[
                "entry_points"]["gbm_regressor.fit"] + 1}
        ),
    }
    broken = contracts_mod.check_contracts(
        baseline=corrupted, report=_copy(traced)
    )
    assert any(
        v.contract == "budget" and v.entry_point == "gbm_regressor.fit"
        for v in broken.violations
    )
    assert contracts_mod.check_contracts(
        baseline=base, report=_copy(traced)
    ).ok


@pytest.mark.parametrize(
    "family", ["gbm", "boosting", "bagging", "stacking"]
)
def test_family_budgets_traced(traced, family):
    assert f"{family}_regressor.fit" in traced.budgets
    assert f"{family}_regressor.predict" in traced.budgets
    assert f"{family}_classifier.fit" in traced.budgets
    assert f"{family}_classifier.predict_proba" in traced.budgets


def test_serving_warmup_budget(traced):
    # one method x the bucket ladder; the exact value is pinned in the
    # baseline (asserted above) — here pin the invariant that warmup
    # compiled SOMETHING and the donation check ran or was skipped on cpu
    assert traced.budgets["serving.warmup"] >= 1
    import jax

    if jax.default_backend() == "cpu":
        assert "serving.donation" in traced.skipped


def test_streaming_budgets_traced(traced):
    # the out-of-core fits (data/streaming.py) pin a FIXED program
    # inventory: the tracer runs each family at two shard counts and
    # appends a "streaming" violation if the count grows, so an empty
    # violation list (asserted above) IS the no-new-programs-per-shard
    # contract; here pin that the budgets landed and are shard-free
    assert traced.budgets["gbm_regressor.fit_streaming"] >= 1
    assert traced.budgets["gbm_classifier.fit_streaming"] >= 1
    assert "gbm_regressor.fit_streaming" not in traced.skipped
    assert "gbm_classifier.fit_streaming" not in traced.skipped


def test_operator_budgets_traced(traced):
    # the live operator plane (docs/operator.md) pins TWO zeros: a full
    # scrape (OpenMetrics render + /programz rows + a watchdog tick)
    # dispatches no cached programs, and the watchdog/exporter sources
    # carry no unfenced blocking reads — the empty violation list above
    # IS both contracts; here pin that they traced and landed at zero
    assert traced.budgets["operator.scrape"] == 0
    assert traced.budgets["operator.lint"] == 0


def test_distributed_budget_traced(traced):
    # the pod-scale elastic plane (parallel/elastic.py) pins ONE program
    # inventory across mesh widths AND shard counts: the tracer runs the
    # distributed fit at 2x2 configurations and appends a "distributed"
    # violation on any variation, so the empty violation list above IS
    # the fixed-program-count contract; here pin that it traced at all
    assert traced.budgets["gbm_regressor.fit_streaming_dist"] >= 1
    assert "gbm_regressor.fit_streaming_dist" not in traced.skipped
