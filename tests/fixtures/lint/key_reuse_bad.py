"""Positive fixture: the same key feeds two draws — identical randomness."""

import jax


def draw(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))  # reuses `key`: flagged
    return a + b
