"""Positive fixture: unhashable values in static argument positions."""

import jax


def body(x, cfg):
    return x * len(cfg)


jitted = jax.jit(body, static_argnums=(1,))
out = jitted(1.0, [1, 2, 3])  # list literal in a static slot: flagged

misdeclared = jax.jit(body, static_argnums=("cfg",))  # str in argNUMS: flagged
