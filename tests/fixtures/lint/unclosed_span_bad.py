"""Positive fixture: spans started without a guaranteed end."""


def dropped(telem):
    # bare statement: the returned span is discarded — nothing ends it
    telem.begin_span("round_chunk", chunk_seq=0)


def bound_but_leaky(telem, items):
    sp = telem.begin_span("shard_load")
    for item in items:
        item.process()
    sp.end()  # a plain call can be skipped by any raise above it
    return items


def conditional_end(tracer, ok):
    span = tracer.start_span("serve")
    if ok:
        span.end()
    return ok
