"""Positive fixture: a jitted function closing over module-level mutable
state — jit bakes the trace-time value in."""

import jax

tables = []


@jax.jit
def forward(x):
    return x + len(tables)  # `tables` frozen at trace time: flagged
