"""Negative fixture: keys threaded through split; branch-exclusive draws."""

import jax


def draw(key):
    key, sub = jax.random.split(key)
    a = jax.random.normal(sub, (4,))
    key, sub2 = jax.random.split(key)
    b = jax.random.uniform(sub2, (4,))
    return a + b


def branchy(key, replacement):
    # mutually exclusive draws from the same key are NOT reuse
    if replacement:
        return jax.random.poisson(key, 1.0, (4,))
    return jax.random.bernoulli(key, 0.5, (4,))
