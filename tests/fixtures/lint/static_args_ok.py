"""Negative fixture: hashable static arguments, correctly declared."""

import jax


def body(x, n):
    return x * n


jitted = jax.jit(body, static_argnums=(1,))
out = jitted(1.0, 3)

named = jax.jit(body, static_argnames=("n",))
