"""Negative fixture: reads inside a perf_counter fence or after a telemetry
charge are measured, not hazards."""

import time

import numpy as np


def timed(model, X):
    t0 = time.perf_counter()
    out = np.asarray(model.predict(X))
    wall = time.perf_counter() - t0
    return out, wall


def charged(model, X, telem):
    telem.blocking_read(model.predict(X))
    # arrays were fenced-and-charged above; this conversion cannot block
    return np.asarray(model.predict(X))


def fenced_join(fut, telem):
    # the prefetcher's shard-wait shape (data/prefetch.py): the join is
    # timed and charged to the host-blocked ledger
    t0 = time.perf_counter()
    arr = fut.result()
    telem.host_blocked(time.perf_counter() - t0)
    return arr


def bounded_join(fut):
    # timeout-bounded joins (tools, tests) are outside the rule's scope
    return fut.result(timeout=60)
