"""Positive fixture: explicit float64 on the device path."""

import jax
import jax.numpy as jnp

x = jnp.zeros((4,), dtype=jnp.float64)  # f64 constructor dtype: flagged
y = x.astype("float64")  # f64 astype: flagged
jax.config.update("jax_enable_x64", True)  # global x64 flip: flagged
