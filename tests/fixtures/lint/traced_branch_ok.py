"""Negative fixture: static-attribute and `is None` tests are trace-safe."""

import jax
import jax.numpy as jnp


@jax.jit
def flatten(x, lo):
    if x.ndim > 1:  # .ndim is static at trace time
        x = x.reshape(-1)
    return jnp.minimum(x, lo)


@jax.jit
def add_opt(x, y=None):
    if y is None:  # pytree-structure check, static
        return x
    return x + y
