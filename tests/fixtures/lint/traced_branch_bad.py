"""Positive fixture: Python `if` on a traced argument inside jit."""

import jax


@jax.jit
def clamp(x, lo):
    if x > lo:  # `x` is a tracer here: flagged
        return lo
    return x
