"""Positive fixture: a blocking device read with no timed fence around it."""

import jax


def run(model, X):
    out = model.predict(X)
    return jax.block_until_ready(out)  # unfenced host stall: flagged


def join(futures):
    return [f.result() for f in futures]  # unfenced future join: flagged
