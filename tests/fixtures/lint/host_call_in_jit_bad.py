"""Positive fixture: a wall-clock read inside a jitted function — it runs
once at trace time and becomes a constant."""

import time

import jax


@jax.jit
def step(x):
    t = time.time()  # baked in at trace time: flagged
    return x + t
