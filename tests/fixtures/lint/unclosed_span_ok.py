"""Negative fixture: every span's end is syntactically guaranteed (or
ownership moved to something that ends it)."""


def with_form(telem, items):
    with telem.begin_span("round_chunk", chunk_seq=0):
        for item in items:
            item.process()


def bound_then_entered(telem, work):
    sp = telem.begin_span("serve") if telem else None
    with sp:
        work()


def try_finally(telem, work):
    sp = telem.begin_span("checkpoint_save")
    try:
        work()
    finally:
        sp.end()


def handoff_to_container(telem, pending):
    # the executor's shape: the span rides a tuple whose consumer ends it
    pending.append((telem.begin_span("round_chunk"), object()))


def handoff_to_attribute(telem, req):
    req.span = telem.begin_span("fleet_request")


def factory(tracer):
    # returning transfers ownership to the caller
    return tracer.begin_span("fit")
