"""Negative fixture: f32 device arrays; host-side np.float64 accounting."""

import jax.numpy as jnp
import numpy as np

x = jnp.zeros((4,), dtype=jnp.float32)
y = x.astype(jnp.float32)
acc = np.float64(0.0)  # host accounting, not a device value
