"""Negative fixture: host values resolved OUTSIDE the traced scope."""

import time

import jax
import jax.numpy as jnp


@jax.jit
def step(x):
    return jnp.sin(x)


def timed_apply(fn, x):
    t0 = time.time()  # untraced caller: fine
    out = jax.jit(fn)(x)
    return out, time.time() - t0
