"""Negative fixture: constants and the default-arg capture-by-value idiom."""

import jax

SIZES = (4, 8)  # UPPER + immutable: a deliberate constant


@jax.jit
def forward(x):
    return x + len(SIZES)


def outer(tables):
    # `tables=tables` evaluates at def time: capture by VALUE, not closure
    @jax.jit
    def inner(x, tables=tables):
        return x + len(tables)

    tables = None  # rebinding the outer name cannot affect `inner`
    return inner
