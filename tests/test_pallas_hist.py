"""Pallas level-histogram tier (`ops/pallas_hist.py`, hist_precision=
"pallas"): parity with the exact matmul tier on shapes where the 2-pass
hi/lo split is exact, metric-level agreement elsewhere, and the static
VMEM-budget fallback.  Off-TPU the kernel runs in interpreter mode, so
every shape here is tiny."""

import jax
import jax.numpy as jnp
import numpy as np

import spark_ensemble_tpu as se
from spark_ensemble_tpu.ops.binning import bin_features, compute_bins
from spark_ensemble_tpu.ops.pallas_hist import hist_level_pallas
from spark_ensemble_tpu.ops.tree import fit_forest


def _binned(rng, n, d, B):
    X = rng.randn(n, d).astype(np.float32)
    bins = compute_bins(jnp.asarray(X), B)
    return bin_features(jnp.asarray(X), bins), bins


def test_kernel_matches_dense_reference():
    """Histogram parity against a dense numpy reference, on value channels
    whose hi/lo bf16 split is exact (small dyadic rationals)."""
    rng = np.random.RandomState(0)
    n, d, M, C, n_nodes, B = 500, 4, 3, 2, 4, 8
    Xb, _ = _binned(rng, n, d, B)
    node = rng.randint(0, n_nodes, size=(n, M)).astype(np.int32)
    vals = (rng.randint(-8, 9, size=(n, M, C)) / 4.0).astype(np.float32)

    H = np.asarray(
        hist_level_pallas(
            Xb, jnp.asarray(node), jnp.asarray(vals),
            n_nodes=n_nodes, max_bins=B,
        )
    )
    Xb_np = np.asarray(Xb)
    ref = np.zeros((M, n_nodes, C, d, B), np.float32)
    for i in range(n):
        for m in range(M):
            for f in range(d):
                ref[m, node[i, m], :, f, Xb_np[i, f]] += vals[i, m]
    np.testing.assert_allclose(H, ref, rtol=0, atol=1e-5)


def test_padding_rows_contribute_nothing():
    """n not a multiple of the block size: the kernel pads internally with
    zero value channels, which must not perturb any bin."""
    rng = np.random.RandomState(1)
    n, d, M, C, B = 277, 3, 2, 2, 8  # prime n -> guaranteed padding
    Xb, _ = _binned(rng, n, d, B)
    node = rng.randint(0, 2, size=(n, M)).astype(np.int32)
    vals = rng.randn(n, M, C).astype(np.float32)
    H = np.asarray(
        hist_level_pallas(Xb, jnp.asarray(node), jnp.asarray(vals),
                          n_nodes=2, max_bins=B)
    )
    # total weight per member must equal the sum over the REAL rows
    # (H[:, :, 0] is [M, nodes, d, B]; each row lands in one bin PER
    # feature, so the grand total counts every row d times)
    np.testing.assert_allclose(
        H[:, :, 0].sum(axis=(1, 2, 3)) / d, vals[:, :, 0].sum(axis=0),
        rtol=1e-4,
    )


def test_forest_fit_parity_with_exact_tier():
    """Same splits and (f32-exact-input) leaf values as the exact matmul
    tier on dyadic-rational weights/targets."""
    rng = np.random.RandomState(2)
    n, d, M, k, B = 600, 6, 3, 1, 16
    Xb, bins = _binned(rng, n, d, B)
    Y = (rng.randint(-16, 17, size=(n, M, k)) / 8.0).astype(np.float32)
    w = (rng.randint(0, 3, size=(n, M)) / 2.0).astype(np.float32)
    kw = dict(max_depth=3, max_bins=B)
    exact = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), bins.thresholds,
                       hist_precision="highest", hist="matmul", **kw)
    pallas = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), bins.thresholds,
                        hist_precision="pallas", **kw)
    np.testing.assert_array_equal(
        np.asarray(exact.split_feature), np.asarray(pallas.split_feature)
    )
    np.testing.assert_array_equal(
        np.asarray(exact.split_bin), np.asarray(pallas.split_bin)
    )
    np.testing.assert_allclose(
        np.asarray(exact.leaf_value), np.asarray(pallas.leaf_value),
        rtol=1e-4, atol=1e-5,
    )


def test_gbm_with_pallas_tier_metric_parity():
    rng = np.random.RandomState(3)
    X = rng.randn(800, 8).astype(np.float32)
    c = rng.randn(4, 8).astype(np.float32)
    y = np.argmax(X @ c.T, axis=1).astype(np.float32)
    cfg = dict(num_base_learners=3, learning_rate=0.5, seed=0)
    a_hi = float(np.mean(np.asarray(
        se.GBMClassifier(**cfg).fit(X, y).predict(X)) == y))
    a_pl = float(np.mean(np.asarray(
        se.GBMClassifier(
            base_learner=se.DecisionTreeRegressor(hist_precision="pallas"),
            **cfg,
        ).fit(X, y).predict(X)) == y))
    assert abs(a_hi - a_pl) < 0.02, (a_hi, a_pl)


def test_vmem_budget_falls_back_to_matmul(monkeypatch):
    """Configs whose accumulator exceeds the kernel's VMEM budget silently
    take the 'high' matmul tier instead (static-shape decision)."""
    import spark_ensemble_tpu.ops.pallas_hist as ph

    monkeypatch.setattr(ph, "_VMEM_BUDGET", 1)
    rng = np.random.RandomState(4)
    n, d, M, k, B = 300, 4, 2, 1, 8
    Xb, bins = _binned(rng, n, d, B)
    Y = rng.randn(n, M, k).astype(np.float32)
    w = np.ones((n, M), np.float32)
    # must run (via the matmul fallback) and produce a sane forest
    f = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), bins.thresholds,
                   hist_precision="pallas", max_depth=3, max_bins=B)
    assert np.isfinite(np.asarray(f.leaf_value)).all()


def test_off_tpu_large_n_falls_back_to_high_tier(monkeypatch):
    """Off-TPU above _INTERPRET_MAX_ROWS the tier must warn and take the
    'high' matmul path instead of dispatching the (effectively hanging)
    interpreted kernel (advisor r4).  Pinning the threshold low keeps the
    test tiny while exercising the real guard."""
    import warnings as _warnings

    import spark_ensemble_tpu.ops.pallas_hist as ph

    monkeypatch.setattr(ph, "_INTERPRET_MAX_ROWS", 100)
    # force the off-TPU decision so the test is backend-independent
    # (tree.py imports _interpret at call time, so the patch is seen)
    monkeypatch.setattr(ph, "_interpret", lambda: True)
    rng = np.random.RandomState(5)
    # shapes distinct from every other test in this file: the guard (and
    # its warning) runs at TRACE time, so a shape collision would reuse a
    # cached program and skip it
    n, d, M, k, B = 310, 5, 2, 1, 8
    Xb, bins = _binned(rng, n, d, B)
    Y = rng.randn(n, M, k).astype(np.float32)
    w = np.ones((n, M), np.float32)
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter("always")
        f = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), bins.thresholds,
                       hist_precision="pallas", max_depth=3, max_bins=B)
    assert any("falling back to the 'high'" in str(r.message) for r in rec)
    hi = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), bins.thresholds,
                    hist_precision="high", hist="matmul",
                    max_depth=3, max_bins=B)
    np.testing.assert_array_equal(
        np.asarray(f.split_feature), np.asarray(hi.split_feature)
    )
    np.testing.assert_allclose(
        np.asarray(f.leaf_value), np.asarray(hi.leaf_value),
        rtol=1e-5, atol=1e-6,
    )


def test_kernel_lowers_for_tpu(monkeypatch):
    """Cross-platform export: the REAL (non-interpret) kernel must lower
    through Mosaic for the TPU target at the benchmark shapes — the only
    TPU-compilation check a chipless CI can run."""
    from jax import export

    import spark_ensemble_tpu.ops.pallas_hist as ph

    monkeypatch.setattr(ph, "_interpret", lambda: False)
    for n, d, M, C, n_nodes, B in (
        (15000, 16, 26, 2, 16, 64),  # letter headline, deepest level
        (1024, 8, 4, 2, 1, 16),  # level 0
    ):
        # the inner impl is the jit-wrapped function export needs; the
        # public wrapper resolves the (tunable) block size at trace time
        exp = export.export(ph._hist_level_pallas, platforms=("tpu",))(
            jnp.zeros((n, d), jnp.int32),
            jnp.zeros((n, M), jnp.int32),
            jnp.zeros((n, M, C), jnp.float32),
            n_nodes=n_nodes,
            max_bins=B,
            blk=ph.block_rows(),
        )
        assert "tpu_custom_call" in exp.mlir_module()
    # the monkeypatched interpret=False decision is baked into the jit
    # trace cache (its key ignores it); drop those traces so later tests
    # with colliding shapes cannot dispatch a Mosaic kernel on CPU
    jax.clear_caches()


def test_pallas_persists_and_validates():
    est = se.DecisionTreeRegressor(hist_precision="pallas")
    assert est.hist_precision == "pallas"
    try:
        se.DecisionTreeRegressor(hist_precision="nope")
        raise AssertionError("validator must reject unknown tiers")
    except ValueError:
        pass
