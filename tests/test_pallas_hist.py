"""Pallas level-histogram tier (`ops/pallas_hist.py`, hist_precision=
"pallas"): parity with the exact matmul tier on shapes where the 2-pass
hi/lo split is exact, metric-level agreement elsewhere, and the static
VMEM-budget fallback.  Off-TPU the kernel runs in interpreter mode, so
every shape here is tiny."""

import jax
import jax.numpy as jnp
import numpy as np

import spark_ensemble_tpu as se
from spark_ensemble_tpu.ops.binning import bin_features, compute_bins
from spark_ensemble_tpu.ops.pallas_hist import hist_level_pallas
from spark_ensemble_tpu.ops.tree import fit_forest


def _binned(rng, n, d, B):
    X = rng.randn(n, d).astype(np.float32)
    bins = compute_bins(jnp.asarray(X), B)
    return bin_features(jnp.asarray(X), bins), bins


def test_kernel_matches_dense_reference():
    """Histogram parity against a dense numpy reference, on value channels
    whose hi/lo bf16 split is exact (small dyadic rationals)."""
    rng = np.random.RandomState(0)
    n, d, M, C, n_nodes, B = 500, 4, 3, 2, 4, 8
    Xb, _ = _binned(rng, n, d, B)
    node = rng.randint(0, n_nodes, size=(n, M)).astype(np.int32)
    vals = (rng.randint(-8, 9, size=(n, M, C)) / 4.0).astype(np.float32)

    H = np.asarray(
        hist_level_pallas(
            Xb, jnp.asarray(node), jnp.asarray(vals),
            n_nodes=n_nodes, max_bins=B,
        )
    )
    Xb_np = np.asarray(Xb)
    ref = np.zeros((M, n_nodes, C, d, B), np.float32)
    for i in range(n):
        for m in range(M):
            for f in range(d):
                ref[m, node[i, m], :, f, Xb_np[i, f]] += vals[i, m]
    np.testing.assert_allclose(H, ref, rtol=0, atol=1e-5)


def test_padding_rows_contribute_nothing():
    """n not a multiple of the block size: the kernel pads internally with
    zero value channels, which must not perturb any bin."""
    rng = np.random.RandomState(1)
    n, d, M, C, B = 277, 3, 2, 2, 8  # prime n -> guaranteed padding
    Xb, _ = _binned(rng, n, d, B)
    node = rng.randint(0, 2, size=(n, M)).astype(np.int32)
    vals = rng.randn(n, M, C).astype(np.float32)
    H = np.asarray(
        hist_level_pallas(Xb, jnp.asarray(node), jnp.asarray(vals),
                          n_nodes=2, max_bins=B)
    )
    # total weight per member must equal the sum over the REAL rows
    # (H[:, :, 0] is [M, nodes, d, B]; each row lands in one bin PER
    # feature, so the grand total counts every row d times)
    np.testing.assert_allclose(
        H[:, :, 0].sum(axis=(1, 2, 3)) / d, vals[:, :, 0].sum(axis=0),
        rtol=1e-4,
    )


def test_forest_fit_parity_with_exact_tier():
    """Same splits and (f32-exact-input) leaf values as the exact matmul
    tier on dyadic-rational weights/targets."""
    rng = np.random.RandomState(2)
    n, d, M, k, B = 600, 6, 3, 1, 16
    Xb, bins = _binned(rng, n, d, B)
    Y = (rng.randint(-16, 17, size=(n, M, k)) / 8.0).astype(np.float32)
    w = (rng.randint(0, 3, size=(n, M)) / 2.0).astype(np.float32)
    kw = dict(max_depth=3, max_bins=B)
    exact = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), bins.thresholds,
                       hist_precision="highest", hist="matmul", **kw)
    pallas = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), bins.thresholds,
                        hist_precision="pallas", **kw)
    np.testing.assert_array_equal(
        np.asarray(exact.split_feature), np.asarray(pallas.split_feature)
    )
    np.testing.assert_array_equal(
        np.asarray(exact.split_bin), np.asarray(pallas.split_bin)
    )
    np.testing.assert_allclose(
        np.asarray(exact.leaf_value), np.asarray(pallas.leaf_value),
        rtol=1e-4, atol=1e-5,
    )


def test_gbm_with_pallas_tier_metric_parity():
    rng = np.random.RandomState(3)
    X = rng.randn(800, 8).astype(np.float32)
    c = rng.randn(4, 8).astype(np.float32)
    y = np.argmax(X @ c.T, axis=1).astype(np.float32)
    cfg = dict(num_base_learners=3, learning_rate=0.5, seed=0)
    a_hi = float(np.mean(np.asarray(
        se.GBMClassifier(**cfg).fit(X, y).predict(X)) == y))
    a_pl = float(np.mean(np.asarray(
        se.GBMClassifier(
            base_learner=se.DecisionTreeRegressor(hist_precision="pallas"),
            **cfg,
        ).fit(X, y).predict(X)) == y))
    assert abs(a_hi - a_pl) < 0.02, (a_hi, a_pl)


def test_vmem_budget_falls_back_to_matmul(monkeypatch):
    """Configs whose accumulator exceeds the kernel's VMEM budget silently
    take the 'high' matmul tier instead (static-shape decision)."""
    import spark_ensemble_tpu.ops.pallas_hist as ph

    monkeypatch.setattr(ph, "_VMEM_BUDGET", 1)
    rng = np.random.RandomState(4)
    n, d, M, k, B = 300, 4, 2, 1, 8
    Xb, bins = _binned(rng, n, d, B)
    Y = rng.randn(n, M, k).astype(np.float32)
    w = np.ones((n, M), np.float32)
    # must run (via the matmul fallback) and produce a sane forest
    f = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), bins.thresholds,
                   hist_precision="pallas", max_depth=3, max_bins=B)
    assert np.isfinite(np.asarray(f.leaf_value)).all()


def test_off_tpu_large_n_falls_back_to_high_tier(monkeypatch):
    """Off-TPU above _INTERPRET_MAX_ROWS the tier must warn and take the
    'high' matmul path instead of dispatching the (effectively hanging)
    interpreted kernel (advisor r4).  Pinning the threshold low keeps the
    test tiny while exercising the real guard."""
    import warnings as _warnings

    import spark_ensemble_tpu.ops.pallas_hist as ph

    monkeypatch.setattr(ph, "_INTERPRET_MAX_ROWS", 100)
    # force the off-TPU decision so the test is backend-independent
    # (tree.py imports _interpret at call time, so the patch is seen)
    monkeypatch.setattr(ph, "_interpret", lambda: True)
    rng = np.random.RandomState(5)
    # shapes distinct from every other test in this file: the guard (and
    # its warning) runs at TRACE time, so a shape collision would reuse a
    # cached program and skip it
    n, d, M, k, B = 310, 5, 2, 1, 8
    Xb, bins = _binned(rng, n, d, B)
    Y = rng.randn(n, M, k).astype(np.float32)
    w = np.ones((n, M), np.float32)
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter("always")
        f = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), bins.thresholds,
                       hist_precision="pallas", max_depth=3, max_bins=B)
    assert any("falling back to the 'high'" in str(r.message) for r in rec)
    hi = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), bins.thresholds,
                    hist_precision="high", hist="matmul",
                    max_depth=3, max_bins=B)
    np.testing.assert_array_equal(
        np.asarray(f.split_feature), np.asarray(hi.split_feature)
    )
    np.testing.assert_allclose(
        np.asarray(f.leaf_value), np.asarray(hi.leaf_value),
        rtol=1e-5, atol=1e-6,
    )


def test_kernel_lowers_for_tpu(monkeypatch):
    """Cross-platform export: the REAL (non-interpret) kernel must lower
    through Mosaic for the TPU target at the benchmark shapes — the only
    TPU-compilation check a chipless CI can run."""
    from jax import export

    import spark_ensemble_tpu.ops.pallas_hist as ph

    monkeypatch.setattr(ph, "_interpret", lambda: False)
    for n, d, M, C, n_nodes, B in (
        (15000, 16, 26, 2, 16, 64),  # letter headline, deepest level
        (1024, 8, 4, 2, 1, 16),  # level 0
    ):
        # the inner impl is the jit-wrapped function export needs; the
        # public wrapper resolves the (tunable) block size at trace time
        exp = export.export(ph._hist_level_pallas, platforms=("tpu",))(
            jnp.zeros((n, d), jnp.int32),
            jnp.zeros((n, M), jnp.int32),
            jnp.zeros((n, M, C), jnp.float32),
            n_nodes=n_nodes,
            max_bins=B,
            blk=ph.block_rows(),
        )
        assert "tpu_custom_call" in exp.mlir_module()
    # the monkeypatched interpret=False decision is baked into the jit
    # trace cache (its key ignores it); drop those traces so later tests
    # with colliding shapes cannot dispatch a Mosaic kernel on CPU
    jax.clear_caches()


def test_pallas_persists_and_validates():
    est = se.DecisionTreeRegressor(hist_precision="pallas")
    assert est.hist_precision == "pallas"
    try:
        se.DecisionTreeRegressor(hist_precision="nope")
        raise AssertionError("validator must reject unknown tiers")
    except ValueError:
        pass


# -- fused round kernel (hist="fused"): bit-packed bins, in-kernel routing --


def _fused_forest(Xb, Y, w, thresholds, **kw):
    return fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), thresholds,
                      hist="fused", **kw)


def test_pack_unpack_roundtrip():
    """pack_bins/unpack_bins are exact inverses for every lane width and
    for feature counts that do and do not fill the last word."""
    from spark_ensemble_tpu.ops.binning import (
        pack_bins, pack_width, unpack_bins,
    )

    rng = np.random.RandomState(10)
    for B, want_bits in ((12, 4), (16, 4), (200, 8), (256, 8), (500, 32)):
        assert pack_width(B) == want_bits
        for d in (1, 7, 8, 16, 17):
            Xb = rng.randint(0, B, size=(53, d)).astype(np.int32)
            cb = pack_bins(jnp.asarray(Xb), B, want_bits)
            assert cb.bits == want_bits
            np.testing.assert_array_equal(np.asarray(unpack_bins(cb)), Xb)


def test_fused_kernel_matches_dense_reference_edge_shapes():
    """Unrouted level histogram parity against a dense numpy reference at
    the edge shapes: n not a multiple of the block size (prime), a
    non-power-of-two bin count, M=1, and zero-weight padding rows."""
    from spark_ensemble_tpu.ops.binning import pack_bins, pack_width
    from spark_ensemble_tpu.ops.pallas_hist import fused_round_level

    rng = np.random.RandomState(11)
    for n, d, M, C, n_nodes, B in (
        (263, 5, 3, 2, 4, 8),  # prime n -> internal padding
        (96, 4, 2, 2, 2, 12),  # non-power-of-two bins
        (64, 3, 1, 3, 4, 16),  # M=1
    ):
        bits = pack_width(B)
        Xb = rng.randint(0, B, size=(n, d)).astype(np.int32)
        node = rng.randint(0, n_nodes, size=(n, M)).astype(np.int32)
        vals = (rng.randint(-8, 9, size=(n, M, C)) / 4.0).astype(np.float32)
        vals[: n // 4] = 0.0  # zero-weight rows must contribute exactly 0
        cb = pack_bins(jnp.asarray(Xb), B, bits)
        H, node_out = fused_round_level(
            cb.packed, jnp.asarray(node), jnp.asarray(vals),
            n_nodes=n_nodes, max_bins=B, bits=bits, num_features=d,
        )
        ref = np.zeros((M, n_nodes, C, d, B), np.float32)
        for i in range(n):
            for m in range(M):
                for f in range(d):
                    ref[m, node[i, m], :, f, Xb[i, f]] += vals[i, m]
        np.testing.assert_allclose(np.asarray(H), ref, rtol=0, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(node_out), node)


def test_fused_routing_matches_route_members():
    """Deferred in-kernel routing is bit-identical to `_route_members`."""
    from spark_ensemble_tpu.ops.binning import pack_bins, pack_width
    from spark_ensemble_tpu.ops.pallas_hist import fused_round_level
    from spark_ensemble_tpu.ops.tree import _route_members, _routing_precision

    rng = np.random.RandomState(12)
    n, d, M, C, B = 301, 6, 3, 2, 16
    half, n_nodes = 4, 8
    bits = pack_width(B)
    Xb = rng.randint(0, B, size=(n, d)).astype(np.int32)
    prev = rng.randint(0, half, size=(n, M)).astype(np.int32)
    vals = rng.randn(n, M, C).astype(np.float32)
    bf = rng.randint(0, d, size=(M, half)).astype(np.int32)
    bt = rng.randint(0, B, size=(M, half)).astype(np.int32)
    cb = pack_bins(jnp.asarray(Xb), B, bits)
    _, node_out = fused_round_level(
        cb.packed, jnp.asarray(prev), jnp.asarray(vals),
        jnp.asarray(bf), jnp.asarray(bt),
        n_nodes=n_nodes, max_bins=B, bits=bits, num_features=d,
    )
    ref = _route_members(
        jnp.asarray(Xb), jnp.asarray(prev), jnp.asarray(bf),
        jnp.asarray(bt), half, _routing_precision(B),
    )
    np.testing.assert_array_equal(np.asarray(node_out), np.asarray(ref))


def test_fused_forest_parity_with_scatter_tier():
    """Same splits as the exact scatter tier on dyadic-rational inputs
    (the fused kernel's hi/lo statistics are exact there), leaf values
    allclose."""
    rng = np.random.RandomState(13)
    n, d, M, k, B = 640, 6, 3, 1, 16
    Xb, bins = _binned(rng, n, d, B)
    Y = (rng.randint(-16, 17, size=(n, M, k)) / 8.0).astype(np.float32)
    w = (rng.randint(0, 3, size=(n, M)) / 2.0).astype(np.float32)
    kw = dict(max_depth=3, max_bins=B)
    exact = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), bins.thresholds,
                       hist="scatter", **kw)
    fused = _fused_forest(Xb, Y, w, bins.thresholds, **kw)
    np.testing.assert_array_equal(
        np.asarray(exact.split_feature), np.asarray(fused.split_feature)
    )
    np.testing.assert_array_equal(
        np.asarray(exact.split_bin), np.asarray(fused.split_bin)
    )
    np.testing.assert_allclose(
        np.asarray(exact.leaf_value), np.asarray(fused.leaf_value),
        rtol=1e-4, atol=1e-5,
    )


def test_fused_forest_return_leaf_ids():
    """return_leaf must hand back the same leaf ids as the matmul tier —
    the GBM leaf-id-reuse path depends on it."""
    rng = np.random.RandomState(14)
    n, d, M, k, B = 420, 5, 2, 1, 16
    Xb, bins = _binned(rng, n, d, B)
    Y = (rng.randint(-8, 9, size=(n, M, k)) / 4.0).astype(np.float32)
    w = np.ones((n, M), np.float32)
    kw = dict(max_depth=3, max_bins=B, return_leaf=True)
    exact, node_e = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w),
                               bins.thresholds, hist="matmul", **kw)
    fused, node_f = _fused_forest(Xb, Y, w, bins.thresholds, **kw)
    np.testing.assert_array_equal(
        np.asarray(exact.split_feature), np.asarray(fused.split_feature)
    )
    np.testing.assert_array_equal(np.asarray(node_e), np.asarray(node_f))


def test_fused_gbm_letter_leg_parity():
    """The acceptance pin (docs/fused_kernel.md precision contract): a GBM
    classifier fit with hist='fused' stays tight-allclose to hist='matmul'
    on the letter-leg workload shape — probabilities within 1e-3, train
    accuracy within 0.02.  The kernel's 3-term bf16 statistic split is
    f32-grade (~24-bit mantissa), so split choices match the dense tier
    up to genuine f32 ties and probabilities track to ~1e-4 even after
    boosting rounds compound."""
    rng = np.random.RandomState(15)
    X = rng.randn(800, 8).astype(np.float32)
    c = rng.randn(4, 8).astype(np.float32)
    y = np.argmax(X @ c.T, axis=1).astype(np.float32)
    cfg = dict(num_base_learners=3, learning_rate=0.5, seed=0)

    def run(tier):
        m = se.GBMClassifier(
            base_learner=se.DecisionTreeRegressor(hist=tier, max_bins=16),
            **cfg,
        ).fit(X, y)
        return (
            np.asarray(m.predict_proba(X)),
            float(np.mean(np.asarray(m.predict(X)) == y)),
        )

    p_mat, a_mat = run("matmul")
    p_fus, a_fus = run("fused")
    np.testing.assert_allclose(p_fus, p_mat, atol=1e-3)
    assert abs(a_fus - a_mat) < 0.02, (a_fus, a_mat)


def test_fused_vmem_budget_falls_back_with_warning(monkeypatch):
    """Over the VMEM budget the tier must warn and take the auto fallback
    (matmul here), producing the fallback tier's exact forest."""
    import warnings as _warnings

    import spark_ensemble_tpu.ops.pallas_hist as ph

    monkeypatch.setattr(ph, "_FUSED_VMEM_BUDGET", 1)
    rng = np.random.RandomState(16)
    n, d, M, k, B = 330, 4, 2, 1, 8  # shapes unique in this file (trace cache)
    Xb, bins = _binned(rng, n, d, B)
    Y = (rng.randint(-8, 9, size=(n, M, k)) / 4.0).astype(np.float32)
    w = np.ones((n, M), np.float32)
    kw = dict(max_depth=3, max_bins=B)
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter("always")
        f = _fused_forest(Xb, Y, w, bins.thresholds, **kw)
    assert any("hist='fused' falling back" in str(r.message) for r in rec)
    ref = fit_forest(Xb, jnp.asarray(Y), jnp.asarray(w), bins.thresholds,
                     hist=se.ops.tree._auto_hist_heuristic(n, d, B), **kw)
    np.testing.assert_array_equal(
        np.asarray(f.split_feature), np.asarray(ref.split_feature)
    )
    np.testing.assert_allclose(
        np.asarray(f.leaf_value), np.asarray(ref.leaf_value), rtol=1e-6
    )


def test_fused_off_tpu_large_n_falls_back(monkeypatch):
    """Off-TPU past _INTERPRET_MAX_ROWS the fused tier must warn and fall
    back instead of dispatching the interpreted kernel at scale."""
    import warnings as _warnings

    import spark_ensemble_tpu.ops.pallas_hist as ph

    monkeypatch.setattr(ph, "_INTERPRET_MAX_ROWS", 100)
    monkeypatch.setattr(ph, "_interpret", lambda: True)
    rng = np.random.RandomState(17)
    n, d, M, k, B = 350, 5, 2, 1, 8  # unique shapes (see above)
    Xb, bins = _binned(rng, n, d, B)
    Y = rng.randn(n, M, k).astype(np.float32)
    w = np.ones((n, M), np.float32)
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter("always")
        f = _fused_forest(Xb, Y, w, bins.thresholds, max_depth=3, max_bins=B)
    assert any("hist='fused' falling back" in str(r.message) for r in rec)
    assert np.isfinite(np.asarray(f.leaf_value)).all()


def test_fused_max_bins_over_256_falls_back():
    """B > 256 is outside the packable range AND the routing exactness
    proof; the tier must resolve away from fused."""
    from spark_ensemble_tpu.ops.tree import _resolve_hist

    assert _resolve_hist("fused", 1000, 4, 300, warn=False) != "fused"
    assert _resolve_hist("fused", 1000, 4, 256, warn=False) == "fused"


def test_auto_resolution_never_picks_fused():
    """Bit-identity contract: with autotune off and hist unset, resolution
    is exactly the pre-fused heuristic — 'auto' never lands on the fused
    tier unless a measured winner says so."""
    from spark_ensemble_tpu import autotune as at
    from spark_ensemble_tpu.ops.tree import _resolve_hist

    with at.override(mode="off"):
        for n in (100, 10_000, 5_000_000):
            assert _resolve_hist("auto", n, 16, 64, warn=False) != "fused"


def test_fused_kernel_lowers_for_tpu(monkeypatch):
    """The REAL (non-interpret) fused kernel must lower through Mosaic for
    the TPU target at the benchmark shapes — routed level + leaf pass."""
    from jax import export

    import spark_ensemble_tpu.ops.pallas_hist as ph

    monkeypatch.setattr(ph, "_interpret", lambda: False)
    n, d, M, C, B = 15000, 16, 26, 2, 16
    bits = 4
    W = -(-d // (32 // bits))
    for n_nodes, half, leaf in ((16, 8, False), (32, 16, True)):
        exp = export.export(ph._fused_round_level, platforms=("tpu",))(
            jnp.zeros((n, W), jnp.uint32),
            jnp.zeros((n, M), jnp.int32),
            jnp.zeros((n, M, C), jnp.float32),
            jnp.zeros((M, half), jnp.int32),
            jnp.zeros((M, half), jnp.int32),
            n_nodes=n_nodes, max_bins=B, bits=bits, num_features=d,
            leaf=leaf, route=True, blk=ph.fused_block_rows(),
        )
        assert "tpu_custom_call" in exp.mlir_module()
    # see test_kernel_lowers_for_tpu: drop the interpret=False traces
    jax.clear_caches()
