"""Test harness: virtual 8-device CPU mesh, dataset fixtures.

The reference tests "distributed" behavior with local-mode Spark
(``local[*]``, e.g. `GBMClassifierSuite.scala:33-45`); we do the equivalent
with 8 virtual XLA CPU devices so sharding/collective paths are exercised
without TPU hardware.  Env vars must be set before jax is imported.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# a site hook may have force-registered an accelerator plugin before this
# conftest ran; pin the platform explicitly so tests always run on the
# 8-device virtual CPU mesh
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from spark_ensemble_tpu.utils import datasets as ds


@pytest.fixture(scope="session")
def data_mesh8():
    """A plain 8-device ("data",) mesh over the virtual CPU devices."""
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()).reshape(8), ("data",))


@pytest.fixture(autouse=True, scope="module")
def _bound_compiled_program_accumulation():
    """Free compiled XLA executables between test modules.

    A full single-process run of this suite compiles hundreds of programs
    (including the large scan-chunked round loops); on this jax/jaxlib
    (0.9.0) the CPU backend segfaults inside `backend_compile_and_load`
    after ~130 tests' worth of accumulated executables — reproducibly, at
    whichever compile happens to run late in the suite, with RSS only a few
    GB (an XLA-internal resource limit, not host OOM).  Dropping the
    process-wide program cache and jax's compiled-function caches at module
    boundaries keeps the live-executable population bounded and the full
    suite green; per-module reuse (the hot path) is unaffected.
    """
    yield
    from spark_ensemble_tpu.models.base import _PROGRAM_CACHE

    _PROGRAM_CACHE.clear()
    jax.clear_caches()


def _synthetic_regression(n=2000, d=12, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (
        2.0 * X[:, 0]
        + np.sin(3.0 * X[:, 1])
        + X[:, 2] * X[:, 3]
        + 0.1 * rng.randn(n)
    ).astype(np.float32)
    return X, y


def _synthetic_multiclass(n=2000, d=10, k=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    centers = rng.randn(k, d).astype(np.float32) * 2.0
    logits = X @ centers.T + 0.5 * rng.randn(n, k)
    y = np.argmax(logits, axis=1).astype(np.float32)
    return X, y


def _subsample(X, y, n, seed=0):
    idx = np.random.RandomState(seed).permutation(X.shape[0])[:n]
    return X[idx], y[idx]


@pytest.fixture(scope="session")
def cpusmall():
    """Regression dataset (reference `data/cpusmall`), full 8191 rows."""
    if ds.has_reference_data():
        return ds.load_dataset("cpusmall")
    return _synthetic_regression()


@pytest.fixture(scope="session")
def letter():
    """26-class dataset (reference `data/letter`), subsampled for CPU CI."""
    if ds.has_reference_data():
        X, y = ds.load_dataset("letter")
        return _subsample(X, y, 4000)
    return _synthetic_multiclass(k=8)


@pytest.fixture(scope="session")
def letter_full():
    """Full 15k-row letter, for tests whose statistics need the full data
    (SAMME vs SAMME.R needs mixed depth-10 leaves)."""
    if ds.has_reference_data():
        return ds.load_dataset("letter")
    return _synthetic_multiclass(n=8000, k=8)


@pytest.fixture(scope="session")
def adult_full():
    """Full 32.5k-row adult; newton-update GBM statistics need full-size
    leaves (subsampled runs overfit the huge -g/h residuals)."""
    if ds.has_reference_data():
        return ds.load_dataset("adult")
    X, y = _synthetic_multiclass(k=2)
    return X, y


@pytest.fixture(scope="session")
def adult():
    """Binary dataset (reference `data/adult`), subsampled for CPU CI."""
    if ds.has_reference_data():
        X, y = ds.load_dataset("adult")
        return _subsample(X, y, 8000)
    X, y = _synthetic_multiclass(k=2)
    return X, y


def split(X, y, seed=0, test_fraction=0.3):
    return ds.train_test_split(X, y, test_fraction=test_fraction, seed=seed)


def accuracy(pred, y):
    return float(np.mean(np.asarray(pred) == np.asarray(y)))


def rmse(pred, y):
    return float(np.sqrt(np.mean((np.asarray(pred) - np.asarray(y)) ** 2)))
