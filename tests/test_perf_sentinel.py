"""Perf-regression sentinel tests (tools/perf_sentinel.py;
docs/tracing.md#perf-sentinel): direction-aware noise floors, the
platform-mismatch and missing-metric skip rules, baseline updates, and
the CLI exit codes CI gates on — including the ISSUE-pinned pair: a
synthetic regressed record FAILS while the repo's real newest bench
record PASSES against the committed ``PERF_BASELINE.json``."""

import importlib.util
import json
import os

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "perf_sentinel", os.path.join(_ROOT, "tools", "perf_sentinel.py")
)
sentinel = importlib.util.module_from_spec(spec)
spec.loader.exec_module(sentinel)


BASE = {
    "platform": "cpu",
    "value": 10.0,
    "fit_seconds": 5.0,
    "predict_rows_per_sec": 10_000.0,
    "serving_p99_ms": 8.0,
    "compiles_since_warmup": 0,
    "trace_overhead_pct": 0.2,
}


def _names(rows):
    return {r["metric"] for r in rows}


# ---------------------------------------------------------------------------
# compare(): direction + floors
# ---------------------------------------------------------------------------


def test_identical_record_is_clean():
    v = sentinel.compare(BASE, dict(BASE))
    assert v["regressions"] == []
    assert _names(v["ok"]) == set(BASE) - {"platform"}


def test_higher_is_better_regression_fires():
    bench = dict(BASE, value=5.0)  # half the throughput: way past 10%
    v = sentinel.compare(BASE, bench)
    assert _names(v["regressions"]) == {"value"}
    (row,) = v["regressions"]
    assert row["direction"] == "higher"
    assert row["worse_by"] == pytest.approx(5.0)


def test_lower_is_better_regression_fires():
    bench = dict(BASE, fit_seconds=8.0, serving_p99_ms=30.0)
    v = sentinel.compare(BASE, bench)
    assert _names(v["regressions"]) == {"fit_seconds", "serving_p99_ms"}


def test_noise_floor_absorbs_jitter():
    # value -5% (floor 10%), fit_seconds +0.3s (abs floor 0.5s),
    # p99 +0.5ms (abs floor 1.0ms): all inside the floors
    bench = dict(
        BASE, value=9.5, fit_seconds=5.3, serving_p99_ms=8.5,
    )
    v = sentinel.compare(BASE, bench)
    assert v["regressions"] == []


def test_improvements_never_fail():
    bench = dict(
        BASE, value=20.0, fit_seconds=1.0, serving_p99_ms=2.0,
        trace_overhead_pct=0.0,
    )
    assert sentinel.compare(BASE, bench)["regressions"] == []


def test_zero_compile_contract_has_no_floor():
    # compiles_since_warmup pins EXACTLY zero: one steady-state compile
    # is a regression, not jitter
    v = sentinel.compare(BASE, dict(BASE, compiles_since_warmup=1))
    assert _names(v["regressions"]) == {"compiles_since_warmup"}


def test_missing_metric_skips_with_note():
    bench = {"platform": "cpu", "value": 10.0}
    v = sentinel.compare(BASE, bench)
    assert v["regressions"] == []
    assert _names(v["ok"]) == {"value"}
    assert _names(v["skipped"]) == set(BASE) - {"platform", "value"}
    assert all("absent" in r["note"] for r in v["skipped"])


def test_non_numeric_metric_skips():
    v = sentinel.compare(BASE, dict(BASE, value="NaN-ish"))
    assert v["regressions"] == []
    assert "value" in _names(v["skipped"])


def test_platform_mismatch_skips_everything():
    bench = dict(BASE, platform="tpu", value=0.001)  # terrible, but...
    v = sentinel.compare(BASE, bench)
    assert v["regressions"] == [] and v["ok"] == []
    (row,) = v["skipped"]
    assert row["metric"] == "*" and "platform_mismatch" in row["note"]


def test_unpinned_baseline_metric_is_ignored():
    base = {"platform": "cpu", "value": 10.0}  # only one metric pinned
    v = sentinel.compare(base, dict(BASE, fit_seconds=500.0))
    assert v["regressions"] == []
    assert _names(v["ok"]) == {"value"}


# ---------------------------------------------------------------------------
# payload loading + baseline update
# ---------------------------------------------------------------------------


def test_load_bench_unwraps_driver_parsed_wrapper(tmp_path):
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps({"round": 1, "parsed": dict(BASE)}))
    assert sentinel.load_bench(str(p)) == BASE
    raw = tmp_path / "raw.json"
    raw.write_text(json.dumps(BASE))
    assert sentinel.load_bench(str(raw)) == BASE


def test_newest_bench_sorts_by_round(tmp_path):
    for r in (3, 11, 7):
        (tmp_path / f"BENCH_r{r:02d}.json").write_text("{}")
    assert sentinel.newest_bench(str(tmp_path)).endswith("BENCH_r11.json")
    assert sentinel.newest_bench(str(tmp_path / "empty")) is None


def test_update_baseline_writes_compared_metrics_only(tmp_path):
    path = str(tmp_path / "PERF_BASELINE.json")
    bench = dict(BASE, device="TFRT_CPU_0", error="", extra_junk=1)
    written = sentinel.update_baseline(bench, path)
    on_disk = json.loads(open(path).read())
    assert on_disk == written
    assert set(written) == set(BASE) | {"source"}
    assert written["source"] == "TFRT_CPU_0"
    assert "extra_junk" not in written and "error" not in written


# ---------------------------------------------------------------------------
# CLI exit codes (what CI gates on)
# ---------------------------------------------------------------------------


def _write(tmp_path, name, payload):
    p = tmp_path / name
    p.write_text(json.dumps(payload))
    return str(p)


def test_cli_fails_on_synthetic_regressed_record(tmp_path, capsys):
    baseline = _write(tmp_path, "PERF_BASELINE.json", BASE)
    bench = _write(
        tmp_path, "BENCH_r99.json",
        {"parsed": dict(BASE, value=BASE["value"] * 0.5)},
    )
    rc = sentinel.main(["--bench", bench, "--baseline", baseline])
    assert rc == 1
    captured = capsys.readouterr()
    assert "PERF REGRESSION" in captured.err
    assert "--update-baseline" in captured.err  # the documented escape hatch
    assert _names(json.loads(captured.out)["regressions"]) == {"value"}


def test_cli_passes_on_clean_record(tmp_path, capsys):
    baseline = _write(tmp_path, "PERF_BASELINE.json", BASE)
    bench = _write(tmp_path, "BENCH_r99.json", dict(BASE))
    assert sentinel.main(["--bench", bench, "--baseline", baseline]) == 0
    assert json.loads(capsys.readouterr().out)["regressions"] == []


def test_cli_update_baseline_roundtrip(tmp_path, capsys):
    baseline = str(tmp_path / "PERF_BASELINE.json")
    bench = _write(tmp_path, "BENCH_r99.json", dict(BASE))
    rc = sentinel.main(
        ["--bench", bench, "--baseline", baseline, "--update-baseline"]
    )
    assert rc == 0 and os.path.exists(baseline)
    capsys.readouterr()
    # a fresh baseline from a record compares clean against that record
    assert sentinel.main(["--bench", bench, "--baseline", baseline]) == 0


def test_cli_missing_baseline_or_bench_skips(tmp_path, capsys):
    bench = _write(tmp_path, "BENCH_r99.json", dict(BASE))
    missing = str(tmp_path / "nope.json")
    assert sentinel.main(["--bench", bench, "--baseline", missing]) == 0
    assert "skipped" in json.loads(capsys.readouterr().out)


def test_repo_real_bench_passes_committed_baseline(capsys):
    """The acceptance pair's other half: the repo's own newest bench
    record must compare clean against the committed baseline (CI runs
    exactly this invocation)."""
    newest = sentinel.newest_bench()
    committed = os.path.join(_ROOT, "PERF_BASELINE.json")
    if newest is None or not os.path.exists(committed):
        pytest.skip("no committed bench record / baseline in this checkout")
    assert sentinel.main(["--bench", newest, "--baseline", committed]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["regressions"] == []
    assert verdict["ok"], "baseline and bench share no comparable metric"
