"""Pod-scale elastic training plane tests (docs/distributed.md):
deterministic manifest partitioning, subset-verified store opens, the
PINNED bit-identity of distributed-histogram streaming fits vs
single-host ones, the fixed program-count contract across shard/host
counts, and host-preemption repartition+rewind+resume bit-identity
(single-process simulation; the two-process cell lives in
tests/test_multiprocess.py)."""

import numpy as np
import pytest

import jax

import spark_ensemble_tpu as se
from spark_ensemble_tpu.autotune.resolve import override
from spark_ensemble_tpu.data import write_shards
from spark_ensemble_tpu.data.partition import (
    PartitionedShardReader,
    ShardPartition,
    digest_words,
    manifest_digest,
    partition_shards,
    partition_steps,
)
from spark_ensemble_tpu.data.shards import ShardStore
from spark_ensemble_tpu.models.base import observe_program_calls
from spark_ensemble_tpu.models.tree import DecisionTreeRegressor
from spark_ensemble_tpu.parallel import multihost
from spark_ensemble_tpu.parallel.elastic import (
    DistributedSweep,
    ElasticCoordinator,
    HostLostError,
    survivor_mesh,
)
from spark_ensemble_tpu.parallel.mesh import (
    data_member_mesh,
    hybrid_data_member_mesh,
)
from spark_ensemble_tpu.robustness import chaos
from spark_ensemble_tpu.telemetry import record_fits

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="elastic tests need >= 4 devices"
)


def _data(n=300, d=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


def _cls_labels(X):
    return (
        (X[:, 0] + X[:, 1] > 0).astype(np.int32)
        + (X[:, 2] > 0.5).astype(np.int32)
    )


def _base(**kw):
    kw.setdefault("max_depth", 3)
    kw.setdefault("max_bins", 16)
    kw.setdefault("hist", "stream")
    return DecisionTreeRegressor(**kw)


def _store(tmp_path, X, shard_rows=32, name="store"):
    return write_shards(
        X, str(tmp_path / name), max_bins=16, shard_rows=shard_rows
    )


def _reg(ckdir=None, **kw):
    kw.setdefault("base_learner", _base())
    kw.setdefault("num_base_learners", 4)
    kw.setdefault("seed", 0)
    if ckdir is not None:
        kw.update(checkpoint_dir=ckdir, checkpoint_interval=1)
    return se.GBMRegressor(**kw)


def _assert_params_equal(m1, m2):
    l1 = jax.tree_util.tree_leaves(m1.params)
    l2 = jax.tree_util.tree_leaves(m2.params)
    assert len(l1) == len(l2)
    for a, b in zip(l1, l2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    yield
    chaos.install(None)


# ---------------------------------------------------------------------------
# partition plane
# ---------------------------------------------------------------------------


def test_partition_round_robin_total_and_disjoint():
    S, W = 13, 4
    parts = [partition_shards(S, W, w) for w in range(W)]
    assert parts[0] == (0, 4, 8, 12)
    assert parts[1] == (1, 5, 9)
    flat = sorted(s for p in parts for s in p)
    assert flat == list(range(S))
    assert partition_steps(S, W) == 4
    # fewer shards than parts: empty tail parts, still one step
    assert partition_shards(2, 4, 3) == ()
    assert partition_steps(2, 4) == 1


def test_partition_validates_arguments():
    with pytest.raises(ValueError):
        partition_shards(10, 0, 0)
    with pytest.raises(ValueError):
        partition_shards(10, 4, 4)
    with pytest.raises(ValueError):
        partition_steps(10, -1)


def test_partitioned_reader_step_major_order(tmp_path):
    X, _ = _data(n=10 * 32)
    store = _store(tmp_path, X, shard_rows=32)  # S = 10
    rdr = PartitionedShardReader(store, positions=(1, 3), num_parts=4)
    assert rdr.steps == 3
    assert rdr.num_shards == 6
    # step-major: (k=0: 1, 3), (k=1: 5, 7), (k=2: 9, 11-tail)
    order = [rdr.global_index(j) for j in range(rdr.num_shards)]
    assert order == [1, 3, 5, 7, 9, 11]
    np.testing.assert_array_equal(rdr.load_shard(0), store.load_shard(1))
    np.testing.assert_array_equal(rdr.load_shard(4), store.load_shard(9))
    # past the manifest end: an all-zero block (exact +0 contribution)
    tail = rdr.load_shard(5)
    assert tail.shape == (store.shard_rows, store.words_per_row)
    assert not tail.any()


def test_partitioned_reader_rejects_bad_positions(tmp_path):
    X, _ = _data(n=64)
    store = _store(tmp_path, X, shard_rows=32)
    with pytest.raises(ValueError):
        PartitionedShardReader(store, positions=(), num_parts=2)
    with pytest.raises(ValueError):
        PartitionedShardReader(store, positions=(2,), num_parts=2)
    with pytest.raises(ValueError):
        PartitionedShardReader(store, positions=(0, 0), num_parts=2)


def test_manifest_digest_and_partition_metadata(tmp_path):
    X, _ = _data(n=96)
    store = _store(tmp_path, X, shard_rows=32)
    dig = manifest_digest(store)
    assert dig == manifest_digest(ShardStore.open(store.directory))
    assert digest_words(dig).shape == (8,)
    other = _store(tmp_path, X[:64], shard_rows=32, name="other")
    assert manifest_digest(other) != dig
    part = ShardPartition.from_store(store, 2, 1)
    assert part.shards == (1,)
    assert part.steps == 2
    assert part.digest == dig


# ---------------------------------------------------------------------------
# subset-verified store opens
# ---------------------------------------------------------------------------


def test_store_open_subset_verifies_and_guards(tmp_path):
    X, _ = _data(n=5 * 32)
    full = _store(tmp_path, X, shard_rows=32)
    sub = ShardStore.open(full.directory, shards=[1, 3])
    assert sub.verified_shards == frozenset({1, 3})
    assert full.verified_shards is None
    np.testing.assert_array_equal(sub.load_shard(3), full.load_shard(3))
    with pytest.raises(ValueError, match="verified subset"):
        sub.load_shard(0)
    # geometry properties still reflect the full manifest
    assert sub.n == full.n and sub.num_shards == full.num_shards
    np.testing.assert_array_equal(sub.thresholds, full.thresholds)


def test_store_open_subset_rejects_bad_indices(tmp_path):
    X, _ = _data(n=96)
    store = _store(tmp_path, X, shard_rows=32)
    with pytest.raises(ValueError, match="out of range"):
        ShardStore.open(store.directory, shards=[0, 99])
    with pytest.raises(ValueError, match="duplicate"):
        ShardStore.open(store.directory, shards=[1, 1])


def test_store_open_subset_skips_other_shards_bytes(tmp_path):
    import os

    X, _ = _data(n=96)
    store = _store(tmp_path, X, shard_rows=32)
    # corrupt a shard OUTSIDE the subset: the subset open must not care
    victim = store.shard_meta(2)["file"]
    with open(os.path.join(store.directory, victim), "wb") as f:
        f.write(b"garbage")
    sub = ShardStore.open(store.directory, shards=[0])
    np.testing.assert_array_equal(sub.load_shard(0), store.load_shard(0))
    # ... but a full open still fails loudly
    with pytest.raises(ValueError):
        ShardStore.open(store.directory)


def test_store_open_rejects_manifest_global_disagreement(tmp_path):
    import json
    import os

    X, _ = _data(n=96)
    store = _store(tmp_path, X, shard_rows=32)
    mpath = os.path.join(store.directory, "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["n"] = manifest["n"] + 7
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    # the global row count no longer matches the shard tiling: every
    # open — full or subset — must refuse before any math runs
    with pytest.raises(ValueError, match="global row count"):
        ShardStore.open(store.directory, shards=[0], verify=False)
    with pytest.raises(ValueError, match="global row count"):
        ShardStore.open(store.directory, verify=False)


# ---------------------------------------------------------------------------
# mesh satellites
# ---------------------------------------------------------------------------


def test_slice_count_and_auto_hybrid_mesh():
    # CPU devices carry no slice_index -> one slice
    assert multihost.slice_count() == 1
    assert multihost.slice_count(jax.devices()[:2]) == 1
    m = hybrid_data_member_mesh(dcn_data="auto", devices=jax.devices()[:4])
    assert m.shape["dcn_data"] == 1
    assert m.shape["data"] == 4

    class _FakeSliced:
        def __init__(self, d, s):
            self._d, self.slice_index = d, s

        def __getattr__(self, name):
            return getattr(object.__getattribute__(self, "_d"), name)

    fake = [
        _FakeSliced(d, i % 2) for i, d in enumerate(jax.devices()[:4])
    ]
    assert multihost.slice_count(fake) == 2


# ---------------------------------------------------------------------------
# chaos host_preempt fault
# ---------------------------------------------------------------------------


def test_chaos_host_preempt_budget_and_determinism():
    ctl = chaos.ChaosController(seed=3, rate=1.0, faults=("host_preempt",))
    assert ctl.host_preempt("fit:level:0:dist_step:0")
    # at-most-once per site AND budget 1 overall
    assert not ctl.host_preempt("fit:level:0:dist_step:0")
    assert not ctl.host_preempt("fit:level:0:dist_step:1")
    assert ctl.fired == [("host_preempt", "fit:level:0:dist_step:0")]
    # the pick is a pure function of (seed, fault, site)
    again = chaos.ChaosController(seed=3, rate=1.0)
    assert ctl.pick("host_preempt", "s", 4) == again.pick(
        "host_preempt", "s", 4
    )
    noop = chaos._NoopController()
    assert noop.host_preempt("anything") is False


class _HostPreemptAt:
    """Controller firing host_preempt at exactly one site, with a
    pinned victim (the full-surface controller protocol, as
    test_streaming._PreemptAtSite)."""

    enabled = True

    def __init__(self, site, victim):
        self.site = site
        self.victim = victim
        self.fired = []

    def host_preempt(self, site):
        if site == self.site and not self.fired:
            self.fired.append(site)
            return True
        return False

    def pick(self, fault, site, n):
        return self.victim % n

    def preempt(self, site):
        pass

    def transient(self, site):
        pass

    def poison_array(self, site, arr):
        return arr

    def poison_member_stack(self, site, tree):
        return tree

    def poison_tree(self, site, tree):
        return tree

    def corrupt_checkpoint(self, site, state_path):
        pass


# ---------------------------------------------------------------------------
# distributed-histogram bit-identity
# ---------------------------------------------------------------------------


def test_distributed_regressor_bit_identical(tmp_path):
    X, y = _data()
    store = _store(tmp_path, X, shard_rows=32)  # S = 10
    kw = dict(base_learner=_base(), num_base_learners=4, seed=0)
    single = se.GBMRegressor(**kw).fit_streaming(store, y)
    mesh = data_member_mesh(4, member=1)
    dist = se.GBMRegressor(**kw).fit_streaming(store, y, mesh=mesh)
    _assert_params_equal(single, dist)
    np.testing.assert_array_equal(
        np.asarray(single.predict(X)), np.asarray(dist.predict(X))
    )
    # a hybrid {dcn_data, data} mesh reduces over BOTH row axes and
    # must land on the same bits
    hybrid = hybrid_data_member_mesh(dcn_data=2, devices=jax.devices()[:8])
    m_h = se.GBMRegressor(**kw).fit_streaming(store, y, mesh=hybrid)
    _assert_params_equal(single, m_h)
    # a ragged width (W=3 over S=10: uneven slices + zero tail) too
    m3 = se.GBMRegressor(**kw).fit_streaming(
        store, y, mesh=data_member_mesh(3, member=1)
    )
    _assert_params_equal(single, m3)


def test_distributed_matches_resident_stream_fit(tmp_path):
    # the ISSUE-level contract: distributed streaming == the resident
    # hist="stream" fit at matched shard size (transitively via the
    # streaming==resident pin, asserted here directly)
    X, y = _data(n=157, d=5)
    with override(stream_chunk_rows=64, shard_rows=64):
        store = _store(tmp_path, X, shard_rows=64)
        kw = dict(base_learner=_base(), num_base_learners=4, seed=0)
        res = se.GBMRegressor(**kw).fit(X, y)
        dist = se.GBMRegressor(**kw).fit_streaming(
            store, y, mesh=data_member_mesh(4, member=1)
        )
        _assert_params_equal(res, dist)


def test_distributed_classifier_bit_identical(tmp_path):
    X, _ = _data(n=256, d=5, seed=3)
    y = _cls_labels(X)
    store = _store(tmp_path, X, shard_rows=32)
    kw = dict(base_learner=_base(), num_base_learners=3, seed=3)
    single = se.GBMClassifier(**kw).fit_streaming(store, y)
    dist = se.GBMClassifier(**kw).fit_streaming(
        store, y, mesh=data_member_mesh(2, member=1)
    )
    _assert_params_equal(single, dist)
    np.testing.assert_array_equal(
        np.asarray(single.predict(X)), np.asarray(dist.predict(X))
    )


def test_distributed_psum_mode_allclose(tmp_path):
    X, y = _data()
    store = _store(tmp_path, X, shard_rows=32)
    kw = dict(base_learner=_base(), num_base_learners=4, seed=0)
    single = se.GBMRegressor(**kw).fit_streaming(store, y)
    psum = se.GBMRegressor(**kw).fit_streaming(
        store, y, mesh=data_member_mesh(4, member=1), reduce="psum"
    )
    np.testing.assert_allclose(
        np.asarray(single.predict(X)), np.asarray(psum.predict(X)),
        rtol=1e-4, atol=1e-5,
    )
    with pytest.raises(ValueError, match="reduce"):
        se.GBMRegressor(**kw).fit_streaming(
            store, y, mesh=data_member_mesh(4, member=1), reduce="mean"
        )


def test_distributed_requires_member_one(tmp_path):
    X, y = _data(n=96)
    store = _store(tmp_path, X, shard_rows=32)
    with pytest.raises(ValueError, match="member=1"):
        se.GBMRegressor(
            base_learner=_base(), num_base_learners=2, seed=0
        ).fit_streaming(store, y, mesh=data_member_mesh(4, member=2))


def test_distributed_emits_config_and_agreement(tmp_path):
    X, y = _data(n=128)
    store = _store(tmp_path, X, shard_rows=32)
    with record_fits() as rec:
        se.GBMRegressor(
            base_learner=_base(max_depth=2), num_base_learners=2, seed=0
        ).fit_streaming(store, y, mesh=data_member_mesh(2, member=1))
    events = {e["event"] for e in rec.events}
    assert "dist_config" in events
    assert "dist_manifest_agreed" in events
    assert "dist_sweep" in events
    cfg = next(e for e in rec.events if e["event"] == "dist_config")
    assert cfg["positions"] == 2 and cfg["shards"] == store.num_shards


# ---------------------------------------------------------------------------
# fixed program-count contract
# ---------------------------------------------------------------------------


def test_distributed_program_count_fixed(tmp_path):
    from spark_ensemble_tpu.analysis.contracts import _ProgramRecorder

    X, y = _data(n=160, d=5)
    counts = {}
    for W in (2, 4):
        mesh = data_member_mesh(W, member=1)
        for sr in (32, 16):
            store = _store(tmp_path, X, shard_rows=sr, name=f"s{W}_{sr}")
            rec = _ProgramRecorder()
            with observe_program_calls(rec):
                se.GBMRegressor(
                    base_learner=_base(max_depth=2),
                    num_base_learners=3, seed=0,
                ).fit_streaming(store, y, mesh=mesh)
            counts[(W, sr)] = rec.count()
    # one number regardless of shard count AND mesh width: the PR-8
    # contract extended to the distributed plane
    assert len(set(counts.values())) == 1, counts


# ---------------------------------------------------------------------------
# elasticity: preempt -> repartition -> rewind -> resume
# ---------------------------------------------------------------------------


def test_survivor_mesh_drops_position_single_process():
    mesh = data_member_mesh(4, member=1)
    surv = survivor_mesh(mesh, 1)
    assert surv.shape["data"] == 3
    kept = [d.id for d in surv.devices.flat]
    lost = np.asarray(mesh.devices).reshape(-1)[1].id
    assert lost not in kept and len(kept) == 3


def test_elastic_preempt_resume_bit_identical(tmp_path):
    X, y = _data()
    store = _store(tmp_path, X, shard_rows=32)
    ref = _reg().fit_streaming(store, y)

    site = "GBMRegressor:stream_round:2:level:1:dist_step:1"
    ctl = _HostPreemptAt(site, victim=1)
    chaos.install(ctl)
    coord = ElasticCoordinator(data_member_mesh(4, member=1))
    with record_fits() as rec:
        m = coord.fit_streaming(_reg(str(tmp_path / "ck")), store, y)
    assert ctl.fired == [site]
    assert [(v, s) for v, s, _ in coord.losses] == [(1, site)]
    # survivors: the 4-wide mesh re-laid as 3 positions
    assert coord.mesh.shape["data"] == 3
    events = [e["event"] for e in rec.events]
    assert "host_preempted" in events
    assert "resume_from_checkpoint" in events
    # the rewound, repartitioned fit lands on the SAME bits as an
    # uninterrupted single-host fit (hence also as an uninterrupted
    # distributed fit — see test_distributed_regressor_bit_identical)
    _assert_params_equal(ref, m)


def test_elastic_coordinator_respects_max_losses(tmp_path):
    X, y = _data(n=128)
    store = _store(tmp_path, X, shard_rows=32)

    class _AlwaysPreempt(_HostPreemptAt):
        def host_preempt(self, site):
            if site.endswith("level:0:dist_step:0"):
                self.fired.append(site)
                return True
            return False

    ctl = _AlwaysPreempt("", victim=0)
    chaos.install(ctl)
    coord = ElasticCoordinator(
        data_member_mesh(4, member=1), max_losses=0
    )
    with pytest.raises(HostLostError):
        coord.fit_streaming(_reg(), store, y)
    assert coord.losses == []


# ---------------------------------------------------------------------------
# pod-scope observability: preempt/rewind flows, flight dump, statusz, stalls
# ---------------------------------------------------------------------------


def test_preempt_rewind_flow_and_flight_dump(tmp_path, monkeypatch):
    """The preemption leaves a complete causal record: a ``host_preempt``
    span whose deterministic flow id the resuming attempt's ``rewind``
    span consumes (the single-process stream validates clean on its own),
    the stream fsync'd BEFORE the raise, and the crash flight dump on
    disk next to it."""
    import importlib.util
    import json
    import os

    from spark_ensemble_tpu.parallel.elastic import preempt_flow_id

    tel = tmp_path / "telemetry.jsonl"
    monkeypatch.setenv("SE_TPU_TELEMETRY", str(tel))
    X, y = _data()
    store = _store(tmp_path, X, shard_rows=32)
    site = "GBMRegressor:stream_round:2:level:1:dist_step:1"
    chaos.install(_HostPreemptAt(site, victim=1))
    coord = ElasticCoordinator(data_member_mesh(4, member=1))
    coord.fit_streaming(_reg(str(tmp_path / "ck")), store, y)

    events = [json.loads(line) for line in open(tel)]
    spans = [e for e in events if e.get("event") == "span"]
    fid = preempt_flow_id(1, site)
    preempts = [s for s in spans if s["name"] == "host_preempt"]
    rewinds = [s for s in spans if s["name"] == "rewind"]
    assert len(preempts) == 1 and preempts[0]["flow_out"] == [fid]
    assert preempts[0]["victim"] == 1 and preempts[0]["site"] == site
    assert len(rewinds) == 1 and rewinds[0]["flow_in"] == fid
    # single-process: source and sink live in ONE stream -> clean graph
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "_viewer", os.path.join(repo, "tools", "trace_viewer.py")
    )
    viewer = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(viewer)
    assert viewer.validate(viewer.select_spans(events)) == []
    # the black box landed next to the stream before the raise
    dump = tmp_path / f"flight_p{os.getpid()}.json"
    assert dump.exists()
    payload = json.loads(dump.read_text())
    assert payload["rows"]
    assert any(
        r.get("event") == "host_preempted" for r in payload["rows"]
    )


def test_flight_dir_env_overrides_stream_location(tmp_path, monkeypatch):
    import json
    import os

    box = tmp_path / "blackbox"
    monkeypatch.setenv("SE_TPU_TELEMETRY", str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("SE_TPU_FLIGHT_DIR", str(box))
    X, y = _data(n=128)
    store = _store(tmp_path, X, shard_rows=32)
    site = "GBMRegressor:stream_round:1:level:0:dist_step:0"
    chaos.install(_HostPreemptAt(site, victim=2))
    coord = ElasticCoordinator(data_member_mesh(4, member=1))
    coord.fit_streaming(_reg(str(tmp_path / "ck")), store, y)
    dump = box / f"flight_p{os.getpid()}.json"
    assert dump.exists()
    assert json.loads(dump.read_text())["rows"]


def test_coordinator_statusz_counts_attempts_and_losses(tmp_path):
    from spark_ensemble_tpu.telemetry.events import global_metrics

    X, y = _data()
    store = _store(tmp_path, X, shard_rows=32)
    site = "GBMRegressor:stream_round:2:level:1:dist_step:1"

    seen = {}

    class _Snooping(_HostPreemptAt):
        """Grab the live metrics snapshot from INSIDE the fit — the
        coordinator's statusz source must be visible mid-flight."""

        def host_preempt(self, site_):
            if site_ == self.site and not self.fired:
                seen.update(global_metrics().snapshot())
            return _HostPreemptAt.host_preempt(self, site_)

    chaos.install(_Snooping(site, victim=1))
    coord = ElasticCoordinator(data_member_mesh(4, member=1))
    assert coord.statusz()["attempts"] == 0
    coord.fit_streaming(_reg(str(tmp_path / "ck")), store, y)

    sz = coord.statusz()
    assert sz["attempts"] == 2  # initial + resumed
    assert sz["width"] == 3  # survivors after the loss
    # the recorded width is the SURVIVOR width the fit resumed on
    assert sz["losses"] == [{"victim": 1, "site": site, "width": 3}]
    assert sz["process_count"] == 1 and sz["uptime_s"] >= 0.0
    assert sz["last_fit"]["sweep_s"] >= 0.0
    # the source was registered while fitting...
    mid = seen.get(coord._source_name)
    assert mid is not None and mid["value"]["attempts"] >= 1
    # ...and unregistered after
    assert coord._source_name not in global_metrics().snapshot()


def test_chaos_host_stall_verdict_and_noop():
    ctl = chaos.ChaosController(seed=5, rate=1.0, faults=("host_stall",))
    s = ctl.host_stall_s("fit:level:0:dist_step:0", seconds=0.05)
    assert s == 0.05
    # at-most-once per site, and the pick is deterministic
    assert ctl.host_stall_s("fit:level:0:dist_step:0") == 0.0
    assert ctl.pick("host_stall", "s", 4) == chaos.ChaosController(
        seed=5, rate=1.0
    ).pick("host_stall", "s", 4)
    assert chaos._NoopController().host_stall_s("x") == 0.0


def test_single_process_stall_attribution(tmp_path):
    """An injected host_stall on a simulated host must surface as an
    attributable ``host_stalled`` event, and the skew report must name
    the victim."""
    from spark_ensemble_tpu.telemetry import podview

    X, y = _data(n=128)
    store = _store(tmp_path, X, shard_rows=32)
    stall_site = "GBMRegressor:stream_round:1:level:0:dist_step:0"

    class _StallOnce(_HostPreemptAt):
        def __init__(self):
            _HostPreemptAt.__init__(self, site="", victim=0)
            self.stalled = []

        def host_preempt(self, site):
            return False

        def host_stall_s(self, site, seconds=0.25):
            if site == stall_site and not self.stalled:
                self.stalled.append(site)
                return 0.05
            return 0.0

        def pick(self, fault, site, n):
            return 2 % n

    ctl = _StallOnce()
    chaos.install(ctl)
    mesh = data_member_mesh(4, member=1)
    with record_fits() as rec:
        _reg().fit_streaming(store, y, mesh=mesh)
    assert ctl.stalled == [stall_site]
    stalled = [e for e in rec.events if e["event"] == "host_stalled"]
    assert len(stalled) == 1
    assert stalled[0]["victim"] == 2
    assert stalled[0]["seconds"] == 0.05
    report = podview.skew_report([rec.events])
    assert report["stalls"] == {"2": {"count": 1, "seconds": 0.05}}
    assert "stalls: host 2" in podview.render_skew(report)
