"""Megabatch sweep contract (docs/selection.md#megabatch-sweeps): a whole
CV/TVS candidate batch trained as ONE vmapped program per round chunk must
be BIT-identical to fitting each candidate sequentially — same members,
same weights, same early-stop round, same predictions.  The config axis is
pure batching, never a numerics change."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_ensemble_tpu import (
    CrossValidator,
    DecisionTreeRegressor,
    GBMClassifier,
    GBMRegressor,
    MulticlassClassificationEvaluator,
    ParamGridBuilder,
    RegressionEvaluator,
    TrainValidationSplit,
)
from spark_ensemble_tpu.models.gbm_sweep import (
    fit_sweep,
    sweep_group_key,
    sweep_unsupported_reason,
)


def _data(n=96, d=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] * 2 - X[:, 1] + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


def _tree_eq(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, z in zip(la, lb):
        xa, za = np.asarray(x), np.asarray(z)
        assert xa.shape == za.shape
        assert np.array_equal(xa, za, equal_nan=True)


@pytest.mark.slow
def test_regressor_sweep_bit_identical_to_sequential():
    X, y = _data()
    base = GBMRegressor(num_base_learners=5, seed=3)
    cands = [
        base.copy(learning_rate=0.1, seed=1),
        base.copy(learning_rate=0.3, seed=2, subsample_ratio=0.7),
        base.copy(learning_rate=0.05, seed=3, num_base_learners=3),
    ]
    w0 = np.ones(len(y), np.float32)
    w0[10:20] = 0.0  # tuning's zero-weight fold mask
    sws = [w0, None, None]
    models = fit_sweep(cands, X, y, sample_weights=sws)
    for est, sw, m in zip(cands, sws, models):
        ref = est.fit(X, y, sample_weight=sw)
        assert m.num_members == ref.num_members
        _tree_eq(m.params, ref.params)
        assert np.array_equal(
            np.asarray(m.predict(X)), np.asarray(ref.predict(X))
        )


@pytest.mark.slow
def test_regressor_sweep_validation_patience_equivalence():
    """Per-lane patience must stop each candidate at exactly the round the
    sequential driver would — including lanes that stop rounds apart."""
    X, y = _data(n=120)
    vi = np.zeros(len(y), bool)
    vi[::4] = True
    base = GBMRegressor(num_base_learners=10, seed=3)
    cands = [
        base.copy(learning_rate=0.4, num_rounds=2, validation_tol=0.05),
        base.copy(learning_rate=0.05, num_rounds=1, validation_tol=0.2,
                  seed=9),
        base.copy(learning_rate=0.2, num_base_learners=6, num_rounds=3),
    ]
    models = fit_sweep(cands, X, y, validation_indicator=vi)
    for est, m in zip(cands, models):
        ref = est.fit(X, y, validation_indicator=vi)
        assert m.num_members == ref.num_members
        _tree_eq(m.params, ref.params)  # includes the val_hist trace


@pytest.mark.slow
def test_regressor_sweep_huber():
    X, y = _data()
    vi = np.zeros(len(y), bool)
    vi[::5] = True
    base = GBMRegressor(num_base_learners=4, loss="huber", alpha=0.8)
    cands = [base.copy(learning_rate=0.1),
             base.copy(learning_rate=0.2, seed=5)]
    models = fit_sweep(cands, X, y, validation_indicator=vi)
    for est, m in zip(cands, models):
        ref = est.fit(X, y, validation_indicator=vi)
        _tree_eq(m.params, ref.params)


@pytest.mark.slow
def test_classifier_sweep_bit_identical_to_sequential():
    X, y = _data()
    yc = (y > 0).astype(np.float32)
    base = GBMClassifier(num_base_learners=4, seed=2)
    cands = [base.copy(learning_rate=0.1),
             base.copy(learning_rate=0.3, seed=4, subsample_ratio=0.8)]
    w0 = np.ones(len(yc), np.float32)
    w0[:15] = 0.0
    models = fit_sweep(cands, X, yc, sample_weights=[w0, None])
    for est, sw, m in zip(cands, [w0, None], models):
        ref = est.fit(X, yc, sample_weight=sw)
        _tree_eq(m.params, ref.params)
        assert np.array_equal(
            np.asarray(m.predict_proba(X)), np.asarray(ref.predict_proba(X))
        )


@pytest.mark.slow
def test_sweep_slab_padding_invariant():
    """3 candidates at configs_per_dispatch=2 force a padded second slab;
    padded lanes are computed-and-discarded, so results must match the
    one-slab fit bit for bit."""
    from spark_ensemble_tpu import autotune

    X, y = _data()
    base = GBMRegressor(num_base_learners=4, seed=1)
    cands = [base.copy(learning_rate=0.1 + 0.1 * i, seed=i)
             for i in range(3)]
    wide = fit_sweep([e.copy() for e in cands], X, y)
    with autotune.override(configs_per_dispatch=2):
        narrow = fit_sweep([e.copy() for e in cands], X, y)
    for a, b in zip(wide, narrow):
        _tree_eq(a.params, b.params)


def test_sweep_rejects_structural_mix_and_unsupported():
    X, y = _data()
    a = GBMRegressor(num_base_learners=2)
    b = a.copy(base_learner=DecisionTreeRegressor(max_depth=7))
    assert sweep_group_key(a) != sweep_group_key(b)
    with pytest.raises(ValueError, match="structural"):
        fit_sweep([a, b], X, y)
    # batchable params do NOT split the group
    assert sweep_group_key(a) == sweep_group_key(
        a.copy(learning_rate=0.7, seed=9, num_base_learners=30)
    )
    assert sweep_unsupported_reason(a) is None
    assert "checkpoint" in sweep_unsupported_reason(
        a.copy(checkpoint_dir="/tmp/ck")
    )
    assert "megabatch" in sweep_unsupported_reason(DecisionTreeRegressor())
    with pytest.raises(ValueError, match="sweep"):
        fit_sweep([a.copy(checkpoint_dir="/tmp/ck")], X, y)


def test_sweep_routes_sampling_and_linear_leaves_sequential():
    """Gradient-based row sampling and piecewise-linear leaves have no
    megabatch round core: the reason gate must route them to the
    sequential loop under megabatch='auto' and raise under 'on'."""
    X, y = _data()
    a = GBMRegressor(num_base_learners=2)
    assert "sampling" in sweep_unsupported_reason(a.copy(sampling="goss"))
    assert "sampling" in sweep_unsupported_reason(a.copy(sampling="mvs"))
    assert "linear" in sweep_unsupported_reason(a.copy(leaf_model="linear"))
    assert sweep_unsupported_reason(
        a.copy(sampling="none", leaf_model="constant")
    ) is None
    grid = ParamGridBuilder().add_grid("learning_rate", [0.1, 0.3]).build()
    kw = dict(
        estimator=a.copy(sampling="goss"),
        estimator_param_maps=grid,
        evaluator=RegressionEvaluator(metric="rmse"),
        seed=0,
    )
    with pytest.raises(ValueError, match="sampling"):
        TrainValidationSplit(megabatch="on", **kw).fit(X, y)


@pytest.mark.slow
def test_sweep_auto_falls_back_sequential_for_sampled_fits():
    """megabatch='auto' on a sampled grid must land byte-for-byte on the
    sequential loop's answer (the fallback IS the sequential loop)."""
    X, y = _data()
    grid = ParamGridBuilder().add_grid("learning_rate", [0.1, 0.3]).build()
    kw = dict(
        estimator=GBMRegressor(num_base_learners=2, sampling="goss"),
        estimator_param_maps=grid,
        evaluator=RegressionEvaluator(metric="rmse"),
        seed=0,
    )
    seq = TrainValidationSplit(megabatch="off", **kw).fit(X, y)
    auto = TrainValidationSplit(megabatch="auto", **kw).fit(X, y)
    assert seq.validation_metrics == auto.validation_metrics
    assert seq.best_index == auto.best_index


def test_chol_solve_psd_lane_independent_and_accurate():
    """The hand-rolled Cholesky solve exists because LAPACK's batched
    kernel under vmap reorders arithmetic per lane.  Pin the property the
    sweep needs from it: within ONE batched program every lane's result
    depends only on that lane's inputs (permuting lanes permutes outputs
    bit-for-bit — the invariant that makes padded lanes harmless), and the
    solve itself is accurate against a float64 reference.  The sweep-vs-
    sequential bit-identity itself is pinned end-to-end above."""
    from spark_ensemble_tpu.ops.linesearch import chol_solve_psd

    rng = np.random.RandomState(0)
    batched = jax.jit(jax.vmap(chol_solve_psd))
    for k in (1, 3, 7, 26):
        A = rng.randn(8, k, k).astype(np.float32)
        A = np.einsum("bij,bkj->bik", A, A) + 1e-3 * np.eye(k, dtype=np.float32)
        b = rng.randn(8, k).astype(np.float32)
        out = np.asarray(batched(A, b))
        perm = rng.permutation(8)
        shuffled = np.asarray(batched(A[perm], b[perm]))
        assert np.array_equal(out[perm], shuffled)
        ref = np.linalg.solve(
            A.astype(np.float64), b.astype(np.float64)[..., None]
        )[..., 0]
        np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-3)


@pytest.mark.slow
def test_cv_megabatch_matches_sequential():
    X, y = _data(n=150)
    grid = (
        ParamGridBuilder()
        .add_grid("learning_rate", [0.1, 0.3])
        .add_grid("seed", [0, 7])
        .build()
    )
    kw = dict(
        estimator=GBMRegressor(num_base_learners=3),
        estimator_param_maps=grid,
        evaluator=RegressionEvaluator(metric="rmse"),
        num_folds=2,
        seed=0,
    )
    seq = CrossValidator(megabatch="off", **kw).fit(X, y)
    mb = CrossValidator(megabatch="on", **kw).fit(X, y)
    auto = CrossValidator(megabatch="auto", **kw).fit(X, y)
    assert seq.avg_metrics == mb.avg_metrics == auto.avg_metrics
    assert seq.best_index == mb.best_index == auto.best_index


@pytest.mark.slow
def test_cv_megabatch_structural_grid_partitions():
    """A grid that sweeps a structural param (num_base_learners is
    batchable, base_learner depth is NOT) partitions into one megabatch
    per group key and still matches sequential exactly."""
    X, y = _data(n=120)
    grid = [
        {"learning_rate": 0.1,
         "base_learner": DecisionTreeRegressor(max_depth=2)},
        {"learning_rate": 0.3,
         "base_learner": DecisionTreeRegressor(max_depth=2)},
        {"learning_rate": 0.1,
         "base_learner": DecisionTreeRegressor(max_depth=4)},
    ]
    kw = dict(
        estimator=GBMRegressor(num_base_learners=3),
        estimator_param_maps=grid,
        evaluator=RegressionEvaluator(metric="rmse"),
        num_folds=2,
        seed=1,
    )
    seq = CrossValidator(megabatch="off", **kw).fit(X, y)
    mb = CrossValidator(megabatch="on", **kw).fit(X, y)
    assert seq.avg_metrics == mb.avg_metrics
    assert seq.best_index == mb.best_index


@pytest.mark.slow
def test_tvs_megabatch_matches_sequential_classifier():
    X, y = _data(n=150)
    yc = (y > 0).astype(np.float32)
    grid = ParamGridBuilder().add_grid(
        "learning_rate", [0.1, 0.3, 0.6]
    ).build()
    kw = dict(
        estimator=GBMClassifier(num_base_learners=3, loss="logloss"),
        estimator_param_maps=grid,
        evaluator=MulticlassClassificationEvaluator(metric="accuracy"),
        train_ratio=0.75,
        seed=0,
    )
    seq = TrainValidationSplit(megabatch="off", **kw).fit(X, yc)
    mb = TrainValidationSplit(megabatch="on", **kw).fit(X, yc)
    assert seq.validation_metrics == mb.validation_metrics
    assert seq.best_index == mb.best_index


def test_megabatch_on_raises_for_unsupported_auto_falls_back():
    X, y = _data()
    grid = ParamGridBuilder().add_grid("max_depth", [2, 3]).build()
    kw = dict(
        estimator=DecisionTreeRegressor(),
        estimator_param_maps=grid,
        evaluator=RegressionEvaluator(metric="rmse"),
        num_folds=2,
        seed=0,
    )
    with pytest.raises(ValueError, match="megabatch"):
        CrossValidator(megabatch="on", **kw).fit(X, y)
    seq = CrossValidator(megabatch="off", **kw).fit(X, y)
    auto = CrossValidator(megabatch="auto", **kw).fit(X, y)
    assert seq.avg_metrics == auto.avg_metrics
    assert seq.best_index == auto.best_index


def test_megabatch_requires_share_binning():
    """A megabatch IS shared binning: an explicit share_binning=False
    wins over 'auto' (sequential fits, bit-identical scores) and
    conflicts with 'on' (raise before any fit)."""
    X, y = _data()
    grid = ParamGridBuilder().add_grid("learning_rate", [0.1, 0.3]).build()
    kw = dict(
        estimator=GBMRegressor(num_base_learners=2),
        estimator_param_maps=grid,
        evaluator=RegressionEvaluator(metric="rmse"),
        num_folds=2,
        seed=0,
    )
    with pytest.raises(ValueError, match="share_binning"):
        CrossValidator(megabatch="on", share_binning=False, **kw).fit(X, y)
    seq = CrossValidator(megabatch="off", share_binning=False, **kw).fit(X, y)
    auto = CrossValidator(megabatch="auto", share_binning=False, **kw).fit(X, y)
    assert seq.avg_metrics == auto.avg_metrics
    assert seq.best_index == auto.best_index


@pytest.mark.slow
def test_tuning_candidate_events_emitted(tmp_path):
    """Every (map, fold) candidate lands one tuning_candidate event with
    its attribution fields, and the sweep fit emits per-chunk round-ledger
    events (the per-candidate cost attribution the report renders)."""
    import json

    X, y = _data(n=120)
    path = str(tmp_path / "tune.jsonl")
    grid = ParamGridBuilder().add_grid("learning_rate", [0.1, 0.3]).build()
    CrossValidator(
        estimator=GBMRegressor(num_base_learners=2),
        estimator_param_maps=grid,
        evaluator=RegressionEvaluator(metric="rmse"),
        num_folds=2,
        seed=0,
        megabatch="on",
        telemetry_path=path,
    ).fit(X, y)
    events = [json.loads(line) for line in open(path)]
    cands = [e for e in events if e.get("event") == "tuning_candidate"]
    assert len(cands) == 4  # 2 maps x 2 folds
    assert {(e["map_index"], e["fold"]) for e in cands} == {
        (0, 0), (0, 1), (1, 0), (1, 1)
    }
    for e in cands:
        assert e["tuner"] == "CrossValidator"
        assert e["megabatch"] is True
        assert e["rounds"] >= 1
        assert e["wall_s"] >= 0.0
        assert isinstance(e["metric"], float)
    chunks = [e for e in events if e.get("event") == "sweep_chunk"]
    assert chunks and all(e["candidates"] >= 1 for e in chunks)
    assert all("per_candidate_round_s" in e for e in chunks)


def test_telemetry_report_renders_tuning_section(tmp_path, capsys):
    import json
    import sys

    sys.path.insert(0, "tools")
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)

    path = str(tmp_path / "tune.jsonl")
    with open(path, "w") as fh:
        for mi, fi, metric in ((0, 0, 0.5), (0, 1, 0.6), (1, 0, 0.4),
                               (1, 1, 0.3)):
            fh.write(json.dumps({
                "event": "tuning_candidate", "fit_id": "tuner",
                "tuner": "CrossValidator", "map_index": mi, "fold": fi,
                "metric": metric, "rounds": 3, "wall_s": 0.25,
                "megabatch": True,
            }) + "\n")
    assert telemetry_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "== tuning ==" in out
    assert "4 candidates (2 maps x 2 folds)" in out
    assert "megabatch 4/4" in out
    # a stream of only tuning_candidate events must NOT render as a fit
    assert "== tuner ==" not in out
