"""Stacking tests (mirrors `StackingClassifierSuite.scala:49-87`,
`StackingRegressorSuite.scala:78-109`: stacking beats the best member)."""

import numpy as np
import pytest

import spark_ensemble_tpu as se
from tests.conftest import accuracy, rmse, split


def test_stacking_regressor_beats_weakest_member(cpusmall):
    X, y = cpusmall
    Xtr, ytr, Xte, yte = split(X, y)
    bases = [
        se.DecisionTreeRegressor(max_depth=5),
        se.LinearRegression(),
        se.DecisionTreeRegressor(max_depth=2),
    ]
    stack = se.StackingRegressor(
        base_learners=bases, stacker=se.LinearRegression()
    ).fit(Xtr, ytr)
    member_errs = [rmse(b.fit(Xtr, ytr).predict(Xte), yte) for b in bases]
    stack_err = rmse(stack.predict(Xte), yte)
    assert stack_err < max(member_errs)
    assert stack_err < min(member_errs) * 1.1


@pytest.mark.slow
@pytest.mark.parametrize("method", ["class", "raw", "proba"])
def test_stacking_classifier_stack_methods(letter, method):
    X, y = letter
    Xtr, ytr, Xte, yte = split(X, y)
    bases = [
        se.DecisionTreeClassifier(max_depth=5),
        se.GaussianNaiveBayes(),
    ]
    # "class" meta-features are raw class indices — a linear stacker can't
    # consume those (the reference's class-method users pair it with tree
    # stackers); use a tree stacker there, logistic elsewhere
    stacker = (
        se.DecisionTreeClassifier(max_depth=5)
        if method == "class"
        else se.LogisticRegression(max_iter=50)
    )
    stack = se.StackingClassifier(
        base_learners=bases, stacker=stacker, stack_method=method
    ).fit(Xtr, ytr)
    member_accs = [accuracy(b.fit(Xtr, ytr).predict(Xte), yte) for b in bases]
    assert accuracy(stack.predict(Xte), yte) >= min(member_accs) - 0.02


@pytest.mark.slow
def test_stacking_with_ensemble_members(letter):
    """The reference stacks meta-estimators as members
    (`StackingClassifierSuite.scala:49-87`: DT + Boosting + GBM + LR with a
    raw-method LR stacker beating every member)."""
    X, y = letter
    Xtr, ytr, Xte, yte = split(X, y)
    bases = [
        se.DecisionTreeClassifier(max_depth=5),
        se.BoostingClassifier(
            base_learner=se.DecisionTreeClassifier(max_depth=5), num_base_learners=5
        ),
        se.LogisticRegression(max_iter=50),
    ]
    stack = se.StackingClassifier(
        base_learners=bases,
        stacker=se.LogisticRegression(max_iter=50),
        stack_method="raw",
    ).fit(Xtr, ytr)
    stack_acc = accuracy(stack.predict(Xte), yte)
    member_accs = [accuracy(m.predict(Xte), yte) for m in stack.base_models]
    assert stack_acc > max(member_accs)


def test_stacking_classifier_beats_members_proba(letter):
    X, y = letter
    Xtr, ytr, Xte, yte = split(X, y)
    bases = [
        se.DecisionTreeClassifier(max_depth=5),
        se.GaussianNaiveBayes(),
    ]
    stack = se.StackingClassifier(
        base_learners=bases, stacker=se.LogisticRegression(max_iter=50),
        stack_method="proba",
    ).fit(Xtr, ytr)
    member_accs = [accuracy(b.fit(Xtr, ytr).predict(Xte), yte) for b in bases]
    assert accuracy(stack.predict(Xte), yte) > max(member_accs) - 0.02


def test_stacking_heterogeneous_regression_bases(cpusmall):
    X, y = cpusmall
    Xtr, ytr, Xte, yte = split(X, y)
    stack = se.StackingRegressor(
        base_learners=[se.LinearRegression(), se.DummyRegressor()],
        stacker=se.LinearRegression(),
    ).fit(Xtr, ytr)
    lin_err = rmse(se.LinearRegression().fit(Xtr, ytr).predict(Xte), yte)
    assert rmse(stack.predict(Xte), yte) <= lin_err * 1.05


@pytest.mark.slow
def test_parallel_fits_match_sequential():
    """parallelism > 1 (thread-pool member fits, the reference's driver
    Futures) must produce identical models to sequential fitting."""
    rng = np.random.RandomState(4)
    X = rng.randn(400, 6).astype(np.float32)
    y = rng.randint(0, 3, 400).astype(np.float32)
    bases = lambda: [
        se.DecisionTreeClassifier(max_depth=4),
        se.LogisticRegression(max_iter=20),
        se.GaussianNaiveBayes(),
    ]
    seq = se.StackingClassifier(
        base_learners=bases(), stack_method="proba", parallelism=1
    ).fit(X, y)
    par = se.StackingClassifier(
        base_learners=bases(), stack_method="proba", parallelism=3
    ).fit(X, y)
    np.testing.assert_allclose(
        np.asarray(seq.predict_raw(X)), np.asarray(par.predict_raw(X)),
        rtol=1e-5, atol=1e-5,
    )


def test_logistic_solvers_agree(adult):
    """Newton (exact softmax-CE Hessian) and LBFGS must reach the same
    optimum — same accuracy and near-identical probabilities — on both
    binary (sigmoid-reduced path) and multiclass problems."""
    X, y = adult
    ms = [
        se.LogisticRegression(solver=s).fit(X, y) for s in ("newton", "lbfgs")
    ]
    a0 = accuracy(ms[0].predict(X), y)
    a1 = accuracy(ms[1].predict(X), y)
    assert abs(a0 - a1) < 0.005, (a0, a1)
    p0 = np.asarray(ms[0].predict_proba(X[:500]))
    p1 = np.asarray(ms[1].predict_proba(X[:500]))
    assert np.max(np.abs(p0 - p1)) < 0.01

    rng = np.random.RandomState(2)
    Xm = rng.randn(1200, 6).astype(np.float32)
    centers = rng.randn(4, 6).astype(np.float32)
    ym = np.argmax(Xm @ centers.T, axis=1).astype(np.float32)
    mm = [
        se.LogisticRegression(solver=s).fit(Xm, ym) for s in ("newton", "lbfgs")
    ]
    am = [accuracy(m.predict(Xm), ym) for m in mm]
    assert abs(am[0] - am[1]) < 0.01, am
