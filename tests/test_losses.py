"""Loss-layer property tests (mirrors `GBMLossSuite.scala:84-125`: numerical
gradient checking of every loss and, via the (grad, hess) pair trick, of
every hessian)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_ensemble_tpu.ops import losses as L

ALL_LOSSES = [
    L.SquaredLoss(),
    L.AbsoluteLoss(),
    L.LogCoshLoss(),
    L.ScaledLogCoshLoss(0.3),
    L.HuberLoss(1.3),
    L.QuantileLoss(0.25),
    L.LogLoss(5),
    L.ExponentialLoss(),
    L.BernoulliLoss(),
]


def _random_labels(loss, n, rng):
    if isinstance(loss, L.LogLoss):
        return jnp.asarray(rng.randint(0, loss.num_classes, n), jnp.float32)
    if isinstance(loss, (L.ExponentialLoss, L.BernoulliLoss)):
        return jnp.asarray(rng.randint(0, 2, n), jnp.float32)
    return jnp.asarray(rng.randn(n), jnp.float32)


@pytest.mark.parametrize("loss", ALL_LOSSES, ids=lambda l: l.name)
def test_gradient_matches_autodiff(loss):
    rng = np.random.RandomState(0)
    y = _random_labels(loss, 64, rng)
    enc = loss.encode_label(y)
    pred = jnp.asarray(rng.randn(64, loss.dim), jnp.float32)
    auto = jax.grad(lambda p: jnp.sum(loss.loss(enc, p)))(pred)
    manual = loss.gradient(enc, pred)
    assert float(jnp.max(jnp.abs(auto - manual))) < 1e-4


@pytest.mark.parametrize(
    "loss",
    [l for l in ALL_LOSSES if l.has_hessian],
    ids=lambda l: l.name,
)
def test_hessian_matches_autodiff(loss):
    """Treat (gradient, hessian) as a (loss, grad) pair: the hessian must be
    the elementwise derivative of the gradient wrt the same output dim."""
    rng = np.random.RandomState(1)
    y = _random_labels(loss, 32, rng)
    enc = loss.encode_label(y)
    pred = jnp.asarray(rng.randn(32, loss.dim), jnp.float32)

    def grad_k(p):
        return jnp.sum(loss.gradient(enc, p))

    # d(grad_j)/d(pred_j): diagonal of the per-dim jacobian
    diag = jax.vmap(
        lambda e, p: jnp.diag(jax.jacfwd(lambda q: loss.gradient(e[None], q[None])[0])(p))
    )(enc, pred)
    manual = loss.hessian(enc, pred)
    assert float(jnp.max(jnp.abs(diag - manual))) < 1e-3


def test_negative_gradient():
    loss = L.SquaredLoss()
    y = jnp.asarray([[1.0], [2.0]])
    p = jnp.asarray([[0.5], [3.0]])
    assert jnp.allclose(loss.negative_gradient(y, p), -loss.gradient(y, p))


def test_logloss_encode_onehot():
    loss = L.LogLoss(4)
    enc = loss.encode_label(jnp.asarray([0.0, 3.0]))
    assert enc.shape == (2, 4)
    assert jnp.allclose(enc[0], jnp.asarray([1, 0, 0, 0]))
    assert jnp.allclose(enc[1], jnp.asarray([0, 0, 0, 1]))


def test_plus_minus_one_encoding():
    for loss in [L.ExponentialLoss(), L.BernoulliLoss()]:
        enc = loss.encode_label(jnp.asarray([0.0, 1.0]))
        assert jnp.allclose(enc[:, 0], jnp.asarray([-1.0, 1.0]))


def test_raw2probability_logloss_softmax():
    loss = L.LogLoss(3)
    raw = jnp.asarray([[1.0, 2.0, 3.0]])
    p = loss.raw2probability(raw)
    assert jnp.allclose(jnp.sum(p, axis=-1), 1.0, atol=1e-6)
    assert jnp.allclose(p, jax.nn.softmax(raw, axis=-1))


def test_raw2probability_bernoulli_orientation():
    """With the GBM binary raw convention (-f, f), P(y=1) must be sigmoid(f)
    (`GBMLoss.scala:311-316` composed with `GBMClassifier.scala:583-587`)."""
    loss = L.BernoulliLoss()
    f = jnp.asarray([[2.0]])
    raw = jnp.concatenate([-f, f], axis=1)
    p = loss.raw2probability(raw)
    assert float(p[0, 1]) == pytest.approx(float(jax.nn.sigmoid(2.0)), abs=1e-6)


def test_aggregate_loss_weighted_mean():
    loss = L.SquaredLoss()
    y = jnp.asarray([1.0, 2.0, 3.0])
    enc = loss.encode_label(y)
    pred = jnp.zeros((3, 1))
    w = jnp.asarray([1.0, 0.0, 1.0])
    got = L.aggregate_loss(loss, enc, w, pred)
    assert float(got) == pytest.approx((0.5 * 1 + 0.5 * 9) / 2.0, rel=1e-6)


def test_registry_roundtrip():
    for cfg in [
        {"name": "huber", "delta": 2.0},
        {"name": "quantile", "quantile": 0.2},
        {"name": "logloss", "num_classes": 7},
        {"name": "squared"},
    ]:
        loss = L.loss_from_config(cfg)
        assert loss.config()["name"] == cfg["name"]
