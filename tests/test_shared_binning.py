"""Shared feature binning across a tuning search (tuning.py).

Weight-mask folds fit every (param-map, fold) candidate on the identical
full ``X``, so the base learner's fit context — feature binning and bin
assignment, the dominant host-side setup cost — is computed ONCE per
search and shared.  ``share_binning`` toggles only the memoization, so
scores must be bit-identical either way.
"""

import numpy as np
import pytest

import spark_ensemble_tpu as se
from spark_ensemble_tpu.evaluation import (
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from spark_ensemble_tpu.models.tree import DecisionTreeRegressor
from spark_ensemble_tpu.tuning import (
    CrossValidator,
    ParamGridBuilder,
    TrainValidationSplit,
)


def _clf_data(n=500, d=8, k=3, seed=11):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    centers = rng.randn(k, d).astype(np.float32)
    y = np.argmax(X @ centers.T, axis=1).astype(np.float32)
    return X, y


@pytest.fixture
def ctx_counter(monkeypatch):
    """Count DecisionTreeRegressor.make_fit_ctx calls (the binning pass of
    every GBM base fit in these tests)."""
    calls = {"n": 0}
    orig = DecisionTreeRegressor.make_fit_ctx

    def counting(self, X, num_classes=None):
        calls["n"] += 1
        return orig(self, X, num_classes)

    monkeypatch.setattr(DecisionTreeRegressor, "make_fit_ctx", counting)
    return calls


def test_cv_shared_binning_single_pass_and_identical_scores(ctx_counter):
    X, y = _clf_data()
    grid = ParamGridBuilder().add_grid("num_base_learners", [2, 4]).build()
    ev = MulticlassClassificationEvaluator(metric="accuracy")

    def run(share):
        ctx_counter["n"] = 0
        cv = CrossValidator(
            estimator=se.GBMClassifier(),
            evaluator=ev,
            estimator_param_maps=grid,
            num_folds=3,
            share_binning=share,
        )
        model = cv.fit(X, y)
        return model, ctx_counter["n"]

    shared, n_shared = run(True)
    unshared, n_unshared = run(False)
    # 2 maps x 3 folds + 1 best-map refit = 7 independent binning passes
    # without sharing; exactly one with
    assert n_shared == 1
    assert n_unshared == 2 * 3 + 1
    assert shared.avg_metrics == unshared.avg_metrics
    assert shared.fold_metrics == unshared.fold_metrics
    assert shared.best_index == unshared.best_index


def test_tvs_shared_binning_single_pass_and_identical_scores(ctx_counter):
    rng = np.random.RandomState(5)
    X = rng.randn(400, 6).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2).astype(np.float32)
    grid = ParamGridBuilder().add_grid("learning_rate", [0.1, 0.3]).build()
    ev = RegressionEvaluator(metric="rmse")

    def run(share):
        ctx_counter["n"] = 0
        tvs = TrainValidationSplit(
            estimator=se.GBMRegressor(num_base_learners=3),
            evaluator=ev,
            estimator_param_maps=grid,
            share_binning=share,
        )
        model = tvs.fit(X, y)
        return model, ctx_counter["n"]

    shared, n_shared = run(True)
    unshared, n_unshared = run(False)
    assert n_shared == 1
    assert n_unshared == 2 + 1  # 2 maps + best refit
    assert shared.validation_metrics == unshared.validation_metrics
    assert shared.best_index == unshared.best_index


def test_cv_with_sample_weights_identical(ctx_counter):
    X, y = _clf_data(n=360)
    w = np.random.RandomState(0).uniform(0.5, 2.0, size=X.shape[0])
    ev = MulticlassClassificationEvaluator(metric="accuracy")

    def run(share):
        cv = CrossValidator(
            estimator=se.GBMClassifier(num_base_learners=3),
            evaluator=ev,
            num_folds=2,
            share_binning=share,
        )
        return cv.fit(X, y, sample_weight=w)

    assert run(True).avg_metrics == run(False).avg_metrics
