"""Weighted median/quantile and sampling property tests (mirrors
`UtilsSuite.scala:29-67` and `HasSubBagSuite.scala:60-105`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_ensemble_tpu.utils.quantile import weighted_median, weighted_quantile
from spark_ensemble_tpu.utils.random import bootstrap_weights, subspace_mask


def test_weighted_median_equals_unweighted_for_unit_weights():
    rng = np.random.RandomState(0)
    for trial in range(10):
        v = rng.randn(101).astype(np.float32)  # odd count: unique median
        got = float(weighted_median(jnp.asarray(v), jnp.ones(101)))
        assert got == pytest.approx(float(np.median(v)), abs=1e-6)


def test_weighted_median_ignores_zero_weights():
    v = jnp.asarray([100.0, 1.0, 2.0, 3.0, 200.0])
    w = jnp.asarray([0.0, 1.0, 1.0, 1.0, 0.0])
    assert float(weighted_median(v, w)) == 2.0


def test_weighted_median_scale_invariant_in_weights():
    rng = np.random.RandomState(1)
    v = jnp.asarray(rng.randn(50), jnp.float32)
    w = jnp.asarray(rng.rand(50) + 0.1, jnp.float32)
    a = float(weighted_median(v, w))
    b = float(weighted_median(v, 7.3 * w))
    assert a == b


def test_weighted_median_dominant_weight():
    v = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    w = jnp.asarray([1.0, 1.0, 1.0, 10.0])
    assert float(weighted_median(v, w)) == 4.0


def test_weighted_quantile_matches_numpy_on_uniform_weights():
    rng = np.random.RandomState(2)
    v = rng.randn(500).astype(np.float32)
    for q in [0.1, 0.5, 0.9]:
        got = float(weighted_quantile(jnp.asarray(v), q))
        # inverted-CDF quantile: within one order statistic of numpy's
        expect = np.quantile(v, q, method="inverted_cdf")
        assert got == pytest.approx(float(expect), abs=1e-5)


def test_subspace_mask_expected_size_and_nonempty():
    key = jax.random.PRNGKey(0)
    d = 200
    sizes = []
    for i in range(50):
        m = subspace_mask(jax.random.fold_in(key, i), d, 0.3)
        sizes.append(int(jnp.sum(m)))
        assert sizes[-1] >= 1
    assert np.mean(sizes) == pytest.approx(0.3 * d, rel=0.15)


def test_subspace_mask_ratio_one_is_identity():
    m = subspace_mask(jax.random.PRNGKey(3), 17, 1.0)
    assert bool(jnp.all(m))


def test_bootstrap_weights_poisson_expectation():
    w = bootstrap_weights(jax.random.PRNGKey(0), 20000, True, 0.7)
    assert float(jnp.mean(w)) == pytest.approx(0.7, rel=0.05)
    assert float(jnp.max(w)) > 1.0  # replacement -> counts can exceed 1


def test_bootstrap_weights_bernoulli():
    w = bootstrap_weights(jax.random.PRNGKey(0), 20000, False, 0.4)
    assert set(np.unique(np.asarray(w))) <= {0.0, 1.0}
    assert float(jnp.mean(w)) == pytest.approx(0.4, rel=0.05)


def test_infer_num_classes_validation():
    """Label validation parity (`BoostingClassifier.scala:152-161`): labels
    must be finite non-negative integers, optionally within [0, K)."""
    import pytest

    from spark_ensemble_tpu.models.base import infer_num_classes

    assert infer_num_classes([0, 1, 2]) == 3
    assert infer_num_classes([0, 0, 0]) == 2  # degenerate: still binary-shaped
    assert infer_num_classes([0, 1], num_classes=5) == 5
    with pytest.raises(ValueError, match="non-negative integers"):
        infer_num_classes([0.5, 1.0])
    with pytest.raises(ValueError, match="non-negative integers"):
        infer_num_classes([-1, 0, 1])
    with pytest.raises(ValueError, match="finite"):
        infer_num_classes([0.0, float("nan")])
    with pytest.raises(ValueError, match="num_classes"):
        infer_num_classes([0, 1, 4], num_classes=3)


def test_classifier_fit_rejects_bad_labels():
    import numpy as np
    import pytest

    import spark_ensemble_tpu as se

    X = np.random.RandomState(0).randn(50, 3).astype(np.float32)
    y_bad = np.linspace(0, 1, 50).astype(np.float32)
    with pytest.raises(ValueError, match="non-negative integers"):
        se.BoostingClassifier(num_base_learners=2).fit(X, y_bad)
    # explicit num_classes sizes the model even when the top class is absent
    y = (X[:, 0] > 0).astype(np.float32)
    m = se.BaggingClassifier(num_base_learners=2).fit(X, y, num_classes=3)
    assert m.num_classes == 3
    assert m.predict_raw(X[:5]).shape == (5, 3)


@pytest.mark.slow
def test_feature_metadata_propagates_through_subspaces(tmp_path):
    """`Utils.getFeaturesMetadata` analogue (`Utils.scala:42-61`): names
    re-index through member subspace masks and survive save/load."""
    import numpy as np

    import spark_ensemble_tpu as se
    from spark_ensemble_tpu.utils.features import FeatureMetadata

    md = FeatureMetadata.resolve(["a", "b", "c", "d"], 4)
    assert md.select(np.array([True, False, True, False])).names == ["a", "c"]
    assert md.select(np.array([3, 1])).names == ["d", "b"]
    assert FeatureMetadata.default(2).names == ["f0", "f1"]

    rng = np.random.RandomState(0)
    X = rng.randn(300, 6).astype(np.float32)
    y = (X[:, 0] + 0.1 * rng.randn(300)).astype(np.float32)
    names = [f"col{i}" for i in range(6)]
    model = se.BaggingRegressor(
        num_base_learners=3, subspace_ratio=0.5, feature_names=names
    ).fit(X, y)
    masks = np.asarray(model.params["masks"])
    for i in range(3):
        assert model.member_feature_names(i) == [
            n for n, m in zip(names, masks[i]) if m
        ]
    path = str(tmp_path / "m")
    model.save(path)
    loaded = se.load(path)
    assert loaded.feature_names == names
    assert loaded.member_feature_names(0) == model.member_feature_names(0)

    import pytest

    with pytest.raises(ValueError, match="feature_names"):
        _ = se.DecisionTreeRegressor(feature_names=["x"]).fit(X, y).feature_metadata


def test_logistic_no_intercept_scores_through_origin():
    """fit_intercept=False pins the intercept to zero DURING optimization
    (scale-only standardization — centering would smuggle an implicit
    intercept back in).  Zero input must then score exactly zero raw margin
    difference between symmetric points, and the model must still separate
    data whose boundary passes through the origin."""
    import numpy as np

    from spark_ensemble_tpu.models.linear import LogisticRegression

    rng = np.random.RandomState(0)
    X = rng.randn(600, 4).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)  # boundary at 0
    for solver in ("newton", "lbfgs"):
        m = LogisticRegression(fit_intercept=False, solver=solver).fit(X, y)
        assert float(np.asarray(m.params["intercept"]).max()) == 0.0
        assert float(np.asarray(m.params["intercept"]).min()) == 0.0
        # raw scores are odd under x -> -x when there is no intercept
        raw_p = np.asarray(m.predict_raw(X[:50]))
        raw_n = np.asarray(m.predict_raw(-X[:50]))
        np.testing.assert_allclose(
            raw_p - raw_p.mean(axis=1, keepdims=True),
            -(raw_n - raw_n.mean(axis=1, keepdims=True)),
            rtol=1e-4, atol=1e-4,
        )
        acc = float(np.mean(np.asarray(m.predict(X)) == y))
        assert acc > 0.95, (solver, acc)
