"""Weighted median/quantile and sampling property tests (mirrors
`UtilsSuite.scala:29-67` and `HasSubBagSuite.scala:60-105`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_ensemble_tpu.utils.quantile import weighted_median, weighted_quantile
from spark_ensemble_tpu.utils.random import bootstrap_weights, subspace_mask


def test_weighted_median_equals_unweighted_for_unit_weights():
    rng = np.random.RandomState(0)
    for trial in range(10):
        v = rng.randn(101).astype(np.float32)  # odd count: unique median
        got = float(weighted_median(jnp.asarray(v), jnp.ones(101)))
        assert got == pytest.approx(float(np.median(v)), abs=1e-6)


def test_weighted_median_ignores_zero_weights():
    v = jnp.asarray([100.0, 1.0, 2.0, 3.0, 200.0])
    w = jnp.asarray([0.0, 1.0, 1.0, 1.0, 0.0])
    assert float(weighted_median(v, w)) == 2.0


def test_weighted_median_scale_invariant_in_weights():
    rng = np.random.RandomState(1)
    v = jnp.asarray(rng.randn(50), jnp.float32)
    w = jnp.asarray(rng.rand(50) + 0.1, jnp.float32)
    a = float(weighted_median(v, w))
    b = float(weighted_median(v, 7.3 * w))
    assert a == b


def test_weighted_median_dominant_weight():
    v = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    w = jnp.asarray([1.0, 1.0, 1.0, 10.0])
    assert float(weighted_median(v, w)) == 4.0


def test_weighted_quantile_matches_numpy_on_uniform_weights():
    rng = np.random.RandomState(2)
    v = rng.randn(500).astype(np.float32)
    for q in [0.1, 0.5, 0.9]:
        got = float(weighted_quantile(jnp.asarray(v), q))
        # inverted-CDF quantile: within one order statistic of numpy's
        expect = np.quantile(v, q, method="inverted_cdf")
        assert got == pytest.approx(float(expect), abs=1e-5)


def test_subspace_mask_expected_size_and_nonempty():
    key = jax.random.PRNGKey(0)
    d = 200
    sizes = []
    for i in range(50):
        m = subspace_mask(jax.random.fold_in(key, i), d, 0.3)
        sizes.append(int(jnp.sum(m)))
        assert sizes[-1] >= 1
    assert np.mean(sizes) == pytest.approx(0.3 * d, rel=0.15)


def test_subspace_mask_ratio_one_is_identity():
    m = subspace_mask(jax.random.PRNGKey(3), 17, 1.0)
    assert bool(jnp.all(m))


def test_bootstrap_weights_poisson_expectation():
    w = bootstrap_weights(jax.random.PRNGKey(0), 20000, True, 0.7)
    assert float(jnp.mean(w)) == pytest.approx(0.7, rel=0.05)
    assert float(jnp.max(w)) > 1.0  # replacement -> counts can exceed 1


def test_bootstrap_weights_bernoulli():
    w = bootstrap_weights(jax.random.PRNGKey(0), 20000, False, 0.4)
    assert set(np.unique(np.asarray(w))) <= {0.0, 1.0}
    assert float(jnp.mean(w)) == pytest.approx(0.4, rel=0.05)
