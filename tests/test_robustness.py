"""Fault-tolerant training runtime tests (docs/robustness.md): input
validation, numeric-guard policies on all four families, retry/backoff,
crash-consistent checkpoint fallback, kill-and-resume equivalence, the
chaos harness's determinism, and the ``fit_aborted`` terminal event."""

import json
import os

import numpy as np
import pytest

import spark_ensemble_tpu as se
from spark_ensemble_tpu.robustness import chaos
from spark_ensemble_tpu.robustness.chaos import (
    ChaosController,
    ChaosPreemption,
    ChaosTransientError,
)
from spark_ensemble_tpu.robustness.guards import (
    NONFINITE_POLICIES,
    NonFiniteError,
    NumericGuard,
)
from spark_ensemble_tpu.robustness.retry import RetryPolicy, retry_call
from spark_ensemble_tpu.robustness.validate import validate_fit_inputs
from spark_ensemble_tpu.telemetry import record_fits
from spark_ensemble_tpu.utils.checkpoint import TrainingCheckpointer


def _data(n=120, d=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


def _cls_data(n=120, d=5, seed=0):
    X, y = _data(n, d, seed)
    return X, (y > np.median(y)).astype(np.float32)


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    """Every test leaves the process chaos-free (the env path is also
    bypassed: install(None) only clears an explicit controller, so tests
    never see a stray SE_TPU_CHAOS from the invoking shell unless they are
    the chaos CI job's tier-1 run — where the harness is the point)."""
    yield
    chaos.install(None)


def _chaos(**kw):
    kw.setdefault("rate", 1.0)
    ctl = ChaosController(seed=kw.pop("seed", 11), **kw)
    chaos.install(ctl)
    return ctl


# ---------------------------------------------------------------------------
# input validation
# ---------------------------------------------------------------------------


def test_validate_raises_on_nan_features():
    X, y = _data()
    X[3, 1] = np.nan
    with pytest.raises(ValueError, match="X contains NaN or Inf"):
        se.GBMRegressor(num_base_learners=2).fit(X, y)


def test_validate_raises_on_inf_labels():
    X, y = _data()
    y[7] = np.inf
    with pytest.raises(ValueError, match="y contains NaN or Inf"):
        se.BaggingRegressor(num_base_learners=2).fit(X, y)


def test_validate_allow_nan_escape_hatch():
    X, y = _data()
    validate_fit_inputs(X, y)  # clean passes
    X[0, 0] = np.nan
    with pytest.raises(ValueError):
        validate_fit_inputs(X, y)
    validate_fit_inputs(X, y, allow_nan=True)  # no raise


@pytest.mark.parametrize(
    "est_cls",
    [se.BoostingClassifier, se.BaggingClassifier, se.StackingClassifier],
)
def test_validate_wired_into_classifier_fits(est_cls):
    X, y = _cls_data()
    X[1, 1] = np.inf
    with pytest.raises(ValueError, match="contains NaN or Inf"):
        est_cls().fit(X, y)


# ---------------------------------------------------------------------------
# guard primitives
# ---------------------------------------------------------------------------


def test_guard_rejects_unknown_policy():
    with pytest.raises(ValueError, match="on_nonfinite"):
        NumericGuard("explode")
    for p in NONFINITE_POLICIES:
        NumericGuard(p)


def test_guard_params_are_nan_only_arrays_are_strict():
    import jax.numpy as jnp

    g = NumericGuard("raise")
    # tree params legitimately carry Inf split-threshold sentinels
    params = {"thr": jnp.array([[jnp.inf, 1.0], [2.0, -jnp.inf]])}
    weights = jnp.array([0.5, 0.25])
    assert g.first_nonfinite(params, weights) is None
    # NaN in params IS a detection
    params_bad = {"thr": jnp.array([[1.0, 2.0], [jnp.nan, 3.0]])}
    assert g.first_nonfinite(params_bad, weights) == 1
    # Inf in the weight/step-size group IS a detection
    assert g.first_nonfinite(params, jnp.array([0.5, jnp.inf])) == 1
    assert g.first_nonfinite(params, jnp.array([jnp.nan, 1.0])) == 0


def test_estimator_rejects_bad_policy_param():
    with pytest.raises(ValueError):
        se.GBMRegressor(on_nonfinite="explode")


# ---------------------------------------------------------------------------
# retry/backoff
# ---------------------------------------------------------------------------


def test_retry_transient_then_success_and_delays():
    calls = []
    slept = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("transient")
        return "ok"

    policy = RetryPolicy(max_retries=3, base_delay=0.05, jitter=0.0)
    out = retry_call(flaky, policy=policy, op="t", sleep=slept.append)
    assert out == "ok"
    assert len(calls) == 3
    # exponential backoff: base, 2*base
    assert slept == pytest.approx([0.05, 0.10])


def test_retry_exhaustion_reraises():
    policy = RetryPolicy(max_retries=2, base_delay=0.0)

    def always():
        raise RuntimeError("down")

    with pytest.raises(RuntimeError, match="down"):
        retry_call(always, policy=policy, op="t", sleep=lambda s: None)


def test_retry_zero_retries_and_non_retryable():
    def boom():
        raise RuntimeError("x")

    with pytest.raises(RuntimeError):
        retry_call(boom, policy=RetryPolicy(max_retries=0), op="t")

    # ChaosPreemption deliberately does NOT derive from RuntimeError:
    # a preemption must kill the fit, not be absorbed by the retry layer
    def preempted():
        raise ChaosPreemption("gone")

    with pytest.raises(ChaosPreemption):
        retry_call(
            preempted, policy=RetryPolicy(max_retries=5),
            op="t", sleep=lambda s: None,
        )
    assert not issubclass(ChaosPreemption, RuntimeError)
    assert issubclass(ChaosTransientError, RuntimeError)


def test_retry_emits_telemetry_event():
    X, y = _data()
    _chaos(seed=7, faults=("transient",))
    with record_fits() as rec:
        se.GBMRegressor(num_base_learners=2, scan_chunk=2).fit(X, y)
    retries = [e for e in rec.events if e["event"] == "retry"]
    assert retries, "transient chaos must surface a retry event"
    ev = retries[0]
    assert ev["error_type"] == "ChaosTransientError"
    assert ev["attempt"] == 1
    assert ev["delay_s"] > 0


# ---------------------------------------------------------------------------
# chaos harness
# ---------------------------------------------------------------------------


def test_chaos_is_deterministic_and_at_most_once_per_site():
    a = ChaosController(seed=5, rate=0.5, faults=("transient",))
    b = ChaosController(seed=5, rate=0.5, faults=("transient",))
    sites = [f"site:{i}" for i in range(40)]
    for ctl in (a, b):
        for s in sites:
            try:
                ctl.transient(s)
            except ChaosTransientError:
                pass
            # second visit never fires (retries always succeed)
            ctl.transient(s)
    assert a.fired == b.fired
    assert 0 < len(a.fired) < len(sites)


def test_chaos_env_parsing(monkeypatch):
    monkeypatch.setenv("SE_TPU_CHAOS", "42")
    monkeypatch.setenv("SE_TPU_CHAOS_FAULTS", "transient,ckpt_corrupt")
    monkeypatch.setenv("SE_TPU_CHAOS_RATE", "0.25")
    chaos._env_cache = None  # drop the cached env controller
    try:
        ctl = chaos.controller()
        assert ctl.enabled
        assert ctl.seed == 42
        assert ctl.rate == 0.25
        assert ctl.faults == {"transient", "ckpt_corrupt"}
    finally:
        chaos._env_cache = None


def test_chaos_disabled_by_default(monkeypatch):
    monkeypatch.delenv("SE_TPU_CHAOS", raising=False)
    chaos._env_cache = None
    assert not chaos.controller().enabled


def test_chaos_log_jsonl(tmp_path):
    log = tmp_path / "faults.jsonl"
    ctl = ChaosController(
        seed=1, rate=1.0, faults=("transient",), log_path=str(log)
    )
    with pytest.raises(ChaosTransientError):
        ctl.transient("s1")
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    assert recs and recs[0]["fault"] == "transient" and recs[0]["site"] == "s1"


# ---------------------------------------------------------------------------
# checkpoint crash consistency
# ---------------------------------------------------------------------------


def _two_saves(tmp_path):
    ck = TrainingCheckpointer(str(tmp_path / "ck"), interval=1, async_save=False)
    ck.save(0, {"round_tag": 0, "v": [1.0, 2.0]})
    ck.save(1, {"round_tag": 1, "v": [3.0, 4.0]})
    return ck


def test_truncated_state_json_falls_back_to_old(tmp_path):
    ck = _two_saves(tmp_path)
    latest = os.path.join(ck.directory, "latest", "state.json")
    with open(latest, "r+b") as f:
        f.truncate(os.path.getsize(latest) // 2)
    rnd, st = ck.load_latest()
    assert rnd == 0 and st["round_tag"] == 0
    assert ck.last_load_detail == {"round": 0, "source": ".ckpt-old", "fallback": True}


def test_manifest_tamper_falls_back(tmp_path):
    ck = _two_saves(tmp_path)
    # byte-size matches but content differs -> sha256 catches it
    latest = os.path.join(ck.directory, "latest", "state.json")
    data = bytearray(open(latest, "rb").read())
    data[-2] ^= 0xFF
    with open(latest, "wb") as f:
        f.write(data)
    rnd, _ = ck.load_latest()
    assert rnd == 0 and ck.last_load_detail["fallback"] is True


def test_both_copies_corrupt_means_fresh_start(tmp_path):
    ck = _two_saves(tmp_path)
    for src in ("latest", ".ckpt-old"):
        p = os.path.join(ck.directory, src, "state.json")
        with open(p, "w") as f:
            f.write("{not json")
    assert ck.load_latest() is None


def test_clean_load_reports_latest(tmp_path):
    ck = _two_saves(tmp_path)
    rnd, st = ck.load_latest()
    assert rnd == 1 and st["round_tag"] == 1
    assert ck.last_load_detail == {"round": 1, "source": "latest", "fallback": False}


def test_chaos_ckpt_corrupt_self_heals(tmp_path):
    """A chaos-torn 'latest' costs one interval, not the run."""
    ck = TrainingCheckpointer(str(tmp_path / "ck"), interval=1, async_save=False)
    ck.save(0, {"r": 0})
    ctl = _chaos(seed=5, faults=("ckpt_corrupt",))  # tear only the 2nd save
    ck.save(1, {"r": 1})
    chaos.install(None)
    assert ctl.fired
    rnd, st = ck.load_latest()
    assert rnd == 0 and st["r"] == 0
    assert ck.last_load_detail["fallback"] is True


# ---------------------------------------------------------------------------
# guard policies end-to-end (chaos nan_grad on every family)
# ---------------------------------------------------------------------------


def test_gbm_clean_fit_identical_with_guard_on_and_off():
    X, y = _data()
    p_on = se.GBMRegressor(num_base_learners=4, scan_chunk=2).fit(X, y).predict(X)
    p_off = (
        se.GBMRegressor(num_base_learners=4, scan_chunk=2, on_nonfinite="off")
        .fit(X, y)
        .predict(X)
    )
    assert np.array_equal(np.asarray(p_on), np.asarray(p_off))


@pytest.mark.parametrize("policy", ["skip_round", "halve_step", "stop_early"])
def test_gbm_recovers_from_nan_round(policy):
    X, y = _data()
    ctl = _chaos(faults=("nan_grad",), budgets={"nan_grad": 1})
    m = se.GBMRegressor(
        num_base_learners=5, scan_chunk=2, on_nonfinite=policy
    ).fit(X, y)
    assert ctl.fired
    p = np.asarray(m.predict(X))
    assert np.all(np.isfinite(p))
    if policy == "stop_early":
        assert m.num_members < 5  # truncated to the last good round


def test_gbm_default_policy_raises_with_round_attribution():
    X, y = _data()
    _chaos(faults=("nan_grad",), budgets={"nan_grad": 1})
    with pytest.raises(NonFiniteError) as ei:
        se.GBMClassifier(num_base_learners=4, scan_chunk=2).fit(
            X, (y > 0).astype(np.float32)
        )
    assert ei.value.round_index is not None
    assert ei.value.family == "GBMClassifier"


def test_gbm_guard_emits_telemetry():
    X, y = _data()
    _chaos(faults=("nan_grad",), budgets={"nan_grad": 1})
    with record_fits() as rec:
        se.GBMRegressor(
            num_base_learners=4, scan_chunk=2, on_nonfinite="skip_round"
        ).fit(X, y)
    evs = [e for e in rec.events if e["event"] == "guard_nonfinite"]
    assert evs and evs[0]["action"] == "skip_round"


def test_boosting_true_drops_poisoned_member():
    X, y = _cls_data()
    ctl = _chaos(faults=("nan_grad",), budgets={"nan_grad": 1})
    # SAMME.R prediction ignores estimator weights, so the poisoned member
    # must be DROPPED, not zero-weighted
    m = se.BoostingClassifier(
        num_base_learners=4, scan_chunk=2, algorithm="real",
        on_nonfinite="skip_round",
    ).fit(X, y)
    assert ctl.fired
    proba = np.asarray(m.predict_proba(X))
    assert np.all(np.isfinite(proba))


def test_bagging_drops_bad_members_and_scales_probabilities():
    X, y = _cls_data()
    _chaos(seed=21, faults=("nan_grad",), budgets={"nan_grad": 1})
    m = se.BaggingClassifier(
        num_base_learners=5, voting_strategy="soft", on_nonfinite="skip_round"
    ).fit(X, y)
    assert m.num_members == 4  # one member dropped
    proba = np.asarray(m.predict_proba(X))
    assert np.all(np.isfinite(proba))
    # probabilities divide by the FITTED member count, not the param
    assert np.allclose(proba.sum(axis=-1), 1.0, atol=1e-5)


def test_stacking_drops_bad_member_keeps_consistent_layout():
    X, y = _data()
    _chaos(seed=31, faults=("nan_grad",), budgets={"nan_grad": 1})
    m = se.StackingRegressor(on_nonfinite="skip_round").fit(X, y)
    assert len(m.base_models) == 1  # one of the two defaults dropped
    assert np.all(np.isfinite(np.asarray(m.predict(X))))


def test_stacking_raise_is_default():
    X, y = _data()
    _chaos(seed=31, faults=("nan_grad",), budgets={"nan_grad": 1})
    with pytest.raises(NonFiniteError):
        se.StackingRegressor().fit(X, y)


# ---------------------------------------------------------------------------
# kill-and-resume equivalence
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "make_est",
    [
        lambda ckdir: se.GBMRegressor(
            num_base_learners=6, scan_chunk=2,
            checkpoint_dir=ckdir, checkpoint_interval=1,
        ),
        lambda ckdir: se.BoostingRegressor(
            num_base_learners=6, scan_chunk=2,
            checkpoint_dir=ckdir, checkpoint_interval=1,
        ),
    ],
    ids=["gbm", "boosting"],
)
def test_kill_and_resume_matches_uninterrupted(tmp_path, make_est):
    X, y = _data()
    ref = make_est(None).fit(X, y)
    p_ref = np.asarray(ref.predict(X))

    est = make_est(str(tmp_path / "ck"))
    _chaos(seed=3, faults=("preempt",), budgets={"preempt": 1})
    with pytest.raises(ChaosPreemption):
        est.fit(X, y)
    chaos.install(None)

    with record_fits() as rec:
        m = est.fit(X, y)  # resumes from the checkpoint
    resumes = [e for e in rec.events if e["event"] == "resume_from_checkpoint"]
    assert resumes and resumes[0]["round"] >= 1
    # deterministic replay: the resumed fit is bit-identical
    assert np.array_equal(np.asarray(m.predict(X)), p_ref)


# ---------------------------------------------------------------------------
# fit_aborted terminal event
# ---------------------------------------------------------------------------


def test_fit_aborted_event_on_midfit_failure():
    X, y = _data()
    _chaos(seed=3, faults=("preempt",), budgets={"preempt": 1})
    with record_fits() as rec:
        with pytest.raises(ChaosPreemption):
            se.GBMRegressor(num_base_learners=6, scan_chunk=2).fit(X, y)
    aborted = [e for e in rec.events if e["event"] == "fit_aborted"]
    assert len(aborted) == 1
    ev = aborted[0]
    assert ev["error_type"] == "ChaosPreemption"
    assert ev["rounds"] >= 1  # rounds completed before the preemption
    # the aborted stream has a terminal event but never a fit_end
    fit_ids = {e["fit_id"] for e in aborted}
    ends = [
        e for e in rec.events
        if e["event"] == "fit_end" and e["fit_id"] in fit_ids
    ]
    assert not ends


def test_fit_aborted_on_validation_error_has_zero_rounds():
    X, y = _data()
    X[0, 0] = np.nan
    with record_fits() as rec:
        with pytest.raises(ValueError):
            se.GBMRegressor(num_base_learners=2).fit(X, y)
    aborted = [e for e in rec.events if e["event"] == "fit_aborted"]
    # validation raises BEFORE telemetry starts: no stream, nothing to abort
    assert aborted == [] or aborted[0]["rounds"] == 0


# ---------------------------------------------------------------------------
# lookahead pipeline x chaos (docs/pipeline.md): speculation must not
# change what a fault recovery produces
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("policy", ["skip_round", "halve_step", "stop_early"])
def test_gbm_guard_recovery_identical_across_pipeline(monkeypatch, policy):
    X, y = _data()
    results = {}
    for depth in ("0", "1"):
        monkeypatch.setenv("SE_TPU_PIPELINE", depth)
        ctl = _chaos(faults=("nan_grad",), budgets={"nan_grad": 1})
        m = se.GBMRegressor(
            num_base_learners=5, scan_chunk=2, on_nonfinite=policy
        ).fit(X, y)
        assert ctl.fired
        chaos.install(None)
        results[depth] = (m.num_members, np.asarray(m.predict(X)))
    assert results["0"][0] == results["1"][0]
    assert np.array_equal(results["0"][1], results["1"][1])


def test_boosting_guard_recovery_identical_across_pipeline(monkeypatch):
    X, y = _cls_data()
    results = {}
    for depth in ("0", "1"):
        monkeypatch.setenv("SE_TPU_PIPELINE", depth)
        ctl = _chaos(faults=("nan_grad",), budgets={"nan_grad": 1})
        m = se.BoostingClassifier(
            num_base_learners=4, scan_chunk=2, algorithm="real",
            on_nonfinite="skip_round",
        ).fit(X, y)
        assert ctl.fired
        chaos.install(None)
        results[depth] = (m.num_members, np.asarray(m.predict_proba(X)))
    assert results["0"][0] == results["1"][0]
    assert np.array_equal(results["0"][1], results["1"][1])


@pytest.mark.parametrize(
    "make_est",
    [
        lambda ckdir: se.GBMRegressor(
            num_base_learners=6, scan_chunk=2,
            checkpoint_dir=ckdir, checkpoint_interval=1,
        ),
        lambda ckdir: se.BoostingRegressor(
            num_base_learners=6, scan_chunk=2,
            checkpoint_dir=ckdir, checkpoint_interval=1,
        ),
    ],
    ids=["gbm", "boosting"],
)
def test_pipelined_kill_and_resume_matches_sync(
    tmp_path, monkeypatch, make_est
):
    """Kill-and-resume under the pipeline: the checkpoint written while
    speculative chunks were in flight must hold only COMMITTED state, so
    the resumed pipelined fit lands bit-identical to an uninterrupted
    synchronous fit."""
    X, y = _data()
    monkeypatch.setenv("SE_TPU_PIPELINE", "0")
    p_ref = np.asarray(make_est(None).fit(X, y).predict(X))

    monkeypatch.setenv("SE_TPU_PIPELINE", "1")
    est = make_est(str(tmp_path / "ck"))
    _chaos(seed=3, faults=("preempt",), budgets={"preempt": 1})
    with pytest.raises(ChaosPreemption):
        est.fit(X, y)
    chaos.install(None)

    with record_fits() as rec:
        m = est.fit(X, y)  # resumes from the checkpoint, pipeline on
    resumes = [
        e for e in rec.events if e["event"] == "resume_from_checkpoint"
    ]
    assert resumes and resumes[0]["round"] >= 1
    assert np.array_equal(np.asarray(m.predict(X)), p_ref)


# ---------------------------------------------------------------------------
# fused round kernel (hist="fused") under the robustness machinery
# ---------------------------------------------------------------------------


def _fused_gbm(ckdir=None):
    kw = dict(checkpoint_dir=ckdir, checkpoint_interval=1) if ckdir else {}
    return se.GBMRegressor(
        num_base_learners=6, scan_chunk=2,
        base_learner=se.DecisionTreeRegressor(hist="fused", max_bins=16),
        **kw,
    )


def test_fused_gbm_recovers_from_nan_round():
    """The numeric guard sees the fused tier's rounds like any other: a
    chaos-poisoned round is skipped and the fit completes finite."""
    X, y = _data()
    ctl = _chaos(faults=("nan_grad",), budgets={"nan_grad": 1})
    m = _fused_gbm().copy(on_nonfinite="skip_round").fit(X, y)
    assert ctl.fired
    assert np.all(np.isfinite(np.asarray(m.predict(X))))


def test_fused_kill_and_resume_matches_uninterrupted(tmp_path):
    """Crash-consistent resume with hist='fused': the resumed fit must be
    bit-identical to an uninterrupted fused fit — the packed-bins state is
    rebuilt from data, never checkpointed, so replay determinism holds."""
    X, y = _data()
    p_ref = np.asarray(_fused_gbm().fit(X, y).predict(X))

    est = _fused_gbm(str(tmp_path / "ck"))
    _chaos(seed=3, faults=("preempt",), budgets={"preempt": 1})
    with pytest.raises(ChaosPreemption):
        est.fit(X, y)
    chaos.install(None)

    with record_fits() as rec:
        m = est.fit(X, y)  # resumes from the checkpoint
    resumes = [
        e for e in rec.events if e["event"] == "resume_from_checkpoint"
    ]
    assert resumes and resumes[0]["round"] >= 1
    assert np.array_equal(np.asarray(m.predict(X)), p_ref)
