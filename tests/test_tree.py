"""Histogram decision-tree kernel tests."""

import jax
import jax.numpy as jnp
import pytest
import numpy as np

from spark_ensemble_tpu.ops.binning import bin_features, compute_bins
from spark_ensemble_tpu.ops.tree import fit_tree, predict_tree, predict_tree_binned
from tests.conftest import rmse


def _data(n=2000, d=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (2 * X[:, 0] + np.sin(3 * X[:, 1]) + 0.1 * rng.randn(n)).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(y)


def _fit(X, y, w=None, mask=None, depth=5, bins=64):
    b = compute_bins(X, bins)
    Xb = bin_features(X, b)
    if w is None:
        w = jnp.ones(X.shape[0])
    return (
        fit_tree(Xb, y[:, None], w, b.thresholds, mask, max_depth=depth, max_bins=bins),
        Xb,
    )


def test_binned_and_raw_predict_agree():
    X, y = _data()
    tree, Xb = _fit(X, y)
    raw = predict_tree(tree, X)
    binned = predict_tree_binned(tree, Xb)
    assert float(jnp.max(jnp.abs(raw - binned))) == 0.0


def test_tree_reduces_variance():
    X, y = _data()
    tree, _ = _fit(X, y)
    pred = predict_tree(tree, X)[:, 0]
    assert rmse(pred, y) < 0.5 * float(jnp.std(y))


def test_deeper_trees_fit_better():
    X, y = _data()
    errs = []
    for depth in [1, 3, 5]:
        tree, _ = _fit(X, y, depth=depth)
        errs.append(rmse(predict_tree(tree, X)[:, 0], y))
    assert errs[0] > errs[1] > errs[2]


def test_feature_mask_excludes_features():
    X, y = _data()
    # only allow the (useless) last feature: tree must not use feature 0
    mask = jnp.zeros(X.shape[1], bool).at[-1].set(True)
    tree, _ = _fit(X, y, mask=mask)
    used = np.unique(np.asarray(tree.split_feature))
    assert set(used) <= {X.shape[1] - 1, 0} or bool(
        np.all(np.isinf(np.asarray(tree.split_threshold)) | (used == X.shape[1] - 1))
    )
    # forced-left placeholder nodes store feature 0 with +inf threshold; any
    # real split must be on the allowed feature
    real_splits = np.asarray(tree.split_feature)[
        ~np.isinf(np.asarray(tree.split_threshold))
    ]
    assert set(np.unique(real_splits)) <= {X.shape[1] - 1}


def test_zero_weight_rows_ignored():
    X, y = _data(500)
    # corrupt half the rows but zero their weights: fit must match clean fit
    y_bad = jnp.where(jnp.arange(500) < 250, y, 1000.0)
    w = (jnp.arange(500) < 250).astype(jnp.float32)
    tree_bad, _ = _fit(X, y_bad, w=w)
    pred = predict_tree(tree_bad, X[:250])[:, 0]
    assert rmse(pred, y[:250]) < float(jnp.std(y[:250]))


def test_constant_target_yields_single_leaf_value():
    X, _ = _data(300)
    y = jnp.full((300,), 3.25)
    tree, _ = _fit(X, y)
    pred = predict_tree(tree, X)
    assert float(jnp.max(jnp.abs(pred - 3.25))) < 1e-5


def test_classification_gini_one_hot():
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.randn(1500, 6), jnp.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(jnp.float32)
    Y = jax.nn.one_hot(y.astype(jnp.int32), 2)
    b = compute_bins(X, 64)
    Xb = bin_features(X, b)
    tree = fit_tree(Xb, Y, jnp.ones(1500), b.thresholds, max_depth=4, max_bins=64)
    acc = float(jnp.mean(jnp.argmax(predict_tree(tree, X), -1) == y))
    assert acc > 0.9
    # leaf values behave like class distributions
    leaves = tree.leaf_value
    assert float(jnp.min(leaves)) >= -1e-5
    assert np.allclose(np.asarray(jnp.sum(leaves, -1)), 1.0, atol=1e-4)


def test_vmap_members_match_sequential():
    X, y = _data(800)
    b = compute_bins(X, 32)
    Xb = bin_features(X, b)
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    ws = jax.vmap(lambda k: jax.random.poisson(k, 1.0, (800,)).astype(jnp.float32))(
        keys
    )
    fit_one = lambda w: fit_tree(
        Xb, y[:, None], w, b.thresholds, max_depth=4, max_bins=32
    )
    stacked = jax.vmap(fit_one)(ws)
    for i in range(3):
        single = fit_one(ws[i])
        assert jnp.array_equal(stacked.split_feature[i], single.split_feature)
        assert jnp.allclose(stacked.leaf_value[i], single.leaf_value, atol=1e-5)


def test_sharded_histogram_fit_matches_single_device():
    """Data-parallel tree fit via shard_map + psum == single-device fit."""
    import functools
    from jax.sharding import Mesh, PartitionSpec as P
    from spark_ensemble_tpu.compat import shard_map

    X, y = _data(1024, 4)
    b = compute_bins(X, 32)
    Xb = bin_features(X, b)
    w = jnp.ones(1024)
    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, ("data",))

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("data", None), P("data"), P("data")),
        out_specs=P(),
    )
    def sharded_fit(Xb_s, y_s, w_s):
        return fit_tree(
            Xb_s,
            y_s[:, None],
            w_s,
            b.thresholds,
            max_depth=3,
            max_bins=32,
            axis_name="data",
        )

    sharded = sharded_fit(Xb, y, w)
    single = fit_tree(Xb, y[:, None], w, b.thresholds, max_depth=3, max_bins=32)
    assert jnp.array_equal(sharded.split_feature, single.split_feature)
    assert jnp.array_equal(sharded.split_bin, single.split_bin)
    assert jnp.allclose(sharded.leaf_value, single.leaf_value, atol=1e-4)


def test_hist_matmul_matches_scatter():
    """The MXU one-hot-matmul histogram path must build the same tree as
    the scatter path (same splits, same leaf values)."""
    X, y = _data(n=1500, d=6, seed=3)
    b = compute_bins(X, 32)
    Xb = bin_features(X, b)
    w = jnp.asarray(np.random.RandomState(0).rand(1500).astype(np.float32))
    t_scatter = fit_tree(
        Xb, y[:, None], w, b.thresholds, max_depth=4, max_bins=32, hist="scatter"
    )
    t_matmul = fit_tree(
        Xb, y[:, None], w, b.thresholds, max_depth=4, max_bins=32, hist="matmul"
    )
    np.testing.assert_array_equal(
        np.asarray(t_scatter.split_feature), np.asarray(t_matmul.split_feature)
    )
    np.testing.assert_array_equal(
        np.asarray(t_scatter.split_bin), np.asarray(t_matmul.split_bin)
    )
    np.testing.assert_allclose(
        np.asarray(t_scatter.leaf_value),
        np.asarray(t_matmul.leaf_value),
        rtol=1e-4,
        atol=1e-4,
    )


def test_hist_matmul_multioutput_and_mask():
    """Matmul path with k>1 targets and a feature mask (bagging-classifier
    shape) matches scatter."""
    rng = np.random.RandomState(1)
    X = jnp.asarray(rng.randn(1000, 5).astype(np.float32))
    ylab = rng.randint(0, 3, 1000)
    Y = jnp.asarray(np.eye(3, dtype=np.float32)[ylab])
    b = compute_bins(X, 16)
    Xb = bin_features(X, b)
    w = jnp.ones((1000,))
    mask = jnp.asarray([True, True, False, True, False])
    kw = dict(max_depth=3, max_bins=16)
    t1 = fit_tree(Xb, Y, w, b.thresholds, mask, hist="scatter", **kw)
    t2 = fit_tree(Xb, Y, w, b.thresholds, mask, hist="matmul", **kw)
    np.testing.assert_array_equal(
        np.asarray(t1.split_feature), np.asarray(t2.split_feature)
    )
    np.testing.assert_allclose(
        np.asarray(t1.leaf_value), np.asarray(t2.leaf_value), rtol=1e-4, atol=1e-4
    )


def test_fit_forest_matches_vmapped_fit_tree():
    """The fused multi-member forest fit (one histogram matmul per level for
    all members) must build the same trees as vmapping fit_tree — same
    splits, same leaf values — for both histogram backends, with per-member
    weights and feature masks."""
    from spark_ensemble_tpu.ops.tree import fit_forest

    rng = np.random.RandomState(7)
    n, d, M = 900, 6, 5
    X = jnp.asarray(rng.randn(n, d).astype(np.float32))
    b = compute_bins(X, 32)
    Xb = bin_features(X, b)
    # distinct targets + weights per member (GBM class-dim shape)
    Y = jnp.asarray(rng.randn(n, M, 1).astype(np.float32))
    w = jnp.asarray(rng.rand(n, M).astype(np.float32))
    masks = jnp.asarray(rng.rand(M, d) > 0.3)
    kw = dict(max_depth=4, max_bins=32)

    ref = jax.vmap(
        lambda Ym, wm, fm: fit_tree(Xb, Ym, wm, b.thresholds, fm, **kw),
        in_axes=(1, 1, 0),
    )(Y, w, masks)
    for hist in ("scatter", "matmul"):
        got = fit_forest(Xb, Y, w, b.thresholds, masks, hist=hist, **kw)
        np.testing.assert_array_equal(
            np.asarray(got.split_feature), np.asarray(ref.split_feature), err_msg=hist
        )
        np.testing.assert_array_equal(
            np.asarray(got.split_bin), np.asarray(ref.split_bin), err_msg=hist
        )
        np.testing.assert_allclose(
            np.asarray(got.leaf_value),
            np.asarray(ref.leaf_value),
            rtol=1e-4,
            atol=1e-4,
            err_msg=hist,
        )


def test_fit_forest_multioutput_and_shared_mask():
    """Fused forest with k>1 targets (bagging-classifier shape) and a single
    shared feature mask matches the vmapped per-tree fit."""
    from spark_ensemble_tpu.ops.tree import fit_forest

    rng = np.random.RandomState(9)
    n, d, M, K = 600, 5, 3, 4
    X = jnp.asarray(rng.randn(n, d).astype(np.float32))
    b = compute_bins(X, 16)
    Xb = bin_features(X, b)
    ylab = rng.randint(0, K, n)
    Y1 = jnp.asarray(np.eye(K, dtype=np.float32)[ylab])
    Y = jnp.broadcast_to(Y1[:, None, :], (n, M, K))
    w = jnp.asarray(rng.rand(n, M).astype(np.float32))
    mask = jnp.asarray([True, False, True, True, True])
    kw = dict(max_depth=3, max_bins=16)

    ref = jax.vmap(
        lambda Ym, wm: fit_tree(Xb, Ym, wm, b.thresholds, mask, **kw),
        in_axes=(1, 1),
    )(Y, w)
    got = fit_forest(Xb, Y, w, b.thresholds, mask, hist="matmul", **kw)
    np.testing.assert_array_equal(
        np.asarray(got.split_feature), np.asarray(ref.split_feature)
    )
    np.testing.assert_allclose(
        np.asarray(got.leaf_value), np.asarray(ref.leaf_value), rtol=1e-4, atol=1e-4
    )


def test_fit_forest_sharded_matches_single_device():
    """Fused forest under shard_map row sharding (psum histograms) == the
    single-device fused forest."""
    import functools
    from jax.sharding import Mesh, PartitionSpec as P
    from spark_ensemble_tpu.compat import shard_map
    from spark_ensemble_tpu.ops.tree import fit_forest

    rng = np.random.RandomState(13)
    n, d, M = 1024, 4, 3
    X = jnp.asarray(rng.randn(n, d).astype(np.float32))
    b = compute_bins(X, 16)
    Xb = bin_features(X, b)
    Y = jnp.asarray(rng.randn(n, M, 1).astype(np.float32))
    w = jnp.ones((n, M))
    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, ("data",))
    kw = dict(max_depth=3, max_bins=16, hist="matmul")

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("data", None), P("data", None, None), P("data", None)),
        out_specs=P(),
    )
    def sharded(Xb_s, Y_s, w_s):
        return fit_forest(Xb_s, Y_s, w_s, b.thresholds, axis_name="data", **kw)

    got = sharded(Xb, Y, w)
    ref = fit_forest(Xb, Y, w, b.thresholds, **kw)
    np.testing.assert_array_equal(
        np.asarray(got.split_feature), np.asarray(ref.split_feature)
    )
    np.testing.assert_allclose(
        np.asarray(got.leaf_value), np.asarray(ref.leaf_value), rtol=1e-4, atol=1e-4
    )


def test_predict_forest_fused_matches_vmapped():
    """The fused all-members predict (one column-select matmul) must equal
    the vmapped per-tree predict bit for bit, including NaN/inf routing."""
    from spark_ensemble_tpu.ops.tree import fit_forest, predict_forest, predict_tree

    rng = np.random.RandomState(21)
    n, d, M = 700, 6, 5
    X = jnp.asarray(rng.randn(n, d).astype(np.float32))
    b = compute_bins(X, 16)
    Xb = bin_features(X, b)
    Y = jnp.asarray(rng.randn(n, M, 2).astype(np.float32))
    w = jnp.asarray(rng.rand(n, M).astype(np.float32))
    trees = fit_forest(Xb, Y, w, b.thresholds, max_depth=4, max_bins=16)

    Xq = np.asarray(X[:64]).copy()
    Xq[0, 1] = np.nan
    Xq[1, 2] = np.inf
    Xq[2, 0] = -np.inf
    Xq = jnp.asarray(Xq)
    ref = jax.vmap(lambda t: predict_tree(t, Xq))(trees)
    got = predict_forest(trees, Xq, fused=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # auto path on CPU falls back to the vmapped predict
    auto = predict_forest(trees, Xq)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(ref))


def test_matmul_predict_matches_reference_walk():
    """The path-scoring matmul predict must equal the classic per-level heap
    walk (node = 2*node + 1 + right) bit for bit."""
    import numpy as np

    from spark_ensemble_tpu.ops.binning import bin_features, compute_bins
    from spark_ensemble_tpu.ops.tree import fit_tree, predict_tree

    rng = np.random.RandomState(11)
    X = rng.randn(512, 7).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.randn(512)).astype(np.float32)
    bins = compute_bins(X, 32)
    tree = fit_tree(
        bin_features(X, bins),
        y[:, None],
        np.ones(512, np.float32),
        bins.thresholds,
        max_depth=4,
        max_bins=32,
    )
    got = np.asarray(predict_tree(tree, X))[:, 0]

    sf = np.asarray(tree.split_feature)
    st = np.asarray(tree.split_threshold)
    lv = np.asarray(tree.leaf_value)
    leaf_first = sf.shape[0]
    node = np.zeros(512, np.int64)
    for _ in range(4):
        f = sf[node]
        thr = st[node]
        x = X[np.arange(512), f]
        node = 2 * node + np.where(x <= thr, 1, 2)
    want = lv[node - leaf_first][:, 0]
    assert np.array_equal(got, want)


def test_predict_handles_nonfinite_features():
    """Regression: NaN/inf in any feature must not poison the matmul
    selection; NaN and +inf go right at real splits, -inf goes left, like
    the classic walk."""
    import numpy as np

    from spark_ensemble_tpu.ops.binning import bin_features, compute_bins
    from spark_ensemble_tpu.ops.tree import fit_tree, predict_tree

    rng = np.random.RandomState(3)
    X = rng.randn(400, 5).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1]).astype(np.float32)
    bins = compute_bins(X, 16)
    tree = fit_tree(
        bin_features(X, bins),
        y[:, None],
        np.ones(400, np.float32),
        bins.thresholds,
        max_depth=3,
        max_bins=16,
    )
    Xq = X[:4].copy()
    Xq[0, 2] = np.nan
    Xq[1, 0] = np.inf
    Xq[2, 3] = -np.inf
    out = np.asarray(predict_tree(tree, Xq))
    assert np.all(np.isfinite(out)), out

    sf = np.asarray(tree.split_feature)
    st = np.asarray(tree.split_threshold)
    lv = np.asarray(tree.leaf_value)
    Xc = np.nan_to_num(Xq, nan=3.4028235e38, posinf=3.4028235e38, neginf=-3.4028235e38)
    node = np.zeros(4, np.int64)
    for _ in range(3):
        x = Xc[np.arange(4), sf[node]]
        node = 2 * node + np.where(x <= st[node], 1, 2)
    want = lv[node - sf.shape[0]]
    assert np.array_equal(out, want)


def test_deep_tree_predict_uses_walk_fallback():
    """Regression: depth > 10 must not build the 4^depth path matrix; the
    walk fallback serves deep trees with identical semantics."""
    import numpy as np

    from spark_ensemble_tpu.ops.binning import bin_features, compute_bins
    from spark_ensemble_tpu.ops.tree import (
        _MATMUL_PREDICT_MAX_DEPTH,
        fit_tree,
        predict_tree,
        predict_tree_binned,
    )

    depth = _MATMUL_PREDICT_MAX_DEPTH + 2
    rng = np.random.RandomState(5)
    X = rng.randn(300, 4).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] ** 2).astype(np.float32)
    bins = compute_bins(X, 8)
    Xb = bin_features(X, bins)
    tree = fit_tree(
        Xb, y[:, None], np.ones(300, np.float32), bins.thresholds,
        max_depth=depth, max_bins=8,
    )
    out = np.asarray(predict_tree(tree, X))
    assert out.shape == (300, 1)
    assert np.all(np.isfinite(out))
    # binned and raw predicts agree (same routing on in-range data)
    outb = np.asarray(predict_tree_binned(tree, Xb))
    assert np.allclose(out, outb)


def test_hist_precision_tiers():
    """'high'/'default' statistic-matmul precisions produce valid trees
    whose quality degrades gracefully; 'highest' stays the bit-exact
    reference tier.  (On CPU all tiers execute as f32 — exactness across
    tiers here; the distinction is TPU MXU passes.)"""
    import numpy as np

    from spark_ensemble_tpu.models.tree import DecisionTreeRegressor

    rng = np.random.RandomState(0)
    X = rng.randn(800, 6).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(2 * X[:, 1]) + 0.05 * rng.randn(800)).astype(
        np.float32
    )
    preds = {}
    for tier in ("highest", "high", "default"):
        m = DecisionTreeRegressor(hist_precision=tier).fit(X, y)
        p = np.asarray(m.predict(X))
        rmse = float(np.sqrt(np.mean((p - y) ** 2)))
        assert rmse < 0.6, (tier, rmse)
        preds[tier] = p
    # CPU backend: every tier runs the same f32 dot -> identical trees
    np.testing.assert_allclose(preds["highest"], preds["high"], atol=1e-6)


def test_hist_precision_param_validated_and_persisted(tmp_path):
    import numpy as np
    import pytest

    import spark_ensemble_tpu as se
    from spark_ensemble_tpu.models.tree import DecisionTreeRegressor

    with pytest.raises(ValueError):
        DecisionTreeRegressor(hist_precision="bf16")
    rng = np.random.RandomState(1)
    X = rng.randn(100, 3).astype(np.float32)
    y = X[:, 0].astype(np.float32)
    m = DecisionTreeRegressor(hist_precision="high").fit(X, y)
    m.save(str(tmp_path / "t"))
    m2 = se.load(str(tmp_path / "t"))
    assert m2.hist_precision == "high"
    np.testing.assert_array_equal(np.asarray(m.predict(X)), np.asarray(m2.predict(X)))


def test_fast_tier_matmul_prefix_sums_metric_parity():
    """Fast precision tiers compute bin prefix sums as triangular matmuls
    (MXU) instead of cumsum scans; vs the exact tier the trees may differ
    by ulp-order split flips only — model quality must match."""
    from spark_ensemble_tpu.ops.tree import fit_forest

    rng = np.random.RandomState(3)
    n, d = 900, 7
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] * 2 + np.cos(2 * X[:, 1]) + 0.05 * rng.randn(n)).astype(
        np.float32
    )
    b = compute_bins(X, 32)
    Xb = bin_features(X, b)
    w = np.ones((n,), np.float32)
    kw = dict(max_depth=4, max_bins=32, hist="matmul")
    t_exact = fit_tree(Xb, y[:, None], w, b.thresholds, **kw)
    t_fast = fit_tree(
        Xb, y[:, None], w, b.thresholds, hist_precision="high", **kw
    )
    p_exact = np.asarray(predict_tree_binned(t_exact, Xb))
    p_fast = np.asarray(predict_tree_binned(t_fast, Xb))
    r_e = float(np.sqrt(np.mean((p_exact[:, 0] - y) ** 2)))
    r_f = float(np.sqrt(np.mean((p_fast[:, 0] - y) ** 2)))
    assert abs(r_e - r_f) < 0.02 * max(r_e, r_f) + 1e-6, (r_e, r_f)

    # forest flavor: the fused fast-tier path must match the exact-tier
    # forest at the metric level too (same bar as the single-tree half)
    M = 3
    Y = np.broadcast_to(y[:, None, None], (n, M, 1)).copy()
    W = rng.rand(n, M).astype(np.float32) + 0.5
    f_exact = fit_forest(Xb, Y, W, b.thresholds, **kw)
    f_fast = fit_forest(
        Xb, Y, W, b.thresholds, hist_precision="default", **kw
    )
    import jax

    for f in (f_exact, f_fast):
        assert f.leaf_value.shape[0] == M
    pe = np.asarray(jax.vmap(
        lambda t: predict_tree_binned(t, Xb))(f_exact))
    pf = np.asarray(jax.vmap(
        lambda t: predict_tree_binned(t, Xb))(f_fast))
    for m in range(M):
        r_e = float(np.sqrt(np.mean((pe[m, :, 0] - y) ** 2)))
        r_f = float(np.sqrt(np.mean((pf[m, :, 0] - y) ** 2)))
        assert abs(r_e - r_f) < 0.03 * max(r_e, r_f) + 1e-6, (m, r_e, r_f)


@pytest.mark.slow
def test_feature_importances_gain_based():
    """Gain-based importances (Spark `featureImportances` analogue): the
    only informative feature dominates; normalized to sum 1; members
    aggregate across every tree-backed ensemble family; gains survive
    persistence; non-tree learners raise."""
    import spark_ensemble_tpu as se

    rng = np.random.RandomState(0)
    X = rng.randn(1500, 6).astype(np.float32)
    y = (X[:, 2] + 0.1 * rng.randn(1500)).astype(np.float32)
    yk = (X[:, 2] > 0).astype(np.float32)

    t = se.DecisionTreeRegressor(max_depth=4).fit(X, y)
    fi = t.feature_importances_
    assert fi.shape == (6,)
    assert abs(fi.sum() - 1.0) < 1e-9
    assert fi[2] > 0.9

    for model in (
        se.GBMRegressor(num_base_learners=4).fit(X, y),
        se.BaggingClassifier(num_base_learners=4).fit(X, yk),
        se.BoostingClassifier(num_base_learners=3).fit(X, yk),
        se.GBMClassifier(num_base_learners=3).fit(X, yk),
    ):
        efi = model.feature_importances_
        assert abs(efi.sum() - 1.0) < 1e-9, type(model).__name__
        assert efi[2] == efi.max(), (type(model).__name__, efi)

    import pytest as _pytest

    with _pytest.raises(AttributeError):
        se.MLPClassifier(max_iter=5).fit(X, yk).feature_importances_


def test_feature_importances_persist_round_trip(tmp_path):
    import spark_ensemble_tpu as se
    from spark_ensemble_tpu.utils import persist

    rng = np.random.RandomState(1)
    X = rng.randn(600, 5).astype(np.float32)
    y = (2.0 * X[:, 1] - X[:, 3] + 0.1 * rng.randn(600)).astype(np.float32)
    m = se.GBMRegressor(num_base_learners=3).fit(X, y)
    m.save(str(tmp_path / "m"))
    m2 = persist.load(str(tmp_path / "m"))
    np.testing.assert_allclose(
        m2.feature_importances_, m.feature_importances_
    )


def test_fit_tree_gain_paths_agree():
    """split_gain parity between the scatter and matmul histogram paths
    (same invariant as the split tables themselves)."""
    rng = np.random.RandomState(3)
    n, d = 1200, 5
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.05 * rng.randn(n)).astype(np.float32)
    b = compute_bins(jnp.asarray(X), 32)
    Xb = bin_features(jnp.asarray(X), b)
    w = jnp.ones((n,))
    kw = dict(max_depth=4, max_bins=32)
    t_s = fit_tree(Xb, jnp.asarray(y)[:, None], w, b.thresholds, hist="scatter", **kw)
    t_m = fit_tree(Xb, jnp.asarray(y)[:, None], w, b.thresholds, hist="matmul", **kw)
    np.testing.assert_allclose(
        np.asarray(t_s.split_gain), np.asarray(t_m.split_gain), rtol=1e-4
    )
    assert float(np.asarray(t_s.split_gain).max()) > 0


def test_load_pre_split_gain_tree_saves(tmp_path):
    """Saves made before Tree grew split_gain (round 3) must still load:
    the missing field decodes as zero gains (predictions unaffected,
    importances degrade to zeros)."""
    import json
    import os

    import spark_ensemble_tpu as se
    from spark_ensemble_tpu.utils import persist

    rng = np.random.RandomState(0)
    X = rng.randn(300, 4).astype(np.float32)
    y = (X[:, 1] + 0.1 * rng.randn(300)).astype(np.float32)
    m = se.DecisionTreeRegressor(max_depth=3).fit(X, y)
    path = str(tmp_path / "m")
    m.save(path)

    # rewrite the artifact as the OLD format: drop the split_gain field
    # from the spec and its array from the npz
    meta = json.load(open(os.path.join(path, "metadata.json")))

    def strip(spec):
        if isinstance(spec, dict):
            if "__namedtuple__" in spec:
                spec["fields"].pop("split_gain", None)
            for v in spec.values():
                strip(v)
        elif isinstance(spec, list):
            for v in spec:
                strip(v)

    strip(meta.get("learned", {}))
    json.dump(meta, open(os.path.join(path, "metadata.json"), "w"))

    m2 = persist.load(path)
    np.testing.assert_allclose(
        np.asarray(m2.predict(X)), np.asarray(m.predict(X))
    )
    assert float(np.sum(m2.feature_importances_)) == 0.0


def test_feature_importances_normalize_per_member():
    """Spark TreeEnsembleModel semantics: member trees are normalized
    BEFORE averaging, so late GBM rounds (tiny residual gains) count as
    much as round 1 — a feature split on only in later rounds must not
    vanish from the importances."""
    import spark_ensemble_tpu as se
    from spark_ensemble_tpu.ops.tree import Tree, feature_gains

    # two synthetic member trees over d=3: member 0 splits feature 0 with
    # huge gain, member 1 splits feature 2 with tiny gain
    def tree(feat, gain):
        return Tree(
            split_feature=jnp.asarray([feat], jnp.int32),
            split_bin=jnp.asarray([0], jnp.int32),
            split_threshold=jnp.asarray([0.0], jnp.float32),
            leaf_value=jnp.zeros((2, 1), jnp.float32),
            split_gain=jnp.asarray([gain], jnp.float32),
        )

    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), tree(0, 1e6), tree(2, 1e-4)
    )
    model = se.BaggingRegressor(num_base_learners=2).fit(
        np.zeros((8, 3), np.float32), np.zeros((8,), np.float32)
    )
    model.params["members"] = stacked
    fi = model.feature_importances_
    np.testing.assert_allclose(fi, [0.5, 0.0, 0.5], atol=1e-12)
    # raw gains helper keeps member axes
    assert feature_gains(stacked, 3).shape == (2, 3)


def test_histogram_subtraction_tier_matches_exact_splits():
    """Fast tiers derive right-child histograms as parent - left (one
    matmul per level over HALF the nodes); a child/parent interleave bug
    would scramble deep splits, so pin near-exact agreement with the
    full-computation exact tier on a well-separated problem."""
    rng = np.random.RandomState(7)
    n, d = 3000, 6
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + np.sin(2 * X[:, 2])).astype(np.float32)
    b = compute_bins(jnp.asarray(X), 32)
    Xb = bin_features(jnp.asarray(X), b)
    w = jnp.ones((n,))
    kw = dict(max_depth=5, max_bins=32, hist="matmul")
    t_ex = fit_tree(
        Xb, jnp.asarray(y)[:, None], w, b.thresholds,
        hist_precision="highest", **kw
    )
    t_hi = fit_tree(
        Xb, jnp.asarray(y)[:, None], w, b.thresholds,
        hist_precision="high", **kw
    )
    agree = float(
        np.mean(np.asarray(t_ex.split_feature) == np.asarray(t_hi.split_feature))
    )
    assert agree > 0.9, agree
    r_ex = rmse(predict_tree_binned(t_ex, Xb)[:, 0], y)
    r_hi = rmse(predict_tree_binned(t_hi, Xb)[:, 0], y)
    assert abs(r_ex - r_hi) < 0.03 * max(r_ex, r_hi) + 1e-6


def test_subtraction_path_empty_children_record_no_spurious_splits():
    """An empty child's derived histogram (parent - left) carries tier
    rounding noise instead of exact zeros; the tier-scaled validity floor
    must keep such nodes split-free (else garbage split_gain pollutes
    feature importances).  Construction: one binary informative feature,
    all others constant — below level 1 every node is pure, its children
    route fully left, so right children at level >= 2 are empty.

    CPU caveat: matmul Precision tiers are all f32 on CPU, so the bf16
    noise itself cannot materialize here — this pins the exact-zero
    behavior and that the floor logic traces/runs; the tier-scaled,
    carried-forward floor (`_derived_hist_weight_floor`) is sized
    analytically for the on-chip bf16 noise bound (~2^-8 relative,
    floor 1e-2 of the tree-parent weight, never decaying down a chain
    of empty nodes)."""
    n = 512
    X = np.zeros((n, 3), np.float32)
    X[: n // 2, 0] = 1.0
    y = X[:, 0].copy()
    b = compute_bins(jnp.asarray(X), 16)
    Xb = bin_features(jnp.asarray(X), b)
    w = jnp.ones((n,))
    for tier in ("default", "high"):
        t = fit_tree(
            Xb, jnp.asarray(y)[:, None], w, b.thresholds,
            max_depth=4, max_bins=16, hist="matmul", hist_precision=tier,
        )
        gains = np.asarray(t.split_gain)
        assert gains[0] > 0  # the real root split
        np.testing.assert_allclose(gains[1:], 0.0, atol=1e-6)
        feats = np.asarray(t.split_feature)
        assert (feats[1:] == 0).all()  # sentinel feature 0, no real splits


# --- stream tier (row-chunked fused forest; the HBM-scale path) ------------


def test_stream_tier_matches_matmul(monkeypatch):
    """hist='stream' == the dense matmul tier: identical splits, close
    leaves — across multiple chunks WITH padding (n=1000 at chunk=128)."""
    import spark_ensemble_tpu.ops.tree as T

    monkeypatch.setattr(T, "_STREAM_CHUNK_ROWS", 128)
    rng = np.random.RandomState(21)
    n, d, M, k, B = 1000, 6, 3, 2, 16
    X = jnp.asarray(rng.randn(n, d).astype(np.float32))
    b = compute_bins(X, B)
    Xb = bin_features(X, b)
    Y = jnp.asarray((rng.randint(-16, 17, size=(n, M, k)) / 8.0).astype(np.float32))
    w = jnp.asarray((rng.randint(0, 3, size=(n, M)) / 2.0).astype(np.float32))
    kw = dict(max_depth=4, max_bins=B)
    dense = T.fit_forest(
        Xb, Y, w, b.thresholds, hist="matmul", **kw
    )
    stream = T.fit_forest(
        Xb, Y, w, b.thresholds, hist="stream", **kw
    )
    np.testing.assert_array_equal(
        np.asarray(dense.split_feature), np.asarray(stream.split_feature)
    )
    np.testing.assert_array_equal(
        np.asarray(dense.split_bin), np.asarray(stream.split_bin)
    )
    np.testing.assert_allclose(
        np.asarray(dense.leaf_value), np.asarray(stream.leaf_value),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(dense.split_threshold),
        np.asarray(stream.split_threshold), rtol=1e-5,
    )


def test_stream_tier_sharded_matches_single_device(monkeypatch):
    """Stream tier under shard_map row sharding: the per-level histogram
    psum happens AFTER the chunk scan, so the mesh result matches the
    single-device stream fit (and the collective stays O(nodes·bins·k))."""
    import functools

    from spark_ensemble_tpu.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    import spark_ensemble_tpu.ops.tree as T

    monkeypatch.setattr(T, "_STREAM_CHUNK_ROWS", 64)
    rng = np.random.RandomState(22)
    n, d, M = 1024, 4, 3
    X = jnp.asarray(rng.randn(n, d).astype(np.float32))
    b = compute_bins(X, 16)
    Xb = bin_features(X, b)
    Y = jnp.asarray(rng.randn(n, M, 1).astype(np.float32))
    w = jnp.ones((n, M))
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    kw = dict(max_depth=3, max_bins=16, hist="stream")

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("data", None), P("data", None, None), P("data", None)),
        out_specs=P(),
    )
    def sharded(Xb_s, Y_s, w_s):
        return T.fit_forest(
            Xb_s, Y_s, w_s, b.thresholds, axis_name="data", **kw
        )

    got = sharded(Xb, Y, w)
    ref = T.fit_forest(Xb, Y, w, b.thresholds, **kw)
    np.testing.assert_array_equal(
        np.asarray(got.split_feature), np.asarray(ref.split_feature)
    )
    np.testing.assert_allclose(
        np.asarray(got.leaf_value), np.asarray(ref.leaf_value),
        rtol=1e-4, atol=1e-4,
    )


def test_fit_tree_stream_delegates(monkeypatch):
    """Single-tree hist='stream' (the fused path's M=1 case) matches the
    dense single-tree fit."""
    import spark_ensemble_tpu.ops.tree as T

    monkeypatch.setattr(T, "_STREAM_CHUNK_ROWS", 256)
    X, y = _data(n=900, d=5, seed=23)
    b = compute_bins(jnp.asarray(X), 16)
    Xb = bin_features(jnp.asarray(X), b)
    w = jnp.ones((X.shape[0],))
    kw = dict(max_depth=4, max_bins=16)
    dense = fit_tree(
        Xb, jnp.asarray(y)[:, None], w, b.thresholds, hist="matmul", **kw
    )
    stream = fit_tree(
        Xb, jnp.asarray(y)[:, None], w, b.thresholds, hist="stream", **kw
    )
    np.testing.assert_array_equal(
        np.asarray(dense.split_feature), np.asarray(stream.split_feature)
    )
    np.testing.assert_allclose(
        np.asarray(dense.leaf_value), np.asarray(stream.leaf_value),
        rtol=1e-4, atol=1e-5,
    )


def test_resolve_hist_auto_prefers_stream_past_matmul_budget(monkeypatch):
    """On accelerator backends the auto policy takes the stream tier (not
    the serializing scatter path) once the bin-one-hot outgrows its
    budget; CPU keeps segment_sum at any n."""
    import spark_ensemble_tpu.ops.tree as T

    monkeypatch.setattr(T.jax, "default_backend", lambda: "tpu")
    small = T._resolve_hist("auto", 10_000, 16, 64)
    big = T._resolve_hist("auto", 4_000_000, 64, 64)
    assert (small, big) == ("matmul", "stream")
    monkeypatch.setattr(T.jax, "default_backend", lambda: "cpu")
    assert T._resolve_hist("auto", 4_000_000, 64, 64) == "scatter"


def test_stream_wins_over_pallas_precision(monkeypatch):
    """hist='stream' + hist_precision='pallas': the stream tier must be
    honored (its statistics run at the 'high' precision pallas maps to),
    not silently rerouted through the dense pallas/per-tree path whose
    one-hot operands the stream setting exists to avoid."""
    import spark_ensemble_tpu.ops.tree as T

    monkeypatch.setattr(T, "_STREAM_CHUNK_ROWS", 128)
    rng = np.random.RandomState(25)
    n, d, M, B = 700, 4, 2, 16
    X = jnp.asarray(rng.randn(n, d).astype(np.float32))
    b = compute_bins(X, B)
    Xb = bin_features(X, b)
    Y = jnp.asarray(rng.randn(n, M, 1).astype(np.float32))
    w = jnp.ones((n, M))
    kw = dict(max_depth=3, max_bins=B)
    got = T.fit_forest(
        Xb, Y, w, b.thresholds, hist="stream", hist_precision="pallas", **kw
    )
    ref = T.fit_forest(
        Xb, Y, w, b.thresholds, hist="stream", hist_precision="high", **kw
    )
    np.testing.assert_array_equal(
        np.asarray(got.split_feature), np.asarray(ref.split_feature)
    )
    np.testing.assert_allclose(
        np.asarray(got.leaf_value), np.asarray(ref.leaf_value), rtol=1e-5
    )


def test_stream_param_validated_and_plumbed():
    import spark_ensemble_tpu as se

    est = se.DecisionTreeRegressor(hist="stream")
    assert est.hist == "stream"
    with pytest.raises(ValueError):
        se.DecisionTreeRegressor(hist="nope")
    # estimator-level: a small stream-tier GBM fit tracks the default fit
    rng = np.random.RandomState(24)
    X = rng.randn(700, 6).astype(np.float32)
    yc = (X[:, 0] + 0.3 * rng.randn(700) > 0).astype(np.float32)
    cfg = dict(num_base_learners=3, learning_rate=0.5, seed=0)
    a_ref = float(np.mean(np.asarray(
        se.GBMClassifier(**cfg).fit(X, yc).predict(X)) == yc))
    a_st = float(np.mean(np.asarray(
        se.GBMClassifier(
            base_learner=se.DecisionTreeRegressor(hist="stream"), **cfg
        ).fit(X, yc).predict(X)) == yc))
    assert abs(a_ref - a_st) < 0.02, (a_ref, a_st)


def test_predict_forest_row_chunking_matches_direct(monkeypatch):
    """predict_forest lax.maps over row chunks past its one-hot budget
    (every non-GBM ensemble predict rides this); a tiny budget must not
    change a single output, incl. padding (non-divisible n)."""
    import spark_ensemble_tpu.ops.tree as T

    rng = np.random.RandomState(33)
    n, d, M = 2500, 5, 3
    X = jnp.asarray(rng.randn(n, d).astype(np.float32))
    b = compute_bins(X, 16)
    Xb = bin_features(X, b)
    Y = jnp.asarray(rng.randn(n, M, 1).astype(np.float32))
    w = jnp.ones((n, M))
    f = T.fit_forest(Xb, Y, w, b.thresholds, max_depth=4, max_bins=16,
                     hist="matmul")
    direct = T.predict_forest(f, X, fused=True)  # budget not yet patched
    monkeypatch.setattr(T, "_PREDICT_FUSED_MAX_CELLS", 64 * 1024)
    # chunk = max(1024, 65536 // (3 * 16)) = 1365 < 2500 -> chunked path
    chunked = T.predict_forest(f, X, fused=True)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(chunked))
    # parity against the unchunked reference walk
    ref = jax.vmap(lambda t: T.predict_tree(t, X))(f)
    np.testing.assert_allclose(
        np.asarray(chunked), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("hist", ["scatter", "matmul", "stream"])
def test_fit_and_direction_matches_predict(hist, monkeypatch):
    """The leaf-id-reuse direction (fit_and_direction /
    fit_many_and_directions) must be BIT-identical to predicting with the
    fitted tree — the invariant the GBM round's re-route elimination
    rests on.  Parametrized over every histogram backend: each has its
    own return_leaf plumbing (loop-final node / vmap transpose / stream
    scan reshape)."""
    import spark_ensemble_tpu as se
    import spark_ensemble_tpu.ops.tree as T

    monkeypatch.setattr(T, "_STREAM_CHUNK_ROWS", 512)  # multi-chunk + pad
    rng = np.random.RandomState(51)
    n, d, M = 1500, 6, 3
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) + 0.1 * rng.randn(n)).astype(np.float32)
    est = se.DecisionTreeRegressor(max_depth=4, hist=hist)
    ctx = est.make_fit_ctx(jnp.asarray(X))
    w = jnp.ones((n,))
    key = jax.random.PRNGKey(0)
    params, direction = est.fit_and_direction(
        ctx, jnp.asarray(y), w, None, key, jnp.asarray(X)
    )
    np.testing.assert_array_equal(
        np.asarray(direction), np.asarray(est.predict_fn(params, jnp.asarray(X)))
    )
    # fused-member version
    ys = jnp.asarray(np.stack([y, -y, y * 0.5], axis=1))
    ws = jnp.ones((n, M))
    keys = jax.random.split(key, M)
    trees, dirs = est.fit_many_and_directions(
        ctx, ys, ws, None, keys, jnp.asarray(X)
    )
    ref = jax.vmap(lambda p: est.predict_fn(p, jnp.asarray(X)))(trees).T
    np.testing.assert_array_equal(np.asarray(dirs), np.asarray(ref))

    # classifier: the argmax direction feeds boosting's discrete-round
    # weight updates — must match predict_fn exactly too
    yc = jnp.asarray((X[:, 0] > 0).astype(np.float32))
    cest = se.DecisionTreeClassifier(max_depth=3, hist=hist)
    cctx = cest.make_fit_ctx(jnp.asarray(X), num_classes=2)
    cparams, cdir = cest.fit_and_direction(
        cctx, yc, w, None, key, jnp.asarray(X)
    )
    np.testing.assert_array_equal(
        np.asarray(cdir),
        np.asarray(cest.predict_fn(cparams, jnp.asarray(X))),
    )
    # probabilities (SAMME.R's input) must match predict_proba_fn exactly
    pparams, proba = cest.fit_and_proba(
        cctx, yc, w, None, key, jnp.asarray(X)
    )
    np.testing.assert_array_equal(
        np.asarray(proba),
        np.asarray(cest.predict_proba_fn(pparams, jnp.asarray(X))),
    )


def test_stream_tier_uint8_boundary_at_256_bins(monkeypatch):
    """max_bins=256 is the uint8 storage boundary (bin ids 0..255): the
    stream tier must stay exact there, and above it (max_bins=300) the
    storage falls back to the wider dtype — both match the dense tier."""
    import spark_ensemble_tpu.ops.tree as T

    monkeypatch.setattr(T, "_STREAM_CHUNK_ROWS", 256)
    rng = np.random.RandomState(61)
    for B in (256, 300):
        n, d, M = 700, 3, 2
        X = jnp.asarray(rng.randn(n, d).astype(np.float32))
        b = compute_bins(X, B)
        Xb = bin_features(X, b)
        # force occupancy of the HIGHEST bins incl. id B-1
        assert int(jnp.max(Xb)) >= B - 2, int(jnp.max(Xb))
        Y = jnp.asarray(rng.randn(n, M, 1).astype(np.float32))
        w = jnp.ones((n, M))
        kw = dict(max_depth=3, max_bins=B)
        dense = T.fit_forest(Xb, Y, w, b.thresholds, hist="matmul", **kw)
        stream = T.fit_forest(Xb, Y, w, b.thresholds, hist="stream", **kw)
        np.testing.assert_array_equal(
            np.asarray(dense.split_feature), np.asarray(stream.split_feature),
            err_msg=f"B={B}",
        )
        np.testing.assert_array_equal(
            np.asarray(dense.split_bin), np.asarray(stream.split_bin),
            err_msg=f"B={B}",
        )
        np.testing.assert_allclose(
            np.asarray(dense.leaf_value), np.asarray(stream.leaf_value),
            rtol=1e-4, atol=1e-5,
        )
