"""Persistence round-trip tests: save -> load -> identical predictions for
every estimator family (mirrors the round-trip archetype in every reference
suite, e.g. `GBMClassifierSuite.scala:247-295`)."""

import numpy as np
import pytest

import spark_ensemble_tpu as se


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    X = rng.randn(600, 8).astype(np.float32)
    yr = (2 * X[:, 0] + np.sin(3 * X[:, 1]) + 0.1 * rng.randn(600)).astype(np.float32)
    ym = np.digitize(X[:, 0] + X[:, 1], [-1, 0, 1]).astype(np.float32)
    return X, yr, ym


MODEL_BUILDERS = [
    ("dtr", lambda X, yr, ym: se.DecisionTreeRegressor(max_depth=4).fit(X, yr)),
    ("dtc", lambda X, yr, ym: se.DecisionTreeClassifier(max_depth=4).fit(X, ym)),
    ("linreg", lambda X, yr, ym: se.LinearRegression().fit(X, yr)),
    ("logreg", lambda X, yr, ym: se.LogisticRegression(max_iter=30).fit(X, ym)),
    ("gnb", lambda X, yr, ym: se.GaussianNaiveBayes().fit(X, ym)),
    ("dummy_r", lambda X, yr, ym: se.DummyRegressor(strategy="median").fit(X, yr)),
    ("dummy_c", lambda X, yr, ym: se.DummyClassifier().fit(X, ym)),
    ("bag_r", lambda X, yr, ym: se.BaggingRegressor(num_base_learners=3).fit(X, yr)),
    ("bag_c", lambda X, yr, ym: se.BaggingClassifier(num_base_learners=3).fit(X, ym)),
    ("boost_r", lambda X, yr, ym: se.BoostingRegressor(num_base_learners=3).fit(X, yr)),
    ("boost_c", lambda X, yr, ym: se.BoostingClassifier(num_base_learners=3).fit(X, ym)),
    ("gbm_r", lambda X, yr, ym: se.GBMRegressor(num_base_learners=3).fit(X, yr)),
    ("gbm_c", lambda X, yr, ym: se.GBMClassifier(num_base_learners=3).fit(X, ym)),
    (
        "stack_r",
        lambda X, yr, ym: se.StackingRegressor(
            base_learners=[se.DecisionTreeRegressor(max_depth=3), se.LinearRegression()],
            stacker=se.LinearRegression(),
        ).fit(X, yr),
    ),
    (
        "stack_c",
        lambda X, yr, ym: se.StackingClassifier(
            base_learners=[
                se.DecisionTreeClassifier(max_depth=3),
                se.GaussianNaiveBayes(),
            ],
            stacker=se.LogisticRegression(max_iter=30),
            stack_method="proba",
        ).fit(X, ym),
    ),
]


@pytest.mark.parametrize("name,builder", MODEL_BUILDERS, ids=[n for n, _ in MODEL_BUILDERS])
def test_save_load_identical_predictions(tmp_path, data, name, builder):
    X, yr, ym = data
    model = builder(X, yr, ym)
    path = str(tmp_path / name)
    model.save(path)
    loaded = se.load(path)
    a = np.asarray(model.predict(X[:100]))
    b = np.asarray(loaded.predict(X[:100]))
    assert np.allclose(a, b, atol=1e-5), np.abs(a - b).max()
    if hasattr(model, "predict_proba"):
        pa = np.asarray(model.predict_proba(X[:50]))
        pb = np.asarray(loaded.predict_proba(X[:50]))
        assert np.allclose(pa, pb, atol=1e-5)


def test_loaded_model_params_match(tmp_path, data):
    X, yr, _ = data
    gbm = se.GBMRegressor(num_base_learners=2, learning_rate=0.7, loss="huber").fit(
        X, yr
    )
    gbm.save(str(tmp_path / "g"))
    loaded = se.load(str(tmp_path / "g"))
    assert loaded.learning_rate == 0.7
    assert loaded.loss == "huber"
    assert loaded.num_members == gbm.num_members


def test_estimator_save_load(tmp_path):
    est = se.BaggingRegressor(
        num_base_learners=7,
        base_learner=se.DecisionTreeRegressor(max_depth=3, max_bins=16),
    )
    est_path = str(tmp_path / "est")
    from spark_ensemble_tpu.utils.persist import save

    save(est, est_path)
    loaded = se.load(est_path)
    assert loaded.num_base_learners == 7
    assert loaded.base_learner.max_depth == 3
    assert loaded.base_learner.max_bins == 16
