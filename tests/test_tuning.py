"""CrossValidator / TrainValidationSplit / Pipeline behavior (the model-
selection composition the reference gets from Spark, `docs/example.md`)."""

import pytest as _pytest

pytestmark = _pytest.mark.slow


import numpy as np
import pytest

from spark_ensemble_tpu import (
    BaggingClassifier,
    CrossValidator,
    DecisionTreeRegressor,
    GBMRegressor,
    MinMaxScaler,
    MulticlassClassificationEvaluator,
    ParamGridBuilder,
    Pipeline,
    RegressionEvaluator,
    StandardScaler,
    TrainValidationSplit,
    load,
)
from tests.conftest import accuracy, rmse, split


def test_param_grid_builder():
    grid = (
        ParamGridBuilder()
        .add_grid("num_base_learners", [5, 10])
        .add_grid("learning_rate", [0.1, 0.3, 1.0])
        .base_on({"seed": 7})
        .build()
    )
    assert len(grid) == 6
    assert all(g["seed"] == 7 for g in grid)
    assert {g["learning_rate"] for g in grid} == {0.1, 0.3, 1.0}


def test_cross_validator_picks_better_depth(letter):
    X_tr, y_tr, X_te, y_te = split(*letter)
    grid = ParamGridBuilder().add_grid("num_base_learners", [1, 8]).build()
    cv = CrossValidator(
        estimator=BaggingClassifier(subspace_ratio=0.6, subsample_ratio=0.7),
        estimator_param_maps=grid,
        evaluator=MulticlassClassificationEvaluator(metric="accuracy"),
        num_folds=3,
        seed=0,
    )
    cv_model = cv.fit(X_tr, y_tr)
    assert len(cv_model.avg_metrics) == 2
    # more members should win, and the refit model should predict well
    assert cv_model.best_index == 1
    assert cv_model.avg_metrics[1] >= cv_model.avg_metrics[0]
    assert accuracy(cv_model.predict(X_te), y_te) > 0.3


def test_train_validation_split_regression(cpusmall):
    X_tr, y_tr, X_te, y_te = split(*cpusmall)
    grid = ParamGridBuilder().add_grid("num_base_learners", [2, 20]).build()
    tvs = TrainValidationSplit(
        estimator=GBMRegressor(learning_rate=0.3),
        estimator_param_maps=grid,
        evaluator=RegressionEvaluator(metric="rmse"),
        train_ratio=0.75,
        seed=0,
    )
    model = tvs.fit(X_tr, y_tr)
    assert len(model.validation_metrics) == 2
    assert model.best_index == 1  # 20 rounds beats 2
    assert rmse(model.predict(X_te), y_te) < rmse(np.full_like(y_te, y_te.mean()), y_te)


def test_pipeline_scaler_then_gbm(cpusmall):
    X_tr, y_tr, X_te, y_te = split(*cpusmall)
    pipe = Pipeline(
        stages=[StandardScaler(), GBMRegressor(num_base_learners=10, learning_rate=0.3)]
    )
    model = pipe.fit(X_tr, y_tr)
    r = rmse(model.predict(X_te), y_te)
    assert r < rmse(np.full_like(y_te, y_te.mean()), y_te)
    # scaling is affine-monotone per column; tree-based GBM is invariant, so
    # the piped model should match the unpiped one closely
    direct = GBMRegressor(num_base_learners=10, learning_rate=0.3).fit(X_tr, y_tr)
    assert r == pytest.approx(rmse(direct.predict(X_te), y_te), abs=0.3)


def test_pipeline_transformers_compose():
    rng = np.random.RandomState(0)
    X = rng.randn(100, 3).astype(np.float32) * 10 + 5
    scaled = StandardScaler().fit(X).transform(X)
    assert np.allclose(np.asarray(scaled).mean(axis=0), 0.0, atol=1e-4)
    assert np.allclose(np.asarray(scaled).std(axis=0), 1.0, atol=1e-3)
    unit = MinMaxScaler().fit(X).transform(X)
    unit = np.asarray(unit)
    assert unit.min() >= -1e-6 and unit.max() <= 1 + 1e-6


def test_pipeline_fitted_stage_passthrough(cpusmall):
    """A pre-fitted Model stage must pass through untouched, never re-fit
    (Spark semantics), and transform() on a predictor-ending pipeline
    returns the feature matrix."""
    X_tr, y_tr, X_te, _ = split(*cpusmall)
    fitted_tree = DecisionTreeRegressor(max_depth=3).fit(X_tr, y_tr)
    pm = Pipeline(stages=[fitted_tree]).fit(X_tr[:100], y_tr[:100] * 0.0)
    np.testing.assert_allclose(
        np.asarray(pm.predict(X_te)), np.asarray(fitted_tree.predict(X_te)), rtol=1e-6
    )
    # predictor-final pipeline: transform applies the feature stages only
    pm2 = Pipeline(
        stages=[StandardScaler(), GBMRegressor(num_base_learners=2)]
    ).fit(X_tr[:500], y_tr[:500])
    feats = np.asarray(pm2.transform(X_te[:50]))
    assert feats.shape == X_te[:50].shape
    # and a fitted pipeline nests as a stage of another pipeline
    outer = Pipeline(stages=[pm2.stage_models[0], DecisionTreeRegressor(max_depth=2)])
    outer_model = outer.fit(X_tr[:500], y_tr[:500])
    assert np.asarray(outer_model.predict(X_te[:50])).shape == (50,)


def test_cv_model_with_estimator_grid_saves(tmp_path, cpusmall):
    """A grid sweeping estimator-valued params must not break save()."""
    X_tr, y_tr, _, _ = split(*cpusmall)
    grid = [
        {"base_learner": DecisionTreeRegressor(max_depth=2)},
        {"base_learner": DecisionTreeRegressor(max_depth=5)},
    ]
    tvs = TrainValidationSplit(
        estimator=GBMRegressor(num_base_learners=2, learning_rate=0.5),
        estimator_param_maps=grid,
        evaluator=RegressionEvaluator(metric="rmse"),
        seed=0,
    )
    model = tvs.fit(X_tr[:1500], y_tr[:1500])
    path = str(tmp_path / "tvs")
    model.save(path)
    loaded = load(path)
    np.testing.assert_allclose(
        np.asarray(model.predict(X_tr[:50])),
        np.asarray(loaded.predict(X_tr[:50])),
        rtol=1e-5,
    )


def test_pipeline_save_load(tmp_path, cpusmall):
    X_tr, y_tr, X_te, _ = split(*cpusmall)
    pipe = Pipeline(
        stages=[StandardScaler(), GBMRegressor(num_base_learners=5, learning_rate=0.3)]
    )
    model = pipe.fit(X_tr, y_tr)
    path = str(tmp_path / "pipe")
    model.save(path)
    loaded = load(path)
    np.testing.assert_allclose(
        np.asarray(model.predict(X_te)), np.asarray(loaded.predict(X_te)), rtol=1e-5
    )


def test_cv_pipeline_fold_missing_top_class():
    """A tuned Pipeline gets the full label set's class count even when a
    training fold lacks the top class (num_classes plumbing through
    Pipeline.fit)."""
    import numpy as np

    import spark_ensemble_tpu as se
    from spark_ensemble_tpu.pipeline import Pipeline, StandardScaler

    rng = np.random.RandomState(0)
    X = rng.randn(120, 4).astype(np.float32)
    y = np.where(X[:, 0] > 0, 1.0, 0.0).astype(np.float32)
    y[:3] = 2.0  # rare top class: some folds won't see it
    pipe = Pipeline(stages=[StandardScaler(), se.DecisionTreeClassifier(max_depth=3)])
    assert pipe.is_classifier
    cv = se.CrossValidator(
        estimator=pipe,
        estimator_param_maps=[{}],
        evaluator=se.MulticlassClassificationEvaluator(metric="accuracy"),
        num_folds=4,
    )
    model = cv.fit(X, y)
    assert model.best_model.num_classes == 3


@pytest.mark.parametrize("num_folds", [2, 4])
def test_cv_megabatch_bit_identical_across_grids_and_folds(num_folds):
    """Property pin (docs/selection.md#megabatch-sweeps): for any grid of
    batchable params and any fold count, megabatch CV must produce
    avg_metrics and best_index BIT-identical to the sequential loop —
    the config axis is batching, never a numerics change."""
    rng = np.random.RandomState(num_folds)
    X = rng.randn(240, 6).astype(np.float32)
    y = (X[:, 0] - 2.0 * X[:, 1] + 0.1 * rng.randn(240)).astype(np.float32)
    grid = (
        ParamGridBuilder()
        .add_grid("learning_rate", [0.05, 0.3])
        .add_grid("num_base_learners", [2, 4])
        .add_grid("subsample_ratio", [0.7, 1.0])
        .build()
    )
    kw = dict(
        estimator=GBMRegressor(seed=3),
        estimator_param_maps=grid,
        evaluator=RegressionEvaluator(metric="rmse"),
        num_folds=num_folds,
        seed=num_folds,
    )
    seq = CrossValidator(megabatch="off", **kw).fit(X, y)
    mb = CrossValidator(megabatch="on", **kw).fit(X, y)
    assert seq.avg_metrics == mb.avg_metrics
    assert seq.best_index == mb.best_index


def test_megabatch_sweep_patience_property_random_configs():
    """Randomized early-stopping property: candidates drawing random
    batchable params (including num_rounds patience and validation_tol)
    with a validation split must stop at exactly the sequential round and
    match the sequential model bit for bit, lane by lane."""
    import jax

    from spark_ensemble_tpu.models.gbm_sweep import fit_sweep

    rng = np.random.RandomState(7)
    X = rng.randn(160, 6).astype(np.float32)
    y = (np.sin(X[:, 0]) + X[:, 1] * X[:, 2] + 0.2 * rng.randn(160)).astype(
        np.float32
    )
    vi = rng.rand(160) < 0.25
    base = GBMRegressor(seed=0)
    cands = [
        base.copy(
            learning_rate=float(rng.choice([0.05, 0.1, 0.3, 0.6])),
            seed=int(rng.randint(100)),
            subsample_ratio=float(rng.choice([0.6, 0.8, 1.0])),
            subspace_ratio=float(rng.choice([0.7, 1.0])),
            num_base_learners=int(rng.randint(3, 10)),
            num_rounds=int(rng.choice([1, 2, 3])),
            validation_tol=float(rng.choice([0.01, 0.1, 0.3])),
        )
        for _ in range(6)
    ]
    models = fit_sweep([e.copy() for e in cands], X, y,
                       validation_indicator=vi)
    stop_rounds = set()
    for est, m in zip(cands, models):
        ref = est.fit(X, y, validation_indicator=vi)
        assert m.num_members == ref.num_members
        stop_rounds.add(m.num_members)
        for a, b in zip(
            jax.tree_util.tree_leaves(m.params),
            jax.tree_util.tree_leaves(ref.params),
        ):
            assert np.array_equal(
                np.asarray(a), np.asarray(b), equal_nan=True
            )
    # the draw must actually exercise divergent stopping, or the property
    # silently weakens to the lockstep case
    assert len(stop_rounds) > 1


def test_cv_and_pipeline_mesh_passthrough():
    """mesh= flows from CrossValidator / Pipeline into every mesh-aware
    estimator fit — a CV sweep over a distributed GBM trains each
    (param-map, fold) candidate on the mesh, like Spark CV launching
    cluster jobs per fold."""
    import numpy as np

    from spark_ensemble_tpu import GBMClassifier
    from spark_ensemble_tpu.evaluation import MulticlassClassificationEvaluator
    from spark_ensemble_tpu.parallel.mesh import data_member_mesh
    from spark_ensemble_tpu.pipeline import Pipeline, StandardScaler
    from spark_ensemble_tpu.tuning import CrossValidator, ParamGridBuilder

    rng = np.random.RandomState(6)
    n, d, k = 640, 6, 3
    X = rng.randn(n, d).astype(np.float32)
    centers = rng.randn(k, d).astype(np.float32)
    y = np.argmax(X @ centers.T + 0.5 * rng.randn(n, k), axis=1).astype(
        np.float32
    )
    mesh = data_member_mesh(8, member=1)
    grid = ParamGridBuilder().add_grid("learning_rate", [0.3, 1.0]).build()
    cv = CrossValidator(
        estimator=GBMClassifier(num_base_learners=2, loss="logloss"),
        evaluator=MulticlassClassificationEvaluator(metric="accuracy"),
        estimator_param_maps=grid,
        num_folds=2,
        seed=0,
    )
    m = cv.fit(X, y, mesh=mesh)
    assert len(m.avg_metrics) == 2
    assert max(m.avg_metrics) > 0.7

    pipe = Pipeline(stages=[
        StandardScaler(),
        GBMClassifier(num_base_learners=2, loss="logloss"),
    ])
    pm = pipe.fit(X, y, mesh=mesh)
    acc = float(np.mean(np.asarray(pm.predict(X)) == y))
    assert acc > 0.7
