"""Gradient-based row sampling (docs/sampling.md): GOSS/MVS selection
statistics, bit-identity of the untouched path, composition with bagging
/ CV weight masks / the pipelined executor, the O(1)-programs bucket
ladder, piecewise-linear leaves, and kill-and-resume on a sampled fit.

The load-bearing pins:

- ``sampling="none"`` + ``leaf_model="constant"`` is BIT-identical to a
  default fit — the sampling stage must be unreachable, not merely
  inert, on the default path.
- two GOSS rate pairs landing in the same pow2 bucket re-enter the SAME
  compiled program set (rates are traced operands; the graftlint
  ``sampling`` contract pins the same thing at tier 2).
- a sampled fit is deterministic, composes with ``subsample_ratio`` and
  zero-weight rows (dead rows never survive compaction), and replays
  bit-identically through checkpoint resume.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import spark_ensemble_tpu as se
from spark_ensemble_tpu import autotune
from spark_ensemble_tpu.models.base import observe_program_calls
from spark_ensemble_tpu.models.gbm import (
    GBMClassifier,
    GBMRegressor,
    _sample_compact,
    _sample_pow2_bucket,
)
from spark_ensemble_tpu.robustness import chaos
from spark_ensemble_tpu.robustness.chaos import ChaosController, ChaosPreemption
from spark_ensemble_tpu.telemetry import record_fits

pytestmark = pytest.mark.slow


def _data(n=400, d=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


def _cls_data(n=400, d=6, seed=0):
    X, y = _data(n, d, seed)
    return X, (y > np.median(y)).astype(np.float32)


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    yield
    chaos.install(None)


# ---------------------------------------------------------------------------
# selection helper statistics
# ---------------------------------------------------------------------------


def test_pow2_bucket_ladder():
    assert _sample_pow2_bucket(1000, 300, 256) == 512
    assert _sample_pow2_bucket(1000, 100, 256) == 256  # floored
    assert _sample_pow2_bucket(1000, 3, 1) == 4
    assert _sample_pow2_bucket(100, 300, 256) == 100  # clamped to n
    # the same bucket serves a band of rates: O(1) traced programs
    assert _sample_pow2_bucket(1000, 260, 256) == _sample_pow2_bucket(
        1000, 510, 256
    )


def _goss_samp(k_top, k_rand, amp):
    return (
        jnp.int32(k_top), jnp.int32(k_rand),
        jnp.float32(amp), jnp.float32(0.0),
    )


def test_goss_selection_exact_counts_and_top_rows():
    n, k_top, k_rand = 1000, 200, 100
    amp = (1.0 - 0.2) / 0.1
    rng = np.random.default_rng(0)
    score = jnp.asarray(np.abs(rng.normal(size=n)).astype(np.float32))
    alive = jnp.ones(n, bool)
    m = _sample_pow2_bucket(n, k_top + k_rand, 256)
    idx, mult = _sample_compact(
        "goss", score, alive, jax.random.PRNGKey(0), m,
        _goss_samp(k_top, k_rand, amp),
    )
    idx, mult = np.asarray(idx), np.asarray(mult)
    s = np.asarray(score)
    assert int(np.sum(mult == 1.0)) == k_top
    assert int(np.sum(np.isclose(mult, amp))) == k_rand
    assert int(np.sum(mult == 0.0)) == m - k_top - k_rand
    # the unit-weight rows ARE the |grad| top set, gathered rank-first
    assert set(idx[:k_top].tolist()) == set(np.argsort(-s)[:k_top].tolist())
    assert len(set(idx.tolist())) == m  # no duplicate gathers


def test_goss_amplification_unbiased():
    """E[amplified small-grad mass] == the true non-top mass (the (1-a)/b
    reweighting that keeps split gains unbiased, arXiv 1911.08820)."""
    n, k_top, k_rand = 600, 120, 60
    amp = (1.0 - 0.2) / 0.1
    rng = np.random.default_rng(1)
    s = np.abs(rng.normal(size=n)).astype(np.float32)
    score, alive = jnp.asarray(s), jnp.ones(n, bool)
    m = _sample_pow2_bucket(n, k_top + k_rand, 64)
    top = np.argsort(-s)[:k_top]
    rest_true = float(np.sum(s) - np.sum(s[top]))
    est = []
    for i in range(80):
        idx, mult = _sample_compact(
            "goss", score, alive, jax.random.PRNGKey(i), m,
            _goss_samp(k_top, k_rand, amp),
        )
        idx, mult = np.asarray(idx), np.asarray(mult)
        est.append(float(np.sum(s[idx] * mult) - np.sum(s[top])))
    assert abs(np.mean(est) - rest_true) / rest_true < 0.1


def test_mvs_expected_size_and_mass():
    """MVS keeps ~k rows in expectation and its importance weights
    preserve the total sampling-probability mass (unbiasedness)."""
    n, k, lam = 600, 200, 0.1
    rng = np.random.default_rng(2)
    g = np.abs(rng.normal(size=n)).astype(np.float32)
    score, alive = jnp.asarray(g), jnp.ones(n, bool)
    m = _sample_pow2_bucket(n, k, 64)
    samp = (jnp.int32(0), jnp.int32(k), jnp.float32(0.0), jnp.float32(lam))
    s_true = np.sqrt(g * g + lam)
    kept, mass = [], []
    for i in range(80):
        idx, mult = _sample_compact(
            "mvs", score, alive, jax.random.PRNGKey(i), m, samp
        )
        idx, mult = np.asarray(idx), np.asarray(mult)
        kept.append(int(np.sum(mult > 0)))
        mass.append(float(np.sum(s_true[idx] * mult)))
    assert abs(np.mean(kept) - k) < 0.1 * k
    total = float(np.sum(s_true))
    assert abs(np.mean(mass) - total) / total < 0.05


@pytest.mark.parametrize("method", ["goss", "mvs"])
def test_dead_rows_never_sampled(method):
    """Rows masked out by bagging or a CV weight fold (w * bag_w == 0)
    must never reach a fitted tree with nonzero weight."""
    n = 500
    rng = np.random.default_rng(3)
    score = jnp.asarray(np.abs(rng.normal(size=n)).astype(np.float32))
    alive_np = rng.random(n) > 0.5
    samp = (
        (jnp.int32(100), jnp.int32(50), jnp.float32(8.0), jnp.float32(0.0))
        if method == "goss"
        else (jnp.int32(0), jnp.int32(150), jnp.float32(0.0), jnp.float32(0.1))
    )
    idx, mult = _sample_compact(
        method, score, jnp.asarray(alive_np), jax.random.PRNGKey(0), 256, samp
    )
    idx, mult = np.asarray(idx), np.asarray(mult)
    assert np.all(mult[~alive_np[idx]] == 0.0)


# ---------------------------------------------------------------------------
# bit-identity of the untouched path
# ---------------------------------------------------------------------------


def test_none_constant_bit_identical_to_default():
    X, y = _data()
    p_default = np.asarray(
        GBMRegressor(num_base_learners=4, seed=7).fit(X, y).predict(X)
    )
    p_explicit = np.asarray(
        GBMRegressor(
            num_base_learners=4, seed=7,
            sampling="none", leaf_model="constant",
        ).fit(X, y).predict(X)
    )
    assert np.array_equal(p_default, p_explicit)
    Xc, yc = _cls_data()
    r_default = np.asarray(
        GBMClassifier(num_base_learners=4, seed=7).fit(Xc, yc).predict_raw(Xc)
    )
    r_explicit = np.asarray(
        GBMClassifier(
            num_base_learners=4, seed=7,
            sampling="none", leaf_model="constant",
        ).fit(Xc, yc).predict_raw(Xc)
    )
    assert np.array_equal(r_default, r_explicit)


# ---------------------------------------------------------------------------
# sampled fits: determinism and composition
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("method", ["goss", "mvs"])
def test_sampled_fit_deterministic(method):
    X, y = _data()
    kw = dict(num_base_learners=4, seed=5, sampling=method)
    p1 = np.asarray(GBMRegressor(**kw).fit(X, y).predict(X))
    p2 = np.asarray(GBMRegressor(**kw).fit(X, y).predict(X))
    assert np.all(np.isfinite(p1))
    assert np.array_equal(p1, p2)


def test_sampling_composes_with_subsample_and_weights():
    """GOSS on top of row bagging and a zero-weight mask: runs, finite,
    deterministic — and the sampled fit only ever sees alive rows (the
    helper-level pin is test_dead_rows_never_sampled)."""
    X, y = _data()
    w = np.ones(len(y), np.float32)
    w[::3] = 0.0  # a CV-style weight-mask fold
    kw = dict(
        num_base_learners=4, seed=5, sampling="goss", subsample_ratio=0.7
    )
    p1 = np.asarray(GBMRegressor(**kw).fit(X, y, sample_weight=w).predict(X))
    p2 = np.asarray(GBMRegressor(**kw).fit(X, y, sample_weight=w).predict(X))
    assert np.all(np.isfinite(p1))
    assert np.array_equal(p1, p2)


def test_sampled_fit_pipeline_bit_identical(monkeypatch):
    """SE_TPU_PIPELINE=1 speculation over a sampled fit commits the same
    model as the synchronous path (absolute round keys; the gathered
    compaction is inside the chunk program, invisible to the executor)."""
    X, y = _data()
    kw = dict(num_base_learners=6, scan_chunk=2, seed=5, sampling="goss")
    monkeypatch.setenv("SE_TPU_PIPELINE", "0")
    p_sync = np.asarray(GBMRegressor(**kw).fit(X, y).predict(X))
    monkeypatch.setenv("SE_TPU_PIPELINE", "1")
    p_pipe = np.asarray(GBMRegressor(**kw).fit(X, y).predict(X))
    assert np.array_equal(p_sync, p_pipe)


@pytest.mark.parametrize("method", ["goss", "mvs"])
def test_sampled_classifier_runs(method):
    Xc, yc = _cls_data()
    kw = dict(num_base_learners=4, seed=5, sampling=method)
    r1 = np.asarray(GBMClassifier(**kw).fit(Xc, yc).predict_raw(Xc))
    r2 = np.asarray(GBMClassifier(**kw).fit(Xc, yc).predict_raw(Xc))
    assert np.all(np.isfinite(r1))
    assert np.array_equal(r1, r2)


def test_sampling_rejects_legacy_goss_mix_and_streaming():
    X, y = _data()
    with pytest.raises(ValueError, match="sample_method"):
        GBMRegressor(sampling="goss", sample_method="goss").fit(X, y)
    with pytest.raises(ValueError, match="sampling"):
        GBMRegressor(sampling="goss").fit_streaming(X, y)
    with pytest.raises(ValueError, match="linear"):
        GBMRegressor(leaf_model="linear").fit_streaming(X, y)


# ---------------------------------------------------------------------------
# the O(1)-programs bucket ladder
# ---------------------------------------------------------------------------


class _Recorder:
    def __init__(self):
        self.keys = set()

    def __call__(self, tag, sig, fn, args, kwargs):
        self.keys.add((tag, sig))


def test_same_bucket_rates_share_program_set():
    """Two GOSS rate pairs whose targets land in one pow2 bucket dispatch
    the SAME compiled programs: the rate scalars ride as traced operands,
    never as trace constants (the graftlint ``sampling`` contract)."""
    X, y = _data(n=512)
    sets = {}
    for rates in ((0.2, 0.1), (0.25, 0.12)):
        rec = _Recorder()
        with autotune.override(sample_bucket_floor=64):
            with observe_program_calls(rec):
                GBMRegressor(
                    num_base_learners=3, seed=0, sampling="goss",
                    top_rate=rates[0], other_rate=rates[1],
                ).fit(X, y)
        sets[rates] = frozenset(rec.keys)
    (r_a, s_a), (r_b, s_b) = sorted(sets.items())
    assert s_a == s_b, (
        f"program set varies with rates: {r_a} vs {r_b} differ by "
        f"{sorted(t for t, _ in s_a.symmetric_difference(s_b))}"
    )


def test_fused_tier_sampled_no_new_programs():
    """The fused-histogram tier re-enters its own program set under
    sampling — the gathered buffer is just a smaller row dim, not a new
    code path."""
    X, y = _data(n=512)
    sets = {}
    for rates in ((0.2, 0.1), (0.25, 0.12)):
        rec = _Recorder()
        with autotune.override(sample_bucket_floor=64):
            with observe_program_calls(rec):
                GBMRegressor(
                    base_learner=se.DecisionTreeRegressor(hist="fused"),
                    num_base_learners=3, seed=0, sampling="goss",
                    top_rate=rates[0], other_rate=rates[1],
                ).fit(X, y)
        sets[rates] = frozenset(rec.keys)
    (_, s_a), (_, s_b) = sorted(sets.items())
    assert s_a == s_b


# ---------------------------------------------------------------------------
# piecewise-linear leaves
# ---------------------------------------------------------------------------


def test_linear_leaves_beat_constant_on_piecewise_linear_target():
    rng = np.random.RandomState(0)
    X = rng.randn(500, 4).astype(np.float32)
    y = np.where(X[:, 0] > 0, 3.0 * X[:, 1], -2.0 * X[:, 2]).astype(
        np.float32
    )
    kw = dict(num_base_learners=8, seed=3)
    mse_const = float(np.mean((np.asarray(
        GBMRegressor(leaf_model="constant", **kw).fit(X, y).predict(X)
    ) - y) ** 2))
    mse_lin = float(np.mean((np.asarray(
        GBMRegressor(leaf_model="linear", **kw).fit(X, y).predict(X)
    ) - y) ** 2))
    assert mse_lin < 0.5 * mse_const


def test_linear_leaves_deterministic_and_compose_with_sampling():
    X, y = _data()
    kw = dict(num_base_learners=4, seed=5, leaf_model="linear")
    p1 = np.asarray(GBMRegressor(**kw).fit(X, y).predict(X))
    p2 = np.asarray(GBMRegressor(**kw).fit(X, y).predict(X))
    assert np.all(np.isfinite(p1)) and np.array_equal(p1, p2)
    pg = np.asarray(
        GBMRegressor(sampling="goss", **kw).fit(X, y).predict(X)
    )
    assert np.all(np.isfinite(pg))


def test_linear_leaf_rejects_foreign_base_learner():
    X, y = _data()
    with pytest.raises(ValueError, match="linear"):
        GBMRegressor(
            leaf_model="linear", base_learner=se.LinearRegression()
        ).fit(X, y)


# ---------------------------------------------------------------------------
# telemetry + kill-and-resume
# ---------------------------------------------------------------------------


def test_sampling_config_event_and_round_fields():
    X, y = _data()
    with record_fits() as rec:
        GBMRegressor(num_base_learners=3, seed=0, sampling="goss").fit(X, y)
    cfgs = [e for e in rec.events if e["event"] == "sampling_config"]
    assert len(cfgs) == 1
    cfg = cfgs[0]
    assert cfg["method"] == "goss"
    assert cfg["sample_bucket"] >= cfg["sampled_rows"] > 0
    ends = [e for e in rec.events if e["event"] == "round_end"]
    assert ends and all(
        e["sample_bucket"] == cfg["sample_bucket"]
        and e["sampled_rows"] == cfg["sampled_rows"]
        and e["hbm_saved_est"] >= 0
        for e in ends
    )


def test_sampled_fit_recovers_from_nan_round():
    """A poisoned gradient inside a sampled round (chaos nan_grad) is
    skipped by the guard exactly like on the full-row path — the
    compacted buffer must not leak NaNs past the recovery rewind."""
    X, y = _data()
    ctl = ChaosController(
        seed=11, rate=1.0, faults=("nan_grad",), budgets={"nan_grad": 1}
    )
    chaos.install(ctl)
    m = GBMRegressor(
        num_base_learners=5, scan_chunk=2, seed=5,
        sampling="goss", on_nonfinite="skip_round",
    ).fit(X, y)
    assert ctl.fired
    assert np.all(np.isfinite(np.asarray(m.predict(X))))


def test_sampled_kill_and_resume_matches_uninterrupted(tmp_path):
    X, y = _data()

    def est(ckdir):
        kw = dict(
            num_base_learners=6, scan_chunk=2, seed=5, sampling="goss"
        )
        if ckdir:
            kw.update(checkpoint_dir=ckdir, checkpoint_interval=1)
        return GBMRegressor(**kw)

    p_ref = np.asarray(est(None).fit(X, y).predict(X))
    interrupted = est(str(tmp_path / "ck"))
    chaos.install(ChaosController(
        seed=3, rate=1.0, faults=("preempt",), budgets={"preempt": 1}
    ))
    with pytest.raises(ChaosPreemption):
        interrupted.fit(X, y)
    chaos.install(None)
    m = interrupted.fit(X, y)  # resumes; sampling keys replay by round
    assert np.array_equal(np.asarray(m.predict(X)), p_ref)
