"""Dummy estimators: every strategy's constant equals the right dataset
statistic, predictions are constant, and sample weights are honored —
the reference's property suite
(`DummyRegressorSuite.scala:54-109` "const is equal to right statistics",
`DummyClassifierSuite.scala:54-79` "prediction is constant")."""

import numpy as np

import spark_ensemble_tpu as se


def _data(seed=0, n=500):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 4).astype(np.float32)
    y = (rng.randn(n) * 10 + 3).astype(np.float32)
    return X, y


def _weighted_crossing(y, w, q):
    """The reference rule: first value (sorted) whose cumweight >= q*total."""
    order = np.argsort(y)
    cum = np.cumsum(w[order])
    return float(y[order][np.searchsorted(cum, q * cum[-1], side="left")])


def test_regressor_strategies_match_statistics():
    X, y = _data()
    for strategy, expect in (
        ("mean", float(np.mean(y))),
        ("median", _weighted_crossing(y, np.ones_like(y), 0.5)),
        ("quantile", _weighted_crossing(y, np.ones_like(y), 0.25)),
        ("constant", -7.5),
    ):
        m = se.DummyRegressor(
            strategy=strategy, quantile=0.25, constant=-7.5
        ).fit(X, y)
        pred = np.asarray(m.predict(X))
        assert np.all(pred == pred[0]), strategy  # constant prediction
        np.testing.assert_allclose(pred[0], expect, rtol=1e-5, err_msg=strategy)


def test_regressor_strategies_honor_sample_weight():
    X, y = _data(1)
    rng = np.random.RandomState(2)
    w = rng.randint(0, 5, size=y.shape[0]).astype(np.float32)
    m = se.DummyRegressor(strategy="mean").fit(X, y, sample_weight=w)
    np.testing.assert_allclose(
        float(np.asarray(m.predict(X[:1]))[0]),
        float(np.average(y, weights=w)),
        rtol=1e-5,
    )
    mq = se.DummyRegressor(strategy="quantile", quantile=0.8).fit(
        X, y, sample_weight=w
    )
    assert float(np.asarray(mq.predict(X[:1]))[0]) == _weighted_crossing(
        y, w, 0.8
    )


def test_classifier_strategies():
    rng = np.random.RandomState(3)
    X = rng.randn(400, 3).astype(np.float32)
    y = rng.choice(3, size=400, p=[0.6, 0.3, 0.1]).astype(np.float32)
    prior = se.DummyClassifier(strategy="prior").fit(X, y)
    assert np.all(np.asarray(prior.predict(X)) == 0)  # majority class
    np.testing.assert_allclose(
        np.asarray(prior.predict_proba(X[:1]))[0],
        np.bincount(y.astype(int), minlength=3) / 400.0,
        atol=1e-6,
    )
    uni = se.DummyClassifier(strategy="uniform").fit(X, y)
    np.testing.assert_allclose(
        np.asarray(uni.predict_proba(X[:1]))[0], np.full(3, 1 / 3), atol=1e-6
    )
    const = se.DummyClassifier(strategy="constant", constant=2).fit(X, y)
    assert np.all(np.asarray(const.predict(X)) == 2)
