"""bench.py harness-logic tests (no accelerator, no measured fits): the
driver records this file's one JSON line every round, so its fallback and
bookkeeping logic is load-bearing."""

import importlib.util
import json
import os


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_finish_vs_baseline_math(capsys):
    bench = _load_bench()
    bench._finish({"value": 26.066, "platform": "cpu"}, [])
    out = json.loads(capsys.readouterr().out.strip())
    assert out["vs_baseline"] == round(26.066 / bench._BASELINES["cpu"], 3)

    bench._finish({"value": 13.982, "platform": "tpu"}, [])
    out = json.loads(capsys.readouterr().out.strip())
    assert out["vs_baseline"] == round(13.982 / bench._BASELINES["tpu"], 3)


def test_finish_carries_errors_and_warnings(capsys):
    bench = _load_bench()
    bench._finish({"value": 1.0, "platform": "cpu"}, ["e1", "e2"], ["w1"])
    out = json.loads(capsys.readouterr().out.strip())
    assert out["error"] == "e1; e2"
    assert out["warnings"] == "w1"


def test_finish_zero_value_keeps_explicit_ratio(capsys):
    bench = _load_bench()
    bench._finish(
        {"value": 0.0, "platform": "cpu", "vs_baseline": 0.0}, ["dead"]
    )
    out = json.loads(capsys.readouterr().out.strip())
    assert out["vs_baseline"] == 0.0


def test_tpu_capture_roundtrip(tmp_path, monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_REPO", str(tmp_path))
    assert bench._load_last_tpu_capture() is None
    capture = {"value": 130.0, "platform": "tpu", "vs_baseline": 18.6}
    with open(tmp_path / "BENCH_TPU_CAPTURE.json", "w") as f:
        json.dump(capture, f)
    loaded = bench._load_last_tpu_capture()
    # replayed captures are STAMPED stale with the capture's mtime so a
    # reader can never mistake an embedded old TPU leg for a fresh one
    assert loaded["tpu_capture_stale"] is True
    assert loaded["tpu_capture_mtime"].endswith("+00:00")
    assert {k: loaded[k] for k in capture} == capture
    # corrupt file: degrade to None, never raise (the fallback path must
    # always emit its JSON line)
    with open(tmp_path / "BENCH_TPU_CAPTURE.json", "w") as f:
        f.write("{not json")
    assert bench._load_last_tpu_capture() is None


def test_main_rejects_bad_tier_without_probing(monkeypatch, capsys):
    bench = _load_bench()
    monkeypatch.setenv("BENCH_HIST_PRECISION", "hi")

    def boom(*a, **k):  # probing would burn minutes; must not be reached
        raise AssertionError("probe should not run for a rejected knob")

    monkeypatch.setattr(bench, "_probe_accelerator", boom)
    rc = bench.main()
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 1 and "BENCH_HIST_PRECISION" in out["error"]


def test_main_arms_full_battery_only_on_real_accelerator(
    tmp_path, monkeypatch, capsys
):
    """A green REAL-accelerator probe arms BENCH_FULL/LARGE/TIERS (one
    perishable window must yield everything); a green CPU-backend probe
    must NOT (no window to protect — the battery costs tens of minutes
    there)."""
    bench = _load_bench()
    for knob in ("BENCH_FULL", "BENCH_LARGE", "BENCH_TIERS"):
        monkeypatch.delenv(knob, raising=False)
    monkeypatch.setattr(bench, "_REPO", str(tmp_path))
    for probe_info, expect_armed in (
        ("tpu 1", True),
        ("cpu 8", False),
        # plugin init noise before the platform line must not confuse it
        ("WARNING: Platform 'axon' is experimental\ntpu 1", True),
        # unrecognized/empty probe output is NOT a window (advisor r4):
        # arming the tens-of-minutes battery needs a recognized platform
        ("", False),
        ("something-unrecognized 3", False),
    ):
        captured = {}
        monkeypatch.setattr(
            bench, "_probe_accelerator", lambda t, i=probe_info: (True, i)
        )

        def fake_inner(env, t, captured=captured):
            captured["armed"] = env.get("BENCH_FULL") == "1"
            return {
                "value": 1.0, "platform": "tpu", "num_rounds": 100,
                "hist_precision": "highest",
            }, None

        monkeypatch.setattr(bench, "_run_inner", fake_inner)
        assert bench.main() == 0
        capsys.readouterr()
        assert captured["armed"] == expect_armed, probe_info


def test_main_armed_timeout_salvages_headline(tmp_path, monkeypatch, capsys):
    """If the auto-armed battery overruns the inner timeout, main retries
    once WITHOUT the extras so the window still yields the headline."""
    bench = _load_bench()
    for knob in ("BENCH_FULL", "BENCH_LARGE", "BENCH_TIERS"):
        monkeypatch.delenv(knob, raising=False)
    monkeypatch.setattr(bench, "_REPO", str(tmp_path))
    monkeypatch.setattr(
        bench, "_probe_accelerator", lambda t: (True, "tpu 1")
    )
    runs = []

    def flaky_inner(env, t):
        runs.append(env.get("BENCH_FULL"))
        if len(runs) == 1:
            return None, "bench run timed out after 10s"
        return {
            "value": 2.0, "platform": "tpu", "num_rounds": 100,
            "hist_precision": "highest",
        }, None

    monkeypatch.setattr(bench, "_run_inner", flaky_inner)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert runs == ["1", None]  # armed first, bare retry second
    assert out["value"] == 2.0
    assert "armed accelerator bench" in out.get("warnings", "")


def test_flops_estimate_positive_and_monotone():
    bench = _load_bench()
    f1 = bench._flops_per_round(10_000, 16, 26, 5, 64)
    f2 = bench._flops_per_round(20_000, 16, 26, 5, 64)
    assert 0 < f1 < f2 and f2 == 2 * f1


def test_run_inner_salvages_headline_from_partial_stdout(monkeypatch):
    """A timeout mid-extras (perishable window closing) must salvage the
    already-printed headline line instead of returning None."""
    import subprocess as sp

    bench = _load_bench()
    partial = json.dumps({
        "metric": "m", "value": 9.9, "platform": "tpu",
        "num_rounds": 100, "hist_precision": "highest",
        "partial": "extras pending",
    })

    def fake_run(*a, **k):
        raise sp.TimeoutExpired(
            cmd="x", timeout=5, output=f"noise\n{partial}\n", stderr=""
        )

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    result, err = bench._run_inner(dict(), 5)
    assert err is None
    assert result["value"] == 9.9
    assert "extras lost" in result["error"]
    assert "partial" not in result and result["extras"] == "lost"

    # a crash AFTER the partial print (nonzero rc, no timeout) must also
    # surface as lost extras, not a clean success
    class Crashed:
        returncode = 3
        stdout = f"{partial}\n"
        stderr = "boom"

    monkeypatch.setattr(
        bench.subprocess, "run", lambda *a, **k: Crashed()
    )
    result, err = bench._run_inner(dict(), 5)
    assert err is None and result["value"] == 9.9
    assert "rc=3" in result["error"] and result["extras"] == "lost"
    # a full final line (no timeout) still wins over the partial
    full = json.dumps({"value": 1.0, "platform": "tpu"})

    class P:
        returncode = 0
        stdout = f"{partial}\n{full}\n"
        stderr = ""

    monkeypatch.setattr(bench.subprocess, "run", lambda *a, **k: P())
    result, err = bench._run_inner(dict(), 5)
    assert result == {"value": 1.0, "platform": "tpu"}


def test_probe_failure_is_single_and_structured(monkeypatch, capsys):
    """A dead accelerator costs ONE probe (no backoff spam) and the JSON
    carries a machine-readable tpu_unavailable record, not joined retry
    strings (BENCH_r05 burned 4x240s on this)."""
    bench = _load_bench()
    monkeypatch.delenv("BENCH_PROBE_RETRIES", raising=False)
    probes = []

    def dead_probe(timeout_s):
        probes.append(timeout_s)
        return False, "backend init timed out after 240s"

    monkeypatch.setattr(bench, "_probe_accelerator", dead_probe)
    monkeypatch.setattr(bench.time, "sleep", lambda s: (_ for _ in ()).throw(
        AssertionError("fail-fast path must not back off")
    ))

    def fake_inner(env, t):
        assert env.get("JAX_PLATFORMS") == "cpu"
        return {
            "metric": bench._METRIC, "value": 2.0, "unit": "iters/sec",
            "platform": "cpu",
        }, None

    monkeypatch.setattr(bench, "_run_inner", fake_inner)
    rc = bench.main()
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert len(probes) == 1
    tu = out["tpu_unavailable"]
    assert tu["probes"] == 1
    assert "timed out" in tu["reason"]
    assert tu["probe_timeout_s"] == 240
    assert "probe 1:" not in out.get("error", "")


def test_probe_retries_remain_opt_in(monkeypatch, capsys):
    bench = _load_bench()
    monkeypatch.setenv("BENCH_PROBE_RETRIES", "3")
    probes = []
    monkeypatch.setattr(
        bench, "_probe_accelerator",
        lambda t: (probes.append(t), (False, "nope"))[1],
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setattr(
        bench, "_run_inner",
        lambda env, t: ({
            "metric": bench._METRIC, "value": 1.0, "unit": "iters/sec",
            "platform": "cpu",
        }, None),
    )
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(probes) == 3
    assert out["tpu_unavailable"]["probes"] == 3
