"""Full-size reference-dataset tier (BASELINE.md quality table).

The reference's suites assert relative quality on the FULL bundled datasets
(letter 15k, adult 32.5k, cpusmall 8.2k); the regular CPU tier subsamples
letter/adult for speed.  This opt-in tier (`pytest -m full`) runs the
BASELINE.md assertions at full size — the behavioral bar the TPU build must
clear — and is what the bench driver can invoke on real hardware.

Archetype sources: `GBMClassifierSuite.scala:51-146`,
`BaggingClassifierSuite.scala:48-155`, `BaggingRegressorSuite.scala:48-75`,
`GBMRegressorSuite.scala:51-76`, `StackingClassifierSuite.scala:49-87`.
"""

import numpy as np
import pytest

import spark_ensemble_tpu as se
from spark_ensemble_tpu.utils import datasets as ds
from tests.conftest import accuracy, rmse, split

# skip (don't silently run on the synthetic fallbacks) when the reference
# datasets aren't mounted: this tier's entire point is the full-size data
pytestmark = [
    pytest.mark.full,
    pytest.mark.skipif(
        not ds.has_reference_data(),
        reason="reference datasets (/root/reference/data) not available; "
        "the full tier asserts behavior on the real full-size data only",
    ),
]


@pytest.fixture(scope="module")
def letter_split(letter_full):
    return split(*letter_full, seed=1)


@pytest.fixture(scope="module")
def adult_split(adult_full):
    return split(*adult_full, seed=1)


@pytest.fixture(scope="module")
def cpusmall_split(cpusmall):
    return split(*cpusmall, seed=1)


def test_gbm_classifier_beats_tree_and_boosting_letter(letter_split):
    """`GBMClassifierSuite.scala:51-87` on full letter."""
    Xtr, ytr, Xte, yte = letter_split
    tree = se.DecisionTreeClassifier(max_depth=5).fit(Xtr, ytr)
    boost = se.BoostingClassifier(num_base_learners=10).fit(Xtr, ytr)
    gbm = se.GBMClassifier(
        num_base_learners=15, updates="newton", learning_rate=0.3
    ).fit(Xtr, ytr)
    acc_tree = accuracy(tree.predict(Xte), yte)
    acc_boost = accuracy(boost.predict(Xte), yte)
    acc_gbm = accuracy(gbm.predict(Xte), yte)
    assert acc_gbm > acc_tree
    assert acc_gbm > acc_boost


def test_gbm_classifier_binary_losses_adult(adult_split):
    """`GBMClassifierSuite.scala:89-146` on full adult: exponential and
    bernoulli GBM beat the single tree."""
    Xtr, ytr, Xte, yte = adult_split
    tree = se.DecisionTreeClassifier(max_depth=5).fit(Xtr, ytr)
    acc_tree = accuracy(tree.predict(Xte), yte)
    for loss in ("exponential", "bernoulli"):
        gbm = se.GBMClassifier(
            num_base_learners=15, loss=loss, updates="newton", learning_rate=0.3
        ).fit(Xtr, ytr)
        assert accuracy(gbm.predict(Xte), yte) > acc_tree, loss


def test_gbm_regressor_beats_tree_cpusmall(cpusmall_split):
    """`GBMRegressorSuite.scala:51-76` on full cpusmall."""
    Xtr, ytr, Xte, yte = cpusmall_split
    tree = se.DecisionTreeRegressor(max_depth=5).fit(Xtr, ytr)
    gbm = se.GBMRegressor(num_base_learners=20, learning_rate=0.3).fit(Xtr, ytr)
    assert rmse(gbm.predict(Xte), yte) < rmse(tree.predict(Xte), yte)


def test_bagging_regressor_beats_tree_cpusmall(cpusmall_split):
    """`BaggingRegressorSuite.scala:48-75` on full cpusmall."""
    Xtr, ytr, Xte, yte = cpusmall_split
    tree = se.DecisionTreeRegressor(max_depth=5).fit(Xtr, ytr)
    bag = se.BaggingRegressor(
        num_base_learners=10, subspace_ratio=0.75,
        base_learner=se.DecisionTreeRegressor(max_depth=8),
    ).fit(Xtr, ytr)
    assert rmse(bag.predict(Xte), yte) < rmse(tree.predict(Xte), yte)


def test_bagging_classifier_beats_members_letter(letter_split):
    """`BaggingClassifierSuite.scala:48-155` on full letter: ensemble beats
    every member; pairwise member agreement < 0.85 (diversity)."""
    Xtr, ytr, Xte, yte = letter_split
    bag = se.BaggingClassifier(
        num_base_learners=10,
        subsample_ratio=0.8,
        subspace_ratio=0.75,
        base_learner=se.DecisionTreeClassifier(max_depth=8),
    ).fit(Xtr, ytr)
    acc_bag = accuracy(bag.predict(Xte), yte)
    member_preds = np.asarray(bag.member_class_predictions(Xte))
    for m in range(member_preds.shape[0]):
        assert acc_bag > accuracy(member_preds[m], yte)
    agree = [
        float(np.mean(member_preds[i] == member_preds[j]))
        for i in range(member_preds.shape[0])
        for j in range(i + 1, member_preds.shape[0])
    ]
    assert max(agree) < 0.85


def test_stacking_beats_best_base_letter(letter_split):
    """`StackingClassifierSuite.scala:49-87` on full letter."""
    Xtr, ytr, Xte, yte = letter_split
    bases = [
        se.DecisionTreeClassifier(max_depth=5),
        se.LogisticRegression(max_iter=50),
        se.GaussianNaiveBayes(),
    ]
    stack = se.StackingClassifier(
        base_learners=bases,
        stacker=se.LogisticRegression(max_iter=50),
        stack_method="proba",
    ).fit(Xtr, ytr)
    base_accs = [
        accuracy(b.fit(Xtr, ytr).predict(Xte), yte) for b in bases
    ]
    assert accuracy(stack.predict(Xte), yte) > max(base_accs)
