"""Fleet serving tests (docs/fleet.md): ensemble-prefix slicing
(``PackedModel.take`` PINNED bit-identical to a k-round fit), engine
prefix tiers (pre-warmed, zero steady-state compiles), replica cloning,
queue-depth routing, hedged retries and crash drain/replay under
deterministic chaos, the circuit breaker's half-open re-admission,
staged degradation + shedding, registry pin-until-reply, and the
per-replica SLO telemetry events."""

import time

import numpy as np
import pytest

import spark_ensemble_tpu as se
from spark_ensemble_tpu.robustness.chaos import ChaosController, install
from spark_ensemble_tpu.robustness.retry import RetryPolicy
from spark_ensemble_tpu.serving import (
    FleetOverloadError,
    FleetResponse,
    FleetRouter,
    InferenceEngine,
    ModelRegistry,
    pack,
)
from spark_ensemble_tpu.telemetry import record_fits
from spark_ensemble_tpu.telemetry.events import compile_snapshot

ROUNDS = 5

# the engine-serving numeric contract (see tests/test_serving.py): packed
# prediction is bit-identical, but the whole-model program fused over a
# padded batch may move rounding by ~1 ulp
TOL = dict(rtol=1e-5, atol=1e-6)


def _data(n=96, d=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


@pytest.fixture(scope="module")
def fitted():
    """One fitted GBM shared across the module (fits dominate runtime;
    every test only reads it)."""
    X, y = _data()
    model = se.GBMRegressor(num_base_learners=ROUNDS, seed=0).fit(X, y)
    return X, y, model


@pytest.fixture(autouse=True)
def _deterministic_chaos():
    # pin a never-fires controller: this battery drives the fleet's fault
    # hooks with its own deterministic controllers, and the exact counter
    # assertions must hold even under an env-configured chaos tier (the
    # serving-chaos CI job runs these tests WITH serving faults exported)
    install(ChaosController(seed=0, rate=0.0))
    yield
    install(None)


# ---------------------------------------------------------------------------
# ensemble-prefix export (PackedModel.take)
# ---------------------------------------------------------------------------


def test_take_prefix_bit_identical_to_k_round_fit(fitted):
    """PINNED: the first k rounds of a packed GBM are bit-identical to a
    k-round fit — GBM round keys and sampling masks derive from absolute
    round indices, so round k+1 never perturbs rounds 1..k.  This is the
    contract that makes prefix degradation exact, not approximate."""
    X, y, model = fitted
    p = pack(model)
    assert p.num_members == ROUNDS
    for k in (1, 3, ROUNDS):
        ref = se.GBMRegressor(num_base_learners=k, seed=0).fit(X, y)
        np.testing.assert_array_equal(
            np.asarray(p.take(k).predict(X)), np.asarray(ref.predict(X))
        )


def test_take_validates(fitted):
    X, y, model = fitted
    p = pack(model)
    for bad in (0, ROUNDS + 1, -1):
        with pytest.raises(ValueError, match="out of range"):
            p.take(bad)
    bag = pack(se.BaggingRegressor(num_base_learners=2).fit(X, y))
    with pytest.raises(TypeError, match="prefix"):
        bag.take(1)


# ---------------------------------------------------------------------------
# engine prefix tiers + cloning
# ---------------------------------------------------------------------------


def test_engine_prefix_tiers_warm_and_exact(fitted):
    X, y, model = fitted
    p = pack(model)
    sizes = (1, 5, 16)
    # reference predictions BEFORE the compile fence: live-model jits must
    # not be mistaken for engine steady-state compiles
    want = {
        (n, k): np.asarray(p.take(k).predict(X[:n]))
        for n in sizes
        for k in (2, 3)
    }
    want.update({(n, 0): np.asarray(p.predict(X[:n])) for n in sizes})
    with record_fits() as rec:
        with InferenceEngine(
            p, prefix_tiers=(2, 3), min_bucket=8, max_batch_size=16
        ) as eng:
            assert eng.prefix_tiers == (2, 3)
            assert set(eng.stats()["compiled"]) == {
                "predict@8", "predict@16",
                "predict@8~2", "predict@16~2",
                "predict@8~3", "predict@16~3",
            }
            c0, _ = compile_snapshot()
            for n in sizes:
                for k in (0, 2, 3):
                    out = eng.predict(X[:n], tier=k)
                    np.testing.assert_allclose(
                        np.asarray(out), want[(n, k)], **TOL
                    )
            # the async queue coalesces tiered requests too
            fut = eng.submit(X[:5], tier=2)
            np.testing.assert_allclose(
                np.asarray(fut.result(timeout=30)), want[(5, 2)], **TOL
            )
            assert compile_snapshot()[0] == c0  # zero steady-state compiles
            assert eng.stats()["compiles_since_warmup"] == 0
            with pytest.raises(ValueError, match="prefix_tiers"):
                eng.predict(X[:4], tier=4)
    warm = [e for e in rec.events if e["event"] == "engine_warmup"]
    assert len(warm) == 6  # 2 buckets x (full + 2 tiers)
    assert sorted({e["tier"] for e in warm}) == [0, 2, 3]


def test_engine_clone_shares_programs(fitted):
    X, y, model = fitted
    p = pack(model)
    want = np.asarray(p.predict(X[:5]))
    want3 = np.asarray(p.take(3).predict(X[:5]))
    with InferenceEngine(
        p, prefix_tiers=(3,), min_bucket=8, max_batch_size=16
    ) as eng:
        c0, _ = compile_snapshot()
        clone = eng.clone("clone")
        try:
            np.testing.assert_allclose(
                np.asarray(clone.predict(X[:5])), want, **TOL
            )
            np.testing.assert_allclose(
                np.asarray(clone.predict(X[:5], tier=3)), want3, **TOL
            )
            fut = clone.submit(X[:5])
            np.testing.assert_allclose(
                np.asarray(fut.result(timeout=30)), want, **TOL
            )
            # cloning compiled NOTHING: programs and arrays are shared
            assert compile_snapshot()[0] == c0
            assert clone.stats()["compiles_since_warmup"] == 0
        finally:
            clone.stop()


# ---------------------------------------------------------------------------
# fleet routing + SLO telemetry
# ---------------------------------------------------------------------------


def test_fleet_routes_and_zero_compiles(fitted):
    X, y, model = fitted
    sizes = (1, 4, 7, 16)
    want = {n: np.asarray(model.predict(X[:n])) for n in sizes}
    with record_fits() as rec:
        with FleetRouter(
            model, replicas=3, min_bucket=8, max_batch_size=16,
            deadline_ms=30_000.0,
        ) as fleet:
            for i in range(8):
                n = sizes[i % len(sizes)]
                resp = fleet.predict(X[:n])
                assert isinstance(resp, FleetResponse)
                assert resp.tier == 0 and not resp.degraded
                np.testing.assert_allclose(resp.value, want[n], **TOL)
            # a concurrent burst spreads across replicas (depth routing)
            futs = [
                fleet.submit(X[: sizes[i % len(sizes)]]) for i in range(24)
            ]
            for i, f in enumerate(futs):
                r = f.result(timeout=30)
                np.testing.assert_allclose(
                    r.value, want[sizes[i % len(sizes)]], **TOL
                )
            snap = fleet.slo_snapshot()
            assert snap["requests"] == 32
            assert snap["compiles_since_warmup"] == 0
            assert snap["shed"] == 0 and snap["crashes"] == 0
            assert sum(
                r["served"] for r in snap["replicas"].values()
            ) >= 32
            busy = [
                r for r in snap["replicas"].values() if r["served"] > 0
            ]
            assert len(busy) >= 2  # the burst did not pile on one replica
            assert snap["p99_ms"] >= snap["p50_ms"] > 0
            assert fleet.stats()["fleet"]["requests"] == 32
    served = [e for e in rec.events if e["event"] == "fleet_request"]
    assert len(served) == 32
    assert all(e["latency_ms"] > 0 and not e["degraded"] for e in served)
    slo = [e for e in rec.events if e["event"] == "fleet_slo"]
    # stop() emits one row per replica plus the aggregate "*" row
    assert {e["replica"] for e in slo} >= {"*"}
    assert len(slo) == 4


# ---------------------------------------------------------------------------
# chaos battery: stall -> hedge, crash -> drain/replay, half-open probe
# ---------------------------------------------------------------------------


def test_fleet_hedges_on_stalled_replica(fitted):
    X, y, model = fitted
    want = np.asarray(model.predict(X[:4]))
    install(ChaosController(seed=7, rate=1.0, faults=("replica_stall",)))
    with FleetRouter(
        model, replicas=2, min_bucket=8, max_batch_size=16,
        deadline_ms=30_000.0, hedge_init_ms=10.0,
    ) as fleet:
        resp = fleet.predict(X[:4])
        np.testing.assert_allclose(resp.value, want, **TOL)
        assert resp.hedged
        snap = fleet.slo_snapshot()
        assert snap["hedges_fired"] >= 1
        assert snap["crashes"] == 0  # stall is hedge territory, not breaker


def test_fleet_kill_replica_drains_and_replays(fitted):
    """The acceptance scenario: one replica killed under load -> every
    in-flight and queued request still resolves exactly once with the
    right value (zero lost, zero duplicated)."""
    X, y, model = fitted
    want = np.asarray(model.predict(X[:4]))
    with FleetRouter(
        model, replicas=2, min_bucket=8, max_batch_size=16,
        deadline_ms=30_000.0, shed_depth=10_000,
    ) as fleet:
        futs = [fleet.submit(X[:4]) for _ in range(40)]
        killed = fleet.kill_replica()
        # the kill pill sits mid-queue: later submits still route to the
        # dying replica and must be drained onto the survivor
        futs += [fleet.submit(X[:4]) for _ in range(20)]
        responses = [f.result(timeout=60) for f in futs]
        assert len(responses) == 60  # zero lost; Futures resolve once
        for r in responses:
            np.testing.assert_allclose(r.value, want, **TOL)
        snap = fleet.slo_snapshot()
        assert snap["crashes"] == 1
        assert snap["replays"] >= 1
        assert snap["replicas"][killed]["state"] == "ejected"
        live = [
            r for r in snap["replicas"].values() if r["state"] != "ejected"
        ]
        assert len(live) == 1 and live[0]["state"] in ("healthy", "degraded")


def test_fleet_chaos_crash_then_half_open_readmission(fitted):
    X, y, model = fitted
    want = np.asarray(model.predict(X[:4]))
    install(ChaosController(seed=3, rate=1.0, faults=("replica_crash",)))
    backoff = RetryPolicy(
        max_retries=0, base_delay=0.05, max_delay=0.1, jitter=0.0
    )
    with FleetRouter(
        model, replicas=2, min_bucket=8, max_batch_size=16,
        deadline_ms=30_000.0, breaker_backoff=backoff,
    ) as fleet:
        # the first serve draws the (budget-1) chaos crash; the request is
        # replayed on the survivor and still succeeds
        resp = fleet.predict(X[:4])
        np.testing.assert_allclose(resp.value, want, **TOL)
        assert resp.replays >= 1
        snap = fleet.slo_snapshot()
        assert snap["crashes"] == 1
        ejected = [
            n for n, r in snap["replicas"].items()
            if r["state"] == "ejected"
        ]
        assert len(ejected) == 1
        time.sleep(0.2)  # past the breaker backoff -> half-open
        for _ in range(8):
            np.testing.assert_allclose(
                fleet.predict(X[:4]).value, want, **TOL
            )
        snap = fleet.slo_snapshot()
        # the probe request re-admitted the crashed replica
        assert all(
            r["state"] == "healthy" for r in snap["replicas"].values()
        )
        assert all(r["served"] > 0 for r in snap["replicas"].values())
        assert snap["requests"] == 9 and snap["crashes"] == 1


# ---------------------------------------------------------------------------
# staged degradation + shedding
# ---------------------------------------------------------------------------


def test_fleet_degrades_to_prefix_under_deadline_pressure(fitted):
    X, y, model = fitted
    p = pack(model)
    want2 = np.asarray(p.take(2).predict(X[:4]))
    want_full = np.asarray(p.predict(X[:4]))
    with FleetRouter(
        model, replicas=2, prefix_tiers=(2,), min_bucket=8,
        max_batch_size=16, deadline_ms=30_000.0, deadline_grace=1e6,
    ) as fleet:
        # a budget far below the latency estimate degrades to the prefix
        resp = fleet.predict(X[:4], deadline_ms=0.25)
        assert resp.degraded and resp.tier == 2
        np.testing.assert_allclose(resp.value, want2, **TOL)
        # a relaxed budget serves the full model again
        full = fleet.predict(X[:4])
        assert not full.degraded and full.tier == 0
        np.testing.assert_allclose(full.value, want_full, **TOL)
        snap = fleet.slo_snapshot()
        assert snap["degraded"] == 1
        assert 0.0 < snap["degraded_share"] < 1.0
        assert snap["compiles_since_warmup"] == 0  # tiers were pre-warmed


def test_fleet_sheds_past_depth_and_without_live_replicas(fitted):
    X, y, model = fitted
    with FleetRouter(
        model, replicas=1, min_bucket=8, max_batch_size=16, shed_depth=0
    ) as fleet:
        with pytest.raises(FleetOverloadError, match="shed"):
            fleet.submit(X[:4])
        assert fleet.slo_snapshot()["shed"] == 1
    slow = RetryPolicy(max_retries=0, base_delay=60.0, max_delay=60.0)
    with FleetRouter(
        model, replicas=1, min_bucket=8, max_batch_size=16,
        deadline_ms=30_000.0, breaker_backoff=slow,
    ) as fleet:
        fleet.predict(X[:4])
        killed = fleet.kill_replica()
        deadline = time.time() + 10.0
        while (
            fleet.slo_snapshot()["replicas"][killed]["state"] != "ejected"
            and time.time() < deadline
        ):
            time.sleep(0.01)
        with pytest.raises(FleetOverloadError, match="no live replica"):
            fleet.submit(X[:4])


def test_fleet_rejects_malformed_requests_without_breaker_damage(fitted):
    X, y, model = fitted
    with FleetRouter(
        model, replicas=2, min_bucket=8, max_batch_size=16,
        deadline_ms=30_000.0,
    ) as fleet:
        with pytest.raises(ValueError):
            fleet.submit(np.zeros((4, 3), np.float32))  # wrong n_features
        snap = fleet.slo_snapshot()
        # a caller error is not a replica fault: no breaker movement
        assert all(
            r["state"] == "healthy" and r["failed"] == 0
            for r in snap["replicas"].values()
        )


# ---------------------------------------------------------------------------
# registry integration (pin-until-reply)
# ---------------------------------------------------------------------------


def test_registry_pin_defers_eviction_until_release(fitted):
    X, y, model = fitted
    other = se.GBMRegressor(num_base_learners=2, seed=1).fit(X, y)
    with ModelRegistry(capacity=1, min_bucket=8, max_batch_size=16) as reg:
        reg.register("g", model)
        reg.register("h", other)
        want = np.asarray(reg.predict("g", X[:4]))
        with reg.lease("g") as eng:
            reg.engine("h")  # over capacity: would evict g, but it's pinned
            st = reg.stats()["g"]
            assert st["resident"] and st["pins"] == 1
            np.testing.assert_array_equal(
                np.asarray(eng.predict(X[:4])), want
            )
        # the deferred offload lands the moment the last lease releases
        st = reg.stats()["g"]
        assert st["pins"] == 0 and not st["resident"]

        # same race through the async path: an in-flight submit pins its
        # version; the reply is served from the buffers eviction targeted
        reg.engine("g")  # reactivate (evicts h)
        fut = reg.submit("g", X[:4])
        reg.engine("h")  # races the queued request
        np.testing.assert_array_equal(
            np.asarray(fut.result(timeout=30)), want
        )
        deadline = time.time() + 10.0
        while reg.stats()["g"]["pins"] > 0 and time.time() < deadline:
            time.sleep(0.005)
        st = reg.stats()["g"]
        assert st["pins"] == 0 and not st["resident"]


def test_fleet_from_registry_pins_until_stop(fitted):
    X, y, model = fitted
    other = se.GBMRegressor(num_base_learners=2, seed=1).fit(X, y)
    with ModelRegistry(capacity=1, min_bucket=8, max_batch_size=16) as reg:
        reg.register("g", model)
        reg.register("h", other)
        fleet = FleetRouter.from_registry(
            reg, "g", replicas=2, deadline_ms=30_000.0
        )
        try:
            want = fleet.predict(X[:4]).value
            reg.engine("h")  # hot-swap pressure: g stays pinned under the fleet
            st = reg.stats()["g"]
            assert st["resident"] and st["pins"] == 1
            resp = fleet.predict(X[:4])
            np.testing.assert_array_equal(
                np.asarray(resp.value), np.asarray(want)
            )
        finally:
            fleet.stop()
        st = reg.stats()["g"]
        assert st["pins"] == 0 and not st["resident"]
