"""The generated API reference (tools/gen_api_docs.py) must cover every
public export — the parity bar is the reference's scaladoc navbar item
(`website/docusaurus.config.js:19` there), where every public class gets a
generated page."""

import importlib.util
import os

import spark_ensemble_tpu as se

_GEN = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "gen_api_docs.py",
)


def _load_gen():
    spec = importlib.util.spec_from_file_location("gen_api_docs", _GEN)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_public_export_gets_a_page(tmp_path):
    gen = _load_gen()
    pages = gen.generate(str(tmp_path))
    covered = {name for names in pages.values() for name in names}
    assert covered == set(se.__all__)
    # one file per page plus the index
    files = {p.name for p in tmp_path.iterdir()}
    assert files == {f"{page}.md" for page in pages} | {"index.md"}


def test_estimator_pages_render_param_tables(tmp_path):
    gen = _load_gen()
    gen.generate(str(tmp_path))
    gbm = (tmp_path / "gbm.md").read_text()
    assert "## `GBMClassifier`" in gbm
    assert "| `num_base_learners` | `10` |" in gbm
    assert "#### `fit(" in gbm
    # the index links every page
    index = (tmp_path / "index.md").read_text()
    for page in ("gbm", "bagging", "stacking", "tree"):
        assert f"[{page}](./{page}.md)" in index


def test_committed_pages_match_the_code(tmp_path):
    """The repo's docs/api must be regeneration-stable (CI enforces the
    same thing with git diff --exit-code)."""
    gen = _load_gen()
    gen.generate(str(tmp_path))
    committed = os.path.join(os.path.dirname(_GEN), "..", "docs", "api")
    # listings must match exactly: an orphaned committed page (module
    # renamed/removed) is as stale as a modified one
    assert {p.name for p in tmp_path.iterdir()} == set(os.listdir(committed))
    for p in sorted(tmp_path.iterdir()):
        with open(os.path.join(committed, p.name)) as f:
            assert f.read() == p.read_text(), f"{p.name} is stale"
