"""Distributed-path tests on the 8-device virtual CPU mesh (the analogue of
the reference testing "distributed" via local-mode Spark, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_ensemble_tpu.ops.binning import bin_features, compute_bins
from spark_ensemble_tpu.ops.losses import LogLoss
from spark_ensemble_tpu.parallel.distributed import make_sharded_gbm_round
from spark_ensemble_tpu.parallel.mesh import create_mesh, pad_to_multiple


def _toy(n=512, d=6, k=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    centers = rng.randn(k, d).astype(np.float32)
    y = np.argmax(X @ centers.T + 0.3 * rng.randn(n, k), axis=1).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(y)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return create_mesh({"data": 4, "member": 2})


def test_sharded_round_reduces_loss(mesh):
    X, y = _toy()
    k = 4
    loss = LogLoss(k)
    bins = compute_bins(X, 16)
    Xb = bin_features(X, bins)
    y_enc = loss.encode_label(y)
    pred = jnp.zeros((X.shape[0], k))
    w = jnp.ones(X.shape[0])
    round_fn = make_sharded_gbm_round(
        mesh, loss, max_depth=3, max_bins=16, updates="newton"
    )
    trees, step_w, new_pred = round_fn(Xb, bins.thresholds, y_enc, pred, w, w)
    before = float(jnp.mean(loss.loss(y_enc, pred)))
    after = float(jnp.mean(loss.loss(y_enc, new_pred)))
    assert after < before
    assert step_w.shape == (k,)
    assert bool(jnp.all(step_w >= 0))


def test_sharded_round_matches_unsharded(mesh):
    """DP x MP GBM round == the single-device round step, bit-for-bit on
    split decisions (psum-ed histograms are exact sums)."""
    from spark_ensemble_tpu.ops.tree import fit_tree

    X, y = _toy(n=256)
    k = 4
    loss = LogLoss(k)
    bins = compute_bins(X, 16)
    Xb = bin_features(X, bins)
    y_enc = loss.encode_label(y)
    pred = jnp.zeros((X.shape[0], k))
    w = jnp.ones(X.shape[0])

    round_fn = make_sharded_gbm_round(
        mesh, loss, max_depth=3, max_bins=16, updates="gradient",
        optimized_weights=False,
    )
    trees_sh, step_sh, pred_sh = round_fn(Xb, bins.thresholds, y_enc, pred, w, w)

    # single-device reference: same pseudo-residuals, same per-class trees
    neg_grad = loss.negative_gradient(y_enc, pred)
    fit_one = lambda j: fit_tree(
        Xb, neg_grad[:, j : j + 1], w, bins.thresholds, max_depth=3, max_bins=16
    )
    for j in range(k):
        single = fit_one(j)
        assert jnp.array_equal(
            jax.tree_util.tree_map(lambda x: x[j], trees_sh).split_feature,
            single.split_feature,
        )
        assert jnp.allclose(
            jax.tree_util.tree_map(lambda x: x[j], trees_sh).leaf_value,
            single.leaf_value,
            atol=1e-4,
        )


def test_pad_to_multiple():
    x = jnp.ones((10, 3))
    padded, n = pad_to_multiple(x, 8)
    assert padded.shape == (16, 3)
    assert n == 10
    same, n2 = pad_to_multiple(jnp.ones((16, 3)), 8)
    assert same.shape == (16, 3)


def test_graft_entry_dryrun():
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[0].shape[0]
    ge.dryrun_multichip(min(8, len(jax.devices())))
