"""Distributed-path tests on the 8-device virtual CPU mesh (the analogue of
the reference testing "distributed" via local-mode Spark, SURVEY.md §4).

The sharded GBM round under test is the ESTIMATOR mesh path itself
(`GBMClassifier.fit(mesh=...)` — rows over "data" with psum-ed histograms,
class dims over "member" with all_gather); kernel-level split decisions are
checked bit-for-bit against single-device ``fit_tree`` since psum-ed
histograms are exact sums of the same addends per node."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_ensemble_tpu import GBMClassifier
from spark_ensemble_tpu.models.tree import DecisionTreeRegressor
from spark_ensemble_tpu.ops.binning import bin_features, compute_bins
from spark_ensemble_tpu.ops.losses import LogLoss
from spark_ensemble_tpu.ops.tree import fit_tree
from spark_ensemble_tpu.parallel.mesh import create_mesh, pad_to_multiple


def _toy(n=512, d=6, k=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    centers = rng.randn(k, d).astype(np.float32)
    y = np.argmax(X @ centers.T + 0.3 * rng.randn(n, k), axis=1).astype(np.float32)
    return X, y


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return create_mesh({"data": 4, "member": 2})


@pytest.mark.slow
def test_sharded_round_reduces_loss(mesh):
    X, y = _toy()
    est = GBMClassifier(
        num_base_learners=1,
        loss="logloss",
        updates="newton",
        base_learner=DecisionTreeRegressor(max_depth=3, max_bins=16),
    )
    model = est.fit(X, y, mesh=mesh)
    loss = LogLoss(4)
    y_enc = loss.encode_label(jnp.asarray(y))
    before = float(jnp.mean(loss.loss(y_enc, jnp.zeros((X.shape[0], 4)))))
    after = float(jnp.mean(loss.loss(y_enc, model.predict_raw(jnp.asarray(X)))))
    assert after < before
    w = np.asarray(model.params["weights"])
    assert w.shape == (1, 4)
    assert np.all(w >= 0)


def test_sharded_round_matches_unsharded_splits(mesh):
    """DP x MP estimator round == single-device ``fit_tree``, bit-for-bit on
    split decisions (psum-ed histograms are exact sums)."""
    X, y = _toy(n=256)
    k = 4
    cfg = dict(
        num_base_learners=1,
        loss="logloss",
        updates="gradient",
        optimized_weights=False,
        base_learner=DecisionTreeRegressor(max_depth=3, max_bins=16),
        seed=9,
    )
    dist = GBMClassifier(**cfg).fit(X, y, mesh=mesh)
    trees_sh = dist.params["members"]  # stacked [1, k] member pytree

    # single-device reference: same pseudo-residuals, same per-class trees
    loss = LogLoss(k)
    Xj = jnp.asarray(X)
    bins = compute_bins(Xj, 16)
    Xb = bin_features(Xj, bins)
    y_enc = loss.encode_label(jnp.asarray(y))
    # init raw = log prior, as the estimator's prior init produces
    init_raw = dist.params["init_raw"]
    pred = jnp.broadcast_to(init_raw[None, :], (X.shape[0], k))
    neg_grad = loss.negative_gradient(y_enc, pred)
    w = jnp.ones(X.shape[0])
    for j in range(k):
        single = fit_tree(
            Xb, neg_grad[:, j : j + 1], w, bins.thresholds, max_depth=3, max_bins=16
        )
        member_j = jax.tree_util.tree_map(lambda x: x[0, j], trees_sh)
        assert jnp.array_equal(member_j.split_feature, single.split_feature), j
        assert jnp.allclose(member_j.leaf_value, single.leaf_value, atol=1e-4), j


def test_pad_to_multiple():
    x = jnp.ones((10, 3))
    padded, n = pad_to_multiple(x, 8)
    assert padded.shape == (16, 3)
    assert n == 10
    same, n2 = pad_to_multiple(jnp.ones((16, 3)), 8)
    assert same.shape == (16, 3)


@pytest.mark.slow
def test_graft_entry_dryrun():
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[0].shape[0]
    ge.dryrun_multichip(min(8, len(jax.devices())))


class _FakeSliceDev:
    """A fake multi-slice TPU device: just enough surface (slice_index,
    coords, core_on_chip) for mesh_utils.create_hybrid_device_mesh to do
    DCN-aware placement.  Each 4-device slice is a 2x2x1 torus."""

    platform = "tpu"
    device_kind = "fake-v5e"

    def __init__(self, i, slice_index):
        self.id = i
        self.process_index = slice_index
        self.slice_index = slice_index
        j = i % 4
        self.coords = (j % 2, j // 2, 0)
        self.core_on_chip = 0

    def __repr__(self):
        return f"FakeDev({self.id},slice={self.slice_index})"


def test_hybrid_mesh_dcn_branch_places_slices_on_dcn_axis():
    """The DCN-aware branch (devices WITH slice_index) must run the real
    mesh_utils.create_hybrid_device_mesh call and put each slice at one
    dcn_data index — cross-slice traffic rides ONLY the dcn_data axis."""
    from spark_ensemble_tpu.parallel.mesh import hybrid_data_member_mesh

    devs = [_FakeSliceDev(i, i // 4) for i in range(8)]
    mesh = hybrid_data_member_mesh(dcn_data=2, member=2, devices=devs)
    assert dict(mesh.shape) == {"dcn_data": 2, "data": 2, "member": 2}
    arr = mesh.devices
    for a in range(2):
        slices = {d.slice_index for d in arr[a].flat}
        assert slices == {a}, (a, slices)


def test_hybrid_mesh_dcn_branch_config_errors_propagate():
    """dcn_data that contradicts the actual slice count must raise (the
    plain-reshape fallback would silently shard across slice boundaries —
    exactly what the DCN branch exists to prevent)."""
    import pytest

    from spark_ensemble_tpu.parallel.mesh import hybrid_data_member_mesh

    devs = [_FakeSliceDev(i, i // 4) for i in range(8)]  # 2 slices
    with pytest.raises(ValueError, match="slices"):
        hybrid_data_member_mesh(dcn_data=4, member=2, devices=devs)


def test_multihost_single_process_contract():
    """Single-process behavior of the multi-host entry point: helpers
    report the degenerate topology, partial explicit args are rejected
    before touching the rendezvous, and an already-initialized (or
    single-process) state makes initialize a no-op path decision."""
    import pytest

    from spark_ensemble_tpu.parallel import multihost

    assert multihost.process_count() == 1
    assert multihost.process_index() == 0
    assert multihost.local_device_count() >= 1
    with pytest.raises(ValueError, match="together"):
        multihost.initialize(coordinator_address="h:1234")
    with pytest.raises(ValueError, match="together"):
        multihost.initialize(num_processes=2, process_id=0)
