"""Estimator-level distributed training: ``fit(..., mesh=...)`` must match
single-device ``fit`` — the behavioral contract of the reference's
distribution story, where the SAME algorithm runs whether data lives on one
executor or many (`GBMClassifier.scala:344-355`,
`BaggingClassifier.scala:180-201`).

Parity tiers (mirroring what is provable in f32 SPMD):
- **pointwise** for single-round GBM and single-round boosting: psum-ed
  statistics equal local sums to float noise;
- **metric-level** for multi-round GBM/boosting and for row-sharded bagging:
  tree splits are argmaxes over psum-ed histogram gains, so a last-ulp
  reduction-order difference can flip a split and compound (bagging: a
  handful of rows near a flipped threshold move) — exactly as Spark's own
  ``treeAggregate`` order differs between local and cluster mode.  The
  fitted models must then agree as *models* (RMSE / accuracy / agreement),
  not bit-for-bit.

Runs on the 8-device virtual CPU mesh from conftest, the analogue of the
reference's ``local[*]`` Spark sessions.
"""

import pytest as _pytest

pytestmark = _pytest.mark.slow


import os

import jax
import numpy as np
import pytest

from spark_ensemble_tpu import (
    BaggingClassifier,
    BaggingRegressor,
    GBMClassifier,
    GBMRegressor,
)
from spark_ensemble_tpu.parallel.mesh import data_member_mesh


@pytest.fixture(scope="module")
def mesh8():
    return data_member_mesh(8, member=1)


@pytest.fixture(scope="module")
def mesh42():
    return data_member_mesh(8, member=2)


def _reg_data(n=700, d=9, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] * 2.0 + np.sin(X[:, 1] * 3.0) + 0.05 * rng.randn(n)).astype(
        np.float32
    )
    return X, y


def _cls_data(n=700, d=8, k=4, seed=4):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    centers = rng.randn(k, d).astype(np.float32)
    y = np.argmax(X @ centers.T + 0.3 * rng.randn(n, k), axis=1).astype(np.float32)
    return X, y


def _rmse(pred, y):
    return float(np.sqrt(np.mean((np.asarray(pred) - y) ** 2)))


def test_gbm_regressor_mesh_pointwise_single_round(mesh8):
    # n=700 is NOT divisible by 8: exercises the zero-weight row padding.
    # One round isolates the machinery (newton hessian psum, subsampled bag
    # weights, Brent line search with psum-ed objective) from split-flip
    # compounding.
    X, y = _reg_data()
    cfg = dict(
        num_base_learners=1,
        loss="squared",
        updates="newton",
        optimized_weights=True,
        subsample_ratio=0.8,
        replacement=False,
        seed=7,
    )
    single = GBMRegressor(**cfg).fit(X, y)
    dist = GBMRegressor(**cfg).fit(X, y, mesh=mesh8)
    np.testing.assert_allclose(
        np.asarray(single.predict(X)), np.asarray(dist.predict(X)),
        rtol=1e-3, atol=1e-3,
    )


def test_gbm_regressor_mesh_metric_parity(mesh8):
    X, y = _reg_data()
    cfg = dict(
        num_base_learners=5,
        loss="squared",
        updates="newton",
        learning_rate=0.5,
        subsample_ratio=0.8,
        replacement=False,
        seed=7,
    )
    single = GBMRegressor(**cfg).fit(X, y)
    dist = GBMRegressor(**cfg).fit(X, y, mesh=mesh8)
    r_s, r_d = _rmse(single.predict(X), y), _rmse(dist.predict(X), y)
    # 4%: psum-order split flips compound over 5 boosted rounds at lr 0.5
    # (the single-round test above is pointwise; this bar only guards
    # against systematic divergence, not f32 trajectory noise)
    assert abs(r_s - r_d) < 0.04 * max(r_s, r_d) + 1e-6, (r_s, r_d)


def test_gbm_regressor_mesh_huber(mesh8):
    X, y = _reg_data()
    cfg = dict(num_base_learners=3, loss="huber", alpha=0.9, seed=1)
    single = GBMRegressor(**cfg).fit(X, y)
    dist = GBMRegressor(**cfg).fit(X, y, mesh=mesh8)
    r_s, r_d = _rmse(single.predict(X), y), _rmse(dist.predict(X), y)
    assert abs(r_s - r_d) < 0.03 * max(r_s, r_d) + 1e-6, (r_s, r_d)


def test_gbm_classifier_mesh_pointwise_single_round(mesh42):
    # ("data": 4, "member": 2) — class dims block-sharded over "member",
    # directions rejoined with all_gather.  Depth-2 trees: deeper trees hit
    # exact gain ties across empty-bin runs whose argmax tie-break is
    # reduction-order-dependent (equivalent splits, different thresholds) —
    # see module docstring; the metric-parity test covers default depth.
    from spark_ensemble_tpu.models.tree import DecisionTreeRegressor

    X, y = _cls_data()
    cfg = dict(
        num_base_learners=1,
        base_learner=DecisionTreeRegressor(max_depth=2),
        loss="logloss",
        updates="newton",
        learning_rate=0.5,
        seed=5,
    )
    single = GBMClassifier(**cfg).fit(X, y)
    dist = GBMClassifier(**cfg).fit(X, y, mesh=mesh42)
    np.testing.assert_allclose(
        np.asarray(single.predict_raw(X)), np.asarray(dist.predict_raw(X)),
        rtol=5e-3, atol=5e-3,
    )


def test_gbm_classifier_mesh_metric_parity(mesh42):
    X, y = _cls_data()
    cfg = dict(
        num_base_learners=4,
        loss="logloss",
        updates="newton",
        learning_rate=0.5,
        seed=5,
    )
    single = GBMClassifier(**cfg).fit(X, y)
    dist = GBMClassifier(**cfg).fit(X, y, mesh=mesh42)
    ps, pd = np.asarray(single.predict(X)), np.asarray(dist.predict(X))
    assert np.mean(ps == pd) > 0.97
    acc_s, acc_d = float(np.mean(ps == y)), float(np.mean(pd == y))
    assert abs(acc_s - acc_d) < 0.02, (acc_s, acc_d)


def test_gbm_classifier_mesh_validation_early_stop(mesh8):
    X, y = _cls_data(n=900)
    vi = np.zeros(900, bool)
    vi[700:] = True
    cfg = dict(num_base_learners=8, loss="logloss", num_rounds=2, seed=2)
    single = GBMClassifier(**cfg).fit(X, y, validation_indicator=vi)
    dist = GBMClassifier(**cfg).fit(X, y, validation_indicator=vi, mesh=mesh8)
    assert abs(single.num_members - dist.num_members) <= 1


def test_bagging_regressor_mesh_parity(mesh42):
    # (data x member): rows 4-way, members 2-way.  Histogram sums now psum
    # over "data", so parity is pointwise only up to reduction-order float
    # noise (a near-tied split can flip — see module docstring)
    X, y = _reg_data()
    cfg = dict(num_base_learners=10, subsample_ratio=0.9, seed=11)
    single = BaggingRegressor(**cfg).fit(X, y)
    dist = BaggingRegressor(**cfg).fit(X, y, mesh=mesh42)
    r_s, r_d = _rmse(single.predict(X), y), _rmse(dist.predict(X), y)
    assert abs(r_s - r_d) < 0.02 * max(r_s, r_d) + 1e-6, (r_s, r_d)
    # all but the flip-affected handful of rows agree tightly
    close = np.isclose(
        np.asarray(single.predict(X)), np.asarray(dist.predict(X)),
        rtol=1e-3, atol=1e-3,
    )
    assert np.mean(close) > 0.98, np.mean(close)


def test_bagging_mesh_shards_rows_and_members(mesh42):
    """The (data x member) placement contract, asserted structurally: the
    fit ctx rows shard 4-way (no device holds the full dataset) and the
    fitted members shard 2-way over "member"."""
    from spark_ensemble_tpu.models.tree import DecisionTreeRegressor

    X, y = _reg_data()
    base = DecisionTreeRegressor()
    ctx = base.make_fit_ctx(jax.numpy.asarray(X))
    fit_w, masks, keys = BaggingRegressor(num_base_learners=10)._member_plan(
        X.shape[0], X.shape[1], jax.numpy.ones(X.shape[0])
    )
    sh_ctx, _, _, _, sy, sfw, _, _ = BaggingRegressor._shard_rows_and_members(
        mesh42, base, ctx, jax.numpy.asarray(y), fit_w, masks, keys
    )
    n_pad = sy.shape[0]
    for leaf in jax.tree_util.tree_leaves(sh_ctx):
        if leaf.ndim and leaf.shape[0] == n_pad:
            local = leaf.sharding.shard_shape(leaf.shape)
            assert local[0] == n_pad // 4, (leaf.shape, local)
    # fit_w shards over (member, data): each device holds a [5, n/4] block
    assert sfw.sharding.shard_shape(sfw.shape) == (
        sfw.shape[0] // 2, n_pad // 4,
    )
    dist = BaggingRegressor(num_base_learners=10, seed=11).fit(
        X, y, mesh=mesh42
    )
    leaf = jax.tree_util.tree_leaves(dist.params["members"])[0]
    # members sharded over the "member" axis (2-way), replicated over "data"
    assert leaf.sharding.shard_shape(leaf.shape)[0] * 2 >= leaf.shape[0]


def test_bagging_classifier_mesh_parity(mesh8):
    X, y = _cls_data()
    cfg = dict(
        num_base_learners=9,  # does not divide 8: exercises uneven sharding
        voting_strategy="soft",
        subspace_ratio=0.8,
        seed=12,
    )
    single = BaggingClassifier(**cfg).fit(X, y)
    dist = BaggingClassifier(**cfg).fit(X, y, mesh=mesh8)
    ps = np.asarray(single.predict(X))
    pd = np.asarray(dist.predict(X))
    assert np.mean(ps == pd) > 0.97
    acc_s, acc_d = float(np.mean(ps == y)), float(np.mean(pd == y))
    assert abs(acc_s - acc_d) < 0.02, (acc_s, acc_d)


def test_gbm_hybrid_mesh_parity():
    """Hybrid multi-slice mesh ("dcn_data", "data", "member"): rows shard
    over BOTH data axes (ICI psum per slice + one DCN hop) and the fit
    matches the single-device model at the metric level."""
    from spark_ensemble_tpu.parallel.mesh import hybrid_data_member_mesh

    X, y = _cls_data()
    mesh = hybrid_data_member_mesh(dcn_data=2, member=2)
    assert dict(mesh.shape) == {"dcn_data": 2, "data": 2, "member": 2}
    cfg = dict(
        num_base_learners=3, loss="logloss", updates="newton",
        learning_rate=0.5, seed=5,
    )
    single = GBMClassifier(**cfg).fit(X, y)
    dist = GBMClassifier(**cfg).fit(X, y, mesh=mesh)
    ps, pd = np.asarray(single.predict(X)), np.asarray(dist.predict(X))
    assert np.mean(ps == pd) > 0.97
    acc_s, acc_d = float(np.mean(ps == y)), float(np.mean(pd == y))
    assert abs(acc_s - acc_d) < 0.02, (acc_s, acc_d)


def test_gbm_mesh_scan_chunk_invariance(mesh42):
    """The chunked SPMD dispatch must produce the same model as chunk=1 on
    the same mesh — identical psum points, identical per-round math, only
    dispatch granularity differs (pointwise: same reduction order)."""
    X, y = _cls_data()
    models = [
        GBMClassifier(
            num_base_learners=4, loss="logloss", updates="newton",
            learning_rate=0.5, seed=6, scan_chunk=c,
        ).fit(X, y, mesh=mesh42)
        for c in (1, 3)
    ]
    np.testing.assert_allclose(
        np.asarray(models[0].predict_raw(X[:200])),
        np.asarray(models[1].predict_raw(X[:200])),
        rtol=1e-5, atol=1e-5,
    )


# ---------------------------------------------------------------------------
# Boosting: the reference runs every boosting round distributed — weights as
# an RDD, error reductions as treeAggregate (`BoostingClassifier.scala:
# 175,235-242`, `BoostingRegressor.scala:232-249`).  Here fit(..., mesh=...)
# shards rows + the boosting weight vector and psums/pmaxes the round
# reductions; the host abort replay must then match the single-device run
# round for round.
# ---------------------------------------------------------------------------


def test_boosting_regressor_mesh_pointwise_single_round(mesh8):
    # n=700 not divisible by 8: exercises the zero-weight row padding AND the
    # maxError validity mask (a padded row's |y - pred| must not set the max)
    from spark_ensemble_tpu import BoostingRegressor

    X, y = _reg_data()
    cfg = dict(num_base_learners=1, loss="exponential", seed=7)
    single = BoostingRegressor(**cfg).fit(X, y)
    dist = BoostingRegressor(**cfg).fit(X, y, mesh=mesh8)
    assert single.num_members == dist.num_members == 1
    np.testing.assert_allclose(
        np.asarray(single.predict(X)), np.asarray(dist.predict(X)),
        rtol=1e-3, atol=1e-3,
    )


def test_boosting_regressor_mesh_metric_parity(mesh8):
    from spark_ensemble_tpu import BoostingRegressor

    X, y = _reg_data()
    for loss in ("linear", "squared"):
        cfg = dict(num_base_learners=6, loss=loss, seed=3)
        single = BoostingRegressor(**cfg).fit(X, y)
        dist = BoostingRegressor(**cfg).fit(X, y, mesh=mesh8)
        # abort/stop replay must fire at the same round index
        assert single.num_members == dist.num_members, loss
        r_s, r_d = _rmse(single.predict(X), y), _rmse(dist.predict(X), y)
        assert abs(r_s - r_d) < 0.03 * max(r_s, r_d) + 1e-6, (loss, r_s, r_d)


def test_boosting_classifier_mesh_discrete_parity(mesh8):
    from spark_ensemble_tpu import BoostingClassifier

    X, y = _cls_data()
    cfg = dict(num_base_learners=6, algorithm="discrete", seed=9)
    single = BoostingClassifier(**cfg).fit(X, y)
    dist = BoostingClassifier(**cfg).fit(X, y, mesh=mesh8)
    assert single.num_members == dist.num_members
    # discrete votes amplify single split flips (a psum-order ulp can move
    # one threshold, changing that member's hard vote on nearby rows), so
    # the bar is metric parity + strong-majority agreement, not pointwise
    ps, pd = np.asarray(single.predict(X)), np.asarray(dist.predict(X))
    assert np.mean(ps == pd) > 0.85
    acc_s, acc_d = float(np.mean(ps == y)), float(np.mean(pd == y))
    assert abs(acc_s - acc_d) < 0.03, (acc_s, acc_d)


def test_boosting_classifier_mesh_real_parity(mesh8):
    from spark_ensemble_tpu import BoostingClassifier

    X, y = _cls_data()
    cfg = dict(num_base_learners=5, algorithm="real", seed=9)
    single = BoostingClassifier(**cfg).fit(X, y)
    dist = BoostingClassifier(**cfg).fit(X, y, mesh=mesh8)
    assert single.num_members == dist.num_members
    # SAMME.R reweights by exp(log-prob sums), so one flipped split shifts
    # every later round's weights — parity is metric-level (accuracy),
    # exactly the tier Spark's own local-vs-cluster treeAggregate order gives
    ps, pd = np.asarray(single.predict(X)), np.asarray(dist.predict(X))
    assert np.mean(ps == pd) > 0.75
    acc_s, acc_d = float(np.mean(ps == y)), float(np.mean(pd == y))
    assert abs(acc_s - acc_d) < 0.03, (acc_s, acc_d)


def test_boosting_regressor_mesh_abort_index(mesh8):
    """Drucker's est_err >= 0.5 abort (`BoostingRegressor.scala:251`) must
    fire at the SAME round distributed: outlier rows soak up boosting weight
    until the psum-ed est_err crosses 0.5 strictly (round 3 with this seed;
    SAMME's err >= 1-1/K is NOT used here because leaf-majority trees can
    only ever TIE that threshold, which f32 reduction order could flip)."""
    from spark_ensemble_tpu import BoostingRegressor
    from spark_ensemble_tpu.models.tree import DecisionTreeRegressor

    rng = np.random.RandomState(2)
    n = 640
    X = rng.randn(n, 4).astype(np.float32)
    y = (2.0 * X[:, 0] + 0.1 * rng.randn(n)).astype(np.float32)
    y = np.where(rng.rand(n) < 0.05, y + 50.0, y).astype(np.float32)
    cfg = dict(
        num_base_learners=10,
        loss="squared",
        base_learner=DecisionTreeRegressor(max_depth=3),
        seed=1,
    )
    single = BoostingRegressor(**cfg).fit(X, y)
    dist = BoostingRegressor(**cfg).fit(X, y, mesh=mesh8)
    # the mid-run abort must actually trigger for this test to mean anything
    assert 0 < single.num_members < 10
    assert single.num_members == dist.num_members


def test_boosting_mesh_scan_chunk_invariance(mesh8):
    """Chunked SPMD dispatch == per-round dispatch on the same mesh
    (identical psum points; only dispatch granularity differs)."""
    from spark_ensemble_tpu import BoostingRegressor

    X, y = _reg_data()
    models = [
        BoostingRegressor(
            num_base_learners=5, loss="exponential", seed=4, scan_chunk=c
        ).fit(X, y, mesh=mesh8)
        for c in (1, 3)
    ]
    assert models[0].num_members == models[1].num_members
    np.testing.assert_allclose(
        np.asarray(models[0].predict(X[:200])),
        np.asarray(models[1].predict(X[:200])),
        rtol=1e-5, atol=1e-5,
    )


def test_bagging_data_only_mesh():
    """A mesh with ONLY a "data" axis (no "member") row-shards the fit and
    replicates members — the GBM-style data-parallel config must keep
    working for bagging too."""
    from spark_ensemble_tpu.parallel.mesh import create_mesh

    X, y = _reg_data()
    mesh = create_mesh({"data": 8})
    cfg = dict(num_base_learners=5, subsample_ratio=0.9, seed=11)
    single = BaggingRegressor(**cfg).fit(X, y)
    dist = BaggingRegressor(**cfg).fit(X, y, mesh=mesh)
    r_s, r_d = _rmse(single.predict(X), y), _rmse(dist.predict(X), y)
    assert abs(r_s - r_d) < 0.02 * max(r_s, r_d) + 1e-6, (r_s, r_d)


def test_gbm_mesh_validation_chunked_invariance(mesh8):
    """mesh+validation now rides the chunked SPMD program (no per-round
    dispatch path remains); the chunk size must not change the fitted model
    — same psum points, same per-round val losses, same patience replay."""
    X, y = _cls_data(n=900)
    vi = np.zeros(900, bool)
    vi[700:] = True
    models = [
        GBMClassifier(
            num_base_learners=8, loss="logloss", num_rounds=2, seed=2,
            scan_chunk=c,
        ).fit(X, y, validation_indicator=vi, mesh=mesh8)
        for c in (1, 3)
    ]
    assert models[0].num_members == models[1].num_members
    np.testing.assert_allclose(
        np.asarray(models[0].predict_raw(X[:100])),
        np.asarray(models[1].predict_raw(X[:100])),
        rtol=1e-5, atol=1e-5,
    )


def test_gbm_regressor_mesh_validation_early_stop(mesh8):
    """Regressor flavor of the chunked mesh+validation path, with huber's
    in-chunk adaptive delta alongside the val-loss evaluation."""
    X, y = _reg_data(n=900)
    vi = np.zeros(900, bool)
    vi[700:] = True
    cfg = dict(
        num_base_learners=8, loss="huber", alpha=0.9, num_rounds=2, seed=2
    )
    single = GBMRegressor(**cfg).fit(X, y, validation_indicator=vi)
    dist = GBMRegressor(**cfg).fit(X, y, validation_indicator=vi, mesh=mesh8)
    assert abs(single.num_members - dist.num_members) <= 1
    r_s = _rmse(single.predict(X), y)
    r_d = _rmse(dist.predict(X), y)
    assert abs(r_s - r_d) < 0.05 * max(r_s, r_d) + 1e-6, (r_s, r_d)


def test_gbm_classifier_mesh_indivisible_class_dim():
    """K not divisible by the member axis: phantom class-dim trees pad the
    member blocks (zero-weight fits, trimmed from the model), so ANY
    (K, member) combination works — the reference's per-dim Futures have no
    divisibility constraint either (`GBMClassifier.scala:377-411`)."""
    X, y = _cls_data(k=5)  # dim 5, member 4 -> blocks of 2 with 3 phantoms
    mesh = data_member_mesh(8, member=4)
    cfg = dict(
        num_base_learners=3, loss="logloss", updates="newton",
        learning_rate=0.5, seed=5,
    )
    single = GBMClassifier(**cfg).fit(X, y)
    dist = GBMClassifier(**cfg).fit(X, y, mesh=mesh)
    assert np.asarray(dist.predict_raw(X[:8])).shape == (8, 5)
    ps, pd = np.asarray(single.predict(X)), np.asarray(dist.predict(X))
    assert np.mean(ps == pd) > 0.95
    acc_s, acc_d = float(np.mean(ps == y)), float(np.mean(pd == y))
    assert abs(acc_s - acc_d) < 0.03, (acc_s, acc_d)


def test_gbm_mesh_validation_cross_topology_resume(mesh8, tmp_path):
    """A single-chip checkpoint whose validation split does NOT divide the
    mesh (nv=101, nv_pad would be 104) must not resume under the mesh —
    the nv_pad fingerprint part forces a fresh start instead of feeding a
    wrong-length pred_val into the SPMD program."""
    from spark_ensemble_tpu.utils.checkpoint import TrainingCheckpointer

    X, y = _cls_data(n=901)
    vi = np.zeros(901, bool)
    vi[800:] = True  # nv = 101
    ckdir = str(tmp_path / "ck")
    cfg = dict(num_base_learners=6, loss="logloss", num_rounds=3, seed=2,
               checkpoint_dir=ckdir, checkpoint_interval=2, scan_chunk=2)
    orig_delete = TrainingCheckpointer.delete
    TrainingCheckpointer.delete = lambda self: None
    try:
        GBMClassifier(**dict(cfg, num_base_learners=4)).fit(
            X, y, validation_indicator=vi
        )
    finally:
        TrainingCheckpointer.delete = orig_delete
    # mesh fit with the stale single-chip checkpoint present: fingerprint
    # mismatch (nv_pad 101 vs 104) -> trains from scratch, no crash
    m = GBMClassifier(**cfg).fit(X, y, validation_indicator=vi, mesh=mesh8)
    s = GBMClassifier(**dict(cfg, checkpoint_dir=None)).fit(
        X, y, validation_indicator=vi, mesh=mesh8
    )
    assert m.num_members == s.num_members
    np.testing.assert_allclose(
        np.asarray(m.predict_raw(X[:50])), np.asarray(s.predict_raw(X[:50])),
        rtol=1e-5, atol=1e-5,
    )


def test_stacking_members_placed_across_devices(mesh8):
    """Heterogeneous stacking members round-robin over the mesh devices
    (member i on device i mod n) — the reference overlaps member fits
    across the cluster (`StackingClassifier.scala:174-186`).  Placement is
    asserted structurally (each fitted member's params live on its own
    device); the fitted model must match the single-device fit."""
    from spark_ensemble_tpu import StackingClassifier
    from spark_ensemble_tpu.models.linear import LogisticRegression
    from spark_ensemble_tpu.models.naive_bayes import GaussianNaiveBayes
    from spark_ensemble_tpu.models.tree import DecisionTreeClassifier

    X, y = _cls_data()
    bases = lambda: [
        DecisionTreeClassifier(),
        LogisticRegression(max_iter=30),
        GaussianNaiveBayes(),
    ]
    cfg = dict(stack_method="proba", parallelism=3, seed=0)
    single = StackingClassifier(base_learners=bases(), **cfg).fit(X, y)
    dist = StackingClassifier(base_learners=bases(), **cfg).fit(
        X, y, mesh=mesh8
    )
    devs = []
    for m in dist.base_models:
        leaves = jax.tree_util.tree_leaves(m.params)
        ds = {d for leaf in leaves for d in leaf.sharding.device_set}
        assert len(ds) == 1, ds  # each member entirely on one device
        devs.append(next(iter(ds)))
    assert len(set(devs)) == 3, devs  # three members, three distinct devices
    np.testing.assert_allclose(
        np.asarray(single.predict_proba(X[:200])),
        np.asarray(dist.predict_proba(X[:200])),
        rtol=2e-3, atol=2e-3,
    )


def test_base_learner_standalone_mesh_fit(mesh8):
    """EVERY base learner trains distributed standalone through the one
    generic shard_map fit (the protocol's axis_name contract): trees,
    logistic/linear, naive bayes, dummy — pointwise parity with the
    single-device fit."""
    from spark_ensemble_tpu.models.dummy import DummyClassifier, DummyRegressor
    from spark_ensemble_tpu.models.linear import (
        LinearRegression,
        LogisticRegression,
    )
    from spark_ensemble_tpu.models.naive_bayes import GaussianNaiveBayes
    from spark_ensemble_tpu.models.tree import (
        DecisionTreeClassifier,
        DecisionTreeRegressor,
    )

    Xr, yr = _reg_data()
    Xc, yc = _cls_data()
    cases = [
        (DecisionTreeRegressor(max_depth=3), Xr, yr, 1e-3),
        (LinearRegression(), Xr, yr, 2e-3),
        (DummyRegressor(strategy="median"), Xr, yr, 1e-5),
        (DecisionTreeClassifier(max_depth=3), Xc, yc, 1e-3),
        (DummyClassifier(strategy="prior"), Xc, yc, 1e-5),
        (LogisticRegression(max_iter=25), Xc, yc, 5e-3),
        (GaussianNaiveBayes(), Xc, yc, 1e-3),
    ]
    for est, X, y, tol in cases:
        single = est.copy().fit(X, y)
        dist = est.copy().fit(X, y, mesh=mesh8)
        np.testing.assert_allclose(
            np.asarray(single.predict(X)), np.asarray(dist.predict(X)),
            rtol=tol, atol=tol,
            err_msg=type(est).__name__,
        )


def test_distributed_inference_via_sharded_inputs(mesh8):
    """Inference distributes with ZERO model code: device_put X row-sharded
    and the cached predict programs partition under GSPMD — outputs come
    back row-sharded and bit-consistent with single-device predict."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    X, y = _cls_data(n=960)
    m = GBMClassifier(num_base_learners=3, loss="logloss", seed=1).fit(X, y)
    Xs = jax.device_put(
        jax.numpy.asarray(X), NamedSharding(mesh8, P("data", None))
    )
    p_sharded = m.predict_proba(Xs)
    np.testing.assert_allclose(
        np.asarray(p_sharded), np.asarray(m.predict_proba(X)),
        rtol=1e-5, atol=1e-6,
    )
    # the output rides the input's sharding (no gather to one device)
    assert "data" in str(p_sharded.sharding.spec)


def test_boosting_and_bagging_hybrid_mesh():
    """Boosting and Bagging on the multi-slice hybrid mesh: rows shard over
    BOTH data axes (("dcn_data", "data") psum/pmax; bagging's member axis
    stays within a slice) — only GBM's hybrid leg was covered before."""
    from spark_ensemble_tpu import BoostingRegressor
    from spark_ensemble_tpu.parallel.mesh import hybrid_data_member_mesh

    X, y = _reg_data()
    mesh = hybrid_data_member_mesh(dcn_data=2, member=2)
    cfg = dict(num_base_learners=4, loss="exponential", seed=5)
    single = BoostingRegressor(**cfg).fit(X, y)
    dist = BoostingRegressor(**cfg).fit(X, y, mesh=mesh)
    assert single.num_members == dist.num_members
    r_s, r_d = _rmse(single.predict(X), y), _rmse(dist.predict(X), y)
    assert abs(r_s - r_d) < 0.03 * max(r_s, r_d) + 1e-6, (r_s, r_d)

    bcfg = dict(num_base_learners=6, subsample_ratio=0.9, seed=6)
    bs = BaggingRegressor(**bcfg).fit(X, y)
    bd = BaggingRegressor(**bcfg).fit(X, y, mesh=mesh)
    rb_s, rb_d = _rmse(bs.predict(X), y), _rmse(bd.predict(X), y)
    assert abs(rb_s - rb_d) < 0.03 * max(rb_s, rb_d) + 1e-6, (rb_s, rb_d)


# --- communication contract -------------------------------------------------
#
# The distributed design's scalability claim, asserted mechanically: on a
# pure data mesh, one GBM round communicates O(1) collectives carrying
# O(nodes * bins * k) bytes — NEVER anything proportional to the row count
# (the reference's treeAggregate contract, `GBMClassifier.scala:413-431`;
# the gather-free quantile and histogram-psum design, ops/tree.py +
# utils/quantile.py).  The REAL estimator programs are compiled in a
# subprocess with --xla_dump_to and the optimized-HLO collectives compared
# across two row counts: identical (op, shape) multisets == both the
# collective COUNT and the communicated BYTES are independent of n.  The
# test fails if anyone reintroduces a row-length all_gather.

_CONTRACT_FIT = """
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import spark_ensemble_tpu as se
from spark_ensemble_tpu.parallel.mesh import data_member_mesh

n = {n}
rng = np.random.RandomState(0)
X = rng.randn(n, 8).astype(np.float32)
yc = rng.randint(0, 4, n).astype(np.float32)
yr = (X @ rng.randn(8).astype(np.float32) + rng.randn(n)).astype(np.float32)
mesh = data_member_mesh(8, member=1)
se.GBMClassifier(
    num_base_learners=2, loss="logloss", updates="newton",
    optimized_weights=True,
).fit(X, yc, mesh=mesh)
se.GBMRegressor(
    num_base_learners=2, loss="huber",  # huber: mesh quantile path
).fit(X, yr, mesh=mesh)
print("contract fit ok")
"""


def _collect_collectives(dump_dir):
    """Multiset of (op, normalized shape) over every optimized-HLO module,
    plus the largest dimension seen in any collective shape."""
    import collections
    import glob
    import re

    ops = collections.Counter()
    max_dim = 0
    pat = re.compile(
        r"= (\([^)]*\)|\S+) (all-reduce|all-gather|all-to-all|"
        r"reduce-scatter|collective-permute)\("
    )
    for path in glob.glob(os.path.join(dump_dir, "*after_optimizations.txt")):
        with open(path) as f:
            for line in f:
                m = pat.search(line)
                if not m:
                    continue
                shape = re.sub(r"\{[^}]*\}", "", m.group(1))  # drop layouts
                ops[(m.group(2), shape)] += 1
                for dim in re.findall(r"\d+", shape):
                    max_dim = max(max_dim, int(dim))
    return ops, max_dim


def test_mesh_round_collectives_independent_of_n(tmp_path):
    """See the section comment: (a) the collective inventory of the whole
    compiled fit is IDENTICAL at n=1024 and n=4096, (b) no collective
    operand carries a row-sized dimension at either n."""
    import subprocess
    import sys

    inventories = {}
    for n in (1024, 4096):
        dump = tmp_path / f"dump_{n}"
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=8 "
            f"--xla_dump_to={dump} --xla_dump_hlo_pass_re=NONE"
        )
        p = subprocess.run(
            [sys.executable, "-c", _CONTRACT_FIT.format(n=n)],
            env=env, capture_output=True, text=True, timeout=900,
        )
        assert p.returncode == 0, p.stderr[-3000:]
        ops, max_dim = _collect_collectives(str(dump))
        assert ops, "no collectives found — dump layout changed?"
        # (b) nothing row-sized crosses the mesh.  The absolute guard only
        # bites at the larger n (the 256-bin quantile histograms are a
        # FIXED width that exceeds the small run's 128-row shards); any
        # row-proportional operand would also break the equality below.
        if n // 8 > 256:
            assert max_dim < n // 8, (
                f"collective operand carries a row-sized dim at n={n}: "
                f"max {max_dim}"
            )
        inventories[n] = ops
    # (a) count AND shapes identical across a 4x row-count change
    assert inventories[1024] == inventories[4096], (
        "collective inventory depends on n:\n"
        f"only@1024: {inventories[1024] - inventories[4096]}\n"
        f"only@4096: {inventories[4096] - inventories[1024]}"
    )


def test_gbm_stream_tier_hybrid_mesh_parity():
    """Stream tier over the multi-slice hybrid mesh: its post-scan psum
    and scan-carry pvary must handle the TUPLE row axis
    ("dcn_data", "data") exactly like the dense path."""
    import spark_ensemble_tpu.ops.tree as T
    from spark_ensemble_tpu.models.tree import DecisionTreeRegressor
    from spark_ensemble_tpu.parallel.mesh import hybrid_data_member_mesh

    X, y = _reg_data(n=768)
    mesh = hybrid_data_member_mesh(dcn_data=2, member=2)
    cfg = dict(num_base_learners=3, learning_rate=0.5, seed=7)
    single = GBMRegressor(
        base_learner=DecisionTreeRegressor(hist="stream"), **cfg
    ).fit(X, y)
    dist = GBMRegressor(
        base_learner=DecisionTreeRegressor(hist="stream"), **cfg
    ).fit(X, y, mesh=mesh)
    r_s, r_d = _rmse(single.predict(X), y), _rmse(dist.predict(X), y)
    assert abs(r_s - r_d) < 0.03 * max(r_s, r_d) + 1e-6, (r_s, r_d)
