"""Benchmark: GBM boosting-iters/sec/chip on letter (26-class, 100 rounds)
plus predict rows/sec — the primary metric pinned by BASELINE.json.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Resilience (this environment's TPU plugin init can hang indefinitely or
error — it took down the round-1 bench): the parent process never touches
jax.  It probes the accelerator in a SUBPROCESS with a timeout, retries with
backoff, runs the measured bench in another subprocess (also bounded), and
on any failure falls back to a CPU-pinned run — so a JSON line is always
produced, carrying an "error" field when the accelerator was unreachable.

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
baseline is the first driver-captured number of this project, recorded in
_BASELINES below per device kind; 1.0 until one exists for the device.
"""

import json
import os
import subprocess
import sys
import time
from datetime import datetime, timezone

_REPO = os.path.dirname(os.path.abspath(__file__))

# One stable metric name across accelerator / CPU-fallback / failure paths —
# the round count varies per path and lives in the "num_rounds" field.
_METRIC = "GBM boosting-iters/sec/chip (letter)"

# extras sections of the bench battery: main() arms them all on a green
# accelerator probe, and inner() prints the salvage-partial headline line
# whenever any is enabled — ONE tuple so the two gates cannot drift
_BATTERY_KNOBS = ("BENCH_FULL", "BENCH_LARGE", "BENCH_TIERS", "BENCH_XL")

# First driver-captured iters/sec per device platform (see BASELINE.md).
# vs_baseline for later rounds = measured / baseline on the same platform.
#
# PROTOCOL NOTE (round 3): timed fits now block on the model params.  The
# earlier protocol timed only dispatch — jax's async dispatch let fit()
# return ~5.8x before the CPU device work finished (measured round 3), so
# pre-round-3 captures are dispatch rates, not compute rates.  Both
# baselines below are blocking-protocol captures, so vs_baseline compares
# like with like on either platform.
_BASELINES = {
    # round 3 blocking-protocol capture, letter 20 rounds on CPU
    "cpu": 2.373,
    # round 3 blocking-protocol real-chip capture, TPU v5 lite, letter
    # 100 rounds, newton+line-search (BENCH_TPU_CAPTURE.json round 3;
    # supersedes the round-2 dispatch-biased 6.991)
    "tpu": 20.30,
}


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _cpu_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_FORCE_CPU"] = "1"
    return env


def _probe_accelerator(timeout_s):
    """Check (in a subprocess, so a hang cannot take us down) that jax can
    bring up the default backend."""
    code = (
        "import jax; ds = jax.devices(); "
        "print(ds[0].platform, len(ds))"
    )
    try:
        p = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend init timed out after {timeout_s}s"
    if p.returncode != 0:
        return False, (p.stderr or p.stdout).strip()[-500:]
    return True, p.stdout.strip()


def _run_inner(env, timeout_s):
    """Run the measured bench in a subprocess; return (json_dict | None, err).

    The inner process prints the HEADLINE json line as soon as it is
    measured and the full line at the end; the LAST parseable line wins —
    so a timeout mid-extras (a perishable accelerator window closing)
    still salvages the headline from the partial stdout."""
    err = None
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--inner"],
            env=env,
            cwd=_REPO,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        stdout, stderr = p.stdout, p.stderr
        if p.returncode != 0:
            # a crash AFTER the partial print must not read as success
            err = (
                f"inner exited rc={p.returncode}: "
                f"{(stderr or '').strip()[-300:]}"
            )
    except subprocess.TimeoutExpired as e:
        err = f"bench run timed out after {timeout_s}s"
        stdout = e.stdout or ""
        stderr = e.stderr or ""
        if isinstance(stdout, bytes):
            stdout = stdout.decode(errors="replace")
        if isinstance(stderr, bytes):
            stderr = stderr.decode(errors="replace")
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            result = json.loads(line)
            if result.pop("partial", None) is not None:
                # the salvage marker is consumed here: record what
                # actually happened to the extras instead
                cause = err or "inner stopped after the headline"
                result["extras"] = "lost"
                result["error"] = (
                    f"extras lost ({cause}); headline salvaged from the "
                    "partial line"
                )
            elif err:
                result["error"] = err
            return result, None
        except json.JSONDecodeError:
            continue
    return None, err or (stderr or stdout).strip()[-800:] or "no output"


def main():
    # probe ONCE by default and fail fast to the CPU leg: this
    # environment's TPU init hang is bimodal (up or hung), and 4 backoff
    # probes burned ~16 min of every capture window for nothing (BENCH_r05).
    # BENCH_PROBE_RETRIES opts back into retrying where init flakes clear.
    probe_timeout = _env_int("BENCH_PROBE_TIMEOUT", 240)
    retries = max(1, _env_int("BENCH_PROBE_RETRIES", 1))
    inner_timeout = _env_int("BENCH_TIMEOUT", 3600)

    # 'pallas' is a legitimate headline tier on TPU (off-TPU the estimator
    # falls back to the 'high' matmul tier at bench scale, ops/tree.py);
    # the canonical-capture rule below stays pinned to 'highest'
    hp = os.environ.get("BENCH_HIST_PRECISION", "highest")
    if hp not in ("highest", "high", "default", "pallas"):
        # reject up front: a typo'd knob must not burn both bounded
        # subprocess runs before surfacing
        print(json.dumps({
            "metric": _METRIC, "value": 0.0, "unit": "iters/sec",
            "vs_baseline": 0.0,
            "error": "BENCH_HIST_PRECISION must be "
                     f"highest|high|default|pallas, got {hp!r}",
        }))
        return 1

    errors = []
    tpu_unavailable = None
    ok = False
    for attempt in range(retries):
        ok, info = _probe_accelerator(probe_timeout)
        if ok:
            tpu_unavailable = None
            break
        # ONE structured record instead of per-probe error spam: the
        # driver JSON gets a machine-readable reason, not a joined string
        tpu_unavailable = {
            "reason": info,
            "probes": attempt + 1,
            "probe_timeout_s": probe_timeout,
        }
        if attempt + 1 < retries:
            # accelerator init hangs are server-side and can clear after
            # minutes; back off harder before burning another probe
            time.sleep(min(60 * (attempt + 1), 240))

    if ok:
        # a green REAL-accelerator probe is a PERISHABLE window
        # (BASELINE.md): arm the full battery so one window yields the
        # headline AND the per-config/large/tier fields without anyone
        # asking.  A green CPU-backend probe (no accelerator registered)
        # is not a window — no arming.  Explicit BENCH_*=0 still disables
        # a section.
        env = dict(os.environ)
        # the probe's platform is the LAST stdout line (plugin init may
        # print noise first); arm the battery only for a RECOGNIZED real
        # accelerator — empty/garbled probe output must not trigger the
        # tens-of-minutes battery
        probed_platform = (info.splitlines() or [""])[-1].split(" ")[0]
        armed = probed_platform in ("tpu", "gpu", "cuda", "rocm")
        if armed:
            for knob in _BATTERY_KNOBS:
                env.setdefault(knob, "1")
        did_arm = env != dict(os.environ)
        result, err = _run_inner(env, inner_timeout)
        if result is None and did_arm:
            # the AUTO-armed battery overran the timeout; the window may
            # still be open — salvage the headline with a bare retry.
            # (If the user set the knobs themselves, a retry would rerun
            # the identical config: skip it.)
            errors.append(f"armed accelerator bench: {err}")
            result, err = _run_inner(dict(os.environ), inner_timeout)
        if result is None:
            errors.append(f"accelerator bench: {err}")
        else:
            result["value"] = result.get("value", 0.0)
            # a green accelerator run is not degraded: earlier probe
            # failures are warnings, not errors
            _finish(result, [], warnings=errors)
            canonical = (
                result.get("platform") not in (None, "cpu")
                and hp == "highest"
                and int(result.get("num_rounds") or 0) >= 100
            )
            if canonical:
                # only the canonical config (exact precision, full round
                # count) is committed as the real-chip capture; smoke runs
                # and tier comparisons must not clobber it
                # persist the perishable-window evidence AFTER _finish so
                # the capture carries vs_baseline; later CPU-fallback runs
                # embed it under "last_tpu"
                try:
                    with open(
                        os.path.join(_REPO, "BENCH_TPU_CAPTURE.json"), "w"
                    ) as f:
                        json.dump(result, f, indent=1)
                except OSError:
                    pass
            return 0

    # CPU fallback: fewer rounds (same metric — iters/sec), error carried.
    # The latest committed real-chip capture (BENCH_TPU_CAPTURE.json, written
    # the moment a TPU window opens) rides along under "last_tpu" so the
    # driver-recorded JSON always carries real-chip evidence.
    env = _cpu_env()
    env.setdefault("BENCH_ROUNDS", os.environ.get("BENCH_ROUNDS_CPU", "20"))
    result, err = _run_inner(env, inner_timeout)
    last_tpu = _load_last_tpu_capture()
    if result is not None and last_tpu is not None:
        result["last_tpu"] = last_tpu
    if result is None:
        errors.append(f"cpu fallback: {err}")
        result = {
            "metric": _METRIC,
            "value": 0.0,
            "unit": "iters/sec",
            "vs_baseline": 0.0,
        }
        if tpu_unavailable is not None:
            result["tpu_unavailable"] = tpu_unavailable
        _finish(result, errors)
        return 1
    if tpu_unavailable is not None:
        result["tpu_unavailable"] = tpu_unavailable
    _finish(result, errors)
    return 0


def _load_last_tpu_capture():
    """The committed real-chip capture, if any (see CPU-fallback note).

    Replayed legs are STAMPED: every ``last_tpu`` embed carries
    ``tpu_capture_stale: true`` plus the capture file's mtime, so a
    BENCH_r*.json reader can tell a months-old replay (e.g. the pre-fused
    mfu_est ≈ 0.005 capture riding along since r03) from fresh real-chip
    numbers — the numbers describe the capture's commit, not this run."""
    path = os.path.join(_REPO, "BENCH_TPU_CAPTURE.json")
    try:
        with open(path) as f:
            capture = json.load(f)
        capture["tpu_capture_stale"] = True
        capture["tpu_capture_mtime"] = datetime.fromtimestamp(
            os.path.getmtime(path), tz=timezone.utc
        ).isoformat(timespec="seconds")
        return capture
    except (OSError, json.JSONDecodeError):
        return None


def _finish(result, errors, warnings=None):
    if errors:
        # append to (never clobber) an error the inner run already carries
        # — e.g. the extras-lost note on a salvaged partial headline
        prior = result.get("error")
        result["error"] = "; ".join(
            ([prior] if prior else []) + errors
        )[-1000:]
    if warnings:
        result["warnings"] = "; ".join(warnings)[-1000:]
    platform = result.get("platform", "cpu")
    base = _BASELINES.get(platform)
    if base and result.get("value"):
        result["vs_baseline"] = round(result["value"] / base, 3)
    else:
        result.setdefault("vs_baseline", 1.0)
    print(json.dumps(result))


# ---------------------------------------------------------------------------
# inner: the actual measurement (runs in a subprocess the parent bounds)
# ---------------------------------------------------------------------------

def _load_letter():
    import numpy as np

    from spark_ensemble_tpu.utils.datasets import has_reference_data, load_dataset

    if has_reference_data():
        return load_dataset("letter")
    rng = np.random.RandomState(0)
    X = rng.randn(15000, 16).astype(np.float32)
    centers = rng.randn(26, 16).astype(np.float32)
    y = np.argmax(X @ centers.T + 0.5 * rng.randn(15000, 26), axis=1).astype(
        np.float32
    )
    return X, y


def _peak_flops(platform: str) -> float:
    """Rough dense-matmul peak for the MFU estimate (v5e bf16 ~197 TFLOP/s;
    nominal 1 TFLOP/s for the CPU fallback)."""
    return 197e12 if platform != "cpu" else 1e12


def _flops_per_round(n, d, k, max_depth, max_bins):
    """FLOP estimate for one GBM round, matmul-histogram path: per level,
    H = A^T[nodes*(1+1), n] @ bin_oh[n, d*B] per class dim, plus leaf pass."""
    per_tree = sum(
        2.0 * n * (2**level * 2) * (d * max_bins)
        for level in range(max_depth)
    ) + 2.0 * n * (2**max_depth * 2)
    return per_tree * k


def _bench_full_extras():
    """BENCH_FULL=1: wall-clock the other BASELINE.md perf configs
    (bagging/boosting/gbm-regressor/stacking on their pinned datasets).
    Returns a dict of extra fields; failures are recorded, not fatal."""
    import time as _time

    import spark_ensemble_tpu as se
    from spark_ensemble_tpu.utils.datasets import has_reference_data, load_dataset

    if not has_reference_data():
        return {"full_error": "reference datasets unavailable"}
    out = {}
    cpusmall = load_dataset("cpusmall")
    adult = load_dataset("adult")

    # ONE stacking config for both the single-device and mesh timings —
    # they must fit the same model or the comparison is meaningless
    def stacking_fit(mesh=None):
        return se.StackingClassifier(
            base_learners=[
                se.DecisionTreeClassifier(),
                se.LogisticRegression(),
                se.GaussianNaiveBayes(),
            ],
            stacker=se.LogisticRegression(),
        ).fit(*adult, mesh=mesh)

    cases = {
        # BaggingRegressor(DT, 10) on cpusmall
        "bagging_cpusmall_fit_s": lambda: se.BaggingRegressor(
            num_base_learners=10
        ).fit(*cpusmall),
        # BoostingClassifier (depth-1 stumps) on adult
        "boosting_adult_fit_s": lambda: se.BoostingClassifier(
            base_learner=se.DecisionTreeClassifier(max_depth=1),
            num_base_learners=10,
        ).fit(*adult),
        # GBMRegressor (squared, 100 rounds) on cpusmall
        "gbmreg_cpusmall_fit_s": lambda: se.GBMRegressor(
            num_base_learners=100
        ).fit(*cpusmall),
        # linear-leaf members reach comparable loss in 10 rounds
        # (models/linear_tree.py; extension beyond the reference)
        "gbmreg_cpusmall_lineartree10_fit_s": lambda: se.GBMRegressor(
            base_learner=se.LinearTreeRegressor(max_depth=5),
            num_base_learners=10,
            learning_rate=0.3,
        ).fit(*cpusmall),
        # StackingClassifier (DT + LR + NB, LR meta) on adult
        "stacking_adult_fit_s": stacking_fit,
    }
    for name, fn in cases.items():
        try:
            fn()  # warmup/compile
            t0 = _time.perf_counter()
            model = fn()
            _block_on_model(model)
            out[name] = round(_time.perf_counter() - t0, 3)
        except Exception as e:  # noqa: BLE001 - carry the error, keep going
            out[name + "_error"] = str(e)[:200]

    # mesh-vs-single stacking: round-robin member placement only wins
    # wall-clock with >1 device (models/stacking.py _fit_bases); on a
    # single-chip run the field records why it was skipped
    import jax

    from spark_ensemble_tpu.parallel.mesh import data_member_mesh

    if len(jax.devices()) > 1:
        try:
            mesh = data_member_mesh(len(jax.devices()), member=1)
            mk = lambda: stacking_fit(mesh)  # noqa: E731
            mk()  # warmup/compile
            t0 = _time.perf_counter()
            model = mk()
            _block_on_model(model)
            out["stacking_adult_mesh_fit_s"] = round(
                _time.perf_counter() - t0, 3
            )
        except Exception as e:  # noqa: BLE001 - carry the error, keep going
            out["stacking_adult_mesh_error"] = str(e)[:200]
    else:
        out["stacking_adult_mesh_note"] = "single device; mesh placement moot"
    out["full_autotune"] = _autotune_record()
    return out


def _bench_large_extras():
    """BENCH_LARGE=1: a synthetic large-batch GBM config (n=131072, d=32,
    8 classes) where the histogram matmuls dominate dispatch — the MFU
    scaling point BASELINE.md's roofline note predicts.  Extra JSON fields;
    failures recorded, not fatal."""
    import time as _time

    import numpy as np

    import jax

    from spark_ensemble_tpu import GBMClassifier

    try:
        n, d, k = 131072, 32, 8
        rng = np.random.RandomState(0)
        X = rng.randn(n, d).astype(np.float32)
        centers = rng.randn(k, d).astype(np.float32)
        y = np.argmax(X @ centers.T + 0.5 * rng.randn(n, k), axis=1).astype(
            np.float32
        )
        rounds = _env_int("BENCH_LARGE_ROUNDS", 20)
        est = GBMClassifier(
            num_base_learners=rounds, loss="logloss", updates="newton",
            learning_rate=0.3,
        )
        # warmup with the SAME round count: the scan-chunked loop compiles
        # one program per distinct chunk length (16 and the remainder), so a
        # 1-round warmup would leave both compiles inside the timed window
        est.fit(X, y)
        model, fit_s = _timed_fit(est, X, y)
        flops = _flops_per_round(n, d, k, 5, 64)
        platform = jax.devices()[0].platform
        out = {
            "large_iters_per_sec": round(rounds / fit_s, 3),
            "large_fit_seconds": round(fit_s, 2),
            "large_config": f"synthetic n={n} d={d} k={k} rounds={rounds}",
            "large_autotune": _autotune_record(n),
        }
        if platform != "cpu":
            # see inner(): MFU is only reported against a real chip's peak
            out["large_mfu_est"] = round(
                flops * (rounds / fit_s) / _peak_flops(platform), 5
            )
        if platform == "tpu":
            # the pallas histogram tier's HBM win scales with n (the
            # bin-one-hot it avoids streaming is ~1 GB here) — time it at
            # the large config whenever a real chip can compile it
            try:
                from spark_ensemble_tpu import DecisionTreeRegressor

                p_est = est.copy(
                    base_learner=DecisionTreeRegressor(
                        hist_precision="pallas"
                    )
                )
                p_est.fit(X, y)  # warmup/compile
                _, p_fit_s = _timed_fit(p_est, X, y)
                out["large_pallas_iters_per_sec"] = round(
                    rounds / p_fit_s, 3
                )
            except Exception as e:  # noqa: BLE001 - carry, keep going
                out["large_pallas_error"] = str(e)[:200]
        return out
    except Exception as e:  # noqa: BLE001 - carry the error, keep going
        return {"large_error": str(e)[:200]}


def _bench_xl_extras():
    """BENCH_XL=1: HBM-relevant scale — n=2,097,152 x d=64, k=8, 64 bins,
    hist='stream' (the row-chunked tier, ops/tree.py _fit_forest_streamed;
    the dense path's bin-one-hot operand alone would be ~16 GB here).  On
    CPU the row count drops (BENCH_XL_ROWS, default 262144) so the same
    tier program still executes end-to-end; the full-scale number rides a
    TPU window.  Extra JSON fields; failures recorded, not fatal."""
    import numpy as np

    import jax

    from spark_ensemble_tpu import DecisionTreeRegressor, GBMClassifier

    try:
        platform = jax.devices()[0].platform
        n = _env_int(
            "BENCH_XL_ROWS", 2_097_152 if platform != "cpu" else 262_144
        )
        d, k = 64, 8
        rng = np.random.RandomState(0)
        X = rng.randn(n, d).astype(np.float32)
        centers = rng.randn(k, d).astype(np.float32)
        y = np.argmax(
            X @ centers.T + 0.5 * rng.randn(n, k), axis=1
        ).astype(np.float32)
        rounds = _env_int(
            "BENCH_XL_ROUNDS", 10 if platform != "cpu" else 3
        )
        est = GBMClassifier(
            num_base_learners=rounds, loss="logloss", updates="newton",
            learning_rate=0.3,
            base_learner=DecisionTreeRegressor(hist="stream"),
        )
        # warmup at the SAME round count (see _bench_large_extras)
        est.fit(X, y)
        model, fit_s = _timed_fit(est, X, y)
        flops = _flops_per_round(n, d, k, 5, 64)
        out = {
            "xl_iters_per_sec": round(rounds / fit_s, 3),
            "xl_fit_seconds": round(fit_s, 2),
            "xl_config": (
                f"synthetic n={n} d={d} k={k} rounds={rounds} hist=stream"
            ),
            "xl_autotune": _autotune_record(n),
        }
        if platform != "cpu":
            out["xl_mfu_est"] = round(
                flops * (rounds / fit_s) / _peak_flops(platform), 5
            )
            # the 3-pass tier cuts the stream matmuls' MXU passes in half;
            # capture the comparison in the same perishable window
            try:
                h_est = est.copy(
                    base_learner=est.base_learner.copy(
                        hist_precision="high"
                    )
                )
                h_est.fit(X, y)  # warmup/compile
                _, h_fit_s = _timed_fit(h_est, X, y)
                out["xl_high_iters_per_sec"] = round(rounds / h_fit_s, 3)
            except Exception as e:  # noqa: BLE001 - carry, keep going
                out["xl_high_error"] = str(e)[:200]
        return out
    except Exception as e:  # noqa: BLE001 - carry the error, keep going
        return {"xl_error": str(e)[:200]}


def _bench_fleet(model, X, y, num_rounds):
    """Fleet load-generator leg (docs/fleet.md): closed-loop batteries
    against the replicated router at 0 and 1 injected replica faults, plus
    a skewed two-model open-loop.  The resilience evidence rides the BENCH
    json: a replica killed under load fails ZERO requests ("failed" in the
    faulted leg) and the faulted p99 stays within small multiples of the
    clean leg ("p99_fault_ratio").  Failures recorded, not fatal."""
    import threading as _th

    import numpy as np

    from spark_ensemble_tpu import GBMClassifier
    from spark_ensemble_tpu.serving import FleetRouter, InferenceEngine

    try:
        tier = max(1, num_rounds // 4)
        req_rows, n_req, n_threads = 32, 96, 4
        reqs = [
            np.asarray(X[(i * 131) % (X.shape[0] - req_rows) :][:req_rows])
            for i in range(n_req)
        ]
        # ONE warmed engine feeds every leg: replicas are clones sharing
        # its AOT programs, so the fleets below add zero compile cost
        base = InferenceEngine(
            model, prefix_tiers=(tier,), min_bucket=32, max_batch_size=256,
            label="bench-fleet",
        )

        def _closed_loop(kill_at=None):
            failed = [0]

            def _run(fleet):
                def worker(tid):
                    for i in range(tid, n_req, n_threads):
                        if kill_at is not None and tid == 0 and i == kill_at:
                            fleet.kill_replica()
                        try:
                            fleet.predict(reqs[i], deadline_ms=10_000.0)
                        except Exception:  # noqa: BLE001 - counted, not fatal
                            failed[0] += 1

                threads = [
                    _th.Thread(target=worker, args=(t,))
                    for t in range(n_threads)
                ]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=300)
                wall = time.perf_counter() - t0
                snap = fleet.slo_snapshot()
                return {
                    "qps": round(n_req / wall, 1),
                    "p50_ms": round(snap["p50_ms"], 3),
                    "p99_ms": round(snap["p99_ms"], 3),
                    "failed": failed[0],
                    "hedge_rate": round(
                        snap["hedges_fired"] / max(snap["requests"], 1), 4
                    ),
                    "degraded_share": round(snap["degraded_share"], 4),
                    "replays": snap["replays"],
                    "crashes": snap["crashes"],
                    "shed": snap["shed"],
                    "compiles_after_warmup": snap["compiles_since_warmup"],
                }

            with FleetRouter(
                base, replicas=2, deadline_ms=10_000.0, label="bench-fleet"
            ) as fleet:
                return _run(fleet)

        clean = _closed_loop()
        faulted = _closed_loop(kill_at=(n_req // 2 // n_threads) * n_threads)

        # hot-swap leg (docs/autopilot.md): the same closed loop while a
        # rolling registry swap AND one add/remove elastic cycle run
        # mid-stream.  Evidence: dropped_requests is exactly 0 (the
        # torn-free rebind holds queued requests and replays them on the
        # new engine), swap_p99_ratio stays within small multiples of the
        # clean leg, and scale_up_warm_ms prices the zero-compile clone
        # warm-in — all three floored by tools/perf_sentinel.py
        from spark_ensemble_tpu.serving import ModelRegistry

        def _swap_loop():
            failed = [0]
            ops = {}
            registry = ModelRegistry(
                capacity=4, min_bucket=32, max_batch_size=256,
            )
            registry.register("prod", base.packed, warm=True)
            # "next" is a refreshed generation stand-in: the prefix slice
            # reuses the fit, and its registry engine is pre-warmed so the
            # rolling swap itself compiles NOTHING
            registry.register("next", base.packed.take(tier), warm=True)
            fleet = FleetRouter.from_registry(
                registry, "prod", replicas=2, deadline_ms=10_000.0,
                label="bench-swap",
            )
            swap_at = (n_req // 2 // n_threads) * n_threads

            def worker(tid):
                for i in range(tid, n_req, n_threads):
                    if tid == 0 and i == swap_at:
                        ops["swap"] = fleet.swap_model("next")
                        t0 = time.perf_counter()
                        added = fleet.add_replica()
                        ops["scale_up_warm_ms"] = round(
                            (time.perf_counter() - t0) * 1e3, 3
                        )
                        fleet.remove_replica(added)
                    try:
                        fleet.predict(reqs[i], deadline_ms=10_000.0)
                    except Exception:  # noqa: BLE001 - counted, not fatal
                        failed[0] += 1

            threads = [
                _th.Thread(target=worker, args=(t,))
                for t in range(n_threads)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            wall = time.perf_counter() - t0
            snap = fleet.slo_snapshot()
            fleet.stop()
            registry.close()
            return {
                "qps": round(n_req / wall, 1),
                "p50_ms": round(snap["p50_ms"], 3),
                "p99_ms": round(snap["p99_ms"], 3),
                "failed": failed[0],
                "swap_ms": round(ops["swap"]["swap_ms"], 3),
                "swap_compiles": ops["swap"]["swap_compiles"],
                "scale_up_warm_ms": ops["scale_up_warm_ms"],
                "version": snap["version"],
                "compiles_after_warmup": snap["compiles_since_warmup"],
            }

        swap = _swap_loop()

        # skewed two-model open-loop: 90% of paced submits hit the hot
        # fleet, 10% a small cold model — the multi-model routing picture
        small = GBMClassifier(
            num_base_learners=5, loss="logloss", learning_rate=0.3
        ).fit(X[:2048], y[:2048])
        shed = [0]
        with FleetRouter(
            base, replicas=2, deadline_ms=10_000.0, label="bench-hot"
        ) as hot, FleetRouter(
            small, replicas=1, min_bucket=32, max_batch_size=256,
            deadline_ms=10_000.0, label="bench-cold",
        ) as cold:
            futs = []
            t0 = time.perf_counter()
            for i in range(n_req):
                target = cold if i % 10 == 9 else hot
                try:
                    futs.append(target.submit(reqs[i % len(reqs)]))
                except Exception:  # noqa: BLE001 - open loop: sheds counted
                    shed[0] += 1
                time.sleep(0.0005)
            for f in futs:
                f.result(timeout=300)
            wall = time.perf_counter() - t0
            hsnap, csnap = hot.slo_snapshot(), cold.slo_snapshot()
            open_loop = {
                "qps": round(len(futs) / wall, 1),
                "hot_p99_ms": round(hsnap["p99_ms"], 3),
                "cold_p99_ms": round(csnap["p99_ms"], 3),
                "hedge_rate": round(
                    (hsnap["hedges_fired"] + csnap["hedges_fired"])
                    / max(hsnap["requests"] + csnap["requests"], 1),
                    4,
                ),
                "degraded_share": round(
                    (hsnap["degraded"] + csnap["degraded"])
                    / max(hsnap["requests"] + csnap["requests"], 1),
                    4,
                ),
                "shed": shed[0],
            }
        # drift-sketch A/B (telemetry/quality.py): the same packed model
        # served through warm programs with the fused histogram capture off
        # vs on, identical request sequence, interleaved passes so shared
        # machine noise cancels — the steady-state cost of the quality
        # plane's sketch, which the sentinel floors at <2%
        # (drift_overhead_pct, docs/quality.md#overhead)
        drift_overhead_pct = None
        packed = base.packed
        if packed.quality is not None:
            eng_off = InferenceEngine(
                packed, min_bucket=32, max_batch_size=256,
                label="bench-drift-off", drift=False,
            )
            eng_on = InferenceEngine(
                packed, min_bucket=32, max_batch_size=256,
                label="bench-drift-on", drift=True, drift_window=2048,
            )
            for eng in (eng_off, eng_on):
                eng.predict(reqs[0])  # untimed touch of the served bucket
            t_off = t_on = 0.0
            for _ in range(2):
                t0 = time.perf_counter()
                for q in reqs:
                    eng_off.predict(q)
                t_off += time.perf_counter() - t0
                t0 = time.perf_counter()
                for q in reqs:
                    eng_on.predict(q)
                t_on += time.perf_counter() - t0
            eng_off.stop()
            eng_on.stop()
            drift_overhead_pct = 100.0 * (t_on - t_off) / max(t_off, 1e-9)
        base.stop()
        return {
            "replicas": 2,
            "prefix_tier": tier,
            "clean": clean,
            "faulted": faulted,
            "p99_fault_ratio": round(
                faulted["p99_ms"] / max(clean["p99_ms"], 1e-9), 3
            ),
            "swap": swap,
            "swap_p99_ratio": round(
                swap["p99_ms"] / max(clean["p99_ms"], 1e-9), 3
            ),
            "scale_up_warm_ms": swap["scale_up_warm_ms"],
            "dropped_requests": swap["failed"],
            "open_loop": open_loop,
            "drift_overhead_pct": (
                round(drift_overhead_pct, 2)
                if drift_overhead_pct is not None
                else None
            ),
        }
    except Exception as e:  # noqa: BLE001 - carry the error, keep going
        return {"error": str(e)[:200]}


def _block_on_model(model):
    """Block on EVERY jax array reachable from the fitted model — composite
    models (stacking, pipelines) keep their arrays in base_models /
    stack_model attributes, not .params, and blocking on .params alone
    leaves their device work uncounted.  One walker shared with the
    profile-trace hook so bench timing and trace capture can never disagree
    about when device work is complete."""
    from spark_ensemble_tpu.utils.instrumentation import block_on_arrays

    block_on_arrays(model)


def _autotune_record(n=None):
    """The leg's resolved tuning state (docs/autotune.md): mode, whether a
    cache entry applied, and the tunables that differ from their shipped
    defaults — every bench leg records this so a number can always be
    traced to the exact config that produced it."""
    from spark_ensemble_tpu import autotune

    snap = autotune.resolved_snapshot(n)
    defaults = autotune.TUNABLES.defaults()
    return {
        "mode": snap["mode"],
        "cache_hit": snap["cache_hit"],
        "tuned": {
            k: v for k, v in snap["values"].items() if v != defaults[k]
        },
    }


def _timed_fit(est, X, y):
    """(model, seconds) with device work INCLUDED: every timed fit in this
    file blocks on the fitted model's reachable arrays so async dispatch
    cannot undercount — one protocol for the headline, tier, large-batch,
    and per-config numbers."""
    import time as _time

    t0 = _time.perf_counter()
    model = est.fit(X, y)
    _block_on_model(model)
    return model, _time.perf_counter() - t0


def inner():
    import numpy as np

    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # env var alone is NOT enough here: a site hook force-registers the
        # accelerator plugin; the config update pins the platform for real
        jax.config.update("jax_platforms", "cpu")

    from spark_ensemble_tpu import GBMClassifier

    X, y = _load_letter()
    num_rounds = _env_int("BENCH_ROUNDS", 100)
    # BENCH_HIST_PRECISION=high|default compares the statistic-matmul MXU
    # tiers (ops/tree.py hist_precision) against the exact-f32 default
    hist_precision = os.environ.get("BENCH_HIST_PRECISION", "highest")

    from spark_ensemble_tpu import DecisionTreeRegressor

    est = GBMClassifier(
        num_base_learners=num_rounds,
        loss="logloss",
        updates="newton",
        learning_rate=0.3,
        optimized_weights=True,
        base_learner=DecisionTreeRegressor(hist_precision=hist_precision),
    )

    # warmup with the SAME config and round count: the scan-chunked loop
    # compiles one program per distinct chunk length, so a 1-round warmup
    # would leave the length-16 and remainder compiles in the timed window
    est.fit(X, y)

    model, fit_s = _timed_fit(est, X, y)
    iters_per_sec = num_rounds / fit_s

    # predict throughput (argmax path; jitted, steady-state)
    Xd = jax.numpy.asarray(X)
    # graftlint: ignore[unfenced-blocking-read] -- warmup compile at the timed shape, deliberately outside the timed window
    jax.block_until_ready(model.predict(Xd))
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        out = model.predict(Xd)
    jax.block_until_ready(out)
    pred_s = (time.perf_counter() - t0) / reps
    rows_per_sec = X.shape[0] / pred_s

    train_acc = float(np.mean(np.asarray(model.predict(Xd)) == y))

    # serving: the packed engine vs a raw per-request predict loop on a
    # stream of small requests (the workload serving exists for).  Raw
    # pays one dispatch + device->host fetch per request; the engine
    # coalesces queued requests into bucket-sized dispatches.  The
    # acceptance bar: engine >= raw, with ZERO compiles after warmup
    # (counted via the jax.monitoring listener in telemetry.events).
    from spark_ensemble_tpu.serving import InferenceEngine
    from spark_ensemble_tpu.telemetry import record_fits

    req_rows, num_reqs = 32, 300
    reqs = [
        np.asarray(X[(i * 101) % (X.shape[0] - req_rows) :][:req_rows])
        for i in range(num_reqs)
    ]
    serve_rows = req_rows * num_reqs
    for r in reqs[:4]:
        np.asarray(model.predict(r))  # warm the raw path's bucket program
    t0 = time.perf_counter()
    for r in reqs:
        np.asarray(model.predict(r))
    raw_small_s = time.perf_counter() - t0

    engine = InferenceEngine(
        model, min_bucket=64, max_batch_size=4096, max_delay_ms=2.0
    )
    with record_fits() as rec:
        t0 = time.perf_counter()
        futs = [engine.submit(r) for r in reqs]
        for f in futs:
            f.result(timeout=300)
        eng_small_s = time.perf_counter() - t0
    lat = sorted(
        e["latency_ms"]
        for e in rec.events
        if e["event"] == "request_served" and e["source"] == "queue"
    )
    # whole-dataset engine throughput (top-bucket chunked), warm
    Xh = np.asarray(X)
    engine.predict(Xh)
    t0 = time.perf_counter()
    engine.predict(Xh)
    eng_bulk_s = time.perf_counter() - t0
    serving_compiles = engine.stats()["compiles_since_warmup"]
    engine.stop()
    serving_rows_per_sec = serve_rows / eng_small_s
    raw_small_rows_per_sec = serve_rows / raw_small_s

    # resilient-fleet load generator (docs/fleet.md): QPS/p50/p99,
    # hedge-rate, and degraded-share at 0 and 1 injected replica faults,
    # plus a skewed two-model open-loop — the serving robustness evidence
    fleet_stats = _bench_fleet(model, X, y, num_rounds)

    # telemetry overhead: re-fit with the JSONL event stream enabled —
    # telemetry_path is not part of any program-cache key, so this fit
    # reuses the warmed programs and the delta is pure host-side
    # event/fencing cost (budget: <2%, docs/telemetry.md).  Measured
    # against an ADJACENT warm baseline fit, not the headline: probe
    # activity drifts machine load between the headline fit and here, and
    # that drift (easily tens of %) would swamp the sub-% telemetry cost
    import tempfile

    tel_path = os.path.join(
        tempfile.mkdtemp(prefix="bench_telemetry_"), "fit.jsonl"
    )
    _, base_fit_s = _timed_fit(est.copy(), X, y)
    _, tel_fit_s = _timed_fit(est.copy(telemetry_path=tel_path), X, y)
    telemetry_overhead_pct = 100.0 * (tel_fit_s - base_fit_s) / base_fit_s

    # tracing-plane disabled-path overhead (docs/tracing.md): spans ride
    # the telemetry sink, so the sink-enabled delta above already prices
    # traced fits.  With NO sink every span call site degrades to the
    # shared NULL_SPAN no-op; its cost is bounded from above by the
    # relative delta between two adjacent warm no-sink fits (no-op calls
    # + machine noise — the perf sentinel pins it under 1% as
    # trace_overhead_pct, docs/tracing.md#perf-sentinel)
    _, base2_fit_s = _timed_fit(est.copy(), X, y)
    trace_overhead_pct = 100.0 * (base2_fit_s - base_fit_s) / base_fit_s

    # live operator plane (docs/operator.md): the same warm fit with the
    # program inventory capturing, the HBM sampler + watchdog running,
    # and a scraper thread sweeping /metrics + /programz + /healthz four
    # times a second (~60x hotter than a production Prometheus interval)
    # for the whole fit.  Baselined against an ADJACENT warm fit that is
    # ALSO under record_fits, so the recorder's own cost cancels and the
    # delta is purely plane + scrape.  The sentinel pins it as
    # exporter_overhead_pct: scraping a production process must be free.
    # The scraped fit's round ledger also yields
    # xla_vs_analytic_cost_ratio — XLA's own flop count for the chunk
    # program against ops/tree.py round_cost_est — the cost-model
    # cross-check the sentinel floors against drift.
    import threading as _threading
    import urllib.request as _urlreq

    from spark_ensemble_tpu.telemetry import start_operator_plane

    operator_stats = {}
    xla_vs_analytic_cost_ratio = None
    try:
        with record_fits():
            _, opbase_fit_s = _timed_fit(est.copy(), X, y)
        plane = start_operator_plane(
            port=0, sampler_interval_s=0.25, watchdog_interval_s=0.5
        )
        plane.sampler._per_tick = 8  # drain analysis fast on short fits
        scrape_stop = _threading.Event()
        scrapes = [0]

        def _scraper():
            while not scrape_stop.is_set():
                for ep in ("/metrics", "/programz?n=5", "/healthz"):
                    try:
                        with _urlreq.urlopen(plane.url + ep, timeout=5) as r:
                            r.read()
                    except OSError:
                        pass
                scrapes[0] += 1
                scrape_stop.wait(0.25)

        scraper = _threading.Thread(target=_scraper, daemon=True)
        scraper.start()
        try:
            with record_fits() as oprec:
                _, scraped_fit_s = _timed_fit(est.copy(), X, y)
        finally:
            scrape_stop.set()
            scraper.join(timeout=5)
        ratios = sorted(
            float(e["xla_vs_analytic_flops_ratio"])
            for e in oprec.events
            if e.get("event") == "round_end"
            and "xla_vs_analytic_flops_ratio" in e
        )
        if ratios:
            xla_vs_analytic_cost_ratio = ratios[len(ratios) // 2]
        inv_summary = plane.inventory.summary()
        operator_stats = {
            "scraped_fit_seconds": round(scraped_fit_s, 3),
            "quiet_fit_seconds": round(opbase_fit_s, 3),
            "scrape_loops": scrapes[0],
            "programs": inv_summary["programs"],
            "analyzed": inv_summary["analyzed"],
            "rounds_with_xla_fields": len(ratios),
        }
        plane.stop()
        exporter_overhead_pct = (
            100.0 * (scraped_fit_s - opbase_fit_s) / opbase_fit_s
        )
    except Exception as e:  # noqa: BLE001 - carry, keep going
        operator_stats = {"error": str(e)[:200]}
        exporter_overhead_pct = None

    # numeric-guard overhead: the default fit above runs with the guard on
    # (on_nonfinite="raise"); an adjacent warm fit with the guard off
    # isolates the per-chunk non-finite reduction + host sync cost
    # (budget: <2%, docs/robustness.md)
    _, off_fit_s = _timed_fit(est.copy(on_nonfinite="off"), X, y)
    robustness_overhead_pct = 100.0 * (base_fit_s - off_fit_s) / off_fit_s
    telemetry_phase_shares = {}
    cost_model_errs: list = []
    try:
        with open(tel_path) as f:
            for line in f:
                ev = json.loads(line)
                if ev.get("event") == "fit_end":
                    wall = float(ev.get("wall_s") or 0.0) or 1.0
                    telemetry_phase_shares = {
                        k: round(float(v) / wall, 4)
                        for k, v in ev.get("phases", {}).items()
                    }
                elif (
                    ev.get("event") == "round_end"
                    and "cost_model_error_pct" in ev
                ):
                    # measured-vs-estimated ledger (telemetry/events.py):
                    # the roofline model's per-round error; medianed below
                    # and pinned by the perf sentinel
                    cost_model_errs.append(float(ev["cost_model_error_pct"]))
    except (OSError, json.JSONDecodeError):
        pass
    cost_model_errs.sort()
    cost_model_error_pct = (
        cost_model_errs[len(cost_model_errs) // 2] if cost_model_errs else None
    )

    # pipeline A/B (docs/pipeline.md): the same headline fit with the
    # lookahead dispatch pipeline pinned OFF (SE_TPU_PIPELINE=0, the
    # synchronous pre-pipeline path) vs ON at depth 1.  Pipeline depth is
    # a driver-level knob — not part of any program-cache key — so both
    # legs reuse the warmed programs and the delta is pure dispatch
    # overlap; host_blocked_us (telemetry fit_end) records how long the
    # host sat in blocking device reads in each leg.  Both legs run under
    # record_fits so the telemetry cost cancels in the ratio.
    def _pipeline_leg(depth):
        prev = os.environ.get("SE_TPU_PIPELINE")
        os.environ["SE_TPU_PIPELINE"] = str(depth)
        try:
            with record_fits() as rec:
                _, leg_s = _timed_fit(est.copy(), X, y)
            fend = next(
                (e for e in rec.events if e.get("event") == "fit_end"), {}
            )
            blocked_s = float(fend.get("host_blocked_us") or 0.0) / 1e6
            return leg_s, blocked_s
        finally:
            if prev is None:
                os.environ.pop("SE_TPU_PIPELINE", None)
            else:
                os.environ["SE_TPU_PIPELINE"] = prev

    sync_s, sync_blocked = _pipeline_leg(0)
    pipe_s, pipe_blocked = _pipeline_leg(1)
    pipeline_ab = {
        "speedup": round(sync_s / pipe_s, 3),
        "sync_fit_seconds": round(sync_s, 3),
        "pipelined_fit_seconds": round(pipe_s, 3),
        "sync_host_blocked_share": round(sync_blocked / max(sync_s, 1e-9), 4),
        "pipelined_host_blocked_share": round(
            pipe_blocked / max(pipe_s, 1e-9), 4
        ),
    }

    # hist-tier A/B (docs/fused_kernel.md): the same round loop with the
    # histogram backend pinned to 'matmul' vs the bit-packed 'fused' round
    # kernel, warm programs on both legs.  On CPU the fused kernel runs in
    # pallas interpret mode, which caps rows (_INTERPRET_MAX_ROWS) — the
    # leg subsamples under the cap and trims rounds so the A/B stays a
    # parity/ratio check there; the timed speedup is only meaningful on a
    # real accelerator.  hbm_bytes_est is static (round_cost_est), so the
    # modeled traffic ratio — the quantity the fused tier exists to move —
    # rides along even when the wall-clock legs are CPU noise.
    from spark_ensemble_tpu.ops.tree import round_cost_est

    platform = jax.devices()[0].platform
    ab_bins = 16  # packs 4-bit: the headline compression case
    if platform == "cpu":
        from spark_ensemble_tpu.ops.pallas_hist import _INTERPRET_MAX_ROWS

        ab_rows = min(X.shape[0], _INTERPRET_MAX_ROWS)
        ab_rounds = min(num_rounds, 10)
    else:
        ab_rows, ab_rounds = X.shape[0], num_rounds
    Xab, yab = X[:ab_rows], y[:ab_rows]

    def _hist_tier_leg(tier):
        leg_est = est.copy(
            num_base_learners=ab_rounds,
            base_learner=DecisionTreeRegressor(
                hist=tier, max_bins=ab_bins, hist_precision=hist_precision
            ),
        )
        leg_est.fit(Xab, yab)  # warmup at the timed round count
        with record_fits() as rec:
            leg_model, leg_s = _timed_fit(leg_est, Xab, yab)
        rend = next(
            (
                e
                for e in rec.events
                if e.get("event") == "round_end" and "hist_tier" in e
            ),
            {},
        )
        # graftlint: ignore[unfenced-blocking-read] -- accuracy readback after the timed fit, outside the dispatch window
        acc = float(np.mean(np.asarray(leg_model.predict(Xab)) == yab))
        return leg_s, rend, acc

    hist_tier_ab = {}
    try:
        mat_s, mat_ev, mat_acc = _hist_tier_leg("matmul")
        fus_s, fus_ev, fus_acc = _hist_tier_leg("fused")
        costs = {
            tier: round_cost_est(
                ab_rows, X.shape[1], 1, 26, 5, ab_bins, hist=tier
            )
            for tier in ("matmul", "fused")
        }
        hist_tier_ab = {
            "fused_speedup": round(mat_s / fus_s, 3),
            "matmul_fit_seconds": round(mat_s, 3),
            "fused_fit_seconds": round(fus_s, 3),
            "resolved_tier": fus_ev.get("hist_tier"),
            "pack_bits": fus_ev.get("pack_bits"),
            "mfu_est": fus_ev.get("mfu_est"),
            "matmul_mfu_est": mat_ev.get("mfu_est"),
            "hbm_bytes_matmul": costs["matmul"]["hbm_bytes_est"],
            "hbm_bytes_fused": costs["fused"]["hbm_bytes_est"],
            "hbm_ratio": round(
                costs["matmul"]["hbm_bytes_est"]
                / max(costs["fused"]["hbm_bytes_est"], 1),
                2,
            ),
            "train_accuracy_delta": round(fus_acc - mat_acc, 4),
            "rows": ab_rows,
            "rounds": ab_rounds,
            "max_bins": ab_bins,
        }
    except Exception as e:  # noqa: BLE001 - carry, keep going
        hist_tier_ab = {"error": str(e)[:200]}

    # tuned-vs-default (docs/autotune.md): the headline above resolved
    # every tunable through the published tuning cache (when one exists
    # for this device); re-measure the same fit + predict with autotuning
    # OFF — every site at its shipped default literal.  >1.0 means the
    # measured winners genuinely beat the hand-guessed constants.  The
    # program caches clear on both edges: trace-time tunables are latched
    # into compiled programs, so each leg must trace under its own config.
    from spark_ensemble_tpu import autotune as _autotune

    autotune_state = _autotune_record(X.shape[0])
    with _autotune.override(mode="off"):
        _autotune.clear_program_caches()
        est_def = est.copy()
        est_def.fit(X, y)  # warm at the SAME round count (see above)
        model_def, def_fit_s = _timed_fit(est_def, X, y)
        jax.block_until_ready(model_def.predict(Xd))
        t0 = time.perf_counter()
        for _ in range(reps):
            out_def = model_def.predict(Xd)
        jax.block_until_ready(out_def)
        def_pred_s = (time.perf_counter() - t0) / reps
    _autotune.clear_program_caches()  # later legs re-trace under live config
    tuned_vs_default = {
        "fit": round(def_fit_s / fit_s, 3),
        "predict": round(def_pred_s / pred_s, 3),
        "default_fit_seconds": round(def_fit_s, 2),
        "default_predict_rows_per_sec": round(X.shape[0] / def_pred_s, 1),
    }

    # emit the HEADLINE result immediately (flushed): the parent takes the
    # LAST parseable stdout line, so if a perishable accelerator window
    # dies mid-extras the already-measured headline still lands instead of
    # the whole run timing out empty
    flops = _flops_per_round(X.shape[0], X.shape[1], 26, 5, 64)
    out = {
        "metric": _METRIC,
        "value": round(iters_per_sec, 3),
        "unit": "iters/sec",
        "vs_baseline": 1.0,
        "predict_rows_per_sec": round(rows_per_sec, 1),
        "fit_seconds": round(fit_s, 2),
        "train_accuracy": round(train_acc, 4),
        "num_rounds": num_rounds,
        "flops_per_round_est": flops,
        "hist_precision": hist_precision,
        "telemetry_overhead_pct": round(telemetry_overhead_pct, 2),
        "trace_overhead_pct": round(trace_overhead_pct, 2),
        "exporter_overhead_pct": (
            round(exporter_overhead_pct, 2)
            if exporter_overhead_pct is not None
            else None
        ),
        "xla_vs_analytic_cost_ratio": (
            round(xla_vs_analytic_cost_ratio, 4)
            if xla_vs_analytic_cost_ratio is not None
            else None
        ),
        "operator": operator_stats,
        "cost_model_error_pct": (
            round(cost_model_error_pct, 2)
            if cost_model_error_pct is not None
            else None
        ),
        "telemetry_phase_shares": telemetry_phase_shares,
        "robustness_overhead_pct": round(robustness_overhead_pct, 2),
        "serving_rows_per_sec": round(serving_rows_per_sec, 1),
        "serving_raw_rows_per_sec": round(raw_small_rows_per_sec, 1),
        "serving_vs_raw": round(
            serving_rows_per_sec / max(raw_small_rows_per_sec, 1e-9), 3
        ),
        "serving_bulk_rows_per_sec": round(X.shape[0] / eng_bulk_s, 1),
        "serving_queue_p50_ms": round(lat[len(lat) // 2], 3) if lat else None,
        "serving_queue_p99_ms": (
            round(lat[min(len(lat) - 1, int(len(lat) * 0.99))], 3)
            if lat else None
        ),
        "serving_compiles_after_warmup": serving_compiles,
        "fleet": fleet_stats,
        "pipeline_speedup": pipeline_ab["speedup"],
        "pipeline": pipeline_ab,
        "fused_speedup": hist_tier_ab.get("fused_speedup"),
        "hist_tier_ab": hist_tier_ab,
        "autotune": autotune_state,
        "tuned_vs_default": tuned_vs_default,
        "platform": platform,
        "device": str(jax.devices()[0]),
    }
    # flat aliases under the exact names tools/perf_sentinel.py pins
    # (docs/tracing.md#perf-sentinel), so the baseline diff never has to
    # reach into nested legs
    out["serving_p99_ms"] = out["serving_queue_p99_ms"]
    out["compiles_since_warmup"] = serving_compiles
    out["host_blocked_share"] = pipeline_ab["pipelined_host_blocked_share"]
    if isinstance(fleet_stats, dict) and isinstance(
        fleet_stats.get("drift_overhead_pct"), (int, float)
    ):
        out["drift_overhead_pct"] = fleet_stats["drift_overhead_pct"]
    if isinstance(fleet_stats, dict):
        for k in ("swap_p99_ratio", "scale_up_warm_ms", "dropped_requests"):
            if isinstance(fleet_stats.get(k), (int, float)):
                out[k] = fleet_stats[k]
    if platform != "cpu":
        # only meaningful against a real accelerator peak; a CPU "MFU"
        # against an invented 1 TFLOP/s nominal is noise, not evidence
        out["mfu_est"] = round(
            flops * iters_per_sec / _peak_flops(platform), 5
        )
    if any(os.environ.get(k) == "1" for k in _BATTERY_KNOBS):
        print(json.dumps({**out, "partial": "extras pending"}), flush=True)

    # out-of-core streaming leg (docs/streaming.md): train letter with the
    # packed bin matrix OUT of device memory — resident on device at any
    # instant is only the prefetch window of shards, an artificial budget
    # far under the full packed matrix.  Reported: the budget vs the
    # matrix, training rows/sec, and the prefetch-overlap evidence
    # (shard_wait share of wall: the host time the prefetcher FAILED to
    # hide; load time >> wait time means the overlap works).
    streaming = {}
    try:
        import tempfile as _tf

        from spark_ensemble_tpu.data import (
            DEFAULT_PREFETCH_DEPTH,
            write_shards,
        )

        st_rows_cap = X.shape[0] if platform != "cpu" else min(
            X.shape[0], 8192
        )
        Xs, ys = X[:st_rows_cap], y[:st_rows_cap]
        st_rounds = num_rounds if platform != "cpu" else min(num_rounds, 10)
        store = write_shards(
            Xs,
            os.path.join(_tf.mkdtemp(prefix="bench_shards_"), "store"),
            max_bins=ab_bins,
            shard_rows=max(256, st_rows_cap // 8),
        )
        # the streaming working set: consumed shard + in-flight prefetch
        # window — the artificial device budget the leg trains under
        shard_bytes = max(
            store.shard_meta(s)["bytes"] for s in range(store.num_shards)
        )
        budget_bytes = shard_bytes * (DEFAULT_PREFETCH_DEPTH + 2)
        st_est = GBMClassifier(
            num_base_learners=st_rounds,
            loss="logloss",
            updates="newton",
            learning_rate=0.3,
            optimized_weights=True,
            base_learner=DecisionTreeRegressor(
                hist="stream", max_bins=ab_bins,
                hist_precision=hist_precision,
            ),
        )
        _block_on_model(st_est.copy().fit_streaming(store, ys))  # warmup
        from spark_ensemble_tpu.telemetry import record_fits as _rf

        with _rf() as rec:
            t0 = time.perf_counter()
            st_model = st_est.fit_streaming(store, ys)
            _block_on_model(st_model)
            st_s = time.perf_counter() - t0
        wait_s = sum(
            e["wait_us"] for e in rec.events if e["event"] == "shard_wait_us"
        ) / 1e6
        load_ev = [e for e in rec.events if e["event"] == "shard_load"]
        hit_ev = [
            e for e in rec.events if e["event"] == "shard_prefetch_hit"
        ]
        streaming = {
            "rows": st_rows_cap,
            "rounds": st_rounds,
            "shards": store.num_shards,
            "packed_bytes": store.packed_nbytes,
            "device_budget_bytes": budget_bytes,
            "budget_vs_packed": round(
                budget_bytes / max(store.packed_nbytes, 1), 3
            ),
            "fit_seconds": round(st_s, 3),
            "train_rows_per_sec": round(st_rows_cap * st_rounds / st_s, 1),
            "shard_wait_share_of_wall": round(wait_s / max(st_s, 1e-9), 4),
            "shard_load_seconds": round(
                sum(e["duration_us"] for e in load_ev) / 1e6, 3
            ),
            "shard_loads": sum(e["count"] for e in load_ev),
            "prefetch_hit_rate": round(
                sum(e["hits"] for e in hit_ev)
                / max(sum(e["hits"] + e["misses"] for e in hit_ev), 1),
                4,
            ),
        }
        if budget_bytes >= store.packed_nbytes:
            streaming["warning"] = (
                "prefetch window not smaller than the packed matrix at "
                "this scale — budget demo needs more shards"
            )
    except Exception as e:  # noqa: BLE001 - carry, keep going
        streaming = {"error": str(e)[:200]}
    out["streaming"] = streaming
    if "shard_wait_share_of_wall" in streaming:
        out["shard_wait_share"] = streaming["shard_wait_share_of_wall"]

    # pod-scale leg (parallel/elastic.py): the SAME streaming fit with the
    # row mesh spread over every device — each position sweeps only its
    # manifest slice and the per-level histograms cross the mesh through
    # the ordered reduce.  Reported: training rows/sec through the
    # distributed plane and the reduce's share of sweep wall
    # (dcn_reduce_share: the fraction an actual DCN hop would own —
    # measured under SE_TPU_DIST_MEASURE fences, so the sweep itself is
    # serialized and rows/sec here is a floor, not a peak).
    multihost = {}
    try:
        if len(jax.devices()) < 2:
            multihost = {"note": "single device; distributed leg moot"}
        elif "fit_seconds" not in streaming:
            multihost = {"note": "streaming leg unavailable; skipped"}
        else:
            from spark_ensemble_tpu.parallel import elastic as _elastic
            from spark_ensemble_tpu.parallel.mesh import data_member_mesh

            mh_w = 4 if len(jax.devices()) >= 4 else 2
            mh_mesh = data_member_mesh(mh_w, member=1)
            mh_est = st_est.copy()
            os.environ["SE_TPU_DIST_MEASURE"] = "1"
            try:
                # the warmup leg rides under record_fits so its dist_level
                # spans feed the pod skew report (telemetry/podview.py) —
                # the timed leg stays sink-free so rows/sec is unpolluted
                with _rf() as mh_rec:
                    _block_on_model(
                        mh_est.copy().fit_streaming(store, ys, mesh=mh_mesh)
                    )  # warmup
                t0 = time.perf_counter()
                _block_on_model(mh_est.fit_streaming(store, ys, mesh=mh_mesh))
                mh_s = time.perf_counter() - t0
            finally:
                os.environ.pop("SE_TPU_DIST_MEASURE", None)
            mh_stats = _elastic.last_fit_stats()
            from spark_ensemble_tpu.telemetry import podview as _podview

            pod_skew = _podview.skew_report([mh_rec.events])
            multihost = {
                "positions": mh_w,
                "rows": st_rows_cap,
                "rounds": st_rounds,
                "shards": store.num_shards,
                "fit_seconds": round(mh_s, 3),
                "rows_per_sec": round(st_rows_cap * st_rounds / mh_s, 1),
                "sweep_seconds": round(mh_stats.get("sweep_s", 0.0), 3),
                "reduce_seconds": round(mh_stats.get("reduce_s", 0.0), 3),
                "dcn_reduce_share": round(
                    mh_stats.get("reduce_s", 0.0)
                    / max(mh_stats.get("sweep_s", 0.0), 1e-9),
                    4,
                ),
                "pod_skew_ratio": round(pod_skew["pod_skew_ratio"], 3),
                "pod_skew_offender": pod_skew["persistent_offender"],
            }
    except Exception as e:  # noqa: BLE001 - carry, keep going
        multihost = {"error": str(e)[:200]}
    out["multihost"] = multihost
    if "rows_per_sec" in multihost:
        out["multihost_rows_per_sec"] = multihost["rows_per_sec"]
        out["dcn_reduce_share"] = multihost["dcn_reduce_share"]
        out["pod_skew_ratio"] = multihost["pod_skew_ratio"]

    # megabatch sweep leg (docs/selection.md#megabatch-sweeps): the SAME
    # 32-candidate hyperparameter sweep fit twice — one est.fit() per
    # candidate (warm programs; the traced-lr contract means sequential
    # recompiles nothing between candidates) vs fit_sweep() vmapping all
    # candidates over a config axis into one batched dispatch per round
    # chunk.  Both legs run identical round math; the quantity megabatch
    # exists to move is PER-DISPATCH overhead (round launch + the guard's
    # blocking readback, paid 32x per round sequentially and once
    # batched), so the leg runs the dispatch-bound regime that dominates
    # real sweeps: tiny per-candidate rounds at scan_chunk=1.  Results
    # are pinned bit-identical (spot-checked here on a prediction probe,
    # contract-pinned in tests/test_megabatch.py).
    # tools/perf_sentinel.py floors sweep_speedup vs PERF_BASELINE.json.
    sweep_ab = {}
    try:
        from spark_ensemble_tpu import GBMRegressor
        from spark_ensemble_tpu.autotune import resolve as _tuned
        from spark_ensemble_tpu.models.gbm_sweep import (
            _CONFIGS_PER_DISPATCH,
            fit_sweep,
        )

        sw_rows, sw_rounds = 128, 16
        sw_rng = np.random.default_rng(7)
        Xsw = sw_rng.normal(size=(sw_rows, 8)).astype(np.float32)
        ysw = (
            Xsw[:, 0] * 2.0 + np.sin(Xsw[:, 1])
        ).astype(np.float32)
        sw_base = GBMRegressor(
            num_base_learners=sw_rounds,
            loss="squared",
            base_learner=DecisionTreeRegressor(max_depth=2),
            scan_chunk=1,
        )
        n_cfgs = 32
        sw_ests = [
            sw_base.copy(learning_rate=0.05 + 0.01 * i, seed=i,
                         subsample_ratio=0.8)
            for i in range(n_cfgs)
        ]
        # warm both legs at the TIMED shapes: one sequential fit compiles
        # the shared round programs; a FULL-width sweep compiles the
        # vmapped slab programs (slab width is a trace shape — warming at
        # fewer candidates would leave the timed leg paying compile)
        _block_on_model(sw_ests[0].copy().fit(Xsw, ysw))
        for m in fit_sweep([e.copy() for e in sw_ests], Xsw, ysw):
            _block_on_model(m)

        t0 = time.perf_counter()
        seq_models = [e.copy().fit(Xsw, ysw) for e in sw_ests]
        for m in seq_models:
            _block_on_model(m)
        seq_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        mb_models = fit_sweep(sw_ests, Xsw, ysw)
        for m in mb_models:
            _block_on_model(m)
        mb_s = time.perf_counter() - t0

        probe = Xsw[:256]

        def _bits(m):
            return np.asarray(m.predict(probe))  # graftlint: ignore[unfenced-blocking-read] -- bit-identity probe after both timed legs, outside the dispatch window

        identical = all(
            np.array_equal(_bits(seq_models[i]), _bits(mb_models[i]))
            for i in (0, n_cfgs // 2, n_cfgs - 1)
        )
        sweep_ab = {
            "configs": n_cfgs,
            "rows": sw_rows,
            "rounds": sw_rounds,
            "sequential_seconds": round(seq_s, 3),
            "megabatch_seconds": round(mb_s, 3),
            "speedup": round(seq_s / mb_s, 3),
            "configs_per_dispatch": int(_tuned(
                "configs_per_dispatch", _CONFIGS_PER_DISPATCH, n=sw_rows
            )),
            "bit_identical": bool(identical),
        }
    except Exception as e:  # noqa: BLE001 - carry, keep going
        sweep_ab = {"error": str(e)[:200]}
    out["sweep"] = sweep_ab
    if "speedup" in sweep_ab:
        out["sweep_speedup"] = sweep_ab["speedup"]
        out["configs_per_dispatch"] = sweep_ab["configs_per_dispatch"]

    # gradient-based sampling Pareto leg (docs/sampling.md): the letter
    # shape fit at sampling='none' vs GOSS(0.2/0.1) vs MVS over a short
    # round budget.  Per-round throughput alone overstates sampling (a
    # sampled round is cheaper AND weaker), so the headline combines it
    # with rounds-to-equal-accuracy into time-to-accuracy: the target is
    # the none leg's accuracy a third of the way through its budget (the
    # early-mid regime sampling exists for — near the plateau every
    # sampled variant needs unboundedly many rounds), each leg's
    # rounds-to-target comes from a take(k) accuracy scan, and
    #   sampling_speedup = max over methods of
    #       (none s/round * rounds_none) / (method s/round * rounds_m)
    # tools/perf_sentinel.py floors sampling_speedup vs
    # PERF_BASELINE.json; hbm_ratio is the modeled per-round ledger
    # traffic of the sampled round against the full-row round.
    sampling_ab = {}
    try:
        sp_rounds = _env_int("BENCH_SAMPLING_ROUNDS", 15)
        probe_n = min(4096, X.shape[0])
        Xp, yp = X[:probe_n], y[:probe_n]

        def _acc_curve(m):
            return [
                float(np.mean(np.asarray(m.take(k).predict(Xp)) == yp))  # graftlint: ignore[unfenced-blocking-read] -- accuracy scan after the timed fit, outside the dispatch window
                for k in range(1, sp_rounds + 1)
            ]

        def _rounds_to(curve, target):
            for i, acc in enumerate(curve):
                if acc >= target:
                    if i == 0:
                        return 1.0
                    lo = curve[i - 1]
                    return i + (target - lo) / max(acc - lo, 1e-9)
            return None

        legs = {}
        for method in ("none", "goss", "mvs"):
            kw = {} if method == "none" else {"sampling": method}
            if method == "goss":
                kw.update(top_rate=0.2, other_rate=0.1)
            sp_est = est.copy(num_base_learners=sp_rounds, **kw)
            with record_fits() as sp_rec:  # ledger ride-along on warmup
                _block_on_model(sp_est.copy().fit(X, y))
            sp_model, sp_s = _timed_fit(sp_est, X, y)
            hbm = next(
                (
                    e["hbm_bytes_est"]
                    for e in sp_rec.events
                    if e.get("event") == "round_end"
                    and "hbm_bytes_est" in e
                ),
                None,
            )
            saved = next(
                (
                    e["hbm_saved_est"]
                    for e in sp_rec.events
                    if e.get("event") == "round_end"
                    and "hbm_saved_est" in e
                ),
                None,
            )
            legs[method] = {
                "seconds": round(sp_s, 3),
                "iters_per_sec": round(sp_rounds / sp_s, 3),
                "curve": _acc_curve(sp_model),
                "hbm_bytes_est": hbm,
                "hbm_saved_est": saved,
            }
        target = legs["none"]["curve"][max(sp_rounds // 3, 1) - 1]
        per_round_none = legs["none"]["seconds"] / sp_rounds
        best = 0.0
        for method in ("goss", "mvs"):
            leg = legs[method]
            r_m = _rounds_to(leg["curve"], target)
            r_none = _rounds_to(legs["none"]["curve"], target)
            leg["rounds_to_equal_accuracy"] = (
                round(r_m, 2) if r_m is not None else None
            )
            if r_m is None or r_none is None:
                continue
            per_round_m = leg["seconds"] / sp_rounds
            leg["speedup_at_equal_accuracy"] = round(
                (per_round_none * r_none) / (per_round_m * r_m), 3
            )
            best = max(best, leg["speedup_at_equal_accuracy"])
        hbm_none = legs["none"]["hbm_bytes_est"]
        for method in ("goss", "mvs"):
            h = legs[method]["hbm_bytes_est"]
            if hbm_none and h:
                legs[method]["hbm_ratio"] = round(h / hbm_none, 3)
        for leg in legs.values():
            leg["curve"] = [round(a, 4) for a in leg["curve"]]
        sampling_ab = {
            "rounds": sp_rounds,
            "target_accuracy": round(target, 4),
            "legs": legs,
        }
        if best > 0:
            sampling_ab["sampling_speedup"] = round(best, 3)
    except Exception as e:  # noqa: BLE001 - carry, keep going
        sampling_ab = {"error": str(e)[:200]}
    out["sampling"] = sampling_ab
    if "sampling_speedup" in sampling_ab:
        out["sampling_speedup"] = sampling_ab["sampling_speedup"]
        goss_leg = sampling_ab["legs"]["goss"]
        if "hbm_ratio" in goss_leg:
            out["sampling_hbm_ratio"] = goss_leg["hbm_ratio"]

    extras = {}
    if os.environ.get("BENCH_FULL") == "1":
        extras = _bench_full_extras()
    if os.environ.get("BENCH_LARGE") == "1":
        extras.update(_bench_large_extras())
    if os.environ.get("BENCH_XL") == "1":
        extras.update(_bench_xl_extras())
    if os.environ.get("BENCH_TIERS") == "1":
        # one run captures the whole hist_precision comparison (a TPU
        # window is perishable; see BASELINE.md): re-fit at the OTHER
        # tiers — the main number above already covers hist_precision —
        # and report their round rates + accuracy deltas.  The pallas
        # kernel tier only COMPILES on TPU (every other backend runs it
        # in Python-level interpret mode, which would hang at bench
        # scale), so it rides the comparison exactly when a TPU window
        # is open
        tiers = ("highest", "high", "default") + (
            ("pallas",) if platform == "tpu" else ()
        )
        for tier in tiers:
            if tier == hist_precision:
                continue
            try:
                t_est = est.copy(
                    base_learner=DecisionTreeRegressor(hist_precision=tier)
                )
                t_est.fit(X, y)  # warmup/compile
                t_model, t_fit = _timed_fit(t_est, X, y)
                t_acc = float(
                    # graftlint: ignore[unfenced-blocking-read] -- accuracy readback after the timed fit, outside the dispatch window
                    np.mean(np.asarray(t_model.predict(Xd)) == y)
                )
                extras[f"tier_{tier}_iters_per_sec"] = round(
                    num_rounds / t_fit, 3
                )
                extras[f"tier_{tier}_train_accuracy"] = round(t_acc, 4)
            except Exception as e:  # noqa: BLE001 - carry, keep going
                extras[f"tier_{tier}_error"] = str(e)[:200]

    out.update(extras)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--inner":
        inner()
        sys.exit(0)
    sys.exit(main())
