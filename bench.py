"""Benchmark: GBM boosting-iters/sec/chip on letter (26-class, 100 rounds)
plus predict rows/sec — the primary metric pinned by BASELINE.json.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
The reference publishes no numbers (BASELINE.md), so vs_baseline is measured
against a conservative JVM-reference estimate recorded in this file once a
reference timing exists; until then it reports 1.0 relative to itself.
"""

import json
import os
import sys
import time

import numpy as np


def _load_letter():
    from spark_ensemble_tpu.utils.datasets import has_reference_data, load_dataset

    if has_reference_data():
        return load_dataset("letter")
    rng = np.random.RandomState(0)
    X = rng.randn(15000, 16).astype(np.float32)
    centers = rng.randn(26, 16).astype(np.float32)
    y = np.argmax(X @ centers.T + 0.5 * rng.randn(15000, 26), axis=1).astype(
        np.float32
    )
    return X, y


def main():
    import jax

    from spark_ensemble_tpu import GBMClassifier

    X, y = _load_letter()
    num_rounds = int(os.environ.get("BENCH_ROUNDS", "100"))

    est = GBMClassifier(
        num_base_learners=num_rounds,
        loss="logloss",
        updates="newton",
        learning_rate=0.3,
        optimized_weights=True,
    )

    # warmup: compile the round step on a small prefix (cached for full run)
    warm = GBMClassifier(
        num_base_learners=1, loss="logloss", updates="newton", learning_rate=0.3
    )
    warm.fit(X, y)

    t0 = time.perf_counter()
    model = est.fit(X, y)
    fit_s = time.perf_counter() - t0
    iters_per_sec = num_rounds / fit_s

    # predict throughput (raw scores; jitted, steady-state)
    Xd = jax.numpy.asarray(X)
    jax.block_until_ready(model.predict(Xd))  # compile at the timed shape
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        out = model.predict(Xd)
    jax.block_until_ready(out)
    pred_s = (time.perf_counter() - t0) / reps
    rows_per_sec = X.shape[0] / pred_s

    train_acc = float(np.mean(np.asarray(model.predict(Xd)) == y))

    print(
        json.dumps(
            {
                "metric": "GBM boosting-iters/sec/chip (letter, 100 rounds)",
                "value": round(iters_per_sec, 3),
                "unit": "iters/sec",
                "vs_baseline": 1.0,
                "predict_rows_per_sec": round(rows_per_sec, 1),
                "fit_seconds": round(fit_s, 2),
                "train_accuracy": round(train_acc, 4),
                "num_rounds": num_rounds,
                "device": str(jax.devices()[0]),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
