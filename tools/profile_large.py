"""Capture an on-chip jax.profiler trace of the BENCH_LARGE GBM config and
print the per-op cost table (utils/profiling.py) — the trace-attribution
workflow VERDICT round 3 asks for ("attack the MFU with the trace, not the
estimate").

Usage:  python tools/profile_large.py [trace_dir] [> PROFILE_TPU.md]

Fits once for compile warmup (untraced), then traces a second fit of the
same program, so the table shows steady-state device work, not compilation.
"""

import os
import sys

import numpy as np


def main() -> int:
    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/prof_large"
    rounds = int(os.environ.get("BENCH_LARGE_ROUNDS", "20"))

    import jax

    from spark_ensemble_tpu import GBMClassifier
    from spark_ensemble_tpu.utils import profiling

    n, d, k = 131072, 32, 8
    rng = np.random.RandomState(0)
    X = rng.randn(n, d).astype(np.float32)
    centers = rng.randn(k, d).astype(np.float32)
    y = np.argmax(X @ centers.T + 0.5 * rng.randn(n, k), axis=1).astype(
        np.float32
    )

    est = GBMClassifier(
        num_base_learners=rounds, loss="logloss", updates="newton",
        learning_rate=0.3,
    )
    est.fit(X, y)  # warmup: compile outside the trace

    est_traced = est.copy(profile_dir=trace_dir)
    model = est_traced.fit(X, y)
    from spark_ensemble_tpu.utils.instrumentation import block_on_arrays

    block_on_arrays(model)

    platform = jax.devices()[0].platform
    print(f"# BENCH_LARGE trace (platform={platform}, n={n}, d={d}, k={k}, "
          f"rounds={rounds})\n")
    files = profiling.find_trace_files(trace_dir)
    if not files:
        print("no trace files captured")
        return 1
    rows, total = profiling.summarize_trace(trace_dir, top=40)
    print(profiling.format_summary(rows, total))

    # ---- predict path (the second headline metric): steady-state reps ----
    pred_dir = trace_dir + "_predict"
    Xd = jax.numpy.asarray(X)
    # graftlint: ignore[unfenced-blocking-read] -- warmup compile outside the profiler trace, deliberately unmeasured
    jax.block_until_ready(model.predict(Xd))
    with jax.profiler.trace(pred_dir):
        for _ in range(10):
            out = model.predict(Xd)
        # graftlint: ignore[unfenced-blocking-read] -- end-of-trace sync: the profiler, not the host accounting, owns this window
        jax.block_until_ready(out)
    print(f"\n# predict trace (10 reps, n={n})\n")
    if not profiling.find_trace_files(pred_dir):
        print("no predict trace files captured")
        return 1
    rows, total = profiling.summarize_trace(pred_dir, top=25)
    print(profiling.format_summary(rows, total))
    return 0


if __name__ == "__main__":
    sys.exit(main())
