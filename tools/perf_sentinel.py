"""Perf-regression sentinel: diff the newest bench record against a
committed baseline and fail CI on regression.

The driver commits one ``BENCH_r*.json`` per round; its ``parsed``
payload is ``bench.py``'s JSON line (headline fit metric, serving
throughput, and — when the bench emits them — telemetry-derived shares
like ``host_blocked_share`` / ``shard_wait_share`` /
``serving_p99_ms`` / ``compiles_since_warmup`` /
``trace_overhead_pct``).  This tool compares the newest record against
``PERF_BASELINE.json`` with a per-metric noise floor and direction, so a
real regression fails loudly while runner jitter does not:

    python tools/perf_sentinel.py                # repo-root defaults
    python tools/perf_sentinel.py --bench BENCH_r05.json \
        --baseline PERF_BASELINE.json
    python tools/perf_sentinel.py --update-baseline   # escape hatch

Rules (docs/tracing.md#perf-sentinel):

- A metric present in the baseline but missing from the bench record is
  SKIPPED with a note (bench payloads are headline-only on some
  platforms), never a failure — absence is not a regression.
- ``platform`` must match; comparing a CPU-fallback run against a TPU
  baseline (or vice versa) is skipped entirely with exit 0 and a
  ``platform_mismatch`` note, because every number would be noise.
- ``--update-baseline`` rewrites ``PERF_BASELINE.json`` from the newest
  bench record.  CI runs WITHOUT it; a deliberate perf change lands by
  running it locally and committing the new baseline in the same PR.

Exit codes: 0 = no regression (or nothing comparable), 1 = regression.
stdlib-only so the CI job needs no jax install.
"""

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_DEFAULT = os.path.join(REPO, "PERF_BASELINE.json")

#: metric -> (direction, relative noise floor, absolute noise floor).
#: direction "higher" means bigger is better (throughput); "lower" means
#: smaller is better (latency, shares, counts).  A candidate only fails
#: when it is worse by MORE than both floors.
METRICS: Dict[str, Any] = {
    "value":                 ("higher", 0.10, 0.0),   # headline iters/sec
    "fit_seconds":           ("lower", 0.15, 0.5),
    "predict_rows_per_sec":  ("higher", 0.15, 0.0),
    "serving_p99_ms":        ("lower", 0.25, 1.0),
    "host_blocked_share":    ("lower", 0.25, 0.02),
    "shard_wait_share":      ("lower", 0.25, 0.02),
    "compiles_since_warmup": ("lower", 0.0, 0.0),     # zero-compile contract
    "trace_overhead_pct":    ("lower", 0.50, 1.0),    # disabled-path <1%
    # pod-scale leg (parallel/elastic.py): rows/sec through the
    # distributed-histogram plane, and the ordered reduce's share of
    # sweep wall (the DCN-hop fraction on a real pod; measured under
    # serializing fences, so it is noisy — wide floors)
    "multihost_rows_per_sec": ("higher", 0.25, 0.0),
    "dcn_reduce_share":       ("lower", 0.25, 0.05),
    # pod observability (telemetry/podview.py): max/median per-host work
    # skew in the multihost leg (simulated hosts on one process — small
    # true skew, wide floors), and the measured-vs-estimated ledger's
    # roofline error (a model-quality tripwire, not a perf number)
    "pod_skew_ratio":        ("lower", 0.50, 0.25),
    "cost_model_error_pct":  ("lower", 0.50, 10.0),
    # live operator plane (docs/operator.md): scrape-under-load fit delta
    # vs the adjacent quiet fit (must stay ~free; wide rel floor because
    # it is a difference of two noisy walls, 1.0 abs = the <1% budget),
    # and the XLA-vs-analytic per-round flop ratio on the GBM letter leg
    # (a cost-model drift tripwire: either model changing moves it)
    "exporter_overhead_pct":      ("lower", 0.50, 1.0),
    "xla_vs_analytic_cost_ratio": ("lower", 0.50, 0.25),
    # model-quality plane (telemetry/quality.py): the fused drift-sketch's
    # steady-state serve cost, drift-on vs drift-off over warm programs on
    # the fleet leg — 2.0 abs = the <2% budget (docs/quality.md#overhead)
    "drift_overhead_pct":         ("lower", 0.50, 2.0),
    # self-healing fleet (docs/autopilot.md): closed-loop p99 during a
    # rolling hot swap + elastic add/remove vs the clean leg (ratio of two
    # noisy p99s — wide floors), the add_replica warm-in wall (a clone of
    # warm programs, so it must stay ~instant), and requests dropped
    # across swap/scale — an exact invariant like the compile contract:
    # zero, no floor
    "swap_p99_ratio":             ("lower", 0.50, 1.0),
    "scale_up_warm_ms":           ("lower", 0.50, 50.0),
    "dropped_requests":           ("lower", 0.0, 0.0),
    # megabatch sweep leg (docs/selection.md#megabatch-sweeps): wall-clock
    # of 32 sequential candidate fits over one vmapped fit_sweep() at the
    # same configs, warm programs both legs.  The ratio prices per-round
    # dispatch amortization — the thing the config axis exists to buy —
    # so a collapse back toward 1.0 means the batched dispatch quietly
    # stopped batching.  Ratio of two noisy walls on shared CI runners:
    # wide rel floor.
    "sweep_speedup":              ("higher", 0.30, 0.0),
    # gradient-based sampling Pareto leg (docs/sampling.md): wall-clock
    # to the target accuracy at sampling='none' over the best sampled
    # method (GOSS/MVS), warm programs both legs.  A collapse toward 1.0
    # means the compacted row buffer quietly stopped paying for its
    # full-row score/gather overhead.  Time-to-accuracy couples two
    # noisy measurements (per-round wall AND a take(k) accuracy scan):
    # wide rel floor.
    "sampling_speedup":           ("higher", 0.30, 0.0),
}


def load_bench(path: str) -> Dict[str, Any]:
    """A bench payload: either ``bench.py``'s raw JSON line or the
    driver's ``{"parsed": ...}`` wrapper around it."""
    with open(path) as fh:
        rec = json.load(fh)
    if isinstance(rec.get("parsed"), dict):
        rec = rec["parsed"]
    return rec


def newest_bench(repo: str = REPO) -> Optional[str]:
    """The newest ``BENCH_r*.json`` by round number (name sort — the
    driver zero-pads round indices)."""
    paths = sorted(glob.glob(os.path.join(repo, "BENCH_r*.json")))
    return paths[-1] if paths else None


def compare(
    baseline: Dict[str, Any], bench: Dict[str, Any]
) -> Dict[str, List[Dict[str, Any]]]:
    """Per-metric verdicts: ``regressions`` / ``ok`` / ``skipped``."""
    out: Dict[str, List[Dict[str, Any]]] = {
        "regressions": [], "ok": [], "skipped": [],
    }
    bp = baseline.get("platform")
    cp = bench.get("platform")
    if bp and cp and bp != cp:
        out["skipped"].append({
            "metric": "*", "note":
            f"platform_mismatch: baseline={bp} bench={cp}; nothing "
            "comparable (commit a baseline from this platform)",
        })
        return out
    for name, (direction, rel, floor) in METRICS.items():
        if name not in baseline:
            continue  # the baseline does not pin this metric
        base = baseline[name]
        if name not in bench or not isinstance(
            bench.get(name), (int, float)
        ):
            out["skipped"].append({
                "metric": name, "note":
                "absent from bench record (headline-only payload)",
            })
            continue
        cur = float(bench[name])
        base = float(base)
        if direction == "higher":
            delta = base - cur          # positive == worse
            allowed = max(abs(base) * rel, floor)
        else:
            delta = cur - base
            allowed = max(abs(base) * rel, floor)
        row = {
            "metric": name, "baseline": base, "bench": cur,
            "direction": direction, "allowed": allowed,
            "worse_by": delta,
        }
        (out["regressions"] if delta > allowed else out["ok"]).append(row)
    return out


def update_baseline(
    bench: Dict[str, Any], path: str = BASELINE_DEFAULT
) -> Dict[str, Any]:
    """Rewrite the committed baseline from a bench payload: only the
    metrics the sentinel compares, plus the platform tag."""
    base = {
        k: bench[k] for k in METRICS
        if isinstance(bench.get(k), (int, float))
    }
    if bench.get("platform"):
        base["platform"] = bench["platform"]
    base["source"] = bench.get("device", "")
    with open(path, "w") as fh:
        json.dump(base, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return base


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--bench", default=None,
                        help="bench record (default: newest BENCH_r*.json)")
    parser.add_argument("--baseline", default=BASELINE_DEFAULT)
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from the bench record "
                        "instead of comparing (commit the result)")
    args = parser.parse_args(argv)
    bench_path = args.bench or newest_bench()
    if bench_path is None:
        print(json.dumps({"skipped": "no BENCH_r*.json found"}))
        return 0
    bench = load_bench(bench_path)
    if args.update_baseline:
        base = update_baseline(bench, args.baseline)
        print(json.dumps({"updated": args.baseline, "baseline": base}))
        return 0
    if not os.path.exists(args.baseline):
        print(json.dumps({
            "skipped": f"{args.baseline} missing; run --update-baseline",
        }))
        return 0
    with open(args.baseline) as fh:
        baseline = json.load(fh)
    verdict = compare(baseline, bench)
    print(json.dumps({
        "bench": os.path.basename(bench_path),
        "baseline": os.path.basename(args.baseline),
        **verdict,
    }, indent=2))
    if verdict["regressions"]:
        names = ", ".join(r["metric"] for r in verdict["regressions"])
        print(f"PERF REGRESSION: {names} (see rows above; a deliberate "
              "change lands via --update-baseline)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
