"""Generate the website API reference from the live package.

The reference publishes scaladoc from its site pipeline
(`/root/reference/website/docusaurus.config.js:19` — the `API` navbar item);
this is the equivalent for the JAX package: introspect every public export
(`spark_ensemble_tpu.__all__`), group by defining module, and emit one
CommonMark page per module into ``docs/api/`` (built by the docusaurus job,
whose docs root is ``../docs``).  No pdoc/sphinx in this image — and the
``Param`` descriptor system renders richer tables (default, constraint,
doc) than generic autodoc would.

Usage: ``python tools/gen_api_docs.py [out_dir]`` (default ``docs/api``).
CI regenerates and fails on drift, so the committed pages always match the
code (see .github/workflows/ci.yml website job).
"""

from __future__ import annotations

import inspect
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _md_cell(text: str) -> str:
    """One-line, pipe-safe markdown table cell."""
    return " ".join(str(text).split()).replace("|", "\\|")


def _first_paragraph(doc: str) -> str:
    return (doc or "").strip().split("\n\n")[0]


def _param_rows(cls):
    """(name, default, doc) for every declared Param — the package's own
    resolution (`Params._param_defs`), so overrides render the same
    most-derived declaration the descriptor lookup uses.  Non-Params
    classes (e.g. ParamGridBuilder) have none."""
    if not hasattr(cls, "_param_defs"):
        return []
    return [
        (p.name, p.default, p.doc) for p in cls._param_defs().values()
    ]


def _public_methods(cls):
    """Methods defined in the package (not object/inherited builtins)."""
    out = []
    for name, fn in inspect.getmembers(cls, inspect.isfunction):
        if name.startswith("_"):
            continue
        if not (fn.__module__ or "").startswith("spark_ensemble_tpu"):
            continue
        out.append((name, fn))
    return out


def _render_class(cls) -> list:
    lines = [f"## `{cls.__name__}`", ""]
    doc = inspect.getdoc(cls)
    if doc:
        lines += [doc, ""]
    params = _param_rows(cls)
    if params:
        lines += [
            "### Parameters", "",
            "| name | default | description |",
            "|---|---|---|",
        ]
        for name, default, pdoc in params:
            lines.append(
                f"| `{name}` | `{_md_cell(repr(default))}` "
                f"| {_md_cell(pdoc) or '—'} |"
            )
        lines.append("")
    methods = _public_methods(cls)
    if methods:
        lines += ["### Methods", ""]
        for name, fn in methods:
            try:
                sig = str(inspect.signature(fn))
            except (TypeError, ValueError):
                sig = "(...)"
            lines.append(f"#### `{name}{sig}`")
            lines.append("")
            mdoc = inspect.getdoc(fn)
            if mdoc:
                lines += [_first_paragraph(mdoc), ""]
    return lines


def _render_function(fn) -> list:
    try:
        sig = str(inspect.signature(fn))
    except (TypeError, ValueError):
        sig = "(...)"
    lines = [f"## `{fn.__name__}{sig}`", ""]
    doc = inspect.getdoc(fn)
    if doc:
        lines += [doc, ""]
    return lines


def generate(out_dir: str) -> dict:
    """Write the pages; returns {page_id: [exported names]}."""
    import jax

    # import side effects must not touch the accelerator plugin (its init
    # can hang); docs generation is host-only work
    jax.config.update("jax_platforms", "cpu")

    import spark_ensemble_tpu as se

    by_module: dict = {}
    for name in se.__all__:
        obj = getattr(se, name)
        module = getattr(obj, "__module__", "spark_ensemble_tpu")
        by_module.setdefault(module, []).append((name, obj))

    # page id = module basename — unless two modules share one (e.g.
    # telemetry/registry.py vs serving/registry.py), which would silently
    # merge unrelated pages; collisions qualify with the parent package
    def _basename(module: str) -> str:
        return module.split(".")[-1].lstrip("_") or "package"

    counts: dict = {}
    for module in by_module:
        counts[_basename(module)] = counts.get(_basename(module), 0) + 1
    groups: dict = {}
    page_owner: dict = {}
    for module, entries in by_module.items():
        page = _basename(module)
        if counts[page] > 1:
            parts = module.split(".")
            page = f"{parts[-2]}_{page}" if len(parts) > 1 else page
        # parent-qualification must actually disambiguate: a residual
        # collision (two modules still mapping to one page id) would merge
        # unrelated pages silently — fail generation instead
        owner = page_owner.setdefault(page, module)
        if owner != module:
            raise SystemExit(
                f"api page collision: modules {owner!r} and {module!r} "
                f"both map to page {page!r}; rename one or deepen the "
                "qualification in tools/gen_api_docs.py"
            )
        groups.setdefault(page, []).extend(entries)

    os.makedirs(out_dir, exist_ok=True)
    for page, entries in sorted(groups.items()):
        lines = [
            f"# `{entries[0][1].__module__}`",
            "",
            "<!-- GENERATED by tools/gen_api_docs.py — edit docstrings, "
            "not this file -->",
            "",
        ]
        mod = sys.modules.get(entries[0][1].__module__)
        mod_doc = inspect.getdoc(mod) if mod else None
        if mod_doc:
            lines += [_first_paragraph(mod_doc), ""]
        seen_classes = set()
        for name, obj in entries:
            if inspect.isclass(obj):
                if obj.__name__ in seen_classes:
                    continue
                seen_classes.add(obj.__name__)
                lines += _render_class(obj)
            elif callable(obj):
                lines += _render_function(obj)
        with open(os.path.join(out_dir, f"{page}.md"), "w") as f:
            f.write("\n".join(lines).rstrip() + "\n")

    index = [
        "# API reference",
        "",
        "<!-- GENERATED by tools/gen_api_docs.py — edit docstrings, not "
        "this file -->",
        "",
        "Generated from the package docstrings and `Param` declarations "
        "(`python tools/gen_api_docs.py`).  One page per module:",
        "",
        "Training observability (the `SE_TPU_TELEMETRY` JSONL stream, "
        "`fit_history_`, `tools/telemetry_report.py`) has a usage guide at "
        "[telemetry](../telemetry.md); the API classes are on the "
        "[telemetry_registry](./telemetry_registry.md) and "
        "[events](./events.md) pages below.  Serving (packed export, the "
        "bucketed inference engine, the LRU model registry) has a guide at "
        "[serving](../serving.md).  Autotuning (the tunable space, the "
        "measured search, the on-disk tuning cache, the persistent "
        "compilation cache) has a guide at [autotune](../autotune.md).  "
        "The lookahead dispatch pipeline (`SE_TPU_PIPELINE`, on-device "
        "patience, the `host_blocked_us` metric) has a guide at "
        "[pipeline](../pipeline.md).  Static analysis (the `graftlint` "
        "rule catalogue, suppression syntax, traced program contracts and "
        "the compile-budget baseline) has a guide at "
        "[static_analysis](../static_analysis.md).",
        "",
    ]
    for page, entries in sorted(groups.items()):
        names = ", ".join(f"`{n}`" for n, _ in entries)
        index.append(f"- [{page}](./{page}.md) — {names}")
    with open(os.path.join(out_dir, "index.md"), "w") as f:
        f.write("\n".join(index) + "\n")
    return {page: [n for n, _ in entries] for page, entries in groups.items()}


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        _REPO, "docs", "api"
    )
    pages = generate(out)
    total = sum(len(v) for v in pages.values())
    print(f"wrote {len(pages) + 1} pages covering {total} exports -> {out}")
