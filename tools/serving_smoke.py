"""Cross-process serving smoke check (CI `serving` job; docs/serving.md).

Two subcommands meant to run in SEPARATE processes, proving the packed
artifact round-trips across a process restart:

    python tools/serving_smoke.py export --out DIR
        Fit a small GBM classifier on synthetic data, pack + save the
        artifact to DIR/model, and save the live model's predictions to
        DIR/expected.npz — the bit-exact expectations a fresh process must
        reproduce.

    python tools/serving_smoke.py serve --out DIR [--telemetry PATH]
        Load the artifact (manifest-verified), assert the loaded
        PackedModel's predictions are BIT-IDENTICAL to the exported
        expectations, then serve through a warmed InferenceEngine (sync +
        micro-batching queue) asserting tight allclose and zero compiles
        after warmup.  Serving telemetry events go to PATH (JSONL).

A third subcommand drives the resilient fleet (CI `serving-chaos` job;
docs/fleet.md) under whatever chaos controller the environment
configures (SE_TPU_CHAOS + serving faults):

    python tools/serving_smoke.py fleet --out DIR [--telemetry PATH]
        Load the artifact, put a FleetRouter over it (prefix tier
        pre-warmed), run a multi-threaded closed loop that kills one
        replica mid-stream ON TOP of any env-injected faults, and assert
        ZERO failed requests, zero steady-state compiles, and exact
        ensemble-prefix degradation.  The per-replica SLO rows land in
        the --telemetry JSONL.  With ``--operator DIR`` the live operator
        plane (docs/operator.md) runs over the battery: /metrics and
        /programz are scraped mid-load and validated, a deterministic
        stall+crash window must flip /healthz to 503 (and recovery must
        flip it back), and the validated snapshot files land in DIR.

A fourth subcommand drives the closed-loop control plane (same CI job;
docs/autopilot.md):

    python tools/serving_smoke.py swap --out DIR [--telemetry PATH]
        Load the artifact into a registry twice (full model + a prefix
        "next" version), serve multi-threaded traffic through a
        registry-backed fleet, and roll a torn-free hot swap plus one
        add/remove elastic cycle mid-load — WITH deterministic
        ``swap_crash``/``scale_crash`` chaos killing a replica mid-rebind
        and a warm-in.  Asserts ZERO failed requests, ZERO compiles
        (registry engines are pre-warmed, clones share programs), every
        response bit-matching exactly ONE version, and every request
        started after the swap returning the new version.

A fifth subcommand drives the model-quality observability plane (same
CI job; docs/quality.md):

    python tools/serving_smoke.py quality --out DIR [--telemetry PATH]
        Load the artifact (its fit-time drift reference included), serve
        in-distribution traffic through a drift-enabled fleet, push a
        deterministic covariate-shifted burst until the on-device sketch
        window flips /healthz to 503 via the quality_psi_max watchdog
        rule, then normalize and assert the alert clears — with
        registry-leased shadow scoring and staged attribution riding
        along, zero steady-state compiles, and the degraded-state
        /qualityz + /metrics scrapes plus the filtered quality JSONL
        landing in --artifacts.

Exit code 0 = every assertion held; any mismatch raises.
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _data(n=600, d=8, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    centers = rng.randn(3, d).astype(np.float32)
    y = np.argmax(X @ centers.T, axis=1).astype(np.float32)
    return X, y


def cmd_export(args):
    import spark_ensemble_tpu as se

    X, y = _data()
    model = se.GBMClassifier(num_base_learners=8).fit(X, y)
    packed = model.pack()
    packed.save(os.path.join(args.out, "model"))
    np.savez(
        os.path.join(args.out, "expected.npz"),
        X=X,
        predict=np.asarray(model.predict(X)),  # graftlint: ignore[unfenced-blocking-read] -- one-off export of expected outputs, no serving path is live yet
        proba=np.asarray(model.predict_proba(X)),  # graftlint: ignore[unfenced-blocking-read] -- one-off export of expected outputs, no serving path is live yet
    )
    print(json.dumps({
        "exported": os.path.join(args.out, "model"),
        "arrays": len(packed.array_names),
        "bytes": packed.nbytes,
        "pid": os.getpid(),
    }))


def cmd_serve(args):
    from spark_ensemble_tpu.autotune import ensure_compilation_cache
    from spark_ensemble_tpu.serving import InferenceEngine, load_packed
    from spark_ensemble_tpu.telemetry.events import (
        _ensure_compile_listener,
        persistent_cache_snapshot,
    )

    # with SE_TPU_COMPILE_CACHE set, every compile request is served from
    # the persistent on-disk cache when warm — a second run must observe
    # ZERO cache misses during warmup (asserted via --max-warmup-compiles
    # in CI; the backend_compile duration event fires on hits too, so
    # misses = requests - hits is the real-compile count)
    ensure_compilation_cache()
    _ensure_compile_listener()
    expected = np.load(os.path.join(args.out, "expected.npz"))
    X = expected["X"]
    packed = load_packed(os.path.join(args.out, "model"))

    # contract 1: the loaded artifact is bit-identical to the exporter's
    # live model (same arrays -> same programs), across the restart
    # graftlint: ignore[unfenced-blocking-read] -- bit-identity assertion readback; the smoke test is not a latency path
    assert np.array_equal(np.asarray(packed.predict(X)), expected["predict"])
    assert np.array_equal(
        # graftlint: ignore[unfenced-blocking-read] -- bit-identity assertion readback; the smoke test is not a latency path
        np.asarray(packed.predict_proba(X)), expected["proba"]
    )

    # contract 2: the warmed engine serves allclose results (whole-model
    # fusion can move float rounding ~1 ulp) with ZERO compiles after
    # warmup, sync and through the coalescing queue
    req0, hit0 = persistent_cache_snapshot()
    engine = InferenceEngine(
        packed,
        methods=("predict", "predict_proba"),
        max_batch_size=256,
        telemetry_path=args.telemetry,
    )
    req1, hit1 = persistent_cache_snapshot()
    warmup_compiles = (req1 - req0) - (hit1 - hit0)
    if args.max_warmup_compiles is not None:
        assert req1 > req0, (
            "persistent compilation cache inactive during warmup "
            "(SE_TPU_COMPILE_CACHE unset or unusable)"
        )
        assert warmup_compiles <= args.max_warmup_compiles, (
            f"warmup ran {warmup_compiles} real backend compiles "
            f"({req1 - req0} requests, {hit1 - hit0} cache hits), expected "
            f"<= {args.max_warmup_compiles} (persistent compile cache cold?)"
        )
    rng = np.random.RandomState(0)
    for n in rng.randint(1, X.shape[0], size=20):
        out = engine.predict(X[:n])
        assert np.allclose(out, expected["predict"][:n], rtol=1e-5, atol=1e-6)
    futs = [
        (n, engine.submit(X[:n], method="predict_proba"))
        for n in rng.randint(1, 64, size=40)
    ]
    for n, fut in futs:
        assert np.allclose(
            fut.result(timeout=60), expected["proba"][:n],
            rtol=1e-5, atol=1e-6,
        )
    stats = engine.stats()
    engine.stop()
    assert stats["compiles_since_warmup"] == 0, stats
    print(json.dumps({
        "served_bit_identical": True,
        "compiles_since_warmup": stats["compiles_since_warmup"],
        "warmup_compiles": warmup_compiles,
        "buckets": list(stats["buckets"]),
        "pid": os.getpid(),
        "telemetry": args.telemetry,
    }))


def _fetch(url):
    """GET a local operator endpoint; returns (status, body) and never
    raises on HTTP error codes (a 503 /healthz is data, not a failure)."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _operator_chaos_window(args, plane, router, X, tier_pred, full_pred):
    """The deterministic /healthz flip (docs/operator.md): a clean-window
    200, a forced 503 while every request stalls past the SLO and a
    replica dies, and a 200 again once the stalls wash out of the
    rolling latency window.  Watchdog ticks are driven explicitly so the
    flip does not depend on runner timing."""
    from spark_ensemble_tpu.robustness.chaos import ChaosController, install
    from spark_ensemble_tpu.telemetry.exporter import write_snapshot

    dog = plane.watchdog
    # install(None) reverts to the ENV controller (SE_TPU_CHAOS is live in
    # the CI job), so the quiet phases need an explicit never-fires
    # controller — rate 0.0 draws never beat the threshold
    quiet = ChaosController(seed=0, rate=0.0)

    def batch(count, size=16):
        for _ in range(count):
            resp = router.predict(X[:size], deadline_ms=10_000.0)
            want = tier_pred if resp.degraded else full_pred
            assert np.allclose(resp.value, want[:size], rtol=1e-5,
                               atol=1e-6)

    # healthy phase: wash the rolling window clean of whatever the
    # env-chaos battery left in it (the deque holds 256 samples), then
    # the p99 probe reads a fast-request window and /healthz must be 200
    install(quiet)
    batch(300)
    dog.evaluate_once()
    dog.evaluate_once()
    code, body = _fetch(plane.url + "/healthz")
    assert code == 200, (code, body)

    # degradation window: EVERY request stalls well past --slo-p99-ms
    # AND a replica dies mid-window; breach_for=1 means one tick flips
    # the verdict, and the alert must name the p99 rule
    install(ChaosController(seed=7, rate=1.0, faults=("replica_stall",)))
    router.kill_replica()
    batch(12)
    dog.evaluate_once()
    code, body = _fetch(plane.url + "/healthz")
    assert code == 503, (code, body)
    verdict = json.loads(body)
    assert verdict["status"] == "degraded", verdict
    assert any(a["metric"] == "serving_p99_ms"
               for a in verdict["alerts"]), verdict

    # recovery: faults off, fast requests push the stalls out of the
    # window, clear_for=2 healthy ticks emit the cleared slo_alert and
    # /healthz goes green again
    install(quiet)
    batch(300)
    dog.evaluate_once()
    dog.evaluate_once()
    code, body = _fetch(plane.url + "/healthz")
    assert code == 200, (code, body)
    install(None)  # hand the env-configured controller back

    # inventory rows into the telemetry stream (trace + report join) and
    # the validated snapshot files for the CI artifact upload
    plane.inventory.analyze_pending()
    programs = plane.inventory.emit_rows(path=args.telemetry)
    paths = write_snapshot(args.operator, inventory=plane.inventory,
                           watchdog=dog)
    return {
        "url": plane.url,
        "snapshot": paths,
        "healthz_flip": ["ok", "degraded", "ok"],
        "alert_metric": "serving_p99_ms",
        "slo_p99_ms": float(args.slo_p99_ms),
        "programs_emitted": programs,
    }


def cmd_fleet(args):
    import threading

    from spark_ensemble_tpu.serving import FleetRouter, load_packed

    expected = np.load(os.path.join(args.out, "expected.npz"))
    X = expected["X"]

    plane = None
    operator_report = {}
    if args.operator:
        os.makedirs(args.operator, exist_ok=True)
        # live operator plane (docs/operator.md), started BEFORE the model
        # loads so the fleet's warmup programs land in /programz.  The
        # watchdog gets one deterministic rule — fleet p99 against
        # --slo-p99-ms with single-tick raise hysteresis — so the
        # degradation flip below is driven by the injected stalls, not by
        # runner-speed luck against the production thresholds.
        from spark_ensemble_tpu.telemetry.exporter import OperatorPlane
        from spark_ensemble_tpu.telemetry.watchdog import (
            Rule,
            Watchdog,
            probe_fleet_max,
        )

        dog = Watchdog(
            rules=[Rule(
                "serving_p99_ms", probe_fleet_max("p99_ms"),
                threshold=float(args.slo_p99_ms),
                breach_for=1, clear_for=2,
            )],
            interval_s=3600.0,  # ticked explicitly below, deterministic
            telemetry_path=args.telemetry,
        )
        plane = OperatorPlane(
            port=0, watchdog=dog, sampler_interval_s=0.1
        ).start()

    packed = load_packed(os.path.join(args.out, "model"))
    tier = max(1, packed.num_members // 2)
    # prefix exactness, pinned BEFORE the fleet warms: the degraded tier
    # IS a k-round model (PackedModel.take), not an approximation
    # graftlint: ignore[unfenced-blocking-read] -- one-off expectation readback before any serving path is live
    tier_pred = np.asarray(packed.take(tier).predict(X))
    full_pred = expected["predict"]

    n_req, n_threads = int(args.requests), 4
    failed = [0]
    router = FleetRouter(
        packed,
        replicas=int(args.replicas),
        prefix_tiers=(tier,),
        max_batch_size=256,
        deadline_ms=10_000.0,
        # the starvation probe below waits synchronously on a 0.25 ms
        # budget; a generous grace keeps the wait from outrunning the reply
        deadline_grace=40_000.0,
        telemetry_path=args.telemetry,
        label="smoke-fleet",
    )

    def worker(tid):
        rng = np.random.RandomState(tid)
        for i in range(tid, n_req, n_threads):
            if tid == 0 and i == (n_req // 2 // n_threads) * n_threads:
                # a deterministic kill ON TOP of whatever the env-chaos
                # controller injects: the acceptance scenario is a replica
                # dying mid-load with zero lost requests
                router.kill_replica()
            n = int(rng.randint(1, 64))
            try:
                resp = router.predict(X[:n], deadline_ms=10_000.0)
            except Exception:  # noqa: BLE001 - counted; zero is the bar
                failed[0] += 1
                continue
            want = tier_pred if resp.degraded else full_pred
            assert np.allclose(resp.value, want[:n], rtol=1e-5, atol=1e-6)

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    if plane is not None:
        # scrape WHILE the battery (and its deterministic kill) is in
        # flight: the exposition must validate under load, and the raw
        # bodies become CI artifacts.  Zero-new-compiles is re-asserted
        # on a post-window snapshot below.
        import time as _time

        from spark_ensemble_tpu.telemetry.exporter import (
            validate_openmetrics,
        )

        _time.sleep(0.2)  # let the workers get requests in flight
        code, metrics_text = _fetch(plane.url + "/metrics")
        assert code == 200, code
        problems = validate_openmetrics(metrics_text)
        assert not problems, problems[:5]
        code, programz_body = _fetch(plane.url + "/programz?n=10")
        assert code == 200, code
        with open(os.path.join(args.operator, "metrics_midload.txt"),
                  "w") as f:
            f.write(metrics_text)
        with open(os.path.join(args.operator, "programz_midload.json"),
                  "w") as f:
            f.write(programz_body)
        operator_report["midload_scrape"] = {
            "metrics_bytes": len(metrics_text),
            "programs": len(json.loads(programz_body)["programs"]),
        }
    for t in threads:
        t.join(timeout=600)

    # a starvation budget forces the degradation path even on an idle
    # runner: the response must carry the explicit flag AND the exact
    # prefix prediction
    resp = router.predict(X[:16], deadline_ms=0.25)
    assert resp.degraded and resp.tier == tier
    assert np.allclose(resp.value, tier_pred[:16], rtol=1e-5, atol=1e-6)

    snap = router.slo_snapshot()
    # live statusz (docs/tracing.md): the operator view must resolve
    # while the fleet is still up — per-replica state machines, queue
    # depth, rolling percentiles, hedge rate
    statusz = router.statusz()
    assert set(statusz["replicas"]) == set(snap["replicas"])
    assert statusz["requests"] == snap["requests"]
    assert 0.0 <= statusz["hedge_rate"] <= 1.0
    assert statusz["trace_id"]
    if plane is not None:
        operator_report.update(
            _operator_chaos_window(args, plane, router, X, tier_pred,
                                   full_pred)
        )
        # the whole operator battery — scrapes under load, the stall
        # window, the recovery washes — must not have compiled anything
        post = router.slo_snapshot()
        assert post["compiles_since_warmup"] == 0, post
        plane.stop()
    router.stop()  # emits the fleet_slo rows to --telemetry
    assert failed[0] == 0, f"{failed[0]} requests failed under faults"
    assert snap["compiles_since_warmup"] == 0, snap
    assert snap["crashes"] >= 1  # the deterministic kill, at minimum
    print(json.dumps({
        "statusz": statusz,
        "requests": snap["requests"],
        "failed": failed[0],
        "crashes": snap["crashes"],
        "replays": snap["replays"],
        "hedges_fired": snap["hedges_fired"],
        "degraded_share": snap["degraded_share"],
        "p50_ms": snap["p50_ms"],
        "p99_ms": snap["p99_ms"],
        "compiles_since_warmup": snap["compiles_since_warmup"],
        "replica_states": {
            name: rep["state"] for name, rep in snap["replicas"].items()
        },
        "pid": os.getpid(),
        "telemetry": args.telemetry,
        "operator": operator_report or None,
    }))


def cmd_swap(args):
    """The hot-swap acceptance arc (CI `serving-chaos` job;
    docs/autopilot.md): a rolling registry swap + one elastic cycle under
    live multi-threaded traffic and deterministic control-plane chaos,
    proving the tentpole invariants — no torn responses, no drops, no
    compiles."""
    import threading

    from spark_ensemble_tpu.robustness.chaos import ChaosController, install
    from spark_ensemble_tpu.serving import FleetRouter, ModelRegistry, load_packed
    from spark_ensemble_tpu.telemetry.events import compile_snapshot

    expected = np.load(os.path.join(args.out, "expected.npz"))
    X = expected["X"]
    packed = load_packed(os.path.join(args.out, "model"))
    tier = max(1, packed.num_members // 2)

    # the env-configured controller stays for the serve path; the swap
    # sites get their own deterministic kills (rate 1.0, budget 1 each)
    install(ChaosController(
        seed=5, rate=1.0, faults=("swap_crash", "scale_crash"),
    ))
    registry = ModelRegistry(
        capacity=4, max_batch_size=256,
        # proba bits distinguish the versions (a prefix classifier often
        # agrees with the full model on argmax labels)
        methods=("predict", "predict_proba"),
        telemetry_path=args.telemetry,
    )
    registry.register("prod", packed, warm=True)
    # "next" is the refreshed generation: a prefix slice distinguishes the
    # versions bit-wise without a second fit
    registry.register("next", packed.take(tier), warm=True)
    router = FleetRouter.from_registry(
        registry, "prod", replicas=int(args.replicas),
        deadline_ms=10_000.0, telemetry_path=args.telemetry,
        label="swap-fleet",
    )
    n_req, n_threads, batch = int(args.requests), 4, 32
    want = {0: np.asarray(
        router.predict(X[:batch], method="predict_proba").value
    )}
    swapped = threading.Event()
    failed = [0]
    results = [[] for _ in range(n_threads)]

    def worker(tid):
        for _ in range(n_req // n_threads):
            after = swapped.is_set()  # sampled BEFORE the request starts
            try:
                resp = router.predict(X[:batch], method="predict_proba")
            except Exception:  # noqa: BLE001 - counted; zero is the bar
                failed[0] += 1
                continue
            results[tid].append(
                (after, resp.version, np.asarray(resp.value))
            )

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    c0, _ = compile_snapshot()
    for t in threads:
        t.start()
    info = router.swap_model("next")
    swapped.set()
    added = router.add_replica()
    removed = router.remove_replica(added)
    for t in threads:
        t.join(timeout=600)
    want[1] = np.asarray(
        router.predict(X[:batch], method="predict_proba").value
    )
    snap = router.slo_snapshot()
    router.stop()
    registry.close()
    install(None)  # hand the env-configured controller back

    assert failed[0] == 0, f"{failed[0]} requests failed during the swap"
    assert info["swap_compiles"] == 0, info
    assert info["swap_crashes"] == 1, info  # the mid-rebind kill landed
    assert snap["crashes"] >= 2, snap       # + the warm-in kill
    assert snap["compiles_since_warmup"] == 0, snap
    assert compile_snapshot()[0] == c0
    assert snap["version"] == 1 and snap["swaps"] == 1
    assert not np.array_equal(want[0], want[1])
    total = 0
    for rows in results:
        for after, version, value in rows:
            total += 1
            assert version in want, version
            # whole-version bits: never a torn (mixed-version) response
            assert np.array_equal(value, want[version]), (
                f"torn response: version {version} bits match neither model"
            )
            if after:  # monotone: post-swap requests serve the new version
                assert version == 1, "stale version served after the swap"
    assert total == sum(len(r) for r in results)
    print(json.dumps({
        "requests": snap["requests"],
        "failed": failed[0],
        "swap": info,
        "scale": {"added": added, "removed": removed},
        "crashes": snap["crashes"],
        "post_swap_monotone": True,
        "versions_seen": sorted({
            v for rows in results for _, v, _ in rows
        }),
        "compiles_since_warmup": snap["compiles_since_warmup"],
        "pid": os.getpid(),
        "telemetry": args.telemetry,
    }))


def cmd_quality(args):
    """The model-quality acceptance arc (CI `serving-chaos` job;
    docs/quality.md), fully deterministic: serve in-distribution traffic
    through a drift-enabled fleet (/healthz 200), push a covariate-
    shifted burst (every feature +3 sigma) until a sketch window scores
    past the PSI threshold and /healthz flips 503 via the
    ``quality_psi_max`` watchdog rule, then normalize traffic and assert
    the alert clears — with ZERO steady-state compiles, shadow scoring
    leasing a prefix candidate from a live registry, and sampled staged
    attribution riding the responses.  The quality events (drift_window
    / shadow_eval / quality_alert) land in --telemetry and the filtered
    quality JSONL + /qualityz + /metrics snapshots in --artifacts."""
    from spark_ensemble_tpu.robustness.chaos import ChaosController, install
    from spark_ensemble_tpu.serving import (
        FleetRouter,
        ModelRegistry,
        load_packed,
    )
    from spark_ensemble_tpu.telemetry.exporter import (
        OperatorPlane,
        validate_openmetrics,
    )
    from spark_ensemble_tpu.telemetry.quality import ShadowScorer
    from spark_ensemble_tpu.telemetry.watchdog import (
        Rule,
        Watchdog,
        probe_quality_max,
    )

    expected = np.load(os.path.join(args.out, "expected.npz"))
    X = expected["X"]
    packed = load_packed(os.path.join(args.out, "model"))
    assert packed.quality is not None, (
        "exported artifact carries no drift reference; re-export with a "
        "binned-fit model"
    )
    os.makedirs(args.artifacts, exist_ok=True)
    if args.telemetry is None:
        # the arc's JSONL assertions need the stream on disk
        args.telemetry = os.path.join(args.artifacts, "telemetry.jsonl")
    # only this run's rows count: a shared/reused stream may hold events
    # from earlier arcs (the CI fleet step appends to the same file)
    tel_offset = (
        os.path.getsize(args.telemetry)
        if os.path.exists(args.telemetry) else 0
    )
    # the env-chaos battery must not perturb the window row counts: a
    # stalled request still serves (rows still counted), but a crashed
    # replica replays rows into the sketch twice — pin a quiet controller
    install(ChaosController(seed=0, rate=0.0))

    window = int(args.drift_window)
    batch = 64
    dog = Watchdog(
        rules=[Rule(
            "quality_psi_max", probe_quality_max("psi_max"),
            threshold=float(args.psi_threshold),
            breach_for=1, clear_for=2,
        )],
        interval_s=3600.0,  # ticked explicitly below, deterministic
        telemetry_path=args.telemetry,
    )
    plane = OperatorPlane(
        port=0, watchdog=dog, sampler_interval_s=3600.0
    ).start()
    registry = ModelRegistry()
    tier = max(1, packed.num_members // 2)
    registry.register("candidate", packed.take(tier), warm=True,
                      min_bucket=batch, max_batch_size=batch)
    shadow = ShadowScorer(
        registry, "candidate", fraction=0.25,
        telemetry_path=args.telemetry,
    )
    router = FleetRouter(
        packed,
        # one replica: a hedged request would serve the same rows twice
        # and double-count them into the shared drift sketch, breaking
        # the one-window-per-phase determinism this smoke pins
        replicas=1,
        prefix_tiers=(tier,),
        min_bucket=batch,
        max_batch_size=batch,
        deadline_ms=10_000.0,
        drift=True,
        drift_window=window,
        attribution_fraction=0.25,
        shadow=shadow,
        telemetry_path=args.telemetry,
        label="quality-fleet",
    )
    try:
        def serve_window(shift=0.0):
            # exactly one sketch window per call: batches never pad
            # (rows == bucket), so window closure is deterministic
            for i in range(window // batch):
                lo = (i * batch) % (X.shape[0] - batch)
                router.predict(X[lo:lo + batch] + np.float32(shift))

        serve_window()            # window 1: the training rows themselves
        dog.evaluate_once()
        code, body = _fetch(plane.url + "/healthz")
        assert code == 200, (code, body)

        serve_window(shift=3.0)   # window 2: covariate-shifted burst
        dog.evaluate_once()
        code, body = _fetch(plane.url + "/healthz")
        assert code == 503, (code, body)
        verdict = json.loads(body)
        assert any(a["metric"] == "quality_psi_max"
                   for a in verdict["alerts"]), verdict

        # scrape the quality surface while degraded: /qualityz must show
        # the live drift stream in alert, /metrics must render the
        # se_tpu_quality_* series and still validate
        code, qbody = _fetch(plane.url + "/qualityz")
        assert code == 200, code
        qz = json.loads(qbody)
        drift_streams = [v for v in qz["streams"].values()
                         if v.get("kind") == "drift"]
        assert drift_streams and drift_streams[0]["alert_active"], qz
        psi_max = float(drift_streams[0]["psi_max"])
        code, metrics_text = _fetch(plane.url + "/metrics")
        assert code == 200, code
        assert "se_tpu_quality_psi_max" in metrics_text
        problems = validate_openmetrics(metrics_text)
        assert not problems, problems[:5]
        with open(os.path.join(args.artifacts, "qualityz_degraded.json"),
                  "w") as f:
            f.write(qbody)
        with open(os.path.join(args.artifacts, "metrics_degraded.txt"),
                  "w") as f:
            f.write(metrics_text)

        serve_window()            # window 3: traffic normalizes
        dog.evaluate_once()
        code, _ = _fetch(plane.url + "/healthz")
        assert code == 503, "clear_for=2 must hold one more tick"
        dog.evaluate_once()
        code, body = _fetch(plane.url + "/healthz")
        assert code == 200, (code, body)

        snap = router.slo_snapshot()
        assert snap["compiles_since_warmup"] == 0, snap
        assert snap["attributed"] >= 1, snap
        shadow_snap = shadow.snapshot()
        assert shadow_snap["evals"] >= 1, shadow_snap
        assert shadow_snap["errors"] == 0, shadow_snap
    finally:
        install(None)  # hand the env-configured controller back
        router.stop()
        shadow.close()
        registry.close()
        plane.stop()

    # the quality JSONL artifact: just this arc's quality-plane events,
    # filtered out of the shared telemetry stream
    quality_events = []
    if os.path.exists(args.telemetry):
        with open(args.telemetry) as f:
            f.seek(tel_offset)
            for line in f:
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if ev.get("event") in ("drift_window", "shadow_eval",
                                       "quality_alert", "slo_alert"):
                    quality_events.append(ev)
    quality_path = os.path.join(args.artifacts, "quality_events.jsonl")
    with open(quality_path, "w") as f:
        for ev in quality_events:
            f.write(json.dumps(ev) + "\n")
    windows = [e for e in quality_events
               if e["event"] == "drift_window"]
    alerts = [e for e in quality_events
              if e["event"] == "quality_alert"
              and e.get("metric") == "psi_max"]
    assert [a["state"] for a in alerts] == ["raised", "cleared"], alerts
    print(json.dumps({
        "healthz_flip": ["ok", "degraded", "ok"],
        "alert_metric": "quality_psi_max",
        "psi_max_degraded": psi_max,
        "psi_threshold": float(args.psi_threshold),
        "drift_windows": len(windows),
        "shadow_evals": shadow_snap["evals"],
        "attributed": snap["attributed"],
        "compiles_since_warmup": snap["compiles_since_warmup"],
        "quality_events": quality_path,
        "pid": os.getpid(),
        "telemetry": args.telemetry,
    }))


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_export = sub.add_parser("export")
    p_export.add_argument("--out", required=True)
    p_export.set_defaults(fn=cmd_export)
    p_serve = sub.add_parser("serve")
    p_serve.add_argument("--out", required=True)
    p_serve.add_argument("--telemetry", default=None)
    p_serve.add_argument(
        "--max-warmup-compiles", type=int, default=None,
        help="assert the engine warmup itself ran at most this many backend "
        "compiles — 0 on a second run with a warm SE_TPU_COMPILE_CACHE "
        "(persistent-cache disk hits emit no backend_compile events)",
    )
    p_serve.set_defaults(fn=cmd_serve)
    p_fleet = sub.add_parser("fleet")
    p_fleet.add_argument("--out", required=True)
    p_fleet.add_argument("--telemetry", default=None)
    p_fleet.add_argument("--replicas", type=int, default=3)
    p_fleet.add_argument("--requests", type=int, default=200)
    p_fleet.add_argument(
        "--operator", metavar="DIR", default=None,
        help="also run the live operator plane (docs/operator.md): scrape "
        "/metrics + /programz mid-battery, force a deterministic /healthz "
        "503 during a stall+crash window, assert recovery, and write the "
        "validated snapshot files into DIR (the CI artifact)",
    )
    p_fleet.add_argument(
        "--slo-p99-ms", type=float, default=100.0,
        help="p99 threshold for the --operator watchdog rule; the chaos "
        "window stalls every request 250 ms so any value well under that "
        "flips deterministically",
    )
    p_fleet.set_defaults(fn=cmd_fleet)
    p_swap = sub.add_parser("swap")
    p_swap.add_argument("--out", required=True)
    p_swap.add_argument("--telemetry", default=None)
    p_swap.add_argument("--replicas", type=int, default=3)
    p_swap.add_argument("--requests", type=int, default=200)
    p_swap.set_defaults(fn=cmd_swap)
    p_quality = sub.add_parser("quality")
    p_quality.add_argument("--out", required=True)
    p_quality.add_argument("--telemetry", default=None)
    p_quality.add_argument(
        "--artifacts", default="/tmp/quality-smoke",
        help="directory for the quality JSONL + degraded-state /qualityz "
        "and /metrics snapshots (the CI artifact)",
    )
    p_quality.add_argument(
        "--drift-window", type=int, default=512,
        help="sketch window in rows; served in 64-row no-pad batches so "
        "each phase closes exactly one window",
    )
    p_quality.add_argument(
        "--psi-threshold", type=float, default=0.25,
        help="watchdog threshold for quality_psi_max; the +3-sigma burst "
        "scores far past any sane value, the clean windows far under",
    )
    p_quality.set_defaults(fn=cmd_quality)
    args = parser.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    args.fn(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
