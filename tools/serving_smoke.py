"""Cross-process serving smoke check (CI `serving` job; docs/serving.md).

Two subcommands meant to run in SEPARATE processes, proving the packed
artifact round-trips across a process restart:

    python tools/serving_smoke.py export --out DIR
        Fit a small GBM classifier on synthetic data, pack + save the
        artifact to DIR/model, and save the live model's predictions to
        DIR/expected.npz — the bit-exact expectations a fresh process must
        reproduce.

    python tools/serving_smoke.py serve --out DIR [--telemetry PATH]
        Load the artifact (manifest-verified), assert the loaded
        PackedModel's predictions are BIT-IDENTICAL to the exported
        expectations, then serve through a warmed InferenceEngine (sync +
        micro-batching queue) asserting tight allclose and zero compiles
        after warmup.  Serving telemetry events go to PATH (JSONL).

Exit code 0 = every assertion held; any mismatch raises.
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _data(n=600, d=8, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    centers = rng.randn(3, d).astype(np.float32)
    y = np.argmax(X @ centers.T, axis=1).astype(np.float32)
    return X, y


def cmd_export(args):
    import spark_ensemble_tpu as se

    X, y = _data()
    model = se.GBMClassifier(num_base_learners=8).fit(X, y)
    packed = model.pack()
    packed.save(os.path.join(args.out, "model"))
    np.savez(
        os.path.join(args.out, "expected.npz"),
        X=X,
        predict=np.asarray(model.predict(X)),  # graftlint: ignore[unfenced-blocking-read] -- one-off export of expected outputs, no serving path is live yet
        proba=np.asarray(model.predict_proba(X)),  # graftlint: ignore[unfenced-blocking-read] -- one-off export of expected outputs, no serving path is live yet
    )
    print(json.dumps({
        "exported": os.path.join(args.out, "model"),
        "arrays": len(packed.array_names),
        "bytes": packed.nbytes,
        "pid": os.getpid(),
    }))


def cmd_serve(args):
    from spark_ensemble_tpu.autotune import ensure_compilation_cache
    from spark_ensemble_tpu.serving import InferenceEngine, load_packed
    from spark_ensemble_tpu.telemetry.events import (
        _ensure_compile_listener,
        persistent_cache_snapshot,
    )

    # with SE_TPU_COMPILE_CACHE set, every compile request is served from
    # the persistent on-disk cache when warm — a second run must observe
    # ZERO cache misses during warmup (asserted via --max-warmup-compiles
    # in CI; the backend_compile duration event fires on hits too, so
    # misses = requests - hits is the real-compile count)
    ensure_compilation_cache()
    _ensure_compile_listener()
    expected = np.load(os.path.join(args.out, "expected.npz"))
    X = expected["X"]
    packed = load_packed(os.path.join(args.out, "model"))

    # contract 1: the loaded artifact is bit-identical to the exporter's
    # live model (same arrays -> same programs), across the restart
    # graftlint: ignore[unfenced-blocking-read] -- bit-identity assertion readback; the smoke test is not a latency path
    assert np.array_equal(np.asarray(packed.predict(X)), expected["predict"])
    assert np.array_equal(
        # graftlint: ignore[unfenced-blocking-read] -- bit-identity assertion readback; the smoke test is not a latency path
        np.asarray(packed.predict_proba(X)), expected["proba"]
    )

    # contract 2: the warmed engine serves allclose results (whole-model
    # fusion can move float rounding ~1 ulp) with ZERO compiles after
    # warmup, sync and through the coalescing queue
    req0, hit0 = persistent_cache_snapshot()
    engine = InferenceEngine(
        packed,
        methods=("predict", "predict_proba"),
        max_batch_size=256,
        telemetry_path=args.telemetry,
    )
    req1, hit1 = persistent_cache_snapshot()
    warmup_compiles = (req1 - req0) - (hit1 - hit0)
    if args.max_warmup_compiles is not None:
        assert req1 > req0, (
            "persistent compilation cache inactive during warmup "
            "(SE_TPU_COMPILE_CACHE unset or unusable)"
        )
        assert warmup_compiles <= args.max_warmup_compiles, (
            f"warmup ran {warmup_compiles} real backend compiles "
            f"({req1 - req0} requests, {hit1 - hit0} cache hits), expected "
            f"<= {args.max_warmup_compiles} (persistent compile cache cold?)"
        )
    rng = np.random.RandomState(0)
    for n in rng.randint(1, X.shape[0], size=20):
        out = engine.predict(X[:n])
        assert np.allclose(out, expected["predict"][:n], rtol=1e-5, atol=1e-6)
    futs = [
        (n, engine.submit(X[:n], method="predict_proba"))
        for n in rng.randint(1, 64, size=40)
    ]
    for n, fut in futs:
        assert np.allclose(
            fut.result(timeout=60), expected["proba"][:n],
            rtol=1e-5, atol=1e-6,
        )
    stats = engine.stats()
    engine.stop()
    assert stats["compiles_since_warmup"] == 0, stats
    print(json.dumps({
        "served_bit_identical": True,
        "compiles_since_warmup": stats["compiles_since_warmup"],
        "warmup_compiles": warmup_compiles,
        "buckets": list(stats["buckets"]),
        "pid": os.getpid(),
        "telemetry": args.telemetry,
    }))


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_export = sub.add_parser("export")
    p_export.add_argument("--out", required=True)
    p_export.set_defaults(fn=cmd_export)
    p_serve = sub.add_parser("serve")
    p_serve.add_argument("--out", required=True)
    p_serve.add_argument("--telemetry", default=None)
    p_serve.add_argument(
        "--max-warmup-compiles", type=int, default=None,
        help="assert the engine warmup itself ran at most this many backend "
        "compiles — 0 on a second run with a warm SE_TPU_COMPILE_CACHE "
        "(persistent-cache disk hits emit no backend_compile events)",
    )
    p_serve.set_defaults(fn=cmd_serve)
    args = parser.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    args.fn(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
